"""Straight-line NumPy/dict oracle for the L4/L7 rollups.

Independent re-implementation of the reference semantics (fanout rules of
collector.rs:500-607/694-821/882-1095, merge rules of meter.rs:97-276)
with Python dicts and exact int64 accumulators. The jit pipeline must
agree with this scorer exactly on meters (within f32 representability)
and on the emitted key set — this is the conformance harness the
reference repo lacks (SURVEY §4).

Kept deliberately scalar/dict-shaped: no jnp, no sorting tricks — so a
bug in the device path can't be mirrored here by construction. (The L4
and L7 paths do share one record walker, parameterized the same way the
reference parameterizes its tagger builders — the shared logic *is* the
shared reference semantics, collector.rs:882/984 get_*_tagger.)
"""

from __future__ import annotations

import dataclasses

from ..datamodel.code import CodeId, Direction, MeterId, SignalSource
from ..datamodel.schema import APP_METER, FLOW_METER, MergeOp, TAG_SCHEMA, MeterSchema
from ..aggregator.fanout import EPC_INTERNET_U16, FanoutConfig, TCP, UDP

_SIDE_MASK = 0xF8


@dataclasses.dataclass
class OracleDoc:
    window: int
    tag: dict
    meter: dict  # int64 values


def _merge_meter(into: dict, add: dict, schema: MeterSchema) -> None:
    for f in schema.fields:
        if f.op is MergeOp.SUM:
            into[f.name] += add[f.name]
        else:
            into[f.name] = max(into[f.name], add[f.name])


def _reversed_meter(m: dict, schema: MeterSchema) -> dict:
    out = dict(m)
    for f in schema.fields:
        if f.reverse_with:
            out[f.name] = m[f.reverse_with]
        if f.zero_on_reverse:
            out[f.name] = 0
    return out


def _empty_tag() -> dict:
    return {n: 0 for n in TAG_SCHEMA.field_names()}


def _tap_side(direction: int) -> int:
    return direction


def _rollup(
    records: list[dict],
    config: FanoutConfig,
    interval: int,
    app: bool,
) -> dict[tuple, OracleDoc]:
    schema = APP_METER if app else FLOW_METER
    meter_id = int(MeterId.APP if app else MeterId.FLOW)
    out: dict[tuple, OracleDoc] = {}
    key_fields = [f.name for f in TAG_SCHEMA.fields if f.key]

    for r in records:
        ts = int(r["timestamp"])
        window = ts // interval
        meter = {f.name: int(r.get("meter", {}).get(f.name, 0)) for f in schema.fields}

        sig = int(r.get("signal_source", 0))
        is_otel = sig == SignalSource.OTEL
        is_packet = sig == SignalSource.PACKET
        proto = int(r.get("protocol", 0))
        dirs = [int(r.get("direction0", 0)), int(r.get("direction1", 0))]
        active = [int(r.get("is_active_host0", 0)), int(r.get("is_active_host1", 0))]
        vip = [int(r.get("is_vip0", 0)), int(r.get("is_vip1", 0))]

        # whole-record drops (collector.rs:489-493, :684-687, :794)
        if config.inactive_ip_aggregation and not active[0] and not active[1]:
            continue
        l7p = int(r.get("l7_protocol", 0))
        if app and l7p == 0 and not is_otel:
            continue

        def epc_fix(v):
            v = int(v) & 0xFFFF
            return 0 if (v >= 0x8000 and is_otel) else v

        epc = [epc_fix(r.get("l3_epc_id", 0)), epc_fix(r.get("l3_epc_id1", 0))]
        ips = [
            [int(r.get(f"ip0_w{w}", 0)) for w in range(4)],
            [int(r.get(f"ip1_w{w}", 0)) for w in range(4)],
        ]
        macs = [
            (int(r.get("mac0_hi", 0)), int(r.get("mac0_lo", 0))),
            (int(r.get("mac1_hi", 0)), int(r.get("mac1_lo", 0))),
        ]

        ignore_port = (not int(r.get("is_active_service", 0)) and config.inactive_server_port_aggregation) or (
            proto != TCP and proto != UDP
        )
        dst_port = 0 if ignore_port else int(r.get("server_port", 0))

        shared_tag = dict(
            meter_id=meter_id,
            global_thread_id=config.global_thread_id,
            agent_id=config.agent_id,
            is_ipv6=int(r.get("is_ipv6", 0)),
            protocol=proto,
            tap_type=int(r.get("tap_type", 0)),
            signal_source=sig,
            pod_id=int(r.get("pod_id", 0)),
        )
        if app:
            shared_tag.update(
                l7_protocol=l7p,
                endpoint_hash=int(r.get("endpoint_hash", 0)),
                biz_type=int(r.get("biz_type", 0)),
                time_span=int(r.get("time_span", 0)),
            )

        docs: list[tuple[dict, dict]] = []

        # --- single docs ---
        for ep in (0, 1):
            d = dirs[ep]
            if d == 0:
                continue
            pure = (d & _SIDE_MASK) == 0
            dir_ok = (pure or not is_packet) if app else pure
            if not dir_ok:
                continue
            if config.inactive_ip_aggregation and not active[ep]:
                continue
            tag = _empty_tag()
            if config.inactive_ip_aggregation:
                keep_ip = bool(active[ep])
            elif ep == 0:
                keep_ip = (epc[0] != EPC_INTERNET_U16) or is_otel
            else:
                keep_ip = True
            ip = ips[ep] if keep_ip else [0, 0, 0, 0]
            has_mac = bool(vip[ep]) or d == Direction.LOCAL_TO_LOCAL
            if app:
                code = CodeId.SINGLE_MAC_IP_PORT_APP if has_mac else CodeId.SINGLE_IP_PORT_APP
            else:
                code = CodeId.SINGLE_MAC_IP_PORT if has_mac else CodeId.SINGLE_IP_PORT
            tag.update(
                code_id=int(code),
                ip0_w0=ip[0],
                ip0_w1=ip[1],
                ip0_w2=ip[2],
                ip0_w3=ip[3],
                l3_epc_id=epc[ep],
                mac0_hi=macs[ep][0] if has_mac else 0,
                mac0_lo=macs[ep][1] if has_mac else 0,
                direction=d,
                tap_side=_tap_side(d),
                server_port=0 if ep == 0 else dst_port,
                gpid0=int(r.get("gpid0" if ep == 0 else "gpid1", 0)),
                **shared_tag,
            )
            m = meter if (ep == 0 or app) else _reversed_meter(meter, schema)
            docs.append((tag, m))

        # --- edge docs ---
        both_none = dirs[0] == 0 and dirs[1] == 0
        edge_ok = True if app else sig in (SignalSource.PACKET, SignalSource.XFLOW)
        if edge_ok:
            edge_dirs = []
            for ep in (0, 1):
                if dirs[ep] != 0:
                    edge_dirs.append(dirs[ep])
                elif ep == 1 and both_none:
                    edge_dirs.append(Direction.APP if is_otel else Direction.NONE)
            for d in edge_dirs:
                tag = _empty_tag()
                if config.inactive_ip_aggregation:
                    keep0, keep1 = bool(active[0]), bool(active[1])
                else:
                    keep0 = (epc[0] != EPC_INTERNET_U16) or is_otel
                    keep1 = True
                src_ip = ips[0] if keep0 else [0, 0, 0, 0]
                dst_ip = ips[1] if keep1 else [0, 0, 0, 0]
                is_ll = d == Direction.LOCAL_TO_LOCAL
                m0 = macs[0] if (vip[0] or is_ll) else (0, 0)
                m1 = macs[1] if (vip[1] or is_ll) else (0, 0)
                any_mac = any(m0) or any(m1)
                if app:
                    code = CodeId.EDGE_MAC_IP_PORT_APP if any_mac else CodeId.EDGE_IP_PORT_APP
                else:
                    code = CodeId.EDGE_MAC_IP_PORT if any_mac else CodeId.EDGE_IP_PORT
                tag.update(
                    code_id=int(code),
                    ip0_w0=src_ip[0],
                    ip0_w1=src_ip[1],
                    ip0_w2=src_ip[2],
                    ip0_w3=src_ip[3],
                    ip1_w0=dst_ip[0],
                    ip1_w1=dst_ip[1],
                    ip1_w2=dst_ip[2],
                    ip1_w3=dst_ip[3],
                    l3_epc_id=epc[0],
                    l3_epc_id1=epc[1],
                    mac0_hi=m0[0],
                    mac0_lo=m0[1],
                    mac1_hi=m1[0],
                    mac1_lo=m1[1],
                    direction=int(d),
                    tap_side=_tap_side(int(d)),
                    server_port=dst_port,
                    tap_port=int(r.get("tap_port", 0)),
                    gpid0=int(r.get("gpid0", 0)),
                    gpid1=int(r.get("gpid1", 0)),
                    **shared_tag,
                )
                docs.append((tag, meter))

        for tag, m in docs:
            key = (window,) + tuple(tag[k] for k in key_fields)
            if key in out:
                _merge_meter(out[key].meter, m, schema)
            else:
                out[key] = OracleDoc(window=window, tag=dict(tag), meter=dict(m))
    return out


def oracle_l4_rollup(
    records: list[dict],
    config: FanoutConfig,
    interval: int = 1,
) -> dict[tuple, OracleDoc]:
    """records: list of flow dicts (FlowBatch.from_records schema, int
    values + 'meter' sub-dict). Returns {(window, key_tuple): OracleDoc}.
    Key tuple = values of TAG_SCHEMA key columns, matching the device
    fingerprint's equality.
    """
    return _rollup(records, config, interval, app=False)


def oracle_l7_rollup(
    records: list[dict],
    config: FanoutConfig,
    interval: int = 1,
) -> dict[tuple, OracleDoc]:
    """L7 twin of oracle_l4_rollup (fill_l7_stats semantics)."""
    return _rollup(records, config, interval, app=True)
