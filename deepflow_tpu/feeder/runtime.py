"""Feeder runtime — multi-queue fan-in for the fused windowed step.

The fused per-batch jit step (aggregator/pipeline.py) runs at device
rate, but nothing upstream could feed it at rate: the receiver fans
frames into bare OverwriteQueues and every caller hand-rolled its own
batch assembly, so the device idled between host-side decode bursts.
The FPGA sketch-acceleration literature hits the same wall — the sketch
core only reaches line rate once a dedicated feed stage owns
coalescing, padding and result drain-out (arXiv:2504.16896,
arXiv:2503.13515). This module is that stage:

  * **fan-in**: drain N overwrite queues round-robin (optionally
    weighted), rotating the start queue each pump so no queue starves;
  * **shape-bucketed coalescing**: decoded records accumulate in a
    pending buffer and emit as fixed-shape batches from a small set of
    buckets (pad-to-bucket) — the fused step compiles once per bucket
    and NEVER retraces across mixed traffic (JitCacheMonitor's
    expected_compiles budget covers the bucket set);
  * **backpressure + deterministic shedding**: per-queue high/low
    watermarks with hysteresis; a queue above its high watermark gets a
    doubled drain budget but only the NEWEST half is admitted — the
    oldest frames are shed WHOLE (never partial batches), counted
    per-frame via a header peek (no decode), and accounted both in the
    feeder's Countable counters (→ deepflow_system via the stats
    sinks) and in the device counter block's CB_FEEDER_SHED lane on
    the next dispatched batch;
  * **double-buffered upload**: the pipeline sink stages batch i+1's
    packed tag matrix (async device put) before dispatching batch i,
    mirroring `async_drain` on the output side.

Sinks adapt the record plane to each window controller:
`PipelineFeedSink` (flow records → RollupPipeline's fused step),
`WindowManagerFeedSink` (pb Documents via ingest/codec.py → the
doc-level WindowManager append), `ShardedFeedSink` (flow records → one
ShardedWindowManager per shard group; run one FeederRuntime per group).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from ..datamodel.batch import FlowBatch
from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_message_spans
from ..utils.spans import (
    SPAN_FEEDER_COALESCE,
    SPAN_FEEDER_DISPATCH,
    SPAN_FEEDER_DRAIN,
    SpanTracer,
)
from ..utils.stats import register_countable
from .flowframe import decode_flowframe_body, peek_rows

# ---------------------------------------------------------------------------
# record chunks — what decoded frames become inside the pending buffer


@dataclasses.dataclass
class FlowChunk:
    """Flow records (pre-fanout), wrapping a FlowBatch."""

    fb: FlowBatch

    @property
    def rows(self) -> int:
        return self.fb.size

    def split(self, n: int) -> tuple["FlowChunk", "FlowChunk"]:
        return FlowChunk(self.fb.slice(0, n)), FlowChunk(self.fb.slice(n, self.fb.size))


@dataclasses.dataclass
class DocChunk:
    """Decoded Documents (post-fanout) for the doc-level append path."""

    timestamp: np.ndarray  # [n] u32
    tags: np.ndarray  # [n, T] u32 (TAG_SCHEMA order)
    meters: np.ndarray  # [n, M] f32

    @property
    def rows(self) -> int:
        return int(self.timestamp.shape[0])

    def split(self, n: int) -> tuple["DocChunk", "DocChunk"]:
        a = DocChunk(self.timestamp[:n], self.tags[:n], self.meters[:n])
        b = DocChunk(self.timestamp[n:], self.tags[n:], self.meters[n:])
        return a, b


# ---------------------------------------------------------------------------
# sinks


class _FlowFrameCodec:
    """Shared decode face for sinks that eat flowframe (TAGGEDFLOW)
    frames."""

    def count_records(self, raw: bytes) -> int:
        body = raw[HEADER_LEN:]
        return sum(peek_rows(body[o : o + ln]) for o, ln in split_message_spans(body))

    def decode_frame(self, raw: bytes) -> FlowChunk | None:
        header = FlowHeader.parse(raw[:HEADER_LEN])
        if header.msg_type != int(MessageType.TAGGEDFLOW):
            raise ValueError(f"flow sink got msg_type {header.msg_type}")
        body = raw[HEADER_LEN:]
        parts = [
            decode_flowframe_body(body[o : o + ln])
            for o, ln in split_message_spans(body)
        ]
        if not parts:
            return None
        return FlowChunk(FlowBatch.concat(parts))


class PipelineFeedSink(_FlowFrameCodec):
    """Flow records → RollupPipeline (the fused windowed step), with the
    double-buffered upload: `emit` STAGES the new batch (async device
    put) and dispatches the PREVIOUSLY staged one, so the tag-matrix
    transfer of batch i+1 overlaps batch i's in-flight compute. Outputs
    therefore trail by one emitted batch until flush()."""

    def __init__(self, pipeline, *, double_buffer: bool = True):
        if not pipeline.config.bucket_sizes:
            raise ValueError(
                "PipelineFeedSink needs PipelineConfig.bucket_sizes — the "
                "feeder's pad-to-bucket contract is what keeps the fused "
                "step from retracing"
            )
        self.pipeline = pipeline
        self.double_buffer = double_buffer
        self.bucket_sizes = tuple(pipeline.config.bucket_sizes)
        self._held = None  # (StagedBatch, shed) awaiting dispatch
        self._shed_carry = 0  # shed count whose batch had no valid rows

    def emit(self, chunks: list[FlowChunk], rows: int, bucket: int, shed: int) -> list:
        fb = FlowBatch.concat([c.fb for c in chunks])
        assert fb.size == rows
        shed += self._shed_carry
        self._shed_carry = 0
        staged = self.pipeline.stage(fb)  # pads to `bucket`, starts upload
        out = self.flush()  # dispatch the previously staged batch
        if staged is None:  # all-padding emit — carry its shed forward
            self._shed_carry = shed
        elif self.double_buffer:
            self._held = (staged, shed)
        else:
            out += self.pipeline.ingest_staged(staged, feeder_shed=shed)
        return out

    def flush(self) -> list:
        """Dispatch the held double-buffered batch, if any."""
        if self._held is None:
            return []
        held, held_shed = self._held
        self._held = None
        return self.pipeline.ingest_staged(held, feeder_shed=held_shed)


class ShardedFeedSink(_FlowFrameCodec):
    """Flow records → ShardedWindowManager (one feeder per shard
    group). Buckets must be divisible by the mesh's device count — the
    sharded step splits the leading dim evenly across devices."""

    def __init__(self, swm, bucket_sizes: tuple[int, ...]):
        d = swm.pipe.n_devices
        bad = [b for b in bucket_sizes if b % d]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} not divisible by device count {d}"
            )
        self.swm = swm
        self.bucket_sizes = tuple(bucket_sizes)
        self.feeder_shed = 0  # sharded path has no device counter block

    def emit(self, chunks: list[FlowChunk], rows: int, bucket: int, shed: int) -> list:
        fb = FlowBatch.concat([c.fb for c in chunks]).pad_to(bucket)
        self.feeder_shed += shed
        return self.swm.ingest(fb.tags, fb.meters, fb.valid)

    def flush(self) -> list:
        return []


class WindowManagerFeedSink:
    """pb Documents (METRICS lane, ingest/codec.py) → the doc-level
    WindowManager append. Keys are the packed-word fingerprints
    computed host-side with the SAME plan the device uses
    (DOC_KEY_PACK + fingerprint64_words), so feeder-fed rows merge with
    device-fingerprinted rows for the same logical key."""

    def __init__(self, wm, bucket_sizes: tuple[int, ...], *, meter_id=None, decoder=None):
        from ..datamodel.code import MeterId
        from ..ingest.codec import DocumentDecoder

        self.wm = wm
        self.bucket_sizes = tuple(bucket_sizes)
        self.meter_id = int(MeterId.FLOW if meter_id is None else meter_id)
        self.decoder = decoder if decoder is not None else DocumentDecoder()
        self.other_meter_rows = 0  # decoded docs of non-target meter types

    def count_records(self, raw: bytes) -> int:
        return len(split_message_spans(raw[HEADER_LEN:]))

    def decode_frame(self, raw: bytes) -> DocChunk | None:
        body = raw[HEADER_LEN:]
        spans = split_message_spans(body)
        batches = self.decoder.decode_parts([(body, spans)])
        chunk = None
        for meter_id, db in batches.items():
            if meter_id != self.meter_id:
                self.other_meter_rows += db.tags.shape[0]
                continue
            chunk = DocChunk(db.timestamp, db.tags, db.meters)
        return chunk

    def emit(self, chunks: list[DocChunk], rows: int, bucket: int, shed: int) -> list:
        from ..datamodel.code import DOC_KEY_PACK, pack_tag_words
        from ..datamodel.schema import TAG_SCHEMA
        from ..ops.hashing import fingerprint64_words

        ts = np.zeros(bucket, dtype=np.uint32)
        tags = np.zeros((bucket, TAG_SCHEMA.num_fields), dtype=np.uint32)
        meters = np.zeros((bucket, self.wm.meter_schema.num_fields), dtype=np.float32)
        valid = np.zeros(bucket, dtype=bool)
        off = 0
        for c in chunks:
            n = c.rows
            ts[off : off + n] = c.timestamp
            tags[off : off + n] = c.tags
            meters[off : off + n] = c.meters
            valid[off : off + n] = True
            off += n
        assert off == rows
        cols = {
            f: tags[:, TAG_SCHEMA.index(f)] for f in DOC_KEY_PACK.field_names()
        }
        hi, lo = fingerprint64_words(pack_tag_words(cols, DOC_KEY_PACK, np), xp=np)
        return self.wm.ingest(
            ts, hi.astype(np.uint32), lo.astype(np.uint32),
            np.ascontiguousarray(tags.T), np.ascontiguousarray(meters.T),
            valid, feeder_shed=shed,
        )

    def flush(self) -> list:
        return []


# ---------------------------------------------------------------------------
# the runtime


@dataclasses.dataclass(frozen=True)
class FeederConfig:
    # frames a queue may contribute per visit (scaled by its weight)
    frames_per_queue: int = 16
    # queue visits per pump() = rounds × len(queues)
    rounds_per_pump: int = 4
    # per-queue depth watermarks, as a fraction of queue capacity, with
    # hysteresis: ≥ high enters pressure (doubled drain budget, oldest
    # half shed), ≤ low leaves it
    high_watermark: float = 0.75
    low_watermark: float = 0.25
    # relative drain weights per queue (None = equal); a weight-2 queue
    # contributes 2× frames_per_queue per visit
    weights: tuple[int, ...] | None = None
    # emit the sub-bucket tail at the end of each pump (freshness) —
    # off, records wait for a full max-size bucket (efficiency)
    emit_partial: bool = True


class FeederRuntime:
    """Drains N overwrite queues into shape-bucketed batches for one
    windowed sink. Drive it explicitly with `pump()` (bench/tests) or
    via the `serve()` polling thread."""

    def __init__(
        self,
        queues: list,
        sink,
        config: FeederConfig = FeederConfig(),
        *,
        name: str = "feeder",
        tracer: SpanTracer | None = None,
    ):
        if not queues:
            raise ValueError("need at least one queue")
        if config.weights is not None and len(config.weights) != len(queues):
            raise ValueError(
                f"{len(config.weights)} weights for {len(queues)} queues"
            )
        if not getattr(sink, "bucket_sizes", None):
            raise ValueError("sink must declare bucket_sizes")
        self.queues = list(queues)
        self.sink = sink
        self.config = config
        self.buckets = tuple(sorted(sink.bucket_sizes))
        self.name = name
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.feeder"
        )
        self._weights = config.weights or (1,) * len(queues)
        self._pressure = [False] * len(queues)
        self._chunks: deque = deque()
        self._rows = 0
        self._shed_pending = 0  # records shed since the last emit
        self._rr = 0  # rotating first-queue index (starvation-proof)
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.counters = {
            "frames_in": 0,
            "records_in": 0,
            "bad_frames": 0,
            "batches_out": 0,
            "records_out": 0,
            "pad_rows": 0,
            "shed_frames": 0,
            "shed_records": 0,
            "pressure_events": 0,
        }
        register_countable("tpu_feeder", self, name=name)
        register_countable("tpu_feeder_spans", self.tracer, name=name)

    # -- countable face --------------------------------------------------
    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
        out["pending_rows"] = self._rows
        out["queue_overwritten"] = sum(
            int(getattr(q, "overwritten", 0)) for q in self.queues
        )
        out["queues_in_pressure"] = sum(self._pressure)
        return out

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- drain + shed ----------------------------------------------------
    def _visit(self, i: int, admit: list) -> int:
        """Drain queue i once; append admitted frames, shed the rest.
        Returns frames drained. Deterministic: the decision depends
        only on queue depth at visit time and the configured
        watermarks (the shed-policy test pins this)."""
        q = self.queues[i]
        budget = self._weights[i] * self.config.frames_per_queue
        cap = int(getattr(q, "capacity", 0) or 0)
        if cap:
            depth = len(q)
            if not self._pressure[i] and depth >= self.config.high_watermark * cap:
                self._pressure[i] = True
                self._count("pressure_events")
            elif self._pressure[i] and depth <= self.config.low_watermark * cap:
                self._pressure[i] = False
        if self._pressure[i]:
            # pressure: drain twice the budget to burn the backlog down,
            # admit only the NEWEST `budget` frames, shed the oldest
            # WHOLE (the OverwriteQueue stance — freshest data wins) and
            # account every dropped record via the header peek
            drained = q.gets(2 * budget, timeout_ms=0)
            cut = max(len(drained) - budget, 0)
            for raw in drained[:cut]:
                self._count("shed_frames")
                n = self.sink.count_records(raw)
                self._count("shed_records", n)
                with self._lock:
                    self._shed_pending += n
            admit.extend(drained[cut:])
            return len(drained)
        drained = q.gets(budget, timeout_ms=0)
        admit.extend(drained)
        return len(drained)

    # -- coalescing ------------------------------------------------------
    def _take(self, n: int) -> list:
        """Pop exactly n rows of chunks from the pending buffer."""
        out = []
        need = n
        while need > 0:
            c = self._chunks.popleft()
            if c.rows <= need:
                out.append(c)
                need -= c.rows
            else:
                head, tail = c.split(need)
                out.append(head)
                self._chunks.appendleft(tail)
                need = 0
        self._rows -= n
        return out

    def _emit(self, rows: int, bucket: int) -> list:
        chunks = self._take(rows)
        with self._lock:
            shed, self._shed_pending = self._shed_pending, 0
        self._count("batches_out")
        self._count("records_out", rows)
        self._count("pad_rows", bucket - rows)
        with self.tracer.span(SPAN_FEEDER_DISPATCH):
            return self.sink.emit(chunks, rows, bucket, shed)

    def _admit(self, chunk, out: list) -> None:
        self._chunks.append(chunk)
        self._rows += chunk.rows
        max_b = self.buckets[-1]
        while self._rows >= max_b:
            out.extend(self._emit(max_b, max_b))

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    # -- the pump --------------------------------------------------------
    def pump(self) -> list:
        """One fan-in cycle: drain every queue (rounds_per_pump visits
        each, rotating the start index), decode + coalesce into bucket
        batches, emit them into the sink, and — with emit_partial —
        flush the sub-bucket tail padded to its smallest bucket.
        Returns whatever the sink's window controller flushed."""
        out: list = []
        nq = len(self.queues)
        for _ in range(self.config.rounds_per_pump):
            admit: list = []
            with self.tracer.span(SPAN_FEEDER_DRAIN):
                drained = 0
                for j in range(nq):
                    drained += self._visit((self._rr + j) % nq, admit)
            self._rr = (self._rr + 1) % nq
            if not admit and not drained:
                break
            with self.tracer.span(SPAN_FEEDER_COALESCE):
                for raw in admit:
                    try:
                        chunk = self.sink.decode_frame(raw)
                    except ValueError:
                        self._count("bad_frames")
                        continue
                    self._count("frames_in")
                    if chunk is None or chunk.rows == 0:
                        continue
                    self._count("records_in", chunk.rows)
                    self._admit(chunk, out)
        if self.config.emit_partial and self._rows > 0:
            out.extend(self._emit(self._rows, self._bucket_for(self._rows)))
        return out

    def flush(self) -> list:
        """Emit every pending record (tail bucket) and push anything the
        sink holds (the double-buffered staged batch); does NOT drain
        the sink's open windows — that stays the owner's shutdown call."""
        out: list = []
        if self._rows > 0:
            out.extend(self._emit(self._rows, self._bucket_for(self._rows)))
        with self.tracer.span(SPAN_FEEDER_DISPATCH):
            out.extend(self.sink.flush())
        return out

    # -- thread ----------------------------------------------------------
    def serve(self, poll_ms: int = 20, on_flush=None) -> None:
        """Background pump loop; `on_flush(outputs)` receives every
        non-empty result (flushed windows must not be dropped on the
        floor by a fire-and-forget loop)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                got = self.pump()
                if got and on_flush is not None:
                    on_flush(got)
                if not got:
                    time.sleep(poll_ms / 1000.0)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)
