"""Feeder runtime — multi-queue fan-in for the fused windowed step.

The fused per-batch jit step (aggregator/pipeline.py) runs at device
rate, but nothing upstream could feed it at rate: the receiver fans
frames into bare OverwriteQueues and every caller hand-rolled its own
batch assembly, so the device idled between host-side decode bursts.
The FPGA sketch-acceleration literature hits the same wall — the sketch
core only reaches line rate once a dedicated feed stage owns
coalescing, padding and result drain-out (arXiv:2504.16896,
arXiv:2503.13515). This module is that stage:

  * **fan-in**: drain N overwrite queues round-robin (optionally
    weighted), rotating the start queue each pump so no queue starves;
  * **shape-bucketed coalescing**: decoded records accumulate in a
    pending buffer and emit as fixed-shape batches from a small set of
    buckets (pad-to-bucket) — the fused step compiles once per bucket
    and NEVER retraces across mixed traffic (JitCacheMonitor's
    expected_compiles budget covers the bucket set);
  * **backpressure + deterministic shedding**: per-queue high/low
    watermarks with hysteresis; a queue above its high watermark gets a
    doubled drain budget but only the NEWEST half is admitted — the
    oldest frames are shed WHOLE (never partial batches), counted
    per-frame via a header peek (no decode), and accounted both in the
    feeder's Countable counters (→ deepflow_system via the stats
    sinks) and in the device counter block's CB_FEEDER_SHED lane on
    the next dispatched batch;
  * **double-buffered upload**: the pipeline sink stages batch i+1's
    packed tag matrix (async device put) before dispatching batch i,
    mirroring `async_drain` on the output side.

Fault tolerance (ISSUE 6) — every failure class on the
feeder→device→flush path is either retried, contained, or counted:

  * **poisoned-frame quarantine**: sink codecs catch ALL decode
    failures at the `decode_frame` boundary (FrameCodecBase), count
    them, and park the head bytes in a bounded quarantine ring —
    corrupt wire data never raises into `pump()`;
  * **graceful degradation**: when a sink dispatch fails even after
    the window manager's transient-retry policy, the runtime flips to
    DEGRADED: drain budgets halve, admitted frames are shed WHOLE and
    counted (`lost_records`/`degraded_shed_records` — no uncounted
    loss), and every `probe_interval` pumps one probe batch flows
    through the full dispatch path; a success flips back to healthy.
    The state machine is (healthy) --emit fail--> (degraded, probe
    countdown) --probe ok--> (healthy);
  * **crash-loop guard**: `serve()` wraps every pump in a containment
    try — a pump exception restarts the loop with capped exponential
    backoff and a counted health state (`pump_errors`,
    `pump_failstreak`) instead of silently killing the daemon thread;
  * **frame journal**: with `journal=` set, every admitted frame is
    appended (pump boundaries marked) BEFORE decode, so recovery =
    restore the window checkpoint + `replay_journal` through the
    normal decode path — bit-exact against an uninterrupted run
    (journal.py has the barrier protocol; `checkpoint()` is the
    flush→snapshot→rotate barrier).

Sinks adapt the record plane to each window controller:
`PipelineFeedSink` (flow records → RollupPipeline's fused step),
`WindowManagerFeedSink` (pb Documents via ingest/codec.py → the
doc-level WindowManager append), `ShardedFeedSink` (flow records → one
ShardedWindowManager per shard group; run one FeederRuntime per group).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from collections import deque
from pathlib import Path

import numpy as np

from .. import chaos
from ..datamodel.batch import FlowBatch
from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_message_spans
from ..utils.spans import (
    SPAN_FEEDER_COALESCE,
    SPAN_FEEDER_DISPATCH,
    SPAN_FEEDER_DRAIN,
    SpanTracer,
)
from ..utils.retry import RetryPolicy, decorrelated_rng
from ..utils.stats import register_countable
from .flowframe import decode_flowframe_body, peek_rows

_log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# record chunks — what decoded frames become inside the pending buffer


@dataclasses.dataclass
class FlowChunk:
    """Flow records (pre-fanout), wrapping a FlowBatch."""

    fb: FlowBatch

    @property
    def rows(self) -> int:
        return self.fb.size

    def split(self, n: int) -> tuple["FlowChunk", "FlowChunk"]:
        return FlowChunk(self.fb.slice(0, n)), FlowChunk(self.fb.slice(n, self.fb.size))


@dataclasses.dataclass
class DocChunk:
    """Decoded Documents (post-fanout) for the doc-level append path."""

    timestamp: np.ndarray  # [n] u32
    tags: np.ndarray  # [n, T] u32 (TAG_SCHEMA order)
    meters: np.ndarray  # [n, M] f32

    @property
    def rows(self) -> int:
        return int(self.timestamp.shape[0])

    def split(self, n: int) -> tuple["DocChunk", "DocChunk"]:
        a = DocChunk(self.timestamp[:n], self.tags[:n], self.meters[:n])
        b = DocChunk(self.timestamp[n:], self.tags[n:], self.meters[n:])
        return a, b


# ---------------------------------------------------------------------------
# sinks

QUARANTINE_KEEP = 8  # poisoned frames retained for diagnosis (head bytes)


class FrameCodecBase:
    """The poisoned-frame quarantine boundary every sink codec shares.

    `decode_frame` NEVER raises: any failure — magic/version/field
    drift, truncation, a decoder bug, an injected chaos fault — is
    counted (`decode_errors`), the frame's head bytes parked in a
    bounded `quarantine` ring, and None returned, so a hostile frame
    is isolated without touching the pump loop (ISSUE 6). Subclasses
    implement `_decode_frame` with the untrusted-edge raise-on-drift
    stance decoders already take."""

    def __init__(self):
        self.decode_errors = 0
        self.quarantine: deque = deque(maxlen=QUARANTINE_KEEP)

    def _decode_frame(self, raw: bytes):
        raise NotImplementedError

    def decode_frame(self, raw: bytes):
        try:
            chaos.maybe_fail(chaos.SITE_DECODE)
            return self._decode_frame(raw)
        except Exception as exc:
            self.decode_errors += 1
            self.quarantine.append(
                (type(exc).__name__, str(exc)[:160], bytes(raw[:64]))
            )
            return None


class _FlowFrameCodec(FrameCodecBase):
    """Shared decode face for sinks that eat flowframe (TAGGEDFLOW)
    frames."""

    def count_records(self, raw: bytes) -> int:
        body = raw[HEADER_LEN:]
        return sum(peek_rows(body[o : o + ln]) for o, ln in split_message_spans(body))

    def _decode_frame(self, raw: bytes) -> FlowChunk | None:
        header = FlowHeader.parse(raw[:HEADER_LEN])
        if header.msg_type != int(MessageType.TAGGEDFLOW):
            raise ValueError(f"flow sink got msg_type {header.msg_type}")
        body = raw[HEADER_LEN:]
        parts = [
            decode_flowframe_body(body[o : o + ln])
            for o, ln in split_message_spans(body)
        ]
        if not parts:
            return None
        return FlowChunk(FlowBatch.concat(parts))


class PipelineFeedSink(_FlowFrameCodec):
    """Flow records → RollupPipeline (the fused windowed step), with the
    double-buffered upload: `emit` STAGES the new batch (async device
    put) and dispatches the PREVIOUSLY staged one, so the tag-matrix
    transfer of batch i+1 overlaps batch i's in-flight compute. Outputs
    therefore trail by one emitted batch until flush().

    Dispatch-failure contract: when the held batch's dispatch raises,
    its rows are counted into `lost_records` and the FRESHLY staged
    batch survives in the double buffer — the runtime's next (probe)
    emit dispatches it, so one device hiccup costs exactly one batch."""

    def __init__(self, pipeline, *, double_buffer: bool = True):
        super().__init__()
        if not pipeline.config.bucket_sizes:
            raise ValueError(
                "PipelineFeedSink needs PipelineConfig.bucket_sizes — the "
                "feeder's pad-to-bucket contract is what keeps the fused "
                "step from retracing"
            )
        self.pipeline = pipeline
        self.double_buffer = double_buffer
        self.bucket_sizes = tuple(pipeline.config.bucket_sizes)
        self._held = None  # (StagedBatch, shed, rows) awaiting dispatch
        self._shed_carry = 0  # shed count whose batch had no valid rows
        self.lost_records = 0  # rows lost to failed dispatches
        # device profiling plane (ISSUE 12): the double-buffered staged
        # upload (tag matrix + meters + valid, device handles awaiting
        # dispatch) is HBM this sink owns — weakly registered so the
        # ledger's tpu_hbm_staged_bytes lane shows the feeder's upload
        # footprint next to the manager's planes
        from ..profiling.ledger import register_profilable

        self._ledger_src = register_profilable("feeder_sink", self)

    def device_planes(self) -> dict:
        held = self._held
        staged = held[0] if held is not None else None
        return {
            "staged": None if staged is None else [
                staged.tag_mat, staged.meters, staged.valid
            ],
        }

    def emit(self, chunks: list[FlowChunk], rows: int, bucket: int, shed: int) -> list:
        fb = FlowBatch.concat([c.fb for c in chunks])
        assert fb.size == rows
        carried = self._shed_carry
        shed += carried
        self._shed_carry = 0
        try:
            staged = self.pipeline.stage(fb)  # pads to `bucket`, starts upload
        except Exception:
            # admission itself failed (e.g. device OOM on the async
            # put): this batch's rows are gone and must be counted, or
            # delivered = records_out − lost_records over-reports. The
            # runtime re-arms only the shed IT passed in, so the carry
            # must go back into the buffer or it undercounts the
            # device-plane feeder_shed lane.
            self.lost_records += rows
            self._shed_carry += carried
            raise
        try:
            out = self.flush()  # dispatch the previously staged batch
        except Exception:
            # the HELD batch failed (flush counted its rows lost); keep
            # the new batch staged for the probe emit. The runtime
            # re-owns `shed` (it re-arms _shed_pending on failure); the
            # carry goes back into the buffer.
            # += not =: flush() may have just deposited the failed
            # batch's own held_shed into the carry
            if staged is not None:
                self._held = (staged, 0, rows)
            self._shed_carry += carried
            raise
        if staged is None:  # all-padding emit — carry its shed forward
            self._shed_carry = shed
        elif self.double_buffer:
            self._held = (staged, shed, rows)
        else:
            try:
                out += self.pipeline.ingest_staged(staged, feeder_shed=shed)
            except Exception:
                # same contract as the stage()/flush() failure paths:
                # the runtime re-arms only the shed IT passed in, so the
                # carried share must go back into the buffer or the
                # device-plane feeder_shed lane permanently undercounts
                self.lost_records += rows
                self._shed_carry += carried
                raise
        return out

    def flush(self) -> list:
        """Dispatch the held double-buffered batch, if any."""
        if self._held is None:
            return []
        held, held_shed, held_rows = self._held
        self._held = None
        try:
            return self.pipeline.ingest_staged(held, feeder_shed=held_shed)
        except Exception:
            # the batch's rows are lost (counted), but its attached shed
            # count must survive into the carry or the device-plane
            # feeder_shed lane permanently undercounts
            self.lost_records += held_rows
            self._shed_carry += held_shed
            raise

    def snapshot(self):
        """Live read plane (ISSUE 10): refresh the pipeline's open
        window snapshot (rate-limited) — the feeder's between-pump
        scheduling hook."""
        return self.pipeline.snapshot_open()


class ShardedFeedSink(_FlowFrameCodec):
    """Flow records → ShardedWindowManager (one feeder per shard
    group). Buckets must be divisible by the mesh's device count — the
    sharded step splits the leading dim evenly across devices."""

    def __init__(self, swm, bucket_sizes: tuple[int, ...]):
        super().__init__()
        d = swm.pipe.n_devices
        bad = [b for b in bucket_sizes if b % d]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} not divisible by device count {d}"
            )
        self.swm = swm
        self.bucket_sizes = tuple(bucket_sizes)
        self.feeder_shed = 0  # sharded path has no device counter block

    def emit(self, chunks: list[FlowChunk], rows: int, bucket: int, shed: int) -> list:
        fb = FlowBatch.concat([c.fb for c in chunks]).pad_to(bucket)
        out = self.swm.ingest(fb.tags, fb.meters, fb.valid)
        # only account the shed once the batch actually landed — on a
        # failed dispatch the runtime re-owns it
        self.feeder_shed += shed
        return out

    def flush(self) -> list:
        return []

    def snapshot(self):
        """Refresh the sharded manager's open-window snapshot (the
        feeder's between-pump live-read hook, ISSUE 10)."""
        return self.swm.snapshot_open()


class WindowManagerFeedSink(FrameCodecBase):
    """pb Documents (METRICS lane, ingest/codec.py) → the doc-level
    WindowManager append. Keys are the packed-word fingerprints
    computed host-side with the SAME plan the device uses
    (DOC_KEY_PACK + fingerprint64_words), so feeder-fed rows merge with
    device-fingerprinted rows for the same logical key."""

    def __init__(self, wm, bucket_sizes: tuple[int, ...], *, meter_id=None, decoder=None):
        from ..datamodel.code import MeterId
        from ..ingest.codec import DocumentDecoder

        super().__init__()
        self.wm = wm
        self.bucket_sizes = tuple(bucket_sizes)
        self.meter_id = int(MeterId.FLOW if meter_id is None else meter_id)
        self.decoder = decoder if decoder is not None else DocumentDecoder()
        self.other_meter_rows = 0  # decoded docs of non-target meter types

    def count_records(self, raw: bytes) -> int:
        return len(split_message_spans(raw[HEADER_LEN:]))

    def _decode_frame(self, raw: bytes) -> DocChunk | None:
        body = raw[HEADER_LEN:]
        spans = split_message_spans(body)
        batches = self.decoder.decode_parts([(body, spans)])
        chunk = None
        for meter_id, db in batches.items():
            if meter_id != self.meter_id:
                self.other_meter_rows += db.tags.shape[0]
                continue
            chunk = DocChunk(db.timestamp, db.tags, db.meters)
        return chunk

    def emit(self, chunks: list[DocChunk], rows: int, bucket: int, shed: int) -> list:
        from ..datamodel.code import DOC_KEY_PACK, pack_tag_words
        from ..datamodel.schema import TAG_SCHEMA
        from ..ops.hashing import fingerprint64_words

        ts = np.zeros(bucket, dtype=np.uint32)
        tags = np.zeros((bucket, TAG_SCHEMA.num_fields), dtype=np.uint32)
        meters = np.zeros((bucket, self.wm.meter_schema.num_fields), dtype=np.float32)
        valid = np.zeros(bucket, dtype=bool)
        off = 0
        for c in chunks:
            n = c.rows
            ts[off : off + n] = c.timestamp
            tags[off : off + n] = c.tags
            meters[off : off + n] = c.meters
            valid[off : off + n] = True
            off += n
        assert off == rows
        cols = {
            f: tags[:, TAG_SCHEMA.index(f)] for f in DOC_KEY_PACK.field_names()
        }
        hi, lo = fingerprint64_words(pack_tag_words(cols, DOC_KEY_PACK, np), xp=np)
        return self.wm.ingest(
            ts, hi.astype(np.uint32), lo.astype(np.uint32),
            np.ascontiguousarray(tags.T), np.ascontiguousarray(meters.T),
            valid, feeder_shed=shed,
        )

    def flush(self) -> list:
        return []


# ---------------------------------------------------------------------------
# the runtime


@dataclasses.dataclass(frozen=True)
class FeederConfig:
    # frames a queue may contribute per visit (scaled by its weight)
    frames_per_queue: int = 16
    # queue visits per pump() = rounds × len(queues)
    rounds_per_pump: int = 4
    # per-queue depth watermarks, as a fraction of queue capacity, with
    # hysteresis: ≥ high enters pressure (doubled drain budget, oldest
    # half shed), ≤ low leaves it
    high_watermark: float = 0.75
    low_watermark: float = 0.25
    # relative drain weights per queue (None = equal); a weight-2 queue
    # contributes 2× frames_per_queue per visit
    weights: tuple[int, ...] | None = None
    # emit the sub-bucket tail at the end of each pump (freshness) —
    # off, records wait for a full max-size bucket (efficiency)
    emit_partial: bool = True
    # pumps between probe dispatches while DEGRADED (ISSUE 6): every
    # probe_interval-th pump lets one batch through the full dispatch
    # path; a success flips the runtime back to healthy
    probe_interval: int = 8
    # serve(): max flushed-output batches held for on_flush redelivery
    # while the callback keeps failing; beyond it the OLDEST are shed
    # and counted (held_outputs_shed lanes) — a broken downstream must
    # not grow the hold list until the process OOMs. 0 = unbounded.
    max_held_outputs: int = 256
    # live read plane (ISSUE 10): refresh the sink's open-window
    # snapshot every N pumps, BETWEEN dispatches — the snapshot read
    # never interleaves into a pump's emit sequence, so the feeder's
    # steady-state ingest fetch budget is untouched (CI-gated,
    # test_perf_gate::test_live_read_budget). The sink must expose
    # `snapshot()` (PipelineFeedSink/ShardedFeedSink → snapshot_open);
    # the refresh keeps the rate-limited snapshot warm so dashboard
    # pulls between pumps return the cached read. 0 = off (pull-only).
    snapshot_interval_pumps: int = 0
    # push query plane (ISSUE 11): the (db, table) the feeder's flushed
    # outputs are attributed to when an event_bus is attached — the
    # WindowClosed/TierClosed events the pump publishes after its last
    # emit carry these, so standing queries over the dogfood table
    # re-evaluate exactly when their data moved
    event_db: str = "deepflow_system"
    event_table: str = "deepflow_system"


class FeederRuntime:
    """Drains N overwrite queues into shape-bucketed batches for one
    windowed sink. Drive it explicitly with `pump()` (bench/tests) or
    via the `serve()` polling thread."""

    def __init__(
        self,
        queues: list,
        sink,
        config: FeederConfig = FeederConfig(),
        *,
        name: str = "feeder",
        tracer: SpanTracer | None = None,
        journal=None,
        event_bus=None,
        lineage=None,
    ):
        if not queues:
            raise ValueError("need at least one queue")
        if config.weights is not None and len(config.weights) != len(queues):
            raise ValueError(
                f"{len(config.weights)} weights for {len(queues)} queues"
            )
        if not getattr(sink, "bucket_sizes", None):
            raise ValueError("sink must declare bucket_sizes")
        self.queues = list(queues)
        self.sink = sink
        self.config = config
        self.buckets = tuple(sorted(sink.bucket_sizes))
        self.name = name
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.feeder"
        )
        self._journal = journal
        # push query plane (ISSUE 11): flushed outputs become
        # WindowClosed/TierClosed events AFTER the pump's last emit —
        # the drain-side hook that turns a window close into an eager
        # cache invalidation + one shared subscription evaluation
        self._event_bus = event_bus
        # window lineage plane (ISSUE 13): the feeder owns the
        # pre-window hops — pump start, receiver-admission pairing and
        # journal appends park in the tracker's pending context and
        # bind to window ids at the sink's dispatch. Every admitted
        # frame must consume exactly one admission stamp; frames the
        # OverwriteQueue silently overwrote never reach the feeder, so
        # each pump drops stamps by the queues' overwritten-counter
        # delta (baseline taken here — pre-attach drops don't count).
        self._lineage = lineage
        self._overwritten_base = sum(
            int(getattr(q, "overwritten", 0)) for q in queues
        )
        self._weights = config.weights or (1,) * len(queues)
        self._pressure = [False] * len(queues)
        self._chunks: deque = deque()
        self._rows = 0
        self._shed_pending = 0  # records shed since the last emit
        self._rr = 0  # rotating first-queue index (starvation-proof)
        self._lock = threading.Lock()
        # serializes pump/flush/checkpoint/replay against each other:
        # a checkpoint racing the serve() thread could otherwise admit
        # (and journal) frames between the barrier flush and
        # sync_offset — below the barrier offset but absent from the
        # snapshot, so replay would skip them (silent loss). RLock:
        # checkpoint() calls flush() re-entrantly.
        self._pump_mutex = threading.RLock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # degraded-mode state machine (ISSUE 6)
        self.degraded = False
        self._probe_now = True
        self._probe_countdown = 0
        self._pump_failstreak = 0  # consecutive serve()-loop pump failures
        self.counters = {
            "frames_in": 0,
            "records_in": 0,
            "bad_frames": 0,
            "batches_out": 0,
            "records_out": 0,
            "pad_rows": 0,
            "shed_frames": 0,
            "shed_records": 0,
            "pressure_events": 0,
            # fault-tolerance lanes
            "emit_failures": 0,
            "lost_records": 0,
            "degraded_entries": 0,
            "degraded_exits": 0,
            "degraded_shed_records": 0,
            "probe_attempts": 0,
            "pump_errors": 0,
            "flush_callback_errors": 0,
            "held_outputs_shed": 0,
            "held_output_shed_records": 0,
            "checkpoint_aborts": 0,
            "replayed_frames": 0,
            # live read plane (ISSUE 10)
            "snapshots_taken": 0,
            "snapshot_errors": 0,
            # push query plane (ISSUE 11)
            "events_published": 0,
        }
        self._pump_count = 0
        self.last_snapshot = None  # most recent scheduled OpenSnapshot
        self._snapshot_err_logged = False
        # False after a checkpoint() that aborted (barrier flush or
        # snapshot save failed) — callers that prune old checkpoints or
        # journals MUST check it before treating the call as durable.
        self.last_checkpoint_ok = True
        self._held_shed_logged = False
        register_countable("tpu_feeder", self, name=name)
        register_countable("tpu_feeder_spans", self.tracer, name=name)

    # -- countable face --------------------------------------------------
    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
        out["pending_rows"] = self._rows
        out["queue_overwritten"] = sum(
            int(getattr(q, "overwritten", 0)) for q in self.queues
        )
        out["queues_in_pressure"] = sum(self._pressure)
        # health lanes: the deepflow_system rows dashboards alert on
        out["degraded"] = int(self.degraded)
        out["pump_failstreak"] = self._pump_failstreak
        out["healthy"] = int(not self.degraded and self._pump_failstreak == 0)
        out["last_checkpoint_ok"] = int(self.last_checkpoint_ok)
        out["decode_errors"] = int(getattr(self.sink, "decode_errors", 0))
        if self._journal is not None:
            for k, v in self._journal.get_counters().items():
                out[f"journal_{k}"] = v
        return out

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- degraded-mode state machine -------------------------------------
    def _count_records_safe(self, raw: bytes) -> int:
        """Header-peek record count that survives corrupt frames — the
        shed accounting must never be the thing that raises."""
        try:
            return self.sink.count_records(raw)
        except Exception:
            return 0

    def _enter_degraded(self) -> None:
        self._probe_countdown = self.config.probe_interval
        self._probe_now = False
        if not self.degraded:
            self.degraded = True
            self._count("degraded_entries")
            _log.warning(
                "feeder %s: sink dispatch failed after retries — entering "
                "degraded mode (shedding, probing every %d pumps)",
                self.name, self.config.probe_interval,
            )

    def _note_emit_ok(self) -> None:
        if self.degraded:
            self.degraded = False
            self._count("degraded_exits")
            _log.warning(
                "feeder %s: probe dispatch succeeded — leaving degraded mode",
                self.name,
            )

    def _probe_tick(self) -> None:
        """Per-pump probe schedule: healthy pumps always dispatch;
        degraded pumps shed until the countdown elapses, then let one
        pump's batches through as the probe."""
        if not self.degraded:
            self._probe_now = True
            return
        self._probe_countdown -= 1
        if self._probe_countdown <= 0:
            self._probe_now = True
            self._probe_countdown = self.config.probe_interval
        else:
            self._probe_now = False

    def _drop_admit_stamp(self) -> None:
        """One admitted frame contributed no rows (bad/empty/shed):
        consume its receiver admission stamp WITHOUT folding it into
        the lineage context, or the FIFO pairing drifts stale
        (ISSUE 13 — every admitted frame must pop exactly one stamp)."""
        if self._lineage is not None:
            self._lineage.drop_stamps(1)

    def _shed_frame(self, raw: bytes) -> None:
        """Degraded-mode shed: whole frames, counted via header peek —
        the same stance as watermark shedding, plus the degraded lane."""
        self._count("shed_frames")
        self._drop_admit_stamp()
        n = self._count_records_safe(raw)
        self._count("shed_records", n)
        self._count("degraded_shed_records", n)
        with self._lock:
            self._shed_pending += n

    # -- drain + shed ----------------------------------------------------
    def _visit(self, i: int, admit: list) -> int:
        """Drain queue i once; append admitted frames, shed the rest.
        Returns frames drained. Deterministic: the decision depends
        only on queue depth at visit time and the configured
        watermarks (the shed-policy test pins this)."""
        q = self.queues[i]
        budget = self._weights[i] * self.config.frames_per_queue
        if self.degraded:
            # shrunk drain budget: a degraded pipeline stops pretending
            # it can keep up — the watermark shed upstream does the rest
            budget = max(1, budget // 2)
        cap = int(getattr(q, "capacity", 0) or 0)
        if cap:
            depth = len(q)
            if not self._pressure[i] and depth >= self.config.high_watermark * cap:
                self._pressure[i] = True
                self._count("pressure_events")
            elif self._pressure[i] and depth <= self.config.low_watermark * cap:
                self._pressure[i] = False
        if self._pressure[i]:
            # pressure: drain twice the budget to burn the backlog down,
            # admit only the NEWEST `budget` frames, shed the oldest
            # WHOLE (the OverwriteQueue stance — freshest data wins) and
            # account every dropped record via the header peek
            drained = q.gets(2 * budget, timeout_ms=0)
            cut = max(len(drained) - budget, 0)
            for raw in drained[:cut]:
                self._count("shed_frames")
                self._drop_admit_stamp()
                n = self._count_records_safe(raw)
                self._count("shed_records", n)
                with self._lock:
                    self._shed_pending += n
            admit.extend(drained[cut:])
            return len(drained)
        drained = q.gets(budget, timeout_ms=0)
        admit.extend(drained)
        return len(drained)

    # -- coalescing ------------------------------------------------------
    def _take(self, n: int) -> list:
        """Pop exactly n rows of chunks from the pending buffer."""
        out = []
        need = n
        while need > 0:
            c = self._chunks.popleft()
            if c.rows <= need:
                out.append(c)
                need -= c.rows
            else:
                head, tail = c.split(need)
                out.append(head)
                self._chunks.appendleft(tail)
                need = 0
        self._rows -= n
        return out

    def _emit(self, rows: int, bucket: int) -> list:
        chunks = self._take(rows)
        if self.degraded:
            # a dispatch attempted while degraded IS the probe — count
            # it here, not in _probe_tick, so idle pumps (which test
            # nothing) never inflate the probe_attempts lane
            self._count("probe_attempts")
        with self._lock:
            shed, self._shed_pending = self._shed_pending, 0
        lost0 = getattr(self.sink, "lost_records", None)
        try:
            with self.tracer.span(SPAN_FEEDER_DISPATCH):
                out = self.sink.emit(chunks, rows, bucket, shed)
        except Exception:
            # containment: the dispatch failed even after the window
            # manager's transient retries. Count what was actually lost
            # (sinks with a double buffer keep the staged batch), re-arm
            # the un-delivered shed so the device lane still sees it on
            # the next successful batch, and flip to degraded.
            lost = rows if lost0 is None else self.sink.lost_records - lost0
            self._count("emit_failures")
            self._count("lost_records", lost)
            # records_out counts rows that LEFT the coalescing buffer in
            # both outcomes (conservation: records_in = records_out +
            # pending_rows always holds); delivered = records_out −
            # lost_records
            self._count("records_out", rows)
            with self._lock:
                self._shed_pending += lost + shed
            self._enter_degraded()
            return []
        self._note_emit_ok()
        self._count("batches_out")
        self._count("records_out", rows)
        self._count("pad_rows", bucket - rows)
        return out

    def _admit(self, chunk, out: list) -> None:
        self._chunks.append(chunk)
        self._rows += chunk.rows
        max_b = self.buckets[-1]
        while self._rows >= max_b:
            out.extend(self._emit(max_b, max_b))
            if self.degraded:
                # the emit just failed — stop hammering the device; the
                # remaining pending rows wait for the probe
                break

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def _process_frame(self, raw: bytes, out: list) -> None:
        """Decode one admitted frame through the sink codec and coalesce
        it — the single path pump() and replay_journal() share, so
        recovery exercises no special-case decode code."""
        errs0 = int(getattr(self.sink, "decode_errors", 0))
        try:
            chunk = self.sink.decode_frame(raw)
        except Exception:
            # sinks quarantine internally (FrameCodecBase); this guard
            # covers foreign sink implementations only
            self._count("bad_frames")
            self._drop_admit_stamp()
            return
        if int(getattr(self.sink, "decode_errors", 0)) > errs0:
            self._count("bad_frames")  # quarantined by the codec
            self._drop_admit_stamp()
            return
        self._count("frames_in")
        if chunk is None or chunk.rows == 0:
            self._drop_admit_stamp()
            return
        if self._lineage is not None:
            # pair this admitted frame with its receiver admission
            # stamp (FIFO) — opens the receiver.admit hop in the
            # pending context
            self._lineage.note_frames(1)
        self._count("records_in", chunk.rows)
        self._admit(chunk, out)

    # -- the pump --------------------------------------------------------
    def pump(self) -> list:
        """One fan-in cycle: drain every queue (rounds_per_pump visits
        each, rotating the start index), decode + coalesce into bucket
        batches, emit them into the sink, and — with emit_partial —
        flush the sub-bucket tail padded to its smallest bucket.
        Returns whatever the sink's window controller flushed."""
        with self._pump_mutex:
            return self._pump_locked()

    def _pump_locked(self) -> list:
        out: list = []
        if self._lineage is not None:
            self._lineage.begin_pump()
            # frames lost to queue OVERWRITE never reach _process_frame
            # — consume their admission stamps here or the FIFO pairing
            # drifts stale under sustained backpressure
            ow = sum(int(getattr(q, "overwritten", 0)) for q in self.queues)
            if ow > self._overwritten_base:
                self._lineage.drop_stamps(ow - self._overwritten_base)
            self._overwritten_base = ow
        self._probe_tick()
        dispatch0 = self.counters["batches_out"] + self.counters["emit_failures"]
        nq = len(self.queues)
        for _ in range(self.config.rounds_per_pump):
            admit: list = []
            with self.tracer.span(SPAN_FEEDER_DRAIN):
                drained = 0
                for j in range(nq):
                    drained += self._visit((self._rr + j) % nq, admit)
            self._rr = (self._rr + 1) % nq
            if not admit and not drained:
                break
            with self.tracer.span(SPAN_FEEDER_COALESCE):
                # one shed decision per round: frames the live run
                # sheds-and-counts are NOT journaled — replay would
                # resurrect rows the counters already declared shed,
                # double-accounting them across the shed and delivered
                # lanes
                shedding = self.degraded and not self._probe_now
                # journal the WHOLE admitted round before touching the
                # device: a kill anywhere downstream (dispatch, fetch,
                # flush) then loses nothing the journal can't replay
                if self._journal is not None and not shedding:
                    j0 = (self._lineage.clock()
                          if self._lineage is not None else 0.0)
                    for raw in admit:
                        self._journal.append(raw)
                    if self._lineage is not None and admit:
                        self._lineage.note_journal(j0)
                for raw in admit:
                    if shedding:
                        self._shed_frame(raw)
                        continue
                    self._process_frame(raw, out)
        if (
            self.config.emit_partial
            and self._rows > 0
            and (self._probe_now or not self.degraded)
        ):
            out.extend(self._emit(self._rows, self._bucket_for(self._rows)))
        if self._journal is not None:
            self._journal.mark()
        if (
            self.degraded
            and self._probe_now
            and self.counters["batches_out"] + self.counters["emit_failures"]
            == dispatch0
        ):
            # the probe pump had no data to send, so nothing was tested:
            # keep the probe armed instead of re-arming the countdown —
            # otherwise a feeder that goes idle while degraded sheds the
            # first frames that arrive after the device already recovered
            self._probe_countdown = 0
        # live snapshot scheduling (ISSUE 10): AFTER the pump's last
        # emit, BEFORE the next pump's first dispatch — the read-only
        # snapshot never stalls the feed path, and snapshot_open's rate
        # limit makes an over-eager schedule harmless. Guarded: a broken
        # snapshot path degrades the live view, never the pump.
        if self.config.snapshot_interval_pumps > 0:
            self._pump_count += 1
            if (
                self._pump_count % self.config.snapshot_interval_pumps == 0
                and hasattr(self.sink, "snapshot")
            ):
                try:
                    self.last_snapshot = self.sink.snapshot()
                    self._count("snapshots_taken")
                except Exception:
                    self._count("snapshot_errors")
                    if not self._snapshot_err_logged:
                        self._snapshot_err_logged = True
                        _log.exception(
                            "feeder %s: open-window snapshot failed — live "
                            "reads degrade to flushed-only", self.name,
                        )
                else:
                    self._publish_snapshot_event()
        self._publish_events(out)
        return out

    # -- push events (ISSUE 11) ------------------------------------------
    def _publish_events(self, out: list) -> None:
        """Flushed outputs → one WindowClosed/TierClosed batch on the
        attached bus. One publish per pump, so K windows closed by one
        drain reach every standing query as ONE delivery (the
        coalescing contract subscriptions/alerts pin). Guarded: the
        event plane must never stall or fail the drain."""
        if self._event_bus is None or not out:
            return
        try:
            from ..querier.events import docbatch_events

            events = docbatch_events(
                out, db=self.config.event_db, table=self.config.event_table
            )
            if events:
                n = self._event_bus.publish(events)
                self._count("events_published", n)
        except Exception:
            _log.debug("feeder %s: event publish failed (contained)",
                       self.name, exc_info=True)

    def _publish_snapshot_event(self) -> None:
        if self._event_bus is None or self.last_snapshot is None:
            return
        try:
            from ..querier.events import SnapshotAdvanced

            n = self._event_bus.publish(SnapshotAdvanced(
                self.config.event_db, self.config.event_table,
                int(getattr(self.last_snapshot, "seq", 0)),
            ))
            self._count("events_published", n)
        except Exception:
            _log.debug("feeder %s: snapshot event publish failed (contained)",
                       self.name, exc_info=True)

    def flush(self) -> list:
        """Emit every pending record (tail bucket) and push anything the
        sink holds (the double-buffered staged batch); does NOT drain
        the sink's open windows — that stays the owner's shutdown call."""
        with self._pump_mutex:
            out: list = []
            if self._rows > 0:
                out.extend(self._emit(self._rows, self._bucket_for(self._rows)))
            lost0 = getattr(self.sink, "lost_records", None)
            try:
                with self.tracer.span(SPAN_FEEDER_DISPATCH):
                    out.extend(self.sink.flush())
            except Exception:
                lost = 0 if lost0 is None else self.sink.lost_records - lost0
                self._count("emit_failures")
                self._count("lost_records", lost)
                with self._lock:
                    self._shed_pending += lost
                self._enter_degraded()
            self._publish_events(out)
            return out

    # -- journal recovery ------------------------------------------------
    def checkpoint(self, save) -> list:
        """The flush→snapshot→rotate checkpoint barrier.

        Flushes every pending row and the sink's staged batch (so the
        window state covers all admitted frames), calls `save(barrier)`
        — a closure around e.g. checkpoint.save_window_state, with
        `barrier` = {"journal_epoch", "journal_offset"} to embed in the
        snapshot meta — then rotates the journal. Returns every output
        the barrier flushed (including whatever `save` returns, e.g.
        save_window_state's in-flight windows); callers must emit them
        BEFORE treating the checkpoint as durable.

        If the barrier flush itself fails to deliver (a sink dispatch
        error), the checkpoint ABORTS — counted (`checkpoint_aborts`)
        and logged, snapshot not written, journal not rotated. The
        failed rows' journal records are the only replayable copy left;
        snapshotting without them and rotating would convert a
        transient failure into permanent loss. The previous checkpoint
        plus the intact journal still recover everything. The returned
        outputs look identical either way, so `last_checkpoint_ok`
        (also a get_counters lane) records per-call success — callers
        that prune older checkpoints/journals after this call MUST
        check it, or an abort turns their pruning into permanent loss.

        Safe to call from any thread while serve() runs: the pump
        mutex holds the barrier (flush → sync_offset → save → rotate)
        closed against concurrent admits — a frame journaled between
        the flush and the barrier offset would be skipped by replay
        yet missing from the snapshot."""
        with self._pump_mutex:
            ef0 = self.counters["emit_failures"]
            out = self.flush()
            if self.counters["emit_failures"] > ef0:
                self.last_checkpoint_ok = False
                self._count("checkpoint_aborts")
                _log.warning(
                    "feeder %s: checkpoint aborted — the barrier flush failed "
                    "to deliver; journal kept (not rotated), snapshot not "
                    "written", self.name,
                )
                return out
            # a snapshot failure must not take the barrier flush's
            # outputs down with it: those windows already left the
            # manager state and the caller is their only route out.
            # Abort (counted), deliver `out`, keep the journal — the
            # old checkpoint + un-rotated journal still recover
            # everything. KillPoint is a BaseException and still
            # pierces (process death must not be absorbed).
            try:
                barrier = None
                if self._journal is not None:
                    epoch, off = self._journal.sync_offset()
                    barrier = {"journal_epoch": epoch, "journal_offset": off}
                res = save(barrier)
            except Exception:
                self.last_checkpoint_ok = False
                self._count("checkpoint_aborts")
                _log.exception(
                    "feeder %s: checkpoint aborted — snapshot save failed; "
                    "journal kept (not rotated), flushed outputs delivered",
                    self.name,
                )
                return out
            if res:
                out.extend(res)
                self._publish_events(res)  # barrier-flushed windows push too
            if self._journal is not None:
                self._journal.rotate()
            self.last_checkpoint_ok = True
            return out

    def quiesce(self, save, *, max_pumps: int = 64) -> list:
        """Drain-to-barrier for an ownership handover (ISSUE 15): pump
        until every queue is empty and no rows are pending, then run
        the flush→snapshot→rotate checkpoint barrier. The resulting
        snapshot + rotated journal are the complete transferable state
        of this feeder's sink — the old owner of a rebalancing shard
        group calls this, the new owner restores from what it wrote.

        Loud by contract: a queue whose backlog stops SHRINKING across
        a full pump (a producer still feeding it — the caller must
        fence admission FIRST, e.g. by flipping the receiver's route
        epoch) or an aborted barrier checkpoint raises
        RebalanceAbortError — a handover must never publish state it
        is not sure is complete. `max_pumps` is slack on top of the
        backlog-sized budget (each pump drains a bounded frame budget,
        so a large FENCED backlog legitimately needs many pumps — the
        abort keys on progress, not an iteration count). Returns every
        output the drain and barrier flushed; the caller emits them
        before treating the handover as durable (the checkpoint()
        contract)."""
        from ..chaos import RebalanceAbortError

        with self._pump_mutex:
            out: list = []
            qlen = sum(len(q) for q in self.queues)
            # fenced admission ⇒ every pump strictly shrinks the
            # backlog ⇒ at most one pump per queued frame (+ slack for
            # pending-row tail emits); unfenced admission trips the
            # no-progress check long before this budget
            for _ in range(qlen + max_pumps):
                out.extend(self.pump())
                if self._rows == 0 and all(
                    len(q) == 0 for q in self.queues
                ):
                    break
                now_qlen = sum(len(q) for q in self.queues)
                if now_qlen >= qlen and now_qlen > 0:
                    err = RebalanceAbortError(
                        f"feeder {self.name}: queue backlog did not "
                        f"shrink across a quiesce pump ({qlen} → "
                        f"{now_qlen} frames) — admission was not "
                        "fenced before the handover (flip the route "
                        "epoch first)"
                    )
                    err.outputs = out  # already-flushed windows must
                    # still reach the caller: the abort cancels the
                    # MOVE, not the drain's deliveries
                    raise err
                qlen = now_qlen
            else:
                err = RebalanceAbortError(
                    f"feeder {self.name}: rows still pending after the "
                    "quiesce pump budget — the sink is not draining"
                )
                err.outputs = out
                raise err
            out.extend(self.checkpoint(save))
            if not self.last_checkpoint_ok:
                err = RebalanceAbortError(
                    f"feeder {self.name}: handover barrier checkpoint "
                    "aborted — state not transferable; the previous "
                    "checkpoint and the un-rotated journal still "
                    "recover everything on THIS host"
                )
                err.outputs = out
                raise err
            return out

    def replay_journal(self, path, *, barrier: dict | None = None) -> list:
        """Recovery: replay a (crashed) feeder's journal through the
        NORMAL decode path. FRAME records flow through _process_frame
        (same coalescing, same bucket emits), MARK records re-create
        the pump-boundary tail emits — so batch boundaries, and
        therefore f32 meter fold order and flushed rows, are bit-exact
        vs the uninterrupted run. `barrier` (from the checkpoint meta)
        skips records the snapshot already covers when the crash landed
        between save and rotate; a rotated journal (epoch advanced)
        replays in full. Frames are re-journaled into THIS runtime's
        journal, so recovery itself is crash-safe. After the replay,
        call pump(): it completes the interrupted pump's tail emit.

        Replaying from THIS runtime's own journal path (the natural
        fixed-path restart) is safe: the entries are read up front and
        the live journal is rotated first, so replayed frames are
        re-appended exactly once into the fresh epoch instead of
        duplicated behind their originals — a second crash would
        otherwise double-apply every one of them."""
        from .journal import REC_FRAME, REC_MARK, read_journal

        with self._pump_mutex:
            out: list = []
            epoch, entries, truncated = read_journal(path)
            if self._journal is not None:
                try:
                    aliased = Path(path).resolve() == self._journal.path.resolve()
                except OSError:
                    aliased = False
                if aliased:
                    self._journal.rotate()
            skip_off = -1
            if barrier and barrier.get("journal_epoch") == epoch:
                skip_off = int(barrier.get("journal_offset", 0))
            if truncated:
                _log.warning(
                    "feeder %s: journal %s has a torn tail (crash mid-write) — "
                    "replaying the clean prefix", self.name, path,
                )
            for kind, payload, off in entries:
                if off < skip_off:
                    continue
                if kind == REC_FRAME:
                    if self._journal is not None:
                        self._journal.append(payload)
                    self._count("replayed_frames")
                    self._process_frame(payload, out)
                elif kind == REC_MARK:
                    if self.config.emit_partial and self._rows > 0:
                        out.extend(self._emit(self._rows, self._bucket_for(self._rows)))
                    if self._journal is not None:
                        self._journal.mark()
            return out

    # -- thread ----------------------------------------------------------
    def _hold_for_redelivery(self, held: list, new: list) -> list:
        """Extend the serve() redelivery buffer, bounded by
        config.max_held_outputs: while on_flush keeps failing the pump
        keeps producing, and an unbounded hold list turns a broken
        downstream into an OOM. Beyond the cap the OLDEST outputs are
        shed and counted (held_outputs_shed / held_output_shed_records)
        — the same counted-shedding contract as every other overflow
        lane, logged once per overflow episode."""
        held.extend(new)
        cap = self.config.max_held_outputs
        if cap and len(held) > cap:
            drop = len(held) - cap
            shed, held = held[:drop], held[drop:]
            rows = sum(
                int(getattr(o, "size", 0) or getattr(o, "count", 0) or 0)
                for o in shed
            )
            self._count("held_outputs_shed", drop)
            self._count("held_output_shed_records", rows)
            if not self._held_shed_logged:
                self._held_shed_logged = True
                _log.error(
                    "feeder %s: on_flush redelivery buffer overflowed — shed "
                    "%d oldest output batches (%d records); downstream has "
                    "been failing past max_held_outputs=%d",
                    self.name, drop, rows, cap,
                )
        return held

    def serve(self, poll_ms: int = 20, on_flush=None) -> None:
        """Background pump loop; `on_flush(outputs)` receives every
        non-empty result (flushed windows must not be dropped on the
        floor by a fire-and-forget loop). Crash-loop guard (ISSUE 6):
        a pump exception is counted (`pump_errors`) and the loop
        restarts with capped exponential backoff — the daemon thread
        never dies silently; `pump_failstreak`/`healthy` expose the
        state. An `on_flush` exception is counted separately
        (`flush_callback_errors`) and its outputs are HELD and
        re-delivered on the next loop — at-least-once up to
        config.max_held_outputs, beyond which the oldest are shed and
        counted (never silently dropped)."""
        if self._thread is not None:
            return
        self._stop.clear()
        idle = poll_ms / 1000.0
        # shared backoff policy, decorrelated per instance: N feeder
        # daemons recovering from the same device fault must not retry
        # in lockstep (the herd the jitter exists to break)
        policy = RetryPolicy(
            base_delay_s=idle, max_delay_s=5.0, multiplier=2.0, jitter=0.5
        )
        rng = decorrelated_rng(hash(self.name) & 0xFFFF)

        def run():
            cb_failstreak = 0
            undelivered: list = []
            while not self._stop.is_set():
                try:
                    got = self.pump()
                except Exception:
                    self._count("pump_errors")
                    self._pump_failstreak += 1
                    if self._pump_failstreak == 1:
                        _log.exception(
                            "feeder %s: pump failed — restarting loop with "
                            "backoff", self.name,
                        )
                    self._stop.wait(policy.delay(self._pump_failstreak, rng))
                    continue
                if self._pump_failstreak:
                    _log.warning(
                        "feeder %s: pump loop recovered after %d failures",
                        self.name, self._pump_failstreak,
                    )
                    self._pump_failstreak = 0
                # flushed windows are held and re-delivered until
                # on_flush accepts them (a callback that raises mid-way
                # may see a window twice); the hold is BOUNDED — see
                # _hold_for_redelivery
                if on_flush is not None:
                    undelivered = self._hold_for_redelivery(undelivered, got)
                if undelivered and on_flush is not None:
                    batch, undelivered = undelivered, []
                    try:
                        on_flush(batch)
                    except Exception:
                        undelivered = batch
                        cb_failstreak += 1
                        self._count("flush_callback_errors")
                        _log.exception(
                            "feeder %s: on_flush failed — holding %d "
                            "outputs for redelivery", self.name, len(batch),
                        )
                        self._stop.wait(policy.delay(cb_failstreak, rng))
                        continue
                    cb_failstreak = 0
                    self._held_shed_logged = False
                if not got:
                    self._stop.wait(idle)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)
