"""Bounded on-disk frame journal — the crash-safety twin of the
window-state checkpoint (ISSUE 6).

The reference survives ingester restarts because committed telemetry
sits behind a durable queue boundary; our device-resident window state
loses every open window on a crash. The recovery contract is the
classic journal+snapshot pair:

    recovered state = load_window_state(checkpoint)
                    + replay(journal frames admitted since the barrier)

through the NORMAL decode path (the feeder's sink codecs), so replay
exercises zero special-case code.

File layout (little-endian):

    header   'DFJH' u32 | version u32 | epoch u32
    record   'DFJR' u32 | kind u8 | len u32 | crc32(payload) u32 | payload

Record kinds: FRAME (a raw wire frame, exactly as admitted) and MARK
(a pump boundary — replay re-creates the same batch coalescing the
live run produced, which is what makes recovery bit-exact against an
uninterrupted oracle: f32 meter sums are replayed in the identical
fold order).

Crash-safety properties:

  * appends are buffered, MARKs flush (optionally fsync) — a crash
    mid-record leaves a truncated tail that `read_journal` detects via
    magic+crc and cleanly stops at;
  * `rotate()` (called only at a checkpoint barrier, after the
    snapshot landed) atomically replaces the file with a fresh one at
    epoch+1 — replay of a rotated journal applies everything;
  * the checkpoint stores (epoch, offset) of the barrier, so if the
    crash lands BETWEEN snapshot save and rotate, replay skips the
    records the snapshot already covers instead of double-applying
    them (`FeederRuntime.replay_journal`);
  * the journal is BOUNDED: past `max_bytes` appends are dropped and
    counted (`overflow_frames`) — durability degrades loudly rather
    than filling the disk; size it well above the checkpoint cadence.

Journal I/O failures never propagate into the pump loop: they are
counted (`io_errors`) and the pipeline keeps flowing with reduced
durability — the graceful-degradation stance everywhere in ISSUE 6.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from pathlib import Path

from .. import chaos

JOURNAL_MAGIC = 0x484A4644  # 'DFJH' little-endian
RECORD_MAGIC = 0x524A4644  # 'DFJR'
JOURNAL_VERSION = 1
_HDR = struct.Struct("<III")  # magic, version, epoch
_REC = struct.Struct("<IBII")  # magic, kind, len, crc

REC_FRAME = 1
REC_MARK = 2


class FrameJournal:
    def __init__(
        self,
        path: str | Path,
        *,
        max_bytes: int = 64 << 20,
        fsync: bool = False,
    ):
        self.path = Path(path)
        self.max_bytes = max_bytes
        self.fsync = fsync
        self.epoch = 0
        self.counters = {
            "frames": 0,
            "bytes": 0,
            "marks": 0,
            "rotations": 0,
            "overflow_frames": 0,
            "io_errors": 0,
            "reopen_truncations": 0,
        }
        self._lock = threading.Lock()
        self._dirty = False
        self._f = None
        self._open()

    # -- lifecycle -------------------------------------------------------
    def _open(self) -> None:
        try:
            if self.path.exists() and self.path.stat().st_size >= _HDR.size:
                epoch, entries, truncated = read_journal(self.path)
                self.epoch = epoch
                # a crash mid-record leaves a torn tail; appending AFTER
                # it would strand every new record beyond replay's reach
                # (read_journal stops at the first bad record) — truncate
                # back to the last valid record boundary first
                end = (
                    entries[-1][2] + _REC.size + len(entries[-1][1])
                    if entries
                    else _HDR.size
                )
                self._f = open(self.path, "r+b")
                if truncated:
                    self._f.truncate(end)
                    self.counters["reopen_truncations"] += 1
                self._f.seek(end)
            else:
                self._f = open(self.path, "wb")
                self._f.write(_HDR.pack(JOURNAL_MAGIC, JOURNAL_VERSION, self.epoch))
                self._f.flush()
        except OSError:
            self.counters["io_errors"] += 1
            self._f = None

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except OSError:
                    self.counters["io_errors"] += 1
                self._f = None

    # -- write side ------------------------------------------------------
    def _write_record(self, kind: int, payload: bytes) -> bool:
        if self._f is None:
            self.counters["io_errors"] += 1
            return False
        try:
            chaos.maybe_fail(chaos.SITE_JOURNAL_IO)
            self._f.write(
                _REC.pack(RECORD_MAGIC, kind, len(payload), zlib.crc32(payload))
            )
            if payload:
                self._f.write(payload)
            return True
        except OSError:
            self.counters["io_errors"] += 1
            return False

    def append(self, raw: bytes) -> bool:
        """Append one admitted frame. False = not journaled (bound hit
        or I/O error) — counted, never raised."""
        with self._lock:
            if self._f is not None and self._f.tell() > self.max_bytes:
                self.counters["overflow_frames"] += 1
                return False
            if not self._write_record(REC_FRAME, bytes(raw)):
                return False
            self.counters["frames"] += 1
            self.counters["bytes"] += len(raw)
            self._dirty = True
            return True

    def mark(self) -> None:
        """Pump-boundary marker + flush: bounds loss to one pump. A
        no-op when nothing was appended since the last mark."""
        with self._lock:
            if not self._dirty:
                return
            if self._write_record(REC_MARK, b""):
                self.counters["marks"] += 1
            try:
                if self._f is not None:
                    self._f.flush()
                    if self.fsync:
                        os.fsync(self._f.fileno())
            except OSError:
                self.counters["io_errors"] += 1
            self._dirty = False

    def sync_offset(self) -> tuple[int, int]:
        """Flush and return the (epoch, byte offset) barrier the caller
        embeds in its checkpoint meta — replay skips records before it
        when the crash lands between snapshot save and rotate.

        Error stance: an offset that is too SMALL is the dangerous
        direction (replay double-applies records the snapshot already
        covers), so a flush failure still returns tell() — the snapshot
        covers every admitted frame whether or not its journal record
        reached disk. Only when no offset can be determined at all does
        this raise: the caller's checkpoint then aborts BEFORE the
        snapshot is written, which is the safe side (old checkpoint +
        full journal replay)."""
        with self._lock:
            try:
                if self._f is not None:
                    try:
                        self._f.flush()
                        if self.fsync:
                            os.fsync(self._f.fileno())
                    except OSError:
                        self.counters["io_errors"] += 1
                    return self.epoch, self._f.tell()
                return self.epoch, self.path.stat().st_size
            except OSError as e:
                self.counters["io_errors"] += 1
                raise OSError(
                    f"journal {self.path}: cannot determine a checkpoint "
                    "barrier offset — refusing to let the caller embed a "
                    f"bogus one ({e})"
                ) from e

    def rotate(self) -> bool:
        """Atomically restart the journal at epoch+1. Call ONLY at a
        checkpoint barrier: every journaled frame must already be
        covered by the snapshot. False (counted) on I/O failure — the
        old journal keeps growing, recovery stays correct via the
        (epoch, offset) barrier in the checkpoint meta."""
        with self._lock:
            tmp = self.path.with_name(self.path.name + ".rot")
            try:
                chaos.maybe_fail(chaos.SITE_JOURNAL_IO)
                with open(tmp, "wb") as f:
                    f.write(
                        _HDR.pack(JOURNAL_MAGIC, JOURNAL_VERSION, self.epoch + 1)
                    )
                    f.flush()
                    os.fsync(f.fileno())
                if self._f is not None:
                    self._f.close()
                os.replace(tmp, self.path)
                self.epoch += 1
                self._f = open(self.path, "ab")
                self.counters["rotations"] += 1
                self._dirty = False
                return True
            except OSError:
                self.counters["io_errors"] += 1
                try:
                    if self._f is None or self._f.closed:
                        self._f = open(self.path, "ab")
                except OSError:
                    self._f = None
                return False

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
        out["epoch"] = self.epoch
        return out


def read_journal(path: str | Path):
    """→ (epoch, [(kind, payload, start_offset)], truncated).

    Validates per-record magic + crc; stops cleanly at the first
    truncated or corrupt record (the crash-mid-write tail) with
    truncated=True. Raises ValueError only when the FILE HEADER is
    wrong — a missing/alien file is an operator error, a torn tail is
    an expected crash artifact."""
    data = Path(path).read_bytes()
    if len(data) < _HDR.size:
        raise ValueError(f"{path}: too short for a frame journal header")
    magic, version, epoch = _HDR.unpack_from(data, 0)
    if magic != JOURNAL_MAGIC or version != JOURNAL_VERSION:
        raise ValueError(f"{path}: not a v{JOURNAL_VERSION} frame journal")
    entries = []
    off = _HDR.size
    truncated = False
    n = len(data)
    while off < n:
        start = off
        if off + _REC.size > n:
            truncated = True
            break
        rmagic, kind, ln, crc = _REC.unpack_from(data, off)
        off += _REC.size
        if rmagic != RECORD_MAGIC or kind not in (REC_FRAME, REC_MARK):
            truncated = True
            break
        if off + ln > n:
            truncated = True
            break
        payload = data[off : off + ln]
        off += ln
        if zlib.crc32(payload) != crc:
            truncated = True
            break
        entries.append((kind, payload, start))
    return epoch, entries, truncated
