"""Feeder runtime — multi-queue fan-in, shape-bucketed coalescing and
deterministic shedding between the receiver's overwrite queues and the
fused windowed step (ISSUE 4; see runtime.py for the design)."""

from .flowframe import (
    decode_flowframe_body,
    encode_flowbatch_body,
    encode_flowbatch_frames,
    peek_rows,
)
from .journal import FrameJournal, read_journal
from .runtime import (
    DocChunk,
    FeederConfig,
    FeederRuntime,
    FlowChunk,
    FrameCodecBase,
    PipelineFeedSink,
    ShardedFeedSink,
    WindowManagerFeedSink,
)

__all__ = [
    "DocChunk",
    "FeederConfig",
    "FeederRuntime",
    "FlowChunk",
    "FrameCodecBase",
    "FrameJournal",
    "PipelineFeedSink",
    "ShardedFeedSink",
    "WindowManagerFeedSink",
    "decode_flowframe_body",
    "encode_flowbatch_body",
    "encode_flowbatch_frames",
    "peek_rows",
    "read_journal",
]
