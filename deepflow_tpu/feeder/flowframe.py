"""Columnar FlowBatch wire frames — the feeder's flow-record transport.

Documents already have a wire form (ingest/codec.py, metric.proto), but
the windowed rollup pipelines consume PRE-fanout flow records
(datamodel/batch.FlowBatch), for which the reference has no
server-ingestible encoding — its collectors receive flows in-process
over queues (quadruple_generator.rs:275). This module gives flow
records the same self-contained-frame property the receiver's Document
lane has, so multi-queue fan-in can carry them through the SAME
Receiver/OverwriteQueue plumbing (MessageType.TAGGEDFLOW lane): one
frame = one columnar chunk, header + [len][body] framing identical to
every other lane (ingest/framing.encode_frame), body a fixed-layout
LE dump of the tag matrix + meter matrix.

Layout (all little-endian):

    u32 magic   'WOLF' (0x464C4F57 reads "FLOW" in LE byte order)
    u32 version (1)
    u32 n_rows
    u32 n_tag_fields   — must equal len(FLOW_RECORD_TAG_FIELDS)
    u32 n_meter_fields — must equal FLOW_METER.num_fields
    u32 [n_tag_fields, n_rows] tag matrix, FLOW_RECORD_TAG_FIELDS order
    f32 [n_rows, n_meter_fields] meter matrix

Only valid rows are encoded (the decoder returns an all-valid batch);
field COUNTS are checked at decode so schema drift fails loudly rather
than bit-casting misaligned columns. `peek_rows` reads the record count
from the header alone — the feeder's shed accounting must know how many
records a dropped frame carried without paying for its decode.
"""

from __future__ import annotations

import struct

import numpy as np

from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS, FlowBatch
from ..datamodel.schema import FLOW_METER
from ..ingest.framing import FlowHeader, MessageType, encode_frame

FLOWFRAME_MAGIC = 0x464C4F57
FLOWFRAME_VERSION = 1
_HDR = struct.Struct("<IIIII")


def encode_flowbatch_body(fb: FlowBatch) -> bytes:
    """One FlowBatch (valid rows only) → one flowframe message body."""
    keep = np.flatnonzero(fb.valid)
    n = int(keep.size)
    tags = np.stack(
        [np.asarray(fb.tags[f], dtype="<u4")[keep] for f in FLOW_RECORD_TAG_FIELDS]
    )
    meters = np.ascontiguousarray(fb.meters[keep].astype("<f4"))
    return (
        _HDR.pack(
            FLOWFRAME_MAGIC,
            FLOWFRAME_VERSION,
            n,
            len(FLOW_RECORD_TAG_FIELDS),
            FLOW_METER.num_fields,
        )
        + tags.tobytes()
        + meters.tobytes()
    )


def encode_flowbatch_frames(
    fb: FlowBatch,
    *,
    agent_id: int = 0,
    org_id: int = 0,
    max_rows_per_frame: int = 2048,
) -> list[bytes]:
    """FlowBatch → raw wire frames (header + framed body) on the
    TAGGEDFLOW lane, chunked so every frame stays well under
    MAX_FRAME_SIZE. These are exactly what `Receiver` queues hold and
    what the feeder drains."""
    frames = []
    for off in range(0, max(fb.size, 1), max_rows_per_frame):
        chunk = fb.slice(off, off + max_rows_per_frame)
        if not np.any(chunk.valid):
            continue
        header = FlowHeader(
            msg_type=int(MessageType.TAGGEDFLOW),
            agent_id=agent_id,
            organization_id=org_id,
        )
        frames.append(encode_frame(header, [encode_flowbatch_body(chunk)]))
    return frames


def peek_rows(body: bytes) -> int:
    """Record count from the body header alone (shed accounting — a
    dropped frame is counted, never decoded)."""
    if len(body) < _HDR.size:
        return 0
    magic, version, n, _t, _m = _HDR.unpack_from(body, 0)
    if magic != FLOWFRAME_MAGIC:
        return 0
    return int(n)


def decode_flowframe_body(body: bytes) -> FlowBatch:
    """One flowframe message body → all-valid FlowBatch. Raises
    ValueError on magic/version/field-count/size drift (the untrusted-
    edge stance every decoder in ingest/ takes)."""
    if len(body) < _HDR.size:
        raise ValueError("flowframe: short body")
    magic, version, n, t, m = _HDR.unpack_from(body, 0)
    if magic != FLOWFRAME_MAGIC:
        raise ValueError(f"flowframe: bad magic {magic:#x}")
    if version != FLOWFRAME_VERSION:
        raise ValueError(f"flowframe: version {version} != {FLOWFRAME_VERSION}")
    if t != len(FLOW_RECORD_TAG_FIELDS) or m != FLOW_METER.num_fields:
        raise ValueError(
            f"flowframe: field counts ({t}, {m}) != "
            f"({len(FLOW_RECORD_TAG_FIELDS)}, {FLOW_METER.num_fields}) — "
            "schema drift between sender and receiver"
        )
    need = _HDR.size + 4 * t * n + 4 * n * m
    if len(body) < need:
        raise ValueError(f"flowframe: truncated body ({len(body)} < {need})")
    off = _HDR.size
    tag_mat = np.frombuffer(body, dtype="<u4", count=t * n, offset=off).reshape(t, n)
    off += 4 * t * n
    meters = np.frombuffer(body, dtype="<f4", count=n * m, offset=off).reshape(n, m)
    tags = {
        f: np.ascontiguousarray(tag_mat[i])
        for i, f in enumerate(FLOW_RECORD_TAG_FIELDS)
    }
    return FlowBatch(
        tags=tags,
        meters=np.ascontiguousarray(meters),
        valid=np.ones(n, dtype=bool),
    )
