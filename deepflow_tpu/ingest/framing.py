"""Telemetry wire framing — the agent↔server transport ABI.

Byte-compatible with the reference's framed TCP/UDP protocol:

  * 19-byte flow header (uniform_sender.rs:110-147; layout comment at
    :109-118): frame_size u32 BE, msg_type u8, version u16 LE (0x8000),
    encoder u8, team_id u32 LE, organization_id u16 LE, reserved_1 u16,
    agent_id u16 LE, reserved_2 u8. frame_size counts the whole frame
    including the header.
  * message-type registry (droplet-message.go:31-88).
  * METRICS frame body: back-to-back [pb_len u32 LE][protobuf Document]
    records (uniform_sender.rs:186-196 cache_to_sender).

The server side parses the header to route by msg_type and extract
org/team/agent identity (receiver.go:631-700 semantics).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
import zlib

try:  # optional real zstd (not in every image)
    import zstandard as _zstd
except ImportError:  # pragma: no cover - image-dependent
    _zstd = None


class MessageType(enum.IntEnum):
    """droplet-message.go:36-60."""

    COMPRESS = 0
    SYSLOG = 1
    SERVER_DFSTATS = 2
    METRICS = 3
    TAGGEDFLOW = 4
    PROTOCOLLOG = 5
    OPENTELEMETRY = 6
    PROMETHEUS = 7
    TELEGRAF = 8
    PACKETSEQUENCE = 9
    DFSTATS = 10
    OPENTELEMETRY_COMPRESSED = 11
    RAW_PCAP = 12
    PROFILE = 13
    PROC_EVENT = 14
    ALERT_EVENT = 15
    K8S_EVENT = 16
    APPLICATION_LOG = 17
    AGENT_LOG = 18
    SKYWALKING = 19
    DATADOG = 20
    # DFPUSH is this build's extension (like ENCODER_DEFLATE below): the
    # wire delivery plane's cross-host push lane — subscription results
    # and alert notifications routed host → FleetSubscriptionRouter.
    # The reference registry ends at DATADOG=20, so 21 is the first free
    # id; the header ABI is unchanged.
    DFPUSH = 21


HEADER_VERSION = 0x8000
HEADER_LEN = 19

# frame_size is BE; everything after msg_type is LE (uniform_sender.rs
# Header::encode mixes endianness exactly like this).
_HDR_TAIL = struct.Struct("<HBIHHHB")  # version, encoder, team, org, rsvd1, agent, rsvd2


@dataclasses.dataclass
class FlowHeader:
    msg_type: int
    frame_size: int = 0  # filled by encode_frame
    version: int = HEADER_VERSION
    encoder: int = 0  # 0 = raw; compression codecs are negotiated ids
    team_id: int = 0
    organization_id: int = 0
    agent_id: int = 0

    def encode(self) -> bytes:
        return (
            struct.pack(">I", self.frame_size)
            + struct.pack("B", self.msg_type)
            + _HDR_TAIL.pack(
                self.version, self.encoder, self.team_id, self.organization_id, 0, self.agent_id, 0
            )
        )

    @classmethod
    def parse(cls, buf: bytes) -> "FlowHeader":
        if len(buf) < HEADER_LEN:
            raise ValueError(f"short header: {len(buf)} < {HEADER_LEN}")
        (frame_size,) = struct.unpack_from(">I", buf, 0)
        msg_type = buf[4]
        version, encoder, team, org, _r1, agent, _r2 = _HDR_TAIL.unpack_from(buf, 5)
        return cls(
            msg_type=msg_type,
            frame_size=frame_size,
            version=version,
            encoder=encoder,
            team_id=team,
            organization_id=org,
            agent_id=agent,
        )


# Upper bound on one wire frame; shared by sender and receiver so a frame
# that encodes is always accepted (a frame at/over the reassembler's limit
# would otherwise desync the whole stream into byte-wise resync).
MAX_FRAME_SIZE = (1 << 24) - 1


# Body compression codecs carried in the header's encoder byte. The
# reference knows Raw=0 and Zstd=3 (trident.rs:382-387 SenderEncoder;
# compression applied over the whole message buffer before framing,
# uniform_sender.rs:230). Deflate=4 is this build's extension: the image
# has no zstd library, so the always-available zlib codec fills the seat
# behind the same flag mechanism; real zstd engages automatically when
# the `zstandard` module is importable.
ENCODER_RAW = 0
ENCODER_ZSTD = 3
ENCODER_DEFLATE = 4


def best_encoder() -> int:
    """The strongest codec this process can both encode and decode."""
    return ENCODER_ZSTD if _zstd is not None else ENCODER_DEFLATE


def compress_body(body: bytes, encoder: int) -> bytes:
    if encoder == ENCODER_RAW:
        return body
    if encoder == ENCODER_ZSTD:
        if _zstd is None:
            raise ValueError("zstd codec unavailable in this image")
        return _zstd.ZstdCompressor().compress(body)
    if encoder == ENCODER_DEFLATE:
        return zlib.compress(body, level=1)
    raise ValueError(f"unknown encoder {encoder}")


def decompress_body(body: bytes, encoder: int, max_size: int = MAX_FRAME_SIZE) -> bytes:
    """Inverse of compress_body, with a decompressed-size bound so a
    malicious/corrupt frame cannot balloon memory (zip-bomb guard)."""
    if encoder == ENCODER_RAW:
        return body
    if encoder == ENCODER_ZSTD:
        if _zstd is None:
            raise ValueError("zstd codec unavailable in this image")
        return _zstd.ZstdDecompressor().decompress(body, max_output_size=max_size)
    if encoder == ENCODER_DEFLATE:
        d = zlib.decompressobj()
        out = d.decompress(body, max_size)
        if d.unconsumed_tail:
            raise ValueError(f"decompressed frame exceeds {max_size} bytes")
        return out
    raise ValueError(f"unknown encoder {encoder}")


def encode_frame(
    header: FlowHeader, messages: list[bytes], encoder: int = ENCODER_RAW
) -> bytes:
    """One wire frame: header + [len u32 LE][pb] per message; the body is
    compressed when `encoder` names a codec (header.encoder records it)."""
    body = b"".join(struct.pack("<I", len(m)) + m for m in messages)
    if encoder != ENCODER_RAW:
        body = compress_body(body, encoder)
    header.encoder = encoder
    frame_size = HEADER_LEN + len(body)
    if frame_size > MAX_FRAME_SIZE:
        raise ValueError(
            f"frame too large: {frame_size} > {MAX_FRAME_SIZE}; batch fewer messages"
        )
    header.frame_size = frame_size
    return header.encode() + body


def split_messages(payload: bytes) -> list[bytes]:
    """Frame body → pb message list (inverse of encode_frame's body)."""
    return [payload[o:o + ln] for o, ln in split_message_spans(payload)]


def split_message_spans(payload: bytes) -> list[tuple[int, int]]:
    """Frame body → [(offset, len)] of each pb message, WITHOUT
    materializing slices — the zero-copy twin of split_messages for
    decoders that consume (buffer, offsets, lens) directly (the r5
    host-path fix: slicing 256 messages per frame and re-joining them
    in decode() was a measurable share of wire-path time)."""
    spans = []
    off = 0
    n = len(payload)
    while off + 4 <= n:
        (size,) = struct.unpack_from("<I", payload, off)
        off += 4
        if off + size > n:
            raise ValueError(f"truncated message at {off}: need {size}, have {n - off}")
        spans.append((off, size))
        off += size
    if off != n:
        raise ValueError(f"trailing garbage: {n - off} bytes")
    return spans


class FrameReassembler:
    """Incremental TCP stream → frames (the receiver's flow-header scan,
    receiver.go:515-585). Feed arbitrary chunks; yields (header, body)."""

    def __init__(self, max_frame: int = MAX_FRAME_SIZE + 1):
        self._buf = bytearray()
        self.max_frame = max_frame
        self.bad_frames = 0

    def feed(self, chunk: bytes) -> list[tuple[FlowHeader, bytes]]:
        self._buf += chunk
        frames = []
        while True:
            if len(self._buf) < HEADER_LEN:
                return frames
            header = FlowHeader.parse(bytes(self._buf[:HEADER_LEN]))
            if (
                header.frame_size < HEADER_LEN
                or header.frame_size >= self.max_frame
                or header.version != HEADER_VERSION
            ):
                # resync: drop one byte (malformed stream)
                self.bad_frames += 1
                del self._buf[0]
                continue
            if len(self._buf) < header.frame_size:
                return frames
            body = bytes(self._buf[HEADER_LEN : header.frame_size])
            del self._buf[: header.frame_size]
            frames.append((header, body))
