"""Misroute-handoff transport — the real wire behind the receiver's
control-plane forward (ISSUE 15).

r18's key-hash fan-in counted misrouted frames and handed them to a
callback seam; this module makes that seam a socket. The reference
ships the same move as agent→analyzer reassignment: while the
controller re-routes agents, in-flight traffic for a moved shard group
keeps arriving at the old host, and the old host forwards it to the
owner instead of dropping it. Design:

  * `HandoffSender` — one bounded overwrite queue + one framed-TCP
    writer thread PER PEER, built on the same retry/backoff machinery
    as `UniformSender` (shared `RetryPolicy`, decorrelated jitter,
    capped exponential reconnect). Frames travel VERBATIM: they are
    already framed on the codec lanes with the originating agent's
    identity in the header, so the receiving end's normal parse path
    needs zero new wire format. Loss is never silent: an unreachable
    or unknown peer sheds frames counted (`shed_frames` — the bounded
    queue's oldest-first overwrite plus the shutdown shed), and the
    `handoff.send` chaos seam scripts transport faults per write for
    deterministic CI replay.
  * `HandoffReceiver` — a dedicated listener that feeds reassembled
    frames into an existing `Receiver`'s dispatch (routing, held-frame
    buffering and queue fanout are shared with the agent front door),
    while keeping its own rx counters so handoff traffic is separately
    attributable (`tpu_handoff_*` in deepflow_system).

Peers discover each other out of band (the controller knows every
host's handoff endpoint; tests/bench exchange a port file).
"""

from __future__ import annotations

import socket
import threading
import time

from .. import chaos
from ..utils.retry import RetryPolicy, decorrelated_rng
from ..utils.stats import register_countable
from .framing import FrameReassembler
from .queues import PyOverwriteQueue

# reconnect backoff: the UniformSender stance — shared capped
# exponential with jitter so a fleet of forwarding hosts does not
# re-dial a recovering peer in lockstep
_RECONNECT = RetryPolicy(base_delay_s=0.05, max_delay_s=2.0, jitter=0.5)
_BACKOFF_CAP_ATTEMPT = 8


class HandoffUnreachable(Exception):
    """Raised into the receiver's guarded handoff callback when a frame
    cannot even be queued (unknown peer / sender closed) — the receiver
    counts it (`handoff_errors`), the sender counts the shed."""


class _Peer:
    __slots__ = ("addr", "queue", "thread", "inflight", "sock", "lock")

    def __init__(self, addr, capacity):
        self.addr = addr
        self.queue = PyOverwriteQueue(capacity)
        self.thread = None
        self.inflight = 0  # frames popped but not yet written (≤1)
        self.sock = None
        # guards the (queue, inflight) PAIR: the writer's pop and
        # inflight-mark must be one step against flush()'s drained
        # check, and a producer's put + overwritten-diff must be one
        # step against a concurrent producer (conn + UDP threads can
        # forward into the same peer)
        self.lock = threading.Lock()


class HandoffSender:
    """Forward raw wire frames to owning peers, at-least-once across
    reconnects, counted shed when a peer stays unreachable."""

    def __init__(self, peers: dict[int, tuple[str, int]], *,
                 queue_capacity: int = 1 << 12,
                 connect_timeout_s: float = 5.0):
        self._peers = {
            int(p): _Peer((host, int(port)), queue_capacity)
            for p, (host, port) in peers.items()
        }
        self.connect_timeout_s = connect_timeout_s
        self._running = True
        self._lock = threading.Lock()
        self._rng = decorrelated_rng(0x4F48)  # 'HO'
        self.counters = {
            "tx_frames": 0, "tx_bytes": 0, "send_errors": 0,
            "reconnects": 0, "reconnect_success": 0,
            "shed_frames": 0,
        }
        self._stats_src = register_countable("tpu_handoff_sender", self)
        for peer in self._peers.values():
            peer.thread = threading.Thread(
                target=self._run_peer, args=(peer,), daemon=True
            )
            peer.thread.start()

    # -- producer side ---------------------------------------------------
    def send(self, process_index: int, raw_frame: bytes) -> None:
        """Queue one frame for `process_index`. Raises
        HandoffUnreachable (after counting the shed) when the peer is
        unknown or the sender is closed — the receiver's handoff guard
        turns that into its own counted error lane."""
        peer = self._peers.get(int(process_index))
        if peer is None or not self._running:
            self._count("shed_frames")
            raise HandoffUnreachable(
                f"no handoff peer for process {process_index} "
                f"(known: {sorted(self._peers)}, running={self._running})"
            )
        with peer.lock:
            before = peer.queue.overwritten
            accepted = peer.queue.put(raw_frame)
            dropped = peer.queue.overwritten - before
        if not accepted:
            # put() returns False on a closed queue — a send racing
            # close() past the _running check above. The frame was NOT
            # accepted: count it and surface unreachable, same as the
            # pre-check path (loss is never silent).
            self._count("shed_frames")
            raise HandoffUnreachable(
                f"handoff peer {process_index} closed mid-send"
            )
        if dropped:
            # bounded-queue overwrite: the peer is too far behind —
            # oldest frames shed whole, counted (never silent)
            self._count("shed_frames", dropped)

    def route(self, topology):
        """→ the `Receiver.attach_topology(handoff=...)` callback for
        `topology`: group → owning process → send. Bind a NEW callback
        at every epoch flip so the routing table always matches the
        topology the receiver dispatches under."""
        def forward(group: int, raw_frame: bytes) -> None:
            self.send(topology.group_process(group), raw_frame)
        return forward

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
        out["queue_depth"] = sum(len(p.queue) for p in self._peers.values())
        out["peers"] = len(self._peers)
        out["connected"] = sum(
            1 for p in self._peers.values() if p.sock is not None
        )
        return out

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    @staticmethod
    def _drained(peer: _Peer) -> bool:
        # under peer.lock: pairs with _pop, so a frame can never be
        # invisible to BOTH len(queue) and inflight
        with peer.lock:
            return len(peer.queue) == 0 and peer.inflight == 0

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Block until every queued frame has been WRITTEN to its
        peer's socket (or timeout). Drivers use this as the
        step-boundary fence: after flush, the bytes are in the kernel
        on their way — the receiving dispatch is the peer's business."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(self._drained(p) for p in self._peers.values()):
                return True
            time.sleep(0.002)
        return False

    def close(self, drain_timeout_s: float = 5.0) -> None:
        self.flush(drain_timeout_s)
        self._running = False
        for peer in self._peers.values():
            peer.queue.close()
        shed = 0
        for peer in self._peers.values():
            if peer.thread is not None:
                peer.thread.join(timeout=drain_timeout_s)
            # anything the writer thread left behind is a counted shed
            # (it already counted its own in-flight frame on exit)
            shed += len(peer.queue)
            if peer.sock is not None:
                try:
                    peer.sock.close()
                except OSError:
                    pass
        if shed:
            self._count("shed_frames", shed)
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- per-peer writer thread ------------------------------------------
    def _connect(self, peer: _Peer) -> bool:
        try:
            s = socket.create_connection(
                peer.addr, timeout=self.connect_timeout_s
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer.sock = s
            return True
        except OSError:
            return False

    @staticmethod
    def _pop(peer: _Peer) -> bytes | None:
        """Pop-and-mark-in-flight as ONE step vs the flush() fence —
        a blocking gets() would empty the queue before inflight rises,
        letting flush observe a drained wire with a frame unsent."""
        with peer.lock:
            got = peer.queue.gets(1, timeout_ms=0)
            if not got:
                return None
            peer.inflight = 1
            return got[0]

    def _run_peer(self, peer: _Peer) -> None:
        attempt = 1
        pending: bytes | None = None
        while self._running or pending is not None or len(peer.queue):
            if pending is None:
                pending = self._pop(peer)
                if pending is None:
                    if not self._running:
                        return
                    time.sleep(0.005)  # idle poll (pop is non-blocking)
                    continue
            if peer.sock is None and not self._connect(peer):
                self._count("send_errors")
                if not self._running:
                    # shutdown with the peer unreachable: the in-flight
                    # frame is a counted shed (close() counts whatever
                    # is still queued), like every other loss lane
                    self._count("shed_frames", 1)
                    peer.inflight = 0
                    return
                time.sleep(_RECONNECT.delay(attempt, self._rng))
                attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)
                continue
            try:
                # THE chaos seam (ISSUE 15): scripted transport loss —
                # an injected fault here behaves exactly like a broken
                # pipe (reconnect + resend of the in-flight frame)
                chaos.maybe_fail(chaos.SITE_HANDOFF_SEND)
                peer.sock.sendall(pending)
                self._count("tx_frames")
                self._count("tx_bytes", len(pending))
                pending = None
                peer.inflight = 0
                attempt = 1
            except Exception:
                # at-least-once: the in-flight frame stays pending
                # across the reconnect (the bounded queue remains the
                # only shed point)
                self._count("send_errors")
                self._count("reconnects")
                try:
                    if peer.sock is not None:
                        peer.sock.close()
                except OSError:
                    pass
                peer.sock = None
                time.sleep(_RECONNECT.delay(attempt, self._rng))
                attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)


class HandoffReceiver:
    """Dedicated intake for forwarded frames: a TCP listener whose
    reassembled frames flow into an existing `Receiver`'s dispatch —
    same routing, same held-frame buffer, same queues — with separate
    rx accounting so handoff traffic is attributable on its own."""

    def __init__(self, receiver, host: str = "127.0.0.1", port: int = 0):
        self.receiver = receiver
        self.host = host
        self.port = port
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._running = False
        self.counters = {
            "rx_frames": 0, "rx_bytes": 0, "bad_frames": 0, "conns": 0,
        }
        self._stats_src = register_countable("tpu_handoff_receiver", self)

    def get_counters(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def endpoint(self) -> tuple[str, int]:
        """The (host, port) peers dial — advertise it to the fleet."""
        return (self.host, self.port)

    def start(self) -> None:
        self._running = True
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        self.port = s.getsockname()[1]
        s.listen(16)
        s.settimeout(0.5)  # close() does not wake accept() on Linux
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in list(self._threads):
            t.join(timeout=2)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            self._count("conns")
            with self._lock:
                self._conns.add(conn)
                self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._conn_loop, args=(conn, addr), daemon=True
            )
            t.start()
            with self._lock:
                self._threads.append(t)

    def _conn_loop(self, conn: socket.socket, addr) -> None:
        asm = FrameReassembler()
        seen_bad = 0
        try:
            while self._running:
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                if not chunk:
                    return
                for header, body in asm.feed(chunk):
                    raw = header.encode() + body
                    # into the SHARED dispatch: routing (under the
                    # receiver's current epoch), held-frame buffering
                    # and queue fanout are one code path for agent and
                    # handoff traffic alike. Counters move AFTER the
                    # dispatch returns so `rx_frames == N` means N
                    # frames are fully delivered (enqueued/held) — the
                    # fence drivers poll at a step boundary
                    self.receiver._dispatch(header, raw, addr)
                    self._count("rx_frames")
                    self._count("rx_bytes", len(raw))
                if asm.bad_frames != seen_bad:
                    self._count("bad_frames", asm.bad_frames - seen_bad)
                    seen_bad = asm.bad_frames
        except OSError:
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
