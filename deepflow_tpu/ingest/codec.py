"""metric.proto Document codec — wire-compatible, dependency-free.

Hand-rolled proto3 wire format (varint + length-delimited submessages)
for the Document message tree of /root/reference/message/metric.proto:14-196:

    Document{timestamp=1, tag=2 (MiniTag{field=1 MiniField, code=2}),
             meter=3 (Meter{meter_id=1, flow=2, usage=3, app=4}),
             flags=4}

Field ids below cite metric.proto exactly; the agent-side encoder this
must interoperate with is document.rs:363-418 + meter pb impls. Encoding
walks DocBatch rows; decoding fills SoA columns. This is the reference
implementation the native C++ decoder (deepflow_tpu/native) must match —
the Python path stays as the conformance oracle for it.

Strings (app_service/app_instance/endpoint) are dictionary-encoded at
decode into a per-batch StringDict (SmartEncoding boundary, flow_tag
pattern): the device only ever sees endpoint_hash / service ids.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..datamodel.batch import DocBatch
from ..datamodel.code import CODE_OF_ID, CodeId, MeterId
from ..datamodel.schema import APP_METER, FLOW_METER, TAG_SCHEMA, USAGE_METER, MeterSchema
from ..ops.hashing import fingerprint64

_T = TAG_SCHEMA

# ---------------------------------------------------------------------------
# proto3 wire primitives

_VARINT = 0
_LEN = 2


def _put_varint(buf: bytearray, v: int):
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _get_varint(buf: bytes, off: int) -> tuple[int, int]:
    out = 0
    shift = 0
    n = len(buf)
    while True:
        if off >= n:
            # truncated input is a rejected frame, same as overflow —
            # decoders at the untrusted edge catch ValueError uniformly
            raise ValueError("truncated varint")
        b = buf[off]
        off += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, off
        shift += 7
        if shift >= 70:
            raise ValueError("varint overflow")


def _put_tag_varint(buf: bytearray, field: int, v: int):
    if v:
        _put_varint(buf, field << 3 | _VARINT)
        _put_varint(buf, v)


def _put_tag_i32(buf: bytearray, field: int, v: int):
    """proto3 int32: negatives as 10-byte two's-complement varint."""
    if v:
        _put_varint(buf, field << 3 | _VARINT)
        _put_varint(buf, v & ((1 << 64) - 1) if v < 0 else v)


def _put_tag_bytes(buf: bytearray, field: int, v: bytes):
    if v:
        _put_varint(buf, field << 3 | _LEN)
        _put_varint(buf, len(v))
        buf += v


# unconditional writers (the _put_tag_* family skips falsy values —
# encoders that must emit zero/empty fields use these). THE shared
# protobuf writer helpers: formats.py and trident_grpc.py import them.
def pb_varint(out: bytearray, field: int, v: int) -> None:
    _put_varint(out, field << 3 | 0)
    _put_varint(out, int(v) & ((1 << 64) - 1))


def pb_bytes(out: bytearray, field: int, b: bytes) -> None:
    _put_varint(out, field << 3 | _LEN)
    _put_varint(out, len(b))
    out += b


def pb_str(out: bytearray, field: int, s: str) -> None:
    pb_bytes(out, field, s.encode())


def pb_fixed64(out: bytearray, field: int, v: int) -> None:
    _put_varint(out, field << 3 | 1)
    out += (int(v) & ((1 << 64) - 1)).to_bytes(8, "little")


def _iter_fields(buf: bytes):
    off = 0
    n = len(buf)
    while off < n:
        key, off = _get_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            v, off = _get_varint(buf, off)
            yield field, v
        elif wire == _LEN:
            size, off = _get_varint(buf, off)
            yield field, buf[off : off + size]
            off += size
        elif wire == 5:  # fixed32
            yield field, int.from_bytes(buf[off : off + 4], "little")
            off += 4
        elif wire == 1:  # fixed64
            yield field, int.from_bytes(buf[off : off + 8], "little")
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


# ---------------------------------------------------------------------------
# meter layout tables: column name → (submessage field id, field id)
# (metric.proto:70-196)

FLOW_METER_LAYOUT: dict[str, tuple[int, int]] = {
    # Traffic = 1
    "packet_tx": (1, 1), "packet_rx": (1, 2), "byte_tx": (1, 3), "byte_rx": (1, 4),
    "l3_byte_tx": (1, 5), "l3_byte_rx": (1, 6), "l4_byte_tx": (1, 7), "l4_byte_rx": (1, 8),
    "new_flow": (1, 9), "closed_flow": (1, 10), "l7_request": (1, 11), "l7_response": (1, 12),
    "syn": (1, 13), "synack": (1, 14), "direction_score": (1, 15),
    # Latency = 2
    "rtt_max": (2, 1), "rtt_client_max": (2, 2), "rtt_server_max": (2, 3), "srt_max": (2, 4),
    "art_max": (2, 5), "rrt_max": (2, 6), "cit_max": (2, 19),
    "rtt_sum": (2, 7), "rtt_client_sum": (2, 8), "rtt_server_sum": (2, 9), "srt_sum": (2, 10),
    "art_sum": (2, 11), "rrt_sum": (2, 12), "cit_sum": (2, 20),
    "rtt_count": (2, 13), "rtt_client_count": (2, 14), "rtt_server_count": (2, 15),
    "srt_count": (2, 16), "art_count": (2, 17), "rrt_count": (2, 18), "cit_count": (2, 21),
    # Performance = 3
    "retrans_tx": (3, 1), "retrans_rx": (3, 2), "zero_win_tx": (3, 3), "zero_win_rx": (3, 4),
    "retrans_syn": (3, 5), "retrans_synack": (3, 6),
    # Anomaly = 4
    "client_rst_flow": (4, 1), "server_rst_flow": (4, 2), "server_syn_miss": (4, 3),
    "client_ack_miss": (4, 4), "client_half_close_flow": (4, 5), "server_half_close_flow": (4, 6),
    "client_source_port_reuse": (4, 7), "client_establish_reset": (4, 8), "server_reset": (4, 9),
    "server_queue_lack": (4, 10), "server_establish_reset": (4, 11), "tcp_timeout": (4, 12),
    "l7_client_error": (4, 13), "l7_server_error": (4, 14), "l7_timeout": (4, 15),
    # FlowLoad = 5. flow_count is a framework-internal column (the
    # commutative flow_load model, schema.py) — not on the wire.
    "flow_load": (5, 1),
}

APP_METER_LAYOUT: dict[str, tuple[int, int]] = {
    # AppTraffic = 1
    "request": (1, 1), "response": (1, 2), "direction_score": (1, 3),
    # AppLatency = 2
    "rrt_max": (2, 1), "rrt_sum": (2, 2), "rrt_count": (2, 3),
    # AppAnomaly = 3
    "client_error": (3, 1), "server_error": (3, 2), "timeout": (3, 3),
}

USAGE_METER_LAYOUT: dict[str, tuple[int, int]] = {
    # UsageMeter is flat (metric.proto:160-169): submessage id 0 = flat
    "packet_tx": (0, 1), "packet_rx": (0, 2), "byte_tx": (0, 3), "byte_rx": (0, 4),
    "l3_byte_tx": (0, 5), "l3_byte_rx": (0, 6), "l4_byte_tx": (0, 7), "l4_byte_rx": (0, 8),
}

# Meter.{flow=2, usage=3, app=4} (metric.proto:71-76)
_METER_OF_ID = {
    int(MeterId.FLOW): (2, FLOW_METER, FLOW_METER_LAYOUT),
    int(MeterId.USAGE): (3, USAGE_METER, USAGE_METER_LAYOUT),
    int(MeterId.APP): (4, APP_METER, APP_METER_LAYOUT),
}

_ID_OF_CODE = {int(v): k for k, v in CODE_OF_ID.items()}


@dataclasses.dataclass
class StringDict:
    """Per-batch string dictionary (SmartEncoding sidecar): value → id."""

    values: list[str] = dataclasses.field(default_factory=list)
    _index: dict[str, int] = dataclasses.field(default_factory=dict)

    def intern(self, s: str) -> int:
        """0 is reserved for the empty string."""
        if not s:
            return 0
        i = self._index.get(s)
        if i is None:
            i = len(self.values) + 1
            self.values.append(s)
            self._index[s] = i
        return i

    def lookup(self, i: int) -> str:
        return "" if i == 0 else self.values[i - 1]


def _hash_str(s: str) -> int:
    """Stable u32 fingerprint for strings entering tag columns (the
    agent's endpoint_hash role)."""
    if not s:
        return 0
    data = s.encode()
    pad = (-len(data)) % 4
    words = np.frombuffer(data + b"\0" * pad, dtype="<u4").reshape(1, -1)
    hi, _ = fingerprint64(words, xp=np)
    return int(hi[0])


# ---------------------------------------------------------------------------
# encode


def _encode_minifield(tag_row, strings: dict[str, str]) -> bytes:
    t = lambda name: int(tag_row[_T.index(name)])
    buf = bytearray()
    is_v6 = t("is_ipv6")
    if is_v6:
        ip = b"".join(int(t(f"ip0_w{w}")).to_bytes(4, "big") for w in range(4))
        ip1 = b"".join(int(t(f"ip1_w{w}")).to_bytes(4, "big") for w in range(4))
    else:
        ip = t("ip0_w3").to_bytes(4, "big")
        ip1 = t("ip1_w3").to_bytes(4, "big")
    _put_tag_bytes(buf, 1, ip if any(ip) else b"")
    _put_tag_bytes(buf, 2, ip1 if any(ip1) else b"")
    _put_tag_varint(buf, 3, t("global_thread_id"))
    _put_tag_varint(buf, 4, is_v6)

    def unfold_epc(v):  # u16 sign-fold → i32
        return v - 0x10000 if v >= 0x8000 else v

    _put_tag_i32(buf, 5, unfold_epc(t("l3_epc_id")))
    _put_tag_i32(buf, 6, unfold_epc(t("l3_epc_id1")))
    _put_tag_varint(buf, 7, t("mac0_hi") << 32 | t("mac0_lo"))
    _put_tag_varint(buf, 8, t("mac1_hi") << 32 | t("mac1_lo"))
    _put_tag_varint(buf, 9, t("direction"))
    _put_tag_varint(buf, 10, t("tap_side"))
    _put_tag_varint(buf, 11, t("protocol"))
    _put_tag_varint(buf, 12, t("acl_gid"))
    _put_tag_varint(buf, 13, t("server_port"))
    _put_tag_varint(buf, 14, t("agent_id"))  # vtap_id
    _put_tag_varint(buf, 15, t("tap_port"))
    _put_tag_varint(buf, 16, t("tap_type"))
    _put_tag_varint(buf, 17, t("l7_protocol"))
    _put_tag_varint(buf, 20, t("gpid0"))
    _put_tag_varint(buf, 21, t("gpid1"))
    _put_tag_varint(buf, 22, t("signal_source"))
    _put_tag_bytes(buf, 23, strings.get("app_service", "").encode())
    _put_tag_bytes(buf, 24, strings.get("app_instance", "").encode())
    _put_tag_bytes(buf, 25, strings.get("endpoint", "").encode())
    _put_tag_varint(buf, 27, t("pod_id"))
    _put_tag_varint(buf, 28, t("biz_type"))
    return bytes(buf)


def _encode_meter(meter_row, meter_id: int) -> bytes:
    sub_field, schema, layout = _METER_OF_ID[meter_id]
    subs: dict[int, bytearray] = {}
    flat = bytearray()
    _put_tag_varint(flat, 1, meter_id)
    for i, f in enumerate(schema.fields):
        loc = layout.get(f.name)
        if loc is None:
            continue
        sub, fid = loc
        v = int(meter_row[i])
        if not v:
            continue
        if sub == 0:
            target = subs.setdefault(-1, bytearray())
        else:
            target = subs.setdefault(sub, bytearray())
        _put_tag_varint(target, fid, v)
    inner = bytearray()
    if -1 in subs:  # flat UsageMeter
        inner += subs[-1]
    else:
        for sub in sorted(subs):
            _put_tag_bytes(inner, sub, bytes(subs[sub]))
    _put_tag_bytes(flat, sub_field, bytes(inner))
    return bytes(flat)


def encode_document(
    timestamp: int,
    tag_row,
    meter_row,
    flags: int = 0,
    strings: dict[str, str] | None = None,
) -> bytes:
    """One DocBatch row → Document pb bytes."""
    meter_id = int(tag_row[_T.index("meter_id")])
    code = int(CODE_OF_ID.get(CodeId(int(tag_row[_T.index("code_id")])), 0))
    minitag = bytearray()
    _put_tag_bytes(minitag, 1, _encode_minifield(tag_row, strings or {}))
    _put_tag_varint(minitag, 2, code)

    buf = bytearray()
    _put_tag_varint(buf, 1, int(timestamp))
    _put_tag_bytes(buf, 2, bytes(minitag))
    _put_tag_bytes(buf, 3, _encode_meter(meter_row, meter_id))
    _put_tag_varint(buf, 4, int(flags))
    return bytes(buf)


def encode_docbatch(db: DocBatch, flags: int = 0) -> list[bytes]:
    return [
        encode_document(db.timestamp[i], db.tags[i], db.meters[i], flags)
        for i in range(db.size)
        if db.valid[i]
    ]


# ---------------------------------------------------------------------------
# decode


@dataclasses.dataclass
class DecodedBatch:
    """SoA decode result for one meter type."""

    meter_id: int
    meter_schema: MeterSchema
    tags: np.ndarray  # [N, T] u32
    meters: np.ndarray  # [N, M] f32
    timestamp: np.ndarray  # [N] u32
    flags: np.ndarray  # [N] u32
    strings: StringDict
    # per-row string dictionary ids (app_service/app_instance/endpoint)
    service_ids: np.ndarray  # [N, 3] u32

    def to_docbatch(self) -> DocBatch:
        return DocBatch(
            tags=self.tags,
            meters=self.meters,
            timestamp=self.timestamp,
            valid=np.ones(self.tags.shape[0], dtype=bool),
            tag_schema=_T,
            meter_schema=self.meter_schema,
        )


class DocumentDecoder:
    """pb Documents → per-meter SoA batches (the DecodePB hot loop,
    libs/app/codec.go:28, reimplemented columnar)."""

    def __init__(self):
        self.decode_errors = 0
        self.unknown_codes = 0

    def decode_parts(
        self, parts: list[tuple[bytes, list[tuple[int, int]]]]
    ) -> dict[int, DecodedBatch]:
        """Span-based twin of NativeDocumentDecoder.decode_parts (the
        Python path still slices — it is the fallback, not the fast
        path)."""
        msgs = [body[o:o + ln] for body, spans in parts for o, ln in spans]
        return self.decode(msgs)

    def decode(self, messages: list[bytes]) -> dict[int, DecodedBatch]:
        rows: dict[int, list] = {}
        strings = StringDict()
        for msg in messages:
            try:
                row = self._decode_one(msg, strings)
            except Exception:
                # hostile/corrupt wire data must never kill the batch —
                # count and continue (unmarshaller.go decode_errors stance)
                self.decode_errors += 1
                continue
            rows.setdefault(row[0], []).append(row)

        out = {}
        for meter_id, rlist in rows.items():
            _, schema, _ = _METER_OF_ID[meter_id]
            n = len(rlist)
            tags = np.zeros((n, _T.num_fields), dtype=np.uint32)
            meters = np.zeros((n, schema.num_fields), dtype=np.float32)
            ts = np.zeros(n, dtype=np.uint32)
            flags = np.zeros(n, dtype=np.uint32)
            service_ids = np.zeros((n, 3), dtype=np.uint32)
            for i, (_, t, tag_vec, meter_vec, fl, sids) in enumerate(rlist):
                ts[i] = t
                tags[i] = tag_vec
                meters[i] = meter_vec
                flags[i] = fl
                service_ids[i] = sids
            out[meter_id] = DecodedBatch(
                meter_id=meter_id,
                meter_schema=schema,
                tags=tags,
                meters=meters,
                timestamp=ts,
                flags=flags,
                strings=strings,
                service_ids=service_ids,
            )
        return out

    def _decode_one(self, msg: bytes, strings: StringDict):
        ts = 0
        flags = 0
        minitag = b""
        meter_buf = b""
        for field, v in _iter_fields(msg):
            if field == 1:
                ts = v & 0xFFFFFFFF  # native twin masks to u32 too
            elif field == 2:
                minitag = v
            elif field == 3:
                meter_buf = v
            elif field == 4:
                flags = v & 0xFFFFFFFF

        code = 0
        minifield = b""
        for field, v in _iter_fields(minitag):
            if field == 1:
                minifield = v
            elif field == 2:
                code = v

        tag_vec = np.zeros(_T.num_fields, dtype=np.uint32)
        sids = np.zeros(3, dtype=np.uint32)
        raw_strs: dict[int, str] = {}

        def set_tag(name, v):
            tag_vec[_T.index(name)] = v & 0xFFFFFFFF

        for field, v in _iter_fields(minifield):
            if field == 1 or field == 2:
                pre = "ip0" if field == 1 else "ip1"
                b = v
                if len(b) == 4:
                    set_tag(f"{pre}_w3", int.from_bytes(b, "big"))
                elif len(b) == 16:
                    for w in range(4):
                        set_tag(f"{pre}_w{w}", int.from_bytes(b[w * 4 : w * 4 + 4], "big"))
            elif field == 3:
                set_tag("global_thread_id", v)
            elif field == 4:
                set_tag("is_ipv6", v)
            elif field in (5, 6):
                # i32 sign-fold back to u16 (schema.py TAG_SCHEMA note)
                iv = v - (1 << 64) if v >> 63 else v
                set_tag("l3_epc_id" if field == 5 else "l3_epc_id1", iv & 0xFFFF)
            elif field == 7:
                set_tag("mac0_hi", v >> 32)
                set_tag("mac0_lo", v & 0xFFFFFFFF)
            elif field == 8:
                set_tag("mac1_hi", v >> 32)
                set_tag("mac1_lo", v & 0xFFFFFFFF)
            elif field == 9:
                set_tag("direction", v)
            elif field == 10:
                set_tag("tap_side", v)
            elif field == 11:
                set_tag("protocol", v)
            elif field == 12:
                set_tag("acl_gid", v)
            elif field == 13:
                set_tag("server_port", v)
            elif field == 14:
                set_tag("agent_id", v)
            elif field == 15:
                set_tag("tap_port", v)
            elif field == 16:
                set_tag("tap_type", v)
            elif field == 17:
                set_tag("l7_protocol", v)
            elif field == 20:
                set_tag("gpid0", v)
            elif field == 21:
                set_tag("gpid1", v)
            elif field == 22:
                set_tag("signal_source", v)
            elif field in (23, 24, 25):
                # defer interning until the row fully decodes — a row that
                # errors later must not pollute the shared StringDict (the
                # native decoder skips error rows entirely)
                s = v.decode(errors="replace")
                raw_strs[field - 23] = s
                if field == 25:
                    set_tag("endpoint_hash", _hash_str(s))
            elif field == 27:
                set_tag("pod_id", v)
            elif field == 28:
                set_tag("biz_type", v)

        code_id = _ID_OF_CODE.get(code)
        if code_id is None:
            self.unknown_codes += 1
            code_id = CodeId.NONE
        set_tag("code_id", int(code_id))

        meter_id = 0
        sub_bufs: dict[int, bytes] = {}
        for field, v in _iter_fields(meter_buf):
            if field == 1:
                meter_id = v
            elif isinstance(v, (bytes, bytearray)):
                sub_bufs[field] = v
        if meter_id not in _METER_OF_ID:
            raise ValueError(f"unknown meter_id {meter_id}")
        sub_field, schema, layout = _METER_OF_ID[meter_id]
        set_tag("meter_id", meter_id)

        meter_vec = np.zeros(schema.num_fields, dtype=np.float32)
        inner = sub_bufs.get(sub_field, b"")
        rev = {loc: name for name, loc in layout.items()}
        if meter_id == int(MeterId.USAGE):
            for fid, v in _iter_fields(inner):
                name = rev.get((0, fid))
                if name:
                    meter_vec[schema.index(name)] = v
        else:
            for sub, subbuf in _iter_fields(inner):
                if not isinstance(subbuf, (bytes, bytearray)):
                    continue
                for fid, v in _iter_fields(subbuf):
                    name = rev.get((sub, fid))
                    if name:
                        meter_vec[schema.index(name)] = v

        for j, s in raw_strs.items():
            sids[j] = strings.intern(s)
        return meter_id, ts, tag_vec, meter_vec, flags, sids
