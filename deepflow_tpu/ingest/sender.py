"""UniformSender — the agent-side telemetry transport client.

Re-creates `agent/src/sender/uniform_sender.rs` behavior on the host:
batches encoded pb messages into framed TCP writes (header layout in
framing.py), flushes on size or interval, reconnects with exponential
backoff, and fails over across a server list (uniform_sender.rs:398-560).
Messages that cannot be shipped are shed oldest-first by the bounded
overwrite queue — same backpressure stance as the rest of the pipeline.
"""

from __future__ import annotations

import socket
import threading
import time

from ..utils.stats import register_countable
from .framing import (
    ENCODER_RAW,
    MAX_FRAME_SIZE,
    FlowHeader,
    MessageType,
    best_encoder,
    encode_frame,
)
from .queues import new_queue
from ..utils.retry import RetryPolicy, decorrelated_rng

# reconnect backoff: the shared capped-exponential-with-jitter policy
# (utils/retry.py), so a fleet of senders does not re-dial a
# recovering server in lockstep (ISSUE 6). attempts is irrelevant here
# — the reconnect loop is unbounded, only .delay() is used.
_RECONNECT = RetryPolicy(base_delay_s=0.05, max_delay_s=2.0, jitter=0.5)
_BACKOFF_CAP_ATTEMPT = 8  # delay saturates at max_delay_s well before this


class UniformSender:
    def __init__(
        self,
        servers: list[tuple[str, int]],
        msg_type: MessageType,
        *,
        agent_id: int = 1,
        team_id: int = 0,
        organization_id: int = 0,
        batch_bytes: int = 1 << 17,
        flush_interval: float = 0.2,
        queue_capacity: int = 1 << 14,
        prefer_native_queue: bool = True,
        compression: int | str = ENCODER_RAW,
    ):
        if not servers:
            raise ValueError("need at least one server")
        self.servers = list(servers)
        self.msg_type = MessageType(msg_type)
        # "auto" = strongest codec available in-process (framing.best_encoder)
        self.compression = best_encoder() if compression == "auto" else int(compression)
        self.agent_id = agent_id
        self.team_id = team_id
        self.organization_id = organization_id
        self.batch_bytes = min(batch_bytes, MAX_FRAME_SIZE // 2)
        self.flush_interval = flush_interval
        self._q = new_queue(queue_capacity, prefer_native=prefer_native_queue)
        self._sock: socket.socket | None = None
        self._server_idx = 0
        self._running = True
        self._reconnect_pending = False  # a loss happened; next connect is a re-connect
        self._retry_rng = decorrelated_rng(0x5E4DE2)
        self.counters = {
            "tx_frames": 0, "tx_bytes": 0, "tx_msgs": 0,
            "reconnects": 0, "reconnect_success": 0, "send_errors": 0,
            "shutdown_shed_msgs": 0,
        }
        # reconnect attempts/successes are queryable in deepflow_system
        # like every other component (weakly held — a dropped sender
        # deregisters itself)
        self._stats_src = register_countable(
            "tpu_sender", self, msg_type=self.msg_type.name
        )
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- producer side --------------------------------------------------
    def send(self, msgs: list[bytes]) -> None:
        for m in msgs:
            self._q.put(m)

    @property
    def dropped(self) -> int:
        return self._q.overwritten

    def get_counters(self) -> dict:
        """Countable face (utils/stats.StatsCollector)."""
        out = dict(self.counters)
        out["dropped"] = int(self._q.overwritten)
        out["queue_depth"] = len(self._q)
        out["connected"] = int(self._sock is not None)
        return out

    def close(self, drain_timeout: float = 5.0) -> None:
        deadline = time.time() + drain_timeout
        while len(self._q) and time.time() < deadline:
            time.sleep(0.02)
        self._running = False
        self._q.close()
        self._thread.join(timeout=drain_timeout)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    # -- sender thread ---------------------------------------------------
    def _connect(self) -> bool:
        """Try each server once, starting from the current; True on success."""
        for i in range(len(self.servers)):
            host, port = self.servers[(self._server_idx + i) % len(self.servers)]
            try:
                s = socket.create_connection((host, port), timeout=5)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = s
                self._server_idx = (self._server_idx + i) % len(self.servers)
                if self._reconnect_pending:
                    self.counters["reconnect_success"] += 1
                    self._reconnect_pending = False
                return True
            except OSError:
                continue
        return False

    def _frame(self, msgs: list[bytes]) -> bytes:
        header = FlowHeader(
            msg_type=int(self.msg_type),
            agent_id=self.agent_id,
            team_id=self.team_id,
            organization_id=self.organization_id,
        )
        # encode_frame enforces MAX_FRAME_SIZE — a frame that encodes is
        # always accepted by the receiver's reassembler
        return encode_frame(header, msgs, encoder=self.compression)

    def _run(self) -> None:
        attempt = 1  # consecutive connect/send failures (drives backoff)
        pending: list[bytes] = []
        pending_bytes = 0
        last_flush = time.monotonic()
        while self._running or pending or len(self._q):
            if not pending:
                got = self._q.gets(256, timeout_ms=50)
                if not got and not self._running:
                    return
                for m in got:
                    pending.append(m)
                    pending_bytes += len(m) + 4
            elif pending_bytes < self.batch_bytes and self._running:
                # accumulate until flush deadline — wait on the queue for
                # the remaining window instead of spinning
                remaining = self.flush_interval - (time.monotonic() - last_flush)
                if remaining > 0:
                    for m in self._q.gets(256, timeout_ms=max(1, int(remaining * 1000))):
                        pending.append(m)
                        pending_bytes += len(m) + 4
            now = time.monotonic()
            if pending and (pending_bytes >= self.batch_bytes or now - last_flush >= self.flush_interval or not self._running):
                if self._sock is None and not self._connect():
                    self.counters["send_errors"] += 1
                    if not self._running:
                        # shutdown with every server unreachable: shed
                        # the pending buffer (and whatever close() left
                        # in the queue) instead of spinning the thread
                        # forever — counted, like every other shed lane
                        self.counters["shutdown_shed_msgs"] += (
                            len(pending) + len(self._q)
                        )
                        return
                    time.sleep(_RECONNECT.delay(attempt, self._retry_rng))
                    attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)
                    continue
                try:
                    # chunk so no frame exceeds batch_bytes (≤ MAX_FRAME_SIZE/2)
                    while pending:
                        chunk, sz = [], 0
                        while pending and (not chunk or sz + len(pending[0]) + 4 <= self.batch_bytes):
                            m = pending.pop(0)
                            chunk.append(m)
                            sz += len(m) + 4
                        try:
                            frame = self._frame(chunk)
                        except ValueError:
                            # a single message too large for any frame — drop
                            self.counters["send_errors"] += 1
                            continue
                        self._sock.sendall(frame)
                        self.counters["tx_frames"] += 1
                        self.counters["tx_bytes"] += len(frame)
                        self.counters["tx_msgs"] += len(chunk)
                    pending_bytes = 0
                    last_flush = now
                    attempt = 1
                except OSError:
                    # requeue the in-flight chunk: the overwrite queue is
                    # the only place messages may be shed (at-least-once
                    # across reconnects, like the reference's resend of
                    # its current buffer)
                    pending = chunk + pending
                    pending_bytes = sum(len(m) + 4 for m in pending)
                    self.counters["send_errors"] += 1
                    self.counters["reconnects"] += 1
                    self._reconnect_pending = True
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    self._server_idx = (self._server_idx + 1) % len(self.servers)
                    time.sleep(_RECONNECT.delay(attempt, self._retry_rng))
                    attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)
