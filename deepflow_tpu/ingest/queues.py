"""Bounded overwrite queues for the ingest plane.

Mirrors the reference's `OverwriteQueue` (libs/queue/queue.go:43-260):
fixed capacity, *overwrites oldest on overflow* (backpressure sheds the
oldest data, never blocks the producer), blocking batched `Gets` with
timeout on the consumer side.

Two interchangeable implementations: the C++ ring in native/src/queue.cc
(used when the shared object builds) and a Python fallback with identical
semantics. `new_queue` picks automatically.
"""

from __future__ import annotations

import collections
import threading

from .. import native


class PyOverwriteQueue:
    """Python twin of native.OverwriteQueue (same API/semantics)."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._dq: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._overwritten = 0
        self._closed = False

    def put(self, item: bytes) -> bool:
        """Enqueue (overwriting oldest on overflow). Returns False when
        the queue is already closed — the item was NOT accepted; the
        producer (Receiver._dispatch) counts that instead of silently
        losing the frame in the check-then-put race."""
        with self._cond:
            if self._closed:
                return False
            if len(self._dq) >= self.capacity:
                self._dq.popleft()
                self._overwritten += 1
            self._dq.append(bytes(item))
            self._cond.notify()
            return True

    def gets(self, max_items: int, timeout_ms: int = -1) -> list[bytes]:
        """Block until ≥1 item (or timeout/close); pop up to max_items."""
        timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
        with self._cond:
            if not self._dq and not self._closed:
                self._cond.wait(timeout)
            out = []
            while self._dq and len(out) < max_items:
                out.append(self._dq.popleft())
            return out

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def overwritten(self) -> int:
        with self._lock:
            return self._overwritten

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def get_counters(self) -> dict:
        """Countable face (utils/stats.StatsCollector): queue overruns
        were previously discarded unless a caller polled `overwritten`;
        registering queues makes them queryable like every other
        counter (deepflow_system tables via the system sink)."""
        with self._lock:
            return {
                "depth": len(self._dq),
                "capacity": self.capacity,
                "overwritten": self._overwritten,
                "closed": int(self._closed),
            }


def new_queue(capacity: int, prefer_native: bool = True):
    """OverwriteQueue factory: native C++ ring when built, else Python."""
    if prefer_native and native.native_available():
        return native.OverwriteQueue(capacity)
    return PyOverwriteQueue(capacity)


def register_queue_stats(module: str, queues, **tags: str):
    """Register every queue on the default StatsCollector, one source
    per queue (tagged with its index) — the RegisterCountable stance:
    overwrite drops become visible the moment the queue exists, not
    only when an owner remembers to poll. Queues are weakly held, so a
    dropped handler's queues deregister themselves. Returns the
    CounterSource list (callers may deregister explicitly)."""
    from ..utils.stats import register_countable

    return [
        register_countable(module, q, queue=str(i), **tags)
        for i, q in enumerate(queues)
    ]
