"""Telemetry receiver — the server's front door (TCP+UDP :20033 analog).

Re-creates `server/libs/receiver/receiver.go` semantics the TPU-host way:
one TCP listener + one UDP socket, a per-message-type handler registry
(`register_handler`, receiver.go:444), org/team/agent identity parsed from
the 19-byte flow header (:631-700), per-agent liveness/status tracking,
and hash fanout into the handler's N overwrite queues (:515-585) keyed by
agent id so one agent's stream stays ordered within a queue.

Queue items are the *raw frame* (header + body): self-contained bytes so
the native C++ ring can carry them and any worker can re-parse identity
without shared state.
"""

from __future__ import annotations

import socket
import threading
import time
import zlib

from .framing import (
    ENCODER_RAW,
    HEADER_LEN,
    FlowHeader,
    FrameReassembler,
    MessageType,
    decompress_body,
)


class AgentStatus:
    __slots__ = ("agent_id", "org_id", "team_id", "addr", "first_seen", "last_seen", "frames", "bytes")

    def __init__(self, agent_id, org_id, team_id, addr):
        self.agent_id = agent_id
        self.org_id = org_id
        self.team_id = team_id
        self.addr = addr
        self.first_seen = self.last_seen = time.time()
        self.frames = 0
        self.bytes = 0


class Receiver:
    """Framed TCP/UDP intake with per-msg-type queue fanout."""

    def __init__(self, host: str = "127.0.0.1", tcp_port: int = 0, udp_port: int = 0):
        self.host = host
        self.tcp_port = tcp_port
        self.udp_port = udp_port
        self._handlers: dict[int, list] = {}
        self._threads: list[threading.Thread] = []
        self._conn_threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()
        self._tcp_sock: socket.socket | None = None
        self._udp_sock: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._running = False
        self.agents: dict[tuple[int, int], AgentStatus] = {}  # (org, agent) → status
        self.counters = {
            "rx_frames": 0,
            "rx_bytes": 0,
            "bad_frames": 0,
            "no_handler": 0,
            "queue_closed": 0,
            "udp_frames": 0,
            "tcp_conns": 0,
        }
        self._queue_stat_sources: list = []
        # window lineage plane (ISSUE 13): when a LineageTracker is
        # attached, every frame admitted into a handler queue leaves a
        # wall stamp — the feeder pairs stamps to frames FIFO, so the
        # receiver.admit hop opens a window's trace without any header
        # field on the wire
        self.lineage = None

    def agent_list(self) -> list[AgentStatus]:
        """Snapshot for observers (REST/debug) — .agents mutates under
        _stats_lock on every dispatched frame."""
        with self._stats_lock:
            return list(self.agents.values())

    # -- registry (receiver.go:444 RegistHandler) -----------------------
    def register_handler(self, msg_type: MessageType, queues: list) -> None:
        if not queues:
            raise ValueError("need at least one queue")
        self._handlers[int(msg_type)] = list(queues)
        # surface each queue's depth/overrun counters on the default
        # stats collector — overwrite drops were previously invisible
        # unless an owner polled .overwritten (ISSUE 4 satellite)
        from .queues import register_queue_stats

        self._queue_stat_sources += register_queue_stats(
            "ingest_queue", queues, msg_type=str(int(msg_type))
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._running = True
        self._tcp_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._tcp_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._tcp_sock.bind((self.host, self.tcp_port))
        self.tcp_port = self._tcp_sock.getsockname()[1]
        self._tcp_sock.listen(64)
        # timeouts on every blocking op: on Linux, close() does NOT wake a
        # thread blocked in accept()/recv(), which would keep the listening
        # socket alive (and the port EADDRINUSE) after stop()
        self._tcp_sock.settimeout(0.5)

        self._udp_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._udp_sock.bind((self.host, self.udp_port))
        self.udp_port = self._udp_sock.getsockname()[1]
        self._udp_sock.settimeout(0.5)

        for target in (self._accept_loop, self._udp_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        for s in (self._tcp_sock, self._udp_sock):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            threads = self._threads + self._conn_threads
        for t in threads:
            t.join(timeout=2)

    # -- dispatch -------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        # dict += is a non-atomic read-modify-write; conn threads + the UDP
        # thread all dispatch concurrently
        with self._stats_lock:
            self.counters[key] += n

    def _dispatch(self, header: FlowHeader, raw_frame: bytes, addr) -> None:
        if header.encoder != ENCODER_RAW:
            # decompress at the front door and re-frame raw, so every
            # downstream consumer keeps its encoder-oblivious parse
            try:
                body = decompress_body(raw_frame[HEADER_LEN:], header.encoder)
            except (ValueError, zlib.error):
                self._count("bad_frames")
                return
            header.encoder = ENCODER_RAW
            header.frame_size = HEADER_LEN + len(body)
            raw_frame = header.encode() + body
        key = (header.organization_id, header.agent_id)
        with self._stats_lock:
            self.counters["rx_frames"] += 1
            self.counters["rx_bytes"] += len(raw_frame)
            st = self.agents.get(key)
            if st is None:
                st = self.agents[key] = AgentStatus(
                    header.agent_id, header.organization_id, header.team_id, addr
                )
            st.last_seen = time.time()
            st.frames += 1
            st.bytes += len(raw_frame)

        queues = self._handlers.get(header.msg_type)
        if not queues:
            self._count("no_handler")
            return
        q = queues[header.agent_id % len(queues)]
        # a handler shutting down mid-stream closes its queues; frames
        # racing that close are counted and skipped — never raised into
        # the conn/UDP loop (which would tear down the whole connection
        # for every agent sharing it). put() returning False covers the
        # check-then-put race (queues.py); the pre-check stays as the
        # fast path and for queue impls whose put has no return signal.
        if getattr(q, "closed", False):
            self._count("queue_closed")
            return
        try:
            if q.put(raw_frame) is False:
                self._count("queue_closed")
                return
        except Exception:
            self._count("queue_closed")
            return
        lin = self.lineage
        if lin is not None:
            lin.note_admit()

    # -- TCP ------------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._tcp_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            self._count("tcp_conns")
            with self._lock:
                self._conns.add(conn)
                # prune finished handler threads so a long-lived receiver
                # doesn't grow the list unboundedly
                self._conn_threads = [t for t in self._conn_threads if t.is_alive()]
            t = threading.Thread(target=self._conn_loop, args=(conn, addr), daemon=True)
            t.start()
            with self._lock:
                self._conn_threads.append(t)

    def _conn_loop(self, conn: socket.socket, addr) -> None:
        asm = FrameReassembler()
        seen_bad = 0
        try:
            while self._running:
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                if not chunk:
                    return
                for header, body in asm.feed(chunk):
                    self._dispatch(header, header.encode() + body, addr)
                if asm.bad_frames != seen_bad:
                    self._count("bad_frames", asm.bad_frames - seen_bad)
                    seen_bad = asm.bad_frames
        except OSError:
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- UDP (one frame per datagram, receiver.go UDP path) -------------
    def _udp_loop(self) -> None:
        while self._running:
            try:
                data, addr = self._udp_sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            self._count("udp_frames")
            if len(data) < HEADER_LEN:
                self._count("bad_frames")
                continue
            try:
                header = FlowHeader.parse(data[:HEADER_LEN])
            except ValueError:
                self._count("bad_frames")
                continue
            if header.frame_size != len(data):
                self._count("bad_frames")
                continue
            self._dispatch(header, data, addr)
