"""Telemetry receiver — the server's front door (TCP+UDP :20033 analog).

Re-creates `server/libs/receiver/receiver.go` semantics the TPU-host way:
one TCP listener + one UDP socket, a per-message-type handler registry
(`register_handler`, receiver.go:444), org/team/agent identity parsed from
the 19-byte flow header (:631-700), per-agent liveness/status tracking,
and hash fanout into the handler's N overwrite queues (:515-585) keyed by
agent id so one agent's stream stays ordered within a queue.

Queue items are the *raw frame* (header + body): self-contained bytes so
the native C++ ring can carry them and any worker can re-parse identity
without shared state.
"""

from __future__ import annotations

import socket
import threading
import time
import zlib

from .framing import (
    ENCODER_RAW,
    HEADER_LEN,
    FlowHeader,
    FrameReassembler,
    MessageType,
    decompress_body,
)


class AgentStatus:
    __slots__ = ("agent_id", "org_id", "team_id", "addr", "first_seen",
                 "last_seen", "frames", "bytes", "route")

    def __init__(self, agent_id, org_id, team_id, addr):
        self.agent_id = agent_id
        self.org_id = org_id
        self.team_id = team_id
        self.addr = addr
        self.first_seen = self.last_seen = time.time()
        self.frames = 0
        self.bytes = 0
        # key-hash routing cache (ISSUE 14): the (org, agent) → group
        # map is pure, so it is computed once per agent per topology
        # epoch instead of a numpy fingerprint fold per FRAME. ONE
        # (epoch, group) tuple — epoch and group are never split
        # across two stores, so a re-attach race cannot stamp a
        # new-topology group with an old epoch
        self.route: tuple | None = None


class Receiver:
    """Framed TCP/UDP intake with per-msg-type queue fanout."""

    def __init__(self, host: str = "127.0.0.1", tcp_port: int = 0, udp_port: int = 0,
                 *, held_frames_cap: int = 256):
        self.host = host
        self.tcp_port = tcp_port
        self.udp_port = udp_port
        # msg_type → {shard_group_or_None: [queues]} — the None slot is
        # the ungrouped handler every pre-topology caller registers
        self._handlers: dict[int, dict] = {}
        self._threads: list[threading.Thread] = []
        self._conn_threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()
        self._tcp_sock: socket.socket | None = None
        self._udp_sock: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._running = False
        self.agents: dict[tuple[int, int], AgentStatus] = {}  # (org, agent) → status
        self.counters = {
            "rx_frames": 0,
            "rx_bytes": 0,
            "bad_frames": 0,
            "no_handler": 0,
            "queue_closed": 0,
            "udp_frames": 0,
            "tcp_conns": 0,
            # key-hash fan-in routing (ISSUE 14): frames whose shard
            # group another process owns — counted and forwarded
            # through the control-plane handoff, NEVER enqueued into a
            # wrong-group handler (the data path never crosses hosts)
            "frames_misrouted": 0,
            "frames_handoff": 0,
            "handoff_errors": 0,
            # epoch-flip hold buffer (ISSUE 15): frames for a group
            # this process owns in the NEW epoch but whose handler is
            # still mid-restore are held-and-redelivered, never counted
            # as misroutes against a peer that no longer owns them;
            # overflow sheds the OLDEST held frame, counted
            "frames_held": 0,
            "frames_held_dropped": 0,
            "frames_redelivered": 0,
        }
        # bounded (msg_type, group, raw_frame, addr) hold ring — sized
        # for the re-route window of one rebalance, not a durability
        # buffer (the journal is; this only bridges the flip)
        self._held_cap = int(held_frames_cap)
        self._held: list = []
        # serializes whole redelivery PASSES (the swap is under
        # _stats_lock, but routing the swapped batch happens outside
        # it — two concurrent passes could interleave one agent's
        # frames out of arrival order)
        self._redeliver_mutex = threading.Lock()
        # multi-host fan-in (ISSUE 14): key-hash topology routing +
        # the control-plane forward for misrouted frames, published as
        # ONE immutable (topology, handoff, epoch) tuple so a dispatch
        # thread racing a re-attach can never pair the new topology
        # with a stale per-agent cached group (the epoch invalidates
        # those caches, and it travels WITH the topology it stamps)
        self._routing: tuple | None = None
        self._route_epoch = 0
        self._queue_stat_sources: list = []
        # misroute/drop visibility in deepflow_system: the receiver is
        # a Countable like the queues it fans into
        from ..utils.stats import register_countable

        self._stats_src = register_countable("tpu_receiver", self)
        # window lineage plane (ISSUE 13): when a LineageTracker is
        # attached, every frame admitted into a handler queue leaves a
        # wall stamp — the feeder pairs stamps to frames FIFO, so the
        # receiver.admit hop opens a window's trace without any header
        # field on the wire
        self.lineage = None

    def agent_list(self) -> list[AgentStatus]:
        """Snapshot for observers (REST/debug) — .agents mutates under
        _stats_lock on every dispatched frame."""
        with self._stats_lock:
            return list(self.agents.values())

    def get_counters(self) -> dict:
        """Countable face (→ deepflow_system as tpu_receiver_*): frame/
        byte totals, drop classes, and the fan-in routing lanes."""
        with self._stats_lock:
            out = dict(self.counters)
            out["agents_seen"] = len(self.agents)
        return out

    # -- key-hash fan-in routing (ISSUE 14) ------------------------------
    @property
    def routing(self):
        """The published (topology, handoff, epoch) tuple, or None
        before any attach — the rebalance rollback reads the pre-flip
        handoff from here so an aborted move restores forwarding."""
        return self._routing

    def attach_topology(self, topology, handoff=None) -> None:
        """Route agents to shard groups by key-hash (MeshTopology.
        group_for_agent over the packed identity words). Frames of
        locally-owned groups enqueue into that group's handler queues;
        misrouted frames are counted (`frames_misrouted`) and forwarded
        through `handoff(group, raw_frame)` — the control-plane path to
        the owning host (e.g. a UniformSender), guarded and counted.
        With no handoff attached misroutes are counted drops: silently
        feeding a wrong-group pipeline would split one agent's keys
        across two exact stashes.

        Routing applies PER MESSAGE TYPE, and only to types with at
        least one group-registered handler — lanes whose handlers are
        all ungrouped (METRICS, SYSLOG, ...) keep delivering every
        agent's frames locally, sharded-plane topology or not.

        Re-attaching publishes a new epoch (ISSUE 15 rebalance flip):
        per-agent route caches invalidate, and any held frames re-route
        under the new table — a frame held for a group this process
        just stopped owning forwards instead of rotting in the hold."""
        self._route_epoch += 1
        # single atomic publish: dispatch threads read the tuple once
        self._routing = (topology, handoff, self._route_epoch)
        self._redeliver_held()

    # -- registry (receiver.go:444 RegistHandler) -----------------------
    def register_handler(self, msg_type: MessageType, queues: list,
                         *, shard_group: int | None = None) -> None:
        """Register a handler's queue fanout; `shard_group` pins the
        queues to one key-hash group (one handler per owned group —
        the ISSUE 14 fan-in shape). Ungrouped registration (None) stays
        the fallback for every group this process owns."""
        if not queues:
            raise ValueError("need at least one queue")
        self._handlers.setdefault(int(msg_type), {})[shard_group] = list(queues)
        # surface each queue's depth/overrun counters on the default
        # stats collector — overwrite drops were previously invisible
        # unless an owner polled .overwritten (ISSUE 4 satellite)
        from .queues import register_queue_stats

        tags = {"msg_type": str(int(msg_type))}
        if shard_group is not None:
            tags["group"] = str(shard_group)
        self._queue_stat_sources += register_queue_stats(
            "ingest_queue", queues, **tags
        )
        # epoch-flip hold (ISSUE 15): frames that arrived for this
        # group while its handler was mid-restore redeliver now, in
        # arrival order, ahead of anything the conn threads enqueue next
        self._redeliver_held()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._running = True
        self._tcp_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._tcp_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._tcp_sock.bind((self.host, self.tcp_port))
        self.tcp_port = self._tcp_sock.getsockname()[1]
        self._tcp_sock.listen(64)
        # timeouts on every blocking op: on Linux, close() does NOT wake a
        # thread blocked in accept()/recv(), which would keep the listening
        # socket alive (and the port EADDRINUSE) after stop()
        self._tcp_sock.settimeout(0.5)

        self._udp_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._udp_sock.bind((self.host, self.udp_port))
        self.udp_port = self._udp_sock.getsockname()[1]
        self._udp_sock.settimeout(0.5)

        for target in (self._accept_loop, self._udp_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)
        for s in (self._tcp_sock, self._udp_sock):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            threads = self._threads + self._conn_threads
        for t in threads:
            t.join(timeout=2)

    # -- dispatch -------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        # dict += is a non-atomic read-modify-write; conn threads + the UDP
        # thread all dispatch concurrently
        with self._stats_lock:
            self.counters[key] += n

    def _dispatch(self, header: FlowHeader, raw_frame: bytes, addr) -> None:
        if header.encoder != ENCODER_RAW:
            # decompress at the front door and re-frame raw, so every
            # downstream consumer keeps its encoder-oblivious parse
            try:
                body = decompress_body(raw_frame[HEADER_LEN:], header.encoder)
            except (ValueError, zlib.error):
                self._count("bad_frames")
                return
            header.encoder = ENCODER_RAW
            header.frame_size = HEADER_LEN + len(body)
            raw_frame = header.encode() + body
        key = (header.organization_id, header.agent_id)
        with self._stats_lock:
            self.counters["rx_frames"] += 1
            self.counters["rx_bytes"] += len(raw_frame)
            st = self.agents.get(key)
            if st is None:
                st = self.agents[key] = AgentStatus(
                    header.agent_id, header.organization_id, header.team_id, addr
                )
            st.last_seen = time.time()
            st.frames += 1
            st.bytes += len(raw_frame)
        self._route_frame(header, raw_frame, addr, st)

    def _route_frame(self, header: FlowHeader, raw_frame: bytes, addr,
                     st: "AgentStatus", *, from_hold: bool = False) -> bool:
        """Route one rx-accounted frame: key-hash topology routing,
        misroute handoff, the epoch-flip hold buffer, queue fanout.
        Shared by live dispatch and held-frame redelivery (which must
        not re-count rx). Returns False only when the frame was
        (re-)held."""
        groups = self._handlers.get(header.msg_type)
        if not groups:
            self._count("no_handler")
            return True
        routing = self._routing  # one read: (topology, handoff, epoch)
        group = None
        if routing is not None and any(k is not None for k in groups):
            topo, handoff, epoch = routing
            # key-hash fan-in (ISSUE 14): the agent's packed identity
            # words pick the shard group; only locally-owned frames may
            # enqueue — the data path never crosses hosts, so a frame
            # for a remote group forwards via the control-plane handoff.
            # Scope: ONLY message types with group-registered handlers
            # route — a lane whose handlers are all ungrouped serves
            # every agent locally regardless of the sharded topology.
            # The pure (org, agent) → group map is cached per agent
            # (st is this frame's AgentStatus from the stats block) as
            # ONE (epoch, group) tuple; pairing the epoch from the
            # SAME tuple as the topology guarantees the cache is never
            # read or written against a different attach.
            route = st.route
            if route is None or route[0] != epoch:
                route = (epoch, topo.group_for_agent(
                    header.organization_id, header.agent_id
                ))
                st.route = route
            group = route[1]
            if not topo.owns_group(group):
                self._count("frames_misrouted")
                if handoff is not None:
                    try:
                        handoff(group, raw_frame)
                        self._count("frames_handoff")
                    except Exception:
                        # the forward path must never raise into the
                        # conn/UDP loop; the drop is counted
                        self._count("handoff_errors")
                return True
        queues = groups.get(group)
        if queues is None and group is not None:
            queues = groups.get(None)
        if not queues:
            if group is not None:
                # epoch-flip hold (ISSUE 15): this process owns the
                # group in the CURRENT epoch but its handler is still
                # mid-restore — hold and redeliver at register_handler
                # instead of counting a misroute against a peer that no
                # longer owns the group (or dropping outright)
                self._hold_frame(raw_frame, addr, recount=not from_hold)
                if not from_hold:
                    # close the hold-vs-register race: if the handler
                    # (or a new epoch) landed between our registry read
                    # and the hold append, ITS redelivery pass has
                    # already drained — re-drain so this frame cannot
                    # strand in the hold until some future flip. The
                    # hold append and the registering thread's drain
                    # serialize on _stats_lock, so one of the two
                    # passes always sees the frame.
                    now = self._handlers.get(header.msg_type)
                    if self._routing is not routing or (
                        now is not None and now.get(group) is not None
                    ):
                        self._redeliver_held()
                return False
            self._count("no_handler")
            return True
        q = queues[header.agent_id % len(queues)]
        # a handler shutting down mid-stream closes its queues; frames
        # racing that close are counted and skipped — never raised into
        # the conn/UDP loop (which would tear down the whole connection
        # for every agent sharing it). put() returning False covers the
        # check-then-put race (queues.py); the pre-check stays as the
        # fast path and for queue impls whose put has no return signal.
        if getattr(q, "closed", False):
            self._count("queue_closed")
            return True
        try:
            if q.put(raw_frame) is False:
                self._count("queue_closed")
                return True
        except Exception:
            self._count("queue_closed")
            return True
        lin = self.lineage
        if lin is not None:
            lin.note_admit()
        return True

    # -- epoch-flip hold buffer (ISSUE 15) -------------------------------
    def _hold_frame(self, raw_frame: bytes, addr, *,
                    recount: bool = True) -> None:
        """Bounded hold: overflow sheds the OLDEST held frame, counted
        (`frames_held_dropped`) — freshest-wins, the OverwriteQueue
        stance. Only (frame, addr) is held: redelivery re-parses the
        header and re-routes under the CURRENT table, never the
        held-time msg_type/group."""
        with self._stats_lock:
            self._held.append((raw_frame, addr))
            if recount:
                self.counters["frames_held"] += 1
            if len(self._held) > self._held_cap:
                self._held.pop(0)
                self.counters["frames_held_dropped"] += 1

    def _redeliver_held(self) -> None:
        """Re-route every held frame under the current handler registry
        and epoch (called after register_handler / attach_topology).
        Frames that still have no home re-hold without recounting;
        everything else leaves through its normal counted lane. The
        whole pass serializes on _redeliver_mutex: a second caller
        (conn thread closing the hold-vs-register race) blocks until
        the first batch has fully routed, so one agent's held frames
        always leave in arrival order."""
        with self._redeliver_mutex:
            with self._stats_lock:
                if not self._held:
                    return
                held, self._held = self._held, []
            for raw_frame, addr in held:
                try:
                    header = FlowHeader.parse(raw_frame[:HEADER_LEN])
                except ValueError:
                    self._count("bad_frames")
                    continue
                key = (header.organization_id, header.agent_id)
                with self._stats_lock:
                    st = self.agents.get(key)
                    if st is None:
                        st = self.agents[key] = AgentStatus(
                            header.agent_id, header.organization_id,
                            header.team_id, addr,
                        )
                if self._route_frame(header, raw_frame, addr, st,
                                     from_hold=True):
                    self._count("frames_redelivered")

    # -- TCP ------------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._tcp_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            self._count("tcp_conns")
            with self._lock:
                self._conns.add(conn)
                # prune finished handler threads so a long-lived receiver
                # doesn't grow the list unboundedly
                self._conn_threads = [t for t in self._conn_threads if t.is_alive()]
            t = threading.Thread(target=self._conn_loop, args=(conn, addr), daemon=True)
            t.start()
            with self._lock:
                self._conn_threads.append(t)

    def _conn_loop(self, conn: socket.socket, addr) -> None:
        asm = FrameReassembler()
        seen_bad = 0
        try:
            while self._running:
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                if not chunk:
                    return
                for header, body in asm.feed(chunk):
                    self._dispatch(header, header.encode() + body, addr)
                if asm.bad_frames != seen_bad:
                    self._count("bad_frames", asm.bad_frames - seen_bad)
                    seen_bad = asm.bad_frames
        except OSError:
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- UDP (one frame per datagram, receiver.go UDP path) -------------
    def _udp_loop(self) -> None:
        while self._running:
            try:
                data, addr = self._udp_sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            self._count("udp_frames")
            if len(data) < HEADER_LEN:
                self._count("bad_frames")
                continue
            try:
                header = FlowHeader.parse(data[:HEADER_LEN])
            except ValueError:
                self._count("bad_frames")
                continue
            if header.frame_size != len(data):
                self._count("bad_frames")
                continue
            self._dispatch(header, data, addr)
