"""Synthetic flow replay — the test/bench firehose.

Stands in for the reference's pcap-replay drivers (SURVEY §4): generates
accumulated-flow records over a fixed population of 5-tuples with
realistic field distributions, either as python dicts (oracle input) or
as ready-made SoA FlowBatches (device input). Deterministic per seed.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..datamodel.batch import FlowBatch
from ..datamodel.code import Direction, L7Protocol, SignalSource
from ..datamodel.schema import APP_METER, FLOW_METER


@dataclasses.dataclass
class SyntheticFlowGen:
    num_tuples: int = 10_000  # unique flow population (BASELINE config 1)
    seed: int = 0
    start_time: int = 1_700_000_000
    agent_id: int = 1
    # fraction of flows with both directions known / one / none
    p_both_dirs: float = 0.7
    p_one_dir: float = 0.2

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_tuples
        self.pop = {
            "ip0": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "ip1": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "port": rng.choice(
                np.array([80, 443, 3306, 6379, 8080, 9092], dtype=np.uint32), n
            ),
            "proto": rng.choice(np.array([6, 6, 6, 17], dtype=np.uint32), n),
            "epc0": rng.integers(1, 50, n, dtype=np.uint32),
            "epc1": rng.integers(1, 50, n, dtype=np.uint32),
            "pod0": rng.integers(1, 500, n, dtype=np.uint32),
            "gpid0": rng.integers(0, 1000, n, dtype=np.uint32),
            "gpid1": rng.integers(0, 1000, n, dtype=np.uint32),
        }
        u = rng.random(n)
        self.pop_dir0 = np.where(u < self.p_both_dirs + self.p_one_dir, np.uint32(Direction.CLIENT_TO_SERVER), 0)
        self.pop_dir1 = np.where(u < self.p_both_dirs, np.uint32(Direction.SERVER_TO_CLIENT), 0)
        self._rng = rng

    def _draw(self, batch: int, t: int):
        rng = self._rng
        idx = rng.integers(0, self.num_tuples, batch)
        pkts = rng.integers(1, 100, batch)
        bytes_ = pkts * rng.integers(64, 1400, batch)
        rtt = rng.integers(100, 50_000, batch)
        return idx, pkts, bytes_, rtt

    def records(self, batch: int, t: int) -> list[dict]:
        """One batch of flow dicts at timestamp t (oracle/codec input)."""
        idx, pkts, bytes_, rtt = self._draw(batch, t)
        p = self.pop
        out = []
        for i in range(batch):
            j = int(idx[i])
            out.append(
                {
                    "timestamp": t,
                    "global_thread_id": 1,
                    "agent_id": self.agent_id,
                    "signal_source": int(SignalSource.PACKET),
                    "ip0_w3": int(p["ip0"][j]),
                    "ip1_w3": int(p["ip1"][j]),
                    "l3_epc_id": int(p["epc0"][j]),
                    "l3_epc_id1": int(p["epc1"][j]),
                    "gpid0": int(p["gpid0"][j]),
                    "gpid1": int(p["gpid1"][j]),
                    "pod_id": int(p["pod0"][j]),
                    "protocol": int(p["proto"][j]),
                    "server_port": int(p["port"][j]),
                    "tap_type": 3,
                    "tap_port": 1,
                    "direction0": int(self.pop_dir0[j]),
                    "direction1": int(self.pop_dir1[j]),
                    "is_active_host0": 1,
                    "is_active_host1": 1,
                    "is_active_service": 1,
                    "meter": {
                        "packet_tx": int(pkts[i]),
                        "packet_rx": int(pkts[i] // 2),
                        "byte_tx": int(bytes_[i]),
                        "byte_rx": int(bytes_[i] // 2),
                        "l3_byte_tx": int(bytes_[i] * 9 // 10),
                        "l3_byte_rx": int(bytes_[i] * 9 // 20),
                        "new_flow": 1,
                        "closed_flow": 0,
                        "rtt_max": int(rtt[i]),
                        "rtt_sum": int(rtt[i]),
                        "rtt_count": 1,
                        "syn": 1,
                        "synack": 1,
                    },
                }
            )
        return out

    def flow_batch(self, batch: int, t: int) -> FlowBatch:
        """Columnar batch straight into the device pipeline (fast path)."""
        idx, pkts, bytes_, rtt = self._draw(batch, t)
        p = self.pop
        from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS

        tags = {f: np.zeros(batch, dtype=np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        tags["timestamp"][:] = t
        tags["global_thread_id"][:] = 1
        tags["agent_id"][:] = self.agent_id
        tags["signal_source"][:] = int(SignalSource.PACKET)
        tags["ip0_w3"] = p["ip0"][idx]
        tags["ip1_w3"] = p["ip1"][idx]
        tags["l3_epc_id"] = p["epc0"][idx]
        tags["l3_epc_id1"] = p["epc1"][idx]
        tags["gpid0"] = p["gpid0"][idx]
        tags["gpid1"] = p["gpid1"][idx]
        tags["pod_id"] = p["pod0"][idx]
        tags["protocol"] = p["proto"][idx]
        tags["server_port"] = p["port"][idx]
        tags["tap_type"][:] = 3
        tags["tap_port"][:] = 1
        tags["direction0"] = self.pop_dir0[idx]
        tags["direction1"] = self.pop_dir1[idx]
        tags["is_active_host0"][:] = 1
        tags["is_active_host1"][:] = 1
        tags["is_active_service"][:] = 1

        meters = np.zeros((batch, FLOW_METER.num_fields), dtype=np.float32)
        col = FLOW_METER.index
        meters[:, col("packet_tx")] = pkts
        meters[:, col("packet_rx")] = pkts // 2
        meters[:, col("byte_tx")] = bytes_
        meters[:, col("byte_rx")] = bytes_ // 2
        meters[:, col("l3_byte_tx")] = bytes_ * 9 // 10
        meters[:, col("l3_byte_rx")] = bytes_ * 9 // 20
        meters[:, col("new_flow")] = 1
        meters[:, col("rtt_max")] = rtt
        meters[:, col("rtt_sum")] = rtt
        meters[:, col("rtt_count")] = 1
        meters[:, col("syn")] = 1
        meters[:, col("synack")] = 1
        return FlowBatch(tags=tags, meters=meters, valid=np.ones(batch, dtype=bool))


@dataclasses.dataclass
class SyntheticAppGen:
    """L7 request-log firehose (BASELINE config 2): a service population
    with per-service endpoint sets, RED meters and log-normal-ish request
    latencies. Emits AppMeterWithFlow-shaped records/batches."""

    num_services: int = 64
    endpoints_per_service: int = 16
    seed: int = 0
    agent_id: int = 1
    p_error: float = 0.02

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_services
        self.svc = {
            "ip1": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "port": rng.choice(np.array([80, 443, 8080, 9000], dtype=np.uint32), n),
            "epc1": rng.integers(1, 50, n, dtype=np.uint32),
            "l7": rng.choice(
                np.array(
                    [L7Protocol.HTTP1, L7Protocol.GRPC, L7Protocol.MYSQL, L7Protocol.REDIS],
                    dtype=np.uint32,
                ),
                n,
            ),
            # median latency per service, µs
            "lat_med": rng.integers(500, 20_000, n).astype(np.float64),
        }
        self._rng = rng

    def _draw(self, batch: int):
        rng = self._rng
        svc = rng.integers(0, self.num_services, batch)
        ep = rng.integers(0, self.endpoints_per_service, batch)
        # endpoint_hash as the reference computes it agent-side (a hash of
        # the endpoint string); here a mixed function of (svc, ep).
        ep_hash = (
            (svc.astype(np.uint64) * np.uint64(2654435761) + ep.astype(np.uint64))
            & np.uint64(0xFFFFFFFF)
        ).astype(np.uint32)
        client_ip = rng.integers(0x0A000000, 0x0AFFFFFF, batch, dtype=np.uint32)
        lat = (self.svc["lat_med"][svc] * rng.lognormal(0.0, 0.6, batch)).astype(np.uint32)
        err = rng.random(batch) < self.p_error
        return svc, ep_hash, client_ip, lat, err

    def records(self, batch: int, t: int, draw=None) -> list[dict]:
        svc, ep_hash, client_ip, lat, err = draw if draw is not None else self._draw(batch)
        s = self.svc
        out = []
        for i in range(batch):
            j = int(svc[i])
            out.append(
                {
                    "timestamp": t,
                    "global_thread_id": 1,
                    "agent_id": self.agent_id,
                    "signal_source": int(SignalSource.PACKET),
                    "ip0_w3": int(client_ip[i]),
                    "ip1_w3": int(s["ip1"][j]),
                    "l3_epc_id": 10,
                    "l3_epc_id1": int(s["epc1"][j]),
                    "protocol": 6,
                    "server_port": int(s["port"][j]),
                    "tap_type": 3,
                    "tap_port": 1,
                    "l7_protocol": int(s["l7"][j]),
                    "endpoint_hash": int(ep_hash[i]),
                    "direction0": int(Direction.CLIENT_TO_SERVER),
                    "direction1": int(Direction.SERVER_TO_CLIENT),
                    "is_active_host0": 1,
                    "is_active_host1": 1,
                    "is_active_service": 1,
                    "meter": {
                        "request": 1,
                        "response": 1,
                        "rrt_max": int(lat[i]),
                        "rrt_sum": int(lat[i]),
                        "rrt_count": 1,
                        "server_error": int(err[i]),
                    },
                }
            )
        return out

    def app_batch(self, batch: int, t: int, draw=None) -> FlowBatch:
        """Columnar batch (meters follow APP_METER). Pass the same `draw`
        (from `_draw`) to records() and app_batch to get both views of one
        workload — the conformance test uses that to pin their equivalence.
        """
        svc, ep_hash, client_ip, lat, err = draw if draw is not None else self._draw(batch)
        s = self.svc
        from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS

        tags = {f: np.zeros(batch, dtype=np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        tags["timestamp"][:] = t
        tags["global_thread_id"][:] = 1
        tags["agent_id"][:] = self.agent_id
        tags["signal_source"][:] = int(SignalSource.PACKET)
        tags["ip0_w3"] = client_ip
        tags["ip1_w3"] = s["ip1"][svc]
        tags["l3_epc_id"][:] = 10
        tags["l3_epc_id1"] = s["epc1"][svc]
        tags["protocol"][:] = 6
        tags["server_port"] = s["port"][svc]
        tags["tap_type"][:] = 3
        tags["tap_port"][:] = 1
        tags["l7_protocol"] = s["l7"][svc]
        tags["endpoint_hash"] = ep_hash
        tags["direction0"][:] = int(Direction.CLIENT_TO_SERVER)
        tags["direction1"][:] = int(Direction.SERVER_TO_CLIENT)
        tags["is_active_host0"][:] = 1
        tags["is_active_host1"][:] = 1
        tags["is_active_service"][:] = 1

        meters = np.zeros((batch, APP_METER.num_fields), dtype=np.float32)
        col = APP_METER.index
        meters[:, col("request")] = 1
        meters[:, col("response")] = 1
        meters[:, col("rrt_max")] = lat
        meters[:, col("rrt_sum")] = lat
        meters[:, col("rrt_count")] = 1
        meters[:, col("server_error")] = err
        return FlowBatch(tags=tags, meters=meters, valid=np.ones(batch, dtype=bool))


@dataclasses.dataclass
class SyntheticTaggedFlowGen:
    """Per-second TaggedFlow emission stream for the flow-log plane.

    Models what FlowMap's inject_flush_ticker hands to FlowAggr
    (flow_map.rs:555 → flow_aggr.rs:216): every active flow emits one row
    per second carrying delta counters and its current lifecycle state;
    the final emission carries close_type. Flow lifetimes are drawn so a
    slice of the population spans minute boundaries — the case
    minute_merge exists for.
    """

    num_flows: int = 1000
    seed: int = 0
    agent_id: int = 1
    max_life_s: int = 90

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_flows
        self.flow_id = rng.integers(1, 1 << 62, n).astype(np.uint64)
        self.ip0 = rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32)
        self.ip1 = rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32)
        self.cport = rng.integers(32768, 61000, n, dtype=np.uint32)
        self.sport = rng.choice(np.array([80, 443, 3306, 6379], dtype=np.uint32), n)
        self.epc0 = rng.integers(1, 50, n, dtype=np.uint32)
        self.epc1 = rng.integers(1, 50, n, dtype=np.uint32)
        self.start_off = rng.integers(0, 30, n)
        self.life = rng.integers(1, self.max_life_s, n)
        self._rng = rng

    def batches_for_second(self, t0: int, sec: int, schema=None):
        """FlowLogBatch of all flows active at t0+sec (may be empty)."""
        from ..flowlog.aggr import FlowLogBatch
        from ..flowlog.schema import L4_FLOW_LOG

        schema = schema or L4_FLOW_LOG
        rng = self._rng
        active = np.nonzero(
            (self.start_off <= sec) & (sec < self.start_off + self.life)
        )[0]
        n = len(active)
        ints = np.zeros((n, len(schema.ints)), np.uint32)
        nums = np.zeros((n, len(schema.nums)), np.float32)
        ii = schema.int_index
        ni = schema.num_index
        fid = self.flow_id[active]
        ints[:, ii("flow_id_hi")] = (fid >> np.uint64(32)).astype(np.uint32)
        ints[:, ii("flow_id_lo")] = (fid & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        ints[:, ii("agent_id")] = self.agent_id
        ints[:, ii("ip0_w3")] = self.ip0[active]
        ints[:, ii("ip1_w3")] = self.ip1[active]
        ints[:, ii("client_port")] = self.cport[active]
        ints[:, ii("server_port")] = self.sport[active]
        ints[:, ii("protocol")] = 6
        ints[:, ii("l3_epc_id_0")] = self.epc0[active]
        ints[:, ii("l3_epc_id_1")] = self.epc1[active]
        ints[:, ii("tap_type")] = 3
        ints[:, ii("tap_side")] = 1
        ints[:, ii("start_time")] = t0 + self.start_off[active]
        ints[:, ii("end_time")] = t0 + sec
        is_first = self.start_off[active] == sec
        is_last = (self.start_off[active] + self.life[active] - 1) == sec
        # lifecycle: 1 opening, 2 established, 3 closed
        ints[:, ii("state")] = np.where(is_last, 3, np.where(is_first, 1, 2))
        ints[:, ii("close_type")] = np.where(is_last, 1, 0)  # 1 = TCP_FIN
        ints[:, ii("status")] = 1
        ints[:, ii("tcp_flags_bit_0")] = np.where(
            is_first, 0x02, np.where(is_last, 0x11, 0x10)
        )
        pkts = rng.integers(1, 50, n)
        nums[:, ni("packet_tx")] = pkts
        nums[:, ni("packet_rx")] = pkts // 2
        nums[:, ni("byte_tx")] = pkts * rng.integers(64, 1400, n)
        nums[:, ni("byte_rx")] = (pkts // 2) * rng.integers(64, 1400, n)
        nums[:, ni("syn_count")] = is_first.astype(np.float32)
        nums[:, ni("rtt")] = np.where(is_first, rng.integers(100, 40_000, n), 0)
        nums[:, ni("retrans_tx")] = rng.random(n) < 0.05
        return FlowLogBatch(schema, ints, nums, np.ones(n, bool))


@dataclasses.dataclass
class SyntheticL7LogGen:
    """L7 request-log record stream (AppProtoLogs analog) with string
    fields for the l7_flow_log path."""

    num_services: int = 32
    seed: int = 0
    agent_id: int = 1

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_services
        self.ip1 = rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32)
        self.port = rng.choice(np.array([80, 443, 8080], dtype=np.uint32), n)
        self.l7 = rng.choice(
            np.array([L7Protocol.HTTP1, L7Protocol.GRPC, L7Protocol.MYSQL], dtype=np.uint32), n
        )
        self.domain = [f"svc-{i}.example.local" for i in range(n)]
        self._rng = rng

    def batch(self, batch: int, t: int):
        from ..flowlog.aggr import FlowLogBatch
        from ..flowlog.schema import L7_FLOW_LOG

        schema = L7_FLOW_LOG
        rng = self._rng
        svc = rng.integers(0, self.num_services, batch)
        ints = np.zeros((batch, len(schema.ints)), np.uint32)
        nums = np.zeros((batch, len(schema.nums)), np.float32)
        ii = schema.int_index
        ints[:, ii("flow_id_hi")] = rng.integers(0, 1 << 31, batch)
        ints[:, ii("flow_id_lo")] = rng.integers(0, 1 << 31, batch)
        ints[:, ii("agent_id")] = self.agent_id
        ints[:, ii("ip0_w3")] = rng.integers(0x0A000000, 0x0AFFFFFF, batch)
        ints[:, ii("ip1_w3")] = self.ip1[svc]
        ints[:, ii("client_port")] = rng.integers(32768, 61000, batch)
        ints[:, ii("server_port")] = self.port[svc]
        ints[:, ii("protocol")] = 6
        ints[:, ii("l7_protocol")] = self.l7[svc]
        ints[:, ii("type")] = 2  # session
        ints[:, ii("status")] = np.where(rng.random(batch) < 0.03, 4, 1)
        ints[:, ii("status_code")] = np.where(ints[:, ii("status")] == 4, 500, 200)
        ints[:, ii("start_time")] = t
        ints[:, ii("end_time")] = t
        ints[:, ii("response_duration")] = rng.integers(200, 100_000, batch)
        ints[:, ii("tap_side")] = 1
        strs = {f.name: [""] * batch for f in schema.strs}
        for r in range(batch):
            s = int(svc[r])
            strs["request_type"][r] = "GET"
            strs["request_domain"][r] = self.domain[s]
            strs["request_resource"][r] = f"/api/v1/item/{int(rng.integers(0, 50))}"
            strs["endpoint"][r] = f"/api/v1/item/{{id}}"
            strs["app_service"][r] = f"svc-{s}"
        return FlowLogBatch(schema, ints, nums, np.ones(batch, bool), strs)
