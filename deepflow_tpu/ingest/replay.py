"""Synthetic flow replay — the test/bench firehose.

Stands in for the reference's pcap-replay drivers (SURVEY §4): generates
accumulated-flow records over a fixed population of 5-tuples with
realistic field distributions, either as python dicts (oracle input) or
as ready-made SoA FlowBatches (device input). Deterministic per seed.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..datamodel.batch import FlowBatch
from ..datamodel.code import Direction, L7Protocol, SignalSource
from ..datamodel.schema import APP_METER, FLOW_METER


@dataclasses.dataclass
class SyntheticFlowGen:
    num_tuples: int = 10_000  # unique flow population (BASELINE config 1)
    seed: int = 0
    start_time: int = 1_700_000_000
    agent_id: int = 1
    # fraction of flows with both directions known / one / none
    p_both_dirs: float = 0.7
    p_one_dir: float = 0.2

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_tuples
        self.pop = {
            "ip0": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "ip1": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "port": rng.choice(
                np.array([80, 443, 3306, 6379, 8080, 9092], dtype=np.uint32), n
            ),
            "proto": rng.choice(np.array([6, 6, 6, 17], dtype=np.uint32), n),
            "epc0": rng.integers(1, 50, n, dtype=np.uint32),
            "epc1": rng.integers(1, 50, n, dtype=np.uint32),
            "pod0": rng.integers(1, 500, n, dtype=np.uint32),
            "gpid0": rng.integers(0, 1000, n, dtype=np.uint32),
            "gpid1": rng.integers(0, 1000, n, dtype=np.uint32),
        }
        u = rng.random(n)
        self.pop_dir0 = np.where(u < self.p_both_dirs + self.p_one_dir, np.uint32(Direction.CLIENT_TO_SERVER), 0)
        self.pop_dir1 = np.where(u < self.p_both_dirs, np.uint32(Direction.SERVER_TO_CLIENT), 0)
        self._rng = rng

    def _draw(self, batch: int, t: int):
        rng = self._rng
        idx = rng.integers(0, self.num_tuples, batch)
        pkts = rng.integers(1, 100, batch)
        bytes_ = pkts * rng.integers(64, 1400, batch)
        rtt = rng.integers(100, 50_000, batch)
        return idx, pkts, bytes_, rtt

    def records(self, batch: int, t: int) -> list[dict]:
        """One batch of flow dicts at timestamp t (oracle/codec input)."""
        idx, pkts, bytes_, rtt = self._draw(batch, t)
        p = self.pop
        out = []
        for i in range(batch):
            j = int(idx[i])
            out.append(
                {
                    "timestamp": t,
                    "global_thread_id": 1,
                    "agent_id": self.agent_id,
                    "signal_source": int(SignalSource.PACKET),
                    "ip0_w3": int(p["ip0"][j]),
                    "ip1_w3": int(p["ip1"][j]),
                    "l3_epc_id": int(p["epc0"][j]),
                    "l3_epc_id1": int(p["epc1"][j]),
                    "gpid0": int(p["gpid0"][j]),
                    "gpid1": int(p["gpid1"][j]),
                    "pod_id": int(p["pod0"][j]),
                    "protocol": int(p["proto"][j]),
                    "server_port": int(p["port"][j]),
                    "tap_type": 3,
                    "tap_port": 1,
                    "direction0": int(self.pop_dir0[j]),
                    "direction1": int(self.pop_dir1[j]),
                    "is_active_host0": 1,
                    "is_active_host1": 1,
                    "is_active_service": 1,
                    "meter": {
                        "packet_tx": int(pkts[i]),
                        "packet_rx": int(pkts[i] // 2),
                        "byte_tx": int(bytes_[i]),
                        "byte_rx": int(bytes_[i] // 2),
                        "l3_byte_tx": int(bytes_[i] * 9 // 10),
                        "l3_byte_rx": int(bytes_[i] * 9 // 20),
                        "new_flow": 1,
                        "closed_flow": 0,
                        "rtt_max": int(rtt[i]),
                        "rtt_sum": int(rtt[i]),
                        "rtt_count": 1,
                        "syn": 1,
                        "synack": 1,
                    },
                }
            )
        return out

    def flow_batch(self, batch: int, t: int) -> FlowBatch:
        """Columnar batch straight into the device pipeline (fast path)."""
        idx, pkts, bytes_, rtt = self._draw(batch, t)
        p = self.pop
        from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS

        tags = {f: np.zeros(batch, dtype=np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        tags["timestamp"][:] = t
        tags["global_thread_id"][:] = 1
        tags["agent_id"][:] = self.agent_id
        tags["signal_source"][:] = int(SignalSource.PACKET)
        tags["ip0_w3"] = p["ip0"][idx]
        tags["ip1_w3"] = p["ip1"][idx]
        tags["l3_epc_id"] = p["epc0"][idx]
        tags["l3_epc_id1"] = p["epc1"][idx]
        tags["gpid0"] = p["gpid0"][idx]
        tags["gpid1"] = p["gpid1"][idx]
        tags["pod_id"] = p["pod0"][idx]
        tags["protocol"] = p["proto"][idx]
        tags["server_port"] = p["port"][idx]
        tags["tap_type"][:] = 3
        tags["tap_port"][:] = 1
        tags["direction0"] = self.pop_dir0[idx]
        tags["direction1"] = self.pop_dir1[idx]
        tags["is_active_host0"][:] = 1
        tags["is_active_host1"][:] = 1
        tags["is_active_service"][:] = 1

        meters = np.zeros((batch, FLOW_METER.num_fields), dtype=np.float32)
        col = FLOW_METER.index
        meters[:, col("packet_tx")] = pkts
        meters[:, col("packet_rx")] = pkts // 2
        meters[:, col("byte_tx")] = bytes_
        meters[:, col("byte_rx")] = bytes_ // 2
        meters[:, col("l3_byte_tx")] = bytes_ * 9 // 10
        meters[:, col("l3_byte_rx")] = bytes_ * 9 // 20
        meters[:, col("new_flow")] = 1
        meters[:, col("rtt_max")] = rtt
        meters[:, col("rtt_sum")] = rtt
        meters[:, col("rtt_count")] = 1
        meters[:, col("syn")] = 1
        meters[:, col("synack")] = 1
        return FlowBatch(tags=tags, meters=meters, valid=np.ones(batch, dtype=bool))


@dataclasses.dataclass
class SyntheticAppGen:
    """L7 request-log firehose (BASELINE config 2): a service population
    with per-service endpoint sets, RED meters and log-normal-ish request
    latencies. Emits AppMeterWithFlow-shaped records/batches."""

    num_services: int = 64
    endpoints_per_service: int = 16
    seed: int = 0
    agent_id: int = 1
    p_error: float = 0.02

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        n = self.num_services
        self.svc = {
            "ip1": rng.integers(0x0A000000, 0x0AFFFFFF, n, dtype=np.uint32),
            "port": rng.choice(np.array([80, 443, 8080, 9000], dtype=np.uint32), n),
            "epc1": rng.integers(1, 50, n, dtype=np.uint32),
            "l7": rng.choice(
                np.array(
                    [L7Protocol.HTTP1, L7Protocol.GRPC, L7Protocol.MYSQL, L7Protocol.REDIS],
                    dtype=np.uint32,
                ),
                n,
            ),
            # median latency per service, µs
            "lat_med": rng.integers(500, 20_000, n).astype(np.float64),
        }
        self._rng = rng

    def _draw(self, batch: int):
        rng = self._rng
        svc = rng.integers(0, self.num_services, batch)
        ep = rng.integers(0, self.endpoints_per_service, batch)
        # endpoint_hash as the reference computes it agent-side (a hash of
        # the endpoint string); here a mixed function of (svc, ep).
        ep_hash = (
            (svc.astype(np.uint64) * np.uint64(2654435761) + ep.astype(np.uint64))
            & np.uint64(0xFFFFFFFF)
        ).astype(np.uint32)
        client_ip = rng.integers(0x0A000000, 0x0AFFFFFF, batch, dtype=np.uint32)
        lat = (self.svc["lat_med"][svc] * rng.lognormal(0.0, 0.6, batch)).astype(np.uint32)
        err = rng.random(batch) < self.p_error
        return svc, ep_hash, client_ip, lat, err

    def records(self, batch: int, t: int, draw=None) -> list[dict]:
        svc, ep_hash, client_ip, lat, err = draw if draw is not None else self._draw(batch)
        s = self.svc
        out = []
        for i in range(batch):
            j = int(svc[i])
            out.append(
                {
                    "timestamp": t,
                    "global_thread_id": 1,
                    "agent_id": self.agent_id,
                    "signal_source": int(SignalSource.PACKET),
                    "ip0_w3": int(client_ip[i]),
                    "ip1_w3": int(s["ip1"][j]),
                    "l3_epc_id": 10,
                    "l3_epc_id1": int(s["epc1"][j]),
                    "protocol": 6,
                    "server_port": int(s["port"][j]),
                    "tap_type": 3,
                    "tap_port": 1,
                    "l7_protocol": int(s["l7"][j]),
                    "endpoint_hash": int(ep_hash[i]),
                    "direction0": int(Direction.CLIENT_TO_SERVER),
                    "direction1": int(Direction.SERVER_TO_CLIENT),
                    "is_active_host0": 1,
                    "is_active_host1": 1,
                    "is_active_service": 1,
                    "meter": {
                        "request": 1,
                        "response": 1,
                        "rrt_max": int(lat[i]),
                        "rrt_sum": int(lat[i]),
                        "rrt_count": 1,
                        "server_error": int(err[i]),
                    },
                }
            )
        return out

    def app_batch(self, batch: int, t: int, draw=None) -> FlowBatch:
        """Columnar batch (meters follow APP_METER). Pass the same `draw`
        (from `_draw`) to records() and app_batch to get both views of one
        workload — the conformance test uses that to pin their equivalence.
        """
        svc, ep_hash, client_ip, lat, err = draw if draw is not None else self._draw(batch)
        s = self.svc
        from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS

        tags = {f: np.zeros(batch, dtype=np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        tags["timestamp"][:] = t
        tags["global_thread_id"][:] = 1
        tags["agent_id"][:] = self.agent_id
        tags["signal_source"][:] = int(SignalSource.PACKET)
        tags["ip0_w3"] = client_ip
        tags["ip1_w3"] = s["ip1"][svc]
        tags["l3_epc_id"][:] = 10
        tags["l3_epc_id1"] = s["epc1"][svc]
        tags["protocol"][:] = 6
        tags["server_port"] = s["port"][svc]
        tags["tap_type"][:] = 3
        tags["tap_port"][:] = 1
        tags["l7_protocol"] = s["l7"][svc]
        tags["endpoint_hash"] = ep_hash
        tags["direction0"][:] = int(Direction.CLIENT_TO_SERVER)
        tags["direction1"][:] = int(Direction.SERVER_TO_CLIENT)
        tags["is_active_host0"][:] = 1
        tags["is_active_host1"][:] = 1
        tags["is_active_service"][:] = 1

        meters = np.zeros((batch, APP_METER.num_fields), dtype=np.float32)
        col = APP_METER.index
        meters[:, col("request")] = 1
        meters[:, col("response")] = 1
        meters[:, col("rrt_max")] = lat
        meters[:, col("rrt_sum")] = lat
        meters[:, col("rrt_count")] = 1
        meters[:, col("server_error")] = err
        return FlowBatch(tags=tags, meters=meters, valid=np.ones(batch, dtype=bool))
