from .replay import SyntheticFlowGen

__all__ = ["SyntheticFlowGen"]
