from .replay import SyntheticAppGen, SyntheticFlowGen

__all__ = ["SyntheticAppGen", "SyntheticFlowGen"]
