"""Runtime-managed downsampling — the datasource manager.

The reference materializes coarser granularities inside ClickHouse:
`datasource/handle.go:316-463` creates an AggregatingMergeTree + a
materialized view per datasource (1m→1h→1d), with configurable
aggregations for summable vs. unsummable metrics and per-datasource
retention, managed at runtime over REST (:20106).

Here the store has no MV engine, so the downsampler *is* the view: each
DataSource tracks a partition watermark on its base table; `process()`
scans newly-closed partitions, re-keys rows to the coarser interval and
runs the same device sort→segment-reduce groupby as the ingest stash
(one jit call per partition batch), writing results into the derived
table. Summable columns aggregate per their schema op (SUM/MAX);
unsummable (MAX-class) columns support the reference's Avg/Max choice —
Avg divides the per-group sum by the group's row count.

String (U256) columns join the group key via host-side factorization —
they are dictionary ids in all but representation.
"""

from __future__ import annotations

import dataclasses
import threading

import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import MeterSchema
from ..ops.hashing import fingerprint64
from ..ops.segment import groupby_reduce
from ..storage.store import ColumnarStore, ColumnSpec, TableSchema
from ..utils.stats import register_countable
from .metrics_tables import METER_OF_TABLE, METRICS_DB, MetricsTableID, TABLE_NAMES

_INTERVALS = {"1m": 60, "1h": 3600, "1d": 86400}

# -- cascade-served tiers (ISSUE 9) -----------------------------------------
# The rollup cascade (aggregator/cascade.py) maintains 1m/1h tiers as
# device-side folds of closed finer windows — those granularities are
# SERVED without a datasource job. Pipelines register their tiers here
# at construction so the operator-facing datasource listings (dfctl
# datasource, REST /v1/datasources, the debug UDP "datasources" cmd)
# reflect which granularities the cascade covers vs which the
# store-side Downsampler materializes. Suffixes come from the
# querier's TIER_SUFFIX_S so a listed tier name is exactly the name
# bare-family tier routing can resolve (a non-standard interval is
# listed as "<N>s" but is NOT bare-name routable — query it by its
# explicit table name).

_FAMILIES_OF_METER = {
    "flow": ("network", "network_map"),
    "app": ("application", "application_map"),
    "usage": ("traffic_policy",),
}
_CASCADE_TIERS: dict[tuple[str, int], dict] = {}
# live registrants per tier (weakly held — the stats-registry stance:
# a torn-down pipeline's tiers leave the listing with it). A tier
# registered without an owner is permanent (operator/config-driven).
_CASCADE_OWNERS: dict[tuple[str, int], object] = {}


def register_cascade_tiers(meter_name: str, intervals, owner=None) -> None:
    """Record that a cascade now serves `intervals` (seconds) for every
    table family of `meter_name` ("flow"/"app"/"usage"). Idempotent —
    re-registering the same tier refreshes it. With `owner` (the
    serving pipeline/manager), the registration is weakly held and the
    tier drops out of the listing when the owner is collected."""
    import weakref

    from ..querier.translation import TIER_SUFFIX_S

    suffix_of_s = {s: n for n, s in TIER_SUFFIX_S.items()}
    for family in _FAMILIES_OF_METER.get(meter_name, (meter_name,)):
        for s in intervals:
            suffix = suffix_of_s.get(int(s), f"{int(s)}s")
            key = (family, int(s))
            _CASCADE_TIERS[key] = {
                "name": f"{family}_{suffix}",
                "base_table": f"{family}_1s",
                "interval": suffix,
                "served_by": "cascade",
            }
            owners = _CASCADE_OWNERS.setdefault(key, weakref.WeakSet())
            if owner is None:
                _CASCADE_OWNERS[key] = None  # permanent
            elif owners is not None:
                owners.add(owner)


def list_cascade_tiers() -> list[dict]:
    """Listing rows for the cascade-served tiers (stable order);
    weakly-owned tiers whose every registrant died are dropped."""
    out = []
    for key in sorted(_CASCADE_TIERS):
        owners = _CASCADE_OWNERS.get(key)
        if owners is not None and not len(owners):
            continue  # every registering pipeline is gone
        out.append(dict(_CASCADE_TIERS[key]))
    return out


@dataclasses.dataclass
class DataSource:
    """One derived granularity of a base metrics table."""

    base_table: str  # e.g. "network_1s"
    interval: str  # "1m" | "1h" | "1d"
    db: str = METRICS_DB
    aggr_unsummable: str = "avg"  # "avg" | "max" (handle.go summable/unsummable)
    retention_hours: int = 24 * 30
    # highest fully-processed chunk (units of chunk_s; persisted)
    watermark: int = -1

    def __post_init__(self):
        if self.interval not in _INTERVALS:
            raise ValueError(f"bad interval {self.interval}")
        if self.aggr_unsummable not in ("avg", "max"):
            raise ValueError(f"bad aggr {self.aggr_unsummable}")
        base_family = self.base_table.rsplit("_", 1)[0]
        self.name = f"{base_family}_{self.interval}"
        self.interval_s = _INTERVALS[self.interval]
        if self.name == self.base_table:
            raise ValueError(f"datasource {self.name} would write into its base table")


def _meter_schema_for(table: str) -> MeterSchema:
    family = table.replace(".", "_")
    for tid, name in TABLE_NAMES.items():
        if name.replace(".", "_") == family:
            return METER_OF_TABLE[tid]
    # derived tables (network_1h…) share the family's meter schema
    base = table.rsplit("_", 1)[0]
    for tid, name in TABLE_NAMES.items():
        if name.replace(".", "_").rsplit("_", 1)[0] == base:
            return METER_OF_TABLE[tid]
    raise KeyError(f"no meter schema for table {table}")


class Downsampler:
    """Owns the DataSource registry; `process()` advances watermarks."""

    def __init__(self, store: ColumnarStore, *, delay_s: int = 60):
        self.store = store
        self.delay_s = delay_s
        self._sources: dict[str, DataSource] = {}
        self._lock = threading.Lock()
        self._proc_lock = threading.Lock()
        self.counters = {"rows_in": 0, "rows_out": 0, "partitions": 0}
        register_countable("downsampler", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    # -- registry (the REST surface, datasource/handle.go Add/Del) ------
    def add(self, ds: DataSource) -> DataSource:
        base_schema = self.store.schema(ds.db, ds.base_table)
        _meter_schema_for(ds.base_table)  # validates the table family
        native = {n.replace(".", "_") for n in TABLE_NAMES.values()}
        if ds.name in native:
            raise ValueError(
                f"datasource {ds.name} collides with a natively-ingested table"
            )
        target = TableSchema(
            ds.name,
            tuple(ColumnSpec(c.name, c.dtype) for c in base_schema.columns),
            time_column=base_schema.time_column,
            partition_s=max(base_schema.partition_s, ds.interval_s),
            ttl_hours=ds.retention_hours,
        )
        self.store.create_table(ds.db, target)
        ds.watermark = max(ds.watermark, self._load_watermark(ds))
        with self._lock:
            if ds.name in self._sources:
                raise ValueError(f"datasource {ds.name} exists")
            self._sources[ds.name] = ds
        return ds

    def delete(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def list(self) -> list[DataSource]:
        with self._lock:
            return list(self._sources.values())

    # -- watermark persistence ------------------------------------------
    _WM_SCHEMA = TableSchema(
        "datasource_watermark",
        (ColumnSpec("time", "u4"), ColumnSpec("name", "U128"), ColumnSpec("watermark", "i8")),
        partition_s=1 << 30,
    )

    def _load_watermark(self, ds: DataSource) -> int:
        self.store.create_table(ds.db, self._WM_SCHEMA)
        rows = self.store.scan(ds.db, "datasource_watermark")
        mine = rows["watermark"][rows["name"] == ds.name]
        return int(mine.max()) if len(mine) else -1

    def _save_watermark(self, ds: DataSource) -> None:
        self.store.insert(
            ds.db,
            "datasource_watermark",
            {
                "time": np.zeros(1, np.uint32),
                "name": np.array([ds.name]),
                "watermark": np.array([ds.watermark], np.int64),
            },
        )
        # compact: saves append one-row parts forever otherwise; fold to
        # one row per datasource once the part count grows
        if self.store.part_count(ds.db, "datasource_watermark", 0) > 64:
            rows = self.store.scan(ds.db, "datasource_watermark")
            best: dict[str, int] = {}
            for nm, wm in zip(rows["name"], rows["watermark"]):
                best[str(nm)] = max(best.get(str(nm), -1), int(wm))
            self.store.drop_partition(ds.db, "datasource_watermark", 0)
            self.store.insert(
                ds.db,
                "datasource_watermark",
                {
                    "time": np.zeros(len(best), np.uint32),
                    "name": np.array(list(best)),
                    "watermark": np.array(list(best.values()), np.int64),
                },
            )

    # -- processing -----------------------------------------------------
    def process(self, now: int) -> int:
        """Roll up all chunks fully closed before `now - delay`; returns
        total rows written. Serialized: a second concurrent call returns
        0 instead of double-processing the same chunks."""
        if not self._proc_lock.acquire(blocking=False):
            return 0
        try:
            total = 0
            for ds in self._topo_sources():
                total += self._process_one(ds, now)
            return total
        finally:
            self._proc_lock.release()

    def _topo_sources(self) -> list[DataSource]:
        """Dependency order: a datasource whose base table is itself a
        registered datasource (e.g. network_1h over network_1m) must run
        after that base has rolled the chunk, or it would scan the base
        table before the finer rollup wrote it, advance its watermark,
        and never re-roll the missing rows."""
        sources = self.list()
        by_name = {ds.name: ds for ds in sources}
        ordered: list[DataSource] = []
        seen: set[str] = set()

        def visit(ds: DataSource, chain: tuple[str, ...] = ()):
            if ds.name in seen:
                return
            if ds.name in chain:  # defensive: cycles can't roll anyway
                return
            base = by_name.get(ds.base_table)
            if base is not None:
                visit(base, chain + (ds.name,))
            seen.add(ds.name)
            ordered.append(ds)

        for ds in sources:
            visit(ds)
        return ordered

    def _process_one(self, ds: DataSource, now: int) -> int:
        """Scan in chunks of max(interval, partition) so every output
        group is complete: an interval window never spans two chunks
        (chunk is a multiple of interval) and a chunk never splits a
        partition (chunk is a multiple of partition_s)."""
        base_schema = self.store.schema(ds.db, ds.base_table)
        part_s = base_schema.partition_s
        chunk_s = max(ds.interval_s, part_s)
        closed_before = (now - self.delay_s) // chunk_s  # chunks < this are closed
        chunks = sorted(
            {
                p * part_s // chunk_s
                for p in self.store.partitions(ds.db, ds.base_table)
            }
        )
        written = 0
        for c in chunks:
            if not (ds.watermark < c < closed_before):
                continue
            t0, t1 = c * chunk_s, (c + 1) * chunk_s
            cols = self.store.scan(ds.db, ds.base_table, time_range=(t0, t1))
            n = len(cols[base_schema.time_column])
            if n:
                # chunk_s equals the derived table's partition_s, so one
                # chunk is exactly one target partition: dropping it first
                # makes re-rolls after a crash idempotent
                self.store.drop_partition(ds.db, ds.name, c)
                written += self._rollup(ds, base_schema, cols, n)
            ds.watermark = c
            self._save_watermark(ds)  # per chunk: crash re-rolls ≤1 chunk
            with self._lock:
                self.counters["partitions"] += 1
                self.counters["rows_in"] += n
        with self._lock:
            self.counters["rows_out"] += written
        return written

    def _rollup(self, ds: DataSource, base_schema: TableSchema, cols, n: int) -> int:
        meter = _meter_schema_for(ds.base_table)
        meter_names = set(meter.field_names())
        time_col = base_schema.time_column

        tag_names = [
            c.name
            for c in base_schema.columns
            if c.name != time_col and c.name not in meter_names
        ]
        int_tags, str_tags, str_values = [], [], {}
        for nm in tag_names:
            arr = cols[nm]
            if arr.dtype.kind == "U":
                codes, uniq = _factorize(arr)
                int_tags.append(codes)
                str_tags.append(nm)
                str_values[nm] = uniq
            else:
                int_tags.append(arr.astype(np.uint32))

        slot = (cols[time_col].astype(np.int64) // ds.interval_s).astype(np.uint32)
        key_mat = np.stack(int_tags, axis=1)
        hi, lo = fingerprint64(key_mat, xp=np)

        meters = np.stack(
            [cols[f].astype(np.float32) for f in meter.field_names()], axis=1
        )
        sum_cols = np.nonzero(meter.sum_mask)[0].astype(np.int32)
        max_cols = np.nonzero(meter.max_mask)[0].astype(np.int32)
        if ds.aggr_unsummable == "avg":
            # unsummable → Avg: sum them, divide by group row count
            meters_in = np.concatenate([meters, np.ones((n, 1), np.float32)], axis=1)
            g = groupby_reduce(
                jnp.asarray(slot),
                jnp.asarray(hi),
                jnp.asarray(lo),
                jnp.asarray(key_mat.T),
                jnp.asarray(meters_in),
                jnp.ones(n, bool),
                np.concatenate([sum_cols, max_cols, [meters.shape[1]]]).astype(np.int32),
                np.array([], np.int32),
            )
        else:
            g = groupby_reduce(
                jnp.asarray(slot),
                jnp.asarray(hi),
                jnp.asarray(lo),
                jnp.asarray(key_mat.T),
                jnp.asarray(meters),
                jnp.ones(n, bool),
                sum_cols,
                max_cols,
            )
        m = int(np.asarray(g.num_segments))
        out_tags = np.asarray(g.tags).T[:m]
        out_meters = np.array(g.meters).T[:m]  # writable host copy
        out_slot = np.asarray(g.slot[:m]).astype(np.int64)
        if ds.aggr_unsummable == "avg" and max_cols.size:
            count = np.maximum(out_meters[:, -1], 1.0)
            out_meters[:, max_cols] = out_meters[:, max_cols] / count[:, None]
            out_meters = out_meters[:, :-1]
        elif ds.aggr_unsummable == "avg":
            out_meters = out_meters[:, :-1]

        out_cols: dict[str, np.ndarray] = {time_col: (out_slot * ds.interval_s).astype(np.uint32)}
        for j, nm in enumerate(tag_names):
            vals = out_tags[:, j]
            if nm in str_values:
                out_cols[nm] = str_values[nm][vals]
            else:
                out_cols[nm] = vals
        for j, f in enumerate(meter.field_names()):
            out_cols[f] = out_meters[:, j]
        self.store.insert(ds.db, ds.name, out_cols)
        return m


def _factorize(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    uniq, codes = np.unique(arr, return_inverse=True)
    return codes.astype(np.uint32), uniq
