"""Metrics table routing + the store-backed document writer.

The reference routes each Document to a `MetricsTableID` — network /
network_map / application / application_map × {1m, 1s} plus
traffic_policy.1m — from its Code combination and flags
(server/libs/flow-metrics/tag.go:446-520), then appends columnar blocks
via ckwriter. `DocStoreWriter` is that seat for the TPU build: it takes
`EnrichedBatch`es from the flow_metrics ingester, splits rows by table id
(meter discriminant × edge-ness × granularity), widens tag + enrichment
+ meter columns into the table schema, and feeds per-table TableWriters,
with the app_service flow_tag sidecar written alongside
(unmarshaller.go:259-270).
"""

from __future__ import annotations

import enum
import threading

import numpy as np

from ..datamodel.code import CodeId, DocumentFlag, MeterId
from ..datamodel.schema import APP_METER, FLOW_METER, TAG_SCHEMA, USAGE_METER, MeterSchema
from ..enrich.platform import ENRICH_FIELDS
from ..storage.flow_tag import AppServiceTagWriter
from ..storage.store import ColumnarStore, ColumnSpec, TableSchema, org_db
from ..storage.writer import TableWriter
from .flow_metrics import EnrichedBatch

METRICS_DB = "flow_metrics"


class MetricsTableID(enum.IntEnum):
    # tag.go:446-461 ordering.
    NETWORK_1M = 0
    NETWORK_MAP_1M = 1
    APPLICATION_1M = 2
    APPLICATION_MAP_1M = 3
    NETWORK_1S = 4
    NETWORK_MAP_1S = 5
    APPLICATION_1S = 6
    APPLICATION_MAP_1S = 7
    TRAFFIC_POLICY_1M = 8


TABLE_NAMES = {
    MetricsTableID.NETWORK_1M: "network.1m",
    MetricsTableID.NETWORK_MAP_1M: "network_map.1m",
    MetricsTableID.APPLICATION_1M: "application.1m",
    MetricsTableID.APPLICATION_MAP_1M: "application_map.1m",
    MetricsTableID.NETWORK_1S: "network.1s",
    MetricsTableID.NETWORK_MAP_1S: "network_map.1s",
    MetricsTableID.APPLICATION_1S: "application.1s",
    MetricsTableID.APPLICATION_MAP_1S: "application_map.1s",
    MetricsTableID.TRAFFIC_POLICY_1M: "traffic_policy.1m",
}

METER_OF_TABLE: dict[MetricsTableID, MeterSchema] = {
    MetricsTableID.NETWORK_1M: FLOW_METER,
    MetricsTableID.NETWORK_MAP_1M: FLOW_METER,
    MetricsTableID.APPLICATION_1M: APP_METER,
    MetricsTableID.APPLICATION_MAP_1M: APP_METER,
    MetricsTableID.NETWORK_1S: FLOW_METER,
    MetricsTableID.NETWORK_MAP_1S: FLOW_METER,
    MetricsTableID.APPLICATION_1S: APP_METER,
    MetricsTableID.APPLICATION_MAP_1S: APP_METER,
    MetricsTableID.TRAFFIC_POLICY_1M: USAGE_METER,
}

# string-dictionary side columns carried per row (codec service_ids order)
_SERVICE_COLS = ("app_service", "app_instance", "endpoint")


def table_schema(tid: MetricsTableID, partition_s: int = 3600, ttl_hours: int = 168) -> TableSchema:
    meter = METER_OF_TABLE[tid]
    cols = [ColumnSpec("time", "u4")]
    cols += [ColumnSpec(f.name, "u4") for f in TAG_SCHEMA.fields]
    cols += [ColumnSpec(f"{f}_0", "u4") for f in ENRICH_FIELDS]
    cols += [ColumnSpec(f"{f}_1", "u4") for f in ENRICH_FIELDS]
    cols += [ColumnSpec(c, "U256") for c in _SERVICE_COLS]
    cols += [ColumnSpec(f.name, "f4") for f in meter.fields]
    return TableSchema(
        TABLE_NAMES[tid].replace(".", "_"),
        tuple(cols),
        partition_s=partition_s,
        ttl_hours=ttl_hours,
    )


def route_table_ids(
    meter_id: int, code_id: np.ndarray, flags: np.ndarray
) -> np.ndarray:
    """Vectorized doc.TableID(): [N] code ids + flags → [N] MetricsTableID."""
    is_edge = (code_id >= CodeId.EDGE_IP_PORT) & (code_id <= CodeId.EDGE_MAC_IP_PORT_APP)
    is_sec = (flags & int(DocumentFlag.PER_SECOND_METRICS)) != 0
    if meter_id == MeterId.USAGE:
        return np.full(code_id.shape, int(MetricsTableID.TRAFFIC_POLICY_1M), np.int32)
    if meter_id == MeterId.APP:
        base = np.where(
            is_sec,
            np.where(is_edge, MetricsTableID.APPLICATION_MAP_1S, MetricsTableID.APPLICATION_1S),
            np.where(is_edge, MetricsTableID.APPLICATION_MAP_1M, MetricsTableID.APPLICATION_1M),
        )
    else:
        base = np.where(
            is_sec,
            np.where(is_edge, MetricsTableID.NETWORK_MAP_1S, MetricsTableID.NETWORK_1S),
            np.where(is_edge, MetricsTableID.NETWORK_MAP_1M, MetricsTableID.NETWORK_1M),
        )
    return base.astype(np.int32)


class DocStoreWriter:
    """EnrichedBatch → per-(org, table) columnar writes + flow_tag sidecar."""

    def __init__(
        self,
        store: ColumnarStore,
        *,
        partition_s: int = 3600,
        ttl_hours: int = 168,
        writer_args: dict | None = None,
        exporter_hub=None,
        live_registry=None,
    ):
        self.store = store
        self.partition_s = partition_s
        self.ttl_hours = ttl_hours
        self.writer_args = writer_args or {}
        self.exporter_hub = exporter_hub
        # ISSUE 11 satellite (ROADMAP item (a)): with a LiveRegistry
        # attached, every per-table writer registers its pending rows
        # as a live source — the server-layer network/application
        # families answer range-ending-now queries with partial rows
        # (and live-aware tier selection prefers them) instead of going
        # dark for the writer's flush interval
        self.live_registry = live_registry
        self._writers: dict[tuple[str, MetricsTableID], TableWriter] = {}
        self._app_tags = AppServiceTagWriter(store)
        self._lock = threading.Lock()
        self.counters = {"rows": 0, "batches": 0}

    def _writer(self, db: str, tid: MetricsTableID) -> TableWriter:
        with self._lock:
            w = self._writers.get((db, tid))
            if w is None:
                w = TableWriter(
                    self.store,
                    db,
                    table_schema(tid, self.partition_s, self.ttl_hours),
                    live_registry=self.live_registry,
                    **self.writer_args,
                )
                self._writers[(db, tid)] = w
            return w

    def put(self, batch: EnrichedBatch) -> None:
        d = batch.decoded
        keep = np.asarray(batch.keep, bool)
        if not keep.any():
            return
        db = org_db(METRICS_DB, batch.header.organization_id)
        tids = route_table_ids(
            d.meter_id, d.tags[:, TAG_SCHEMA.index("code_id")], d.flags
        )
        strings = d.strings
        svc = d.service_ids
        for tid_val in np.unique(tids[keep]):
            tid = MetricsTableID(int(tid_val))
            sel = keep & (tids == tid_val)
            cols: dict[str, np.ndarray] = {"time": d.timestamp[sel]}
            for i, f in enumerate(TAG_SCHEMA.fields):
                cols[f.name] = d.tags[sel, i]
            for side in (0, 1):
                enriched = batch.side0 if side == 0 else batch.side1
                for f in ENRICH_FIELDS:
                    cols[f"{f}_{side}"] = (
                        np.asarray(enriched[f])[sel]
                        if enriched is not None
                        else np.zeros(int(sel.sum()), np.uint32)
                    )
            for j, name in enumerate(_SERVICE_COLS):
                cols[name] = np.array([strings.lookup(int(x)) for x in svc[sel, j]])
            for j, f in enumerate(METER_OF_TABLE[tid].fields):
                cols[f.name] = d.meters[sel, j]
            self._writer(db, tid).put(cols)
            if self.exporter_hub is not None:
                # exporters tap enriched columns post-routing
                # (unmarshaller.go:284-303 export point)
                self.exporter_hub.export(TABLE_NAMES[tid].replace(".", "_"), cols)
            # app_service sidecar rows for docs that carry a service string
            pairs = {
                (strings.lookup(int(s)), strings.lookup(int(i)))
                for s, i in svc[sel, :2]
                if int(s) != 0
            }
            if pairs:
                self._app_tags.write(
                    int(d.timestamp[sel][0]),
                    TABLE_NAMES[tid].replace(".", "_"),
                    sorted(pairs),
                )
        with self._lock:
            self.counters["rows"] += int(keep.sum())
            self.counters["batches"] += 1

    def flush(self) -> None:
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.flush()
        self._app_tags.flush()

    def stop(self) -> None:
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.stop()
