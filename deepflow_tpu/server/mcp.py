"""MCP server — the LLM tool-calling surface over the querier.

The reference runs a streamable-HTTP Model Context Protocol server
exposing DeepFlow data to LLM agents (server/mcp/mcp.go:42-74; one
registered tool, analyzeProfileData). This build speaks the same MCP
JSON-RPC 2.0 wire protocol (initialize / tools/list / tools/call over
`POST /mcp`) and registers the full query surface:

  query_sql        DeepFlow-SQL against the columnar store
  query_promql     PromQL instant queries
  query_trace      one trace id → assembled service tree
  trace_map        service-edge aggregation over a time range
  analyze_profile  flame-tree summary for an app_service (the
                   analyzeProfileData seat)

No external MCP SDK (nothing may be installed); the protocol subset is
hand-rolled — it is three JSON-RPC methods.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PROTOCOL_VERSION = "2024-11-05"
MAX_BODY_BYTES = 4 << 20


def _tool(name, description, params, required=()):
    return {
        "name": name,
        "description": description,
        "inputSchema": {
            "type": "object",
            "properties": params,
            "required": list(required),
        },
    }


_S = {"type": "string"}
_I = {"type": "integer"}

TOOLS = [
    _tool(
        "query_sql",
        "Run a DeepFlow SQL query (SELECT ... FROM <table> ...) against "
        "the telemetry store and return rows as JSON.",
        {"sql": _S}, ("sql",),
    ),
    _tool(
        "query_promql",
        "Evaluate a PromQL instant query at a unix-seconds timestamp.",
        {"promql": _S, "time": _I}, ("promql",),
    ),
    _tool(
        "query_trace",
        "Fetch the assembled distributed-trace service tree for a trace id.",
        {"trace_id": _S, "org": _I}, ("trace_id",),
    ),
    _tool(
        "trace_map",
        "Aggregate service-to-service call edges over all traces in a "
        "time range (unix seconds).",
        {"start_time": _I, "end_time": _I, "org": _I},
    ),
    _tool(
        "list_catalog",
        "List the queryable tags and metrics of a table (name, type, "
        "unit, allowed operators) — the db_descriptions catalog.",
        {"table": _S}, ("table",),
    ),
    _tool(
        "analyze_profile",
        "Summarize continuous-profiling data for an app service: top "
        "stacks by self time from the flame tree.",
        {"app_service": _S, "start_time": _I, "end_time": _I},
        ("app_service",),
    ),
]


class MCPServer:
    """Streamable-HTTP MCP endpoint bound to a running Server's planes."""

    def __init__(self, server, *, host: str = "127.0.0.1", port: int = 0):
        self._df = server
        self.counters = {"requests": 0, "tool_calls": 0, "errors": 0}
        # ThreadingHTTPServer handles requests concurrently; dict += is a
        # non-atomic read-modify-write (same stance as receiver.py)
        self._clock = threading.Lock()
        mcp = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                if self.path.rstrip("/") not in ("", "/mcp"):
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                if n > MAX_BODY_BYTES:
                    self.send_error(413)
                    return
                try:
                    req = json.loads(self.rfile.read(n))
                except (ValueError, UnicodeDecodeError):
                    self._reply({"jsonrpc": "2.0", "id": None,
                                 "error": {"code": -32700, "message": "parse error"}})
                    return
                self._reply(mcp.handle(req))

            def _reply(self, obj):
                if obj is None:  # notification → 202, no body
                    self.send_response(202)
                    self.end_headers()
                    return
                body = json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    # -- protocol -------------------------------------------------------
    def _count(self, key: str) -> None:
        with self._clock:
            self.counters[key] += 1

    def handle(self, req: dict) -> dict | None:
        self._count("requests")
        rid = req.get("id")
        method = req.get("method", "")
        if method.startswith("notifications/"):
            return None
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "deepflow-tpu mcp server", "version": "1.0.0"},
                }
            elif method == "tools/list":
                result = {"tools": TOOLS}
            elif method == "tools/call":
                p = req.get("params", {})
                result = self._call(p.get("name", ""), p.get("arguments", {}) or {})
            elif method == "ping":
                result = {}
            else:
                return {"jsonrpc": "2.0", "id": rid,
                        "error": {"code": -32601, "message": f"unknown method {method}"}}
        except Exception as e:  # tool errors surface as MCP tool results
            self._count("errors")
            return {
                "jsonrpc": "2.0",
                "id": rid,
                "result": {
                    "content": [{"type": "text", "text": f"error: {e}"}],
                    "isError": True,
                },
            }
        return {"jsonrpc": "2.0", "id": rid, "result": result}

    # -- tools ----------------------------------------------------------
    def _call(self, name: str, args: dict) -> dict:
        self._count("tool_calls")
        df = self._df
        if name == "query_sql":
            res = df.query.execute(args["sql"])
            out = res.to_dicts()
        elif name == "query_promql":
            from ..querier.promql import query_instant

            out = query_instant(
                df.store, args["promql"], int(args.get("time") or 0) or None
            )
        elif name == "query_trace":
            out = df.query_trace(args["trace_id"], org=int(args.get("org") or 1))
            if out is None:
                out = {"error": "trace not found"}
        elif name == "trace_map":
            tr = None
            if args.get("start_time") or args.get("end_time"):
                tr = (int(args.get("start_time") or 0),
                      int(args.get("end_time") or (1 << 31)))
            out = df.trace_map(time_range=tr, org=int(args.get("org") or 1))
        elif name == "list_catalog":
            out = df.query.catalogs(args["table"])
        elif name == "analyze_profile":
            from ..querier.profile import query_flame

            tr = None
            if args.get("start_time") or args.get("end_time"):
                tr = (int(args.get("start_time") or 0),
                      int(args.get("end_time") or (1 << 31)))
            out = query_flame(
                df.store, app_service=args["app_service"], time_range=tr
            )
        else:
            raise ValueError(f"unknown tool {name}")
        return {
            "content": [{"type": "text", "text": json.dumps(out, default=str)}],
            "isError": False,
        }

    def get_counters(self):
        with self._clock:
            return dict(self.counters)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
