"""event + app_log + pcap ingesters — the three remaining ingest seats.

Reference pipelines:
  * event: resource-change / proc / K8s / alert events → `event` db
    (server/ingester/event/{decoder,dbwriter}; EventStore row model
    event/dbwriter/event.go:54-100).
  * app_log: application logs (syslog / OTel logs) → `application_log`
    db (server/ingester/app_log/dbwriter/log.go:63-100).
  * pcap: policy-triggered raw packet batches → `pcap` db
    (server/ingester/pcap/).

Wire format deviation (documented): the reference carries these as
protobuf (eventapi / app_log pb); this build's control-ish planes are
JSON messages inside the standard 19-byte framed transport — same
framing, same queue fanout, same org routing, simpler codec. The pcap
plane is binary: [flow_id u64][ts_us u64][pkt_len u32][pkt bytes].
"""

from __future__ import annotations

import json
import struct
import threading

import numpy as np

from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_messages
from ..ingest.queues import new_queue
from ..ingest.receiver import Receiver
from ..storage.store import ColumnarStore, ColumnSpec, TableSchema, org_db
from ..storage.writer import TableWriter
from ..utils.stats import register_countable

EVENT_SCHEMA = TableSchema(
    "event",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("start_time", "u8"),  # µs
        ColumnSpec("end_time", "u8"),  # µs
        ColumnSpec("signal_source", "u4"),
        ColumnSpec("event_type", "U64"),
        ColumnSpec("event_description", "U1024"),
        ColumnSpec("process_kname", "U128"),
        ColumnSpec("gprocess_id", "u4"),
        ColumnSpec("agent_id", "u4"),
        ColumnSpec("pod_id", "u4"),
        ColumnSpec("l3_epc_id", "u4"),
        ColumnSpec("resource_type", "U64"),
        ColumnSpec("resource_id", "u4"),
        ColumnSpec("resource_name", "U256"),
    ),
)

ALERT_SCHEMA = TableSchema(
    "alert_event",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("policy_id", "u4"),
        ColumnSpec("policy_name", "U256"),
        ColumnSpec("level", "u4"),  # 1 info / 2 warn / 3 error / 4 critical
        ColumnSpec("target_tags", "U1024"),
        ColumnSpec("metric_value", "f8"),
        ColumnSpec("event_description", "U1024"),
    ),
)

APP_LOG_SCHEMA = TableSchema(
    "log",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("timestamp_us", "u8"),
        ColumnSpec("agent_id", "u4"),
        ColumnSpec("app_service", "U128"),
        ColumnSpec("severity_number", "u4"),
        ColumnSpec("severity_text", "U16"),
        ColumnSpec("body", "U4096"),
        ColumnSpec("trace_id", "U64"),
        ColumnSpec("span_id", "U32"),
        ColumnSpec("attributes", "U1024"),
    ),
)

# per-packet TCP sequence records (flow_log decoder's l4_packet lane,
# decoder.go:387; log_data/l4_packet.go row model condensed). Wire:
# back-to-back 28-byte records [flow_id u64][ts_us u64][seq u32][ack u32]
# [payload_len u16][tcp_flags u8][direction u8].
L4_PACKET_SCHEMA = TableSchema(
    "l4_packet",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("agent_id", "u4"),
        ColumnSpec("flow_id_hi", "u4"),
        ColumnSpec("flow_id_lo", "u4"),
        ColumnSpec("ts_us", "u8"),
        ColumnSpec("seq", "u4"),
        ColumnSpec("ack", "u4"),
        ColumnSpec("payload_len", "u4"),
        ColumnSpec("tcp_flags", "u4"),
        ColumnSpec("direction", "u4"),
    ),
)

PCAP_SCHEMA = TableSchema(
    "pcap",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("agent_id", "u4"),
        ColumnSpec("flow_id_hi", "u4"),
        ColumnSpec("flow_id_lo", "u4"),
        ColumnSpec("ts_us", "u8"),
        ColumnSpec("packet_len", "u4"),
        ColumnSpec("packet", "U4096"),  # hex-encoded capture bytes
    ),
)

_SEVERITIES = {"trace": 1, "debug": 5, "info": 9, "warn": 13, "error": 17, "fatal": 21}


class EventIngester:
    """PROC_EVENT / K8S_EVENT / ALERT_EVENT / APPLICATION_LOG / RAW_PCAP
    frames → event / application_log / pcap databases."""

    _TYPES = (
        MessageType.PROC_EVENT,
        MessageType.K8S_EVENT,
        MessageType.ALERT_EVENT,
        MessageType.APPLICATION_LOG,
        MessageType.RAW_PCAP,
        MessageType.PACKETSEQUENCE,
        MessageType.SYSLOG,
        MessageType.AGENT_LOG,
    )

    def __init__(
        self,
        receiver: Receiver,
        store: ColumnarStore,
        *,
        queue_capacity: int = 1 << 12,
        writer_args: dict | None = None,
        max_pcap_bytes: int = 2048,
    ):
        self.store = store
        self.writer_args = writer_args or {"flush_interval_s": 0.5}
        self.max_pcap_bytes = max_pcap_bytes
        self._writers: dict[tuple[str, str], TableWriter] = {}
        self._lock = threading.Lock()
        self.counters = {"frames_in": 0, "rows_written": 0, "decode_errors": 0}
        self._running = True
        self._threads = []
        self.queues = {}
        for mt in self._TYPES:
            q = new_queue(queue_capacity, prefer_native=False)
            receiver.register_handler(mt, [q])
            self.queues[mt] = q
            t = threading.Thread(target=self._worker, args=(mt, q), daemon=True)
            t.start()
            self._threads.append(t)
        register_countable("event_ingester", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def _writer(self, db: str, schema: TableSchema) -> TableWriter:
        with self._lock:
            w = self._writers.get((db, schema.name))
            if w is None:
                w = TableWriter(self.store, db, schema, **self.writer_args)
                self._writers[(db, schema.name)] = w
            return w

    # -- workers --------------------------------------------------------
    def _worker(self, mt: MessageType, q) -> None:
        while self._running:
            frames = q.gets(64, timeout_ms=100)
            for raw in frames:
                try:
                    header = FlowHeader.parse(raw[:HEADER_LEN])
                    msgs = split_messages(raw[HEADER_LEN:])
                except ValueError:
                    with self._lock:
                        self.counters["decode_errors"] += 1
                    continue
                with self._lock:
                    self.counters["frames_in"] += 1
                for msg in msgs:
                    try:
                        self._dispatch(mt, header, msg)
                    except Exception:
                        with self._lock:
                            self.counters["decode_errors"] += 1

    def _dispatch(self, mt: MessageType, header: FlowHeader, msg: bytes) -> None:
        org = header.organization_id
        if mt in (MessageType.PROC_EVENT, MessageType.K8S_EVENT):
            self._event(org, header, msg, mt)
        elif mt == MessageType.ALERT_EVENT:
            self._alert(org, msg)
        elif mt == MessageType.APPLICATION_LOG:
            self._app_log(org, header, msg)
        elif mt == MessageType.RAW_PCAP:
            self._pcap(org, header, msg)
        elif mt == MessageType.PACKETSEQUENCE:
            self._l4_packet(org, header, msg)
        elif mt in (MessageType.SYSLOG, MessageType.AGENT_LOG):
            self._syslog(org, header, msg, mt)

    def _event(self, org: int, header: FlowHeader, msg: bytes, mt) -> None:
        ev = json.loads(msg)
        sig = 1 if mt == MessageType.PROC_EVENT else 2  # proc / k8s
        start = int(ev.get("start_time_us") or 0)
        self._writer(org_db("event", org), EVENT_SCHEMA).put(
            {
                "time": np.array([ev.get("time") or start // 1_000_000], np.uint32),
                "start_time": np.array([start], np.uint64),
                "end_time": np.array([int(ev.get("end_time_us") or start)], np.uint64),
                "signal_source": np.array([int(ev.get("signal_source") or sig)], np.uint32),
                "event_type": np.array([str(ev.get("event_type", ""))]),
                "event_description": np.array([str(ev.get("description", ""))]),
                "process_kname": np.array([str(ev.get("process_kname", ""))]),
                "gprocess_id": np.array([int(ev.get("gprocess_id") or 0)], np.uint32),
                "agent_id": np.array([header.agent_id], np.uint32),
                "pod_id": np.array([int(ev.get("pod_id") or 0)], np.uint32),
                "l3_epc_id": np.array([int(ev.get("l3_epc_id") or 0) & 0xFFFFFFFF], np.uint32),
                "resource_type": np.array([str(ev.get("resource_type", ""))]),
                "resource_id": np.array([int(ev.get("resource_id") or 0)], np.uint32),
                "resource_name": np.array([str(ev.get("resource_name", ""))]),
            }
        )
        with self._lock:
            self.counters["rows_written"] += 1

    def _alert(self, org: int, msg: bytes) -> None:
        ev = json.loads(msg)
        self._writer(org_db("event", org), ALERT_SCHEMA).put(
            {
                "time": np.array([int(ev.get("time") or 0)], np.uint32),
                "policy_id": np.array([int(ev.get("policy_id") or 0)], np.uint32),
                "policy_name": np.array([str(ev.get("policy_name", ""))]),
                "level": np.array([int(ev.get("level") or 1)], np.uint32),
                "target_tags": np.array([json.dumps(ev.get("target_tags", {}), sort_keys=True)]),
                "metric_value": np.array([float(ev.get("metric_value") or 0.0)]),
                "event_description": np.array([str(ev.get("description", ""))]),
            }
        )
        with self._lock:
            self.counters["rows_written"] += 1

    def _app_log(self, org: int, header: FlowHeader, msg: bytes) -> None:
        ev = json.loads(msg)
        ts_us = int(ev.get("timestamp_us") or 0)
        sev_text = str(ev.get("severity_text", "")).lower()
        sev = int(ev.get("severity_number") or _SEVERITIES.get(sev_text, 0))
        self._writer(org_db("application_log", org), APP_LOG_SCHEMA).put(
            {
                "time": np.array([ev.get("time") or ts_us // 1_000_000], np.uint32),
                "timestamp_us": np.array([ts_us], np.uint64),
                "agent_id": np.array([header.agent_id], np.uint32),
                "app_service": np.array([str(ev.get("app_service", ""))]),
                "severity_number": np.array([sev], np.uint32),
                "severity_text": np.array([sev_text]),
                "body": np.array([str(ev.get("body", ""))]),
                "trace_id": np.array([str(ev.get("trace_id", ""))]),
                "span_id": np.array([str(ev.get("span_id", ""))]),
                "attributes": np.array([json.dumps(ev.get("attributes", {}), sort_keys=True)]),
            }
        )
        with self._lock:
            self.counters["rows_written"] += 1

    # RFC 5424 severity (0=emergency … 7=debug) → (OTel severity_number,
    # text). The application_log column is OTel-scaled (higher = worse,
    # _SEVERITIES writes info=9/error=17), so syslog levels must be
    # translated onto that scale or filters/sorts interleave two
    # opposite-direction scales in one table.
    _SYSLOG_SEV = {
        0: (24, "emergency"), 1: (22, "alert"), 2: (21, "critical"),
        3: (17, "error"), 4: (13, "warning"), 5: (10, "notice"),
        6: (9, "info"), 7: (5, "debug"),
    }

    @staticmethod
    def _syslog_timestamp(line: str) -> tuple[int, str]:
        """Extract an event timestamp from an RFC 5424 line head
        ("1 2026-07-30T06:12:33.5Z host …"). Returns (ts_us,
        remaining_line) — (0, line) when no tz-qualified time leads the
        message; buffered 5424 lines re-shipped after an outage keep
        their event time instead of the ingest time."""
        import datetime as _dt
        import re as _re

        m = _re.match(r"1 (\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}(?:\.\d+)?)(Z|[+-]\d{2}:\d{2})?\s*", line)
        if m:
            try:
                iso = m.group(1)
                if "." in iso:
                    # py3.10 fromisoformat only takes 3/6-digit fractions
                    head, frac = iso.split(".")
                    iso = head + "." + frac[:6].ljust(6, "0")
                iso += (m.group(2) or "+00:00").replace("Z", "+00:00")
                dt = _dt.datetime.fromisoformat(iso)
                return int(dt.timestamp() * 1_000_000), line[m.end():]
            except ValueError:
                return 0, line
        # RFC 3164 heads ("Jul 30 06:12:33") carry no timezone, so the
        # instant is ambiguous by the sender's UTC offset — worse than
        # ingest time. Leave them in the body and let the caller stamp
        # ingest time; only tz-qualified 5424 timestamps are trusted.
        return 0, line

    def _syslog(self, org: int, header: FlowHeader, msg: bytes, mt) -> None:
        """SYSLOG / AGENT_LOG frames → application_log rows.

        The reference routes agent-forwarded syslog and the agent's own
        log stream to the server (droplet-message TYPE_SYSLOG /
        AGENT_LOG); here both land in the same application_log table the
        OTel/app-log lane writes, tagged by source. Payload is the raw
        text line, optionally RFC 3164/5424 "<PRI>" prefixed; ts comes
        from the frame when no structured time is present."""
        import time as _time

        line = msg.decode(errors="replace").rstrip("\n")
        syslog_sev = 6  # info default
        if line.startswith("<"):
            end = line.find(">", 1, 6)
            if end > 0 and line[1:end].isdigit():
                syslog_sev = int(line[1:end]) & 0x7
                line = line[end + 1 :]
        sev_num, sev_text = self._SYSLOG_SEV[syslog_sev]
        svc = "syslog" if mt == MessageType.SYSLOG else "deepflow-agent"
        ts_us, line = self._syslog_timestamp(line)
        if ts_us == 0:  # no structured time in the payload
            ts_us = int(_time.time() * 1_000_000)
        self._writer(org_db("application_log", org), APP_LOG_SCHEMA).put(
            {
                "time": np.array([ts_us // 1_000_000], np.uint32),
                "timestamp_us": np.array([ts_us], np.uint64),
                "agent_id": np.array([header.agent_id], np.uint32),
                "app_service": np.array([svc]),
                "severity_number": np.array([sev_num], np.uint32),
                "severity_text": np.array([sev_text]),
                "body": np.array([line]),
                "trace_id": np.array([""]),
                "span_id": np.array([""]),
                "attributes": np.array(["{}"]),
            }
        )
        with self._lock:
            self.counters["rows_written"] += 1

    def _pcap(self, org: int, header: FlowHeader, msg: bytes) -> None:
        # [flow_id u64 BE][ts_us u64 BE][pkt_len u32 BE][pkt bytes]
        if len(msg) < 20:
            raise ValueError("short pcap record")
        flow_id, ts_us, pkt_len = struct.unpack_from(">QQI", msg, 0)
        pkt = msg[20 : 20 + min(pkt_len, self.max_pcap_bytes)]
        self._writer(org_db("pcap", org), PCAP_SCHEMA).put(
            {
                "time": np.array([ts_us // 1_000_000], np.uint32),
                "agent_id": np.array([header.agent_id], np.uint32),
                "flow_id_hi": np.array([flow_id >> 32], np.uint32),
                "flow_id_lo": np.array([flow_id & 0xFFFFFFFF], np.uint32),
                "ts_us": np.array([ts_us], np.uint64),
                "packet_len": np.array([pkt_len], np.uint32),
                "packet": np.array([pkt.hex()]),
            }
        )
        with self._lock:
            self.counters["rows_written"] += 1

    # 28-byte packet-sequence record; parsed as one structured-dtype
    # frombuffer — this is the highest-volume lane (one record per TCP
    # packet), a per-record unpack loop would dominate the worker
    _L4P_DT = np.dtype([
        ("fid", ">u8"), ("ts", ">u8"), ("seq", ">u4"), ("ack", ">u4"),
        ("plen", ">u2"), ("flags", "u1"), ("dir", "u1"),
    ])

    def _l4_packet(self, org: int, header: FlowHeader, msg: bytes) -> None:
        n = len(msg) // self._L4P_DT.itemsize
        if n == 0:
            raise ValueError("short l4_packet record")
        a = np.frombuffer(msg, dtype=self._L4P_DT, count=n)
        ts = a["ts"].astype(np.uint64)
        out = {
            "time": (ts // 1_000_000).astype(np.uint32),
            "agent_id": np.full(n, header.agent_id, np.uint32),
            "flow_id_hi": (a["fid"] >> np.uint64(32)).astype(np.uint32),
            "flow_id_lo": (a["fid"] & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            "ts_us": ts,
            "seq": a["seq"].astype(np.uint32),
            "ack": a["ack"].astype(np.uint32),
            "payload_len": a["plen"].astype(np.uint32),
            "tcp_flags": a["flags"].astype(np.uint32),
            "direction": a["dir"].astype(np.uint32),
        }
        self._writer(org_db("flow_log", org), L4_PACKET_SCHEMA).put(out)
        with self._lock:
            self.counters["rows_written"] += n

    # -- lifecycle ------------------------------------------------------
    def flush(self):
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.flush()

    def stop(self, timeout: float = 5.0):
        self._running = False
        for q in self.queues.values():
            q.close()
        for t in self._threads:
            t.join(timeout=timeout)
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.stop()
