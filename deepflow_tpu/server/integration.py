"""Server-side integration ingesters: ext_metrics (Telegraf/Influx +
self-telemetry dfstats), Prometheus remote-write, profiles, and OTel
spans — the ext_metrics / prometheus / profile ingester seats plus
flow_log's OTel decoder path.

Table shapes (the reference uses CK map columns + flow_tag sidecars for
dynamic tags; our store has fixed columns, so dynamic tags pack into a
sorted `k=v,k=v` string column with flow_tag rows recording the
dictionary — queryable by exact match or via the flow_tag catalog):

  ext_metrics.metrics        (time, virtual_table, tags, field_name, value)
  deepflow_stats.stats       (same shape — agent/self counters, DFSTATS)
  prometheus.samples         (time, metric, labels, value)
  profile.in_process_profile (time, app_service, stack, value)
  flow_log.l7_flow_log       (OTel spans — same table as packet L7 logs,
                              signal_source=OTEL)
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..datamodel.code import L7Protocol, SignalSource
from ..flowlog.aggr import FlowLogBatch
from ..flowlog.schema import L7_FLOW_LOG
from ..flowlog.server import log_batch_to_columns, log_table_schema
from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_messages
from ..ingest.queues import new_queue
from ..ingest.receiver import Receiver
from ..integration.formats import (
    InfluxPoint,
    pack_tags,
    parse_folded,
    parse_influx_lines,
    parse_otlp_traces,
    parse_remote_write,
)
from ..storage.flow_tag import FlowTagWriter
from ..storage.store import ColumnarStore, ColumnSpec, TableSchema, org_db
from ..storage.writer import TableWriter
from ..utils.stats import register_countable

EXT_METRICS_SCHEMA = TableSchema(
    "metrics",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("virtual_table", "U64"),
        ColumnSpec("tags", "U512"),
        ColumnSpec("field_name", "U128"),
        ColumnSpec("value", "f8"),
    ),
)

PROM_SCHEMA = TableSchema(
    "samples",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("metric", "U128"),
        ColumnSpec("labels", "U512"),
        ColumnSpec("value", "f8"),
    ),
)

PROFILE_SCHEMA = TableSchema(
    "in_process_profile",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("app_service", "U128"),
        ColumnSpec("profile_event_type", "U32"),
        ColumnSpec("stack", "U2048"),
        ColumnSpec("value", "u8"),
    ),
)


class IntegrationIngester:
    """TELEGRAF / DFSTATS / PROMETHEUS / PROFILE / OPENTELEMETRY frames
    → storage tables, one worker per message type."""

    _TYPES = (
        MessageType.TELEGRAF,
        MessageType.DFSTATS,
        MessageType.SERVER_DFSTATS,
        MessageType.PROMETHEUS,
        MessageType.PROFILE,
        MessageType.OPENTELEMETRY,
        MessageType.OPENTELEMETRY_COMPRESSED,
        MessageType.SKYWALKING,
        MessageType.DATADOG,
    )

    def __init__(
        self,
        receiver: Receiver,
        store: ColumnarStore,
        *,
        queue_capacity: int = 1 << 13,
        writer_args: dict | None = None,
        trace_builder=None,  # tracing.TraceTreeBuilder | None
        prom_labels=None,  # default-org PrometheusLabelRegistry | None (enables SmartEncoding)
    ):
        self.store = store
        self.trace_builder = trace_builder
        self.prom_labels = prom_labels
        # id spaces are per-tenant: each org gets its own registry, loaded
        # from (and flushed into) its own prometheus db — sharing one
        # would leak label values across orgs and desync dictionaries
        self._prom_regs: dict[int, object] = {}
        self.writer_args = writer_args or {"flush_interval_s": 0.5}
        self._writers: dict[tuple[str, str], TableWriter] = {}
        self._flow_tags: dict[str, FlowTagWriter] = {}
        self._lock = threading.Lock()
        self.counters = {
            "frames_in": 0,
            "rows_written": 0,
            "decode_errors": 0,
        }
        self._running = True
        self._threads = []
        self.queues = {}
        for mt in self._TYPES:
            q = new_queue(queue_capacity, prefer_native=False)
            receiver.register_handler(mt, [q])
            self.queues[mt] = q
            t = threading.Thread(target=self._worker, args=(mt, q), daemon=True)
            t.start()
            self._threads.append(t)
        register_countable("integration_ingester", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def _writer(self, db: str, schema: TableSchema) -> TableWriter:
        with self._lock:
            w = self._writers.get((db, schema.name))
            if w is None:
                w = TableWriter(self.store, db, schema, **self.writer_args)
                self._writers[(db, schema.name)] = w
            return w

    def _flow_tag(self, db: str) -> FlowTagWriter:
        with self._lock:
            ft = self._flow_tags.get(db)
            if ft is None:
                ft = self._flow_tags[db] = FlowTagWriter(self.store, f"{db}_flow_tag")
            return ft

    # -- workers --------------------------------------------------------
    def _worker(self, mt: MessageType, q) -> None:
        while self._running:
            frames = q.gets(64, timeout_ms=100)
            for raw in frames:
                try:
                    header = FlowHeader.parse(raw[:HEADER_LEN])
                    msgs = split_messages(raw[HEADER_LEN:])
                except ValueError:
                    with self._lock:
                        self.counters["decode_errors"] += 1
                    continue
                with self._lock:
                    self.counters["frames_in"] += 1
                for msg in msgs:
                    self._dispatch(mt, header, msg)

    def _dispatch(self, mt: MessageType, header: FlowHeader, msg: bytes) -> None:
        org = header.organization_id
        try:
            if mt == MessageType.TELEGRAF:
                self._influx(org, "ext_metrics", msg)
            elif mt in (MessageType.DFSTATS, MessageType.SERVER_DFSTATS):
                self._influx(org, "deepflow_stats", msg)
            elif mt == MessageType.PROMETHEUS:
                self._prometheus(org, msg)
            elif mt == MessageType.PROFILE:
                self._profile(org, msg)
            elif mt == MessageType.OPENTELEMETRY:
                self._otel(org, header, msg)
            elif mt == MessageType.SKYWALKING:
                self._skywalking(org, header, msg)
            elif mt == MessageType.DATADOG:
                self._datadog(org, header, msg)
            elif mt == MessageType.OPENTELEMETRY_COMPRESSED:
                # agent-side zlib over the OTLP body (decoder.go:244
                # decodeOTelCompressed); bounded via the shared zip-bomb
                # guard in framing.decompress_body
                from ..ingest.framing import ENCODER_DEFLATE, decompress_body

                self._otel(org, header, decompress_body(msg, ENCODER_DEFLATE))
        except Exception:
            with self._lock:
                self.counters["decode_errors"] += 1

    def _influx(self, org: int, base_db: str, msg: bytes) -> None:
        points, errors = parse_influx_lines(msg.decode(errors="replace"))
        with self._lock:
            self.counters["decode_errors"] += errors
        if not points:
            return
        db = org_db(base_db, org)
        rows = {"time": [], "virtual_table": [], "tags": [], "field_name": [], "value": []}
        # timestamp-less lines get receipt time (line-protocol spec: the
        # server assigns its clock) — epoch 0 would hide them from every
        # time-ranged scan
        now_fallback = int(time.time())
        tag_catalog: dict[str, dict[str, dict[str, int]]] = {}
        for p in points:
            sec = p.timestamp_ns // 1_000_000_000 if p.timestamp_ns else now_fallback
            packed = pack_tags(p.tags)
            for fname, val in p.fields.items():
                rows["time"].append(sec)
                rows["virtual_table"].append(p.measurement)
                rows["tags"].append(packed)
                rows["field_name"].append(fname)
                rows["value"].append(val)
            cat = tag_catalog.setdefault(p.measurement, {})
            for k, v in p.tags.items():
                cat.setdefault(k, {})[v] = cat.get(k, {}).get(v, 0) + 1
        schema = EXT_METRICS_SCHEMA if base_db == "ext_metrics" else TableSchema(
            "stats", EXT_METRICS_SCHEMA.columns
        )
        n = len(rows["time"])
        self._writer(db, schema).put(
            {
                "time": np.asarray(rows["time"], np.uint32),
                "virtual_table": np.asarray(rows["virtual_table"]),
                "tags": np.asarray(rows["tags"]),
                "field_name": np.asarray(rows["field_name"]),
                "value": np.asarray(rows["value"], np.float64),
            }
        )
        ft = self._flow_tag(db)
        for table, fields in tag_catalog.items():
            ft.write(int(rows["time"][0]), table, fields)
        with self._lock:
            self.counters["rows_written"] += n

    def _prom_reg(self, org: int):
        from ..storage.store import DEFAULT_ORG_ID

        if org in (0, DEFAULT_ORG_ID):
            return self.prom_labels
        reg = self._prom_regs.get(org)
        if reg is None:
            from ..controller.prom_labels import PrometheusLabelRegistry

            reg = self._prom_regs[org] = PrometheusLabelRegistry.load(
                self.store, db=org_db("prometheus", org)
            )
        return reg

    def _prometheus(self, org: int, msg: bytes) -> None:
        series = parse_remote_write(msg)
        if not series:
            return
        rows = {"time": [], "metric": [], "labels": [], "value": []}
        for s in series:
            name = s.labels.get("__name__", "")
            packed = pack_tags({k: v for k, v in s.labels.items() if k != "__name__"})
            for ts_ms, val in s.samples:
                rows["time"].append(ts_ms // 1000)
                rows["metric"].append(name)
                rows["labels"].append(packed)
                rows["value"].append(val)
        self._writer(org_db("prometheus", org), PROM_SCHEMA).put(
            {
                "time": np.asarray(rows["time"], np.uint32),
                "metric": np.asarray(rows["metric"]),
                "labels": np.asarray(rows["labels"]),
                "value": np.asarray(rows["value"], np.float64),
            }
        )
        if self.prom_labels is not None:
            # SmartEncoding lane (grpc_label_ids.go seat): id-encoded
            # samples + dictionary sidecars, alongside the string table
            from ..controller.prom_labels import SAMPLES_ENC

            reg = self._prom_reg(org)
            enc_rows = {"time": [], "metric_id": [], "label_ids": [], "value": []}
            for s in series:
                mid, packed_ids = reg.encode(s.labels)
                for ts_ms, val in s.samples:
                    enc_rows["time"].append(ts_ms // 1000)
                    enc_rows["metric_id"].append(mid)
                    enc_rows["label_ids"].append(packed_ids)
                    enc_rows["value"].append(val)
            self._writer(org_db("prometheus", org), SAMPLES_ENC).put(
                {
                    "time": np.asarray(enc_rows["time"], np.uint32),
                    "metric_id": np.asarray(enc_rows["metric_id"], np.uint32),
                    "label_ids": np.asarray(enc_rows["label_ids"]),
                    "value": np.asarray(enc_rows["value"], np.float64),
                }
            )
            reg.flush_dicts(
                self.store, db=org_db("prometheus", org),
                now=int(rows["time"][0]) if rows["time"] else 0,
            )
        with self._lock:
            self.counters["rows_written"] += len(rows["time"])

    def _profile(self, org: int, msg: bytes) -> None:
        # msg: "service\x00event_type\x00timestamp_s\n" header + folded body
        head, _, body = msg.decode(errors="replace").partition("\n")
        service, _, rest = head.partition("\x00")
        event_type, _, ts_s = rest.partition("\x00")
        samples, errors = parse_folded(body)
        with self._lock:
            self.counters["decode_errors"] += errors
        if not samples:
            return
        sec = int(ts_s or 0)
        self._writer(org_db("profile", org), PROFILE_SCHEMA).put(
            {
                "time": np.full(len(samples), sec, np.uint32),
                "app_service": np.full(len(samples), service),
                "profile_event_type": np.full(len(samples), event_type or "cpu"),
                "stack": np.asarray([s.stack for s in samples]),
                "value": np.asarray([s.value for s in samples], np.uint64),
            }
        )
        with self._lock:
            self.counters["rows_written"] += len(samples)

    def _otel(self, org: int, header: FlowHeader, msg: bytes) -> None:
        self._spans(org, header, parse_otlp_traces(msg))

    def _skywalking(self, org: int, header: FlowHeader, msg: bytes) -> None:
        from ..integration.trace_imports import parse_skywalking_segment

        self._spans(org, header, parse_skywalking_segment(msg))

    def _datadog(self, org: int, header: FlowHeader, msg: bytes) -> None:
        from ..integration.trace_imports import parse_datadog_traces

        self._spans(org, header, parse_datadog_traces(msg))

    def _spans(self, org: int, header: FlowHeader, spans) -> None:
        """OtelSpan list → l7_flow_log rows + trace-tree observation —
        one lane shared by the OTLP / SkyWalking / Datadog imports
        (decoder.go:244/:289/:338 all converge on L7FlowLog the same way)."""
        if not spans:
            return
        s = L7_FLOW_LOG
        n = len(spans)
        ints = np.zeros((n, len(s.ints)), np.uint32)
        nums = np.zeros((n, len(s.nums)), np.float32)
        strs = {f.name: [""] * n for f in s.strs}
        ii = s.int_index
        for r, sp in enumerate(spans):
            ints[r, ii("agent_id")] = header.agent_id
            ints[r, ii("signal_source")] = int(SignalSource.OTEL)
            ints[r, ii("l7_protocol")] = int(
                L7Protocol.HTTP1 if sp.attributes.get("http.method") else L7Protocol.OTHER
            )
            ints[r, ii("type")] = 2
            ints[r, ii("tap_side")] = 49 if sp.kind == 3 else 50  # c-app / s-app
            ints[r, ii("start_time")] = sp.start_us // 1_000_000
            ints[r, ii("end_time")] = sp.end_us // 1_000_000
            ints[r, ii("response_duration")] = max(0, sp.end_us - sp.start_us)
            ints[r, ii("status")] = 4 if sp.status_code == 2 else 1
            code = sp.attributes.get("http.status_code", "")
            ints[r, ii("status_code")] = int(code) if code.isdigit() else 0
            strs["app_service"][r] = sp.service
            strs["endpoint"][r] = sp.name
            strs["request_type"][r] = sp.attributes.get("http.method", "")
            strs["request_resource"][r] = sp.attributes.get("http.target", sp.name)
            strs["request_domain"][r] = sp.attributes.get("http.host", "")
            strs["trace_id"][r] = sp.trace_id
            strs["span_id"][r] = sp.span_id
            strs["parent_span_id"][r] = sp.parent_span_id
            strs["x_request_id"][r] = sp.attributes.get(
                "http.request.header.x_request_id",
                sp.attributes.get("x_request_id", ""),
            )
        batch = FlowLogBatch(s, ints, nums, np.ones(n, bool), strs)
        db = org_db("flow_log", org)
        w = self._writer(db, log_table_schema(s))
        w.put(log_batch_to_columns(batch))
        with self._lock:
            self.counters["rows_written"] += n
        if self.trace_builder is not None:
            from ..tracing.tree import SpanRow

            self.trace_builder.observe(
                [
                    SpanRow(
                        trace_id=sp.trace_id,
                        span_id=sp.span_id,
                        parent_span_id=sp.parent_span_id,
                        app_service=sp.service,
                        tap_side=int(ints[r, ii("tap_side")]),
                        start_us=sp.start_us,
                        end_us=sp.end_us,
                        response_duration_us=max(0, sp.end_us - sp.start_us),
                        server_error=sp.status_code == 2,
                    )
                    for r, sp in enumerate(spans)
                ],
                org=org,
            )

    # -- lifecycle ------------------------------------------------------
    def flush(self):
        with self._lock:
            writers = list(self._writers.values())
            fts = list(self._flow_tags.values())
        for w in writers:
            w.flush()
        for ft in fts:
            ft.flush()

    def stop(self, timeout: float = 5.0):
        self._running = False
        for q in self.queues.values():
            q.close()
        for t in self._threads:
            t.join(timeout=timeout)
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.stop()
