"""Universal export framework — the server/ingester/exporters seat.

The reference exports enriched telemetry to external sinks (Kafka /
OTLP / Prometheus remote-write) with per-exporter data-source filters
and universal-tag re-translation to strings (exporters/config,
universal_tag/). Same composition here: `Exporter` strategies receive
(table_name, columns) batches tapped off the ingest write path after
enrichment; the tag translator renders integer ids back to names so
sinks get self-describing records.

Sinks: JSONL file (the Kafka-topic stand-in — no broker in-image),
Prometheus remote-write POST (re-using our own encoder), and a callback
for embedding. Filters: table prefixes ("network", "application_map").
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np

from ..integration.formats import PromSeries, encode_remote_write
from ..utils.stats import register_countable

# tag columns re-translated to names when a translator is present
_TRANSLATED = ("pod_id_0", "pod_id_1", "auto_service_id_0", "auto_service_id_1", "region_id_0")


class Exporter:
    """Base: filter + counters; subclasses implement _send(rows)."""

    def __init__(self, *, data_sources: tuple[str, ...] = (), translator=None):
        self.data_sources = data_sources
        self.translator = translator
        self.counters = {"batches": 0, "rows": 0, "errors": 0, "filtered": 0}
        self._lock = threading.Lock()
        register_countable("exporter", self, sink=type(self).__name__)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def accepts(self, table: str) -> bool:
        return not self.data_sources or any(
            table.startswith(p) for p in self.data_sources
        )

    def export(self, table: str, cols: dict[str, np.ndarray]) -> None:
        if not self.accepts(table):
            with self._lock:
                self.counters["filtered"] += 1
            return
        rows = self._to_rows(table, cols)
        try:
            self._send(table, rows)
            with self._lock:
                self.counters["batches"] += 1
                self.counters["rows"] += len(rows)
        except Exception:
            with self._lock:
                self.counters["errors"] += 1

    def _to_rows(self, table: str, cols: dict[str, np.ndarray]) -> list[dict]:
        names = {}
        if self.translator is not None:
            for c in _TRANSLATED:
                if c in cols:
                    names[c.replace("_id", "_name").replace("pod_id", "pod_name")] = (
                        self.translator.translate(table, c, np.asarray(cols[c]))
                    )
        n = len(next(iter(cols.values()))) if cols else 0
        out = []
        for i in range(n):
            row = {k: _py(v[i]) for k, v in cols.items()}
            for k, v in names.items():
                row[k] = str(v[i])
            out.append(row)
        return out

    def _send(self, table: str, rows: list[dict]) -> None:
        raise NotImplementedError


def _py(v):
    return v.item() if hasattr(v, "item") else v


class FileExporter(Exporter):
    """JSONL sink — the Kafka-topic stand-in (one file per table)."""

    def __init__(self, directory: str | Path, **kw):
        super().__init__(**kw)
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def _send(self, table: str, rows: list[dict]) -> None:
        with open(self.directory / f"{table}.jsonl", "a") as f:
            for r in rows:
                f.write(json.dumps({"table": table, **r}) + "\n")


class CallbackExporter(Exporter):
    def __init__(self, fn, **kw):
        super().__init__(**kw)
        self.fn = fn

    def _send(self, table: str, rows: list[dict]) -> None:
        self.fn(table, rows)


class RemoteWriteExporter(Exporter):
    """Meter columns → Prometheus remote-write POSTs: one series per
    (metric column, table), labels from the translated tag columns."""

    def __init__(self, url: str, *, metrics: tuple[str, ...] = (), **kw):
        super().__init__(**kw)
        self.url = url
        self.metrics = metrics

    def _send(self, table: str, rows: list[dict]) -> None:
        series = []
        for row in rows:
            ts_ms = int(row.get("time", 0)) * 1000
            labels = {
                k: str(v)
                for k, v in row.items()
                if isinstance(v, str) and v and k != "time"
            }
            for m in self.metrics:
                if m in row:
                    series.append(
                        PromSeries(
                            {"__name__": f"deepflow_{table}_{m}", **labels},
                            [(ts_ms, float(row[m]))],
                        )
                    )
        if not series:
            return
        req = urllib.request.Request(
            self.url,
            data=encode_remote_write(series),
            headers={"Content-Type": "application/x-protobuf"},
        )
        urllib.request.urlopen(req, timeout=5).read()


# Tables whose rows are spans/logs, not metric documents — they must
# only ever reach the OTLP traces lane.
_TRACE_TABLES = ("l7_flow_log",)


class OtlpExporter(Exporter):
    """OTLP/HTTP protobuf sink (exporters/otlp_exporter/otlp_exporter.go).

    l7_flow_log rows → OTLP trace spans (ExportTraceServiceRequest) and
    metric-table rows → OTLP Sum/Gauge metrics, POSTed with
    Content-Type application/x-protobuf. Pointing `traces_url` at our
    own IntegrationCollector's /v1/traces closes the loop: exported
    spans re-ingest through the OTel lane (the round-trip test pins
    this)."""


    def __init__(self, traces_url: str = "", metrics_url: str = "", *,
                 metrics: tuple[str, ...] = (), **kw):
        kw.setdefault("data_sources", ("l7_flow_log", "network", "application"))
        super().__init__(**kw)
        self.traces_url = traces_url
        self.metrics_url = metrics_url
        self.metrics = metrics

    def _send(self, table: str, rows: list[dict]) -> None:
        from ..integration.formats import (
            OtelSpan,
            OtlpMetric,
            OtlpMetricPoint,
            encode_otlp_metrics,
            encode_otlp_traces,
        )

        if table in _TRACE_TABLES:
            # trace rows NEVER fall through to the metrics branch: with
            # metrics_url set but traces_url empty, l7_flow_log rows
            # used to be exported as bogus deepflow_l7_flow_log_*
            # metrics (ADVICE.md #4) — now they are skipped AND counted
            # (deepflow_stats `exporter.trace_rows_skipped`) until a
            # traces_url is configured, so the drop is observable.
            if self.traces_url:
                spans = [self._row_to_span(r) for r in rows]
                self._post(self.traces_url, encode_otlp_traces(spans))
            else:
                with self._lock:
                    self.counters["trace_rows_skipped"] = (
                        self.counters.get("trace_rows_skipped", 0) + len(rows)
                    )
        elif self.metrics_url and self.metrics:
            points: dict[str, list[OtlpMetricPoint]] = {}
            for r in rows:
                t_ns = int(r.get("time", 0)) * 1_000_000_000
                attrs = {k: str(v) for k, v in r.items()
                         if isinstance(v, str) and v and k != "time"}
                for m in self.metrics:
                    if m in r:
                        points.setdefault(m, []).append(
                            OtlpMetricPoint(attrs, t_ns, float(r[m]))
                        )
            from ..querier.metrics import metric_type

            # counters export as monotonic cumulative Sums, everything
            # else (delays, ratios, gauges, untyped) as Gauges
            ms = [
                OtlpMetric("deepflow", f"deepflow_{table}_{m}", "",
                           metric_type(table, m) == "counter", pts)
                for m, pts in points.items()
            ]
            if ms:
                self._post(self.metrics_url, encode_otlp_metrics(ms))

    @staticmethod
    def _row_to_span(r: dict):
        from ..datamodel.code import L7Protocol
        from ..integration.formats import OtelSpan

        tap_side = int(r.get("tap_side", 0) or 0)
        try:
            l7_name = L7Protocol(int(r.get("l7_protocol", 0) or 0)).name
        except ValueError:
            l7_name = str(r.get("l7_protocol", ""))
        attrs = {"df.capture.tap_side": str(tap_side),
                 "df.l7_protocol": l7_name}
        for col, attr in (
            ("request_type", "df.request_type"),
            ("request_domain", "df.request_domain"),
            ("request_resource", "df.request_resource"),
            ("endpoint", "df.endpoint"),
            ("x_request_id", "df.x_request_id"),
            ("response_exception", "df.response_exception"),
        ):
            v = r.get(col)
            if v:
                attrs[attr] = str(v)
        for col in ("status_code", "server_port", "pod_id_0", "pod_id_1",
                    "auto_service_id_0", "auto_service_id_1"):
            v = r.get(col)
            if v:
                attrs[f"df.{col}"] = str(v)
        start_us = int(r.get("start_time", 0) or 0) * 1_000_000
        end_us = start_us + int(r.get("response_duration", 0) or 0)
        status = int(r.get("status", 0) or 0)  # 1 ok / 3 client / 4 server err
        return OtelSpan(
            service=str(r.get("app_service") or "deepflow"),
            name=str(r.get("endpoint") or r.get("request_resource") or l7_name),
            trace_id=str(r.get("trace_id", "") or ""),
            span_id=str(r.get("span_id", "") or ""),
            parent_span_id=str(r.get("parent_span_id", "") or ""),
            # tap_side 1 = client-side capture → CLIENT(3), else SERVER(2)
            kind=3 if tap_side == 1 else 2,
            start_us=start_us,
            end_us=end_us,
            status_code=2 if status in (3, 4) else 1,
            attributes=attrs,
        )

    @staticmethod
    def _post(url: str, body: bytes) -> None:
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/x-protobuf"}
        )
        urllib.request.urlopen(req, timeout=5).read()


class ExporterHub:
    """Fan one write-path tap into all configured exporters —
    asynchronously. The ingest hot path must never block on a sink (the
    reference feeds exporters through queues, unmarshaller.go:284); a
    slow/unreachable sink sheds batches here instead of stalling writes.
    """

    def __init__(self, exporters: list[Exporter], *, queue_size: int = 256):
        self.exporters = exporters
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self.counters = {"dropped_full": 0}
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        register_countable("exporter_hub", self)

    def get_counters(self):
        return dict(self.counters)

    def export(self, table: str, cols: dict[str, np.ndarray]) -> None:
        try:
            self._q.put_nowait((table, cols))
        except queue.Full:
            self.counters["dropped_full"] += 1

    def _run(self) -> None:
        while self._running:
            try:
                table, cols = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            for e in self.exporters:
                e.export(table, cols)

    def flush(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def stop(self) -> None:
        self.flush()
        self._running = False
        self._thread.join(timeout=2)
