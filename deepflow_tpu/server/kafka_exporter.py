"""Kafka exporter — real wire-protocol Produce requests, no client lib.

The reference's kafka_exporter (server/ingester/exporters/
kafka_exporter/kafka_exporter.go) ships enriched rows to a broker via
sarama. No broker or client library exists in this image, so this
module implements the subset of the Kafka protocol a stock broker
accepts, byte-for-byte:

  * RecordBatch v2 (magic 2): zigzag-varint records, CRC32C over the
    attributes..records span (known-answer-tested Castagnoli, table
    driven over NumPy for whole-batch speed);
  * Produce request v3 (header: api_key 0, api_version 3) with
    configurable acks; acks=0 is fire-and-forget, acks=1 reads the
    response frame;
  * `KafkaExporter(Exporter)`: rows → JSON values keyed by table name,
    one batch per export() call, reconnect-on-error.

The agent-side L7 Kafka PARSER in this repo reads the same wire format
— the round-trip test feeds the exporter's bytes to a fake broker and
cross-checks framing with an independent decode.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

from .exporters import Exporter

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — Kafka RecordBatch checksums. Table-driven over
# plain Python ints (measured ~7x faster than a NumPy-scalar loop;
# iterating a bytes object yields ints directly).

_CRC32C_POLY = 0x82F63B78


def _make_table() -> list[int]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    table = _TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# wire primitives


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _varint(v: int) -> bytes:
    v = _zigzag(v) & ((1 << 64) - 1)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _str(s: str | None) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes32(b: bytes) -> bytes:
    return struct.pack(">i", len(b)) + b


def encode_record_batch(
    records: list[tuple[bytes | None, bytes]], timestamp_ms: int
) -> bytes:
    """[(key, value)] → one RecordBatch v2 (kafka protocol magic 2)."""
    recs = bytearray()
    for i, (key, value) in enumerate(records):
        body = bytearray()
        body += b"\x00"  # attributes
        body += _varint(0)  # timestampDelta
        body += _varint(i)  # offsetDelta
        if key is None:
            body += _varint(-1)
        else:
            body += _varint(len(key)) + key
        body += _varint(len(value)) + value
        body += _varint(0)  # headers
        recs += _varint(len(body)) + body

    n = len(records)
    after_crc = bytearray()
    after_crc += struct.pack(">h", 0)  # attributes (no compression)
    after_crc += struct.pack(">i", n - 1)  # lastOffsetDelta
    after_crc += struct.pack(">q", timestamp_ms)  # firstTimestamp
    after_crc += struct.pack(">q", timestamp_ms)  # maxTimestamp
    after_crc += struct.pack(">q", -1)  # producerId
    after_crc += struct.pack(">h", -1)  # producerEpoch
    after_crc += struct.pack(">i", -1)  # baseSequence
    after_crc += struct.pack(">i", n) + recs

    batch = bytearray()
    batch += struct.pack(">q", 0)  # baseOffset
    body = bytearray()
    body += struct.pack(">i", 0)  # partitionLeaderEpoch
    body += b"\x02"  # magic
    body += struct.pack(">I", crc32c(bytes(after_crc)))
    body += after_crc
    batch += struct.pack(">i", len(body)) + body
    return bytes(batch)


def encode_produce_request(
    topic: str,
    records: list[tuple[bytes | None, bytes]],
    *,
    correlation_id: int = 1,
    client_id: str = "deepflow-tpu",
    acks: int = 0,
    timeout_ms: int = 5000,
    partition: int = 0,
    timestamp_ms: int = 0,
) -> bytes:
    """One Produce v3 request frame (length-prefixed)."""
    batch = encode_record_batch(records, timestamp_ms)
    req = bytearray()
    req += struct.pack(">hhi", 0, 3, correlation_id)  # api, ver, corr
    req += _str(client_id)
    req += _str(None)  # transactional_id
    req += struct.pack(">hi", acks, timeout_ms)
    req += struct.pack(">i", 1)  # one topic
    req += _str(topic)
    req += struct.pack(">i", 1)  # one partition
    req += struct.pack(">i", partition)
    req += _bytes32(batch)
    return struct.pack(">i", len(req)) + bytes(req)


def _produce_response_error(resp: bytes, want_corr: int) -> int:
    """First nonzero per-partition error_code of a Produce v3 response
    (0 when every partition succeeded). A correlation-id mismatch is
    reported as -1 — the stream is out of sync."""
    try:
        corr, ntopics = struct.unpack(">ii", resp[:8])
        if corr != want_corr:
            return -1
        off = 8
        for _ in range(ntopics):
            tl, = struct.unpack(">h", resp[off:off + 2])
            off += 2 + tl
            nparts, = struct.unpack(">i", resp[off:off + 4])
            off += 4
            for _ in range(nparts):
                _, err = struct.unpack(">ih", resp[off:off + 6])
                if err:
                    return err
                off += 6 + 8 + 8  # index+err, base_offset, log_append_time
        return 0
    except struct.error:
        return -1


class KafkaExporter(Exporter):
    """Rows → JSON values on a per-table topic over the real protocol.

    acks=0 (the reference's RequiredAcks default seat) never waits;
    acks=1 reads one response frame per request. Connection errors
    surface as Exporter error counts and force a reconnect."""

    def __init__(self, host: str, port: int = 9092, *,
                 topic_prefix: str = "deepflow.", acks: int = 0, **kw):
        super().__init__(**kw)
        self.addr = (host, port)
        self.topic_prefix = topic_prefix
        self.acks = acks
        self._sock: socket.socket | None = None
        self._corr = 0
        self._slock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=5)
        return self._sock

    def _send(self, table: str, rows: list[dict]) -> None:
        records = [
            (table.encode(), json.dumps(r, default=str).encode())
            for r in rows
        ]
        if not records:
            return
        ts_ms = int(rows[0].get("time", 0)) * 1000
        with self._slock:
            self._corr += 1
            frame = encode_produce_request(
                self.topic_prefix + table, records,
                correlation_id=self._corr, acks=self.acks,
                timestamp_ms=ts_ms,
            )
            try:
                s = self._conn()
                s.sendall(frame)
                if self.acks:
                    size = struct.unpack(">i", self._read_n(s, 4))[0]
                    resp = self._read_n(s, size)
                    err = _produce_response_error(resp, self._corr)
                    if err:
                        raise OSError(f"broker produce error_code {err}")
            except OSError:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    finally:
                        self._sock = None
                raise

    @staticmethod
    def _read_n(s: socket.socket, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = s.recv(n - len(out))
            if not chunk:
                raise OSError("broker closed")
            out += chunk
        return out

    def close(self) -> None:
        with self._slock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
