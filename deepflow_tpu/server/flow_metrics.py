"""flow_metrics ingester — receiver to storage, the server hot path.

The TPU re-composition of `server/ingester/flow_metrics/flow_metrics.go:50`
+ `unmarshaller/unmarshaller.go:220`: the receiver fans METRICS frames
into N overwrite queues; each unmarshaller worker drains its queue in
batches, decodes pb Documents columnar (native C++ decoder when built,
Python twin otherwise), runs the whole batch through the device
enrichment kernel (enrich/platform.py — the DocumentExpand analog), and
hands enriched column batches to the writer.

Like the reference, no re-aggregation happens here — agents pre-aggregate
and docs are written as-is (flow_metrics.go design); server-side rollups
are the downsampler's job. `disable_second_write` mirrors
unmarshaller.go:246.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from ..datamodel.code import DocumentFlag
from ..datamodel.schema import TAG_SCHEMA
from ..enrich.platform import PlatformState, enrich_docs
from ..ingest.codec import DecodedBatch, DocumentDecoder
from ..ingest.framing import (
    HEADER_LEN,
    FlowHeader,
    MessageType,
    split_message_spans,
)
from ..ingest.queues import new_queue
from ..ingest.receiver import Receiver
from .. import native


@dataclasses.dataclass
class EnrichedBatch:
    """What the writer receives: decoded docs + device enrichment."""

    header: FlowHeader
    decoded: DecodedBatch
    side0: dict[str, np.ndarray] | None
    side1: dict[str, np.ndarray] | None
    keep: np.ndarray  # [N] bool (False = other-region drop)


class FlowMetricsIngester:
    """METRICS pipeline: queues → decode → enrich → writer.put(batch)."""

    def __init__(
        self,
        receiver: Receiver,
        writer,
        *,
        platform_state: PlatformState | None = None,
        n_workers: int = 1,
        queue_capacity: int = 1 << 14,
        batch_size: int = 256,
        disable_second_write: bool = False,
        prefer_native: bool = True,
        enrich_chunk: int = 8192,
    ):
        self.writer = writer
        self.platform_state = platform_state
        self.batch_size = batch_size
        self.enrich_chunk = enrich_chunk
        self.disable_second_write = disable_second_write
        self._use_native = prefer_native and native.native_available()
        self.queues = [new_queue(queue_capacity, prefer_native=prefer_native) for _ in range(n_workers)]
        receiver.register_handler(MessageType.METRICS, self.queues)
        self.counters = {
            "frames_in": 0,
            "docs_in": 0,
            "docs_written": 0,
            "decode_errors": 0,
            "drop_other_region": 0,
            "drop_second_write": 0,
        }
        self._lock = threading.Lock()
        self._running = True
        self._threads = [
            threading.Thread(target=self._worker, args=(q,), daemon=True) for q in self.queues
        ]
        for t in self._threads:
            t.start()
        from ..utils.stats import register_countable

        register_countable("flow_metrics_ingester", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def stop(self, timeout: float = 5.0) -> None:
        self._running = False
        for q in self.queues:
            q.close()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- worker ---------------------------------------------------------
    def _worker(self, q) -> None:
        decoder = native.NativeDocumentDecoder() if self._use_native else DocumentDecoder()
        while self._running:
            frames = q.gets(self.batch_size, timeout_ms=100)
            if not frames:
                continue
            # Coalesce the whole Gets batch into per-org message lists
            # BEFORE decoding (unmarshaller.go:220 batch semantics): one
            # columnar decode + ONE enrichment kernel launch per org per
            # drain, instead of one per ≤256-doc frame — the device-scale
            # batching the r3 verdict flagged (weak #5). Org is the only
            # routing key the writer uses (metrics_tables.py:153);
            # per-agent identity lives in the doc tag columns.
            groups: dict[int, list] = {}  # org → [header, parts, n_msgs]
            n_frames = bad = 0
            for raw in frames:
                try:
                    header = FlowHeader.parse(raw[:HEADER_LEN])
                    body = raw[HEADER_LEN:]
                    spans = split_message_spans(body)
                except ValueError:  # short/garbage frame must not kill the worker
                    bad += 1
                    continue
                n_frames += 1
                g = groups.get(header.organization_id)
                if g is None:
                    groups[header.organization_id] = [header, [(body, spans)], len(spans)]
                else:
                    g[1].append((body, spans))
                    g[2] += len(spans)
            with self._lock:
                self.counters["decode_errors"] += bad
                self.counters["frames_in"] += n_frames
            for header, parts, n_msgs in groups.values():
                self._process_parts(decoder, header, parts, n_msgs)

    def _process_parts(self, decoder, header: FlowHeader, parts, n_msgs: int) -> None:
        errors_before = decoder.decode_errors
        batches = decoder.decode_parts(parts)
        with self._lock:
            self.counters["docs_in"] += n_msgs
            self.counters["decode_errors"] += decoder.decode_errors - errors_before

        for decoded in batches.values():
            valid = np.ones(decoded.tags.shape[0], dtype=bool)
            if self.disable_second_write:
                # 1s-granularity docs carry PER_SECOND_METRICS
                # (unmarshaller.go:246 disableSecondWrite)
                second = (decoded.flags & int(DocumentFlag.PER_SECOND_METRICS)) != 0
                with self._lock:
                    self.counters["drop_second_write"] += int(second.sum())
                valid &= ~second
            if self.platform_state is not None:
                # ONE fixed kernel shape: enrich in fixed-size chunks
                # (pad the tail) so the whole run compiles exactly once —
                # per-frame power-of-2 padding recompiled on every new
                # drain size and dominated e2e time (bench/e2e_ingest.py)
                n = decoded.tags.shape[0]
                c = self.enrich_chunk
                s0_parts, s1_parts, keep_parts, drops = [], [], [], 0
                for off in range(0, n, c):
                    m = min(c, n - off)
                    tags_p = np.zeros((c, decoded.tags.shape[1]), dtype=np.uint32)
                    tags_p[:m] = decoded.tags[off : off + m]
                    valid_p = np.zeros(c, dtype=bool)
                    valid_p[:m] = valid[off : off + m]
                    c0, c1, ckeep, cdrops = enrich_docs(
                        self.platform_state, tags_p, valid_p
                    )
                    s0_parts.append({k: np.asarray(v)[:m] for k, v in c0.items()})
                    s1_parts.append({k: np.asarray(v)[:m] for k, v in c1.items()})
                    keep_parts.append(np.asarray(ckeep)[:m])
                    drops += int(cdrops)
                s0 = {
                    k: np.concatenate([p[k] for p in s0_parts]) for k in s0_parts[0]
                }
                s1 = {
                    k: np.concatenate([p[k] for p in s1_parts]) for k in s1_parts[0]
                }
                keep = np.concatenate(keep_parts)
                with self._lock:
                    self.counters["drop_other_region"] += int(drops)
            else:
                s0 = s1 = None
                keep = valid
            with self._lock:
                self.counters["docs_written"] += int(keep.sum())
            self.writer.put(EnrichedBatch(header=header, decoded=decoded, side0=s0, side1=s1, keep=keep))


class ListWriter:
    """Test/bring-up writer: collects EnrichedBatches in memory."""

    def __init__(self):
        self.batches: list[EnrichedBatch] = []
        self._lock = threading.Lock()

    def put(self, batch: EnrichedBatch) -> None:
        with self._lock:
            self.batches.append(batch)

    def doc_count(self) -> int:
        with self._lock:
            return sum(int(b.keep.sum()) for b in self.batches)
