"""flow_metrics ingester — receiver to storage, the server hot path.

The TPU re-composition of `server/ingester/flow_metrics/flow_metrics.go:50`
+ `unmarshaller/unmarshaller.go:220`: the receiver fans METRICS frames
into N overwrite queues; each unmarshaller worker drains its queue in
batches, decodes pb Documents columnar (native C++ decoder when built,
Python twin otherwise), runs the whole batch through the device
enrichment kernel (enrich/platform.py — the DocumentExpand analog), and
hands enriched column batches to the writer.

Like the reference, no re-aggregation happens here — agents pre-aggregate
and docs are written as-is (flow_metrics.go design); server-side rollups
are the downsampler's job. `disable_second_write` mirrors
unmarshaller.go:246.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from ..datamodel.code import DocumentFlag
from ..datamodel.schema import TAG_SCHEMA
from ..enrich.platform import PlatformState, enrich_docs
from ..ingest.codec import DecodedBatch, DocumentDecoder
from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_messages
from ..ingest.queues import new_queue
from ..ingest.receiver import Receiver
from .. import native


@dataclasses.dataclass
class EnrichedBatch:
    """What the writer receives: decoded docs + device enrichment."""

    header: FlowHeader
    decoded: DecodedBatch
    side0: dict[str, np.ndarray] | None
    side1: dict[str, np.ndarray] | None
    keep: np.ndarray  # [N] bool (False = other-region drop)


class FlowMetricsIngester:
    """METRICS pipeline: queues → decode → enrich → writer.put(batch)."""

    def __init__(
        self,
        receiver: Receiver,
        writer,
        *,
        platform_state: PlatformState | None = None,
        n_workers: int = 1,
        queue_capacity: int = 1 << 14,
        batch_size: int = 256,
        disable_second_write: bool = False,
        prefer_native: bool = True,
    ):
        self.writer = writer
        self.platform_state = platform_state
        self.batch_size = batch_size
        self.disable_second_write = disable_second_write
        self._use_native = prefer_native and native.native_available()
        self.queues = [new_queue(queue_capacity, prefer_native=prefer_native) for _ in range(n_workers)]
        receiver.register_handler(MessageType.METRICS, self.queues)
        self.counters = {
            "frames_in": 0,
            "docs_in": 0,
            "docs_written": 0,
            "decode_errors": 0,
            "drop_other_region": 0,
            "drop_second_write": 0,
        }
        self._lock = threading.Lock()
        self._running = True
        self._threads = [
            threading.Thread(target=self._worker, args=(q,), daemon=True) for q in self.queues
        ]
        for t in self._threads:
            t.start()
        from ..utils.stats import register_countable

        register_countable("flow_metrics_ingester", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def stop(self, timeout: float = 5.0) -> None:
        self._running = False
        for q in self.queues:
            q.close()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- worker ---------------------------------------------------------
    def _worker(self, q) -> None:
        decoder = native.NativeDocumentDecoder() if self._use_native else DocumentDecoder()
        while self._running:
            frames = q.gets(self.batch_size, timeout_ms=100)
            if not frames:
                continue
            for raw in frames:
                self._process_frame(decoder, raw)

    def _process_frame(self, decoder, raw: bytes) -> None:
        try:
            header = FlowHeader.parse(raw[:HEADER_LEN])
            msgs = split_messages(raw[HEADER_LEN:])
        except ValueError:  # short/garbage frame must not kill the worker
            with self._lock:
                self.counters["decode_errors"] += 1
            return
        errors_before = decoder.decode_errors
        batches = decoder.decode(msgs)
        with self._lock:
            self.counters["frames_in"] += 1
            self.counters["docs_in"] += len(msgs)
            self.counters["decode_errors"] += decoder.decode_errors - errors_before

        for decoded in batches.values():
            valid = np.ones(decoded.tags.shape[0], dtype=bool)
            if self.disable_second_write:
                # 1s-granularity docs carry PER_SECOND_METRICS
                # (unmarshaller.go:246 disableSecondWrite)
                second = (decoded.flags & int(DocumentFlag.PER_SECOND_METRICS)) != 0
                with self._lock:
                    self.counters["drop_second_write"] += int(second.sum())
                valid &= ~second
            if self.platform_state is not None:
                # pad rows to a power of two so jit compiles O(log N)
                # distinct shapes, not one per frame size
                n = decoded.tags.shape[0]
                p = 1
                while p < n:
                    p *= 2
                tags_p = np.zeros((p, decoded.tags.shape[1]), dtype=np.uint32)
                tags_p[:n] = decoded.tags
                valid_p = np.zeros(p, dtype=bool)
                valid_p[:n] = valid
                s0, s1, keep, drops = enrich_docs(self.platform_state, tags_p, valid_p)
                s0 = {k: np.asarray(v)[:n] for k, v in s0.items()}
                s1 = {k: np.asarray(v)[:n] for k, v in s1.items()}
                keep = np.asarray(keep)[:n]
                with self._lock:
                    self.counters["drop_other_region"] += int(drops)
            else:
                s0 = s1 = None
                keep = valid
            with self._lock:
                self.counters["docs_written"] += int(keep.sum())
            self.writer.put(EnrichedBatch(header=header, decoded=decoded, side0=s0, side1=s1, keep=keep))


class ListWriter:
    """Test/bring-up writer: collects EnrichedBatches in memory."""

    def __init__(self):
        self.batches: list[EnrichedBatch] = []
        self._lock = threading.Lock()

    def put(self, batch: EnrichedBatch) -> None:
        with self._lock:
            self.batches.append(batch)

    def doc_count(self) -> int:
        with self._lock:
            return sum(int(b.keep.sum()) for b in self.batches)
