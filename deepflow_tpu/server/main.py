"""Server composition root — the cmd/server/main.go seat.

One process wiring every plane the way the reference boots
(main.go:31-40: controller.Start → ingester.Start → querier.Start):
config → store → controller (resources, tagrecorder, trisolaris,
election) → receiver + ingesters (flow metrics, flow logs,
integrations) → downsampler → debug endpoint → query engine.
`Server.start()` brings it all up; `tick()` drives the periodic work
(tagrecorder sync, downsampler, stats) so tests and the CLI can step
time deterministically; `stop()` tears down in reverse.
"""

from __future__ import annotations

import os
import time

from ..controller.cloud import CloudTask
from ..controller.election import LeaderElection
from ..controller.genesis import GenesisStore
from ..controller.rebalance import AnalyzerBalancer
from ..controller.recorder import Recorder
from ..controller.resources import ResourceDB
from ..controller.prom_labels import PrometheusLabelRegistry
from ..controller.rest import RestServer
from ..controller.tagrecorder import TagRecorder
from ..controller.trisolaris import TrisolarisService
from ..flowlog.server import FlowLogIngester
from ..ingest.receiver import Receiver
from ..querier import QueryEngine
from ..querier.translation import Translator
from ..server.datasource import DataSource, Downsampler
from ..server.debug import DebugServer
from ..server.events import EventIngester
from ..server.exporters import ExporterHub, FileExporter, OtlpExporter, RemoteWriteExporter


def build_exporters(specs) -> list:
    """Config-driven sink construction (the reference's
    exporters/config seat): each spec is {"kind": ..., kwargs...}.
    Unknown kinds raise at boot — a misconfigured sink must not
    silently drop telemetry."""
    out = []
    for spec in specs or ():
        spec = dict(spec)
        kind = spec.pop("kind", None)
        if "data_sources" in spec:
            spec["data_sources"] = tuple(spec["data_sources"])
        if kind == "kafka":
            from ..server.kafka_exporter import KafkaExporter

            out.append(KafkaExporter(**spec))
        elif kind == "otlp":
            out.append(OtlpExporter(**spec))
        elif kind == "prom_rw":
            if "metrics" in spec:
                spec["metrics"] = tuple(spec["metrics"])
            out.append(RemoteWriteExporter(**spec))
        elif kind == "jsonl":
            out.append(FileExporter(**spec))
        else:
            raise ValueError(f"unknown exporter kind {kind!r}")
    return out
from ..server.flow_metrics import FlowMetricsIngester
from ..server.integration import IntegrationIngester
from ..server.mcp import MCPServer
from ..server.metrics_tables import DocStoreWriter
from ..storage.issu import upgrade as issu_upgrade
from ..storage.monitor import StoreMonitor
from ..storage.store import ColumnarStore
from ..tracing.builder import TraceTreeBuilder
from ..utils.config import ServerConfig, load_config
from ..utils.stats import default_collector


class Server:
    def __init__(self, config: ServerConfig | None = None, *, exporters=None, lease_path=None):
        self.config = config or load_config(None)[0]
        self.exporters = (
            exporters if exporters is not None
            else build_exporters(self.config.exporters)
        )
        self.lease_path = lease_path
        self.started = False

    def start(self) -> "Server":
        cfg = self.config
        self.store = ColumnarStore(cfg.storage.root)
        # in-service schema upgrade before anything touches tables
        # (ckissu.go:51 boot ordering)
        self.issu_report = issu_upgrade(self.store)
        self.resources = ResourceDB()
        self.translator = Translator(self.store)
        self.tagrecorder = TagRecorder(self.resources, self.store, translator=self.translator)
        # resource plane: discovery sources → recorder → ResourceDB.
        # Genesis fills from agent sync payloads; cloud sources attach
        # via add_cloud_source(). Resource-change events ride the event
        # plane once the event ingester is up (sink bound below).
        self._resource_events: list = []
        self.recorder = Recorder(self.resources, event_sink=self._resource_events.append)
        # id stability across restarts (MySQL seat): without this, a
        # rebooted recorder re-allocates ids and the persisted tag
        # dictionaries alias onto the wrong resources
        self._recorder_state_path = (
            os.path.join(cfg.storage.root, "recorder_ids.json")
            if cfg.storage.root
            else None
        )
        if self._recorder_state_path:
            self.recorder.load(self._recorder_state_path)
        self._was_leader = False
        self.genesis = GenesisStore()
        self.balancer = AnalyzerBalancer()
        self._analyzer_ip = cfg.receiver.host or "127.0.0.1"
        self.balancer.register(self._analyzer_ip)
        self.cloud_tasks: list[CloudTask] = []
        self.trisolaris = TrisolarisService(
            self.resources, genesis=self.genesis, balancer=self.balancer
        )
        # holder must be unique ACROSS processes — heap addresses collide
        self.election = (
            LeaderElection(self.lease_path, holder=f"server-{os.getpid()}-{id(self):x}")
            if self.lease_path
            else None
        )
        self._platform_version = self.resources.version

        self.receiver = Receiver(
            host=cfg.receiver.host,
            tcp_port=cfg.receiver.tcp_port,
            udp_port=cfg.receiver.udp_port,
        )
        self.receiver.start()

        writer_args = {
            "batch_size": cfg.storage.writer_batch_size,
            "flush_interval_s": cfg.storage.writer_flush_s,
        }
        # push query plane (ISSUE 11): store mutations publish on the
        # process-wide event bus → eager result-cache invalidation; the
        # subscription manager and alert engine evaluate standing
        # queries on those events. The doc writer registers its pending
        # rows as live sources (ROADMAP item (a)): the server-layer
        # network/application families answer range-ending-now queries
        # with partial rows instead of going dark for a flush interval.
        from ..querier.alerts import AlertEngine
        from ..querier.events import connect_store_events, default_event_bus
        from ..querier.live import default_live_registry
        from ..querier.subscribe import SubscriptionManager

        self.event_bus = default_event_bus
        connect_store_events(self.store, self.event_bus)
        self.subscriptions = SubscriptionManager(
            self.store, bus=self.event_bus, name="server"
        )
        self.alerts = AlertEngine(self.store, bus=self.event_bus, name="server")
        if cfg.alert_rules:
            # rule persistence (ISSUE 13 satellite): alert rules load
            # from the config-named file at boot — a malformed rule
            # fails the boot loudly rather than dropping the page
            self.alerts.load_rules(cfg.alert_rules)
        # device profiling plane (ISSUE 12): each collector tick that
        # lands profiling rows publishes a ProfileSnapshot, so standing
        # queries / span-latency alert rules over deepflow_system
        # re-evaluate at the sample tick instead of waiting for a poll
        from ..profiling import profile_tick_sink

        self._profile_sink = profile_tick_sink(self.event_bus)
        default_collector.add_sink(self._profile_sink)

        self.exporter_hub = ExporterHub(self.exporters) if self.exporters else None
        self.doc_writer = DocStoreWriter(
            self.store,
            partition_s=cfg.storage.partition_s,
            ttl_hours=cfg.storage.ttl_hours,
            writer_args=writer_args,
            exporter_hub=self.exporter_hub,
            live_registry=default_live_registry,
        )
        platform_state = self.resources.build_platform_table(cfg.region_id).build()
        self.flow_metrics = FlowMetricsIngester(
            self.receiver,
            self.doc_writer,
            platform_state=platform_state,
            n_workers=cfg.ingester.n_decoders,
            queue_capacity=cfg.ingester.queue_capacity,
            batch_size=cfg.ingester.batch_size,
            disable_second_write=cfg.ingester.disable_second_write,
            prefer_native=cfg.ingester.prefer_native,
        )
        self.flow_log = FlowLogIngester(
            self.receiver,
            self.store,
            platform_state=platform_state,
            l4_throttle=cfg.ingester.l4_throttle,
            l7_throttle=cfg.ingester.l7_throttle,
            writer_args=writer_args,
        )
        self.trace_builder = TraceTreeBuilder(self.store, writer_args=writer_args)
        # restart-safe: ids re-load from the persisted dictionaries so
        # encoded rows never alias onto re-allocated ids
        self.prom_labels = PrometheusLabelRegistry.load(self.store)
        self.integration = IntegrationIngester(
            self.receiver, self.store, writer_args=writer_args,
            trace_builder=self.trace_builder,
            prom_labels=self.prom_labels,
        )
        self.events = EventIngester(self.receiver, self.store, writer_args=writer_args)
        self.downsampler = Downsampler(self.store)
        self.debug = DebugServer(
            context={
                "store": self.store,
                "trisolaris": self.trisolaris,
                "downsampler": self.downsampler,
                "subscriptions": self.subscriptions,
                "alerts": self.alerts,
            }
        )
        self.monitor = StoreMonitor(
            self.store, max_bytes=cfg.storage.max_disk_bytes or None
        )
        self.query = QueryEngine(self.store, translator=self.translator)
        # fleet telemetry fan-in (opt-in): the aggregator listener lands
        # per-host frames in THIS server's deepflow_system store, so the
        # SQL/PromQL/alert planes serve fleet-wide queries with
        # host/group labels and REST grows /v1/fleet/*
        self.fleet = None
        if cfg.fleet.enabled:
            from ..fleet import FleetAggregator

            self.fleet = FleetAggregator(
                host=cfg.fleet.listen_host,
                port=cfg.fleet.listen_port,
                store=self.store,
                bus=self.event_bus,
                expiry_s=cfg.fleet.expiry_s,
            ).start()
        # wire delivery plane (ISSUE 19): the SSE lane off the REST
        # server maps each /v1/watch connection onto a bounded watcher
        # queue; the optional router turns this process into the fleet
        # fan-out aggregator (pipeline hosts' WirePublishers dial in),
        # and the optional TCP listener serves the framed variant
        self.wire = None
        self.wire_router = None
        self.wire_tcp = None
        if cfg.wire.enabled:
            from ..wire import FleetSubscriptionRouter, WireHub, WireListener

            if cfg.wire.router_enabled:
                self.wire_router = FleetSubscriptionRouter(
                    host=cfg.wire.router_host, port=cfg.wire.router_port,
                ).start()
            self.wire = WireHub(
                self.subscriptions, alerts=self.alerts,
                router=self.wire_router, bus=self.event_bus,
                lease_s=cfg.wire.lease_s, maxlen=cfg.wire.queue_maxlen,
                name="server",
            )
            if cfg.wire.tcp_enabled:
                self.wire_tcp = WireListener(
                    self.wire, host=cfg.wire.tcp_host,
                    port=cfg.wire.tcp_port,
                ).start()
        self.mcp = MCPServer(self)  # LLM tool surface (mcp.go seat)
        self.rest = RestServer(self)  # controller/querier REST + pprof seat
        if self.election:
            self.election.start()
        self.started = True
        return self

    # -- periodic work (the reference's internal tickers) ---------------
    def tick(self, now: int | None = None) -> dict:
        now = int(time.time()) if now is None else now
        leader = self.election.is_leader() if self.election else True
        if leader and not self._was_leader and self._recorder_state_path:
            # promoted follower: re-read the id maps the previous leader
            # saved, or the first reconcile would re-allocate live ids
            self.recorder.load(self._recorder_state_path)
        self._was_leader = leader
        did = {"leader": leader, "tagrecorder": False, "downsampled": 0, "platform": False}
        # enrichment follows resources, every node (the periodic
        # PlatformInfoTable refresh — not leader-gated in the reference)
        if self.resources.version != self._platform_version:
            self.refresh_platform()
            did["platform"] = True
        did["traces_closed"] = self.trace_builder.tick()
        did["monitor"] = self.monitor.check(now)
        # alert `for`-durations must mature even when a watched table
        # goes quiet (no events BECAUSE traffic stopped is itself an
        # alertable condition) — the wall-clock evaluation lane
        self.alerts.tick(now)
        # abandoned dashboard watchers (missed lease renewals) reap on
        # the tick as well as on event batches — a quiet store must not
        # keep dead clients' queues alive forever (ISSUE 12 satellite)
        self.subscriptions.reap()
        # ...and the wire plane's own topics (alert watchers, fleet
        # router entries, stream records) sweep on the same cadence
        if self.wire is not None:
            self.wire.reap()
        # this process IS the local analyzer — its liveness follows the
        # tick, every node (remote analyzers heartbeat via their own sync)
        self.balancer.heartbeat(self._analyzer_ip)
        if leader:
            did["tagrecorder"] = self.tagrecorder.sync()
            did["downsampled"] = self.downsampler.process(now)
            # discovery: cloud sources + the genesis inventory reconcile
            # into ResourceDB; change events land in the event table.
            # Source errors are non-fatal (CloudTask._loop's stance) —
            # one flaky apiserver must not take the server down.
            for task in self.cloud_tasks:
                task.safe_poll()
            cs = self.recorder.reconcile(self.genesis.domain, self.genesis.snapshot())
            did["resource_changes"] = cs.total + sum(
                t.last_change.total for t in self.cloud_tasks if t.last_change
            )
            self._drain_resource_events()
            self.balancer.rebalance()
            if self._recorder_state_path and self.recorder.dirty:
                self.recorder.save(self._recorder_state_path)
        default_collector.tick()
        return did

    def add_cloud_source(self, source) -> "CloudTask":
        """Attach a cloud discovery source (KubernetesGather /
        FileReaderPlatform); polled on the leader tick."""
        task = CloudTask(source, self.recorder)
        self.cloud_tasks.append(task)
        return task

    def _drain_resource_events(self) -> None:
        """Resource-change events → the event table (the reference's
        eventapi → event ingester path, in-process here)."""
        import json as _json

        from ..ingest.framing import FlowHeader, MessageType

        # FIFO: a create+delete pair for a churned uid shares the same
        # int-second timestamp, so write order is the only order
        events, self._resource_events[:] = list(self._resource_events), []
        for ev in events:
            self.events._event(
                1,
                FlowHeader(msg_type=int(MessageType.K8S_EVENT)),
                _json.dumps(
                    {
                        "time": ev["time"],
                        "event_type": ev["type"],
                        "resource_type": ev["resource_type"],
                        "resource_name": ev["instance"],
                    }
                ).encode(),
                MessageType.K8S_EVENT,
            )

    def query_trace(self, trace_id: str, org: int = 1):
        from ..tracing.query import query_trace

        return query_trace(self.store, trace_id, org=org)

    def query_window_trace(self, window_idx: int, *, interval: int = 1,
                           service: str | None = None, org: int = 1):
        """Window lineage plane (ISSUE 13): the assembled trace tree of
        one pipeline window — exported spans from the store when
        present, else live from a registered LineageTracker."""
        from ..tracing.lineage import DEFAULT_SERVICE, query_window_trace

        return query_window_trace(
            self.store, window_idx, interval=interval,
            service=service or DEFAULT_SERVICE, org=org,
        )

    def trace_map(self, time_range=None, org: int = 1):
        from ..tracing.query import trace_map

        return trace_map(self.store, time_range=time_range, org=org)

    def refresh_platform(self) -> None:
        """Resource changes → new enrichment generation (the periodic
        PlatformInfoTable refresh, grpc_platformdata.go:147)."""
        state = self.resources.build_platform_table(self.config.region_id).build()
        self.flow_metrics.platform_state = state
        self.flow_log.platform_state = state
        self._platform_version = self.resources.version

    def add_datasource(self, **kw) -> DataSource:
        return self.downsampler.add(DataSource(**kw))

    def stop(self) -> None:
        if not self.started:
            return
        if self.election:
            self.election.stop()
        self.flow_metrics.stop()
        self.flow_log.stop()
        self.integration.stop()
        self.events.stop()
        self.trace_builder.stop()
        self.mcp.stop()
        # wire teardown BEFORE rest.stop(): close() flips the hub's
        # closing flag so in-flight SSE handler threads end their
        # streams instead of spinning on heartbeats into dead sockets
        if self.wire is not None:
            self.wire.close()
        if self.wire_tcp is not None:
            self.wire_tcp.stop()
        if self.wire_router is not None:
            self.wire_router.stop()
        self.rest.stop()
        if self.fleet is not None:
            self.fleet.stop()
        self.doc_writer.flush()
        self.doc_writer.stop()
        if self.exporter_hub is not None:
            self.exporter_hub.stop()
        self.debug.stop()
        self.trisolaris.stop()
        self.receiver.stop()
        # detach the push plane from the PROCESS-WIDE bus: a stopped
        # server's managers must not keep evaluating against its store
        # when another server (tests, restarts) publishes
        self.subscriptions.close()
        self.alerts.close()
        default_collector.remove_sink(self._profile_sink)
        self.store.set_mutation_hook(None)
        self.started = False
