"""UDP debug endpoint — the ingesterctl / libs/debug seat.

The reference exposes a UDP RPC on every server for `deepflow-ctl
ingester`/`agent` debug commands: queue taps, counter dumps, platform
dumps, loglevel (server/libs/debug/simple_debug.go;
ingesterctl/const.go:27-61). Here: one JSON-datagram endpoint serving
the counter registry, table/row inventories, agent liveness, and
datasource listings. Request {"cmd": ..., **args} → JSON reply
(truncated to fit one datagram; big answers page with "offset").
"""

from __future__ import annotations

import json
import socket
import threading

from ..utils.stats import default_collector

MAX_DGRAM = 60000


class DebugServer:
    def __init__(self, *, host: str = "127.0.0.1", port: int = 0, context: dict | None = None):
        """context: named objects commands can inspect — "store",
        "trisolaris", "downsampler", "ingesters"… all optional."""
        self.context = context or {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self._sock.settimeout(0.2)
        while self._running:
            try:
                data, addr = self._sock.recvfrom(65535)
            except (TimeoutError, OSError):
                continue
            try:
                req = json.loads(data)
                resp = self._handle(req)
            except Exception as e:
                resp = {"error": str(e)}
            payload = json.dumps(resp).encode()
            if len(payload) > MAX_DGRAM:
                payload = json.dumps({"error": "reply too large; page with offset/limit"}).encode()
            try:
                self._sock.sendto(payload, addr)
            except OSError:
                pass

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "counters":
            # read the ring, never tick(): a read-only RPC must not push
            # snapshots into sinks (the dfstats pipeline) as a side effect
            pts = default_collector.recent() or default_collector.tick()
            module = req.get("module")
            out = [
                {"module": p.module, "tags": dict(p.tags), "fields": p.fields}
                for p in pts
                if module is None or p.module == module
            ]
            off = int(req.get("offset", 0))
            return {"counters": out[off : off + int(req.get("limit", 200))]}
        if cmd == "tables":
            store = self.context.get("store")
            if store is None:
                return {"error": "no store attached"}
            out = {}
            for db in store.databases():
                out[db] = {t: store.row_count(db, t) for t in store.tables(db)}
            return {"tables": out}
        if cmd == "agents":
            tri = self.context.get("trisolaris")
            if tri is None:
                return {"error": "no controller attached"}
            return {"agents": {str(k): v for k, v in tri.agents.items()}}
        if cmd == "datasources":
            dsm = self.context.get("downsampler")
            if dsm is None:
                return {"error": "no downsampler attached"}
            from .datasource import list_cascade_tiers

            return {
                "datasources": [
                    {
                        "name": d.name,
                        "base": d.base_table,
                        "interval": d.interval,
                        "watermark": d.watermark,
                        "served_by": "downsampler",
                    }
                    for d in dsm.list()
                ]
                # tiers the rollup cascade serves on device (ISSUE 9):
                # no watermark — the tier closes with its last child
                # window, there is no store-side scan to track
                + [
                    {"name": r["name"], "base": r["base_table"],
                     "interval": r["interval"], "served_by": "cascade"}
                    for r in list_cascade_tiers()
                ]
            }
        if cmd == "subscriptions":
            # push query plane (ISSUE 11): active standing queries with
            # watcher counts and eval latency — the dfctl listing
            subs = self.context.get("subscriptions")
            if subs is None:
                return {"error": "no subscription manager attached"}
            return {
                "subscriptions": subs.list_subscriptions(),
                "counters": subs.get_counters(),
            }
        if cmd == "alerts":
            alerts = self.context.get("alerts")
            if alerts is None:
                return {"error": "no alert engine attached"}
            return {
                "alerts": alerts.list_rules(),
                "counters": alerts.get_counters(),
            }
        if cmd == "ping":
            return {"pong": True}
        return {"error": f"unknown cmd {cmd!r}"}

    def stop(self):
        self._running = False
        self._thread.join(timeout=2)
        self._sock.close()


def debug_request(host: str, port: int, req: dict, timeout: float = 3.0) -> dict:
    """Client side (the deepflow-ctl UDP call)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(json.dumps(req).encode(), (host, port))
        data, _ = s.recvfrom(65535)
        return json.loads(data)
    finally:
        s.close()
