"""Window-state checkpoint/resume.

The reference has no checkpointing (streaming system; durable state
lives in its databases — SURVEY §5), but the TPU build's device-resident
window state (stash + accumulator rings + host window span) is exactly
the state a preempted chip loses. These helpers serialize a
WindowManager to one .npz so an evicted worker resumes mid-window
instead of dropping every open window's partial aggregates.

Format: the StashState/AccumState arrays (device → host), the host
counters, and a version tag. Resume rebuilds device arrays lazily on
first use (jnp.asarray on merge).
"""

from __future__ import annotations

import io
import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import MeterSchema, TagSchema
from .stash import AccumState, StashState
from .window import WindowConfig, WindowManager

_VERSION = 1


def save_window_state(wm: WindowManager, path: str | Path) -> None:
    arrays = {
        "stash_slot": np.asarray(wm.state.slot),
        "stash_key_hi": np.asarray(wm.state.key_hi),
        "stash_key_lo": np.asarray(wm.state.key_lo),
        "stash_tags": np.asarray(wm.state.tags),
        "stash_meters": np.asarray(wm.state.meters),
        "stash_valid": np.asarray(wm.state.valid),
        "stash_dropped": np.asarray(wm.state.dropped_overflow),
    }
    if wm.acc is not None:
        arrays.update(
            acc_slot=np.asarray(wm.acc.slot),
            acc_key_hi=np.asarray(wm.acc.key_hi),
            acc_key_lo=np.asarray(wm.acc.key_lo),
            acc_tags=np.asarray(wm.acc.tags),
            acc_meters=np.asarray(wm.acc.meters),
        )
    meta = {
        "version": _VERSION,
        "fill": wm.fill,
        "start_window": wm.start_window,
        "drop_before_window": wm.drop_before_window,
        "total_docs_in": wm.total_docs_in,
        "total_flushed": wm.total_flushed,
        "interval": wm.config.interval,
        "delay": wm.config.delay,
        "capacity": wm.config.capacity,
        "accum_batches": wm.config.accum_batches,
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
                        **arrays)
    Path(path).write_bytes(buf.getvalue())


def load_window_state(
    path: str | Path, tag_schema: TagSchema, meter_schema: MeterSchema
) -> WindowManager:
    with np.load(io.BytesIO(Path(path).read_bytes())) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta["version"] != _VERSION:
            raise ValueError(f"checkpoint version {meta['version']} != {_VERSION}")
        cfg = WindowConfig(
            interval=meta["interval"],
            delay=meta["delay"],
            capacity=meta["capacity"],
            accum_batches=meta["accum_batches"],
        )
        wm = WindowManager(cfg, tag_schema, meter_schema)
        wm.state = StashState(
            slot=jnp.asarray(z["stash_slot"]),
            key_hi=jnp.asarray(z["stash_key_hi"]),
            key_lo=jnp.asarray(z["stash_key_lo"]),
            tags=jnp.asarray(z["stash_tags"]),
            meters=jnp.asarray(z["stash_meters"]),
            valid=jnp.asarray(z["stash_valid"]),
            dropped_overflow=jnp.asarray(z["stash_dropped"]),
        )
        if "acc_slot" in z:
            wm.acc = AccumState(
                slot=jnp.asarray(z["acc_slot"]),
                key_hi=jnp.asarray(z["acc_key_hi"]),
                key_lo=jnp.asarray(z["acc_key_lo"]),
                tags=jnp.asarray(z["acc_tags"]),
                meters=jnp.asarray(z["acc_meters"]),
            )
        wm.fill = meta["fill"]
        wm.start_window = meta["start_window"]
        wm.drop_before_window = meta["drop_before_window"]
        wm.total_docs_in = meta["total_docs_in"]
        wm.total_flushed = meta["total_flushed"]
    return wm
