"""Window-state checkpoint/resume.

The reference has no checkpointing (streaming system; durable state
lives in its databases — SURVEY §5), but the TPU build's device-resident
window state (stash + accumulator rings + host window span) is exactly
the state a preempted chip loses. These helpers serialize a
WindowManager to one .npz so an evicted worker resumes mid-window
instead of dropping every open window's partial aggregates.

Format v2: ONE packed u32 matrix per direction — the stash leaves
(slot/keys/valid/tags/bit-cast meters) concatenate on device into a
single [4+T+M, S] array fetched in one transfer, and restore uploads one
matrix and splits it back in a single jitted call. v1 paid the PERF.md
§8 per-leaf transfer tax: 7 stash + 5 accumulator round trips per
save/restore. The v1 LOAD branch was removed after two rounds of
v2-only writers (ROADMAP): v1 files also predate the r6 packed-word key
fingerprint, so their stash keys could never merge with freshly-hashed
rows anyway — loading one now raises with a re-save instruction instead
of resuming into silently unmergeable state.
"""

from __future__ import annotations

import io
import json
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import MeterSchema, TagSchema
from .stash import AccumState, StashState, pack_u32_columns
from .window import WindowConfig, WindowManager

_VERSION = 2


@jax.jit
def _pack_stash(state: StashState) -> jnp.ndarray:
    """[4+T+M, S] u32: slot, key_hi, key_lo, valid, tags…, meters…"""
    return pack_u32_columns(
        state.slot, state.key_hi, state.key_lo, state.tags, state.meters,
        valid=state.valid,
    )


@partial(jax.jit, static_argnames=("num_tags",))
def _unpack_stash(mat, dropped, *, num_tags: int) -> StashState:
    return StashState(
        slot=mat[0],
        key_hi=mat[1],
        key_lo=mat[2],
        valid=mat[3].astype(bool),
        tags=mat[4 : 4 + num_tags],
        meters=jax.lax.bitcast_convert_type(mat[4 + num_tags :], jnp.float32),
        dropped_overflow=jnp.asarray(dropped, dtype=jnp.int32),
    )


@jax.jit
def _pack_acc(acc: AccumState) -> jnp.ndarray:
    """[3+T+M, A] u32: slot, key_hi, key_lo, tags…, meters…"""
    return pack_u32_columns(acc.slot, acc.key_hi, acc.key_lo, acc.tags, acc.meters)


@partial(jax.jit, static_argnames=("num_tags",))
def _unpack_acc(mat, *, num_tags: int) -> AccumState:
    return AccumState(
        slot=mat[0],
        key_hi=mat[1],
        key_lo=mat[2],
        tags=mat[3 : 3 + num_tags],
        meters=jax.lax.bitcast_convert_type(mat[3 + num_tags :], jnp.float32),
    )


def save_window_state(wm: WindowManager, path: str | Path):
    """Snapshot `wm` to one .npz. Returns the FlushedWindows that were
    still in flight in async_drain mode (deferred stats / dispatched
    flushes) — their rows have already left the stash, so the CALLER
    must emit them before treating the checkpoint as the resume point;
    an unsettled snapshot would silently lose those windows' documents.
    Empty list in sync mode."""
    from ..utils.spans import SPAN_CHECKPOINT_SAVE

    with wm.tracer.span(SPAN_CHECKPOINT_SAVE):
        in_flight = wm.settle()
        arrays = {"stash_packed": np.asarray(_pack_stash(wm.state))}
        if wm.acc is not None:
            arrays["acc_packed"] = np.asarray(_pack_acc(wm.acc))
        meta = {
            "version": _VERSION,
            "num_tags": wm.tag_schema.num_fields,
            "dropped_overflow": int(np.asarray(wm.state.dropped_overflow)),
            "fill": wm.fill,
            "start_window": wm.start_window,
            "drop_before_window": wm.drop_before_window,
            "total_docs_in": wm.total_docs_in,
            "total_flushed": wm.total_flushed,
            "aux_count": wm.aux_count,
            "excess_word_hits": wm.excess_word_hits,
            "feeder_shed": wm.feeder_shed,
            "interval": wm.config.interval,
            "delay": wm.config.delay,
            "capacity": wm.config.capacity,
            "accum_batches": wm.config.accum_batches,
            "async_drain": wm.config.async_drain,
            "stats_ring": wm.config.stats_ring,
            # fold strategy rides the checkpoint: a merge-mode stash is
            # canonical (live sorted prefix) and must resume merge-mode;
            # a full-mode stash may hold per-window flush holes and must
            # NOT resume into the rank-merge
            "fold_mode": wm.config.fold_mode,
        }
        buf = io.BytesIO()
        np.savez_compressed(
            buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8), **arrays
        )
        Path(path).write_bytes(buf.getvalue())
    return in_flight


def load_window_state(
    path: str | Path, tag_schema: TagSchema, meter_schema: MeterSchema
) -> WindowManager:
    with np.load(io.BytesIO(Path(path).read_bytes())) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta["version"] == 1:
            # v1 readers were dropped once two rounds had shipped with
            # v2-only writers (ROADMAP). No silent fallback: a v1 stash
            # predates the packed-word key fingerprint and could never
            # merge with freshly-hashed rows.
            raise ValueError(
                "checkpoint format v1 is unsupported (v1 load support was "
                "removed after v2 writers shipped); re-save the window "
                "state with a v2 writer"
            )
        if meta["version"] != _VERSION:
            raise ValueError(f"checkpoint version {meta['version']} != {_VERSION}")
        cfg = WindowConfig(
            interval=meta["interval"],
            delay=meta["delay"],
            capacity=meta["capacity"],
            accum_batches=meta["accum_batches"],
            async_drain=meta.get("async_drain", False),
            stats_ring=meta.get("stats_ring", 1),
            fold_mode=meta.get("fold_mode", "full"),
        )
        wm = WindowManager(cfg, tag_schema, meter_schema)
        t = tag_schema.num_fields
        if meta["num_tags"] != t:
            # the packed split is shape-valid for ANY num_tags — a
            # mismatch would bit-cast misaligned words into meters
            # silently, so schema drift must fail loudly
            raise ValueError(
                f"checkpoint tag schema width {meta['num_tags']} != "
                f"{t} ({tag_schema.__class__.__name__}); cannot unpack"
            )
        # one upload + one jitted split per direction
        wm.state = _unpack_stash(
            jnp.asarray(z["stash_packed"]),
            np.int32(meta["dropped_overflow"]),
            num_tags=t,
        )
        if "acc_packed" in z:
            wm.acc = _unpack_acc(jnp.asarray(z["acc_packed"]), num_tags=t)
        wm.fill = meta["fill"]
        wm.start_window = meta["start_window"]
        wm.drop_before_window = meta["drop_before_window"]
        wm.total_docs_in = meta["total_docs_in"]
        wm.total_flushed = meta["total_flushed"]
        # telemetry counters landed after v2 writers; absent = 0
        wm.aux_count = meta.get("aux_count", 0)
        wm.excess_word_hits = meta.get("excess_word_hits", 0)
        wm.feeder_shed = meta.get("feeder_shed", 0)
        # the save settled (ring drained), so the restored host span IS
        # the device gate state — mirror it back onto the device
        wm._sync_device_sw()
    return wm
