"""Window-state checkpoint/resume.

The reference has no checkpointing (streaming system; durable state
lives in its databases — SURVEY §5), but the TPU build's device-resident
window state (stash + accumulator rings + host window span) is exactly
the state a preempted chip loses. These helpers serialize a
WindowManager to one .npz so an evicted worker resumes mid-window
instead of dropping every open window's partial aggregates.

Format v3 (ISSUE 6): v2's one-packed-u32-matrix-per-direction layout
plus crash-safety — the file is written to a temp name and
`os.replace`d into place (a mid-write kill leaves the PREVIOUS
checkpoint intact, never a torn file), meta embeds a sha256 content
digest over every array, and the loader fails LOUDLY (a ValueError
naming the file and the failure class, not a numpy/zipfile traceback)
on truncation or digest mismatch. Meta also carries the feeder's
journal barrier (epoch, offset) when saved through
`FeederRuntime.checkpoint`, closing the journal+snapshot recovery
loop. v2 files (pre-digest) still load; the v1 LOAD branch was removed
after two rounds of v2-only writers (ROADMAP): v1 files also predate
the r6 packed-word key fingerprint, so their stash keys could never
merge with freshly-hashed rows anyway — loading one now raises with a
re-save instruction instead of resuming into silently unmergeable
state.

`save_sharded_state` / `restore_sharded_state` are the
ShardedWindowManager twins (same file family, kind="sharded"): the
per-device stash packs via a vmapped pack into one [D, 4+T+M, S]
array, sketch planes ride alongside, and restore re-shards onto the
manager's mesh — the missing piece for kill-and-recover on the mesh
path.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos
from ..datamodel.schema import MeterSchema, TagSchema
from .cascade import (
    CascadeConfig,
    pending_block_arrays,
    restore_pending_blocks,
)
from .sketchplane import SketchConfig, SketchState, sketch_init
from .stash import AccumState, StashState, pack_u32_columns
from .window import WindowConfig, WindowManager

# v5 (ISSUE 9): + rollup-cascade tier state — per-tier stash planes
# (casc_t<i>_packed, same packed-u32 layout as the main stash), host
# watermarks / device counter lanes in meta, and the open parents'
# partially-merged sketch blocks (cascblk_* arrays). v4-and-earlier
# files load with the tiers re-initialized + a LOUD log (open tier
# windows' partial aggregates restart; the journal replay rebuilds them
# where it covers the span).
# v6 (ISSUE 20): + pooled sketch-memory lanes — the compact arena,
# the slot routing table, the wide close/count lanes and the
# spill/promotion scalars ride alongside the classic lanes (zero-size
# arrays in slab mode, so slab checkpoints cost nothing). v5 files
# restore into a pool-CONFIGURED manager with the sketch tier
# re-initialized + a LOUD log (pooled arenas cannot be re-seated from
# slab planes); `promote_fill` is deliberately NOT serialized — it
# re-derives from the manager's PoolConfig at restore, so a knob change
# takes effect without invalidating checkpoints.
_VERSION = 6
_MIN_READ_VERSION = 2  # v2 = pre-digest layout, still loadable

_log = logging.getLogger(__name__)

# sketch-plane lanes (v4): one checkpoint array per device lane, with a
# leading device dim on the sharded kind. v2/v3 files predate the plane
# — loading one re-initializes the sketches with a LOUD log (partial
# aggregates of open windows' sketches are rebuilt from replay where the
# journal covers them; approximate tiers degrade, they never crash).
_SKETCH_LANES = (
    "win", "count", "hll", "cms", "hist",
    "tk_votes", "tk_hi", "tk_lo", "tk_ida", "tk_idb",
    "pend", "pend_win",
)

# pooled sketch-memory lanes (v6, ISSUE 20): zero-size in slab mode —
# they serialize (and hash into the digest) at no cost either way
_POOL_LANES = (
    "slot_of", "p_hll", "p_cms", "p_hist", "p_tkv", "p_tkh", "p_tkl",
    "p_tia", "p_tib", "wide_close", "wide_count",
)


def _sketch_arrays(sk: SketchState, prefix: str = "sk_") -> dict:
    return {
        prefix + name: np.asarray(getattr(sk, name))
        for name in _SKETCH_LANES + _POOL_LANES
    }


def _sketch_meta(sk: SketchState, cfg: SketchConfig) -> dict:
    return {
        "sketch": cfg.meta(),
        "sketch_pend_n": np.asarray(sk.pend_n).tolist(),
        "sketch_rows": np.asarray(sk.rows).tolist(),
        "sketch_shed": np.asarray(sk.shed).tolist(),
        "sketch_pool_spill": np.asarray(sk.pool_spill).tolist(),
        "sketch_pool_promos": np.asarray(sk.pool_promos).tolist(),
    }


def _restore_sketch(meta: dict, arrays: dict, cfg: SketchConfig,
                    ring: int, path, *, sharded_dim: int | None = None):
    """→ SketchState from a v4 checkpoint, or a LOUDLY-logged fresh
    plane when the file predates the sketch tier (v2/v3) or was saved
    with sketches off."""
    if "sk_win" not in arrays:
        _log.warning(
            "checkpoint %s (version %s) carries no sketch planes — "
            "re-initializing the per-window sketch tier empty; open "
            "windows' approximate answers restart from this point",
            path, meta.get("version"),
        )
        sk = sketch_init(cfg, ring)
        if sharded_dim is not None:
            sk = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (sharded_dim,) + x.shape), sk
            )
        return sk
    import dataclasses as _dc

    saved_cfg = SketchConfig.from_meta(meta["sketch"])
    if saved_cfg != cfg:
        if _dc.replace(saved_cfg, pool=None) == _dc.replace(cfg, pool=None):
            # same wide-plane shapes, different pool geometry — incl.
            # the v5-into-pooled-manager path (v5 meta has no "pool").
            # Pooled arenas cannot be re-seated from slab planes (or
            # from differently-factored arenas), so this is the loud
            # re-init contract, NOT the config-mismatch crash.
            _log.warning(
                "checkpoint %s sketch pool geometry %s != manager pool "
                "geometry %s — pooled arenas cannot be re-seated; "
                "re-initializing the sketch tier empty (open windows' "
                "approximate answers restart from this point)",
                path, saved_cfg.pool, cfg.pool,
            )
            sk = sketch_init(cfg, ring)
            if sharded_dim is not None:
                sk = jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        x[None], (sharded_dim,) + x.shape
                    ),
                    sk,
                )
            return sk
        raise ValueError(
            f"checkpoint {path} sketch config {saved_cfg} != manager "
            f"sketch config {cfg} — plane shapes/error knobs disagree"
        )
    kw = {name: jnp.asarray(arrays["sk_" + name]) for name in _SKETCH_LANES}
    scal = lambda v, dt: jnp.asarray(np.asarray(v), dt)
    # pooled lanes (v6): absent from pre-v6 files — by the config gate
    # above that only happens with pool=None, where the fields are
    # zero-size; synthesize them from a fresh init so v5 slab files
    # keep loading bit-exact. promote_fill is never serialized: it
    # re-derives from the manager's PoolConfig here.
    fresh = sketch_init(cfg, ring)
    for name in _POOL_LANES:
        if "sk_" + name in arrays:
            kw[name] = jnp.asarray(arrays["sk_" + name])
        else:
            f = np.asarray(getattr(fresh, name))
            shape = f.shape if sharded_dim is None else (sharded_dim,) + f.shape
            kw[name] = jnp.zeros(shape, f.dtype)
    pf = jnp.asarray(fresh.promote_fill)
    if sharded_dim is not None:
        pf = jnp.broadcast_to(pf[None], (sharded_dim,))
    zero = 0 if sharded_dim is None else [0] * sharded_dim
    return SketchState(
        **kw,
        pend_n=scal(meta["sketch_pend_n"], jnp.int32),
        rows=scal(meta["sketch_rows"], jnp.uint32),
        shed=scal(meta["sketch_shed"], jnp.uint32),
        pool_spill=scal(meta.get("sketch_pool_spill", zero), jnp.uint32),
        pool_promos=scal(meta.get("sketch_pool_promos", zero), jnp.uint32),
        promote_fill=pf,
    )


def _cascade_save(pending: list[dict], tiers: list, watermarks: list,
                  lanes_dev, config: CascadeConfig, *, sharded: bool,
                  tier_windows: int) -> tuple[dict, dict]:
    """(meta, arrays) for the cascade's tier state (checkpoint v5):
    per-tier packed stash planes, host watermarks, the device counter
    lanes and the open parents' partially-merged sketch blocks."""
    pack = _pack_stash_sharded if sharded else _pack_stash
    arrays = {
        f"casc_t{i}_packed": np.asarray(pack(t)) for i, t in enumerate(tiers)
    }
    if sharded:
        for i, t in enumerate(tiers):
            arrays[f"casc_t{i}_dropped"] = np.asarray(t.dropped_overflow)
    pend_meta, pend_arrays = pending_block_arrays(pending)
    arrays.update(pend_arrays)
    meta = {
        "cascade": config.meta(),
        "cascade_watermarks": [int(w) for w in watermarks],
        "cascade_lanes": np.asarray(lanes_dev).tolist(),
        "cascade_pending": pend_meta,
        "cascade_tier_windows": int(tier_windows),
    }
    if not sharded:
        meta["cascade_dropped"] = [
            int(np.asarray(t.dropped_overflow)) for t in tiers
        ]
    return meta, arrays


def _restore_cascade_tiers(meta: dict, arrays: dict, config: CascadeConfig,
                           num_tags: int, path, *, sharded: bool,
                           sketch_config) -> "tuple[list, list, jnp.ndarray, list[dict]] | None":
    """→ (tier stashes, watermarks, lanes, pending blocks) from a v5
    checkpoint, or None (with a LOUD log) when the file predates the
    cascade — the caller keeps its freshly-initialized tiers and the
    open tier windows' partial aggregates restart from here."""
    if "casc_t0_packed" not in arrays:
        _log.warning(
            "checkpoint %s (version %s) carries no cascade tier state — "
            "re-initializing the 1m/1h rollup tiers empty; open tier "
            "windows' partial aggregates restart from this point",
            path, meta.get("version"),
        )
        return None
    saved = CascadeConfig.from_meta(meta["cascade"])
    if saved != config:
        raise ValueError(
            f"checkpoint {path} cascade config {saved} != manager cascade "
            f"config {config} — tier shapes/intervals disagree"
        )
    from .stash import stash_canonicalize

    tiers = []
    for i in range(len(config.intervals)):
        mat = jnp.asarray(arrays[f"casc_t{i}_packed"])
        if sharded:
            t = _unpack_stash_sharded(
                mat, jnp.asarray(arrays[f"casc_t{i}_dropped"], jnp.int32),
                num_tags=num_tags,
            )
            t = jax.vmap(stash_canonicalize)(t)
        else:
            t = _unpack_stash(
                mat, np.int32(meta["cascade_dropped"][i]), num_tags=num_tags,
            )
            t = stash_canonicalize(t)
        # one restore-time sort per tier: pre-v6 files hold tier
        # stashes with mid-prefix holes (their flushes never
        # compacted), and the shared-sort ring fold (ISSUE 20)
        # rank-merges against the standing canonical order
        tiers.append(t)
    lanes = jnp.asarray(np.asarray(meta["cascade_lanes"], np.uint32))
    pending: list[dict] = [{} for _ in config.intervals]
    if meta.get("cascade_pending"):
        if sketch_config is None:
            raise ValueError(
                f"checkpoint {path} holds pending cascade sketch blocks "
                "but the manager has no sketch config to type them"
            )
        restore_pending_blocks(
            pending, meta["cascade_pending"], arrays, sketch_config
        )
    return tiers, list(meta["cascade_watermarks"]), lanes, pending


@jax.jit
def _pack_stash(state: StashState) -> jnp.ndarray:
    """[4+T+M, S] u32: slot, key_hi, key_lo, valid, tags…, meters…"""
    return pack_u32_columns(
        state.slot, state.key_hi, state.key_lo, state.tags, state.meters,
        valid=state.valid,
    )


def _unpack_stash_impl(mat, dropped, num_tags: int) -> StashState:
    return StashState(
        slot=mat[0],
        key_hi=mat[1],
        key_lo=mat[2],
        valid=mat[3].astype(bool),
        tags=mat[4 : 4 + num_tags],
        meters=jax.lax.bitcast_convert_type(mat[4 + num_tags :], jnp.float32),
        dropped_overflow=jnp.asarray(dropped, dtype=jnp.int32),
    )


@partial(jax.jit, static_argnames=("num_tags",))
def _unpack_stash(mat, dropped, *, num_tags: int) -> StashState:
    return _unpack_stash_impl(mat, dropped, num_tags)


@jax.jit
def _pack_acc(acc: AccumState) -> jnp.ndarray:
    """[3+T+M, A] u32: slot, key_hi, key_lo, tags…, meters…"""
    return pack_u32_columns(acc.slot, acc.key_hi, acc.key_lo, acc.tags, acc.meters)


@partial(jax.jit, static_argnames=("num_tags",))
def _unpack_acc(mat, *, num_tags: int) -> AccumState:
    return AccumState(
        slot=mat[0],
        key_hi=mat[1],
        key_lo=mat[2],
        tags=mat[3 : 3 + num_tags],
        meters=jax.lax.bitcast_convert_type(mat[3 + num_tags :], jnp.float32),
    )


# the sharded twins: vmap the same pack/unpack over the device dim so
# one transfer per direction still covers the whole mesh
_pack_stash_sharded = jax.jit(
    jax.vmap(
        lambda s: pack_u32_columns(
            s.slot, s.key_hi, s.key_lo, s.tags, s.meters, valid=s.valid
        )
    )
)


@partial(jax.jit, static_argnames=("num_tags",))
def _unpack_stash_sharded(mats, dropped, *, num_tags: int) -> StashState:
    return jax.vmap(lambda m, d: _unpack_stash_impl(m, d, num_tags))(mats, dropped)


# ---------------------------------------------------------------------------
# crash-safe file layer (shared by both checkpoint kinds)


def _digest(arrays: dict[str, np.ndarray]) -> str:
    """sha256 over every array's (name, dtype, shape, bytes) — the
    content digest the loader verifies."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _write_checkpoint(path: str | Path, meta: dict, arrays: dict) -> None:
    """Serialize + ATOMICALLY replace: a kill at any point leaves
    either the previous checkpoint or the new one, never a torn file."""
    meta = dict(meta)
    meta["digest"] = _digest(arrays)
    buf = io.BytesIO()
    np.savez_compressed(
        buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8), **arrays
    )
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    chaos.maybe_fail(chaos.SITE_CHECKPOINT_IO)
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
        f.flush()
        # without the fsync a power loss after the rename can still
        # surface a renamed-but-empty file — the torn artifact the
        # atomic writer exists to rule out
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(path.parent, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # directory fsync is best-effort (not supported everywhere)


def _read_checkpoint(path: str | Path) -> tuple[dict, dict]:
    """→ (meta, arrays), with the loud-failure contract: truncation,
    corruption or a digest mismatch raise a ValueError naming the file
    and the failure — never a bare numpy/zipfile traceback. A missing
    file still raises FileNotFoundError (that is an operator error,
    not corruption)."""
    raw = Path(path).read_bytes()
    try:
        with np.load(io.BytesIO(raw)) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            arrays = {k: np.asarray(z[k]) for k in z.files if k != "meta"}
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} is truncated or corrupt ({type(e).__name__}: "
            f"{e}); restore from the previous checkpoint — the atomic "
            "writer never produces such a file, so this one was torn by "
            "an outside force (partial copy, disk fault)"
        ) from e
    want = meta.get("digest")
    if want is not None and want != _digest(arrays):
        raise ValueError(
            f"checkpoint {path} content digest mismatch — arrays were "
            "modified or corrupted after the save; refusing to resume "
            "from it"
        )
    return meta, arrays


def read_checkpoint_meta(path: str | Path) -> dict:
    """Meta dict only — reads just the meta member, no array
    decompression and no digest pass (the actual state load verifies
    the digest): recovery calls this on the startup critical path to
    read the journal barrier (journal_epoch/journal_offset) before
    deciding what to replay."""
    try:
        with np.load(Path(path)) as z:
            return json.loads(bytes(z["meta"]).decode())
    except FileNotFoundError:
        raise  # missing file = cold start / operator error, not corruption
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} is truncated or corrupt ({type(e).__name__}: "
            f"{e}); restore from the previous checkpoint"
        ) from e


def _check_version(meta: dict, path) -> None:
    v = meta.get("version")
    if v == 1:
        # v1 readers were dropped once two rounds had shipped with
        # v2-only writers (ROADMAP). No silent fallback: a v1 stash
        # predates the packed-word key fingerprint and could never
        # merge with freshly-hashed rows.
        raise ValueError(
            "checkpoint format v1 is unsupported (v1 load support was "
            "removed after v2 writers shipped); re-save the window "
            "state with a current writer"
        )
    if not (_MIN_READ_VERSION <= (v or 0) <= _VERSION):
        raise ValueError(
            f"checkpoint {path} version {v} not in "
            f"[{_MIN_READ_VERSION}, {_VERSION}]"
        )


# ---------------------------------------------------------------------------
# single-chip WindowManager


def save_window_state(wm: WindowManager, path: str | Path, *, extra_meta=None):
    """Snapshot `wm` to one .npz (atomic + digested). Returns the
    FlushedWindows that were still in flight in async_drain mode
    (deferred stats / dispatched flushes) — their rows have already
    left the stash, so the CALLER must emit them before treating the
    checkpoint as the resume point; an unsettled snapshot would
    silently lose those windows' documents. Empty list in sync mode.
    `extra_meta` (e.g. the feeder's journal barrier) merges into meta
    and comes back from `read_checkpoint_meta`."""
    from ..utils.spans import SPAN_CHECKPOINT_SAVE

    with wm.tracer.span(SPAN_CHECKPOINT_SAVE):
        in_flight = wm.settle()
        arrays = {"stash_packed": np.asarray(_pack_stash(wm.state))}
        if wm.acc is not None:
            arrays["acc_packed"] = np.asarray(_pack_acc(wm.acc))
        meta = {
            "version": _VERSION,
            "kind": "window",
            "num_tags": wm.tag_schema.num_fields,
            "dropped_overflow": int(np.asarray(wm.state.dropped_overflow)),
            "fill": wm.fill,
            "start_window": wm.start_window,
            "drop_before_window": wm.drop_before_window,
            "total_docs_in": wm.total_docs_in,
            "total_flushed": wm.total_flushed,
            "n_advances": wm.n_advances,
            "aux_count": wm.aux_count,
            "excess_word_hits": wm.excess_word_hits,
            "feeder_shed": wm.feeder_shed,
            "interval": wm.config.interval,
            "delay": wm.config.delay,
            "capacity": wm.config.capacity,
            "accum_batches": wm.config.accum_batches,
            "async_drain": wm.config.async_drain,
            "stats_ring": wm.config.stats_ring,
            # fold strategy rides the checkpoint: a merge-mode stash is
            # canonical (live sorted prefix) and must resume merge-mode;
            # a full-mode stash may hold per-window flush holes and must
            # NOT resume into the rank-merge
            "fold_mode": wm.config.fold_mode,
        }
        if wm.sk is not None:
            # v4: the per-window sketch plane rides the checkpoint so a
            # resumed manager keeps open windows' approximate state
            # bit-exact. settle() above drained the device pending
            # buffer AND every host-held block married its flush, so
            # the host dict must be empty here — anything left means a
            # block's window never flushed, which would silently vanish
            # across the resume.
            if wm._sketch_blocks:
                raise RuntimeError(
                    "sketch blocks for windows "
                    f"{sorted(wm._sketch_blocks)} are still held after "
                    "settle(); checkpointing would lose them"
                )
            arrays.update(_sketch_arrays(wm.sk))
            meta.update(_sketch_meta(wm.sk, wm.config.sketch))
        if wm.cascade is not None:
            # v5: tier stashes + watermarks + lanes + pending parent
            # blocks — settle() above drained every in-flight advance,
            # so the tier state is exactly the post-advance device
            # truth. Closed tier windows still held (including any the
            # settle itself just produced — async_drain can close a
            # minute during it) are NOT in the snapshot: they left the
            # tier stash, so they ride the in-flight return and the
            # CALLER must emit them, exactly the tier-0 contract.
            in_flight = in_flight + wm.pop_tier_windows()
            # tier accumulator rings fold into their stashes first —
            # the same "ring rows must reach the stash before the
            # snapshot" rule the main ingest ring follows, so the rings
            # themselves never serialize
            wm.cascade.settle_rings()
            c_meta, c_arrays = _cascade_save(
                wm.cascade.pending_blocks, wm.cascade.tiers,
                wm.cascade.watermarks, wm.cascade.lanes_dev,
                wm.config.cascade, sharded=False,
                tier_windows=wm.cascade.tier_windows_flushed,
            )
            meta.update(c_meta)
            arrays.update(c_arrays)
        if extra_meta:
            meta.update(extra_meta)
        # device profiling plane (ISSUE 12): the jitted pack kernels
        # above materialized device scratch of exactly these byte sizes
        # before the host copy — record the peak on the HBM ledger's
        # transient checkpoint_scratch row (steady-state bytes stay 0)
        from ..profiling.ledger import PLANE_CHECKPOINT, default_ledger

        default_ledger.note_transient(
            PLANE_CHECKPOINT, sum(a.nbytes for a in arrays.values())
        )
        _write_checkpoint(path, meta, arrays)
    return in_flight


def load_window_state(
    path: str | Path, tag_schema: TagSchema, meter_schema: MeterSchema,
    *, sketch_config: SketchConfig | None = None,
    cascade_config: CascadeConfig | None = None,
) -> WindowManager:
    """Rebuild a WindowManager from a checkpoint. The sketch plane
    restores from v4 files automatically; `sketch_config` asks for the
    plane explicitly when resuming a pre-v4 file into a sketch-enabled
    deployment (re-initialized with a loud log — never a crash). The
    cascade's tier state restores from v5 files the same way;
    `cascade_config` asks for the cascade explicitly when resuming a
    pre-v5 file into a cascade-enabled deployment (tiers re-initialized
    with a loud log)."""
    meta, arrays = _read_checkpoint(path)
    _check_version(meta, path)
    if meta.get("kind", "window") != "window":
        raise ValueError(
            f"checkpoint {path} is kind={meta.get('kind')!r}, not a "
            "single-chip window checkpoint (restore_sharded_state loads "
            "sharded ones)"
        )
    if sketch_config is None and "sketch" in meta:
        sketch_config = SketchConfig.from_meta(meta["sketch"])
    if cascade_config is None and "cascade" in meta:
        cascade_config = CascadeConfig.from_meta(meta["cascade"])
    cfg = WindowConfig(
        interval=meta["interval"],
        delay=meta["delay"],
        capacity=meta["capacity"],
        accum_batches=meta["accum_batches"],
        async_drain=meta.get("async_drain", False),
        stats_ring=meta.get("stats_ring", 1),
        fold_mode=meta.get("fold_mode", "full"),
        sketch=sketch_config,
        cascade=cascade_config,
    )
    wm = WindowManager(cfg, tag_schema, meter_schema)
    t = tag_schema.num_fields
    if meta["num_tags"] != t:
        # the packed split is shape-valid for ANY num_tags — a
        # mismatch would bit-cast misaligned words into meters
        # silently, so schema drift must fail loudly
        raise ValueError(
            f"checkpoint tag schema width {meta['num_tags']} != "
            f"{t} ({tag_schema.__class__.__name__}); cannot unpack"
        )
    # one upload + one jitted split per direction
    wm.state = _unpack_stash(
        jnp.asarray(arrays["stash_packed"]),
        np.int32(meta["dropped_overflow"]),
        num_tags=t,
    )
    if "acc_packed" in arrays:
        wm.acc = _unpack_acc(jnp.asarray(arrays["acc_packed"]), num_tags=t)
    wm.fill = meta["fill"]
    wm.start_window = meta["start_window"]
    wm.drop_before_window = meta["drop_before_window"]
    wm.total_docs_in = meta["total_docs_in"]
    wm.total_flushed = meta["total_flushed"]
    # telemetry counters landed after v2 writers; absent = 0
    wm.n_advances = meta.get("n_advances", 0)
    wm.aux_count = meta.get("aux_count", 0)
    wm.excess_word_hits = meta.get("excess_word_hits", 0)
    wm.feeder_shed = meta.get("feeder_shed", 0)
    if cfg.sketch is not None:
        wm.sk = _restore_sketch(meta, arrays, cfg.sketch, cfg.ring, path)
        wm.sketch_rows = int(meta.get("sketch_rows", 0))
        wm.sketch_shed = int(meta.get("sketch_shed", 0))
    if cfg.cascade is not None:
        got = _restore_cascade_tiers(
            meta, arrays, cfg.cascade, t, path, sharded=False,
            sketch_config=cfg.sketch,
        )
        if got is not None:
            casc = wm.cascade
            casc.tiers, casc.watermarks, casc.lanes_dev, casc.pending_blocks = got
            casc.tier_windows_flushed = int(meta.get("cascade_tier_windows", 0))
            wm.cascade_rows = int(meta["cascade_lanes"][0])
            wm.cascade_shed = int(meta["cascade_lanes"][1])
    # the save settled (ring drained), so the restored host span IS
    # the device gate state — mirror it back onto the device
    wm._sync_device_sw()
    return wm


# ---------------------------------------------------------------------------
# sharded ShardedWindowManager


def _validate_ownership_transfer(meta: dict, topo, shard_group: int,
                                 path) -> None:
    """Elastic-topology restore contract (ISSUE 15): same-process
    restores (the r11/r18 kill-and-recover path) need nothing; a
    cross-process restore is legal ONLY through a handover manifest
    (`parallel/rebalance.transfer_manifest`) naming this process and
    this topology epoch. Every refusal names both epochs — the one the
    checkpoint was published under and the one this process restores
    into — so a stale pre-handover file is diagnosable at a glance."""
    saved_pi = meta.get("process_index")
    if saved_pi is None:
        return  # pre-topology file: the normal restore
    saved_epoch = meta.get("topology_epoch", 0)
    hand = meta.get("handover")
    here = (
        f"process {topo.process_index} at topology epoch "
        f"{topo.topology_epoch}"
    )
    if int(saved_pi) == topo.process_index:
        # same host: the r11/r18 kill-and-recover path — EXCEPT a
        # handover checkpoint that transfers the group AWAY. The old
        # owner restoring its own handover barrier would resurrect a
        # group another process now serves (split-brain over one
        # key-hash range); only the named to_process may load it.
        if hand is not None and int(
            hand.get("to_process", -1)
        ) != topo.process_index:
            raise ValueError(
                f"checkpoint {path} is the handover barrier that "
                f"transferred group {hand.get('group')} to process "
                f"{hand.get('to_process')} (epoch "
                f"{hand.get('topology_epoch')}); {here} released it — "
                "restoring it here would serve the group on two hosts "
                "at once"
            )
        return
    if hand is None:
        raise ValueError(
            f"checkpoint {path} was saved by process {saved_pi} at "
            f"topology epoch {saved_epoch} with NO ownership-transfer "
            f"manifest, but {here} is restoring it — a stale "
            "(pre-handover) checkpoint cannot change hosts; re-run the "
            "handover so the owner publishes a manifest-bearing barrier "
            "checkpoint"
        )
    if int(hand.get("to_process", -1)) != topo.process_index:
        raise ValueError(
            f"checkpoint {path} transfers group {hand.get('group')} to "
            f"process {hand.get('to_process')} (epoch "
            f"{hand.get('topology_epoch')}), but {here} is restoring it"
        )
    if int(hand.get("group", -1)) != int(shard_group):
        raise ValueError(
            f"checkpoint {path} ownership-transfer manifest names group "
            f"{hand.get('group')} but this manager serves group "
            f"{shard_group}"
        )
    if int(hand.get("topology_epoch", -1)) != topo.topology_epoch:
        raise ValueError(
            f"checkpoint {path} was handed over under topology epoch "
            f"{hand.get('topology_epoch')} but {here} — the checkpoint "
            "is stale relative to this rebalance (or this process never "
            "applied the move); publish a fresh handover barrier"
        )


def save_sharded_state(swm, path: str | Path, *, extra_meta=None) -> list:
    """Snapshot a ShardedWindowManager (kind="sharded"). Folds the
    accumulator ring first (sharded flushes are synchronous, so unlike
    async_drain nothing else is deferred), packs every device stash in
    one vmapped call, and writes sketch planes alongside. Returns the
    held closed tier windows ((interval, DocBatch) pairs — host-side
    only, NOT in the snapshot; the caller must emit them), [] without a
    cascade — the save_window_state in-flight contract."""
    from ..utils.spans import SPAN_CHECKPOINT_SAVE

    in_flight: list = []
    with swm.tracer.span(SPAN_CHECKPOINT_SAVE):
        swm._fold()  # ring rows must reach the stash before the snapshot
        arrays = {
            "stash_packed": np.asarray(_pack_stash_sharded(swm.stash)),
            "dropped": np.asarray(swm.stash.dropped_overflow),
        }
        # v4: per-window sketch lanes, one array per lane with the
        # device dim leading (pend_n/rows/shed are [D] vectors in meta)
        arrays.update(_sketch_arrays(swm.sketches))
        c = swm.pipe.config
        meta = {
            "version": _VERSION,
            "kind": "sharded",
            "num_tags": int(arrays["stash_packed"].shape[1]) - 4
            - int(swm.stash.meters.shape[1]),
            "n_devices": swm.pipe.n_devices,
            "capacity_per_device": c.capacity_per_device,
            "interval": swm.interval,
            "delay": swm.delay,
            "fold_mode": c.fold_mode,
            "start_window": swm.start_window,
            "drop_before_window": swm.drop_before_window,
            "total_docs_in": swm.total_docs_in,
            "total_flushed": swm.total_flushed,
            "n_advances": swm.n_advances,
        }
        # multi-host placement (ISSUE 14): the mesh topology this group
        # was saved under — process_count × devices_per_group and the
        # group index — so a restore onto the wrong host/topology fails
        # loudly at load, not as a shape error deep in shard_map
        topo = getattr(swm.pipe, "topology", None)
        if topo is not None:
            meta.update(topo.describe())
            meta["shard_group"] = swm.pipe.shard_group
        meta.update(_sketch_meta(swm.sketches, c.sketch_config()))
        meta["sketch_ring"] = c.sketch_ring
        if swm._tier_ratios:
            # held tier windows are host-side only (not in the
            # snapshot) — return them so the caller emits them, the
            # same in-flight contract as save_window_state
            in_flight = swm.pop_tier_docbatches()
            swm.settle_tier_rings()  # ring rows reach the stash first
            c_meta, c_arrays = _cascade_save(
                swm._tier_pending_blocks, swm.tier_stashes,
                swm.tier_watermarks, swm.cascade_lanes,
                CascadeConfig(
                    intervals=swm._cascade_intervals,
                    capacity=c.cascade_capacity,
                ),
                sharded=True, tier_windows=swm.tier_windows_flushed,
            )
            meta.update(c_meta)
            arrays.update(c_arrays)
        if extra_meta:
            meta.update(extra_meta)
        # device profiling plane (ISSUE 12): the jitted pack kernels
        # above materialized device scratch of exactly these byte sizes
        # before the host copy — record the peak on the HBM ledger's
        # transient checkpoint_scratch row (steady-state bytes stay 0)
        from ..profiling.ledger import PLANE_CHECKPOINT, default_ledger

        default_ledger.note_transient(
            PLANE_CHECKPOINT, sum(a.nbytes for a in arrays.values())
        )
        _write_checkpoint(path, meta, arrays)
    return in_flight


def restore_sharded_state(swm, path: str | Path):
    """Load a sharded checkpoint INTO a freshly-built
    ShardedWindowManager (the caller owns mesh construction — a
    checkpoint cannot rebuild a Mesh). Validates device count, schema
    width and fold mode loudly; re-shards every plane onto the
    manager's mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..datamodel.schema import TAG_SCHEMA

    meta, arrays = _read_checkpoint(path)
    _check_version(meta, path)
    if meta.get("kind") != "sharded":
        raise ValueError(
            f"checkpoint {path} is kind={meta.get('kind')!r}, not a "
            "sharded checkpoint (load_window_state loads single-chip ones)"
        )
    if meta["n_devices"] != swm.pipe.n_devices:
        raise ValueError(
            f"checkpoint {path} was saved on {meta['n_devices']} devices; "
            f"this mesh has {swm.pipe.n_devices} — per-device stashes "
            "cannot be re-split"
        )
    # multi-host mesh topology (ISSUE 14): device count × process count
    # and group placement must match the restore topology exactly —
    # loudly, instead of a shape error deep in shard_map (or worse, a
    # group silently serving another host's keys)
    topo = getattr(swm.pipe, "topology", None)
    ck_pc = meta.get("process_count")
    if topo is not None:
        topo.validate_restore(meta, path)
        ck_group = meta.get("shard_group")
        if ck_group is not None and int(ck_group) != swm.pipe.shard_group:
            # (group ownership itself is enforced at pipeline
            # construction — group_mesh refuses remote groups)
            raise ValueError(
                f"checkpoint {path} holds shard group {ck_group} but this "
                f"manager serves group {swm.pipe.shard_group} — restoring "
                "it here would serve another group's key-hash range"
            )
        # elastic topology (ISSUE 15): a checkpoint restoring onto a
        # DIFFERENT process must carry an ownership-transfer manifest
        # published for THIS topology epoch — a stale (pre-handover)
        # save, or one published under some other rebalance, would
        # silently split the group's key range across two owners
        _validate_ownership_transfer(meta, topo, swm.pipe.shard_group, path)
    elif ck_pc is not None and (
        int(ck_pc) > 1 or int(meta.get("n_groups", 1)) > 1
    ):
        # multi-process OR multi-group: either way the checkpoint holds
        # one shard group's slice of a partitioned key space — a bare
        # manager restoring it would silently serve the FULL key range
        # with only that group's stashes
        raise ValueError(
            f"checkpoint {path} was saved under a sharded mesh topology "
            f"({ck_pc} process(es), {meta.get('n_groups')} shard groups); "
            "restoring into a topology-less manager would collapse the "
            "key-hash placement — build the pipeline from a MeshTopology "
            "(parallel/topology.py)"
        )
    t = TAG_SCHEMA.num_fields
    if meta["num_tags"] != t:
        raise ValueError(
            f"checkpoint tag schema width {meta['num_tags']} != {t}; "
            "cannot unpack"
        )
    if meta.get("fold_mode", "full") != swm.pipe.config.fold_mode:
        raise ValueError(
            f"checkpoint fold_mode={meta.get('fold_mode')!r} != pipeline "
            f"fold_mode={swm.pipe.config.fold_mode!r} — the stash layout "
            "contract differs between modes (canonical prefix vs holes)"
        )
    if meta["capacity_per_device"] != swm.pipe.config.capacity_per_device:
        raise ValueError(
            f"checkpoint capacity_per_device={meta['capacity_per_device']} "
            f"!= pipeline {swm.pipe.config.capacity_per_device} — stash "
            "shape disagrees with the compiled config"
        )
    if meta["interval"] != swm.interval or meta["delay"] != swm.delay:
        raise ValueError(
            f"checkpoint window timing (interval={meta['interval']}, "
            f"delay={meta['delay']}) != manager (interval={swm.interval}, "
            f"delay={swm.delay}) — start_window/drop_before_window are "
            "window indices in units of interval and would be silently "
            "reinterpreted"
        )
    if "sketch_ring" in meta and meta["sketch_ring"] != swm.pipe.config.sketch_ring:
        raise ValueError(
            f"checkpoint sketch_ring={meta['sketch_ring']} != pipeline "
            f"sketch_ring={swm.pipe.config.sketch_ring} — per-window slot "
            "layout disagrees"
        )
    stash = _unpack_stash_sharded(
        jnp.asarray(arrays["stash_packed"]),
        jnp.asarray(arrays["dropped"], dtype=jnp.int32),
        num_tags=t,
    )
    # sketch planes: v4 restores bit-exact; v2/v3 files carry the old
    # span-global planes (or none) — re-initialize per-window planes
    # with a loud log, never a crash (satellite contract)
    sketches = _restore_sketch(
        meta, arrays, swm.pipe.config.sketch_config(),
        swm.pipe.config.sketch_ring, path,
        sharded_dim=swm.pipe.n_devices,
    )
    spec = NamedSharding(swm.pipe.mesh, P(swm.pipe.axes))
    swm.stash = jax.tree.map(lambda x: jax.device_put(x, spec), stash)
    swm.sketches = jax.tree.map(lambda x: jax.device_put(x, spec), sketches)
    if swm._tier_ratios:
        got = _restore_cascade_tiers(
            meta, arrays,
            CascadeConfig(
                intervals=swm._cascade_intervals,
                capacity=swm.pipe.config.cascade_capacity,
            ),
            t, path, sharded=True, sketch_config=swm._sk_cfg,
        )
        if got is not None:
            tiers, wms, lanes, pending = got
            swm.tier_stashes = [
                jax.tree.map(lambda x: jax.device_put(x, spec), ts)
                for ts in tiers
            ]
            # rings settled at save — restore them empty (lazy re-init)
            swm.tier_accs = [None] * len(tiers)
            swm.tier_fills = [None] * len(tiers)
            swm.tier_watermarks = wms
            swm.cascade_lanes = jax.device_put(lanes, spec)
            swm._tier_pending_blocks = pending
            swm.tier_windows_flushed = int(meta.get("cascade_tier_windows", 0))
            lanes_np = np.asarray(meta["cascade_lanes"], np.int64)
            swm.cascade_rows = int(lanes_np[:, 0].sum())
            swm.cascade_shed = int(lanes_np[:, 1].sum())
    swm.acc = None  # re-sized on the first post-restore batch
    swm.fill = 0
    swm._fold_rows_dev = None
    swm.start_window = meta["start_window"]
    swm.drop_before_window = meta["drop_before_window"]
    swm.total_docs_in = meta["total_docs_in"]
    swm.total_flushed = meta["total_flushed"]
    swm.n_advances = meta.get("n_advances", 0)
    return swm
