"""Document fanout — vectorized `fill_l4_stats` / `fill_l7_stats`.

The reference emits up to 4 documents per accumulated flow
(collector.rs:500-607 for L4, :694-821 for L7): one *single-ended* doc per
endpoint whose direction is known (client view and server view) and one
*edge* doc per known direction (plus a rest/edge doc when both directions
are unknown). Data-dependent emission counts don't exist on TPU, so we
always emit a fixed [4, N] block with a validity mask — lane 0/1 are the
ep0/ep1 single docs, lane 2/3 the ep0/ep1 edge docs (lane 3 doubles as
the both-directions-unknown rest doc).

Tag construction mirrors get_single_tagger / get_edge_tagger
(collector.rs:882-1095): inactive-IP zeroing, Internet-EPC zeroing,
vip-interface MAC gating, server-port suppression
(`ignore_server_port`, collector.rs:877), OTel epc clamping
(get_l3_epc_id, collector.rs:1097), the both-hosts-inactive record drop
(collector.rs:489-493, :684-687). Columns not covered by the doc's Code
are zeroed, which is what makes "fingerprint all key columns" equivalent
to StashKey equality.

L4 vs L7 is one code path (`_make_lanes(app=...)`) differing only in:
  * CodeIds (`*_APP` variants) and meter_id (Flow vs App);
  * the L7 gate l7_protocol != Unknown (OTel exempt, collector.rs:794,816);
  * the single-doc direction gate: L4 takes only pure c/s/local
    directions, L7 additionally admits side-carrying directions
    (c-p/s-p/c-app/s-app/app) for non-Packet signal sources
    (collector.rs:796-803);
  * edge-doc signal gate: L4 edge docs exist only for Packet/XFlow
    (fill_edge_l4_stats, :600-607), L7 edge docs have no gate (:813-821);
  * the app meter is never reversed (both endpoint views share one RED
    meter, :737-787), while the L4 server single doc gets the tx/rx-
    reversed flow meter (meter.rs:169-176);
  * L7 docs carry l7_protocol / endpoint_hash / biz_type / time_span
    key columns.

Not emitted here: the ACL/UsageMeter policy docs (collector.rs:440-487)
come from the policy module's own minute rollup
(agent/policy.py PolicyMeterAggregator), not the per-flow fanout.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.code import CodeId, Direction, MeterId, SignalSource
from ..datamodel.schema import FLOW_METER, TAG_SCHEMA

_T = TAG_SCHEMA

# Docs emitted per flow: ep0/ep1 single + ep0/ep1 edge (lane 3 doubles as
# the rest doc). Fill accounting everywhere keys off this constant.
FANOUT_LANES = 4

TCP = 6
UDP = 17
EPC_INTERNET_U16 = 0xFFFE  # -2 as u16 (EPC_INTERNET, npb_pcap_policy)

_DIR_SIDE_MASK = 0xF8  # document.rs MASK_SIDE
_DIR_CS_MASK = 0x7


@dataclasses.dataclass(frozen=True)
class FanoutConfig:
    """CollectorConfig subset (agent/src/config/handler.rs CollectorAccess)."""

    inactive_ip_aggregation: bool = False
    inactive_server_port_aggregation: bool = False
    agent_id: int = 1
    global_thread_id: int = 1


def _u32(x):
    return jnp.asarray(x, dtype=jnp.uint32)


def _tap_side(direction: jnp.ndarray) -> jnp.ndarray:
    # TapSide::from(Direction) (document.rs:243-264): identity on the bit
    # pattern, with NONE → REST (both 0).
    return direction


def _make_lanes(tags: dict, meters_t: jnp.ndarray, valid: jnp.ndarray, config: FanoutConfig, app: bool):
    """Build the four (cols, lane_valid, lane_meter_t) lanes.

    meters_t is column-major [M, N]; lane meters come back [M, N]."""
    n = meters_t.shape[1]
    zero = jnp.zeros((n,), dtype=jnp.uint32)

    dir0 = tags["direction0"]
    dir1 = tags["direction1"]
    sig = tags["signal_source"]
    is_otel = sig == jnp.uint32(SignalSource.OTEL)
    is_packet = sig == jnp.uint32(SignalSource.PACKET)
    is_pkt_or_xflow = is_packet | (sig == jnp.uint32(SignalSource.XFLOW))
    proto = tags["protocol"]

    active0 = tags["is_active_host0"] != 0
    active1 = tags["is_active_host1"] != 0
    vip0 = tags["is_vip0"] != 0
    vip1 = tags["is_vip1"] != 0

    # Whole-record gates: both-hosts-inactive drop (collector.rs:489-493,
    # :684-687) and, for L7, the unknown-protocol drop (:794,:816).
    if config.inactive_ip_aggregation:
        valid = valid & (active0 | active1)
    if app:
        l7_known = (tags["l7_protocol"] != 0) | is_otel
        valid = valid & l7_known
    else:
        # eBPF-sourced flows carry no L4 packet meters — the reference
        # never feeds them to the L4 QuadrupleGenerator
        # (quadruple_generator.rs:420-423 skips SignalSource::EBPF);
        # they exist only on the L7/App plane.
        valid = valid & (sig != jnp.uint32(SignalSource.EBPF))

    # reversed meter for the L4 server-endpoint single doc (meter.rs:169-176)
    if app:
        meters_rev_t = meters_t
    else:
        perm = jnp.asarray(FLOW_METER.reverse_perm)
        zmask = jnp.asarray(~FLOW_METER.reverse_zero_mask, dtype=meters_t.dtype)
        meters_rev_t = meters_t[perm, :] * zmask[:, None]

    # ignore_server_port (collector.rs:877)
    inactive_service = tags["is_active_service"] == 0
    ignore_port = (inactive_service & config.inactive_server_port_aggregation) | (
        (proto != jnp.uint32(TCP)) & (proto != jnp.uint32(UDP))
    )
    dst_port = jnp.where(ignore_port, zero, tags["server_port"])

    # get_l3_epc_id (collector.rs:1097): negative epc + OTel → 0. EPC ids
    # are i16 semantically — fold to u16 first so a sign-extended u32
    # (0xFFFFFFFE) and the folded form (0xFFFE) compare equal.
    def epc_fix(epc):
        epc = epc & jnp.uint32(0xFFFF)
        is_neg = epc >= jnp.uint32(0x8000)  # sign-folded i16
        return jnp.where(is_neg & is_otel, zero, epc)

    epc0 = epc_fix(tags["l3_epc_id"])
    epc1 = epc_fix(tags["l3_epc_id1"])

    ip0 = [tags[f"ip0_w{w}"] for w in range(4)]
    ip1 = [tags[f"ip1_w{w}"] for w in range(4)]

    def masked_ip(ip, keep):
        return [jnp.where(keep, w, zero) for w in ip]

    meter_id = MeterId.APP if app else MeterId.FLOW
    shared_cols = {
        "meter_id": jnp.full((n,), meter_id, jnp.uint32),
        "global_thread_id": jnp.full((n,), config.global_thread_id, jnp.uint32),
        "agent_id": jnp.full((n,), config.agent_id, jnp.uint32),
        "is_ipv6": tags["is_ipv6"],
        "protocol": proto,
        "tap_type": tags["tap_type"],
        "signal_source": sig,
        "pod_id": tags["pod_id"],
    }
    if app:
        shared_cols.update(
            l7_protocol=tags["l7_protocol"],
            endpoint_hash=tags["endpoint_hash"],
            biz_type=tags["biz_type"],
            time_span=tags["time_span"],
        )

    # ---- single docs (lanes 0, 1) -------------------------------------
    def single_lane(ep):
        d = dir0 if ep == 0 else dir1
        active = active0 if ep == 0 else active1
        vip = vip0 if ep == 0 else vip1
        epc = epc0 if ep == 0 else epc1
        ip = ip0 if ep == 0 else ip1
        gpid = tags["gpid0"] if ep == 0 else tags["gpid1"]
        mac = (tags["mac0_hi"], tags["mac0_lo"]) if ep == 0 else (tags["mac1_hi"], tags["mac1_lo"])

        # emission gate (fill_single_l4_stats / fill_single_l7_stats):
        # pure c/s/local directions; L7 additionally admits sided
        # directions for non-Packet sources.
        pure_dir = (d & jnp.uint32(_DIR_SIDE_MASK)) == 0
        dir_ok = (pure_dir | ~is_packet) if app else pure_dir
        lane_valid = valid & (d != 0) & dir_ok
        if config.inactive_ip_aggregation:
            lane_valid = lane_valid & active

        # ip rewrite (get_single_tagger, Managed mode)
        if config.inactive_ip_aggregation:
            keep_ip = active
        else:
            if ep == 0:
                keep_ip = (epc0 != jnp.uint32(EPC_INTERNET_U16)) | is_otel
            else:
                keep_ip = jnp.ones((n,), dtype=bool)
        ip_w = masked_ip(ip, keep_ip)

        has_mac = vip | (d == jnp.uint32(Direction.LOCAL_TO_LOCAL))
        mac_hi = jnp.where(has_mac, mac[0], zero)
        mac_lo = jnp.where(has_mac, mac[1], zero)
        code_id = jnp.where(
            has_mac,
            jnp.uint32(CodeId.SINGLE_MAC_IP_PORT_APP if app else CodeId.SINGLE_MAC_IP_PORT),
            jnp.uint32(CodeId.SINGLE_IP_PORT_APP if app else CodeId.SINGLE_IP_PORT),
        )
        # "If the resource is located on the client, the service port is
        # ignored" (collector.rs:948-955)
        port = zero if ep == 0 else dst_port

        cols = {
            **shared_cols,
            "code_id": code_id,
            "ip0_w0": ip_w[0],
            "ip0_w1": ip_w[1],
            "ip0_w2": ip_w[2],
            "ip0_w3": ip_w[3],
            "l3_epc_id": epc,
            "mac0_hi": mac_hi,
            "mac0_lo": mac_lo,
            "direction": d,
            "tap_side": _tap_side(d),
            "server_port": port,
            "gpid0": gpid,
        }
        return cols, lane_valid, (meters_t if ep == 0 else meters_rev_t)

    # ---- edge docs (lanes 2, 3) ---------------------------------------
    both_none = (dir0 == 0) & (dir1 == 0)

    def edge_lane(ep):
        d = dir0 if ep == 0 else dir1
        if ep == 1:
            # rest-doc fold: both directions unknown → direction None
            # (or App for OTel), tap_side Rest (collector.rs:584-607)
            d = jnp.where(
                both_none,
                jnp.where(is_otel, jnp.uint32(Direction.APP), jnp.uint32(Direction.NONE)),
                d,
            )
            lane_valid = valid & ((dir1 != 0) | both_none)
        else:
            lane_valid = valid & (d != 0)
        if not app:
            # L4 edge docs exist only for Packet/XFlow (fill_edge_l4_stats)
            lane_valid = lane_valid & is_pkt_or_xflow

        # ip rewrite (get_edge_tagger, Managed)
        if config.inactive_ip_aggregation:
            keep0, keep1 = active0, active1
        else:
            keep0 = (epc0 != jnp.uint32(EPC_INTERNET_U16)) | is_otel
            keep1 = jnp.ones((n,), dtype=bool)
        src_ip = masked_ip(ip0, keep0)
        dst_ip = masked_ip(ip1, keep1)

        # vip gating of macs except local-local (collector.rs:1030-1043)
        is_ll = d == jnp.uint32(Direction.LOCAL_TO_LOCAL)
        keep_mac0 = vip0 | is_ll
        keep_mac1 = vip1 | is_ll
        mac0_hi = jnp.where(keep_mac0, tags["mac0_hi"], zero)
        mac0_lo = jnp.where(keep_mac0, tags["mac0_lo"], zero)
        mac1_hi = jnp.where(keep_mac1, tags["mac1_hi"], zero)
        mac1_lo = jnp.where(keep_mac1, tags["mac1_lo"], zero)
        any_mac = (mac0_hi | mac0_lo | mac1_hi | mac1_lo) != 0
        code_id = jnp.where(
            any_mac,
            jnp.uint32(CodeId.EDGE_MAC_IP_PORT_APP if app else CodeId.EDGE_MAC_IP_PORT),
            jnp.uint32(CodeId.EDGE_IP_PORT_APP if app else CodeId.EDGE_IP_PORT),
        )

        cols = {
            **shared_cols,
            "code_id": code_id,
            "ip0_w0": src_ip[0],
            "ip0_w1": src_ip[1],
            "ip0_w2": src_ip[2],
            "ip0_w3": src_ip[3],
            "ip1_w0": dst_ip[0],
            "ip1_w1": dst_ip[1],
            "ip1_w2": dst_ip[2],
            "ip1_w3": dst_ip[3],
            "l3_epc_id": epc0,
            "l3_epc_id1": epc1,
            "mac0_hi": mac0_hi,
            "mac0_lo": mac0_lo,
            "mac1_hi": mac1_hi,
            "mac1_lo": mac1_lo,
            "direction": d,
            "tap_side": _tap_side(d),
            "server_port": dst_port,
            "tap_port": tags["tap_port"],
            "gpid0": tags["gpid0"],
            "gpid1": tags["gpid1"],
        }
        return cols, lane_valid, meters_t

    return [single_lane(0), single_lane(1), edge_lane(0), edge_lane(1)]


def _fanout_impl(tags: dict, meters: jnp.ndarray, valid: jnp.ndarray, config: FanoutConfig, app: bool):
    meters_t = jnp.transpose(meters)  # [M, N] — column-major from here on
    n = meters_t.shape[1]
    lanes = _make_lanes(tags, meters_t, valid, config, app)

    t_count = _T.num_fields
    zero = jnp.zeros((n,), dtype=jnp.uint32)
    lane_tag_blocks, lane_valids, lane_meters = [], [], []
    for cols, lv, mt in lanes:
        rows = [zero] * t_count
        for name, arr in cols.items():
            rows[_T.index(name)] = _u32(arr)
        lane_tag_blocks.append(jnp.stack(rows))  # [T, n]
        lane_valids.append(lv)
        lane_meters.append(mt)

    doc_tags = jnp.concatenate(lane_tag_blocks, axis=1)  # [T, 4n], lane-major
    doc_meters = jnp.concatenate(lane_meters, axis=1)  # [M, 4n]
    doc_valid = jnp.concatenate(lane_valids)
    ts = jnp.concatenate([tags["timestamp"]] * 4)
    return doc_tags, doc_meters, ts, doc_valid


@partial(jax.jit, static_argnames=("config",))
def fanout_l4(tags: dict, meters: jnp.ndarray, valid: jnp.ndarray, config: FanoutConfig):
    """FlowBatch columns → column-major doc arrays.

    Args:
      tags: dict of [N] u32 columns named per FLOW_RECORD_TAG_FIELDS.
      meters: [N, M] f32 FlowMeter rows (client-view; transposed to
        column-major internally — host batches stay row-major).
      valid: [N] bool.
    Returns:
      (doc_tags [T, 4N] u32, doc_meters [M, 4N] f32, doc_ts [4N] u32,
       doc_valid [4N] bool), lane-major along the row axis.
    """
    return _fanout_impl(tags, meters, valid, config, app=False)


@partial(jax.jit, static_argnames=("config",))
def fanout_l7(tags: dict, meters: jnp.ndarray, valid: jnp.ndarray, config: FanoutConfig):
    """AppMeterWithFlow columns → L7 doc arrays of shape [4N, ...].

    Same contract as fanout_l4 with meters following APP_METER; see the
    module docstring for the L4/L7 semantic deltas.
    """
    return _fanout_impl(tags, meters, valid, config, app=True)
