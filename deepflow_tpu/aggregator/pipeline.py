"""Flow-metrics rollup pipelines (L4 network + L7 application) — the
end-to-end device slice.

Composes: fanout (fill_l4_stats / fill_l7_stats) → key fingerprint →
windowed stash merge → flush → DocBatch emission. This is the TPU
replacement for the reference chains QuadrupleGenerator::inject_flow →
Collector::collect_l4 → Stash::add → flush_stats and
L7QuadrupleGenerator → L7Collector::collect_l7 (SURVEY §3.1), collapsed
into one jit step per batch plus a host-driven window controller.

Since r7 the whole per-batch slice — optional pre-reduce, fanout,
fingerprint, late-arrival gate, window bookkeeping, ring append — runs
as ONE jitted call per batch (`RollupPipeline._build_step`): the ~37 tag
columns upload as a single packed [T, N] matrix (every pytree leaf is a
separate transfer through the tunnel, PERF.md §8) and the only per-batch
download is the 5-scalar stats vector the window controller reads
(window.py module docstring has the full sync budget).
"""

from __future__ import annotations

import dataclasses
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.batch import DocBatch, FlowBatch
from ..datamodel.code import DOC_KEY_PACK, RAW_TAG_PACK, DocumentFlag, pack_tag_words
from ..datamodel.schema import APP_METER, FLOW_METER, TAG_SCHEMA, MeterSchema
from ..ops.hashing import fingerprint64_words
from ..utils.spans import JitCacheMonitor
from ..utils.stats import register_countable
from .fanout import FANOUT_LANES, FanoutConfig, fanout_l4, fanout_l7
from .stash import _append_impl
from .window import (
    FlushedWindow,
    WindowConfig,
    WindowManager,
    batch_counter_block,
    sketch_inputs_from_columns,
    sketch_span_bounds,
)
from .sketchplane import SketchConfig, sketch_plane_step

#: census service-key ordinal — one per pipeline instance, so profile
#: attribution never aliases across concurrently-live pipelines
_PIPELINE_SEQ = itertools.count(1)

_KEY_COLS = np.nonzero(TAG_SCHEMA.key_mask)[0].astype(np.int32)
# DOC_KEY_PACK covers exactly the TAG_SCHEMA key columns — drift between
# the schema and the packing widths table fails at import, not at runtime.
assert set(DOC_KEY_PACK.field_names()) == {
    f.name for f in TAG_SCHEMA.fields if f.key
}, "DOC_KEY_WIDTHS out of sync with TAG_SCHEMA key columns"


def _doc_fingerprint(doc_tags, with_excess: bool = False):
    """(hi, lo[, excess]) over a [T, N] doc tag matrix via the packed-word
    plan: the key columns are bin-packed into ~22 u32 words built once
    (datamodel/code.py), and both murmur seeds fold the words instead
    of 32 raw columns (PERF.md §9d). Row extraction from the
    column-major matrix is free (contiguous [N] slices).

    With `with_excess`, also returns the packing-guard excess word
    ([N] u32, zero for rows whose tag values honor the declared
    DOC_KEY_WIDTHS) so the fused step can count contract violations in
    the device counter block."""
    cols = {f: doc_tags[TAG_SCHEMA.index(f)] for f in DOC_KEY_PACK.field_names()}
    words = pack_tag_words(cols, DOC_KEY_PACK, jnp)
    hi, lo = fingerprint64_words(words)
    if with_excess:
        # the excess word is the last packed word whenever the plan has
        # narrow fields (pack_tag_words contract)
        excess = words[-1] if DOC_KEY_PACK.packed else jnp.zeros_like(hi)
        return hi, lo, excess
    return hi, lo


def batch_prereduce(tags, meters, valid, interval, cap, sum_cols, max_cols):
    """Batch-local pre-reduce BEFORE fanout: group raw rows by their
    full tag fingerprint (incl. timestamp) and reduce meters. Exact:
    identical raw tag rows produce identical doc rows in every fanout
    lane, and the lanes' meter transforms are column permutations/
    copies, which commute with per-column sum/max (PERF.md §7c). This
    collapses the dup factor (10k-tuple rollup workloads repeat keys
    within a batch) so the fold sorts ~1 row/record instead of 4.
    Returns (tags, meters [cap, M], valid, dropped) — rows beyond `cap`
    unique keys are shed; callers count `dropped` (newest-shed
    stance)."""
    from ..ops.segment import groupby_reduce

    names = sorted(tags)
    cols = [jnp.asarray(tags[k], jnp.uint32) for k in names]
    tags_t = jnp.stack(cols)
    # fingerprint the PACKED words, not the raw columns: ~23 fold rounds
    # instead of 37 per seed, built once for both seeds (PERF.md §9d;
    # the [T, N] stack stays only as the groupby payload — r5 bisect V2
    # already showed hashing through it wastes a materialization)
    hi, lo = fingerprint64_words(pack_tag_words(tags, RAW_TAG_PACK, jnp))
    slot = jnp.asarray(tags["timestamp"], jnp.uint32) // jnp.uint32(interval)
    g = groupby_reduce(
        slot, hi, lo, tags_t, meters, valid,
        sum_cols, max_cols, out_capacity=cap,
    )
    r_tags = {k: g.tags[i] for i, k in enumerate(names)}
    dropped = jnp.maximum(g.num_segments - cap, 0)
    return r_tags, jnp.transpose(g.meters), g.seg_valid, dropped


def make_ingest_step(fanout_config: FanoutConfig, interval: int = 1, app: bool = False,
                     batch_unique_cap: int | None = None, fold_mode: str = "full",
                     sketch_config: "SketchConfig | None" = None, delay: int = 2):
    """Build the pure device step pair: FlowBatch columns → stash.

    Returns (append, fold):

      (stash, acc) = append(stash, acc, offset, tags, meters, valid)
      (stash, acc) = fold(stash, acc)

    With `sketch_config` set (ISSUE 8), append grows the per-window
    sketch plane in the same traced step:

      (stash, acc, sk) = append(stash, acc, offset, sk, tags, meters,
                                valid, start_window)

    where `sk` is a sketchplane.SketchState and `start_window` the
    host's open-span gate (the plane derives its close bound from the
    batch itself, exactly like the window managers — `delay` must match
    the manager's).

    `append` runs per batch: fanout → fingerprint → one
    dynamic_update_slice into the accumulator ring at `offset` (a traced
    scalar the host advances). `fold` is the amortized sort+reduce over
    [S + A] rows, fired by the host every accum_batches batches and
    before every window flush — this is what replaced the per-batch
    re-sort of the whole stash (see AccumState, stash.py). The benchmark
    times the (append ×K, fold ×1) cycle; RollupPipeline drives the same
    functions from WindowManager. `app` selects the L7 path (fanout_l7 +
    APP_METER) — fanout and meter schema are coupled by construction so
    they cannot drift apart. `fold_mode` ("full" | "merge") picks the
    fold kernel: the full [S+A] re-sort or the incremental rank-merge
    (stash.py — bit-exact, fold-sort work scales with the ring instead
    of the stash).
    """
    fanout_fn = fanout_l7 if app else fanout_l4
    meter_schema = APP_METER if app else FLOW_METER
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    sum_cols_np = np.asarray(sum_cols, np.int32)
    max_cols_np = np.asarray(max_cols, np.int32)

    from ..ops.segment import SENTINEL_SLOT
    from .stash import (
        _append_impl,
        _fold_impl,
        _merge_fold_impl,
        check_fold_mode,
    )

    check_fold_mode(fold_mode)

    def _base_append(stash, acc, offset, tags, meters, valid):
        if batch_unique_cap is not None:
            tags, meters, valid, dropped = batch_prereduce(
                tags, meters, valid, interval, batch_unique_cap,
                sum_cols_np, max_cols_np,
            )
            stash = dataclasses.replace(
                stash, dropped_overflow=stash.dropped_overflow + dropped
            )
        doc_tags, doc_meters, ts, doc_valid = fanout_fn(tags, meters, valid, fanout_config)
        hi, lo = _doc_fingerprint(doc_tags)  # packed key words, no key_mat take
        window = (ts // jnp.uint32(interval)).astype(jnp.uint32)
        acc = _append_impl(acc, window, hi, lo, doc_tags, doc_meters, doc_valid, offset)
        return stash, acc, tags, meters, valid

    if sketch_config is None:
        def append(stash, acc, offset, tags, meters, valid):
            stash, acc, _, _, _ = _base_append(stash, acc, offset, tags, meters, valid)
            return stash, acc
    else:
        meter_ix = meter_schema.index
        # one-pass knobs captured at BUILD time (ISSUE 17): the caller
        # jits this closure fresh per plane instance, so capturing here
        # pins the path for the closure's whole life — a retrace on a
        # new bucket shape cannot silently flip it mid-stream
        from ..ops.segment import _use_fused_sketch, _use_shared_sort

        shared_sort = _use_shared_sort()
        fused_sketch = _use_fused_sketch()

        def append(stash, acc, offset, sk, tags, meters, valid, start_window):
            stash, acc, r_tags, r_meters, r_valid = _base_append(
                stash, acc, offset, tags, meters, valid
            )
            ts = jnp.asarray(r_tags["timestamp"], jnp.uint32)
            base_w, close_w = sketch_span_bounds(
                start_window, ts, r_valid, interval=interval, delay=delay
            )
            inp = sketch_inputs_from_columns(
                r_tags, r_meters, sk.hll.shape[1], meter_ix
            )
            sk = sketch_plane_step(
                sk, sketch_config.hist,
                window=ts // jnp.uint32(interval), valid=r_valid,
                base_w=base_w, close_w=close_w,
                shared_sort=shared_sort, fused_sketch=fused_sketch, **inp,
            )
            return stash, acc, sk

    if fold_mode == "merge":
        def fold(stash, acc):
            new_stash, new_acc, _fold_rows = _merge_fold_impl(
                stash, acc, jnp.uint32(SENTINEL_SLOT), sum_cols, max_cols
            )
            return new_stash, new_acc
    else:
        def fold(stash, acc):
            return _fold_impl(stash, acc, sum_cols, max_cols)

    return append, fold


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    fanout: FanoutConfig = FanoutConfig()
    window: WindowConfig = WindowConfig()
    batch_size: int = 4096  # static pad size for flow batches
    # batch-local pre-reduce before fanout (batch_prereduce); None = off
    batch_unique_cap: int | None = None
    # Shape buckets (ISSUE 4): when set, each ingested batch pads to the
    # smallest bucket ≥ its row count instead of to batch_size. The
    # fused step compiles ONCE per bucket (JitCacheMonitor's
    # expected_compiles budget covers them — anything beyond is still a
    # retrace), so mixed-size feeder traffic never recompiles in steady
    # state. Must be sorted unique; batches larger than max(buckets) are
    # a caller error (the feeder slices to max(buckets)).
    bucket_sizes: tuple[int, ...] | None = None

    def __post_init__(self):
        if self.bucket_sizes is not None:
            bs = tuple(self.bucket_sizes)
            if not bs or list(bs) != sorted(set(bs)) or bs[0] <= 0:
                raise ValueError(
                    f"bucket_sizes must be sorted unique positive ints, got {bs}"
                )


# Back-compat alias (bench/entry scripts predate the L7 pipeline).
L4PipelineConfig = PipelineConfig


@dataclasses.dataclass
class StagedBatch:
    """A bucket-padded batch whose device upload has been dispatched
    (RollupPipeline.stage) but whose fused step has not yet run — the
    double-buffer unit the feeder runtime holds one of."""

    tag_mat: jnp.ndarray  # [T, B] u32 packed tag matrix (device)
    meters: jnp.ndarray  # [B, M] f32 (device)
    valid: jnp.ndarray  # [B] bool (device)
    padded_rows: int  # B — the bucket this batch padded to
    # lineage plane (ISSUE 13): the batch's host-side event-time bounds
    # (valid rows only), captured in stage() BEFORE upload — t_max <
    # t_min means "not computed" (no lineage attached)
    t_min: int = 0
    t_max: int = -1


class RollupPipeline:
    """Single-granularity (e.g. 1s) rollup pipeline: fanout → fingerprint
    → windowed stash merge, with host-driven window flushes.

    The per-batch device slice is ONE jitted call (see module docstring);
    WindowManager.ingest_step drives the window protocol around it."""

    fanout_fn = staticmethod(fanout_l4)
    meter_schema: MeterSchema = FLOW_METER

    def __init__(self, config: PipelineConfig = PipelineConfig()):
        self.config = config
        self.wm = WindowManager(config.window, TAG_SCHEMA, self.meter_schema)
        self.tracer = self.wm.tracer  # host stage spans (utils/spans)
        # retrace gate for the fused step: one expected compile per
        # shape bucket; any growth beyond that is a real retrace
        self._jit = JitCacheMonitor(
            expected_compiles=len(config.bucket_sizes or ()) or 1
        )
        self._tag_names: tuple | None = None  # fixed on first batch
        self._step = None
        # closed-window sketch blocks (ISSUE 8): DocBatch is the exact
        # writer format, so blocks accumulate here for the sketch sink
        # (integration/dfstats.sketch_system_sink) / querier instead.
        # BOUNDED: a deployment that never drains pop_closed_sketches
        # must not leak a block per window forever — beyond the cap the
        # oldest block drops and is counted (same drop-oldest-counted
        # stance as the device pending buffer).
        self.closed_sketches: list = []
        self.max_held_sketches = 512
        self.sketch_blocks_dropped = 0
        # rollup-cascade tier outputs (ISSUE 9): merged tier sketch
        # blocks held for the sketch sink, same bounded stance
        self.closed_tier_sketches: list = []
        self.tier_sketch_blocks_dropped = 0
        if config.window.cascade is not None:
            # the server's datasource listing reflects which tiers this
            # cascade serves (dfctl datasource / REST /v1/datasources);
            # lazy import — the aggregator must not hard-depend on the
            # server layer
            from ..server.datasource import register_cascade_tiers

            register_cascade_tiers(
                self.meter_schema.name, config.window.cascade.intervals,
                owner=self,
            )
        # self-telemetry registration (reference RegisterCountable stance:
        # every component registers at construction; weakly held, so
        # short-lived pipelines deregister themselves). Handles kept so
        # close() can deregister eagerly (ISSUE 12 lifecycle).
        self._stats_srcs = [
            register_countable(
                "tpu_pipeline", self,
                kind=type(self).__name__,
                interval=f"{config.window.interval}s",
            ),
            register_countable(
                "tpu_pipeline_spans", self.tracer,
                kind=type(self).__name__,
                interval=f"{config.window.interval}s",
            ),
        ]
        # device profiling plane (ISSUE 12): the step-cost census — per
        # bucket shape, the fused step's abstract args + compile wall
        # time captured at first dispatch (metadata only; the expensive
        # XLA analysis runs lazily on the profile pull). The HBM ledger
        # registration lives on the WindowManager, which owns the
        # planes — the pipeline's Profilable face just delegates.
        from ..profiling.census import default_census

        self._census = default_census
        # per-INSTANCE service key: two concurrently-live pipelines of
        # the same class/interval may have different fused-step
        # signatures (sketch on/off), and a shared key would silently
        # attribute one pipeline's shapes/analysis to the other
        self._census_service = (
            f"{type(self).__name__}:{config.window.interval}s"
            f"#{next(_PIPELINE_SEQ)}"
        )
        # window lineage plane (ISSUE 13): opt-in via attach_lineage
        self._lineage = None

    def attach_lineage(self, tracker) -> None:
        """Wire a tracing/lineage.LineageTracker through this pipeline:
        stage() stamps the upload hop and captures the batch's host
        event-time bounds, ingest_staged binds them to the dispatch, and
        the wrapped WindowManager records advance/flush/tier/snapshot
        hops + freshness lags. Host wall stamps only — zero new device
        fetches (CI-gated)."""
        self._lineage = tracker
        self.wm.attach_lineage(tracker)

    def _build_step(self, names: tuple):
        """One fused device step per batch: [T, N] packed tags → stats +
        ring append. `names` orders the packed matrix rows (static)."""
        m = self.meter_schema
        sum_cols = np.nonzero(m.sum_mask)[0].astype(np.int32)
        max_cols = np.nonzero(m.max_mask)[0].astype(np.int32)
        cap_u = self.config.batch_unique_cap
        interval = self.config.window.interval
        delay = self.config.window.delay
        fanout_cfg = self.config.fanout
        fanout_fn = self.fanout_fn
        sketch_cfg = self.config.window.sketch
        m_ix = m.index

        # one-pass knobs captured at step-BUILD time (ISSUE 17) — same
        # retrace-stability stance as make_ingest_step's sketch append
        from ..ops.segment import _use_fused_sketch, _use_shared_sort

        shared_sort = _use_shared_sort()
        fused_sketch = _use_fused_sketch()

        def _sketch(sk, tags, meters, valid, start_window):
            """Per-window plane update from the RAW flow rows (ISSUE 8):
            pre-fanout, so a flow counts once — doc-lane replication
            would multiply every CMS/top-K weight by FANOUT_LANES. With
            the pre-reduce on, the post-reduce rows carry the summed
            meters, so weights stay exact. Traced into the same fused
            step — zero extra dispatches or fetches."""
            ts = jnp.asarray(tags["timestamp"], jnp.uint32)
            base_w, close_w = sketch_span_bounds(
                start_window, ts, valid, interval=interval, delay=delay
            )
            inp = sketch_inputs_from_columns(tags, meters, sk.hll.shape[1], m_ix)
            return sketch_plane_step(
                sk, sketch_cfg.hist,
                window=ts // jnp.uint32(interval), valid=valid,
                base_w=base_w, close_w=close_w,
                shared_sort=shared_sort, fused_sketch=fused_sketch, **inp,
            )

        def step(acc, offset, start_window, stash_valid, stash_evict,
                 feeder_shed, fold_rows, casc_lanes, snap_lanes, sk,
                 tag_mat, meters, valid):
            tags = {k: tag_mat[i] for i, k in enumerate(names)}
            aux = None
            if cap_u is not None:
                tags, meters, valid, aux = batch_prereduce(
                    tags, meters, valid, interval, cap_u, sum_cols, max_cols
                )
            if sk is not None:
                sk = _sketch(sk, tags, meters, valid, start_window)
            doc_tags, doc_meters, ts, doc_valid = fanout_fn(
                tags, meters, valid, fanout_cfg
            )
            hi, lo, excess = _doc_fingerprint(doc_tags, with_excess=True)
            # packing-guard hits: doc rows whose tag values overflow the
            # declared DOC_KEY_WIDTHS contract (datamodel/code.py)
            excess_hits = jnp.sum((excess != 0) & doc_valid)
            gated, window, block = batch_counter_block(
                ts, doc_valid, start_window, interval, aux=aux,
                excess_hits=excess_hits, stash_valid=stash_valid,
                stash_evictions=stash_evict, ring_fill=offset,
                feeder_shed=feeder_shed, fold_rows=fold_rows,
                sketch_rows=None if sk is None else sk.rows,
                sketch_shed=None if sk is None else sk.shed,
                cascade_rows=casc_lanes[0], cascade_shed=casc_lanes[1],
                snapshot_reads=snap_lanes[0], snapshot_bytes=snap_lanes[1],
            )
            acc = _append_impl(
                acc, window, hi, lo, doc_tags, doc_meters, gated, offset
            )
            if sk is None:
                return acc, block
            return acc, block, sk

        if sketch_cfg is None:
            # keep the sketch-free signature (and jit cache key) identical
            # to the pre-ISSUE-8 step: None is not a pytree leaf we want
            # in the dispatch path
            def step_plain(acc, offset, start_window, stash_valid, stash_evict,
                           feeder_shed, fold_rows, casc_lanes, snap_lanes,
                           tag_mat, meters, valid):
                return step(acc, offset, start_window, stash_valid,
                            stash_evict, feeder_shed, fold_rows, casc_lanes,
                            snap_lanes, None, tag_mat, meters, valid)

            return jax.jit(step_plain, donate_argnums=(0,))
        return jax.jit(step, donate_argnums=(0, 9))

    def _pad_target(self, rows: int) -> int:
        """Static pad size for a batch of `rows`: the smallest bucket
        that fits (bucketed mode) or the fixed batch_size."""
        buckets = self.config.bucket_sizes
        if not buckets:
            return self.config.batch_size
        for b in buckets:
            if rows <= b:
                return b
        raise ValueError(
            f"batch of {rows} rows exceeds the largest shape bucket "
            f"{buckets[-1]}; the feeder must slice to max(bucket_sizes)"
        )

    def stage(self, batch: FlowBatch) -> "StagedBatch | None":
        """Pad to the shape bucket and START the host→device upload of
        the packed tag matrix + meters + valid (JAX device puts are
        async) WITHOUT dispatching the fused step. The feeder runtime
        stages batch i+1 while batch i's dispatch is still in flight —
        the upload overlaps compute, mirroring async_drain on the
        output side. Returns None for an all-padding batch."""
        batch = batch.pad_to(self._pad_target(batch.size))
        if not np.any(batch.valid):
            return None
        lin = self._lineage
        t_min, t_max, s0 = 0, -1, 0.0
        if lin is not None:
            # host event-time bounds BEFORE the upload (numpy — free);
            # the dispatch binds them to the lineage window span
            ts = np.asarray(batch.tags["timestamp"])[batch.valid]
            if ts.size:
                t_min, t_max = int(ts.min()), int(ts.max())
            s0 = lin.clock()
        if self._tag_names is None:
            self._tag_names = tuple(sorted(batch.tags))
            self._step = self._build_step(self._tag_names)
            self._jit.attach(self._step)
        # pack the ~37 tag columns into ONE host→device upload
        tag_mat = jnp.asarray(
            np.stack(
                [np.asarray(batch.tags[k], dtype=np.uint32) for k in self._tag_names]
            )
        )
        meters = jnp.asarray(batch.meters)
        valid = jnp.asarray(batch.valid)
        self.wm.bytes_uploaded += (
            tag_mat.nbytes + meters.nbytes + valid.nbytes
        )
        if lin is not None:
            lin.note_stage(s0)
        return StagedBatch(tag_mat=tag_mat, meters=meters, valid=valid,
                           padded_rows=batch.size, t_min=t_min, t_max=t_max)

    def ingest(self, batch: FlowBatch, feeder_shed: int = 0) -> list[DocBatch]:
        """Feed one decoded flow batch; returns any closed windows."""
        staged = self.stage(batch)
        if staged is None:
            # idle heartbeat: skip the upload/append (it would burn ring
            # rows and force empty folds); still settle any deferred
            # async-drain buffers so closed windows aren't held up
            return self._convert_flushed(self.wm.settle())
        return self.ingest_staged(staged, feeder_shed=feeder_shed)

    def ingest_staged(
        self, staged: "StagedBatch", feeder_shed: int = 0
    ) -> list[DocBatch]:
        """Dispatch the fused step for an already-staged batch."""
        # with the pre-reduce on, the append writes a FANOUT_LANES×cap_u
        # block (static groupby output) regardless of batch rows
        cap_u = self.config.batch_unique_cap
        rows = FANOUT_LANES * (cap_u or staged.padded_rows)
        # size the accumulator ring for the LARGEST bucket up front so a
        # small first bucket doesn't build a ring a later one replaces
        max_rows = FANOUT_LANES * (
            cap_u
            or (self.config.bucket_sizes or (self.config.batch_size,))[-1]
        )
        shed = jnp.uint32(feeder_shed)

        def dispatch(acc, offset, start_window):
            # stash lanes read at dispatch time (post any fold) — device
            # handles, no transfer; they fill the counter block's
            # occupancy/eviction/fold_rows/cascade lanes inside the same
            # fused call. The sketch plane rides the same dispatch when on.
            st = self.wm.state
            casc = self.wm._cascade_lanes()
            snap = self.wm._snapshot_lanes()
            args = (acc, offset, start_window, st.valid, st.dropped_overflow,
                    shed, self.wm._fold_rows_dev, casc, snap)
            if self.wm.sk is not None:
                args = args + (self.wm.sk,)
            args = args + (staged.tag_mat, staged.meters, staged.valid)
            # census capture (ISSUE 12): first dispatch of a bucket shape
            # records the abstract arg shapes BEFORE the step consumes
            # its donated buffers — ShapeDtypeStructs only, no compile,
            # no transfer, once per bucket
            if not self._census.seen(self._census_service, "fused_step",
                                     staged.padded_rows):
                self._census.observe(
                    self._census_service, "fused_step", staged.padded_rows,
                    self._step, args,
                )
            return self._step(*args)

        window_span = None
        if self._lineage is not None and staged.t_max >= staged.t_min:
            iv = self.config.window.interval
            window_span = (staged.t_min // iv, staged.t_max // iv)
        compiles0 = sum(self._jit.poll())
        t0 = time.perf_counter()
        flushed = self.wm.ingest_step(
            dispatch, rows, ring_rows=max_rows, window_span=window_span
        )
        wall_s = time.perf_counter() - t0
        if sum(self._jit.poll()) > compiles0:
            # the monitor saw the pjit cache grow on this dispatch: the
            # wall time above IS the bucket's compile + first-execute
            # tax — attribute it (steady-state dispatches skip this)
            self._census.note_compile(
                self._census_service, "fused_step", staged.padded_rows, wall_s
            )
        return self._convert_flushed(flushed)

    def drain(self) -> list[DocBatch]:
        return self._convert_flushed(self.wm.flush_all())

    def snapshot_open(self, *, force: bool = False):
        """Live read plane (ISSUE 10): pull a read-only OpenSnapshot of
        the open window span (rate-limited; see
        WindowManager.snapshot_open). Ingest is untouched — the read
        happens between dispatches and costs 2 pull-path fetches."""
        return self.wm.snapshot_open(force=force)

    def _convert_flushed(self, flushed: list[FlushedWindow]) -> list[DocBatch]:
        """FlushedWindows → writer DocBatches; closed sketch blocks are
        captured into `closed_sketches` (sketch-only windows — every
        exact row shed — produce a block but no DocBatch)."""
        from .sketchplane import hold_blocks

        out = []
        blocks = []
        for f in flushed:
            if f.sketches is not None:
                blocks.append(f.sketches)
            if f.count:
                out.append(self._to_docbatch(f))
        self.sketch_blocks_dropped += hold_blocks(
            self.closed_sketches, blocks, self.max_held_sketches
        )
        return out

    def pop_closed_sketches(self) -> list:
        """Drain the accumulated WindowSketchBlocks (oldest first)."""
        out, self.closed_sketches = self.closed_sketches, []
        return out

    def pop_tier_windows(self) -> list[FlushedWindow]:
        """Drain the cascade's closed tier windows (ISSUE 9) — raw
        FlushedWindow form with tier ≥ 1 and the tier interval set."""
        return self.wm.pop_tier_windows()

    def pop_tier_docbatches(self) -> list[tuple[int, DocBatch]]:
        """Closed cascade tier windows as (tier_interval_s, DocBatch)
        pairs, oldest first. Merged tier sketch blocks are captured
        into `closed_tier_sketches` (a sketch-only tier window — every
        exact row shed — contributes a block but no DocBatch, the same
        coverage contract as tier 0)."""
        from .sketchplane import hold_blocks

        out = []
        blocks = []
        for f in self.wm.pop_tier_windows():
            if f.sketches is not None:
                blocks.append(f.sketches)
            if f.count:
                out.append((f.interval, self._to_docbatch(f)))
        self.tier_sketch_blocks_dropped += hold_blocks(
            self.closed_tier_sketches, blocks, self.max_held_sketches
        )
        return out

    def _to_docbatch(self, f: FlushedWindow) -> DocBatch:
        ts = np.full((f.count,), f.start_time, dtype=np.uint32)
        return DocBatch(
            tags=f.tags,
            meters=f.meters,
            timestamp=ts,
            valid=np.ones((f.count,), dtype=bool),
            tag_schema=TAG_SCHEMA,
            meter_schema=self.meter_schema,
        )

    def get_counters(self) -> dict:
        """Countable face: fetch-free (see WindowManager.get_counters)
        plus the fused-step jit compile/retrace counters."""
        out = self.wm.get_counters()
        out.update(self._jit.get_counters())
        # held closed-window blocks + the drop-oldest overflow counter:
        # a rising dropped count means nobody drains pop_closed_sketches
        out["sketch_blocks_held"] = len(self.closed_sketches)
        out["sketch_blocks_dropped"] = self.sketch_blocks_dropped
        out["tier_sketch_blocks_held"] = len(self.closed_tier_sketches)
        out["tier_sketch_blocks_dropped"] = self.tier_sketch_blocks_dropped
        return out

    # -- device profiling plane (ISSUE 12) --------------------------------
    def device_planes(self) -> dict:
        """Profilable face — delegates to the owning WindowManager (the
        manager holds every device plane; it is also the one registered
        on the HBM ledger, so the flat tpu_hbm_* lanes never
        double-count a pipeline-wrapped manager)."""
        return self.wm.device_planes()

    def profile_snapshot(self, *, analyze: bool = False) -> dict:
        """The per-pipeline profile record: per-plane HBM bytes + the
        step census rows for THIS pipeline's fused step. With
        `analyze=True` the census rows carry the XLA cost/memory
        analysis (may compile — pull path only)."""
        from ..profiling.ledger import plane_bytes

        return {
            "hbm_bytes": {
                name: plane_bytes(tree)[0]
                for name, tree in self.wm.device_planes().items()
            },
            "census": [
                r for r in self._census.snapshot(analyze=analyze)
                if r["service"] == self._census_service
            ],
        }

    def close(self) -> None:
        """Eager profiling/telemetry teardown (weakrefs would get there
        eventually; close() makes it synchronous): the manager leaves
        the HBM ledger and the pipeline's Countable rows stop."""
        self.wm.close()
        from ..utils.stats import default_collector

        for src in self._stats_srcs:
            default_collector.deregister(src)

    def telemetry(self) -> dict:
        """JSON-able snapshot for bench records: the counter-block-backed
        counters plus the per-stage span summary (BENCH files carry
        stage attribution — PERF.md §13) and, since ISSUE 12, the
        device profile record (per-plane HBM bytes + step census, no
        analysis — absence-tolerant consumers)."""
        return {
            "counters": self.get_counters(),
            "spans": self.tracer.summary(),
            "profile": self.profile_snapshot(),
        }

    @property
    def counters(self) -> dict:
        out = dict(self.wm.counters)
        out.update(self._jit.get_counters())
        # legacy name for the CB_PREREDUCE_SHED lane ("prereduce_shed"
        # in get_counters) — kept as the probe-facing alias, computed
        # from the same source so the two cannot drift
        out["prereduce_dropped"] = out.pop("prereduce_shed")
        return out

    @property
    def flags(self) -> DocumentFlag:
        if self.config.window.interval == 1:
            return DocumentFlag.PER_SECOND_METRICS
        return DocumentFlag.NONE


class DualGranularityPipeline:
    """SECOND + MINUTE rollups from one flow stream — ONE device
    dispatch per batch (ISSUE 9).

    The reference runs one SubQuadGen per granularity over the same
    TaggedFlow queue (MetricsType::SECOND|MINUTE,
    quadruple_generator.rs:275-298) and the 1m docs land in the *.1m
    tables that feed the downsampler chain (datasource/handle.go
    1m→1h→1d). The r6–r12 reproduction paid for that with a SECOND full
    device ingest per batch; this shim instead rides the rollup cascade
    (aggregator/cascade.py): the minute series is the 1m tier — a
    device-side fold of closed 1s windows — so dual-granularity traffic
    costs one fused dispatch per batch plus a per-advance tier fold.
    The old double-ingest survives as `DoubleIngestPipeline`, kept as
    the conformance oracle and the cascadebench A/B baseline.

    ingest() returns (flags, DocBatch) pairs: PER_SECOND_METRICS for 1s
    windows, NONE for 1m — exactly what encode_docbatch/table routing
    (metrics_tables.route_table_ids) key off. Minute docs for a minute
    M surface once every 1s window of M has closed (≈ delay seconds
    after the minute ends) — earlier than the old minute pipe's
    minute_delay, never later than the data allows.

    One documented semantic change: minute ADMISSION now equals the 1s
    delay — a row too late for its second is too late for its minute
    (the cascade folds closed seconds; there is no separate minute
    gate). The old pipeline admitted rows up to `minute_delay` late
    into 1m docs its own 1s tier had already dropped; `minute_delay`
    stays in the signature for call-site compatibility but only widens
    nothing. Under identical streams whose lateness stays within the 1s
    delay, minute meters are bit-exact vs the double-ingest
    (tests/test_cascade.py pins it, late minute-boundary rows included).
    """

    def __init__(
        self,
        config: PipelineConfig = PipelineConfig(),
        *,
        minute_delay: int = 10,
        app: bool = False,
        cascade: "CascadeConfig | None" = None,
    ):
        from .cascade import CascadeConfig

        cls = L7Pipeline if app else L4Pipeline
        if config.window.cascade is None:
            # the minute tier keeps the 1s stash's capacity — the same
            # per-granularity bound the old minute pipe had
            casc = cascade or CascadeConfig(
                intervals=(60,), capacity=config.window.capacity
            )
            config = dataclasses.replace(
                config,
                window=dataclasses.replace(config.window, cascade=casc),
            )
        elif cascade is not None and cascade != config.window.cascade:
            raise ValueError(
                f"conflicting cascade configs: the window config carries "
                f"{config.window.cascade} but cascade={cascade} was also "
                "passed — silently preferring one would drop tiers"
            )
        if 60 not in config.window.cascade.intervals:
            raise ValueError(
                "DualGranularityPipeline needs a 1m cascade tier (its "
                f"contract IS the minute series); got intervals="
                f"{config.window.cascade.intervals}"
            )
        self.pipe = cls(config)
        self.minute_delay = minute_delay  # compat knob — see docstring
        # coarser-than-minute tier batches (1h…) do NOT ride the
        # (flags, DocBatch) stream: route_table_ids only distinguishes
        # PER_SECOND vs NONE, so emitting them there would land hourly
        # docs in the *_1m tables and double-count the minute series.
        # They accumulate here for store-side writers (the derived
        # network_1h tables the datasource listing names).
        self.coarse_tiers: list[tuple[int, DocBatch]] = []

    # compat alias: telemetry consumers address `.second`
    @property
    def second(self) -> RollupPipeline:
        return self.pipe

    def _tier_docs(self) -> list[tuple[DocumentFlag, DocBatch]]:
        from .sketchplane import hold_blocks

        out = []
        coarse = []
        for interval, db in self.pipe.pop_tier_docbatches():
            if interval == 60:
                out.append((DocumentFlag.NONE, db))
            else:
                coarse.append((interval, db))
        # bounded drop-oldest like every other held buffer — an
        # undrained coarse-tier consumer must not leak a batch per hour
        hold_blocks(self.coarse_tiers, coarse, 512)
        return out

    def ingest(self, batch) -> list[tuple[DocumentFlag, DocBatch]]:
        out = [(self.pipe.flags, db) for db in self.pipe.ingest(batch)]
        return out + self._tier_docs()

    def drain(self) -> list[tuple[DocumentFlag, DocBatch]]:
        out = [(self.pipe.flags, db) for db in self.pipe.drain()]
        return out + self._tier_docs()

    @property
    def counters(self) -> dict:
        c = self.pipe.counters
        # the "minute" face survives for dashboards that key on it; the
        # minute plane is now the cascade's lanes inside the single
        # pipeline's counters
        return {
            "second": c,
            "minute": {
                "cascade_rows": c.get("cascade_rows", 0),
                "cascade_shed": c.get("cascade_shed", 0),
                "tier_windows": c.get("cascade_tier_windows", 0),
            },
        }

    def telemetry(self) -> dict:
        t = self.pipe.telemetry()
        return {"second": t, "minute": {"counters": self.counters["minute"]}}


class DoubleIngestPipeline:
    """The pre-ISSUE-9 dual-granularity implementation: a full second
    device ingest into a parallel minute pipeline. Kept ONLY as the
    conformance oracle (tests/test_cascade.py pins cascade 1m meters
    bit-exact against it) and the cascadebench A/B baseline — new code
    wants `DualGranularityPipeline`, which produces the same
    (flags, DocBatch) stream from one dispatch per batch."""

    def __init__(
        self,
        config: PipelineConfig = PipelineConfig(),
        *,
        minute_delay: int = 10,
        app: bool = False,
    ):
        cls = L7Pipeline if app else L4Pipeline
        self.second = cls(config)
        minute_window = dataclasses.replace(
            config.window, interval=60, delay=minute_delay, cascade=None
        )
        self.minute = cls(dataclasses.replace(config, window=minute_window))

    def ingest(self, batch) -> list[tuple[DocumentFlag, DocBatch]]:
        out = [(self.second.flags, db) for db in self.second.ingest(batch)]
        out += [(self.minute.flags, db) for db in self.minute.ingest(batch)]
        return out

    def drain(self) -> list[tuple[DocumentFlag, DocBatch]]:
        out = [(self.second.flags, db) for db in self.second.drain()]
        out += [(self.minute.flags, db) for db in self.minute.drain()]
        return out

    @property
    def counters(self) -> dict:
        return {"second": self.second.counters, "minute": self.minute.counters}

    def telemetry(self) -> dict:
        return {
            "second": self.second.telemetry(),
            "minute": self.minute.telemetry(),
        }


class L4Pipeline(RollupPipeline):
    """network / network_map rollup (FlowMeter docs) — the RollupPipeline
    defaults, named for symmetry with L7Pipeline."""


class L7Pipeline(RollupPipeline):
    """application / application_map rollup (AppMeter docs) — the TPU
    replacement for L7QuadrupleGenerator → L7Collector
    (l7_quadruple_generator.rs:93-253, collector.rs:694-821)."""

    fanout_fn = staticmethod(fanout_l7)
    meter_schema = APP_METER
