"""Window ring controller — SubQuadGen/Collector window semantics.

Replicates the reference's windowed-stash protocol
(quadruple_generator.rs:275-352, collector.rs:380-430):

  * time is bucketed into fixed `interval` windows (1s or 60s);
  * a window stays open for `delay` seconds after its end to absorb
    out-of-order arrivals, then is flushed;
  * arrivals older than the oldest open window are dropped and counted
    (`drop_before_window`, collector.rs:386-391).

Control flow is host-driven (the reference drives it from queue ticks);
the data path is device-resident. One deliberate difference: the
reference interleaves per-flow inserts with window moves, while we apply
batch-atomic semantics — merge the whole batch, then advance the window
to `max(batch time) - delay`. Within-batch reordering is invisible to the
output because merges are commutative per window.

Host-sync budget (PERF.md §8: every device→host fetch costs a fixed
~150-200 ms round trip on the TPU tunnel): steady-state `ingest` performs
AT MOST one tiny fetch per batch — the versioned on-device COUNTER BLOCK
the jitted append step computes (late/valid/shed plus stash occupancy &
evictions, packed-key excess-word hits, ring fill and feeder shed; see
COUNTER_BLOCK_VERSION / CB_* below) — plus two fetches per *window
advance* (row count + the packed flush matrix), independent of batch
size and of how many windows closed. With `WindowConfig.stats_ring = K`
the blocks accumulate in a device-resident [K, CB_LEN] ring fetched
once per K dispatches, dropping steady-state syncs to 1/K per batch
(ISSUE 4; late gating moves to device state so flushed rows stay
bit-exact vs per-batch fetching). All transfers route through
`host_fetch` so the CI gate (tests/test_perf_gate.py) can count them and
trip on a reintroduced per-row or per-window fetch; the managers also
account fetch count and bytes per direction, and wrap each host stage
(dispatch / stats fetch / advance / drain) in utils/spans tracer spans.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos
from ..datamodel.schema import FLOW_METER, TAG_SCHEMA, MeterSchema, TagSchema
from ..ops.hashing import fingerprint64
from ..ops.segment import _use_fused_sketch, _use_shared_sort
from .cascade import CascadeConfig, TierCascade, TierFlush
from .sketchplane import (
    SENTINEL_WIN,
    SketchConfig,
    SketchState,
    WindowSketchBlock,
    _flatten_open,
    _pool_mode,
    sketch_drain,
    sketch_init,
    sketch_plane_step,
    unpack_drained,
)
from ..utils.retry import (
    RetryPolicy,
    decorrelated_rng,
    is_dispatch_transient,
    retry_call,
)
from ..utils.spans import (
    SPAN_FLUSH_DRAIN,
    SPAN_INGEST_DISPATCH,
    SPAN_QUERY_SNAPSHOT,
    SPAN_STATS_FETCH,
    SPAN_WINDOW_ADVANCE,
    SPAN_WINDOW_FOLD,
    SpanTracer,
)
from .stash import (
    AccumState,
    StashState,
    _append_impl,
    accum_init,
    check_fold_mode,
    plan_append,
    stash_flush_range,
    stash_fold_counted,
    stash_init,
    stash_merge_fold,
    stash_snapshot_range,
    unpack_flush_rows,
)

_U32_MAX = np.uint32(0xFFFFFFFF)


def host_fetch(x) -> np.ndarray:
    """THE device→host fetch boundary for the windowed path.

    Every transfer WindowManager performs goes through here so the
    perf gate can shim it and assert the per-batch budget; keep new
    fetches behind this seam."""
    return np.asarray(x)


# ---------------------------------------------------------------------------
# Versioned on-device counter block (ISSUE 3). The fused jit step's
# per-batch download widened from the 5-scalar stats vector into this
# u32 block — still ONE fetch, same ≤3-fetch budget. Layout is a
# CONTRACT between the device step and `_process_stats`; bump
# COUNTER_BLOCK_VERSION when it changes (element 0 carries the version
# so a stale host parser fails loudly instead of mis-slicing).
# v2 (ISSUE 4): + feeder_shed — records the feeder runtime dropped
# upstream of this batch's assembly, riding the same fetch so queue
# pressure is visible in the device counter plane.
# v3 (ISSUE 5): + fold_rows — rows the LAST fold's keyed sort touched
# (full-sort mode: whole live stash + ring; merge mode: only the acc
# rows that folded, span-bounded on advances), so the merge-fold's row
# savings are visible in deepflow_system without a new fetch.
# v4 (ISSUE 8): + sketch_rows / sketch_shed — cumulative rows the
# per-window sketch plane folded (the lane asserting sketch updates
# actually ran in the fused dispatch) and rows the plane counted-shed
# (mid-gap jumps, pending-buffer overflow); zero with the plane off.
# v5 (ISSUE 9): + cascade_rows / cascade_shed — cumulative rows the
# rollup cascade's tier folds consumed (closed child-window rows merged
# into 1m/1h tier stashes) and cumulative tier-stash overflow sheds;
# zero with the cascade off. Rides the same fetch as every other lane.
# v6 (ISSUE 10): + snapshot_reads / snapshot_bytes — the live read
# plane's cumulative pull-only snapshot count and fetched bytes (host
# scalars riding the upload direction like feeder_shed, cached as one
# device vector so steady state re-sends the same handle), so a live
# dashboard's read pressure is visible in the device counter plane
# without a new fetch. u32 lanes: bytes wrap mod 2^32 like every other
# cumulative lane; the host ints stay authoritative.
# v7 (ISSUE 20): + sketch_pool_spill / sketch_pool_occ /
# sketch_promotions — the pooled sketch memory's cumulative counted
# spills (windows that wanted a compact slot when the pool was full),
# the occupancy gauge (allocated compact slots + closed-pending wide
# slots at dispatch), and cumulative compact→wide promotions. Zero in
# slab mode (the pool lanes are zero-size arrays whose sums are 0).

COUNTER_BLOCK_VERSION = 7
(
    CB_VERSION,  # constant COUNTER_BLOCK_VERSION
    CB_T_MAX,  # max valid timestamp (pre-gate)
    CB_T_MIN,  # min valid timestamp (pre-gate)
    CB_N_VALID,  # valid rows this batch (pre-gate)
    CB_N_LATE,  # rows dropped by the late-arrival gate
    CB_PREREDUCE_SHED,  # unique keys shed by batch_prereduce this batch
    CB_EXCESS_HITS,  # doc rows whose packed-key excess word != 0
    CB_STASH_OCCUPANCY,  # valid stash rows at dispatch (post-fold)
    CB_STASH_EVICTIONS,  # cumulative stash overflow drops at dispatch
    CB_RING_FILL,  # accumulator rows already occupied at dispatch
    CB_FEEDER_SHED,  # records shed by the feeder before this batch
    CB_FOLD_ROWS,  # rows the last fold's keyed sort touched
    CB_SKETCH_ROWS,  # cumulative rows folded into the sketch plane
    CB_SKETCH_SHED,  # cumulative rows the sketch plane counted-shed
    CB_CASCADE_ROWS,  # cumulative rows the cascade's tier folds consumed
    CB_CASCADE_SHED,  # cumulative tier-stash overflow sheds
    CB_SNAPSHOT_READS,  # cumulative live snapshot_open() reads
    CB_SNAPSHOT_BYTES,  # cumulative live snapshot bytes fetched (mod 2^32)
    CB_SKETCH_POOL_SPILL,  # cumulative pool-exhaustion counted spills
    CB_SKETCH_POOL_OCC,  # pool occupancy gauge at dispatch (compact+wide)
    CB_SKETCH_PROMOTIONS,  # cumulative compact→wide slot promotions
) = range(21)
CB_LEN = 21
CB_FIELDS = (
    "version", "t_max", "t_min", "n_valid", "n_late", "prereduce_shed",
    "excess_word_hits", "stash_occupancy", "stash_evictions", "ring_fill",
    "feeder_shed", "fold_rows", "sketch_rows", "sketch_shed",
    "cascade_rows", "cascade_shed", "snapshot_reads", "snapshot_bytes",
    "sketch_pool_spill", "sketch_pool_occ", "sketch_promotions",
)


def batch_stats(timestamp, valid, start_window, interval, aux=None):
    """Per-batch bookkeeping, device-side (traced): returns (gated_valid,
    window, stats[5] u32) where stats = [t_max, t_min, n_valid, n_late,
    aux]. `start_window` is a traced u32 scalar (0 = no gate yet: no row
    can be late). t_max/t_min are over pre-gate valid rows (0 / U32_MAX
    when none). `aux` rides along so callers piggyback one extra counter
    (e.g. pre-reduce shed rows) on the same single fetch."""
    ts = jnp.asarray(timestamp, dtype=jnp.uint32)
    valid = jnp.asarray(valid)
    window = ts // jnp.uint32(interval)
    late = valid & (window < start_window)
    gated = valid & ~late
    stats = jnp.stack(
        [
            jnp.max(jnp.where(valid, ts, jnp.uint32(0))),
            jnp.min(jnp.where(valid, ts, jnp.uint32(_U32_MAX))),
            jnp.sum(valid).astype(jnp.uint32),
            jnp.sum(late).astype(jnp.uint32),
            jnp.uint32(0) if aux is None else jnp.asarray(aux).astype(jnp.uint32),
        ]
    )
    return gated, window, stats


def batch_counter_block(
    timestamp,
    valid,
    start_window,
    interval,
    *,
    aux=None,
    excess_hits=None,
    stash_valid=None,
    stash_evictions=None,
    ring_fill=None,
    feeder_shed=None,
    fold_rows=None,
    sketch_rows=None,
    sketch_shed=None,
    cascade_rows=None,
    cascade_shed=None,
    snapshot_reads=None,
    snapshot_bytes=None,
    sketch_pool_spill=None,
    sketch_pool_occ=None,
    sketch_promotions=None,
):
    """`batch_stats` widened into the versioned counter block (traced).

    Extra lanes ride the SAME single per-batch fetch: packed-key
    excess-word hits (the datamodel/code.py contract guard), stash
    occupancy summed from the (device-resident — zero transfer) valid
    plane, cumulative eviction count, the accumulator-ring fill at
    dispatch, the feeder's upstream shed count for this batch, and the
    last fold's touched-row count (a device scalar the fold kernels
    return — ISSUE 5). All optional inputs default to zero so every
    caller of the old 5-vector shape can widen incrementally."""
    gated, window, stats = batch_stats(timestamp, valid, start_window, interval, aux=aux)

    def u32(x):
        return jnp.uint32(0) if x is None else jnp.asarray(x).astype(jnp.uint32)

    occ = (
        jnp.uint32(0)
        if stash_valid is None
        else jnp.sum(stash_valid).astype(jnp.uint32)
    )
    block = jnp.concatenate(
        [
            jnp.full((1,), COUNTER_BLOCK_VERSION, dtype=jnp.uint32),
            stats,
            jnp.stack([u32(excess_hits), occ, u32(stash_evictions),
                       u32(ring_fill), u32(feeder_shed), u32(fold_rows),
                       u32(sketch_rows), u32(sketch_shed),
                       u32(cascade_rows), u32(cascade_shed),
                       u32(snapshot_reads), u32(snapshot_bytes),
                       u32(sketch_pool_spill), u32(sketch_pool_occ),
                       u32(sketch_promotions)]),
        ]
    )
    return gated, window, block


@partial(jax.jit, donate_argnums=(0,), static_argnames=("interval",))
def _raw_append_step(acc, offset, start_window, stash_valid, stash_evict,
                     feeder_shed, fold_rows, casc_lanes, snap_lanes,
                     timestamp, key_hi, key_lo, tags, meters, valid,
                     *, interval):
    """One jitted call per raw doc batch: late gate + counter block +
    ring append. `stash_valid`/`stash_evict`/`fold_rows` are
    device-resident lanes folded into the block — inputs already on
    device, no transfer. `feeder_shed` is the feeder's upstream drop
    count for this batch (a host scalar riding the upload direction);
    `casc_lanes` the cascade's device [rows, shed] vector (ISSUE 9 —
    zeros when no cascade is configured); `snap_lanes` the live read
    plane's [reads, bytes] vector (ISSUE 10 — a cached device handle
    rebuilt only when a snapshot actually happens)."""
    gated, window, block = batch_counter_block(
        timestamp, valid, start_window, interval,
        stash_valid=stash_valid, stash_evictions=stash_evict, ring_fill=offset,
        feeder_shed=feeder_shed, fold_rows=fold_rows,
        cascade_rows=casc_lanes[0], cascade_shed=casc_lanes[1],
        snapshot_reads=snap_lanes[0], snapshot_bytes=snap_lanes[1],
    )
    acc = _append_impl(acc, window, key_hi, key_lo, tags, meters, gated, offset)
    return acc, block


def sketch_tag_indices(tag_schema: TagSchema, meter_schema: MeterSchema) -> tuple:
    """Static column-index tuple the sketch-enabled fused steps close
    over: ip0/ip1 words (client + flow identity), server_port /
    protocol / l3_epc_id1 (service grouping + id preview), and the
    byte / rtt meter columns. Raises with the missing field name when a
    schema cannot drive the plane (the plane is TAG_SCHEMA-shaped)."""
    try:
        t = tag_schema.index
        m = meter_schema.index
        return (
            tuple(t(f"ip0_w{w}") for w in range(4))
            + tuple(t(f"ip1_w{w}") for w in range(4))
            + (t("server_port"), t("protocol"), t("l3_epc_id1"),
               m("byte_tx"), m("rtt_sum"), m("rtt_count"))
        )
    except KeyError as e:
        raise ValueError(
            f"sketch plane needs tag/meter column {e} which this "
            f"tag schema / {meter_schema.name} meter schema does not declare"
        ) from e


def sketch_plane_inputs(
    num_groups: int, *, ip0, ip1, server_port, protocol, l3_epc_id1,
    byte_w, rtt_sum, rtt_count,
):
    """Traced: derive the plane's per-row inputs from raw columns.

    Shared by every sketch-enabled step (the raw-doc step here, the
    pipeline's flow-row step, the sharded device step) so all entry
    points sketch identical quantities: the HLL distinct entity is the
    client address (ip0 words), the flow key is the 10-column
    (ip0, ip1, server_port, protocol) fingerprint, the service group is
    the (l3_epc_id1, server_port) hash, the heavy-hitter weight is
    byte_tx, and the id preview is (ip0_w3, port<<16|proto)."""
    u = lambda c: jnp.asarray(c, jnp.uint32)
    ip0 = [u(c) for c in ip0]
    ip1 = [u(c) for c in ip1]
    port, proto, epc = u(server_port), u(protocol), u(l3_epc_id1)
    client_hi, client_lo = fingerprint64(jnp.stack(ip0, axis=1))
    key_hi, key_lo = fingerprint64(jnp.stack(ip0 + ip1 + [port, proto], axis=1))
    group = (epc * jnp.uint32(131) + port) % jnp.uint32(num_groups)
    rtt_cnt = rtt_count
    rtt = rtt_sum / jnp.maximum(rtt_cnt, 1.0)
    return dict(
        group=group, client_hi=client_hi, client_lo=client_lo,
        key_hi=key_hi, key_lo=key_lo, weight=byte_w,
        rtt=rtt, rtt_valid=rtt_cnt > 0,
        id_a=ip0[3],
        id_b=(port << jnp.uint32(16)) | (proto & jnp.uint32(0xFFFF)),
    )


def sketch_inputs_from_matrix(tags, meters, num_groups: int, ix: tuple):
    """`sketch_plane_inputs` over column-major [T, N] tags / [M, N]
    meters via the static `ix` tuple (sketch_tag_indices)."""
    (i00, i01, i02, i03, i10, i11, i12, i13,
     ix_port, ix_proto, ix_epc, m_byte, m_rs, m_rc) = ix
    return sketch_plane_inputs(
        num_groups,
        ip0=[tags[i] for i in (i00, i01, i02, i03)],
        ip1=[tags[i] for i in (i10, i11, i12, i13)],
        server_port=tags[ix_port], protocol=tags[ix_proto],
        l3_epc_id1=tags[ix_epc],
        byte_w=meters[m_byte], rtt_sum=meters[m_rs], rtt_count=meters[m_rc],
    )


def sketch_inputs_from_columns(tags: dict, meters, num_groups: int, meter_ix):
    """`sketch_plane_inputs` over a raw flow-column dict + row-major
    [N, M] meters (`meter_ix` = the meter schema's index fn) — the
    shape every flow-row step holds (RollupPipeline, the sharded device
    step, make_ingest_step's sketch append). One call site per step
    keeps the 'all entry points sketch identical quantities' contract
    a single function instead of three copies."""
    return sketch_plane_inputs(
        num_groups,
        ip0=[tags[f"ip0_w{w}"] for w in range(4)],
        ip1=[tags[f"ip1_w{w}"] for w in range(4)],
        server_port=tags["server_port"], protocol=tags["protocol"],
        l3_epc_id1=tags["l3_epc_id1"],
        byte_w=meters[:, meter_ix("byte_tx")],
        rtt_sum=meters[:, meter_ix("rtt_sum")],
        rtt_count=meters[:, meter_ix("rtt_count")],
    )


def sketch_span_bounds(start_window, ts, valid, *, interval: int, delay: int):
    """Traced: (base_w, close_w) for the plane — the pre-/post-batch
    open-span starts, replicating the host rules exactly: close_w is
    `_process_block`'s advance target (max(gate, (t_max-delay)//i), the
    same value `_stats_ring_push` maintains on device) and base_w is
    the opening rule's max(gate, min(t_min, t_max-delay)//i)."""
    has = jnp.any(valid)
    t_max = jnp.max(jnp.where(valid, ts, jnp.uint32(0)))
    t_min = jnp.min(jnp.where(valid, ts, _U32_MAX))
    t_adj = jnp.where(t_max > jnp.uint32(delay), t_max - jnp.uint32(delay),
                      jnp.uint32(0))
    close_w = jnp.maximum(start_window, t_adj // jnp.uint32(interval))
    base_w = jnp.maximum(
        start_window, jnp.minimum(t_min // jnp.uint32(interval), close_w)
    )
    close_w = jnp.where(has, close_w, start_window)
    base_w = jnp.where(has, base_w, start_window)
    return base_w, close_w


@partial(
    jax.jit,
    donate_argnums=(0, 9),
    static_argnames=("interval", "delay", "ix", "spec", "shared_sort",
                     "fused_sketch"),
)
def _raw_append_step_sk(acc, offset, start_window, stash_valid, stash_evict,
                        feeder_shed, fold_rows, casc_lanes, snap_lanes, sk,
                        timestamp, key_hi, key_lo, tags, meters, valid,
                        *, interval, delay, ix, spec, shared_sort=True,
                        fused_sketch=False):
    """`_raw_append_step` with the per-window sketch plane fused in
    (ISSUE 8): the SAME jit dispatch updates HLL/CMS/histogram/top-K
    slots for every accepted row — key identity is the caller's doc
    fingerprint (key_hi/key_lo), client identity re-derives from the
    ip0 tag words — and the counter block grows the v4 sketch lanes.
    Zero new fetches: the plane's closed blocks leave the device via
    the advance drain, not here.

    `shared_sort`/`fused_sketch` (ISSUE 17) are STATIC: this step is
    module-level-jitted, so an env flip after the first trace would be
    invisible if the plane read the knobs at trace time — the caller
    (WindowManager.merge_batch) reads them per dispatch instead and a
    flip recompiles (counted by the jit monitor like any retrace)."""
    ts = jnp.asarray(timestamp, dtype=jnp.uint32)
    valid_b = jnp.asarray(valid)
    base_w, close_w = sketch_span_bounds(
        start_window, ts, valid_b, interval=interval, delay=delay
    )
    inp = sketch_inputs_from_matrix(tags, meters, sk.hll.shape[1], ix)
    # the caller's fingerprint IS the flow key — sketch estimates then
    # join exactly against flushed exact rows
    inp["key_hi"] = jnp.asarray(key_hi, jnp.uint32)
    inp["key_lo"] = jnp.asarray(key_lo, jnp.uint32)
    sk = sketch_plane_step(
        sk, spec,
        window=ts // jnp.uint32(interval), valid=valid_b,
        base_w=base_w, close_w=close_w,
        shared_sort=shared_sort, fused_sketch=fused_sketch, **inp,
    )
    # pool lanes (CB v7): occupancy gauges sum zero-size arrays in slab
    # mode, so the lanes are 0 there without a mode branch
    pool_occ = (
        jnp.sum(sk.slot_of != jnp.int32(-1))
        + jnp.sum(sk.wide_close != jnp.uint32(SENTINEL_WIN))
    ).astype(jnp.uint32)
    gated, window, block = batch_counter_block(
        ts, valid_b, start_window, interval,
        stash_valid=stash_valid, stash_evictions=stash_evict, ring_fill=offset,
        feeder_shed=feeder_shed, fold_rows=fold_rows,
        sketch_rows=sk.rows, sketch_shed=sk.shed,
        cascade_rows=casc_lanes[0], cascade_shed=casc_lanes[1],
        snapshot_reads=snap_lanes[0], snapshot_bytes=snap_lanes[1],
        sketch_pool_spill=sk.pool_spill, sketch_pool_occ=pool_occ,
        sketch_promotions=sk.pool_promos,
    )
    acc = _append_impl(acc, window, key_hi, key_lo, tags, meters, gated, offset)
    return acc, block, sk


# READ-ONLY open-slot sketch snapshot (ISSUE 10): the packed [R, WIDE]
# block rows + their window ids, no donation — the plane keeps counting.
_sketch_open_snapshot = jax.jit(lambda sk: (_flatten_open(sk), sk.win))


def attach_open_sketch_blocks(
    windows: "list[FlushedWindow]", merged: dict, *,
    interval: int, num_tags: int, num_meters: int,
) -> "list[FlushedWindow]":
    """THE open-snapshot block-marry rule, shared by the single-chip
    and sharded snapshot paths (ISSUE 10): attach each window's merged
    open sketch block, synthesize a row-less partial FlushedWindow for
    every block whose window has no exact rows (same coverage contract
    as the drain's sketch-only windows), and return the list sorted by
    window. `merged` is consumed."""
    exact = {f.window_idx for f in windows}
    for f in windows:
        f.sketches = merged.pop(f.window_idx, None)
    for w in sorted(merged):
        if w in exact:
            continue
        windows.append(
            FlushedWindow(
                window_idx=w,
                start_time=w * interval,
                key_hi=np.zeros((0,), np.uint32),
                key_lo=np.zeros((0,), np.uint32),
                tags=np.zeros((0, num_tags), np.uint32),
                meters=np.zeros((0, num_meters), np.float32),
                count=0,
                sketches=merged[w],
                partial=True,
            )
        )
    windows.sort(key=lambda f: f.window_idx)
    return windows


@partial(jax.jit, donate_argnums=(0,), static_argnames=("interval", "delay"))
def _stats_ring_push(ring, k, sw_state, block, *, interval, delay):
    """Device side of the K-batch counter ring (ISSUE 4): write one
    batch's counter block into the [K, CB_LEN] ring at row `k` and
    advance the DEVICE-RESIDENT window-gate state — all without a host
    sync, so the host fetches the whole ring once per K dispatches.

    `sw_state` is [start_window, opened] u32. The update replicates
    `_process_block`'s host bookkeeping exactly: after ANY non-empty
    block the host span ends at max(previous, (t_max - delay) //
    interval) — on the opening batch it first opens at
    max(0, min(t_min, t_max - delay)) but then advances to that same
    value within the SAME block (open_w ≤ adv_w always), so adv_w is
    the post-block gate in both cases. The late gate of every deferred
    batch therefore sees the SAME start_window it would have seen
    under per-batch fetching — that invariant is what makes the K-ring
    flush output bit-exact against the per-batch oracle: no row that
    per-batch mode would late-drop can reach a window the deferred
    flush later closes."""
    ring = jax.lax.dynamic_update_slice(
        ring, block[None, :].astype(jnp.uint32), (k, jnp.int32(0))
    )
    t_max = block[CB_T_MAX]
    has = block[CB_N_VALID] > 0
    # u32-safe max(0, t_max - delay)
    t_adj = jnp.where(t_max > jnp.uint32(delay), t_max - jnp.uint32(delay),
                      jnp.uint32(0))
    adv_w = t_adj // jnp.uint32(interval)
    new_sw = jnp.where(has, jnp.maximum(sw_state[0], adv_w), sw_state[0])
    new_opened = ((sw_state[1] > 0) | has).astype(jnp.uint32)
    return ring, jnp.stack([new_sw, new_opened])


@dataclasses.dataclass(frozen=True)
class WindowConfig:
    interval: int = 1  # seconds per window
    delay: int = 2  # seconds a window stays open past its end
    capacity: int = 1 << 14  # stash rows shared by all open windows
    # Batches accumulated between sort+reduce folds. The accumulator ring
    # is sized accum_batches × (rows of the first batch); a fold also
    # fires before any window flush so flushed windows always see every
    # row. 8 amortizes the O((S+A) log(S+A)) sort ~8x while keeping the
    # fold shape small enough for fast (remote) XLA compiles.
    accum_batches: int = 8
    # Double-buffered drain: defer each batch's stats fetch by one
    # ingest call, so the host never blocks on the current batch (JAX
    # async dispatch stays ahead) and a closing window's flush is
    # dispatched before — and its packed output fetched after — the
    # next batch's append dispatch, overlapping transfer with compute.
    # Flushed windows are then RETURNED exactly one ingest call later
    # than in sync mode (content is identical — rows that would race
    # the flush are late-dropped either way), and counters trail by
    # ≤1 batch. flush_all()/drain()/settle() always settles.
    async_drain: bool = False
    # K-batch counter ring (ISSUE 4): accumulate K batches' counter
    # blocks into a device-resident [K, CB_LEN] ring and fetch ONCE per
    # K dispatches — steady-state host syncs drop to 1/K per batch. The
    # late gate moves to device-resident state (_stats_ring_push) so
    # flushed rows stay bit-exact vs per-batch fetching; the cost is
    # window-close latency of up to K-1 batches (drain-on-advance: any
    # advance discovered at ring drain flushes immediately during the
    # replay; drain-on-checkpoint: settle() always drains the partial
    # ring first). 1 = per-batch fetch (today's behavior). Mutually
    # exclusive with async_drain — the ring subsumes its deferral.
    stats_ring: int = 1
    # Fold strategy (ISSUE 5). "full": every fold re-sorts the whole
    # [S+A] stash+accumulator concat (the oracle). "merge": exploit the
    # stash's standing (slot, key) sort — sort only the accumulator and
    # rank-merge it in (stash.stash_merge_fold); window advances fold
    # ONLY the acc rows of the closing span and flushes re-canonicalize
    # via the compacting range flush. Bit-exact vs "full" (flushed rows,
    # drop counters — tests/test_merge_fold.py) whenever the stash
    # capacity holds the live segments; under stash OVERFLOW "merge"
    # may defer shedding (open-window rows still in the ring are not
    # eviction candidates until folded), never shed more. Default stays
    # "full" until on-chip numbers land (PERF.md §15).
    fold_mode: str = "full"
    # Per-window device sketch plane (ISSUE 8): HLL / count-min /
    # latency-histogram / invertible top-K state per open window,
    # updated inside the SAME fused dispatch as the exact append and
    # drained as packed blocks riding the advance's existing fetches —
    # distinct-count / quantile / heavy-hitter answers stop depending
    # on exact-stash capacity (sheds degrade detail, not coverage).
    # None = off (today's exact-only behavior, zero cost).
    sketch: SketchConfig | None = None
    # Multi-resolution rollup cascade (ISSUE 9): fold closed windows of
    # THIS manager into bounded coarser tiers (1m/1h) on device instead
    # of running a second ingest per granularity. Tier closes ride the
    # advance drain's existing fetches (≤3-fetch budget intact); tier
    # windows surface via WindowManager.pop_tier_windows(). None = off.
    cascade: "CascadeConfig | None" = None
    # Live read plane (ISSUE 10): minimum wall-clock seconds between two
    # device snapshot reads — `snapshot_open()` calls inside the window
    # return the cached OpenSnapshot, so a dashboard storm costs at most
    # one 2-fetch snapshot per interval (and the result cache keyed on
    # the snapshot seq stays hot in between). Snapshots are PULL-only:
    # nothing is read until someone asks.
    min_snapshot_interval: float = 0.25

    def __post_init__(self):
        check_fold_mode(self.fold_mode)
        if self.cascade is not None:
            self.cascade.validate_base(self.interval)

    @property
    def ring(self) -> int:
        # number of simultaneously-open windows
        return self.delay // self.interval + 2


@dataclasses.dataclass
class _FlushEntry:
    """One dispatched-but-not-yet-fetched window advance: the packed
    exact flush handles plus (optionally) the sketch plane's pending
    blocks and the cascade's closed tier flushes. `_drain_flush` fetches
    the whole entry in the same two transfers regardless of what rode
    along."""

    packed: jnp.ndarray  # [S, 3+T+M] u32 device handle
    total: jnp.ndarray  # scalar i32 device handle
    lo: int
    hi: int
    pend: jnp.ndarray | None = None  # [P, WIDE] u32 (sketch plane on)
    pend_win: jnp.ndarray | None = None  # [P] u32
    pend_n: jnp.ndarray | None = None  # scalar i32
    # pooled sketch memory (ISSUE 20): the wide arena's closed slots
    # drain in place — [Pw, WIDE] rows + [Pw] window ids (SENTINEL_WIN
    # where the slot holds an open/free window). Zero-size in slab mode.
    wide_rows: jnp.ndarray | None = None
    wide_wins: jnp.ndarray | None = None
    tiers: list[TierFlush] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FlushedWindow:
    """One closed window's documents, host-resident and compacted.

    tags/meters are row-major ([n, T] u32 / [n, M] f32) — already
    unpacked from the single flush matrix, so consumers index rows
    directly instead of masking full-capacity device planes."""

    window_idx: int  # absolute window index (timestamp // interval)
    start_time: int  # window start in seconds
    key_hi: np.ndarray  # [n] u32
    key_lo: np.ndarray  # [n] u32
    tags: np.ndarray  # [n, T] u32
    meters: np.ndarray  # [n, M] f32
    count: int
    # the window's approximate summary (ISSUE 8) — present when the
    # sketch plane is enabled; count == 0 with a block attached means
    # the exact stash shed every row of this window but the sketch tier
    # still covered it (degradation of detail, not of coverage)
    sketches: WindowSketchBlock | None = None
    # rollup-cascade provenance (ISSUE 9): 0 = the manager's own
    # resolution; N ≥ 1 = the Nth cascade tier, with `interval` that
    # tier's seconds-per-window (window_idx and start_time are already
    # in tier units — consumers never rescale)
    tier: int = 0
    interval: int = 0
    # live read plane (ISSUE 10): True = a snapshot of a still-OPEN
    # window (rows may keep arriving; the later real flush supersedes
    # this view). Flushed windows are always partial=False.
    partial: bool = False


@dataclasses.dataclass
class OpenSnapshot:
    """One pull of the open device-resident window span (ISSUE 10).

    `windows` are partial=True FlushedWindows — same row layout and
    (window, stash position) order as the real flush, with the open
    sketch slots attached as (partial) WindowSketchBlocks where the
    plane is on. `seq` increments per actual device read (rate-limited
    by `min_snapshot_interval`; cached returns keep their seq) — the
    querier's result cache keys its live token on it, so repeated
    dashboards hit the cache until a NEW snapshot is taken. `open_from`
    is the open span's first second (None = nothing ingested yet)."""

    windows: list["FlushedWindow"]
    taken_monotonic: float
    open_from: int | None = None
    seq: int = 0


class WindowManager:
    """Owns one stash + the open-window span for one granularity."""

    def __init__(
        self,
        config: WindowConfig,
        tag_schema: TagSchema = TAG_SCHEMA,
        meter_schema: MeterSchema = FLOW_METER,
        *,
        tracer: SpanTracer | None = None,
    ):
        if config.stats_ring < 1:
            raise ValueError("stats_ring must be >= 1")
        if config.stats_ring > 1 and config.async_drain:
            raise ValueError(
                "stats_ring > 1 already defers stats fetches; combining it "
                "with async_drain would double-defer — pick one"
            )
        self.config = config
        self.tag_schema = tag_schema
        self.meter_schema = meter_schema
        self.state: StashState = stash_init(config.capacity, tag_schema, meter_schema)
        self.acc: AccumState | None = None  # sized on first batch
        self.fill = 0  # host-tracked accumulator rows
        self.start_window: int | None = None  # oldest open window idx
        self.drop_before_window = 0
        self.total_docs_in = 0
        self.total_flushed = 0
        self.aux_count = 0  # caller-defined stats[4] accumulator
        # device counter-block mirror (as of the last stats fetch; the
        # occupancy/eviction lanes snapshot dispatch time — i.e. the
        # post-fold, pre-flush stash of that batch)
        self.excess_word_hits = 0
        self.stash_occupancy = 0
        self.stash_evictions = 0
        self.device_ring_fill = 0
        self.fold_rows = 0  # CB_FOLD_ROWS mirror: last fold's sorted rows
        # device scalar the fold kernels return; rides into the next
        # dispatch's counter block like the stash lanes (zero transfer)
        self._fold_rows_dev = jnp.zeros((), jnp.uint32)
        # merge mode drains through the compacting range flush so the
        # stash keeps the canonical layout the rank-merge requires
        self._flush_compact = config.fold_mode == "merge"
        # cached zero [rows, shed] lane vector (cascade off)
        self._zero_lanes = jnp.zeros((2,), jnp.uint32)
        # per-window sketch plane (ISSUE 8): device state + the static
        # column-index tuple the fused step closes over; CB-lane mirrors
        self.sk: SketchState | None = None
        self._sketch_ix: tuple | None = None
        self.sketch_rows = 0
        self.sketch_shed = 0
        # pooled sketch memory (ISSUE 20, CB v7): device-lane mirrors —
        # counted spills, the occupancy gauge, and promotions. All zero
        # in slab mode.
        self.sketch_pool_spill = 0
        self.sketch_pool_occ = 0
        self.sketch_promotions = 0
        # closed blocks fetched but whose window has not flushed yet
        # (K-ring replay can drain blocks ahead of their flush range)
        self._sketch_blocks: dict[int, WindowSketchBlock] = {}
        if config.sketch is not None:
            self._sketch_ix = sketch_tag_indices(tag_schema, meter_schema)
            self.sk = sketch_init(config.sketch, config.ring)
        # multi-resolution rollup cascade (ISSUE 9): device tier stashes
        # + host watermarks/pending sketch merges; CB v5 lane mirrors
        self.cascade: TierCascade | None = None
        self.cascade_rows = 0
        self.cascade_shed = 0
        # closed tier windows awaiting a consumer (pop_tier_windows) —
        # bounded drop-oldest-counted like every other held buffer
        self.tier_flushed: list[FlushedWindow] = []
        self.max_held_tier_windows = 4096
        self.tier_windows_dropped = 0
        if config.cascade is not None:
            self.cascade = TierCascade(
                config.cascade, config.interval, tag_schema, meter_schema
            )
        self.n_advances = 0
        # device↔host transfer accounting (the host_fetch seam)
        self.host_fetches = 0
        self.bytes_fetched = 0
        self.bytes_uploaded = 0  # callers add their packed upload sizes
        self.feeder_shed = 0  # CB_FEEDER_SHED lane mirror
        # live read plane (ISSUE 10): host-authoritative snapshot
        # counters + the cached [reads, bytes] device vector riding into
        # every dispatch's counter block (rebuilt only when a snapshot
        # actually happens — steady state re-sends the same handle, so
        # no per-batch upload), the rate-limit cache, and the lane
        # mirrors the device plane reported at the last fetched block
        # (drift beyond the in-flight dispatch = bookkeeping bug)
        self.snapshot_reads = 0
        self.snapshot_bytes = 0
        self.snapshot_seq = 0
        self._snap_lanes_dev = jnp.zeros((2,), jnp.uint32)
        self._snapshot_cache: OpenSnapshot | None = None
        self.device_snapshot_reads = 0
        self.device_snapshot_bytes = 0
        # transient-failure policy (ISSUE 6): dispatch + fetch are
        # retried with backoff+jitter (per-instance decorrelated rng —
        # fault injection itself stays deterministic via the chaos
        # plan's own seeded rng). Retrying a dispatch is sound only for
        # admission-time failures (utils/retry.py has the donation
        # caveat) — the chaos seam fires BEFORE the jitted call, and
        # RESOURCE_EXHAUSTED-class rejections do too.
        self.retry_policy = RetryPolicy()
        self._retry_rng = decorrelated_rng(0xD15EA5E)
        self.dispatch_retries = 0
        self.fetch_retries = 0
        self.tracer = tracer if tracer is not None else SpanTracer()
        # window lineage plane (ISSUE 13): optional per-window hop
        # recorder (tracing/lineage.LineageTracker). Every hop is a
        # host wall stamp — attaching it never adds a device fetch
        # (CI-gated, test_perf_gate::test_lineage_tracing_budget).
        self.lineage = None
        # device profiling plane (ISSUE 12): every device-resident plane
        # this manager owns is enumerable via device_planes(), and the
        # manager registers WEAKLY on the process-wide HBM ledger (the
        # r13 tier-registry stance — GC removes it, close() eagerly so)
        from ..profiling.ledger import register_profilable

        self._ledger_src = register_profilable(
            "window_manager", self,
            interval=f"{config.interval}s",
            sketch=str(config.sketch is not None),
            cascade=str(config.cascade is not None),
        )
        # async-drain double buffers (device handles, fetched next call)
        self._pending_stats = None
        self._pending_flush: list[tuple] = []
        # K-batch counter ring (stats_ring > 1): device [K, CB_LEN] ring
        # + device-resident [start_window, opened] gate state; the host
        # mirror (start_window above) catches up at every ring drain.
        self._cb_ring = (
            jnp.zeros((config.stats_ring, CB_LEN), jnp.uint32)
            if config.stats_ring > 1 else None
        )
        self._ring_count = 0  # blocks in the ring awaiting the fetch
        self._sw_state = (
            jnp.zeros((2,), jnp.uint32) if config.stats_ring > 1 else None
        )

    def _fetch(self, x) -> np.ndarray:
        """host_fetch + per-manager transfer accounting (count + bytes).
        Transient fetch failures (timeouts on the tunnel, injected
        chaos faults) retry with backoff — the device handle stays
        valid across a blown fetch deadline."""

        def once():
            chaos.maybe_fail(chaos.SITE_FETCH)
            return host_fetch(x)

        def on_retry(_attempt, _exc):
            self.fetch_retries += 1

        arr = retry_call(once, self.retry_policy, on_retry=on_retry,
                         rng=self._retry_rng)
        self.host_fetches += 1
        self.bytes_fetched += arr.nbytes
        return arr

    # -- device→host drains ---------------------------------------------
    def _drain_flush(self, entry: "_FlushEntry") -> list[FlushedWindow]:
        """Fetch ONE packed flush result and split it into windows.

        Two transfers regardless of row/window count — with the sketch
        plane and/or the rollup cascade enabled the SAME two transfers
        also carry the closed sketch blocks and the closed TIER windows'
        rows: the scalar fetch widens to [row count, pending block
        count, tier row counts…] and the row fetch becomes one
        concatenated u32 transfer (flush rows ‖ packed blocks ‖ block
        window ids ‖ tier rows per tier), so the ≤3-fetch budget is
        untouched (tests/test_perf_gate.py)."""
        has_sketch = entry.pend is not None
        # pooled sketch memory (ISSUE 20): closed WIDE slots ride the
        # same two transfers. The scalar vector widens by one lane
        # (closed-wide count, so a drain with none skips the wide bytes
        # entirely); when any closed, all Pw rows + window ids join the
        # concatenated fetch and the host filters on SENTINEL_WIN —
        # Pw is a handful of rows, the filter is cheaper than a device
        # compaction.
        has_wide = entry.wide_rows is not None and entry.wide_rows.size > 0
        scalars = [jnp.asarray(entry.total, jnp.int32)]
        if has_sketch:
            scalars.append(jnp.asarray(entry.pend_n, jnp.int32))
        if has_wide:
            scalars.append(
                jnp.sum(entry.wide_wins != jnp.uint32(SENTINEL_WIN)).astype(
                    jnp.int32
                )
            )
        scalars += [jnp.asarray(tf.total, jnp.int32) for tf in entry.tiers]
        n_wide = 0
        if len(scalars) == 1:
            total, n_blocks, tier_totals = int(self._fetch(scalars[0])), 0, []
        else:
            vec = self._fetch(jnp.stack(scalars))
            o = 1 + int(has_sketch) + int(has_wide)
            total = int(vec[0])
            n_blocks = int(vec[1]) if has_sketch else 0
            if has_wide:
                n_wide = int(vec[1 + int(has_sketch)])
            tier_totals = [int(v) for v in vec[o:]]
        if not has_sketch and not entry.tiers and total == 0:
            # pure exact-only drain with nothing flushed. The sketch and
            # cascade paths must NOT return here even with every count
            # zero: previously-held blocks may still marry this drain's
            # [lo, hi) range, and a tier window whose exact rows were
            # all shed (sketch-only coverage) still closes below.
            return []
        row_cols = entry.packed.shape[1]
        wide = entry.pend.shape[1] if has_sketch else 0
        if total == 0 and n_blocks == 0 and n_wide == 0 and not any(tier_totals):
            flat = np.zeros((0,), np.uint32)  # nothing to transfer
        else:
            parts = [entry.packed[:total].reshape(-1)]
            if has_sketch:
                parts += [entry.pend[:n_blocks].reshape(-1),
                          entry.pend_win[:n_blocks]]
            if n_wide:
                parts += [entry.wide_rows.reshape(-1), entry.wide_wins]
            for tf, t in zip(entry.tiers, tier_totals):
                parts.append(tf.packed[:t].reshape(-1))
            if len(parts) == 1:
                # nothing rode along — fetch the 2D rows directly (the
                # reshape+concatenate would compile a kernel per
                # distinct `total`, a real tax at one advance/second)
                flat = self._fetch(entry.packed[:total]).reshape(-1)
            else:
                flat = self._fetch(jnp.concatenate(parts))
        o = 0

        def take(n: int) -> np.ndarray:
            nonlocal o
            out = flat[o : o + n]
            o += n
            return out

        rows = take(total * row_cols).reshape(total, row_cols)
        flushed = []
        if has_sketch:
            block_rows = take(n_blocks * wide).reshape(n_blocks, wide)
            wins = take(n_blocks)
            for blk in unpack_drained(block_rows, wins, self.config.sketch):
                have = self._sketch_blocks.get(blk.window)
                self._sketch_blocks[blk.window] = (
                    blk if have is None else have.merge(blk)
                )
        if n_wide:
            pw, wide_w = entry.wide_rows.shape
            w_rows = take(pw * wide_w).reshape(pw, wide_w)
            w_wins = take(pw)
            keep = w_wins != np.uint32(SENTINEL_WIN)
            for blk in unpack_drained(w_rows[keep], w_wins[keep],
                                      self.config.sketch):
                have = self._sketch_blocks.get(blk.window)
                self._sketch_blocks[blk.window] = (
                    blk if have is None else have.merge(blk)
                )
        if total:
            flushed = self._split_flushed(rows, total)
        # marry blocks to this drain's window range; blocks whose exact
        # rows were all shed become sketch-only windows (count == 0)
        for f in flushed:
            f.sketches = self._sketch_blocks.pop(f.window_idx, None)
        exact_wins = {f.window_idx for f in flushed}
        lo, hi = entry.lo, entry.hi
        for w in sorted(self._sketch_blocks):
            if lo <= w < hi and w not in exact_wins:
                blk = self._sketch_blocks.pop(w)
                flushed.append(
                    FlushedWindow(
                        window_idx=w,
                        start_time=w * self.config.interval,
                        key_hi=np.zeros((0,), np.uint32),
                        key_lo=np.zeros((0,), np.uint32),
                        tags=np.zeros((0, self.tag_schema.num_fields), np.uint32),
                        meters=np.zeros(
                            (0, self.meter_schema.num_fields), np.float32
                        ),
                        count=0,
                        sketches=blk,
                    )
                )
        flushed.sort(key=lambda f: f.window_idx)
        lin = self.lineage
        if lin is not None and flushed:
            lin.note_flush_windows([(f.window_idx, f.count) for f in flushed])
        if self.cascade is not None:
            # this drain's closed child blocks feed the parent merge
            # BEFORE tier windows are built, so a parent closing in the
            # same drain sees every child (merge order is immaterial —
            # the r12 associativity pins)
            for f in flushed:
                if f.sketches is not None:
                    self.cascade.feed_block(0, f.window_idx, f.sketches)
            tier_wins: list[FlushedWindow] = []
            for tf, t in zip(entry.tiers, tier_totals):
                t_rows = take(t * row_cols).reshape(t, row_cols)
                tier_wins.extend(self.cascade.take_tier_windows(tf, t_rows, t))
            if lin is not None and tier_wins:
                lin.note_tier_windows(
                    [(f.interval, f.window_idx, f.count) for f in tier_wins]
                )
            from .sketchplane import hold_blocks

            self.tier_windows_dropped += hold_blocks(
                self.tier_flushed, tier_wins, self.max_held_tier_windows
            )
        return flushed

    def _split_rows(
        self, rows: np.ndarray, total: int, *, partial: bool = False
    ) -> list[FlushedWindow]:
        """Packed (window, stash position)-ordered rows → per-window
        FlushedWindows. Shared by the real flush drain and the live
        snapshot (partial=True) so both split identically."""
        if total == 0:
            return []
        win, key_hi, key_lo, tags, meters = unpack_flush_rows(
            rows, self.tag_schema.num_fields
        )
        flushed = []
        bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1]]).tolist() + [total]
        for a, b in zip(bounds, bounds[1:]):
            w = int(win[a])
            flushed.append(
                FlushedWindow(
                    window_idx=w,
                    start_time=w * self.config.interval,
                    key_hi=key_hi[a:b],
                    key_lo=key_lo[a:b],
                    tags=tags[a:b],
                    meters=meters[a:b],
                    count=b - a,
                    partial=partial,
                )
            )
        return flushed

    def _split_flushed(self, rows: np.ndarray, total: int) -> list[FlushedWindow]:
        self.total_flushed += total
        return self._split_rows(rows, total)

    def _drain_ready(self, ready) -> list[FlushedWindow]:
        if not ready:
            return []
        with self.tracer.span(SPAN_FLUSH_DRAIN):
            out = []
            for entry in ready:
                out.extend(self._drain_flush(entry))
            return out

    def _fold(self):
        """Full-set fold: every accumulated row reaches the stash and
        the ring resets. fold_mode picks the kernel — the full [S+A]
        re-sort or the rank merge — but both consume the whole ring."""
        if self.fill == 0:
            return
        with self.tracer.span(SPAN_WINDOW_FOLD):
            if self.config.fold_mode == "merge":
                self.state, self.acc, self._fold_rows_dev = stash_merge_fold(
                    self.state, self.acc, self.meter_schema
                )
            else:
                self.state, self.acc, self._fold_rows_dev = stash_fold_counted(
                    self.state, self.acc, self.meter_schema
                )
        self.fill = 0

    def _fold_span(self, hi_window: int):
        """Span-bounded advance fold (fold_mode="merge"): merge ONLY the
        acc rows with slot < hi_window — the windows about to flush —
        and leave the rest accumulated. `fill` stays put: consumed rows
        turn sentinel in place and their ring slots are reclaimed by the
        next full fold (plan_append cadence)."""
        if self.fill == 0:
            return
        with self.tracer.span(SPAN_WINDOW_FOLD):
            self.state, self.acc, self._fold_rows_dev = stash_merge_fold(
                self.state, self.acc, self.meter_schema,
                hi_window=np.uint32(hi_window),
            )

    def window_of(self, timestamp):
        return timestamp // self.config.interval

    def attach_lineage(self, tracker) -> None:
        """Wire a tracing/lineage.LineageTracker: dispatch stamps,
        advance/flush/tier-close hops and the freshness lags all record
        from this manager's existing host seams."""
        self.lineage = tracker

    def _lineage_span_of(self, timestamp, valid) -> tuple[int, int] | None:
        """Host-side window span of one batch — ONLY when the arrays
        are already host-resident (a jnp input would force the transfer
        the zero-fetch contract forbids)."""
        if not isinstance(timestamp, np.ndarray):
            return None
        # the valid mask must be host too — np.asarray on a jnp array
        # would force the very transfer the zero-fetch contract forbids
        v = valid if isinstance(valid, np.ndarray) else None
        ts = timestamp[v.astype(bool)] \
            if (v is not None and v.shape == timestamp.shape) else timestamp
        if ts.size == 0:
            return None
        iv = self.config.interval
        return int(ts.min()) // iv, int(ts.max()) // iv

    def _cascade_lanes(self) -> jnp.ndarray:
        """Device [rows, shed] vector for the counter block's v5 lanes —
        the cascade's when configured, a cached zero vector otherwise
        (same handle every dispatch, so no per-batch upload)."""
        if self.cascade is not None:
            return self.cascade.lanes_dev
        return self._zero_lanes

    def pop_tier_windows(self) -> list[FlushedWindow]:
        """Drain the cascade's closed tier windows (1m/1h…), oldest
        first. Each FlushedWindow carries tier ≥ 1 and its tier
        `interval`; count == 0 with a sketch block attached means the
        exact tier stash shed the window but the merged child sketches
        still cover it."""
        out, self.tier_flushed = self.tier_flushed, []
        return out

    # -- live read plane (ISSUE 10) --------------------------------------
    def _snapshot_lanes(self) -> jnp.ndarray:
        """Device [reads, bytes] vector for the counter block's v6 lanes
        — cached, rebuilt only when a snapshot happens, so steady-state
        dispatches re-send the same handle (no per-batch upload)."""
        return self._snap_lanes_dev

    def snapshot_open(self, *, force: bool = False) -> OpenSnapshot:
        """Pull a read-only snapshot of the OPEN window span: every
        stash row with slot ≥ start_window (the accumulator ring is
        folded in first — a pure device dispatch, zero fetches, the
        same fold the next advance would run) plus the open sketch
        slots, fetched in the flush drain's 2-transfer shape (one
        scalar, one concatenated row block). The stash is untouched
        (stash_snapshot_range does not donate), so the later real flush
        of these windows emits the same rows plus whatever arrived
        after the snapshot — the overlay contract the querier relies
        on: flushed rows SUPERSEDE a window's partial snapshot.

        Rate-limited: within `min_snapshot_interval` seconds the cached
        OpenSnapshot returns (same seq — result caches stay hot);
        `force=True` bypasses. Pull-only: ingest never takes one.
        Caveat: the eager fold means that under stash OVERFLOW a
        snapshot can shed at the pull instead of the next natural fold
        — same counted-shed stance, possibly earlier (fold_mode="merge"
        deferral note in WindowConfig)."""
        now = time.monotonic()
        cached = self._snapshot_cache
        if (
            not force
            and cached is not None
            and now - cached.taken_monotonic < self.config.min_snapshot_interval
        ):
            return cached
        with self.tracer.span(SPAN_QUERY_SNAPSHOT):
            snap = self._read_open_snapshot(now)
        self.snapshot_seq += 1
        snap.seq = self.snapshot_seq
        if self.lineage is not None and snap.windows:
            # a live read served these still-open windows: the DISTINCT
            # partial lane (ISSUE 13 — never confusable with post-flush
            # visibility)
            self.lineage.note_snapshot(
                [(w.window_idx, w.count) for w in snap.windows]
            )
        self._snap_lanes_dev = jnp.asarray(
            [self.snapshot_reads & 0xFFFFFFFF, self.snapshot_bytes & 0xFFFFFFFF],
            dtype=jnp.uint32,
        )
        self._snapshot_cache = snap
        return snap

    def _read_open_snapshot(self, now: float) -> OpenSnapshot:
        if self.start_window is None:
            self.snapshot_reads += 1
            return OpenSnapshot(windows=[], taken_monotonic=now)
        b0, f0 = self.bytes_fetched, self.host_fetches
        self._fold()  # ring rows → stash (exact; zero fetches)
        packed, total = stash_snapshot_range(
            self.state, np.uint32(self.start_window), _U32_MAX
        )
        blocks = wins = None
        if self.sk is not None:
            blocks, wins = _sketch_open_snapshot(self.sk)
        total_i = int(self._fetch(jnp.asarray(total, jnp.int32)))
        row_cols = packed.shape[1]
        if blocks is None:
            if total_i:
                rows = self._fetch(packed[:total_i])
            else:
                rows = np.zeros((0, row_cols), np.uint32)
            windows = self._split_rows(rows, total_i, partial=True)
        else:
            r, wide = blocks.shape
            flat = self._fetch(
                jnp.concatenate(
                    [packed[:total_i].reshape(-1), blocks.reshape(-1), wins]
                )
            )
            nb = total_i * row_cols
            rows = flat[:nb].reshape(total_i, row_cols)
            block_rows = flat[nb : nb + r * wide].reshape(r, wide)
            win_np = flat[nb + r * wide :]
            windows = self._split_rows(rows, total_i, partial=True)
            live = win_np != np.uint32(SENTINEL_WIN)
            open_blocks = {
                blk.window: blk
                for blk in unpack_drained(
                    block_rows[live], win_np[live], self.config.sketch
                )
            }
            windows = attach_open_sketch_blocks(
                windows, open_blocks,
                interval=self.config.interval,
                num_tags=self.tag_schema.num_fields,
                num_meters=self.meter_schema.num_fields,
            )
        self.snapshot_reads += 1
        self.snapshot_bytes += self.bytes_fetched - b0
        assert self.host_fetches - f0 <= 2, "snapshot must stay a 2-fetch read"
        return OpenSnapshot(
            windows=windows,
            taken_monotonic=now,
            open_from=self.start_window * self.config.interval,
        )

    # -- stats processing (the ONE per-batch host sync) ------------------
    def _process_stats(self, stats_dev) -> None:
        """Fetch one batch's packed counter block and replay it through
        the host bookkeeping (`_process_block`)."""
        with self.tracer.span(SPAN_STATS_FETCH):
            vec = [int(v) for v in self._fetch(stats_dev)]
        self._process_block(vec)

    def _drain_stats_ring(self) -> None:
        """Fetch the filled prefix of the counter ring in ONE transfer
        and replay every block in dispatch order — window advances land
        exactly where per-batch fetching would have put them, just
        discovered (and flushed) at the drain instead of mid-ring."""
        if self._ring_count == 0:
            return
        with self.tracer.span(SPAN_STATS_FETCH):
            rows = self._fetch(self._cb_ring[: self._ring_count])
        self._ring_count = 0
        for row in rows:
            self._process_block([int(v) for v in row])

    def _sync_device_sw(self) -> None:
        """Reset the device gate state to the host span (checkpoint
        restore / external start_window mutation). Only meaningful with
        stats_ring > 1; requires a drained ring."""
        if self._sw_state is None:
            return
        if self._ring_count:
            raise RuntimeError("cannot resync device gate over a filled ring")
        sw = 0 if self.start_window is None else self.start_window
        opened = 0 if self.start_window is None else 1
        self._sw_state = jnp.asarray([sw, opened], dtype=jnp.uint32)

    def _process_block(self, vec: list[int]) -> None:
        """One batch's counter block → host counters, open-span advance
        and the (dispatched, not fetched) range flush.

        Accepts both the versioned CB_LEN block (element 0 =
        COUNTER_BLOCK_VERSION) and the legacy 5-scalar stats vector, so
        caller-supplied dispatch steps can widen incrementally."""
        lin = self.lineage
        # one block = one dispatch: pop its wall stamp FIRST (whether or
        # not this block advances) so the FIFO pairing stays aligned
        # across K-ring drains and async settles
        lin_stamp = lin.pop_dispatch_stamp() if lin is not None else None
        if len(vec) == CB_LEN:
            if vec[CB_VERSION] != COUNTER_BLOCK_VERSION:
                raise ValueError(
                    f"counter block version {vec[CB_VERSION]} != "
                    f"{COUNTER_BLOCK_VERSION} — device/host layout drift"
                )
            t_max, t_min, n_valid, n_late, aux = vec[CB_T_MAX:CB_PREREDUCE_SHED + 1]
            self.excess_word_hits += vec[CB_EXCESS_HITS]
            self.stash_occupancy = vec[CB_STASH_OCCUPANCY]
            self.stash_evictions = vec[CB_STASH_EVICTIONS]
            self.device_ring_fill = vec[CB_RING_FILL]
            self.feeder_shed += vec[CB_FEEDER_SHED]
            self.fold_rows = vec[CB_FOLD_ROWS]
            # cumulative device scalars — mirror, don't accumulate
            self.sketch_rows = vec[CB_SKETCH_ROWS]
            self.sketch_shed = vec[CB_SKETCH_SHED]
            self.cascade_rows = vec[CB_CASCADE_ROWS]
            self.cascade_shed = vec[CB_CASCADE_SHED]
            # live-read lanes: the host ints above stay authoritative;
            # these are what the device plane carried at that dispatch
            self.device_snapshot_reads = vec[CB_SNAPSHOT_READS]
            self.device_snapshot_bytes = vec[CB_SNAPSHOT_BYTES]
            # pooled sketch memory (ISSUE 20): spill/promotions are
            # cumulative device scalars (mirror), occupancy is a gauge
            self.sketch_pool_spill = vec[CB_SKETCH_POOL_SPILL]
            self.sketch_pool_occ = vec[CB_SKETCH_POOL_OCC]
            self.sketch_promotions = vec[CB_SKETCH_PROMOTIONS]
        elif len(vec) == 5:  # legacy [t_max, t_min, n_valid, n_late, aux]
            t_max, t_min, n_valid, n_late, aux = vec
        else:
            raise ValueError(
                f"counter block of {len(vec)} lanes is neither the "
                f"v{COUNTER_BLOCK_VERSION} CB_LEN={CB_LEN} block nor the "
                "legacy 5-vector — device/host layout drift"
            )
        self.aux_count += aux
        if n_valid == 0:
            return
        if self.start_window is None:
            # Open the ring far enough back that data older than the first
            # batch but within `delay` is still accepted — the reference
            # starts its window 2min in the past for the same reason
            # (quadruple_generator.rs:782-783). The first batch was gated
            # at window 0, which admits exactly the same rows: this start
            # is ≤ the first batch's oldest valid window.
            self.start_window = self.window_of(
                max(0, min(t_min, t_max - self.config.delay))
            )
        self.drop_before_window += n_late
        self.total_docs_in += n_valid - n_late

        # Advance: every window whose end is more than `delay` behind the
        # newest arrival closes now (move_window, quadruple_generator.rs:339).
        # ALL closed windows flush in ONE fused device call; empty
        # intermediate windows shift silently (the packed matrix simply
        # has no rows for them), so a large timestamp gap costs nothing.
        new_start = self.window_of(max(t_max - self.config.delay, 0))
        if self.start_window < new_start:
            with self.tracer.span(SPAN_WINDOW_ADVANCE):
                # flushed windows must see every accumulated row of the
                # closing span; merge mode folds ONLY that span and
                # leaves open windows' rows in the ring
                if self.config.fold_mode == "merge":
                    self._fold_span(new_start)
                else:
                    self._fold()
                self.state, packed, total = stash_flush_range(
                    self.state,
                    np.uint32(self.start_window),
                    np.uint32(new_start),
                    compact=self._flush_compact,
                )
                self._pending_flush.append(
                    self._make_flush_entry(
                        packed, total, self.start_window, new_start
                    )
                )
                if lin is not None:
                    lin.note_advance(self.start_window, new_start, lin_stamp)
                self.start_window = new_start
                self.n_advances += 1

    def _make_flush_entry(self, packed, total, lo: int, hi: int) -> "_FlushEntry":
        """Build one _pending_flush entry: the exact flush handles,
        widened with the sketch plane's pending-drain handles and the
        cascade's tier fold+flush handles (extra DISPATCHES on the
        advance path only, zero extra fetches — _drain_flush bundles
        everything into the existing two transfers)."""
        entry = _FlushEntry(packed=packed, total=total, lo=int(lo), hi=int(hi))
        if self.sk is not None:
            (self.sk, entry.pend, entry.pend_win, entry.pend_n,
             entry.wide_rows, entry.wide_wins) = sketch_drain(
                self.sk, np.uint32(hi)
            )
        if self.cascade is not None:
            entry.tiers = self.cascade.on_advance(packed, total, int(hi))
        return entry

    # -- ingest ----------------------------------------------------------
    def ingest(
        self,
        timestamp,  # [N] u32 seconds (device or host)
        key_hi,
        key_lo,
        tags,
        meters,
        valid,
        feeder_shed: int = 0,
    ) -> list[FlushedWindow]:
        """Merge a doc batch; advance and flush any windows that closed.

        Returns flushed windows in order (possibly empty). With
        `async_drain`, returns the windows closed by the *previous*
        batch instead (double-buffered — see WindowConfig).
        `feeder_shed` rides into the counter block's CB_FEEDER_SHED
        lane (upstream drop accounting, ISSUE 4)."""
        window_span = (
            self._lineage_span_of(timestamp, valid)
            if self.lineage is not None else None
        )
        timestamp = jnp.asarray(timestamp, dtype=jnp.uint32)
        rows = int(timestamp.shape[0])
        interval = self.config.interval

        if self.sk is not None:
            def dispatch(acc, offset, start_window):
                # sketch-enabled twin: the plane state reads/donates at
                # dispatch time like the stash lanes; the step returns
                # the updated plane as a third output
                st = self.state
                return _raw_append_step_sk(
                    acc, offset, start_window, st.valid, st.dropped_overflow,
                    jnp.uint32(feeder_shed), self._fold_rows_dev,
                    self._cascade_lanes(), self._snapshot_lanes(), self.sk,
                    timestamp, key_hi, key_lo, tags, meters, valid,
                    interval=interval, delay=self.config.delay,
                    ix=self._sketch_ix, spec=self.config.sketch.hist,
                    # env knobs read at DISPATCH time (static argnames —
                    # the step is module-level-jitted, so a flip must
                    # recompile rather than silently keep the old path)
                    shared_sort=_use_shared_sort(),
                    fused_sketch=_use_fused_sketch(),
                )
        else:
            def dispatch(acc, offset, start_window):
                # read the stash AT DISPATCH time (ingest_step may fold
                # first) so the block's occupancy/fold_rows lanes see the
                # post-fold plane; all lanes are device-resident — zero
                # transfer
                st = self.state
                return _raw_append_step(
                    acc, offset, start_window, st.valid, st.dropped_overflow,
                    jnp.uint32(feeder_shed), self._fold_rows_dev,
                    self._cascade_lanes(), self._snapshot_lanes(),
                    timestamp, key_hi, key_lo, tags, meters, valid,
                    interval=interval,
                )

        return self.ingest_step(dispatch, rows, window_span=window_span)

    def ingest_step(
        self, dispatch, rows: int, ring_rows: int | None = None,
        window_span: tuple[int, int] | None = None,
    ) -> list[FlushedWindow]:
        """Window protocol around a caller-supplied jitted append step.

        `dispatch(acc, offset, start_window)` must return (new_acc,
        stats[5]) with stats as produced by `batch_stats` — pipelines use
        this to fuse fanout/fingerprint/pre-reduce into the same single
        device call (aggregator/pipeline.py). `rows` is the static number
        of accumulator rows the step appends; `ring_rows` (≥ rows) sizes
        the accumulator ring when bucketed callers know a larger batch
        shape is coming, so a small first bucket doesn't build a ring a
        later big bucket immediately replaces. `window_span` (lo, hi —
        host-computed from the batch's own timestamps) binds this
        dispatch to the lineage plane when one is attached (ISSUE 13)."""
        if rows == 0:
            return self._settle_ready()

        ready = self._pending_flush
        self._pending_flush = []

        if self._pending_stats is not None:
            # async: settle the previous batch BEFORE this one's gate —
            # start_window advances exactly as it would have in sync mode.
            stats, self._pending_stats = self._pending_stats, None
            self._process_stats(stats)

        plan = plan_append(self.fill, self.acc.capacity if self.acc else None, rows)
        if plan == "init":
            self._fold()  # pending rows must reach the stash before the ring is replaced
            if self.fill:
                # the plan_append docstring warns that replacing a ring
                # with pending rows silently loses them — make that
                # failure LOUD if a refactor ever bypasses the fold
                # (e.g. wires a span-bounded fold in here)
                raise AssertionError(
                    f"accumulator ring re-init with {self.fill} pending "
                    "rows — they would be silently lost (plan_append "
                    "'init' contract: fold before replacing the ring)"
                )
            base = max(ring_rows or rows, rows)
            self.acc = accum_init(
                max(self.config.accum_batches * base, rows),
                self.tag_schema,
                self.meter_schema,
            )
        elif plan == "fold":
            self._fold()
        K = self.config.stats_ring
        if K > 1:
            # the gate state is DEVICE-resident between ring drains —
            # the host span may lag by up to K-1 batches, but the gate
            # each batch sees matches per-batch mode exactly
            sw_arg = self._sw_state[0]
        else:
            sw_arg = jnp.uint32(
                0 if self.start_window is None else self.start_window
            )
        def dispatch_once():
            # the chaos seam fires BEFORE the jitted call, so a retried
            # injected fault never sees a consumed (donated) accumulator
            chaos.maybe_fail(chaos.SITE_DISPATCH)
            return dispatch(self.acc, jnp.int32(self.fill), sw_arg)

        def on_retry(_attempt, _exc):
            self.dispatch_retries += 1

        lin = self.lineage
        d0 = lin.clock() if lin is not None else 0.0
        with self.tracer.span(SPAN_INGEST_DISPATCH):
            # admission-time-only classification: the step donates its
            # accumulator (and sketch plane), so a mid-flight
            # UNAVAILABLE/ABORTED must NOT retry against consumed buffers
            out = retry_call(
                dispatch_once, self.retry_policy, on_retry=on_retry,
                rng=self._retry_rng, classify=is_dispatch_transient,
            )
            if self.sk is not None:
                self.acc, stats_dev, self.sk = out
            else:
                self.acc, stats_dev = out
        if lin is not None:
            # bind the batch's window span (host timestamps) + push the
            # wall stamp the counter-block replay pops — device-side
            # hop times are DERIVED from this pairing, never fetched
            lin.note_dispatch(window_span, d0)
        self.fill += rows

        if K > 1:
            self._cb_ring, self._sw_state = _stats_ring_push(
                self._cb_ring, jnp.int32(self._ring_count), self._sw_state,
                stats_dev,
                interval=self.config.interval, delay=self.config.delay,
            )
            self._ring_count += 1
            if self._ring_count >= K:
                self._drain_stats_ring()
        elif self.config.async_drain:
            # defer only the STATS fetch: the host returns before this
            # batch's compute finishes, and the previous batch's flush
            # (dispatched above, before this append) is fetched below —
            # its transfer overlaps this batch's in-flight append.
            self._pending_stats = stats_dev
        else:
            self._process_stats(stats_dev)
        ready.extend(self._pending_flush)
        self._pending_flush = []
        return self._drain_ready(ready)

    def _settle_ready(self) -> list[FlushedWindow]:
        """Drain whatever finished without appending anything new."""
        ready = self._pending_flush
        self._pending_flush = []
        return self._drain_ready(ready)

    def settle(self) -> list[FlushedWindow]:
        """Fetch every deferred buffer (counter-ring blocks, pending
        async stats, dispatched flushes) so host counters/span are
        consistent with the device — the drain-on-checkpoint rule.
        Returns the windows that were in flight — callers that snapshot
        state (checkpoint.save_window_state) MUST emit them, since
        their rows have already left the stash."""
        self._drain_stats_ring()
        if self._pending_stats is not None:
            stats, self._pending_stats = self._pending_stats, None
            self._process_stats(stats)
        return self._settle_ready()

    # -- device profiling plane (ISSUE 12) --------------------------------
    def device_planes(self) -> dict:
        """Profilable face: every device-resident plane this manager
        owns, by name — the HBM ledger walks these (metadata-only
        `.nbytes`, zero fetches). The enumeration IS the ownership
        contract: a new device buffer added to the manager without a
        plane entry here fails the ledger reconciliation test."""
        planes: dict[str, object] = {
            "stash": self.state,
            "accumulator": self.acc,  # None until the first batch
            "stats_ring": [self._cb_ring, self._sw_state],
            "lanes": [self._fold_rows_dev, self._zero_lanes,
                      self._snap_lanes_dev],
            # async-drain holds: the deferred stats vector plus every
            # dispatched-but-unfetched flush's device handles (packed
            # rows, sketch pending, tier flushes) — real HBM between
            # ingest calls, up to a full packed flush block in steady
            # async operation (_FlushEntry/TierFlush are plain
            # dataclasses, not pytrees, so the handles list explicitly)
            "pending_flush": [self._pending_stats] + [
                [e.packed, e.total, e.pend, e.pend_win, e.pend_n,
                 e.wide_rows, e.wide_wins]
                + [[tf.packed, tf.total] for tf in e.tiers]
                for e in self._pending_flush
            ],
        }
        if self.sk is not None:
            if _pool_mode(self.sk):
                # pooled sketch memory (ISSUE 20): split the plane so
                # the ledger's per-pool HBM rows show where the bytes
                # live — the compact hot arena, the wide arena, the
                # pending drain buffer, and the routing/counter meta
                sk = self.sk
                planes["sketch_pool_hot"] = [
                    sk.p_hll, sk.p_cms, sk.p_hist, sk.p_tkv, sk.p_tkh,
                    sk.p_tkl, sk.p_tia, sk.p_tib,
                ]
                planes["sketch_pool_wide"] = [
                    sk.hll, sk.cms, sk.hist, sk.tk_votes, sk.tk_hi,
                    sk.tk_lo, sk.tk_ida, sk.tk_idb,
                ]
                planes["sketch_pending"] = [sk.pend, sk.pend_win]
                planes["sketch_meta"] = [
                    sk.win, sk.count, sk.slot_of, sk.wide_close,
                    sk.wide_count, sk.rows, sk.shed, sk.pend_n,
                    sk.pool_spill, sk.pool_promos, sk.promote_fill,
                ]
            else:
                planes["sketch"] = self.sk
        if self.cascade is not None:
            planes["cascade"] = [
                self.cascade.tiers, self.cascade.accs, self.cascade.fills,
                self.cascade.lanes_dev,
            ]
        return planes

    def close(self) -> None:
        """Eager teardown of the profiling registrations (the weakref
        would get there eventually; close() makes 'this manager's HBM
        left the ledger' a synchronous statement, like the r13 cascade
        tier registry)."""
        from ..profiling.ledger import default_ledger

        default_ledger.deregister(self._ledger_src)

    def make_feeder(self, queues, bucket_sizes, config=None, **kw):
        """Wire this manager behind a feeder runtime: METRICS pb frames
        from `queues` decode via ingest/codec.py and coalesce into
        bucket-shaped doc appends (feeder/runtime.WindowManagerFeedSink)."""
        from ..feeder import FeederConfig, FeederRuntime, WindowManagerFeedSink

        return FeederRuntime(
            queues, WindowManagerFeedSink(self, bucket_sizes),
            config or FeederConfig(), **kw,
        )

    def flush_all(self) -> list[FlushedWindow]:
        """Drain every open window (shutdown path)."""
        flushed = self.settle()
        if self.start_window is None:
            return flushed
        self._fold()
        self.state, packed, total = stash_flush_range(
            self.state, np.uint32(0), _U32_MAX, compact=self._flush_compact
        )
        self._pending_flush.append(
            self._make_flush_entry(packed, total, 0, int(_U32_MAX))
        )
        flushed += self._settle_ready()
        for f in flushed:
            self.start_window = max(self.start_window, f.window_idx + 1)
        # the host span just jumped past every drained window; with a
        # counter ring the DEVICE gate must follow, or a straggler
        # ingest re-admits rows into already-emitted windows (the ring
        # is drained — settle() above — so the resync is legal)
        self._sync_device_sw()
        return flushed

    def get_counters(self) -> dict:
        """Countable face (utils/stats.StatsCollector): host ints and the
        device counter-block cache ONLY — no device access, so a ticking
        collector thread can sample mid-ingest without racing a dispatch
        or burning a host sync. `stash_occupancy`/`stash_evictions` are
        as of the last fused append dispatch; the `counters` property
        below fetches the live values when a probe wants them."""
        return {
            "doc_in": self.total_docs_in,
            "flushed_doc": self.total_flushed,
            "drop_before_window": self.drop_before_window,
            "prereduce_shed": self.aux_count,
            "excess_word_hits": self.excess_word_hits,
            "stash_occupancy": self.stash_occupancy,
            "stash_evictions": self.stash_evictions,
            "acc_fill": self.fill,  # rows awaiting the next fold
            # device-reported ring fill at last dispatch — must track
            # acc_fill minus the in-flight batch; drift = host/device
            # bookkeeping bug
            "device_ring_fill": self.device_ring_fill,
            # rows the last fold's keyed sort touched (CB_FOLD_ROWS, as
            # of the last fetched block): full-sort mode counts the
            # whole live stash + ring, merge mode only the folded acc
            # rows — the lane the fold-work perf gate watches (ISSUE 5)
            "fold_rows": self.fold_rows,
            "window_advances": self.n_advances,
            "host_fetches": self.host_fetches,
            "bytes_fetched": self.bytes_fetched,
            "bytes_uploaded": self.bytes_uploaded,
            # transient-failure lanes (ISSUE 6): non-zero means the
            # retry policy absorbed device/tunnel hiccups
            "dispatch_retries": self.dispatch_retries,
            "fetch_retries": self.fetch_retries,
            # feeder-pressure lane + counter-ring occupancy (ISSUE 4);
            # blocks awaiting the 1/K fetch mean host counters may trail
            # the device by up to stats_ring_pending batches
            "feeder_shed": self.feeder_shed,
            "stats_ring_pending": self._ring_count,
            # sketch-plane lanes (ISSUE 8, CB v4): cumulative rows the
            # plane folded / counted-shed as of the last fetched block —
            # sketch_rows > 0 is the CI assertion that sketch updates
            # actually ran inside the fused dispatch
            "sketch_rows": self.sketch_rows,
            "sketch_shed": self.sketch_shed,
            # pooled sketch memory (ISSUE 20, CB v7): spill > 0 means
            # windows wanted a compact pool slot when none was free —
            # counted, never silent; occupancy is the at-dispatch gauge
            "sketch_pool_spill": self.sketch_pool_spill,
            "sketch_pool_occ": self.sketch_pool_occ,
            "sketch_promotions": self.sketch_promotions,
            # rollup-cascade lanes (ISSUE 9, CB v5): cumulative closed
            # child rows the tier folds consumed / tier-stash overflow
            # sheds, as of the last fetched block; plus the host-side
            # tier-window accounting (held > 0 and rising dropped means
            # nobody drains pop_tier_windows)
            "cascade_rows": self.cascade_rows,
            "cascade_shed": self.cascade_shed,
            "tier_windows_held": len(self.tier_flushed),
            "tier_windows_dropped": self.tier_windows_dropped,
            # live read plane (ISSUE 10, CB v6): host-authoritative
            # snapshot accounting plus the device-plane mirrors (the
            # lanes as of the last fetched block — they trail the host
            # ints by at most the in-flight dispatches)
            "snapshot_reads": self.snapshot_reads,
            "snapshot_bytes": self.snapshot_bytes,
            "device_snapshot_reads": self.device_snapshot_reads,
            "device_snapshot_bytes": self.device_snapshot_bytes,
            **(self.cascade.get_counters() if self.cascade is not None else {}),
        }

    @property
    def counters(self) -> dict:
        out = self.get_counters()
        out.update(
            {
                # scalar device reductions fetched on demand — never the
                # full valid plane (PERF.md §8); live values, unlike the
                # dispatch-time block cache above. Through _fetch: probe
                # syncs must show up in the transfer accounting too.
                "drop_overflow": int(self._fetch(self.state.dropped_overflow)),
                "occupancy": int(
                    self._fetch(jnp.sum(self.state.valid).astype(jnp.int32))
                ),
            }
        )
        return out
