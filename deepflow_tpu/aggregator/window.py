"""Window ring controller — SubQuadGen/Collector window semantics.

Replicates the reference's windowed-stash protocol
(quadruple_generator.rs:275-352, collector.rs:380-430):

  * time is bucketed into fixed `interval` windows (1s or 60s);
  * a window stays open for `delay` seconds after its end to absorb
    out-of-order arrivals, then is flushed;
  * arrivals older than the oldest open window are dropped and counted
    (`drop_before_window`, collector.rs:386-391).

Control flow is host-driven (the reference drives it from queue ticks);
the data path is device-resident. One deliberate difference: the
reference interleaves per-flow inserts with window moves, while we apply
batch-atomic semantics — merge the whole batch, then advance the window
to `max(batch time) - delay`. Within-batch reordering is invisible to the
output because merges are commutative per window.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import FLOW_METER, TAG_SCHEMA, MeterSchema, TagSchema
from .stash import (
    AccumState,
    StashState,
    accum_append,
    accum_init,
    plan_append,
    stash_flush,
    stash_fold,
    stash_init,
)


@dataclasses.dataclass(frozen=True)
class WindowConfig:
    interval: int = 1  # seconds per window
    delay: int = 2  # seconds a window stays open past its end
    capacity: int = 1 << 14  # stash rows shared by all open windows
    # Batches accumulated between sort+reduce folds. The accumulator ring
    # is sized accum_batches × (rows of the first batch); a fold also
    # fires before any window flush so flushed windows always see every
    # row. 8 amortizes the O((S+A) log(S+A)) sort ~8x while keeping the
    # fold shape small enough for fast (remote) XLA compiles.
    accum_batches: int = 8

    @property
    def ring(self) -> int:
        # number of simultaneously-open windows
        return self.delay // self.interval + 2


@dataclasses.dataclass
class FlushedWindow:
    window_idx: int  # absolute window index (timestamp // interval)
    start_time: int  # window start in seconds
    out: dict  # device arrays from stash_flush (mask/tags/meters/...)
    count: int


class WindowManager:
    """Owns one stash + the open-window span for one granularity."""

    def __init__(
        self,
        config: WindowConfig,
        tag_schema: TagSchema = TAG_SCHEMA,
        meter_schema: MeterSchema = FLOW_METER,
    ):
        self.config = config
        self.tag_schema = tag_schema
        self.meter_schema = meter_schema
        self.state: StashState = stash_init(config.capacity, tag_schema, meter_schema)
        self.acc: AccumState | None = None  # sized on first batch
        self.fill = 0  # host-tracked accumulator rows
        self.start_window: int | None = None  # oldest open window idx
        self.drop_before_window = 0
        self.total_docs_in = 0
        self.total_flushed = 0

    def _fold(self):
        if self.fill == 0:
            return
        self.state, self.acc = stash_fold(self.state, self.acc, self.meter_schema)
        self.fill = 0

    def _append(self, window, key_hi, key_lo, tags, meters, valid, rows: int):
        plan = plan_append(self.fill, self.acc.capacity if self.acc else None, rows)
        if plan == "init":
            self._fold()  # pending rows must reach the stash before the ring is replaced
            self.acc = accum_init(
                max(self.config.accum_batches * rows, rows),
                self.tag_schema,
                self.meter_schema,
            )
        elif plan == "fold":
            self._fold()
        self.acc = accum_append(
            self.acc, window, key_hi, key_lo, tags, meters, valid,
            jnp.int32(self.fill),
        )
        self.fill += rows

    def window_of(self, timestamp):
        return timestamp // self.config.interval

    def ingest(
        self,
        timestamp,  # [N] u32 seconds (device or host)
        key_hi,
        key_lo,
        tags,
        meters,
        valid,
    ) -> list[FlushedWindow]:
        """Merge a doc batch; advance and flush any windows that closed.

        Returns flushed windows in order (possibly empty).
        """
        timestamp = jnp.asarray(timestamp, dtype=jnp.uint32)
        valid = jnp.asarray(valid)
        window = (timestamp // jnp.uint32(self.config.interval)).astype(jnp.uint32)

        ts_np = np.asarray(timestamp)
        valid_np = np.asarray(valid)
        if not valid_np.any():
            return []
        t_max = int(ts_np[valid_np].max())

        if self.start_window is None:
            # Open the ring far enough back that data older than the first
            # batch but within `delay` is still accepted — the reference
            # starts its window 2min in the past for the same reason
            # (quadruple_generator.rs:782-783).
            t_min = int(ts_np[valid_np].min())
            self.start_window = self.window_of(max(0, min(t_min, t_max - self.config.delay)))

        # Late-arrival gate: rows for already-flushed windows are dropped.
        window_np = ts_np // self.config.interval
        late = valid_np & (window_np < self.start_window)
        n_late = int(late.sum())
        if n_late:
            self.drop_before_window += n_late
            valid = valid & (window >= jnp.uint32(self.start_window))
        self.total_docs_in += int(valid_np.sum()) - n_late

        self._append(window, key_hi, key_lo, tags, meters, valid, int(ts_np.shape[0]))

        # Advance: every window whose end is more than `delay` behind the
        # newest arrival closes now (move_window, quadruple_generator.rs:339).
        # Flush only the distinct windows actually present in the stash —
        # a large timestamp gap (agent restart, replay skip) must not cost
        # one device call per empty intermediate window.
        flushed: list[FlushedWindow] = []
        new_start = self.window_of(max(t_max - self.config.delay, 0))
        if self.start_window < new_start:
            self._fold()  # flushed windows must see every accumulated row
            slots = np.asarray(self.state.slot)
            valid_rows = np.asarray(self.state.valid)
            occupied = np.unique(slots[valid_rows]) if valid_rows.any() else np.array([], np.uint32)
            for w in sorted(int(w) for w in occupied if w < new_start):
                self.state, out = stash_flush(self.state, np.uint32(w))
                count = int(out["count"])
                self.total_flushed += count
                if count:  # empty slots shift silently (reference emits nothing)
                    flushed.append(
                        FlushedWindow(
                            window_idx=w,
                            start_time=w * self.config.interval,
                            out=out,
                            count=count,
                        )
                    )
            self.start_window = new_start
        return flushed

    def flush_all(self) -> list[FlushedWindow]:
        """Drain every open window (shutdown path)."""
        if self.start_window is None:
            return []
        self._fold()
        flushed = []
        slots = np.asarray(self.state.slot)
        valid = np.asarray(self.state.valid)
        open_windows = sorted(int(w) for w in np.unique(slots[valid])) if valid.any() else []
        for w in open_windows:
            self.state, out = stash_flush(self.state, np.uint32(w))
            count = int(out["count"])
            self.total_flushed += count
            flushed.append(
                FlushedWindow(window_idx=w, start_time=w * self.config.interval, out=out, count=count)
            )
            self.start_window = max(self.start_window, w + 1)
        return flushed

    @property
    def counters(self) -> dict:
        return {
            "doc_in": self.total_docs_in,
            "flushed_doc": self.total_flushed,
            "drop_before_window": self.drop_before_window,
            "drop_overflow": int(self.state.dropped_overflow),
            "occupancy": int(np.asarray(self.state.valid).sum()),
            "acc_fill": self.fill,  # rows awaiting the next fold
        }
