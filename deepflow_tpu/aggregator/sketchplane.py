"""Per-window device-resident sketch plane — the approximate tier of the
windowed pipeline (ISSUE 8).

The exact stash is capacity-bounded: under high-cardinality traffic
(DDoS, scans, per-user flows) it overflows and sheds, which is both a
correctness cliff and the throughput ceiling. This plane keeps, for
every *open window*, a fixed-size approximate summary on device — HLL
registers (distinct clients per service), a count-min plane (per-flow
frequency/bytes), a log-binned latency histogram (t-digest source), and
an invertible top-K sketch (ops/topk.py — heavy flow keys recoverable
from the sketch itself) — updated from the SAME fused jit dispatch as
the exact append, so the shed path degrades *detail*, never *coverage*.

Ring semantics. Open windows span at most R = delay//interval + 2
consecutive indices, so an [R]-slot ring indexed by `window % R` holds
them without aliasing (consecutive windows are distinct mod R). The
fused step closes slots itself: it derives the post-batch span start
(`close_w`, exactly the host's advance rule) and, between folding the
batch's closing-span rows and its new-span rows, moves every slot with
win < close_w into a flat PENDING buffer of packed u32 block rows. The
host drains pending at each window advance, riding the flush drain's
existing fetches (the scalar fetch widens to [2], the packed-row fetch
becomes one concatenated u32 transfer) — the ≤3-fetch budget is
unchanged, gated in CI.

The one coverage exception is counted, never silent: a single batch
whose accepted rows span more than R windows *below* the close bound
(a giant timestamp jump inside one batch) cannot give each of those
already-closing windows its own slot; such rows are dropped from the
sketch tier only (the exact stash still takes them) and counted in the
`shed` lane, which rides the device counter block (CB_SKETCH_SHED).

Closed blocks are host-side `WindowSketchBlock`s: pure-numpy queries
(the shared xp ops math — ops/cms.row_slots, ops/hll.hll_estimate_np),
mergeable across shards (register max / counter add / MJRTY combine),
t-digest export via the histogram→centroid compressor, and the top-K
inversion (candidates from the invertible sketch, estimates from the
same window's count-min plane).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cms import row_slots
from ..ops.hll import clz32, hll_estimate_np
from ..ops.histogram import LogHistSpec, loghist_bin
from ..ops.segment import _use_fused_sketch, _use_shared_sort
from ..ops.tdigest import tdigest_compress, tdigest_quantile
from ..ops.topk import (
    _apply_challengers,
    topk_candidates,
    topk_challengers_presorted,
    topk_select,
    topk_update,
)

_U32_MAX = np.uint32(0xFFFFFFFF)
SENTINEL_WIN = _U32_MAX


@dataclasses.dataclass(frozen=True)
class SketchConfig:
    """Shapes and error knobs of the per-window plane.

    hll_precision=14 meets the <1% north-star cardinality bound
    (~0.81% standard error); the defaults here are sized for the
    many-windows-resident case — bench/sketchbench.py carries the
    measured error/recall for the production settings."""

    num_groups: int = 16  # service rows (HLL + histogram group axis)
    hll_precision: int = 12
    cms_depth: int = 4
    cms_width: int = 1 << 12
    hist: LogHistSpec = LogHistSpec(bins=256, vmin=1.0, gamma=1.04)
    topk_rows: int = 2  # 0 disables the top-K lane
    topk_cols: int = 1 << 9
    pending: int = 16  # closed-block rows buffered between host drains

    def __post_init__(self):
        if self.cms_width & (self.cms_width - 1):
            raise ValueError("cms_width must be a power of two")
        if self.topk_rows and self.topk_cols & (self.topk_cols - 1):
            raise ValueError("topk_cols must be a power of two")

    @property
    def hll_m(self) -> int:
        return 1 << self.hll_precision

    @property
    def block_width(self) -> int:
        """u32 words per packed closed-window block row: the n_updates
        word, then hll / cms / hist / 5 top-K lanes, flattened in that
        order (the layout contract between `_flatten_open`,
        `WindowSketchBlock.from_row` and checkpoint v4)."""
        g = self.num_groups
        return (
            1
            + g * self.hll_m
            + self.cms_depth * self.cms_width
            + g * self.hist.bins
            + 5 * self.topk_rows * self.topk_cols
        )

    def meta(self) -> dict:
        """JSON-able form for checkpoint meta (v4)."""
        return {
            "num_groups": self.num_groups,
            "hll_precision": self.hll_precision,
            "cms_depth": self.cms_depth,
            "cms_width": self.cms_width,
            "hist_bins": self.hist.bins,
            "hist_vmin": self.hist.vmin,
            "hist_gamma": self.hist.gamma,
            "topk_rows": self.topk_rows,
            "topk_cols": self.topk_cols,
            "pending": self.pending,
        }

    @classmethod
    def from_meta(cls, m: dict) -> "SketchConfig":
        return cls(
            num_groups=m["num_groups"],
            hll_precision=m["hll_precision"],
            cms_depth=m["cms_depth"],
            cms_width=m["cms_width"],
            hist=LogHistSpec(
                bins=m["hist_bins"], vmin=m["hist_vmin"], gamma=m["hist_gamma"]
            ),
            topk_rows=m["topk_rows"],
            topk_cols=m["topk_cols"],
            pending=m["pending"],
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SketchState:
    """Device-resident plane (leading mesh dim when sharded).

    Open ring: `win[R]` (absolute window per slot, SENTINEL=empty) +
    per-slot planes. Pending: flat packed closed blocks awaiting the
    host drain. `rows`/`shed` are the cumulative counter-block lanes."""

    win: jnp.ndarray  # [R] u32
    count: jnp.ndarray  # [R] u32 rows folded per open slot
    hll: jnp.ndarray  # [R, G, m] i32
    cms: jnp.ndarray  # [R, D, W] i32
    hist: jnp.ndarray  # [R, G, B] i32
    tk_votes: jnp.ndarray  # [R, d, C] i32
    tk_hi: jnp.ndarray  # [R, d, C] u32
    tk_lo: jnp.ndarray  # [R, d, C] u32
    tk_ida: jnp.ndarray  # [R, d, C] u32
    tk_idb: jnp.ndarray  # [R, d, C] u32
    pend: jnp.ndarray  # [P, WIDE] u32 packed closed blocks
    pend_win: jnp.ndarray  # [P] u32
    pend_n: jnp.ndarray  # scalar i32
    rows: jnp.ndarray  # scalar u32 — CB_SKETCH_ROWS source
    shed: jnp.ndarray  # scalar u32 — CB_SKETCH_SHED source

    @property
    def ring(self) -> int:
        return self.win.shape[-1]


def sketch_init(cfg: SketchConfig, ring: int) -> SketchState:
    g, m = cfg.num_groups, cfg.hll_m
    return SketchState(
        win=jnp.full((ring,), SENTINEL_WIN, dtype=jnp.uint32),
        count=jnp.zeros((ring,), jnp.uint32),
        hll=jnp.zeros((ring, g, m), jnp.int32),
        cms=jnp.zeros((ring, cfg.cms_depth, cfg.cms_width), jnp.int32),
        hist=jnp.zeros((ring, g, cfg.hist.bins), jnp.int32),
        tk_votes=jnp.zeros((ring, cfg.topk_rows, cfg.topk_cols), jnp.int32),
        tk_hi=jnp.zeros((ring, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_lo=jnp.zeros((ring, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_ida=jnp.zeros((ring, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_idb=jnp.zeros((ring, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        pend=jnp.zeros((cfg.pending, cfg.block_width), jnp.uint32),
        pend_win=jnp.full((cfg.pending,), SENTINEL_WIN, dtype=jnp.uint32),
        pend_n=jnp.zeros((), jnp.int32),
        rows=jnp.zeros((), jnp.uint32),
        shed=jnp.zeros((), jnp.uint32),
    )


# ---------------------------------------------------------------------------
# device side (traced helpers — callers fuse these into jitted steps)


def _flatten_open(sk: SketchState) -> jnp.ndarray:
    """[R, WIDE] u32 packed block rows, layout per SketchConfig.block_width."""
    r = sk.ring
    u = lambda x: x.reshape(r, -1).astype(jnp.uint32)
    return jnp.concatenate(
        [
            sk.count[:, None].astype(jnp.uint32),
            u(sk.hll),
            u(sk.cms),
            u(sk.hist),
            u(sk.tk_votes),
            u(sk.tk_hi),
            u(sk.tk_lo),
            u(sk.tk_ida),
            u(sk.tk_idb),
        ],
        axis=1,
    )


def sketch_close(sk: SketchState, close_w) -> SketchState:
    """Move every open slot with win < close_w into the pending buffer
    and reset it. Pending overflow drops the block (never corrupts a
    neighbour) and counts the lost rows into `shed`. Traced; the
    flatten+scatter body runs under a `lax.cond` so the (frequent)
    no-close batches skip the full-plane copy."""
    from jax import lax

    close_w = jnp.asarray(close_w, jnp.uint32)
    r = sk.ring
    p = sk.pend.shape[0]
    close = (sk.win != jnp.uint32(SENTINEL_WIN)) & (sk.win < close_w)

    def do_close(sk: SketchState) -> SketchState:
        n_close = jnp.sum(close.astype(jnp.int32))
        pos = sk.pend_n + jnp.cumsum(close.astype(jnp.int32)) - 1
        pos = jnp.where(close, pos, p)  # non-closing rows → dropped
        overflow = close & (pos >= p)
        pos = jnp.minimum(pos, p)
        blocks = _flatten_open(sk)
        pend = sk.pend.at[pos].set(blocks, mode="drop")
        pend_win = sk.pend_win.at[pos].set(sk.win, mode="drop")
        shed = sk.shed + jnp.sum(jnp.where(overflow, sk.count, 0)).astype(
            jnp.uint32
        )

        def rst(x, fill):
            m = close.reshape((r,) + (1,) * (x.ndim - 1))
            return jnp.where(m, jnp.asarray(fill, x.dtype), x)

        return SketchState(
            win=rst(sk.win, SENTINEL_WIN),
            count=rst(sk.count, 0),
            hll=rst(sk.hll, 0),
            cms=rst(sk.cms, 0),
            hist=rst(sk.hist, 0),
            tk_votes=rst(sk.tk_votes, 0),
            tk_hi=rst(sk.tk_hi, 0),
            tk_lo=rst(sk.tk_lo, 0),
            tk_ida=rst(sk.tk_ida, 0),
            tk_idb=rst(sk.tk_idb, 0),
            pend=pend,
            pend_win=pend_win,
            pend_n=jnp.minimum(sk.pend_n + n_close, p),
            rows=sk.rows,
            shed=shed,
        )

    return lax.cond(jnp.any(close), do_close, lambda s: s, sk)


def _scatter_rows(
    sk: SketchState,
    spec: LogHistSpec,
    mask,
    window,
    group,
    client_hi,
    client_lo,
    key_hi,
    key_lo,
    weight,
    rtt,
    rtt_valid,
    id_a,
    id_b,
    presorted=None,
    fused_sketch: bool = False,
) -> SketchState:
    """Fold one phase's rows into their ring slots (claiming empties).
    Callers guarantee the phase's window span is < R wide, so slots are
    collision-free by construction (consecutive windows ≡ distinct
    mod R).

    With `presorted` (the batch's ONE shared (window, key_hi, key_lo)
    sort from `sketch_plane_step` — ISSUE 17), the count-min and top-K
    lanes consume the shared order instead of sorting again: per-(window,
    key) run weights are summed once and reused as the count-min
    run-dedup weights (one add per run head instead of per row — adds
    commute, totals bit-identical) AND as the top-K challenger weights
    (`topk_challengers_presorted`, zero fresh sorts). The per-row lanes
    whose folds are idempotent or count-shaped (win claim, count, HLL
    register max, histogram) stay on the original row order — a run
    spans one flow key, not one client, so they cannot ride the run
    dedup. `fused_sketch` additionally routes HLL + count-min + the
    challenger scan through the single-pass Pallas kernel
    (ops/sketch_pallas.py) when the shapes support it."""
    r = sk.ring
    g, m = sk.hll.shape[1], sk.hll.shape[2]
    d_cms, w_cms = sk.cms.shape[1], sk.cms.shape[2]
    window = jnp.asarray(window, jnp.uint32)
    slot = (window % jnp.uint32(r)).astype(jnp.int32)
    gslot = jnp.where(mask, slot, r)
    gid = (jnp.asarray(group).astype(jnp.int32)) % g

    win = sk.win.at[gslot].min(window, mode="drop")  # claim (SENTINEL > any)
    count = sk.count.at[gslot].add(1, mode="drop")

    reg = (jnp.asarray(client_lo, jnp.uint32) & jnp.uint32(m - 1)).astype(jnp.int32)
    rho = (clz32(client_hi) + 1).astype(jnp.int32)

    w = jnp.where(mask, jnp.asarray(weight).astype(jnp.int32), 0)

    b = loghist_bin(rtt, spec)
    hslot = jnp.where(mask & rtt_valid, slot, r)
    hist = sk.hist.at[hslot, gid, b].add(1, mode="drop")

    lanes = (sk.tk_votes, sk.tk_hi, sk.tk_lo, sk.tk_ida, sk.tk_idb)
    d_tk = sk.tk_votes.shape[1]

    if presorted is None:
        # multi-sort oracle: per-row CMS scatter + a fresh 3-key sort
        # per top-K hash row (topk_update)
        hll = sk.hll.at[gslot, gid, reg].max(rho, mode="drop")
        rs = row_slots(key_hi, key_lo, d_cms, w_cms)  # [D, N] in [0, D*W)
        flat = gslot[None, :].astype(jnp.int32) * (d_cms * w_cms) + rs
        cms = (
            sk.cms.reshape(-1)
            .at[flat.reshape(-1)]
            .add(jnp.broadcast_to(w[None, :], flat.shape).reshape(-1), mode="drop")
            .reshape(r, d_cms, w_cms)
        )
        if d_tk:
            tkv, tkh, tkl, tia, tib = topk_update(
                lanes, slot, key_hi, key_lo, id_a, id_b, weight, mask,
            )
        else:
            tkv, tkh, tkl, tia, tib = lanes
        return dataclasses.replace(
            sk, win=win, count=count, hll=hll, cms=cms, hist=hist,
            tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
        )

    # -- shared-sort path (ISSUE 17) ------------------------------------
    n = window.shape[0]
    s_win, s_hi, s_lo, s_pos, head, run_id = presorted
    s_slot = (s_win % jnp.uint32(r)).astype(jnp.int32)
    s_mask = mask[s_pos]
    s_w = w[s_pos]
    # per-(window, key) run weight under THIS phase's mask — shared by
    # the count-min head adds and every top-K hash row
    run_w = jax.ops.segment_sum(s_w, run_id, num_segments=n)
    rw = run_w[run_id]
    w_head = jnp.where(head, rw, 0)
    s_ia = jnp.asarray(id_a, jnp.uint32)[s_pos]
    s_ib = jnp.asarray(id_b, jnp.uint32)[s_pos]
    rs = row_slots(s_hi, s_lo, d_cms, w_cms)  # [D, N] in [0, D*W)

    fused_done = False
    if fused_sketch:
        from ..ops.sketch_pallas import fused_sketch_guard, sketch_update_fused

        ok = fused_sketch_guard(
            n, r, g, m, d_cms, w_cms, d_tk, sk.tk_votes.shape[2]
        )
        if ok:
            hll, cms, challengers = sketch_update_fused(
                sk.hll, sk.cms, tk_shape=(d_tk, sk.tk_votes.shape[2]),
                s_slot=s_slot, s_gid=gid[s_pos], s_reg=reg[s_pos],
                s_rho=rho[s_pos], s_mask=s_mask, w_head=w_head, rw=rw,
                cms_slots=rs, s_hi=s_hi, s_lo=s_lo, s_ia=s_ia, s_ib=s_ib,
            )
            fused_done = True
    if not fused_done:
        hll = sk.hll.at[gslot, gid, reg].max(rho, mode="drop")
        # one add per run HEAD (carrying the run's summed weight)
        # instead of per row: non-head rows add 0 at a live cell — a
        # no-op — so cell totals stay bit-identical to the per-row
        # oracle while the scatter's live writes drop to one per
        # (window, key) run. Head slots are always in-range (window
        # % R), so no index masking is needed: fully-unmasked runs
        # carry w_head == 0.
        flat = s_slot[None, :] * (d_cms * w_cms) + rs
        cms = (
            sk.cms.reshape(-1)
            .at[flat.reshape(-1)]
            .add(
                jnp.broadcast_to(w_head[None, :], flat.shape).reshape(-1),
                mode="drop",
            )
            .reshape(r, d_cms, w_cms)
        )
        challengers = (
            topk_challengers_presorted(
                s_slot, s_hi, s_lo, s_ia, s_ib, rw, s_mask,
                r, d_tk, sk.tk_votes.shape[2],
            )
            if d_tk
            else []
        )
    tkv, tkh, tkl, tia, tib = (
        _apply_challengers(lanes, challengers) if d_tk else lanes
    )
    return dataclasses.replace(
        sk, win=win, count=count, hll=hll, cms=cms, hist=hist,
        tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
    )


def sketch_plane_step(
    sk: SketchState,
    spec: LogHistSpec,
    *,
    window,
    valid,
    base_w,
    close_w,
    group,
    client_hi,
    client_lo,
    key_hi,
    key_lo,
    weight,
    rtt,
    rtt_valid,
    id_a,
    id_b,
    shared_sort: bool | None = None,
    fused_sketch: bool | None = None,
) -> SketchState:
    """One batch through the plane, in window order (traced):

      1. closing-span rows (base_w ≤ window < close_w, within the live
         ring span) fold into their still-open slots;
      2. every slot with win < close_w closes into the pending buffer;
      3. new-span rows (window ≥ close_w) claim the freed slots.

    `base_w`/`close_w` are the pre-/post-batch open-span starts — the
    single-chip fused step derives them on device from the same rule
    the host replays; the sharded step receives them from the host
    (which decides advances before dispatch).

    The closing phase's collision-free span is anchored at the OLDEST
    LIVE RING SLOT (or base_w when the ring is empty), not at base_w:
    when a batch's own t_min jumps ahead of windows still open from
    earlier batches, anchoring at base_w would let a closing row alias
    mod R into an older occupied slot and silently merge two windows'
    sketches. Rows in the mid-gap [anchor + R, close_w) — only
    possible when one batch spans more than R windows below its close
    bound — are counted into `shed` instead (module docstring).

    One-pass fold (ISSUE 17). With `shared_sort` (default: the
    DEEPFLOW_SHARED_SORT knob, ON) and the top-K lane enabled, the
    batch's (window, key_hi, key_lo) stable sort runs ONCE here and
    both phases consume it — the per-hash-row fresh sorts inside
    `topk_update` (2 phases × topk_rows sorts) collapse into this one,
    and the count-min scatter dedups to run heads. Bit-exact vs the
    multi-sort path (tests/test_sketch_onepass.py). `fused_sketch`
    (default: DEEPFLOW_FUSED_SKETCH, OFF until on-chip numbers) further
    collapses the sorted-order folds into the single-pass Pallas
    kernel. Both knobs resolve at TRACE time — callers whose jitted
    step outlives an env flip must thread them as static arguments
    (aggregator/window.py does)."""
    if shared_sort is None:
        shared_sort = _use_shared_sort()
    if fused_sketch is None:
        fused_sketch = _use_fused_sketch()
    r = sk.ring
    window = jnp.asarray(window, jnp.uint32)
    base_w = jnp.asarray(base_w, jnp.uint32)
    close_w = jnp.asarray(close_w, jnp.uint32)
    # oldest live slot bounds the alias-free span; SENTINEL (empty
    # ring) never lowers the min below base_w
    anchor = jnp.minimum(jnp.min(sk.win), base_w)
    hi_a = jnp.minimum(close_w, anchor + jnp.uint32(r))
    in_a = valid & (window >= base_w) & (window < hi_a)
    in_c = valid & (window >= jnp.maximum(close_w, base_w))
    shed = (
        valid
        & (window >= jnp.maximum(anchor + jnp.uint32(r), base_w))
        & (window < close_w)
    )

    presorted = None
    if shared_sort and sk.tk_votes.shape[1]:
        # THE batch sort: stable 3-key over the raw lanes + a position
        # payload. No sentinel rekey is needed — phase masks ride
        # through the permutation, and masked-out rows contribute
        # weight 0 without perturbing the relative order of live rows.
        n = window.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        s_win, s_hi, s_lo, s_pos = jax.lax.sort(
            (window, jnp.asarray(key_hi, jnp.uint32),
             jnp.asarray(key_lo, jnp.uint32), iota),
            num_keys=3,
        )
        head = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (s_win[1:] != s_win[:-1])
                | (s_hi[1:] != s_hi[:-1])
                | (s_lo[1:] != s_lo[:-1]),
            ]
        )
        run_id = jnp.cumsum(head.astype(jnp.int32)) - 1
        presorted = (s_win, s_hi, s_lo, s_pos, head, run_id)

    args = (group, client_hi, client_lo, key_hi, key_lo, weight, rtt,
            rtt_valid, id_a, id_b)
    kw = dict(presorted=presorted, fused_sketch=fused_sketch)
    sk = _scatter_rows(sk, spec, in_a, window, *args, **kw)
    sk = sketch_close(sk, close_w)
    sk = _scatter_rows(sk, spec, in_c, window, *args, **kw)
    folded = (jnp.sum(in_a) + jnp.sum(in_c)).astype(jnp.uint32)
    return dataclasses.replace(
        sk,
        rows=sk.rows + folded,
        shed=sk.shed + jnp.sum(shed).astype(jnp.uint32),
    )


def _drain_impl(sk: SketchState, close_w):
    sk = sketch_close(sk, close_w)
    pend, pend_win, n = sk.pend, sk.pend_win, sk.pend_n
    sk = dataclasses.replace(sk, pend_n=jnp.zeros((), jnp.int32))
    return sk, pend, pend_win, n


# donated: the returned state's pending cursor resets while the old
# pend/pend_win buffers come back as outputs — XLA copies whichever
# side cannot alias, so later in-step closes never race the (possibly
# deferred) host fetch of the drained rows.
sketch_drain = jax.jit(_drain_impl, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# host side


@dataclasses.dataclass
class WindowSketchBlock:
    """One closed window's fetched sketch summary (host numpy).

    Mergeable across shards (`merge`). `distinct`/`estimate`/`topk`
    are pure numpy over the fetched arrays (shared-xp ops math — no
    device access); `tdigest`/`quantile` reuse the jitted centroid
    compressor on tiny fixed-size arrays — a device dispatch, but off
    the ingest fetch path and outside the host_fetch budget seam, so
    sink/querier consumers pay it per closed window, never per batch.
    Top-K lanes are kept as flat candidate arrays (bucket layout is
    irrelevant once closed), which is also what makes the cross-shard
    merge a plain concatenation."""

    window: int
    config: SketchConfig
    n_updates: int
    hll: np.ndarray  # [G, m] i32
    cms: np.ndarray  # [D, W] i64 (i64: shard merges must not wrap)
    hist: np.ndarray  # [G, B] i64
    tk_hi: np.ndarray  # [n_cand] u32
    tk_lo: np.ndarray
    tk_ida: np.ndarray
    tk_idb: np.ndarray
    tk_votes: np.ndarray  # [n_cand] i64

    @classmethod
    def from_row(cls, row: np.ndarray, window: int, cfg: SketchConfig):
        """Unpack one [WIDE] u32 packed block row (layout contract:
        SketchConfig.block_width)."""
        g, m = cfg.num_groups, cfg.hll_m
        d, w = cfg.cms_depth, cfg.cms_width
        b = cfg.hist.bins
        tk = cfg.topk_rows * cfg.topk_cols
        o = 0

        def take(n):
            nonlocal o
            out = row[o : o + n]
            o += n
            return out

        n_updates = int(take(1)[0])
        hll = take(g * m).astype(np.int32).reshape(g, m)
        cms = take(d * w).astype(np.int64).reshape(d, w)
        hist = take(g * b).astype(np.int64).reshape(g, b)
        votes = take(tk).astype(np.int32).astype(np.int64)
        hi, lo, ida, idb = (take(tk) for _ in range(4))
        keep = votes > 0
        return cls(
            window=int(window), config=cfg, n_updates=n_updates,
            hll=hll, cms=cms, hist=hist,
            tk_hi=hi[keep].astype(np.uint32), tk_lo=lo[keep].astype(np.uint32),
            tk_ida=ida[keep].astype(np.uint32), tk_idb=idb[keep].astype(np.uint32),
            tk_votes=votes[keep],
        )

    def merge(self, other: "WindowSketchBlock") -> "WindowSketchBlock":
        """Cross-shard combine for the same window: register max,
        counter add, candidate union (estimates re-derive from the
        merged count-min at query time)."""
        assert other.window == self.window, (self.window, other.window)
        return WindowSketchBlock(
            window=self.window,
            config=self.config,
            n_updates=self.n_updates + other.n_updates,
            hll=np.maximum(self.hll, other.hll),
            cms=self.cms + other.cms,
            hist=self.hist + other.hist,
            tk_hi=np.concatenate([self.tk_hi, other.tk_hi]),
            tk_lo=np.concatenate([self.tk_lo, other.tk_lo]),
            tk_ida=np.concatenate([self.tk_ida, other.tk_ida]),
            tk_idb=np.concatenate([self.tk_idb, other.tk_idb]),
            tk_votes=np.concatenate([self.tk_votes, other.tk_votes]),
        )

    # -- queries ---------------------------------------------------------
    def distinct(self, group: int | None = None) -> float:
        """HLL distinct-client estimate: one group, or the whole window
        (register-max union over groups — NOT the per-group sum, which
        would double-count clients seen by several services)."""
        if group is None:
            est = hll_estimate_np(self.hll.max(axis=0, keepdims=True))
            return float(est[0])
        return float(hll_estimate_np(self.hll[group : group + 1])[0])

    def distinct_per_group(self) -> np.ndarray:
        return hll_estimate_np(self.hll)

    def estimate(self, key_hi, key_lo) -> np.ndarray:
        """Count-min point estimates (overestimate-only) for flow keys."""
        from ..ops.cms import cms_query_np

        return cms_query_np(self.cms, key_hi, key_lo)

    def tdigest(self, group: int | None = None, compression: int = 64):
        """(means, weights) centroid export of the latency histogram —
        the compact wire form (ops/tdigest.py). group None pools."""
        hist = self.hist.sum(axis=0) if group is None else self.hist[group]
        spec = self.config.hist
        centers = spec.vmin * np.power(
            spec.gamma, np.arange(spec.bins, dtype=np.float64) + 0.5
        )
        m, w = tdigest_compress(
            jnp.asarray(centers, jnp.float32),
            jnp.asarray(hist, jnp.float32),
            compression=compression,
        )
        return np.asarray(m), np.asarray(w)

    def quantile(self, q: float, group: int | None = None) -> float:
        """Latency quantile through the t-digest export path."""
        m, w = self.tdigest(group)
        return float(
            np.asarray(tdigest_quantile(jnp.asarray(m), jnp.asarray(w),
                                        jnp.asarray([q], jnp.float32)))[0]
        )

    def topk(self, k: int) -> list[dict]:
        """Invert the heavy-hitter sketch: candidates from the bucket
        lanes, ranked by the same window's count-min estimate."""
        if len(self.tk_hi) == 0:
            return []
        est = self.estimate(self.tk_hi, self.tk_lo)
        hi, lo, ida, idb, est_k = topk_select(
            self.tk_hi, self.tk_lo, self.tk_ida, self.tk_idb, est, k
        )
        return [
            {
                "key_hi": int(hi[i]), "key_lo": int(lo[i]),
                "id_a": int(ida[i]), "id_b": int(idb[i]),
                "estimate": int(est_k[i]),
            }
            for i in range(len(hi))
        ]


def hold_blocks(held: list, new_blocks, cap: int) -> int:
    """THE closed-block retention policy, shared by RollupPipeline and
    ShardedWindowManager: append, then drop-oldest beyond `cap` (the
    same counted-drop stance as the device pending buffer). Returns the
    number dropped — callers count it so an undrained
    pop_closed_sketches consumer is loud, not a leak."""
    held.extend(new_blocks)
    overflow = len(held) - cap
    if overflow > 0:
        del held[:overflow]
        return overflow
    return 0


def unpack_drained(rows: np.ndarray, wins: np.ndarray, cfg: SketchConfig):
    """Fetched pending rows ([n, WIDE] u32 + [n] window ids) →
    WindowSketchBlocks. Blocks that never saw a row (possible on the
    sharded path, where a device closes a window its shard had no data
    for) are dropped here."""
    out = []
    for i in range(rows.shape[0]):
        blk = WindowSketchBlock.from_row(rows[i], int(wins[i]), cfg)
        if blk.n_updates or len(blk.tk_hi):
            out.append(blk)
    return out


__all__ = [
    "SketchConfig",
    "SketchState",
    "WindowSketchBlock",
    "sketch_init",
    "sketch_close",
    "sketch_drain",
    "sketch_plane_step",
    "unpack_drained",
    "topk_candidates",
]
