"""Per-window device-resident sketch plane — the approximate tier of the
windowed pipeline (ISSUE 8).

The exact stash is capacity-bounded: under high-cardinality traffic
(DDoS, scans, per-user flows) it overflows and sheds, which is both a
correctness cliff and the throughput ceiling. This plane keeps, for
every *open window*, a fixed-size approximate summary on device — HLL
registers (distinct clients per service), a count-min plane (per-flow
frequency/bytes), a log-binned latency histogram (t-digest source), and
an invertible top-K sketch (ops/topk.py — heavy flow keys recoverable
from the sketch itself) — updated from the SAME fused jit dispatch as
the exact append, so the shed path degrades *detail*, never *coverage*.

Ring semantics. Open windows span at most R = delay//interval + 2
consecutive indices, so an [R]-slot ring indexed by `window % R` holds
them without aliasing (consecutive windows are distinct mod R). The
fused step closes slots itself: it derives the post-batch span start
(`close_w`, exactly the host's advance rule) and, between folding the
batch's closing-span rows and its new-span rows, moves every slot with
win < close_w into a flat PENDING buffer of packed u32 block rows. The
host drains pending at each window advance, riding the flush drain's
existing fetches (the scalar fetch widens to [2], the packed-row fetch
becomes one concatenated u32 transfer) — the ≤3-fetch budget is
unchanged, gated in CI.

The one coverage exception is counted, never silent: a single batch
whose accepted rows span more than R windows *below* the close bound
(a giant timestamp jump inside one batch) cannot give each of those
already-closing windows its own slot; such rows are dropped from the
sketch tier only (the exact stash still takes them) and counted in the
`shed` lane, which rides the device counter block (CB_SKETCH_SHED).

Closed blocks are host-side `WindowSketchBlock`s: pure-numpy queries
(the shared xp ops math — ops/cms.row_slots, ops/hll.hll_estimate_np),
mergeable across shards (register max / counter add / MJRTY combine),
t-digest export via the histogram→centroid compressor, and the top-K
inversion (candidates from the invertible sketch, estimates from the
same window's count-min plane).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from ..ops.cms import cms_expand, row_slots
from ..ops.hll import (
    clz32,
    hll_estimate_np,
    hll_pack_registers,
    hll_unpack_registers_np,
)
from ..ops.histogram import (
    LogHistSpec,
    loghist_bin,
    loghist_coarsen_bin,
    loghist_expand,
)
from ..ops.segment import _use_fused_sketch, _use_shared_sort
from ..ops.tdigest import tdigest_compress, tdigest_quantile
from ..ops.topk import (
    _apply_challengers,
    topk_candidates,
    topk_challengers_presorted,
    topk_select,
    topk_tile,
    topk_update,
)

_U32_MAX = np.uint32(0xFFFFFFFF)
SENTINEL_WIN = _U32_MAX


@dataclasses.dataclass(frozen=True)
class PoolConfig:
    """Disaggregated sketch-memory pool (ISSUE 20).

    Instead of one worst-case-sized slab per ring slot, the plane draws
    from a shared device arena: `compact_slots` narrow sub-sketch slots
    (per lane: full-m int8 HLL registers, CMS width/`cms_factor`,
    top-K cols/`topk_factor`, hist bins/`hist_factor`) plus
    `wide_slots` full-width slots. A window opens compact; when the
    CMS-row-0 fill fraction of its slot reaches `promote_fill` the step
    promotes it to a free wide slot via the r12 merge algebra (HLL
    cast = register max against zero, CMS/hist tile-add, top-K bucket
    tile — ops/{hll,cms,histogram,topk}.py document per-lane
    soundness). Pool exhaustion spills rows from the sketch tier only,
    counted (CB_SKETCH_POOL_SPILL), never silently."""

    compact_slots: int = 3
    wide_slots: int = 1
    cms_factor: int = 8
    topk_factor: int = 4
    hist_factor: int = 8
    promote_fill: float = 0.5

    def meta(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_meta(cls, m: dict) -> "PoolConfig":
        return cls(**m)


def _check_pool(cfg: "SketchConfig") -> None:
    """Pool/ring geometry validation (ISSUE 20 satellite): every way a
    pooled lane could fail to hold — or fail to PROMOTE into — the wide
    lane raises here, naming the lane and both widths, instead of
    surfacing as a shape error inside a jitted step or shard_map body."""
    p = cfg.pool
    if p.compact_slots < 1:
        raise ValueError(
            f"pool compact_slots must be ≥ 1, got {p.compact_slots}"
        )
    if p.wide_slots < 1:
        raise ValueError(
            f"pool wide_slots={p.wide_slots}: the promotion target arena "
            "is empty — a saturated compact slot would have no wide slot "
            "to promote into"
        )
    if cfg.cms_depth < 1:
        raise ValueError(
            "pooled sketch memory requires cms_depth ≥ 1: the promotion "
            "saturation estimator reads the fill of CMS row 0 "
            f"(got cms_depth={cfg.cms_depth})"
        )
    if cfg.hll_m % 4:
        raise ValueError(
            f"pooled HLL packs 4 int8 registers per u32 word; hll_m="
            f"{cfg.hll_m} (precision {cfg.hll_precision}) is not "
            "divisible by 4"
        )
    if not (0.0 < p.promote_fill <= 1.0):
        raise ValueError(
            f"pool promote_fill must be in (0, 1], got {p.promote_fill}"
        )
    lanes = [("cms", p.cms_factor, cfg.cms_width),
             ("hist", p.hist_factor, cfg.hist.bins)]
    if cfg.topk_rows:
        lanes.append(("topk", p.topk_factor, cfg.topk_cols))
    for lane, factor, width in lanes:
        if factor < 1 or (factor & (factor - 1)):
            raise ValueError(
                f"pool {lane}_factor must be a power of two ≥ 1, got "
                f"{factor}"
            )
        if width % factor or width // factor < 1:
            raise ValueError(
                f"pool geometry cannot promote the {lane} lane: factor "
                f"{factor} does not divide the wide width {width} into a "
                f"non-empty compact lane (compact width would be "
                f"{width // factor})"
            )


@dataclasses.dataclass(frozen=True)
class SketchConfig:
    """Shapes and error knobs of the per-window plane.

    hll_precision=14 meets the <1% north-star cardinality bound
    (~0.81% standard error); the defaults here are sized for the
    many-windows-resident case — bench/sketchbench.py carries the
    measured error/recall for the production settings."""

    num_groups: int = 16  # service rows (HLL + histogram group axis)
    hll_precision: int = 12
    cms_depth: int = 4
    cms_width: int = 1 << 12
    hist: LogHistSpec = LogHistSpec(bins=256, vmin=1.0, gamma=1.04)
    topk_rows: int = 2  # 0 disables the top-K lane
    topk_cols: int = 1 << 9
    pending: int = 16  # closed-block rows buffered between host drains
    pool: PoolConfig | None = None  # None → classic per-slot slabs

    def __post_init__(self):
        if self.cms_width & (self.cms_width - 1):
            raise ValueError("cms_width must be a power of two")
        if self.topk_rows and self.topk_cols & (self.topk_cols - 1):
            raise ValueError("topk_cols must be a power of two")
        if self.pool is not None:
            _check_pool(self)

    @property
    def hll_m(self) -> int:
        return 1 << self.hll_precision

    # -- pooled (compact) lane widths (valid only with pool set) --------
    @property
    def pool_cms_width(self) -> int:
        return self.cms_width // self.pool.cms_factor

    @property
    def pool_hist_bins(self) -> int:
        return self.hist.bins // self.pool.hist_factor

    @property
    def pool_topk_cols(self) -> int:
        return self.topk_cols // self.pool.topk_factor if self.topk_rows else 0

    @property
    def block_width(self) -> int:
        """u32 words per packed closed-window block row: the n_updates
        word, then hll / cms / hist / 5 top-K lanes, flattened in that
        order (the layout contract between `_flatten_open`,
        `WindowSketchBlock.from_row` and checkpoint v4)."""
        g = self.num_groups
        return (
            1
            + g * self.hll_m
            + self.cms_depth * self.cms_width
            + g * self.hist.bins
            + 5 * self.topk_rows * self.topk_cols
        )

    @property
    def compact_block_width(self) -> int:
        """u32 words per packed COMPACT pool block row (pool mode only):
        the n_updates word, then packed-i8 hll (4 registers/word), then
        cms / hist / 5 top-K lanes at the pooled widths — same lane
        order as `block_width`. Strictly narrower than `block_width`
        (the HLL lane alone shrinks 4×), which is what lets
        `unpack_drained` dispatch on the row width."""
        g = self.num_groups
        return (
            1
            + g * self.hll_m // 4
            + self.cms_depth * self.pool_cms_width
            + g * self.pool_hist_bins
            + 5 * self.topk_rows * self.pool_topk_cols
        )

    def meta(self) -> dict:
        """JSON-able form for checkpoint meta (v4; "pool" since v6)."""
        return {
            "num_groups": self.num_groups,
            "hll_precision": self.hll_precision,
            "cms_depth": self.cms_depth,
            "cms_width": self.cms_width,
            "hist_bins": self.hist.bins,
            "hist_vmin": self.hist.vmin,
            "hist_gamma": self.hist.gamma,
            "topk_rows": self.topk_rows,
            "topk_cols": self.topk_cols,
            "pending": self.pending,
            "pool": None if self.pool is None else self.pool.meta(),
        }

    @classmethod
    def from_meta(cls, m: dict) -> "SketchConfig":
        # v5 and older meta has no "pool" key → slab plane, so old
        # checkpoints compare equal against slab-configured managers.
        return cls(
            num_groups=m["num_groups"],
            hll_precision=m["hll_precision"],
            cms_depth=m["cms_depth"],
            cms_width=m["cms_width"],
            hist=LogHistSpec(
                bins=m["hist_bins"], vmin=m["hist_vmin"], gamma=m["hist_gamma"]
            ),
            topk_rows=m["topk_rows"],
            topk_cols=m["topk_cols"],
            pending=m["pending"],
            pool=PoolConfig.from_meta(m["pool"]) if m.get("pool") else None,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SketchState:
    """Device-resident plane (leading mesh dim when sharded).

    Open ring: `win[R]` (absolute window per slot, SENTINEL=empty) +
    per-slot planes. Pending: flat packed closed blocks awaiting the
    host drain. `rows`/`shed` are the cumulative counter-block lanes."""

    win: jnp.ndarray  # [R] u32
    count: jnp.ndarray  # [R] u32 rows folded per open slot
    hll: jnp.ndarray  # [R, G, m] i32 (pool mode: [Pw, G, m] wide arena)
    cms: jnp.ndarray  # [R, D, W] i32 (pool mode: [Pw, D, W])
    hist: jnp.ndarray  # [R, G, B] i32 (pool mode: [Pw, G, B])
    tk_votes: jnp.ndarray  # [R, d, C] i32 (pool mode: [Pw, d, C])
    tk_hi: jnp.ndarray  # [R, d, C] u32
    tk_lo: jnp.ndarray  # [R, d, C] u32
    tk_ida: jnp.ndarray  # [R, d, C] u32
    tk_idb: jnp.ndarray  # [R, d, C] u32
    pend: jnp.ndarray  # [P, WIDE] u32 packed closed blocks ([P, CW] pooled)
    pend_win: jnp.ndarray  # [P] u32
    pend_n: jnp.ndarray  # scalar i32
    rows: jnp.ndarray  # scalar u32 — CB_SKETCH_ROWS source
    shed: jnp.ndarray  # scalar u32 — CB_SKETCH_SHED source
    # -- pooled sketch-memory arena (ISSUE 20; all zero-size in slab
    # mode so the slab pytree/step stay bit-identical) ------------------
    slot_of: jnp.ndarray  # [R] i32 pool slot per ring slot: -1 none,
    #                       0..Pc-1 compact arena, Pc+j wide slot j.
    #                       Invariant: slot_of == -1  ⇒  win == SENTINEL
    #                       (spilled rows never claim win or count).
    p_hll: jnp.ndarray  # [Pc, G, m] i8 — full m registers (bit-exact)
    p_cms: jnp.ndarray  # [Pc, D, Wc] i32
    p_hist: jnp.ndarray  # [Pc, G, Bc] i32
    p_tkv: jnp.ndarray  # [Pc, d, Cc] i32
    p_tkh: jnp.ndarray  # [Pc, d, Cc] u32
    p_tkl: jnp.ndarray  # [Pc, d, Cc] u32
    p_tia: jnp.ndarray  # [Pc, d, Cc] u32
    p_tib: jnp.ndarray  # [Pc, d, Cc] u32
    wide_close: jnp.ndarray  # [Pw] u32 closed-awaiting-drain window id
    #                          (SENTINEL = open or free); closed wide
    #                          slots drain IN PLACE — no pend copy.
    wide_count: jnp.ndarray  # [Pw] u32 row count at close
    pool_spill: jnp.ndarray  # scalar u32 — CB_SKETCH_POOL_SPILL source
    pool_promos: jnp.ndarray  # scalar u32 — CB_SKETCH_PROMOTIONS source
    promote_fill: jnp.ndarray  # scalar f32 saturation threshold (from
    #                            PoolConfig at init; 0 in slab mode)

    @property
    def ring(self) -> int:
        return self.win.shape[-1]


def _pool_mode(sk: SketchState) -> bool:
    """Trace-time mode switch: the pool fields are zero-size iff the
    plane was built without a PoolConfig. The trailing dim carries the
    signal so a [D]-leading sharded state answers the same way."""
    return sk.slot_of.shape[-1] > 0


def sketch_init(cfg: SketchConfig, ring: int) -> SketchState:
    g, m = cfg.num_groups, cfg.hll_m
    pool = cfg.pool
    if pool is None:
        pc, pw = 0, 0
        wc, bc, cc = cfg.cms_width, cfg.hist.bins, cfg.topk_cols
        slot_r, arena_rows = 0, ring
        pend_w = cfg.block_width
        fill = 0.0
    else:
        pc, pw = pool.compact_slots, pool.wide_slots
        wc, bc, cc = cfg.pool_cms_width, cfg.pool_hist_bins, cfg.pool_topk_cols
        slot_r, arena_rows = ring, pw
        pend_w = cfg.compact_block_width
        fill = pool.promote_fill
    return SketchState(
        win=jnp.full((ring,), SENTINEL_WIN, dtype=jnp.uint32),
        count=jnp.zeros((ring,), jnp.uint32),
        hll=jnp.zeros((arena_rows, g, m), jnp.int32),
        cms=jnp.zeros((arena_rows, cfg.cms_depth, cfg.cms_width), jnp.int32),
        hist=jnp.zeros((arena_rows, g, cfg.hist.bins), jnp.int32),
        tk_votes=jnp.zeros((arena_rows, cfg.topk_rows, cfg.topk_cols), jnp.int32),
        tk_hi=jnp.zeros((arena_rows, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_lo=jnp.zeros((arena_rows, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_ida=jnp.zeros((arena_rows, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        tk_idb=jnp.zeros((arena_rows, cfg.topk_rows, cfg.topk_cols), jnp.uint32),
        pend=jnp.zeros((cfg.pending, pend_w), jnp.uint32),
        pend_win=jnp.full((cfg.pending,), SENTINEL_WIN, dtype=jnp.uint32),
        pend_n=jnp.zeros((), jnp.int32),
        rows=jnp.zeros((), jnp.uint32),
        shed=jnp.zeros((), jnp.uint32),
        slot_of=jnp.full((slot_r,), -1, dtype=jnp.int32),
        p_hll=jnp.zeros((pc, g, m), jnp.int8),
        p_cms=jnp.zeros((pc, cfg.cms_depth, wc), jnp.int32),
        p_hist=jnp.zeros((pc, g, bc), jnp.int32),
        p_tkv=jnp.zeros((pc, cfg.topk_rows, cc), jnp.int32),
        p_tkh=jnp.zeros((pc, cfg.topk_rows, cc), jnp.uint32),
        p_tkl=jnp.zeros((pc, cfg.topk_rows, cc), jnp.uint32),
        p_tia=jnp.zeros((pc, cfg.topk_rows, cc), jnp.uint32),
        p_tib=jnp.zeros((pc, cfg.topk_rows, cc), jnp.uint32),
        wide_close=jnp.full((pw,), SENTINEL_WIN, dtype=jnp.uint32),
        wide_count=jnp.zeros((pw,), jnp.uint32),
        pool_spill=jnp.zeros((), jnp.uint32),
        pool_promos=jnp.zeros((), jnp.uint32),
        promote_fill=jnp.asarray(fill, jnp.float32),
    )


# ---------------------------------------------------------------------------
# device side (traced helpers — callers fuse these into jitted steps)


def _flatten_compact(sk: SketchState) -> jnp.ndarray:
    """Pool mode: [R, CW] u32 packed compact block rows, layout per
    SketchConfig.compact_block_width. Each ring slot gathers its compact
    arena slot via `slot_of`; slots without a compact allocation (none,
    or promoted wide) come back all-zero."""
    r = sk.ring
    pc = sk.p_hll.shape[0]
    isc = (sk.slot_of >= 0) & (sk.slot_of < pc)
    cp = jnp.clip(sk.slot_of, 0, pc - 1)
    u = lambda x: x[cp].reshape(r, -1).astype(jnp.uint32)
    row = jnp.concatenate(
        [
            jnp.where(isc, sk.count, 0)[:, None].astype(jnp.uint32),
            hll_pack_registers(sk.p_hll[cp]).reshape(r, -1),
            u(sk.p_cms),
            u(sk.p_hist),
            u(sk.p_tkv),
            u(sk.p_tkh),
            u(sk.p_tkl),
            u(sk.p_tia),
            u(sk.p_tib),
        ],
        axis=1,
    )
    return jnp.where(isc[:, None], row, 0)


def _flatten_wide_arena(sk: SketchState, counts) -> jnp.ndarray:
    """[Pw, WIDE] u32 packed rows of the wide arena itself (row j = wide
    slot j), with the given per-slot count word."""
    pw = sk.hll.shape[0]
    u = lambda x: x.reshape(pw, -1).astype(jnp.uint32)
    return jnp.concatenate(
        [
            counts[:, None].astype(jnp.uint32),
            u(sk.hll),
            u(sk.cms),
            u(sk.hist),
            u(sk.tk_votes),
            u(sk.tk_hi),
            u(sk.tk_lo),
            u(sk.tk_ida),
            u(sk.tk_idb),
        ],
        axis=1,
    )


def _flatten_wide_open(sk: SketchState) -> jnp.ndarray:
    """Pool mode: [R, WIDE] u32 — each ring slot's wide-arena view
    (zero unless the slot was promoted)."""
    r = sk.ring
    pc = sk.p_hll.shape[0]
    pw = sk.hll.shape[0]
    isw = sk.slot_of >= pc
    wp = jnp.clip(sk.slot_of - pc, 0, pw - 1)
    packed = _flatten_wide_arena(sk, jnp.zeros((pw,), jnp.uint32))
    row = packed[wp]
    row = row.at[:, 0].set(jnp.where(isw, sk.count, 0).astype(jnp.uint32))
    return jnp.where(isw[:, None], row, 0)


def _flatten_open(sk: SketchState) -> jnp.ndarray:
    """Slab mode: [R, WIDE] u32 packed block rows, layout per
    SketchConfig.block_width. Pool mode (snapshot path): [R, CW + WIDE]
    — compact part ‖ wide part per ring slot; for any live slot exactly
    one part carries a nonzero count word (allocated slots always have
    count ≥ 1), which is how the host picks a side."""
    if _pool_mode(sk):
        return jnp.concatenate(
            [_flatten_compact(sk), _flatten_wide_open(sk)], axis=1
        )
    r = sk.ring
    u = lambda x: x.reshape(r, -1).astype(jnp.uint32)
    return jnp.concatenate(
        [
            sk.count[:, None].astype(jnp.uint32),
            u(sk.hll),
            u(sk.cms),
            u(sk.hist),
            u(sk.tk_votes),
            u(sk.tk_hi),
            u(sk.tk_lo),
            u(sk.tk_ida),
            u(sk.tk_idb),
        ],
        axis=1,
    )


def sketch_close(sk: SketchState, close_w) -> SketchState:
    """Move every open slot with win < close_w out of the ring and reset
    it. Slab mode: the slot's slab flattens into the pending buffer;
    pending overflow drops the block (never corrupts a neighbour) and
    counts the lost rows into `shed`.

    Pool mode: a closing COMPACT slot flattens its (narrow) block into
    the same pending buffer; a closing WIDE slot is merely *marked*
    closed (`wide_close[j] = win`, `wide_count[j] = count`) and drains
    in place at the next `sketch_drain` — the promoted window never
    pays a full-width copy, and a wide slot stays unavailable for
    reallocation until drained. Either way the ring lanes reset and the
    pool slot is freed/zeroed for reuse. Traced; the flatten+scatter
    body runs under a `lax.cond` so the (frequent) no-close batches
    skip the full-plane copy."""
    close_w = jnp.asarray(close_w, jnp.uint32)
    r = sk.ring
    p = sk.pend.shape[0]
    close = (sk.win != jnp.uint32(SENTINEL_WIN)) & (sk.win < close_w)

    def rst(x, fill):
        m = close.reshape((r,) + (1,) * (x.ndim - 1))
        return jnp.where(m, jnp.asarray(fill, x.dtype), x)

    def do_close(sk: SketchState) -> SketchState:
        n_close = jnp.sum(close.astype(jnp.int32))
        pos = sk.pend_n + jnp.cumsum(close.astype(jnp.int32)) - 1
        pos = jnp.where(close, pos, p)  # non-closing rows → dropped
        overflow = close & (pos >= p)
        pos = jnp.minimum(pos, p)
        blocks = _flatten_open(sk)
        pend = sk.pend.at[pos].set(blocks, mode="drop")
        pend_win = sk.pend_win.at[pos].set(sk.win, mode="drop")
        shed = sk.shed + jnp.sum(jnp.where(overflow, sk.count, 0)).astype(
            jnp.uint32
        )

        return dataclasses.replace(
            sk,
            win=rst(sk.win, SENTINEL_WIN),
            count=rst(sk.count, 0),
            hll=rst(sk.hll, 0),
            cms=rst(sk.cms, 0),
            hist=rst(sk.hist, 0),
            tk_votes=rst(sk.tk_votes, 0),
            tk_hi=rst(sk.tk_hi, 0),
            tk_lo=rst(sk.tk_lo, 0),
            tk_ida=rst(sk.tk_ida, 0),
            tk_idb=rst(sk.tk_idb, 0),
            pend=pend,
            pend_win=pend_win,
            pend_n=jnp.minimum(sk.pend_n + n_close, p),
            shed=shed,
        )

    def do_close_pool(sk: SketchState) -> SketchState:
        pc = sk.p_hll.shape[0]
        pw = sk.hll.shape[0]
        isc = (sk.slot_of >= 0) & (sk.slot_of < pc)
        c_close = close & isc
        w_close = close & (sk.slot_of >= pc)
        # compact closes → pending buffer (narrow rows)
        n_close = jnp.sum(c_close.astype(jnp.int32))
        pos = sk.pend_n + jnp.cumsum(c_close.astype(jnp.int32)) - 1
        pos = jnp.where(c_close, pos, p)
        overflow = c_close & (pos >= p)
        pos = jnp.minimum(pos, p)
        blocks = _flatten_compact(sk)
        pend = sk.pend.at[pos].set(blocks, mode="drop")
        pend_win = sk.pend_win.at[pos].set(sk.win, mode="drop")
        shed = sk.shed + jnp.sum(jnp.where(overflow, sk.count, 0)).astype(
            jnp.uint32
        )
        # wide closes → marked in place, drained by sketch_drain
        wix = jnp.where(w_close, sk.slot_of - pc, pw)
        wide_close = sk.wide_close.at[wix].set(sk.win, mode="drop")
        wide_count = sk.wide_count.at[wix].set(sk.count, mode="drop")
        # zero + free the closed compact arena slots (an overflow-shed
        # block is dropped but its arena slot is still reclaimed)
        cz = (
            jnp.zeros((pc,), bool)
            .at[jnp.where(c_close, sk.slot_of, pc)]
            .max(jnp.ones((r,), bool), mode="drop")
        )

        def rstc(x):
            m = cz.reshape((pc,) + (1,) * (x.ndim - 1))
            return jnp.where(m, jnp.asarray(0, x.dtype), x)

        return dataclasses.replace(
            sk,
            win=rst(sk.win, SENTINEL_WIN),
            count=rst(sk.count, 0),
            slot_of=jnp.where(close, jnp.int32(-1), sk.slot_of),
            p_hll=rstc(sk.p_hll),
            p_cms=rstc(sk.p_cms),
            p_hist=rstc(sk.p_hist),
            p_tkv=rstc(sk.p_tkv),
            p_tkh=rstc(sk.p_tkh),
            p_tkl=rstc(sk.p_tkl),
            p_tia=rstc(sk.p_tia),
            p_tib=rstc(sk.p_tib),
            pend=pend,
            pend_win=pend_win,
            pend_n=jnp.minimum(sk.pend_n + n_close, p),
            wide_close=wide_close,
            wide_count=wide_count,
            shed=shed,
        )

    body = do_close_pool if _pool_mode(sk) else do_close
    return lax.cond(jnp.any(close), body, lambda s: s, sk)


def _pool_alloc(sk: SketchState, mask, slot):
    """Claim free COMPACT pool slots for this phase's unallocated ring
    slots (every window opens compact; widening is `_maybe_promote`'s
    job). Fully vectorized rank-matching: the i-th needy ring slot (ring
    order — deterministic) takes the i-th free compact slot; needs past
    the free count stay unallocated, and the caller counts their rows
    into `pool_spill`. Returns (state, alloc_ok[R])."""
    r = sk.ring
    pc = sk.p_hll.shape[0]
    gslot = jnp.where(mask, slot, r)
    touched = (
        jnp.zeros((r,), jnp.int32)
        .at[gslot]
        .max(mask.astype(jnp.int32), mode="drop")
        > 0
    )
    need = touched & (sk.slot_of < 0)
    occ = (
        jnp.zeros((pc,), jnp.int32)
        .at[jnp.where((sk.slot_of >= 0) & (sk.slot_of < pc), sk.slot_of, pc)]
        .max(jnp.ones((r,), jnp.int32), mode="drop")
        > 0
    )
    free = ~occ
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    n_free = jnp.sum(free.astype(jnp.int32))
    # rank → compact slot id (only the first R free slots can be taken —
    # at most R ring slots exist to take them)
    table = (
        jnp.zeros((r,), jnp.int32)
        .at[jnp.where(free & (free_rank < r), free_rank, r)]
        .set(jnp.arange(pc, dtype=jnp.int32), mode="drop")
    )
    need_rank = jnp.cumsum(need.astype(jnp.int32)) - 1
    got = need & (need_rank < n_free)
    slot_of = jnp.where(got, table[jnp.clip(need_rank, 0, r - 1)], sk.slot_of)
    return dataclasses.replace(sk, slot_of=slot_of), slot_of >= 0


def _scatter_rows_pool(
    sk: SketchState,
    mask,
    win,
    count,
    gid,
    reg,
    rho,
    w,
    b,
    rtt_valid,
    key_hi,
    key_lo,
    weight,
    id_a,
    id_b,
    pslot,
    is_c,
    is_w,
    c_ix,
    w_ix,
    presorted,
) -> SketchState:
    """Pool-mode arena scatters for one phase (`_scatter_rows` computed
    the routing: `pslot` = pool slot per row, `is_c`/`is_w` the arena
    split, `c_ix`/`w_ix` the arena-local indices with OOB sentinels).
    Every lane scatters twice — once per arena — with the other arena's
    rows dropped by out-of-range indices, so each row folds into exactly
    the arena its window lives in. Within a phase a pool slot holds
    exactly one window (slot_of is a per-ring-slot map and the phase
    span is alias-free), so the per-arena folds keep the slab path's
    bit-exactness arguments intact at the pooled widths."""
    pc = sk.p_hll.shape[0]
    pw = sk.hll.shape[0]
    d_cms, w_cms = sk.cms.shape[1], sk.cms.shape[2]
    wc = sk.p_cms.shape[2]
    d_tk = sk.tk_votes.shape[1]

    # HLL — compact keeps the FULL m registers in int8 (rho ≤ 33), so
    # the compact fold is bit-identical to a wide fold of the same rows
    hll = sk.hll.at[w_ix, gid, reg].max(rho, mode="drop")
    p_hll = sk.p_hll.at[c_ix, gid, reg].max(
        rho.astype(jnp.int8), mode="drop"
    )

    # histogram — compact bin derives from the already-computed wide
    # bin by exact integer division (ops/histogram.loghist_coarsen_bin)
    cb = loghist_coarsen_bin(b, sk.hist.shape[2] // sk.p_hist.shape[2])
    hist = sk.hist.at[jnp.where(is_w & rtt_valid, w_ix, pw), gid, b].add(
        1, mode="drop"
    )
    p_hist = sk.p_hist.at[jnp.where(is_c & rtt_valid, c_ix, pc), gid, cb].add(
        1, mode="drop"
    )

    upd = dict(
        win=win, count=count, hll=hll, p_hll=p_hll, hist=hist, p_hist=p_hist
    )

    if presorted is None:
        rs = row_slots(key_hi, key_lo, d_cms, w_cms)  # [D, N]
        flat = w_ix[None, :].astype(jnp.int32) * (d_cms * w_cms) + rs
        upd["cms"] = (
            sk.cms.reshape(-1)
            .at[flat.reshape(-1)]
            .add(jnp.broadcast_to(w[None, :], flat.shape).reshape(-1),
                 mode="drop")
            .reshape(pw, d_cms, w_cms)
        )
        rs_c = row_slots(key_hi, key_lo, d_cms, wc)
        flat_c = c_ix[None, :].astype(jnp.int32) * (d_cms * wc) + rs_c
        upd["p_cms"] = (
            sk.p_cms.reshape(-1)
            .at[flat_c.reshape(-1)]
            .add(jnp.broadcast_to(w[None, :], flat_c.shape).reshape(-1),
                 mode="drop")
            .reshape(pc, d_cms, wc)
        )
        if d_tk:
            lanes = (sk.tk_votes, sk.tk_hi, sk.tk_lo, sk.tk_ida, sk.tk_idb)
            tkv, tkh, tkl, tia, tib = topk_update(
                lanes, jnp.where(is_w, pslot - pc, -1),
                key_hi, key_lo, id_a, id_b, weight, is_w,
            )
            p_lanes = (sk.p_tkv, sk.p_tkh, sk.p_tkl, sk.p_tia, sk.p_tib)
            pv, ph, pl, pa, pb = topk_update(
                p_lanes, jnp.where(is_c, pslot, -1),
                key_hi, key_lo, id_a, id_b, weight, is_c,
            )
            upd.update(
                tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
                p_tkv=pv, p_tkh=ph, p_tkl=pl, p_tia=pa, p_tib=pb,
            )
        return dataclasses.replace(sk, **upd)

    # -- shared-sort path: route the sorted order through the arenas --
    n = mask.shape[0]
    s_win, s_hi, s_lo, s_pos, head, run_id = presorted
    r = sk.ring
    s_slot = (s_win % jnp.uint32(r)).astype(jnp.int32)
    s_mask = mask[s_pos]
    s_w = w[s_pos]
    run_w = jax.ops.segment_sum(s_w, run_id, num_segments=n)
    rw = run_w[run_id]
    w_head = jnp.where(head, rw, 0)
    s_ia = jnp.asarray(id_a, jnp.uint32)[s_pos]
    s_ib = jnp.asarray(id_b, jnp.uint32)[s_pos]
    # a run is one (window, key): the whole run lives in ONE arena, so
    # arena routing by the run's window keeps head-add dedup intact
    s_pslot = jnp.take(sk.slot_of, s_slot)
    s_isc = s_mask & (s_pslot >= 0) & (s_pslot < pc)
    s_isw = s_mask & (s_pslot >= pc)
    s_cix = jnp.where(s_isc, s_pslot, pc)
    s_wix = jnp.where(s_isw, s_pslot - pc, pw)

    rs = row_slots(s_hi, s_lo, d_cms, w_cms)
    flat = s_wix[None, :].astype(jnp.int32) * (d_cms * w_cms) + rs
    upd["cms"] = (
        sk.cms.reshape(-1)
        .at[flat.reshape(-1)]
        .add(jnp.broadcast_to(w_head[None, :], flat.shape).reshape(-1),
             mode="drop")
        .reshape(pw, d_cms, w_cms)
    )
    rs_c = row_slots(s_hi, s_lo, d_cms, wc)
    flat_c = s_cix[None, :].astype(jnp.int32) * (d_cms * wc) + rs_c
    upd["p_cms"] = (
        sk.p_cms.reshape(-1)
        .at[flat_c.reshape(-1)]
        .add(jnp.broadcast_to(w_head[None, :], flat_c.shape).reshape(-1),
             mode="drop")
        .reshape(pc, d_cms, wc)
    )
    if d_tk:
        lanes = (sk.tk_votes, sk.tk_hi, sk.tk_lo, sk.tk_ida, sk.tk_idb)
        ch_w = topk_challengers_presorted(
            jnp.where(s_isw, s_pslot - pc, 0), s_hi, s_lo, s_ia, s_ib,
            rw, s_isw, pw, d_tk, sk.tk_votes.shape[2],
        )
        tkv, tkh, tkl, tia, tib = _apply_challengers(lanes, ch_w)
        p_lanes = (sk.p_tkv, sk.p_tkh, sk.p_tkl, sk.p_tia, sk.p_tib)
        ch_c = topk_challengers_presorted(
            jnp.where(s_isc, s_pslot, 0), s_hi, s_lo, s_ia, s_ib,
            rw, s_isc, pc, d_tk, sk.p_tkv.shape[2],
        )
        pv, ph, pl, pa, pb = _apply_challengers(p_lanes, ch_c)
        upd.update(
            tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
            p_tkv=pv, p_tkh=ph, p_tkl=pl, p_tia=pa, p_tib=pb,
        )
    return dataclasses.replace(sk, **upd)


def _scatter_rows(
    sk: SketchState,
    spec: LogHistSpec,
    mask,
    window,
    group,
    client_hi,
    client_lo,
    key_hi,
    key_lo,
    weight,
    rtt,
    rtt_valid,
    id_a,
    id_b,
    presorted=None,
    fused_sketch: bool = False,
) -> SketchState:
    """Fold one phase's rows into their ring slots (claiming empties).
    Callers guarantee the phase's window span is < R wide, so slots are
    collision-free by construction (consecutive windows ≡ distinct
    mod R).

    With `presorted` (the batch's ONE shared (window, key_hi, key_lo)
    sort from `sketch_plane_step` — ISSUE 17), the count-min and top-K
    lanes consume the shared order instead of sorting again: per-(window,
    key) run weights are summed once and reused as the count-min
    run-dedup weights (one add per run head instead of per row — adds
    commute, totals bit-identical) AND as the top-K challenger weights
    (`topk_challengers_presorted`, zero fresh sorts). The per-row lanes
    whose folds are idempotent or count-shaped (win claim, count, HLL
    register max, histogram) stay on the original row order — a run
    spans one flow key, not one client, so they cannot ride the run
    dedup. `fused_sketch` additionally routes HLL + count-min + the
    challenger scan through the single-pass Pallas kernel
    (ops/sketch_pallas.py) when the shapes support it."""
    r = sk.ring
    g, m = sk.hll.shape[1], sk.hll.shape[2]
    d_cms, w_cms = sk.cms.shape[1], sk.cms.shape[2]
    window = jnp.asarray(window, jnp.uint32)
    slot = (window % jnp.uint32(r)).astype(jnp.int32)
    gid = (jnp.asarray(group).astype(jnp.int32)) % g

    pool = _pool_mode(sk)
    if pool:
        # seat this phase's new windows in the compact arena; rows of
        # windows an exhausted pool cannot seat are masked out HERE, so
        # they never claim win/count (invariant: slot_of == -1 ⇒ win ==
        # SENTINEL) and are counted exactly once into pool_spill.
        sk, alloc_ok = _pool_alloc(sk, mask, slot)
        row_ok = mask & jnp.take(alloc_ok, slot)
        sk = dataclasses.replace(
            sk,
            pool_spill=sk.pool_spill
            + jnp.sum(mask & ~row_ok).astype(jnp.uint32),
        )
        mask = row_ok
        pc = sk.p_hll.shape[0]
        pw = sk.hll.shape[0]
        wc = sk.p_cms.shape[2]
        pslot = jnp.take(sk.slot_of, slot)
        is_c = mask & (pslot >= 0) & (pslot < pc)
        is_w = mask & (pslot >= pc)
        c_ix = jnp.where(is_c, pslot, pc)  # OOB → dropped
        w_ix = jnp.where(is_w, pslot - pc, pw)
    gslot = jnp.where(mask, slot, r)

    win = sk.win.at[gslot].min(window, mode="drop")  # claim (SENTINEL > any)
    count = sk.count.at[gslot].add(1, mode="drop")

    reg = (jnp.asarray(client_lo, jnp.uint32) & jnp.uint32(m - 1)).astype(jnp.int32)
    rho = (clz32(client_hi) + 1).astype(jnp.int32)

    w = jnp.where(mask, jnp.asarray(weight).astype(jnp.int32), 0)

    b = loghist_bin(rtt, spec)

    lanes = (sk.tk_votes, sk.tk_hi, sk.tk_lo, sk.tk_ida, sk.tk_idb)
    d_tk = sk.tk_votes.shape[1]

    if pool:
        return _scatter_rows_pool(
            sk, mask, win, count, gid, reg, rho, w, b, rtt_valid,
            key_hi, key_lo, weight, id_a, id_b,
            pslot, is_c, is_w, c_ix, w_ix, presorted,
        )

    hslot = jnp.where(mask & rtt_valid, slot, r)
    hist = sk.hist.at[hslot, gid, b].add(1, mode="drop")

    if presorted is None:
        # multi-sort oracle: per-row CMS scatter + a fresh 3-key sort
        # per top-K hash row (topk_update)
        hll = sk.hll.at[gslot, gid, reg].max(rho, mode="drop")
        rs = row_slots(key_hi, key_lo, d_cms, w_cms)  # [D, N] in [0, D*W)
        flat = gslot[None, :].astype(jnp.int32) * (d_cms * w_cms) + rs
        cms = (
            sk.cms.reshape(-1)
            .at[flat.reshape(-1)]
            .add(jnp.broadcast_to(w[None, :], flat.shape).reshape(-1), mode="drop")
            .reshape(r, d_cms, w_cms)
        )
        if d_tk:
            tkv, tkh, tkl, tia, tib = topk_update(
                lanes, slot, key_hi, key_lo, id_a, id_b, weight, mask,
            )
        else:
            tkv, tkh, tkl, tia, tib = lanes
        return dataclasses.replace(
            sk, win=win, count=count, hll=hll, cms=cms, hist=hist,
            tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
        )

    # -- shared-sort path (ISSUE 17) ------------------------------------
    n = window.shape[0]
    s_win, s_hi, s_lo, s_pos, head, run_id = presorted
    s_slot = (s_win % jnp.uint32(r)).astype(jnp.int32)
    s_mask = mask[s_pos]
    s_w = w[s_pos]
    # per-(window, key) run weight under THIS phase's mask — shared by
    # the count-min head adds and every top-K hash row
    run_w = jax.ops.segment_sum(s_w, run_id, num_segments=n)
    rw = run_w[run_id]
    w_head = jnp.where(head, rw, 0)
    s_ia = jnp.asarray(id_a, jnp.uint32)[s_pos]
    s_ib = jnp.asarray(id_b, jnp.uint32)[s_pos]
    rs = row_slots(s_hi, s_lo, d_cms, w_cms)  # [D, N] in [0, D*W)

    fused_done = False
    if fused_sketch:
        from ..ops.sketch_pallas import fused_sketch_guard, sketch_update_fused

        ok = fused_sketch_guard(
            n, r, g, m, d_cms, w_cms, d_tk, sk.tk_votes.shape[2]
        )
        if ok:
            hll, cms, challengers = sketch_update_fused(
                sk.hll, sk.cms, tk_shape=(d_tk, sk.tk_votes.shape[2]),
                s_slot=s_slot, s_gid=gid[s_pos], s_reg=reg[s_pos],
                s_rho=rho[s_pos], s_mask=s_mask, w_head=w_head, rw=rw,
                cms_slots=rs, s_hi=s_hi, s_lo=s_lo, s_ia=s_ia, s_ib=s_ib,
            )
            fused_done = True
    if not fused_done:
        hll = sk.hll.at[gslot, gid, reg].max(rho, mode="drop")
        # one add per run HEAD (carrying the run's summed weight)
        # instead of per row: non-head rows add 0 at a live cell — a
        # no-op — so cell totals stay bit-identical to the per-row
        # oracle while the scatter's live writes drop to one per
        # (window, key) run. Head slots are always in-range (window
        # % R), so no index masking is needed: fully-unmasked runs
        # carry w_head == 0.
        flat = s_slot[None, :] * (d_cms * w_cms) + rs
        cms = (
            sk.cms.reshape(-1)
            .at[flat.reshape(-1)]
            .add(
                jnp.broadcast_to(w_head[None, :], flat.shape).reshape(-1),
                mode="drop",
            )
            .reshape(r, d_cms, w_cms)
        )
        challengers = (
            topk_challengers_presorted(
                s_slot, s_hi, s_lo, s_ia, s_ib, rw, s_mask,
                r, d_tk, sk.tk_votes.shape[2],
            )
            if d_tk
            else []
        )
    tkv, tkh, tkl, tia, tib = (
        _apply_challengers(lanes, challengers) if d_tk else lanes
    )
    return dataclasses.replace(
        sk, win=win, count=count, hll=hll, cms=cms, hist=hist,
        tk_votes=tkv, tk_hi=tkh, tk_lo=tkl, tk_ida=tia, tk_idb=tib,
    )


def sketch_plane_step(
    sk: SketchState,
    spec: LogHistSpec,
    *,
    window,
    valid,
    base_w,
    close_w,
    group,
    client_hi,
    client_lo,
    key_hi,
    key_lo,
    weight,
    rtt,
    rtt_valid,
    id_a,
    id_b,
    shared_sort: bool | None = None,
    fused_sketch: bool | None = None,
) -> SketchState:
    """One batch through the plane, in window order (traced):

      1. closing-span rows (base_w ≤ window < close_w, within the live
         ring span) fold into their still-open slots;
      2. every slot with win < close_w closes into the pending buffer;
      3. new-span rows (window ≥ close_w) claim the freed slots.

    `base_w`/`close_w` are the pre-/post-batch open-span starts — the
    single-chip fused step derives them on device from the same rule
    the host replays; the sharded step receives them from the host
    (which decides advances before dispatch).

    The closing phase's collision-free span is anchored at the OLDEST
    LIVE RING SLOT (or base_w when the ring is empty), not at base_w:
    when a batch's own t_min jumps ahead of windows still open from
    earlier batches, anchoring at base_w would let a closing row alias
    mod R into an older occupied slot and silently merge two windows'
    sketches. Rows in the mid-gap [anchor + R, close_w) — only
    possible when one batch spans more than R windows below its close
    bound — are counted into `shed` instead (module docstring).

    One-pass fold (ISSUE 17). With `shared_sort` (default: the
    DEEPFLOW_SHARED_SORT knob, ON) and the top-K lane enabled, the
    batch's (window, key_hi, key_lo) stable sort runs ONCE here and
    both phases consume it — the per-hash-row fresh sorts inside
    `topk_update` (2 phases × topk_rows sorts) collapse into this one,
    and the count-min scatter dedups to run heads. Bit-exact vs the
    multi-sort path (tests/test_sketch_onepass.py). `fused_sketch`
    (default: DEEPFLOW_FUSED_SKETCH, OFF until on-chip numbers) further
    collapses the sorted-order folds into the single-pass Pallas
    kernel. Both knobs resolve at TRACE time — callers whose jitted
    step outlives an env flip must thread them as static arguments
    (aggregator/window.py does)."""
    if shared_sort is None:
        shared_sort = _use_shared_sort()
    if fused_sketch is None:
        fused_sketch = _use_fused_sketch()
    if _pool_mode(sk):
        # the Pallas kernel folds into per-ring-slot slabs; the pooled
        # arenas route through plain XLA scatters until the kernel
        # learns the dual-arena layout (documented in PERF.md §28)
        fused_sketch = False
    r = sk.ring
    window = jnp.asarray(window, jnp.uint32)
    base_w = jnp.asarray(base_w, jnp.uint32)
    close_w = jnp.asarray(close_w, jnp.uint32)
    # oldest live slot bounds the alias-free span; SENTINEL (empty
    # ring) never lowers the min below base_w
    anchor = jnp.minimum(jnp.min(sk.win), base_w)
    hi_a = jnp.minimum(close_w, anchor + jnp.uint32(r))
    in_a = valid & (window >= base_w) & (window < hi_a)
    in_c = valid & (window >= jnp.maximum(close_w, base_w))
    shed = (
        valid
        & (window >= jnp.maximum(anchor + jnp.uint32(r), base_w))
        & (window < close_w)
    )

    presorted = None
    if shared_sort and sk.tk_votes.shape[1]:
        # THE batch sort: stable 3-key over the raw lanes + a position
        # payload. No sentinel rekey is needed — phase masks ride
        # through the permutation, and masked-out rows contribute
        # weight 0 without perturbing the relative order of live rows.
        n = window.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        s_win, s_hi, s_lo, s_pos = jax.lax.sort(
            (window, jnp.asarray(key_hi, jnp.uint32),
             jnp.asarray(key_lo, jnp.uint32), iota),
            num_keys=3,
        )
        head = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (s_win[1:] != s_win[:-1])
                | (s_hi[1:] != s_hi[:-1])
                | (s_lo[1:] != s_lo[:-1]),
            ]
        )
        run_id = jnp.cumsum(head.astype(jnp.int32)) - 1
        presorted = (s_win, s_hi, s_lo, s_pos, head, run_id)

    args = (group, client_hi, client_lo, key_hi, key_lo, weight, rtt,
            rtt_valid, id_a, id_b)
    kw = dict(presorted=presorted, fused_sketch=fused_sketch)
    sk = _scatter_rows(sk, spec, in_a, window, *args, **kw)
    sk = sketch_close(sk, close_w)
    sk = _scatter_rows(sk, spec, in_c, window, *args, **kw)
    if _pool_mode(sk):
        sk = _maybe_promote(sk)
    folded = (jnp.sum(in_a) + jnp.sum(in_c)).astype(jnp.uint32)
    return dataclasses.replace(
        sk,
        rows=sk.rows + folded,
        shed=sk.shed + jnp.sum(shed).astype(jnp.uint32),
    )


def _maybe_promote(sk: SketchState) -> SketchState:
    """End-of-step promotion (pool mode): if the most-saturated occupied
    compact slot has reached the `promote_fill` threshold — saturation =
    CMS row-0 fill fraction, computed from device-resident lanes inside
    the fused step, zero new fetches — move it to a free wide slot.

    Promotion IS a merge into an all-zero wide slot (freed wide slots
    are zeroed at drain), so every lane rides the r12 merge algebra at
    the pooled widths: HLL register max (int8→int32 cast — bit-exact),
    CMS tile-add (`cms_expand` — overestimate preserved), histogram
    center placement (`loghist_expand`), top-K bucket tiling
    (`topk_tile` — a key's own wide bucket always holds its entry;
    spurious tiled copies dedupe at `topk_select`). Closed-block answers
    therefore stay inside the §17 error envelope. At most one promotion
    per batch (`lax.cond`); with no free wide slot the window simply
    stays compact — accuracy degrades toward the compact bound, never
    correctness."""
    pc = sk.p_hll.shape[0]
    pw = sk.hll.shape[0]
    r = sk.ring
    ones_r = jnp.ones((r,), jnp.int32)
    isc = (sk.slot_of >= 0) & (sk.slot_of < pc)
    occ = (
        jnp.zeros((pc,), jnp.int32)
        .at[jnp.where(isc, sk.slot_of, pc)]
        .max(ones_r, mode="drop")
        > 0
    )
    fill = jnp.mean((sk.p_cms[:, 0, :] != 0).astype(jnp.float32), axis=-1)
    cand = occ & (fill >= sk.promote_fill)
    w_occ = (
        jnp.zeros((pw,), jnp.int32)
        .at[jnp.where(sk.slot_of >= pc, sk.slot_of - pc, pw)]
        .max(ones_r, mode="drop")
        > 0
    )
    # a closed-awaiting-drain wide slot is NOT free until drained
    w_free = (~w_occ) & (sk.wide_close == jnp.uint32(SENTINEL_WIN))
    do = jnp.any(cand) & jnp.any(w_free)

    def promote(sk: SketchState) -> SketchState:
        pidx = jnp.argmax(jnp.where(cand, fill, -1.0))
        rstar = jnp.argmax((sk.slot_of == pidx).astype(jnp.int32))
        widx = jnp.argmax(w_free.astype(jnp.int32))
        upd = dict(
            hll=sk.hll.at[widx].set(sk.p_hll[pidx].astype(jnp.int32)),
            cms=sk.cms.at[widx].set(
                cms_expand(sk.p_cms[pidx], sk.cms.shape[2])
            ),
            hist=sk.hist.at[widx].set(
                loghist_expand(sk.p_hist[pidx], sk.hist.shape[2])
            ),
        )
        if sk.tk_votes.shape[1]:
            tkv, tkh, tkl, tia, tib = topk_tile(
                (sk.p_tkv[pidx], sk.p_tkh[pidx], sk.p_tkl[pidx],
                 sk.p_tia[pidx], sk.p_tib[pidx]),
                sk.tk_votes.shape[2],
            )
            upd.update(
                tk_votes=sk.tk_votes.at[widx].set(tkv),
                tk_hi=sk.tk_hi.at[widx].set(tkh),
                tk_lo=sk.tk_lo.at[widx].set(tkl),
                tk_ida=sk.tk_ida.at[widx].set(tia),
                tk_idb=sk.tk_idb.at[widx].set(tib),
            )
        return dataclasses.replace(
            sk,
            slot_of=sk.slot_of.at[rstar].set(
                jnp.int32(pc) + widx.astype(jnp.int32)
            ),
            p_hll=sk.p_hll.at[pidx].set(0),
            p_cms=sk.p_cms.at[pidx].set(0),
            p_hist=sk.p_hist.at[pidx].set(0),
            p_tkv=sk.p_tkv.at[pidx].set(0),
            p_tkh=sk.p_tkh.at[pidx].set(0),
            p_tkl=sk.p_tkl.at[pidx].set(0),
            p_tia=sk.p_tia.at[pidx].set(0),
            p_tib=sk.p_tib.at[pidx].set(0),
            pool_promos=sk.pool_promos + jnp.uint32(1),
            **upd,
        )

    return lax.cond(do, promote, lambda s: s, sk)


def _drain_impl(sk: SketchState, close_w):
    sk = sketch_close(sk, close_w)
    pend, pend_win, n = sk.pend, sk.pend_win, sk.pend_n
    sk = dataclasses.replace(sk, pend_n=jnp.zeros((), jnp.int32))
    if _pool_mode(sk):
        # wide slots drain IN PLACE: pack every closed-awaiting-drain
        # slot as a full-width block row, then zero + free it. Open
        # wide slots ride along as all-SENTINEL rows the host skips.
        pw = sk.hll.shape[0]
        wmask = sk.wide_close != jnp.uint32(SENTINEL_WIN)
        wide_rows = _flatten_wide_arena(sk, sk.wide_count)
        wide_rows = jnp.where(wmask[:, None], wide_rows, 0)
        wide_wins = sk.wide_close

        def rstw(x):
            mm = wmask.reshape((pw,) + (1,) * (x.ndim - 1))
            return jnp.where(mm, jnp.asarray(0, x.dtype), x)

        sk = dataclasses.replace(
            sk,
            hll=rstw(sk.hll),
            cms=rstw(sk.cms),
            hist=rstw(sk.hist),
            tk_votes=rstw(sk.tk_votes),
            tk_hi=rstw(sk.tk_hi),
            tk_lo=rstw(sk.tk_lo),
            tk_ida=rstw(sk.tk_ida),
            tk_idb=rstw(sk.tk_idb),
            wide_close=jnp.full((pw,), SENTINEL_WIN, dtype=jnp.uint32),
            wide_count=jnp.where(wmask, jnp.uint32(0), sk.wide_count),
        )
    else:
        wide_rows = jnp.zeros((0, 0), jnp.uint32)
        wide_wins = jnp.zeros((0,), jnp.uint32)
    return sk, pend, pend_win, n, wide_rows, wide_wins


# donated: the returned state's pending cursor resets while the old
# pend/pend_win buffers come back as outputs — XLA copies whichever
# side cannot alias, so later in-step closes never race the (possibly
# deferred) host fetch of the drained rows.
sketch_drain = jax.jit(_drain_impl, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# host side


@dataclasses.dataclass
class WindowSketchBlock:
    """One closed window's fetched sketch summary (host numpy).

    Mergeable across shards (`merge`). `distinct`/`estimate`/`topk`
    are pure numpy over the fetched arrays (shared-xp ops math — no
    device access); `tdigest`/`quantile` reuse the jitted centroid
    compressor on tiny fixed-size arrays — a device dispatch, but off
    the ingest fetch path and outside the host_fetch budget seam, so
    sink/querier consumers pay it per closed window, never per batch.
    Top-K lanes are kept as flat candidate arrays (bucket layout is
    irrelevant once closed), which is also what makes the cross-shard
    merge a plain concatenation."""

    window: int
    config: SketchConfig
    n_updates: int
    hll: np.ndarray  # [G, m] i32
    cms: np.ndarray  # [D, W] i64 (i64: shard merges must not wrap)
    hist: np.ndarray  # [G, B] i64
    tk_hi: np.ndarray  # [n_cand] u32
    tk_lo: np.ndarray
    tk_ida: np.ndarray
    tk_idb: np.ndarray
    tk_votes: np.ndarray  # [n_cand] i64

    @classmethod
    def from_row(cls, row: np.ndarray, window: int, cfg: SketchConfig):
        """Unpack one [WIDE] u32 packed block row (layout contract:
        SketchConfig.block_width)."""
        g, m = cfg.num_groups, cfg.hll_m
        d, w = cfg.cms_depth, cfg.cms_width
        b = cfg.hist.bins
        tk = cfg.topk_rows * cfg.topk_cols
        o = 0

        def take(n):
            nonlocal o
            out = row[o : o + n]
            o += n
            return out

        n_updates = int(take(1)[0])
        hll = take(g * m).astype(np.int32).reshape(g, m)
        cms = take(d * w).astype(np.int64).reshape(d, w)
        hist = take(g * b).astype(np.int64).reshape(g, b)
        votes = take(tk).astype(np.int32).astype(np.int64)
        hi, lo, ida, idb = (take(tk) for _ in range(4))
        keep = votes > 0
        return cls(
            window=int(window), config=cfg, n_updates=n_updates,
            hll=hll, cms=cms, hist=hist,
            tk_hi=hi[keep].astype(np.uint32), tk_lo=lo[keep].astype(np.uint32),
            tk_ida=ida[keep].astype(np.uint32), tk_idb=idb[keep].astype(np.uint32),
            tk_votes=votes[keep],
        )

    @classmethod
    def from_compact_row(cls, row: np.ndarray, window: int, cfg: SketchConfig):
        """Unpack one [CW] u32 compact pool block row (layout contract:
        SketchConfig.compact_block_width) and up-tile it to the full
        block form — HLL unpacks bit-exactly (full m registers, 4 per
        word), CMS/hist expand via the same congruence/center math the
        device promotion uses, and top-K candidates read directly from
        the flat compact lanes (the block keeps candidates, not
        buckets, so no tiling is needed). Every downstream consumer
        (merge algebra, distinct/estimate/topk/quantile, cascade parent
        feeds) then works unchanged."""
        pool = cfg.pool
        assert pool is not None, "compact row without a pool config"
        g, m = cfg.num_groups, cfg.hll_m
        d, w = cfg.cms_depth, cfg.cms_width
        wc = cfg.pool_cms_width
        bc = cfg.pool_hist_bins
        tk = cfg.topk_rows * cfg.pool_topk_cols
        o = 0

        def take(n):
            nonlocal o
            out = row[o : o + n]
            o += n
            return out

        n_updates = int(take(1)[0])
        hll = hll_unpack_registers_np(
            take(g * m // 4).reshape(g, m // 4), m
        )
        cms = cms_expand(
            take(d * wc).astype(np.int64).reshape(d, wc), w, xp=np
        )
        hist = loghist_expand(
            take(g * bc).astype(np.int64).reshape(g, bc), cfg.hist.bins,
            xp=np,
        )
        votes = take(tk).astype(np.int32).astype(np.int64)
        hi, lo, ida, idb = (take(tk) for _ in range(4))
        keep = votes > 0
        return cls(
            window=int(window), config=cfg, n_updates=n_updates,
            hll=hll, cms=cms, hist=hist,
            tk_hi=hi[keep].astype(np.uint32), tk_lo=lo[keep].astype(np.uint32),
            tk_ida=ida[keep].astype(np.uint32), tk_idb=idb[keep].astype(np.uint32),
            tk_votes=votes[keep],
        )

    def merge(self, other: "WindowSketchBlock") -> "WindowSketchBlock":
        """Cross-shard combine for the same window: register max,
        counter add, candidate union (estimates re-derive from the
        merged count-min at query time)."""
        assert other.window == self.window, (self.window, other.window)
        return WindowSketchBlock(
            window=self.window,
            config=self.config,
            n_updates=self.n_updates + other.n_updates,
            hll=np.maximum(self.hll, other.hll),
            cms=self.cms + other.cms,
            hist=self.hist + other.hist,
            tk_hi=np.concatenate([self.tk_hi, other.tk_hi]),
            tk_lo=np.concatenate([self.tk_lo, other.tk_lo]),
            tk_ida=np.concatenate([self.tk_ida, other.tk_ida]),
            tk_idb=np.concatenate([self.tk_idb, other.tk_idb]),
            tk_votes=np.concatenate([self.tk_votes, other.tk_votes]),
        )

    # -- queries ---------------------------------------------------------
    def distinct(self, group: int | None = None) -> float:
        """HLL distinct-client estimate: one group, or the whole window
        (register-max union over groups — NOT the per-group sum, which
        would double-count clients seen by several services)."""
        if group is None:
            est = hll_estimate_np(self.hll.max(axis=0, keepdims=True))
            return float(est[0])
        return float(hll_estimate_np(self.hll[group : group + 1])[0])

    def distinct_per_group(self) -> np.ndarray:
        return hll_estimate_np(self.hll)

    def estimate(self, key_hi, key_lo) -> np.ndarray:
        """Count-min point estimates (overestimate-only) for flow keys."""
        from ..ops.cms import cms_query_np

        return cms_query_np(self.cms, key_hi, key_lo)

    def tdigest(self, group: int | None = None, compression: int = 64):
        """(means, weights) centroid export of the latency histogram —
        the compact wire form (ops/tdigest.py). group None pools."""
        hist = self.hist.sum(axis=0) if group is None else self.hist[group]
        spec = self.config.hist
        centers = spec.vmin * np.power(
            spec.gamma, np.arange(spec.bins, dtype=np.float64) + 0.5
        )
        m, w = tdigest_compress(
            jnp.asarray(centers, jnp.float32),
            jnp.asarray(hist, jnp.float32),
            compression=compression,
        )
        return np.asarray(m), np.asarray(w)

    def quantile(self, q: float, group: int | None = None) -> float:
        """Latency quantile through the t-digest export path."""
        m, w = self.tdigest(group)
        return float(
            np.asarray(tdigest_quantile(jnp.asarray(m), jnp.asarray(w),
                                        jnp.asarray([q], jnp.float32)))[0]
        )

    def topk(self, k: int) -> list[dict]:
        """Invert the heavy-hitter sketch: candidates from the bucket
        lanes, ranked by the same window's count-min estimate."""
        if len(self.tk_hi) == 0:
            return []
        est = self.estimate(self.tk_hi, self.tk_lo)
        hi, lo, ida, idb, est_k = topk_select(
            self.tk_hi, self.tk_lo, self.tk_ida, self.tk_idb, est, k
        )
        return [
            {
                "key_hi": int(hi[i]), "key_lo": int(lo[i]),
                "id_a": int(ida[i]), "id_b": int(idb[i]),
                "estimate": int(est_k[i]),
            }
            for i in range(len(hi))
        ]


def hold_blocks(held: list, new_blocks, cap: int) -> int:
    """THE closed-block retention policy, shared by RollupPipeline and
    ShardedWindowManager: append, then drop-oldest beyond `cap` (the
    same counted-drop stance as the device pending buffer). Returns the
    number dropped — callers count it so an undrained
    pop_closed_sketches consumer is loud, not a leak."""
    held.extend(new_blocks)
    overflow = len(held) - cap
    if overflow > 0:
        del held[:overflow]
        return overflow
    return 0


def unpack_drained(rows: np.ndarray, wins: np.ndarray, cfg: SketchConfig):
    """Fetched drained/snapshotted rows + [n] window ids →
    WindowSketchBlocks, dispatching on the row width: `block_width` =
    wide rows, `compact_block_width` = pooled pending rows, and their
    sum = open-snapshot combo rows (compact part ‖ wide part — the part
    with a nonzero count word is the live one; allocated slots always
    hold count ≥ 1, so at most one side is nonzero). Blocks that never
    saw a row (possible on the sharded path, where a device closes a
    window its shard had no data for) are dropped here."""
    wide_w = cfg.block_width
    cw = cfg.compact_block_width if cfg.pool is not None else None
    out = []
    for i in range(rows.shape[0]):
        row = rows[i]
        if cw is not None and row.shape[0] == cw:
            blk = WindowSketchBlock.from_compact_row(row, int(wins[i]), cfg)
        elif cw is not None and row.shape[0] == cw + wide_w:
            crow, wrow = row[:cw], row[cw:]
            if int(crow[0]):
                blk = WindowSketchBlock.from_compact_row(crow, int(wins[i]), cfg)
            else:
                blk = WindowSketchBlock.from_row(wrow, int(wins[i]), cfg)
        else:
            blk = WindowSketchBlock.from_row(row, int(wins[i]), cfg)
        if blk.n_updates or len(blk.tk_hi):
            out.append(blk)
    return out


__all__ = [
    "PoolConfig",
    "SketchConfig",
    "SketchState",
    "WindowSketchBlock",
    "sketch_init",
    "sketch_close",
    "sketch_drain",
    "sketch_plane_step",
    "unpack_drained",
    "topk_candidates",
]
