from .stash import (
    StashState,
    stash_flush,
    stash_flush_range,
    stash_init,
    stash_merge,
    unpack_flush_rows,
)
from .window import WindowConfig, WindowManager

__all__ = [
    "StashState",
    "stash_init",
    "stash_merge",
    "stash_flush",
    "stash_flush_range",
    "unpack_flush_rows",
    "WindowConfig",
    "WindowManager",
]
