from .stash import (
    StashState,
    stash_flush,
    stash_flush_range,
    stash_fold,
    stash_fold_counted,
    stash_init,
    stash_merge,
    stash_merge_fold,
    unpack_flush_rows,
)
from .sketchplane import SketchConfig, WindowSketchBlock
from .window import WindowConfig, WindowManager

__all__ = [
    "StashState",
    "stash_init",
    "stash_merge",
    "stash_fold",
    "stash_fold_counted",
    "stash_merge_fold",
    "stash_flush",
    "stash_flush_range",
    "unpack_flush_rows",
    "WindowConfig",
    "WindowManager",
    "SketchConfig",
    "WindowSketchBlock",
]
