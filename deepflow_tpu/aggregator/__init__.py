from .stash import StashState, stash_init, stash_merge, stash_flush
from .window import WindowConfig, WindowManager

__all__ = [
    "StashState",
    "stash_init",
    "stash_merge",
    "stash_flush",
    "WindowConfig",
    "WindowManager",
]
