"""Device-resident document stash.

The TPU analogue of the reference's per-window `HashMap<StashKey, Document>`
(collector.rs:806-822) and `QuadrupleStash` (quadruple_generator.rs:233):
a fixed-capacity, HBM-resident table of (window slot, 64-bit key, tag row,
meter row), kept sorted by (slot, key) as an invariant *by construction* —
every merge re-sorts the concatenation of stash and batch, reduces
duplicate keys with the schema's SUM/MAX ops, and keeps the first
`capacity` segments. Sentinel-keyed rows (empty / flushed) sort to the end
and are reclaimed by the same compaction.

Overflow policy: segments beyond capacity are dropped and counted
(`dropped_overflow`). Because the sort is (slot, key)-ordered, drops land
on the *newest* window's keys — older windows (about to flush) are never
evicted. This mirrors the reference's backpressure stance of shedding
newest data under overload (OverwriteQueue, libs/queue/queue.go:139)
while protecting closing windows.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import MeterSchema, TagSchema
from ..ops.segment import SENTINEL_SLOT, groupby_reduce


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StashState:
    slot: jnp.ndarray  # [S] u32 absolute window index (SENTINEL = empty)
    key_hi: jnp.ndarray  # [S] u32
    key_lo: jnp.ndarray  # [S] u32
    tags: jnp.ndarray  # [T, S] u32 (column-major — see ops/segment.py)
    meters: jnp.ndarray  # [M, S] f32
    valid: jnp.ndarray  # [S] bool
    dropped_overflow: jnp.ndarray  # scalar i32, running count of shed segments

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def stash_init(capacity: int, tag_schema: TagSchema, meter_schema: MeterSchema) -> StashState:
    return StashState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), dtype=jnp.uint32),
        key_lo=jnp.zeros((capacity,), dtype=jnp.uint32),
        tags=jnp.zeros((tag_schema.num_fields, capacity), dtype=jnp.uint32),
        meters=jnp.zeros((meter_schema.num_fields, capacity), dtype=jnp.float32),
        valid=jnp.zeros((capacity,), dtype=bool),
        dropped_overflow=jnp.zeros((), dtype=jnp.int32),
    )


def _merge_impl(state: StashState, slot, key_hi, key_lo, tags_t, meters_t, valid, sum_cols_t, max_cols_t):
    s = state.capacity
    sum_cols = np.asarray(sum_cols_t, dtype=np.int32)
    max_cols = np.asarray(max_cols_t, dtype=np.int32)

    all_slot = jnp.concatenate([state.slot, slot])
    all_hi = jnp.concatenate([state.key_hi, key_hi])
    all_lo = jnp.concatenate([state.key_lo, key_lo])
    all_tags = jnp.concatenate([state.tags, tags_t], axis=1)
    all_meters = jnp.concatenate([state.meters, meters_t], axis=1)
    all_valid = jnp.concatenate([state.valid, valid])

    g = groupby_reduce(
        all_slot, all_hi, all_lo, all_tags, all_meters, all_valid,
        sum_cols, max_cols, out_capacity=s,
    )

    dropped = jnp.maximum(g.num_segments - s, 0)
    new_state = StashState(
        slot=g.slot,
        key_hi=g.key_hi,
        key_lo=g.key_lo,
        tags=g.tags,
        meters=g.meters,
        valid=g.seg_valid,
        dropped_overflow=state.dropped_overflow + dropped,
    )
    return new_state


_merge = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0,)
)(_merge_impl)


def stash_merge(
    state: StashState,
    slot,
    key_hi,
    key_lo,
    tags,
    meters,
    valid,
    meter_schema: MeterSchema,
) -> StashState:
    """Merge a doc batch into the stash (one sort of [S+N] rows).

    tags/meters are column-major ([T, N] / [M, N])."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return _merge(state, slot, key_hi, key_lo, tags, meters, valid, sum_cols, max_cols)


@jax.jit
def stash_flush(state: StashState, window_idx) -> tuple[StashState, dict]:
    """Close a window: emit rows of `window_idx`, reclaim their slots.

    Returns (new_state, out) where out holds full-capacity arrays plus a
    `mask` of emitted rows (static shapes; host compacts). The stash keeps
    its sort invariant trivially — holes are sentinel rows reclaimed by the
    next merge's compaction.
    """
    window_idx = jnp.asarray(window_idx, dtype=jnp.uint32)
    mask = state.valid & (state.slot == window_idx)
    out = {
        "mask": mask,
        "slot": state.slot,
        "key_hi": state.key_hi,
        "key_lo": state.key_lo,
        "tags": state.tags,
        "meters": state.meters,
        "count": jnp.sum(mask.astype(jnp.int32)),
    }
    new_state = dataclasses.replace(
        state,
        slot=jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot),
        valid=state.valid & ~mask,
    )
    return new_state, out
