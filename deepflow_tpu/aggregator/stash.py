"""Device-resident document stash.

The TPU analogue of the reference's per-window `HashMap<StashKey, Document>`
(collector.rs:806-822) and `QuadrupleStash` (quadruple_generator.rs:233):
a fixed-capacity, HBM-resident table of (window slot, 64-bit key, tag row,
meter row), kept sorted by (slot, key) as an invariant *by construction* —
every merge re-sorts the concatenation of stash and batch, reduces
duplicate keys with the schema's SUM/MAX ops, and keeps the first
`capacity` segments. Sentinel-keyed rows (empty / flushed) sort to the end
and are reclaimed by the same compaction.

Overflow policy: segments beyond capacity are dropped and counted
(`dropped_overflow`). Because the sort is (slot, key)-ordered, drops land
on the *newest* window's keys — older windows (about to flush) are never
evicted. This mirrors the reference's backpressure stance of shedding
newest data under overload (OverwriteQueue, libs/queue/queue.go:139)
while protecting closing windows.

Two fold strategies share this file (ARCHITECTURE.md "Fold strategies"):
the full-sort fold (`_fold_impl` — re-sorts the [S+A] concat, the
oracle) and the incremental merge-fold (`_merge_fold_impl` — sorts only
the accumulator and rank-merges it against the standing stash order,
optionally span-bounded for window advances). `WindowConfig.fold_mode`
picks one; they are pinned bit-exact against each other.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from ..datamodel.schema import MeterSchema, TagSchema
from ..ops.segment import (
    SENTINEL_SLOT,
    groupby_reduce,
    groupby_reduce_sorted,
    merge_order,
    merge_ranks,
)

_U32_MAX = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StashState:
    slot: jnp.ndarray  # [S] u32 absolute window index (SENTINEL = empty)
    key_hi: jnp.ndarray  # [S] u32
    key_lo: jnp.ndarray  # [S] u32
    tags: jnp.ndarray  # [T, S] u32 (column-major — see ops/segment.py)
    meters: jnp.ndarray  # [M, S] f32
    valid: jnp.ndarray  # [S] bool
    dropped_overflow: jnp.ndarray  # scalar i32, running count of shed segments

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def stash_init(capacity: int, tag_schema: TagSchema, meter_schema: MeterSchema) -> StashState:
    return StashState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), dtype=jnp.uint32),
        key_lo=jnp.zeros((capacity,), dtype=jnp.uint32),
        tags=jnp.zeros((tag_schema.num_fields, capacity), dtype=jnp.uint32),
        meters=jnp.zeros((meter_schema.num_fields, capacity), dtype=jnp.float32),
        valid=jnp.zeros((capacity,), dtype=bool),
        dropped_overflow=jnp.zeros((), dtype=jnp.int32),
    )


def _merge_impl(state: StashState, slot, key_hi, key_lo, tags_t, meters_t, valid, sum_cols_t, max_cols_t):
    s = state.capacity
    sum_cols = np.asarray(sum_cols_t, dtype=np.int32)
    max_cols = np.asarray(max_cols_t, dtype=np.int32)

    all_slot = jnp.concatenate([state.slot, slot])
    all_hi = jnp.concatenate([state.key_hi, key_hi])
    all_lo = jnp.concatenate([state.key_lo, key_lo])
    all_tags = jnp.concatenate([state.tags, tags_t], axis=1)
    all_meters = jnp.concatenate([state.meters, meters_t], axis=1)
    all_valid = jnp.concatenate([state.valid, valid])

    # groupby_reduce consumes row-major meters; the stash keeps its
    # column-major layout (free column selection at flush), so the fold
    # transposes here — at fold scale this replaces the row-gather the
    # reduce no longer performs, and XLA folds it into that copy.
    g = groupby_reduce(
        all_slot, all_hi, all_lo, all_tags, jnp.transpose(all_meters), all_valid,
        sum_cols, max_cols, out_capacity=s,
    )

    dropped = jnp.maximum(g.num_segments - s, 0)
    new_state = StashState(
        slot=g.slot,
        key_hi=g.key_hi,
        key_lo=g.key_lo,
        tags=g.tags,
        meters=g.meters,
        valid=g.seg_valid,
        dropped_overflow=state.dropped_overflow + dropped,
    )
    return new_state


_merge = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0,)
)(_merge_impl)


def stash_merge(
    state: StashState,
    slot,
    key_hi,
    key_lo,
    tags,
    meters,
    valid,
    meter_schema: MeterSchema,
) -> StashState:
    """Merge a doc batch into the stash (one sort of [S+N] rows).

    tags/meters are column-major ([T, N] / [M, N])."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return _merge(state, slot, key_hi, key_lo, tags, meters, valid, sum_cols, max_cols)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AccumState:
    """Raw-row accumulator in front of the stash.

    The reference pays a hash-map probe per document per batch
    (Stash::add, collector.rs:810). A sort-based stash that re-sorts
    [S+N] rows per batch pays the whole O((S+N) log(S+N)) sort per batch
    instead — measured on v5e the sort is overhead-dominated (3.3 ms at
    32k rows but only 4.0 ms at 131k, PERF.md), so the TPU-native shape
    is: *append* each batch into this fixed ring (one
    dynamic_update_slice, bandwidth-bound) and amortize ONE sort+reduce
    over many batches (`collector_fold`), triggered on capacity or
    window close. Invalid rows are sentinel-keyed at append time, so
    the accumulator needs no separate validity lane.
    """

    slot: jnp.ndarray  # [A] u32 (SENTINEL = empty / invalid)
    key_hi: jnp.ndarray  # [A] u32
    key_lo: jnp.ndarray  # [A] u32
    tags: jnp.ndarray  # [T, A] u32
    meters: jnp.ndarray  # [M, A] f32

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def accum_init(capacity: int, tag_schema: TagSchema, meter_schema: MeterSchema) -> AccumState:
    return AccumState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), dtype=jnp.uint32),
        key_lo=jnp.zeros((capacity,), dtype=jnp.uint32),
        tags=jnp.zeros((tag_schema.num_fields, capacity), dtype=jnp.uint32),
        meters=jnp.zeros((meter_schema.num_fields, capacity), dtype=jnp.float32),
    )


def _append_impl(acc: AccumState, slot, key_hi, key_lo, tags_t, meters_t, valid, offset):
    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    upd = jax.lax.dynamic_update_slice
    return AccumState(
        slot=upd(acc.slot, slot, (offset,)),
        key_hi=upd(acc.key_hi, key_hi, (offset,)),
        key_lo=upd(acc.key_lo, key_lo, (offset,)),
        tags=upd(acc.tags, tags_t, (0, offset)),
        meters=upd(acc.meters, meters_t, (0, offset)),
    )


accum_append = jax.jit(_append_impl, donate_argnums=(0,))


def _fold_impl(state: StashState, acc: AccumState, sum_cols_t, max_cols_t):
    """One sort+reduce over [S + A] rows → fresh stash + empty accumulator."""
    new_state = _merge_impl(
        state,
        acc.slot,
        acc.key_hi,
        acc.key_lo,
        acc.tags,
        acc.meters,
        acc.slot != jnp.uint32(SENTINEL_SLOT),
        sum_cols_t,
        max_cols_t,
    )
    # Only the slot lane needs clearing — sentinel slots make key/tag/meter
    # bytes unreachable, and the next appends overwrite them in place.
    new_acc = dataclasses.replace(
        acc, slot=jnp.full((acc.capacity,), SENTINEL_SLOT, dtype=jnp.uint32)
    )
    return new_state, new_acc


collector_fold = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0, 1)
)(_fold_impl)


def stash_fold(
    state: StashState, acc: AccumState, meter_schema: MeterSchema
) -> tuple[StashState, AccumState]:
    """Schema-keyed wrapper over collector_fold."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return collector_fold(state, acc, sum_cols, max_cols)


# ---------------------------------------------------------------------------
# Incremental merge-fold (ISSUE 5). The full-sort fold above re-sorts
# the whole [S+A] stash+accumulator concatenation on every trigger even
# though the stash is ALREADY sorted by (slot, key) — the fold-dominated
# windowed advance (PERF.md §12 drain_ms) pays O((S+A) log(S+A)) 3-key
# compare-exchange for state it holds sorted. The merge-fold sorts only
# the accumulator's [A] rows, rank-merges them against the stash
# (ops/segment.merge_ranks — searchsorted-based merge ranks, then one
# single-key sort or scatter), and feeds the merged run to the SAME
# segment reduce, so it is bit-exact vs `_fold_impl` including the
# overflow stance (tests/test_merge_fold.py).
#
# It requires the CANONICAL stash layout: live rows form a positional,
# (slot, key)-ascending prefix; dead rows (sentinel slot) fill the tail.
# Every producer preserves it — `groupby_reduce` emits segments that
# way, `stash_init` starts empty, and `stash_flush_range(compact=True)`
# re-establishes it after punching out a closed-window prefix. The
# per-window `stash_flush` oracle does NOT (it leaves holes in place);
# fold_mode="merge" managers only ever drain through the compacting
# range flush.


def check_fold_mode(mode: str) -> str:
    """THE fold_mode membership check — every config/entry point shares
    it so a third mode lands everywhere at once."""
    if mode not in ("full", "merge"):
        raise ValueError(f"fold_mode must be 'full' or 'merge', got {mode!r}")
    return mode


def _fold_counted_impl(state: StashState, acc: AccumState, sum_cols_t, max_cols_t):
    """`_fold_impl` + the fold_rows telemetry scalar: live rows the
    fold's keyed sort touched (whole stash + whole accumulator — the
    full-sort fold re-sorts everything). Rides the device counter
    block's CB_FOLD_ROWS lane, zero extra host syncs."""
    fold_rows = (
        jnp.sum(state.valid) + jnp.sum(acc.slot != jnp.uint32(SENTINEL_SLOT))
    ).astype(jnp.uint32)
    new_state, new_acc = _fold_impl(state, acc, sum_cols_t, max_cols_t)
    return new_state, new_acc, fold_rows


collector_fold_counted = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0, 1)
)(_fold_counted_impl)


def stash_fold_counted(
    state: StashState, acc: AccumState, meter_schema: MeterSchema
) -> tuple[StashState, AccumState, jnp.ndarray]:
    """Schema-keyed `collector_fold_counted` → (state, acc, fold_rows)."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return collector_fold_counted(state, acc, sum_cols, max_cols)


@jax.jit
def stash_canonicalize(state: StashState) -> StashState:
    """Re-establish the canonical layout (live rows = (slot, key)-
    ascending positional prefix; dead rows sentinel-keyed behind) with
    ONE 3-key sort, preserving every live row's content bit-for-bit.
    Restore-time only (ISSUE 20): pre-v6 checkpoints could hold
    cascade tier stashes with mid-prefix holes — their tier flushes
    never compacted — and the shared-sort ring fold rank-merges
    against the standing order, so a restored tier must be re-sorted
    once before it re-enters the fold path."""
    sl = jnp.where(state.valid, state.slot, jnp.uint32(SENTINEL_SLOT))
    hi = jnp.where(state.valid, state.key_hi, jnp.uint32(_U32_MAX))
    lo = jnp.where(state.valid, state.key_lo, jnp.uint32(_U32_MAX))
    iota = jnp.arange(state.capacity, dtype=jnp.int32)
    _, _, _, order = lax.sort((sl, hi, lo, iota), num_keys=3)
    return StashState(
        slot=jnp.take(sl, order),
        key_hi=jnp.take(state.key_hi, order),
        key_lo=jnp.take(state.key_lo, order),
        tags=jnp.take(state.tags, order, axis=1),
        meters=jnp.take(state.meters, order, axis=1),
        valid=jnp.take(state.valid, order),
        dropped_overflow=state.dropped_overflow,
    )


def _sorted_merge_reduce(state: StashState, na_sl, na_hi, na_lo,
                         a_sl, a_hi, a_lo, a_perm, acc_tags, acc_meters,
                         sum_cols_t, max_cols_t) -> StashState:
    """Rank-merge one SORTED normalized run against the canonical
    (sorted-prefix) stash and segment-reduce the merged order — the
    shared body of the incremental merge-fold AND the cascade's
    shared-sort ring fold (ISSUE 20). `na_*` are the run's normalized
    lanes in ORIGINAL (unsorted) position — invalid rows re-keyed to
    SENTINEL/U32_MAX; `a_sl/a_hi/a_lo/a_perm` the same lanes sorted
    with their permutation. Payload lanes (`acc_tags` [T, A],
    `acc_meters` [M, A]) stay column-major and unsorted — the merged
    order routes through `a_perm`. Requires the canonical stash layout
    (live rows = (slot, key)-ascending positional prefix)."""
    s = state.capacity

    # normalized stash keys — already sorted by the canonical invariant
    ns_sl = jnp.where(state.valid, state.slot, jnp.uint32(SENTINEL_SLOT))
    ns_hi = jnp.where(state.valid, state.key_hi, jnp.uint32(_U32_MAX))
    ns_lo = jnp.where(state.valid, state.key_lo, jnp.uint32(_U32_MAX))

    rank_s, rank_a = merge_ranks((ns_sl, ns_hi, ns_lo), (a_sl, a_hi, a_lo))
    # order maps merged position → concat([stash, acc]) row; the acc
    # payload routes through a_perm so downstream gathers hit original
    # ring rows (the reduce's tag/meter payloads are never pre-sorted)
    order = merge_order(
        rank_s, rank_a, jnp.arange(s, dtype=jnp.int32), s + a_perm
    )

    cat_sl = jnp.concatenate([ns_sl, na_sl])
    cat_hi = jnp.concatenate([ns_hi, na_hi])
    cat_lo = jnp.concatenate([ns_lo, na_lo])
    cat_tags = jnp.concatenate([state.tags, acc_tags], axis=1)
    # same transpose-at-fold stance as _merge_impl (module layout note)
    cat_meters = jnp.transpose(
        jnp.concatenate([state.meters, acc_meters], axis=1)
    )

    g = groupby_reduce_sorted(
        jnp.take(cat_sl, order),
        jnp.take(cat_hi, order),
        jnp.take(cat_lo, order),
        order,
        cat_tags,
        cat_meters,
        np.asarray(sum_cols_t, dtype=np.int32),
        np.asarray(max_cols_t, dtype=np.int32),
        out_capacity=s,
    )

    dropped = jnp.maximum(g.num_segments - s, 0)
    return StashState(
        slot=g.slot,
        key_hi=g.key_hi,
        key_lo=g.key_lo,
        tags=g.tags,
        meters=g.meters,
        valid=g.seg_valid,
        dropped_overflow=state.dropped_overflow + dropped,
    )


def _merge_fold_impl(state: StashState, acc: AccumState, hi_window, sum_cols_t, max_cols_t):
    """Rank-merge fold: sort [A], merge against the sorted [S] stash,
    reduce the merged run — no full keyed re-sort of the stash lanes.

    `hi_window` bounds the fold span: only acc rows with slot <
    hi_window fold (sentinel-keyed rows never do — SENTINEL ≥ any hi);
    the rest stay accumulated in the ring, untouched. Pass
    SENTINEL_SLOT for the full-set fold (every live row folds, the ring
    empties — same contract as `_fold_impl`). Requires the canonical
    stash layout (see the section comment above); returns
    (new_state, new_acc, fold_rows) where fold_rows counts the acc rows
    this fold's keyed sort actually touched.

    One-pass scoping note (ISSUE 17): this sort is NOT a candidate for
    the sketch plane's shared batch sort — it runs once per FOLD (every
    accum_batches batches, over the acc ring's accumulated rows), not
    per ingest dispatch, and its key space is the doc fingerprint over
    post-fanout rows, not the plane's raw-flow key. The per-dispatch
    sorts the shared-sort rewrite collapses are the sketch plane's
    (sketchplane.sketch_plane_step); the fold's amortized sort already
    IS the one sort of its own dispatch bucket (census-attributed in
    pipeline.telemetry()["profile"])."""
    a = acc.capacity
    hi_window = jnp.asarray(hi_window, dtype=jnp.uint32)

    fold_mask = acc.slot < hi_window
    # normalized acc keys: out-of-span / invalid rows sort last, exactly
    # like groupby_reduce's invalid-row re-keying in the full-sort fold
    na_sl = jnp.where(fold_mask, acc.slot, jnp.uint32(SENTINEL_SLOT))
    na_hi = jnp.where(fold_mask, acc.key_hi, jnp.uint32(_U32_MAX))
    na_lo = jnp.where(fold_mask, acc.key_lo, jnp.uint32(_U32_MAX))
    a_iota = jnp.arange(a, dtype=jnp.int32)
    a_sl, a_hi, a_lo, a_perm = lax.sort((na_sl, na_hi, na_lo, a_iota), num_keys=3)

    new_state = _sorted_merge_reduce(
        state, na_sl, na_hi, na_lo, a_sl, a_hi, a_lo, a_perm,
        acc.tags, acc.meters, sum_cols_t, max_cols_t,
    )
    # consumed rows turn sentinel in place; out-of-span rows stay. Their
    # ring slots are reclaimed when the next FULL fold resets the host
    # fill cursor (plan_append cadence), not here.
    new_acc = dataclasses.replace(
        acc, slot=jnp.where(fold_mask, jnp.uint32(SENTINEL_SLOT), acc.slot)
    )
    fold_rows = jnp.sum(fold_mask).astype(jnp.uint32)
    return new_state, new_acc, fold_rows


collector_merge_fold = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0, 1)
)(_merge_fold_impl)


def stash_merge_fold(
    state: StashState,
    acc: AccumState,
    meter_schema: MeterSchema,
    hi_window=None,
) -> tuple[StashState, AccumState, jnp.ndarray]:
    """Schema-keyed merge-fold → (state, acc, fold_rows). `hi_window`
    None = full-set fold (ring empties — callers reset their fill
    cursor); otherwise only acc rows with slot < hi_window fold (the
    span-bounded window advance — callers must NOT reset fill)."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    hi = SENTINEL_SLOT if hi_window is None else np.uint32(hi_window)
    return collector_merge_fold(state, acc, jnp.uint32(hi), sum_cols, max_cols)


def plan_append(fill: int, capacity: int | None, rows: int) -> str:
    """Host-side accumulator decision shared by the window managers:
    'init' — no ring yet or one too small for this batch (caller must
    fold pending rows BEFORE replacing the ring, or they are lost);
    'fold' — ring exists but this batch won't fit behind `fill`;
    'ok' — append at `fill`."""
    if capacity is None or rows > capacity:
        return "init"
    if fill + rows > capacity:
        return "fold"
    return "ok"


@jax.jit
def stash_flush(state: StashState, window_idx) -> tuple[StashState, dict]:
    """Close a window: emit rows of `window_idx`, reclaim their slots.

    Returns (new_state, out) where out holds full-capacity arrays plus a
    `mask` of emitted rows (static shapes; host compacts). The stash keeps
    its sort invariant trivially — holes are sentinel rows reclaimed by the
    next merge's compaction.

    This is the per-window oracle shape; the production drain is
    `stash_flush_range` (ONE device call + ONE packed fetch for every
    closed window at once — PERF.md §8's per-fetch latency made the
    per-window loop the windowed path's floor).
    """
    window_idx = jnp.asarray(window_idx, dtype=jnp.uint32)
    mask = state.valid & (state.slot == window_idx)
    out = {
        "mask": mask,
        "slot": state.slot,
        "key_hi": state.key_hi,
        "key_lo": state.key_lo,
        "tags": state.tags,
        "meters": state.meters,
        "count": jnp.sum(mask.astype(jnp.int32)),
    }
    new_state = dataclasses.replace(
        state,
        slot=jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot),
        valid=state.valid & ~mask,
    )
    return new_state, out


# Packed flush-row layout: [window, key_hi, key_lo, tags…, meters(bitcast)…]
FLUSH_META_COLS = 3


def pack_u32_columns(slot, key_hi, key_lo, tags, meters, valid=None):
    """Shared packed-u32 layout: [K+T+M, S] with rows slot, key_hi,
    key_lo, (valid,) tags…, bitcast(meters)…; K = FLUSH_META_COLS, +1
    with the optional valid lane (checkpoint format). Every builder of
    this layout (flush range, checkpoint stash/acc) goes through here
    so the row offsets the unpackers hard-code cannot drift."""
    meta = [slot[None, :], key_hi[None, :], key_lo[None, :]]
    if valid is not None:
        meta.append(valid.astype(jnp.uint32)[None, :])
    return jnp.concatenate(
        meta + [tags, jax.lax.bitcast_convert_type(meters, jnp.uint32)], axis=0
    )


def _pack_window_range(state: StashState, lo, hi):
    """Traced: pack every live row in [lo, hi) into a row-major
    [S, 3+T+M] u32 matrix ordered by (window, stash position) — THE
    packed-row builder shared by the mutating range flush and the
    read-only live snapshot (ISSUE 10), so the two emit bit-identical
    rows for the same stash by construction. Returns (mask, packed,
    total)."""
    lo = jnp.asarray(lo, dtype=jnp.uint32)
    hi = jnp.asarray(hi, dtype=jnp.uint32)
    mask = state.valid & (state.slot >= lo) & (state.slot < hi)
    # Stable (window, position) compaction: selected rows first,
    # ascending window, original stash order within a window. Other rows
    # rank as SENTINEL (> any real window — slots are < hi ≤ SENTINEL).
    rank = jnp.where(mask, state.slot, jnp.uint32(SENTINEL_SLOT))
    iota = jnp.arange(state.capacity, dtype=jnp.int32)
    _, order = jax.lax.sort((rank, iota), num_keys=1)
    cols = pack_u32_columns(
        state.slot, state.key_hi, state.key_lo, state.tags, state.meters
    )  # [3+T+M, S]
    packed = jnp.take(cols, order, axis=1).T  # row-major [S, 3+T+M]
    total = jnp.sum(mask.astype(jnp.int32))
    return mask, packed, total


def _flush_range_impl(state: StashState, lo_window, hi_window, *, compact: bool = False):
    """Close every window in [lo_window, hi_window): compact their rows
    to the front of ONE row-major [S, 3+T+M] u32 matrix (window-id,
    key, tags, bit-cast meters per row) and reclaim their slots.

    Rows are ordered by (window, stash position) — exactly the order the
    sequential ascending per-window `stash_flush` loop emits, so the two
    paths are bit-identical (pinned by tests/test_flush_range.py). The
    host fetches the row count, then only `packed[:total]` — two
    transfers per window advance, independent of how many windows closed.

    `compact` (static) re-establishes the CANONICAL layout the
    merge-fold requires (live rows = sorted positional prefix): on a
    canonical input every flushed row sits in the positional prefix
    [0, total) — the closing windows hold the smallest live slots — so
    one roll of every lane by `total` moves the surviving run to the
    front and the freshly-dead prefix behind the tail. Requires
    lo_window ≤ every live slot (the window managers' advance protocol
    guarantees it: older windows were flushed by earlier advances).
    The flushed OUTPUT is identical either way."""
    mask, packed, total = _pack_window_range(state, lo_window, hi_window)
    iota = jnp.arange(state.capacity, dtype=jnp.int32)
    new_slot = jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot)
    new_valid = state.valid & ~mask
    if compact:
        idx = (iota + total) % state.capacity
        new_state = StashState(
            slot=jnp.take(new_slot, idx),
            key_hi=jnp.take(state.key_hi, idx),
            key_lo=jnp.take(state.key_lo, idx),
            tags=jnp.take(state.tags, idx, axis=1),
            meters=jnp.take(state.meters, idx, axis=1),
            valid=jnp.take(new_valid, idx),
            dropped_overflow=state.dropped_overflow,
        )
    else:
        new_state = dataclasses.replace(state, slot=new_slot, valid=new_valid)
    return new_state, packed, total


stash_flush_range = jax.jit(
    _flush_range_impl, donate_argnums=(0,), static_argnames=("compact",)
)


def _snapshot_range_impl(state: StashState, lo_window, hi_window):
    """READ-ONLY twin of `_flush_range_impl` (ISSUE 10 live read plane):
    pack every live row in [lo, hi) — same order, same layout, same
    unpack — WITHOUT reclaiming slots, advancing anything, or
    compacting. The stash is untouched (no donation), so a snapshot can
    interleave anywhere between ingest dispatches and the later real
    flush of the same windows emits bit-identical rows plus whatever
    arrived after the snapshot. Returns (packed, total)."""
    _, packed, total = _pack_window_range(state, lo_window, hi_window)
    return packed, total


# NO donation: the live stash stays valid — the snapshot writes into a
# fresh output buffer (the "double buffer": the read never aliases the
# plane the next append dispatch consumes).
stash_snapshot_range = jax.jit(_snapshot_range_impl)


def unpack_flush_rows(rows: np.ndarray, num_tags: int):
    """Split fetched packed flush rows ([n, 3+T+M] u32, host) back into
    (window, key_hi, key_lo, tags [n, T], meters [n, M] f32)."""
    t0 = FLUSH_META_COLS
    meters = np.ascontiguousarray(rows[:, t0 + num_tags :]).view(np.float32)
    return (
        rows[:, 0],
        rows[:, 1],
        rows[:, 2],
        rows[:, t0 : t0 + num_tags],
        meters,
    )
