"""Device-resident document stash.

The TPU analogue of the reference's per-window `HashMap<StashKey, Document>`
(collector.rs:806-822) and `QuadrupleStash` (quadruple_generator.rs:233):
a fixed-capacity, HBM-resident table of (window slot, 64-bit key, tag row,
meter row), kept sorted by (slot, key) as an invariant *by construction* —
every merge re-sorts the concatenation of stash and batch, reduces
duplicate keys with the schema's SUM/MAX ops, and keeps the first
`capacity` segments. Sentinel-keyed rows (empty / flushed) sort to the end
and are reclaimed by the same compaction.

Overflow policy: segments beyond capacity are dropped and counted
(`dropped_overflow`). Because the sort is (slot, key)-ordered, drops land
on the *newest* window's keys — older windows (about to flush) are never
evicted. This mirrors the reference's backpressure stance of shedding
newest data under overload (OverwriteQueue, libs/queue/queue.go:139)
while protecting closing windows.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.schema import MeterSchema, TagSchema
from ..ops.segment import SENTINEL_SLOT, groupby_reduce


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StashState:
    slot: jnp.ndarray  # [S] u32 absolute window index (SENTINEL = empty)
    key_hi: jnp.ndarray  # [S] u32
    key_lo: jnp.ndarray  # [S] u32
    tags: jnp.ndarray  # [T, S] u32 (column-major — see ops/segment.py)
    meters: jnp.ndarray  # [M, S] f32
    valid: jnp.ndarray  # [S] bool
    dropped_overflow: jnp.ndarray  # scalar i32, running count of shed segments

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def stash_init(capacity: int, tag_schema: TagSchema, meter_schema: MeterSchema) -> StashState:
    return StashState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), dtype=jnp.uint32),
        key_lo=jnp.zeros((capacity,), dtype=jnp.uint32),
        tags=jnp.zeros((tag_schema.num_fields, capacity), dtype=jnp.uint32),
        meters=jnp.zeros((meter_schema.num_fields, capacity), dtype=jnp.float32),
        valid=jnp.zeros((capacity,), dtype=bool),
        dropped_overflow=jnp.zeros((), dtype=jnp.int32),
    )


def _merge_impl(state: StashState, slot, key_hi, key_lo, tags_t, meters_t, valid, sum_cols_t, max_cols_t):
    s = state.capacity
    sum_cols = np.asarray(sum_cols_t, dtype=np.int32)
    max_cols = np.asarray(max_cols_t, dtype=np.int32)

    all_slot = jnp.concatenate([state.slot, slot])
    all_hi = jnp.concatenate([state.key_hi, key_hi])
    all_lo = jnp.concatenate([state.key_lo, key_lo])
    all_tags = jnp.concatenate([state.tags, tags_t], axis=1)
    all_meters = jnp.concatenate([state.meters, meters_t], axis=1)
    all_valid = jnp.concatenate([state.valid, valid])

    # groupby_reduce consumes row-major meters; the stash keeps its
    # column-major layout (free column selection at flush), so the fold
    # transposes here — at fold scale this replaces the row-gather the
    # reduce no longer performs, and XLA folds it into that copy.
    g = groupby_reduce(
        all_slot, all_hi, all_lo, all_tags, jnp.transpose(all_meters), all_valid,
        sum_cols, max_cols, out_capacity=s,
    )

    dropped = jnp.maximum(g.num_segments - s, 0)
    new_state = StashState(
        slot=g.slot,
        key_hi=g.key_hi,
        key_lo=g.key_lo,
        tags=g.tags,
        meters=g.meters,
        valid=g.seg_valid,
        dropped_overflow=state.dropped_overflow + dropped,
    )
    return new_state


_merge = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0,)
)(_merge_impl)


def stash_merge(
    state: StashState,
    slot,
    key_hi,
    key_lo,
    tags,
    meters,
    valid,
    meter_schema: MeterSchema,
) -> StashState:
    """Merge a doc batch into the stash (one sort of [S+N] rows).

    tags/meters are column-major ([T, N] / [M, N])."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return _merge(state, slot, key_hi, key_lo, tags, meters, valid, sum_cols, max_cols)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AccumState:
    """Raw-row accumulator in front of the stash.

    The reference pays a hash-map probe per document per batch
    (Stash::add, collector.rs:810). A sort-based stash that re-sorts
    [S+N] rows per batch pays the whole O((S+N) log(S+N)) sort per batch
    instead — measured on v5e the sort is overhead-dominated (3.3 ms at
    32k rows but only 4.0 ms at 131k, PERF.md), so the TPU-native shape
    is: *append* each batch into this fixed ring (one
    dynamic_update_slice, bandwidth-bound) and amortize ONE sort+reduce
    over many batches (`collector_fold`), triggered on capacity or
    window close. Invalid rows are sentinel-keyed at append time, so
    the accumulator needs no separate validity lane.
    """

    slot: jnp.ndarray  # [A] u32 (SENTINEL = empty / invalid)
    key_hi: jnp.ndarray  # [A] u32
    key_lo: jnp.ndarray  # [A] u32
    tags: jnp.ndarray  # [T, A] u32
    meters: jnp.ndarray  # [M, A] f32

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def accum_init(capacity: int, tag_schema: TagSchema, meter_schema: MeterSchema) -> AccumState:
    return AccumState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), dtype=jnp.uint32),
        key_lo=jnp.zeros((capacity,), dtype=jnp.uint32),
        tags=jnp.zeros((tag_schema.num_fields, capacity), dtype=jnp.uint32),
        meters=jnp.zeros((meter_schema.num_fields, capacity), dtype=jnp.float32),
    )


def _append_impl(acc: AccumState, slot, key_hi, key_lo, tags_t, meters_t, valid, offset):
    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    upd = jax.lax.dynamic_update_slice
    return AccumState(
        slot=upd(acc.slot, slot, (offset,)),
        key_hi=upd(acc.key_hi, key_hi, (offset,)),
        key_lo=upd(acc.key_lo, key_lo, (offset,)),
        tags=upd(acc.tags, tags_t, (0, offset)),
        meters=upd(acc.meters, meters_t, (0, offset)),
    )


accum_append = jax.jit(_append_impl, donate_argnums=(0,))


def _fold_impl(state: StashState, acc: AccumState, sum_cols_t, max_cols_t):
    """One sort+reduce over [S + A] rows → fresh stash + empty accumulator."""
    new_state = _merge_impl(
        state,
        acc.slot,
        acc.key_hi,
        acc.key_lo,
        acc.tags,
        acc.meters,
        acc.slot != jnp.uint32(SENTINEL_SLOT),
        sum_cols_t,
        max_cols_t,
    )
    # Only the slot lane needs clearing — sentinel slots make key/tag/meter
    # bytes unreachable, and the next appends overwrite them in place.
    new_acc = dataclasses.replace(
        acc, slot=jnp.full((acc.capacity,), SENTINEL_SLOT, dtype=jnp.uint32)
    )
    return new_state, new_acc


collector_fold = partial(
    jax.jit, static_argnames=("sum_cols_t", "max_cols_t"), donate_argnums=(0, 1)
)(_fold_impl)


def stash_fold(
    state: StashState, acc: AccumState, meter_schema: MeterSchema
) -> tuple[StashState, AccumState]:
    """Schema-keyed wrapper over collector_fold."""
    sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
    return collector_fold(state, acc, sum_cols, max_cols)


def plan_append(fill: int, capacity: int | None, rows: int) -> str:
    """Host-side accumulator decision shared by the window managers:
    'init' — no ring yet or one too small for this batch (caller must
    fold pending rows BEFORE replacing the ring, or they are lost);
    'fold' — ring exists but this batch won't fit behind `fill`;
    'ok' — append at `fill`."""
    if capacity is None or rows > capacity:
        return "init"
    if fill + rows > capacity:
        return "fold"
    return "ok"


@jax.jit
def stash_flush(state: StashState, window_idx) -> tuple[StashState, dict]:
    """Close a window: emit rows of `window_idx`, reclaim their slots.

    Returns (new_state, out) where out holds full-capacity arrays plus a
    `mask` of emitted rows (static shapes; host compacts). The stash keeps
    its sort invariant trivially — holes are sentinel rows reclaimed by the
    next merge's compaction.

    This is the per-window oracle shape; the production drain is
    `stash_flush_range` (ONE device call + ONE packed fetch for every
    closed window at once — PERF.md §8's per-fetch latency made the
    per-window loop the windowed path's floor).
    """
    window_idx = jnp.asarray(window_idx, dtype=jnp.uint32)
    mask = state.valid & (state.slot == window_idx)
    out = {
        "mask": mask,
        "slot": state.slot,
        "key_hi": state.key_hi,
        "key_lo": state.key_lo,
        "tags": state.tags,
        "meters": state.meters,
        "count": jnp.sum(mask.astype(jnp.int32)),
    }
    new_state = dataclasses.replace(
        state,
        slot=jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot),
        valid=state.valid & ~mask,
    )
    return new_state, out


# Packed flush-row layout: [window, key_hi, key_lo, tags…, meters(bitcast)…]
FLUSH_META_COLS = 3


def pack_u32_columns(slot, key_hi, key_lo, tags, meters, valid=None):
    """Shared packed-u32 layout: [K+T+M, S] with rows slot, key_hi,
    key_lo, (valid,) tags…, bitcast(meters)…; K = FLUSH_META_COLS, +1
    with the optional valid lane (checkpoint format). Every builder of
    this layout (flush range, checkpoint stash/acc) goes through here
    so the row offsets the unpackers hard-code cannot drift."""
    meta = [slot[None, :], key_hi[None, :], key_lo[None, :]]
    if valid is not None:
        meta.append(valid.astype(jnp.uint32)[None, :])
    return jnp.concatenate(
        meta + [tags, jax.lax.bitcast_convert_type(meters, jnp.uint32)], axis=0
    )


def _flush_range_impl(state: StashState, lo_window, hi_window):
    """Close every window in [lo_window, hi_window): compact their rows
    to the front of ONE row-major [S, 3+T+M] u32 matrix (window-id,
    key, tags, bit-cast meters per row) and reclaim their slots.

    Rows are ordered by (window, stash position) — exactly the order the
    sequential ascending per-window `stash_flush` loop emits, so the two
    paths are bit-identical (pinned by tests/test_flush_range.py). The
    host fetches the row count, then only `packed[:total]` — two
    transfers per window advance, independent of how many windows closed.
    """
    lo = jnp.asarray(lo_window, dtype=jnp.uint32)
    hi = jnp.asarray(hi_window, dtype=jnp.uint32)
    mask = state.valid & (state.slot >= lo) & (state.slot < hi)
    # Stable (window, position) compaction: flushed rows first, ascending
    # window, original stash order within a window. Unflushed rows rank
    # as SENTINEL (> any real window — slots are < hi ≤ SENTINEL).
    rank = jnp.where(mask, state.slot, jnp.uint32(SENTINEL_SLOT))
    iota = jnp.arange(state.capacity, dtype=jnp.int32)
    _, order = jax.lax.sort((rank, iota), num_keys=1)
    cols = pack_u32_columns(
        state.slot, state.key_hi, state.key_lo, state.tags, state.meters
    )  # [3+T+M, S]
    packed = jnp.take(cols, order, axis=1).T  # row-major [S, 3+T+M]
    total = jnp.sum(mask.astype(jnp.int32))
    new_state = dataclasses.replace(
        state,
        slot=jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot),
        valid=state.valid & ~mask,
    )
    return new_state, packed, total


stash_flush_range = jax.jit(_flush_range_impl, donate_argnums=(0,))


def unpack_flush_rows(rows: np.ndarray, num_tags: int):
    """Split fetched packed flush rows ([n, 3+T+M] u32, host) back into
    (window, key_hi, key_lo, tags [n, T], meters [n, M] f32)."""
    t0 = FLUSH_META_COLS
    meters = np.ascontiguousarray(rows[:, t0 + num_tags :]).view(np.float32)
    return (
        rows[:, 0],
        rows[:, 1],
        rows[:, 2],
        rows[:, t0 : t0 + num_tags],
        meters,
    )
