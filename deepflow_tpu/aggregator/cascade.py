"""Device-side multi-resolution rollup cascade (ISSUE 9).

The reference server keeps 1s AND 1m series and downsamples 1m→1h→1d
(datasource/handle.go); the old `DualGranularityPipeline` reproduced
that by ingesting every batch TWICE — a full second device dispatch
into a parallel minute pipeline — doubling the hot-path work r6–r12
spent five PRs shrinking. This module replaces the second ingest with a
*fold of closed tier-0 windows*, the split-resolution-across-tiers
design of "Sketch Disaggregation Across Time and Space" (PAPERS.md):

  * **Exact meters**: every window advance already compacts the closing
    1s windows into ONE packed [S, 3+T+M] u32 flush matrix on device
    (stash.stash_flush_range). The cascade consumes that SAME device
    array before the host fetches it: one jitted sort + segment-reduce
    re-keys each row to its parent window (slot // ratio, key words
    unchanged — doc fingerprints carry no timestamp, fanout.py zeroes
    it) and merges it into a bounded per-tier StashState with exactly
    tier 0's overflow semantics (newest-window shed, counted). A 1m
    tier window therefore closes as the fold of its ≤60 closed 1s
    windows; the 1h tier folds closed 1m flush rows the same way.

  * **Sketches**: closed 1s `WindowSketchBlock`s merge host-side per
    parent window via the existing r12 algebra (HLL register max / CMS
    add / hist add / top-K candidate union — all pinned associative +
    commutative in tests/test_sketches.py), so merge-of-60 equals
    build-over-60 and the minute tier keeps the shed-degrades-detail-
    not-coverage contract.

  * **Host-sync budget**: tier folds and tier flushes are extra device
    DISPATCHES on the advance path only; their outputs ride the advance
    drain's existing two transfers (the scalar fetch widens by one lane
    per tier, the row fetch concatenates tier rows) — the ≤3-fetch
    steady-state budget is untouched (tests/test_perf_gate.py gates it
    with the cascade ON, single-chip and sharded).

Tier-close rule: parent window p of a ratio-r tier closes when every
child window < (p+1)·r has closed, i.e. when tier 0's advance target
`hi` satisfies p < hi // r. Late-row admission is therefore tier 0's:
a row too late for its second is too late for its minute (the old
double-ingest's separate `minute_delay` gate no longer exists — the
compat shim documents this).

Counter lanes: the cascade maintains a device [2] u32 lane vector
(cumulative rows folded into tiers, cumulative tier-stash overflow
sheds) that rides the fused append step's counter block (CB v5,
CB_CASCADE_ROWS / CB_CASCADE_SHED) — zero extra fetches.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from ..datamodel.schema import MeterSchema, TagSchema
from ..ops.segment import SENTINEL_SLOT, _use_shared_sort
from .sketchplane import WindowSketchBlock
from .stash import (
    AccumState,
    StashState,
    _append_impl,
    _merge_impl,
    _sorted_merge_reduce,
    accum_init,
    stash_flush_range,
    stash_init,
    unpack_flush_rows,
)

_U32_MAX = np.uint32(0xFFFFFFFF)


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Tier layout: `intervals` are the coarser resolutions to maintain
    above the owning manager's base interval, ascending, each an exact
    multiple of the previous (e.g. (60,) for a 1m tier over a 1s
    pipeline, (60, 3600) to add an hourly tier). `capacity` bounds each
    tier's stash rows — overflow sheds newest-window keys, counted
    (the exact stance tier 0 has)."""

    intervals: tuple[int, ...] = (60,)
    capacity: int = 1 << 14

    def __post_init__(self):
        if not self.intervals:
            raise ValueError("CascadeConfig.intervals must name ≥1 tier")
        if list(self.intervals) != sorted(set(self.intervals)):
            raise ValueError(
                f"cascade intervals must be ascending unique, got {self.intervals}"
            )
        if self.capacity <= 0:
            raise ValueError("cascade capacity must be positive")

    def validate_base(self, base_interval: int) -> None:
        prev = base_interval
        for iv in self.intervals:
            if iv % prev != 0 or iv <= prev:
                raise ValueError(
                    f"cascade tier {iv}s is not a proper multiple of the "
                    f"previous resolution {prev}s — parent windows would "
                    "not align with child window boundaries"
                )
            prev = iv

    def meta(self) -> dict:
        """JSON-able form for checkpoint meta (v5)."""
        return {"intervals": list(self.intervals), "capacity": self.capacity}

    @classmethod
    def from_meta(cls, m: dict) -> "CascadeConfig":
        return cls(intervals=tuple(m["intervals"]), capacity=m["capacity"])


def _parent_columns(packed, hi, *, ratio: int, num_tags: int):
    """Traced: split a packed flush matrix into parent-keyed fold
    columns — (parent, key_hi, key_lo, tags [T, P], meters [M, P],
    valid). Rows with window < `hi` are exactly the rows that flushed
    (dead rows carry SENTINEL, still-open rows windows ≥ hi — the
    advance protocol guarantees lo ≤ every live slot); each re-keys to
    its parent window (window // ratio, key words unchanged — doc
    fingerprints carry no timestamp)."""
    cols = jnp.transpose(packed)  # [3+T+M, P]
    slot = cols[0]
    valid = slot < hi
    parent = jnp.where(valid, slot // jnp.uint32(ratio), _U32_MAX)
    tags = cols[3 : 3 + num_tags]
    meters = lax.bitcast_convert_type(cols[3 + num_tags :], jnp.float32)
    return parent, cols[1], cols[2], tags, meters, valid


def _acc_valid(acc) -> jnp.ndarray:
    return acc.slot != jnp.uint32(SENTINEL_SLOT)


def _ring_fold_impl(tier: StashState, acc, lanes, sum_cols_t, max_cols_t,
                    shared_sort: bool = False):
    """Merge the tier accumulator ring into the tier stash and reset
    it. Overflow sheds count into lanes[1] (CB_CASCADE_SHED).

    With `shared_sort` (static; the DEEPFLOW_SHARED_SORT knob, ISSUE
    20) the fold reuses the dispatch-owned order: the tier stash is
    ALREADY (slot, key)-sorted — every producer keeps the canonical
    layout (groupby reduces, compact=True tier flushes) — so only the
    ring's [A] rows sort and rank-merge against the standing run
    (stash._sorted_merge_reduce, the merge-fold body) instead of a
    second full [S+A] 3-key sort. Bit-exact vs the full-sort path
    (same reduce, same overflow stance); A/B'd in bench/foldbench.py."""
    prev_dropped = tier.dropped_overflow
    if shared_sort:
        valid = _acc_valid(acc)
        na_sl = jnp.where(valid, acc.slot, jnp.uint32(SENTINEL_SLOT))
        na_hi = jnp.where(valid, acc.key_hi, _U32_MAX)
        na_lo = jnp.where(valid, acc.key_lo, _U32_MAX)
        a_iota = jnp.arange(acc.capacity, dtype=jnp.int32)
        a_sl, a_hi, a_lo, a_perm = lax.sort(
            (na_sl, na_hi, na_lo, a_iota), num_keys=3
        )
        new_tier = _sorted_merge_reduce(
            tier, na_sl, na_hi, na_lo, a_sl, a_hi, a_lo, a_perm,
            acc.tags, acc.meters, sum_cols_t, max_cols_t,
        )
    else:
        new_tier = _merge_impl(
            tier, acc.slot, acc.key_hi, acc.key_lo, acc.tags, acc.meters,
            _acc_valid(acc), sum_cols_t, max_cols_t,
        )
    new_acc = dataclasses.replace(
        acc, slot=jnp.full((acc.capacity,), SENTINEL_SLOT, dtype=jnp.uint32)
    )
    shed = (new_tier.dropped_overflow - prev_dropped).astype(jnp.uint32)
    return new_tier, new_acc, lanes + jnp.stack([jnp.uint32(0), shed])


tier_ring_fold = partial(
    jax.jit,
    static_argnames=("sum_cols_t", "max_cols_t", "shared_sort"),
    donate_argnums=(0, 1, 2),
)(_ring_fold_impl)


def _tier_step_impl(tier: StashState, acc, fill, lanes, packed, total, hi,
                    *, ratio: int, num_tags: int, sum_cols_t, max_cols_t,
                    prefix: int, shared_sort: bool = False):
    """One advance's closed rows into the tier — tier 0's own
    append/amortize architecture one level up.

    A naive per-advance merge re-sorts (and re-gathers the full payload
    of) the whole tier stash for every advance, even though a steady
    1-window advance flushes a few thousand rows. Instead the step
    APPENDS: the flushed rows sit in the positional prefix [0, total)
    of `packed` (flush compaction), so when total ≤ `prefix` the step
    copies packed[:prefix] — parent-re-keyed, out-of-range rows
    sentinel-masked — into the tier accumulator ring at the
    device-resident `fill` cursor (one dynamic_update_slice, the same
    bandwidth-bound shape as the ingest append) and the expensive merge
    runs once per ~A/prefix advances. `lax.cond` picks between:

      * append       — total ≤ prefix and the ring has room;
      * fold+append  — total ≤ prefix, ring full: merge the ring into
                       the stash first, then append at 0;
      * direct fold  — total > prefix (multi-window jump / shutdown
                       drain): merge ring + the FULL packed matrix in
                       one sort, ring resets.

    All control state (`fill`) is device-resident — the host never
    needs to know which branch ran. Bit-exact by construction: every
    closed row either lands in the ring (and merges at the next fold)
    or merges directly; `tier_ring_fold` runs before every tier flush
    so flushed parents always see every child. Lane 0 counts rows at
    arrival, lane 1 tier-stash sheds at folds."""
    hi = jnp.asarray(hi, jnp.uint32)
    total = jnp.asarray(total, jnp.int32)
    A = acc.capacity
    prev_dropped = tier.dropped_overflow

    pp, ph, pl, pt, pm, pv = _parent_columns(
        packed[:prefix], hi, ratio=ratio, num_tags=num_tags
    )
    n_small = jnp.sum(pv).astype(jnp.uint32)

    def append(tier, acc, fill, lanes):
        acc = _append_impl(acc, pp, ph, pl, pt, pm, pv, fill)
        return tier, acc, fill + prefix, lanes + jnp.stack(
            [n_small, jnp.uint32(0)]
        )

    def fold_then_append(tier, acc, fill, lanes):
        tier, acc, lanes = _ring_fold_impl(
            tier, acc, lanes, sum_cols_t, max_cols_t,
            shared_sort=shared_sort,
        )
        return append(tier, acc, jnp.int32(0), lanes)

    def direct_fold(tier, acc, fill, lanes):
        fp, fh, fl, ft, fm, fv = _parent_columns(
            packed, hi, ratio=ratio, num_tags=num_tags
        )
        new_tier = _merge_impl(
            tier,
            jnp.concatenate([acc.slot, fp]),
            jnp.concatenate([acc.key_hi, fh]),
            jnp.concatenate([acc.key_lo, fl]),
            jnp.concatenate([acc.tags, ft], axis=1),
            jnp.concatenate([acc.meters, fm], axis=1),
            jnp.concatenate([_acc_valid(acc), fv]),
            sum_cols_t, max_cols_t,
        )
        new_acc = dataclasses.replace(
            acc,
            slot=jnp.full((A,), SENTINEL_SLOT, dtype=jnp.uint32),
        )
        shed = (new_tier.dropped_overflow - prev_dropped).astype(jnp.uint32)
        folded = jnp.sum(fv).astype(jnp.uint32)
        return new_tier, new_acc, jnp.int32(0), lanes + jnp.stack(
            [folded, shed]
        )

    if prefix >= packed.shape[0]:
        # degenerate geometry (tiny child stash): always direct-fold
        return direct_fold(tier, acc, fill, lanes)
    return lax.cond(
        total > prefix,
        direct_fold,
        lambda t, a, f, l: lax.cond(
            f + prefix > A, fold_then_append, append, t, a, f, l
        ),
        tier, acc, fill, lanes,
    )


tier_step = partial(
    jax.jit,
    static_argnames=("ratio", "num_tags", "sum_cols_t", "max_cols_t",
                     "prefix", "shared_sort"),
    donate_argnums=(0, 1, 3),
)(_tier_step_impl)


def tier_prefix(child_capacity: int) -> int:
    """Per-advance append width: HALF the child stash. The prefix must
    cover a typical advance's flushed rows or the step degenerates to
    the direct-fold branch every time (a multi-window advance can
    flush a large fraction of live keys — 1/8 proved too tight under
    the §14 workload); half covers everything short of a full-stash
    drain while still halving the worst-case sort."""
    return max(child_capacity // 2, 256)


def tier_ring_rows(child_capacity: int) -> int:
    """Tier accumulator ring capacity: 4 appends between merges — the
    amortization factor on the merge's full-stash payload rewrite."""
    return 4 * tier_prefix(child_capacity)


def merge_into_parent(pending: dict, window: int, ratio: int,
                      block: WindowSketchBlock) -> None:
    """THE parent-block merge, shared by TierCascade and the sharded
    manager: re-window the child block onto its parent index
    (merge() asserts same-window, so the first child anchors a copy)
    and fold it into the pending merge via the r12 algebra."""
    parent = window // ratio
    reblk = dataclasses.replace(block, window=parent)
    have = pending.get(parent)
    pending[parent] = reblk if have is None else have.merge(reblk)


@dataclasses.dataclass
class TierFlush:
    """One tier's closed-window flush handles, produced at an advance
    and drained (fetched) with the same transfers as the tier-0 rows."""

    tier: int  # 0-based index into CascadeConfig.intervals
    interval: int  # seconds per tier window
    packed: jnp.ndarray  # [S, 3+T+M] u32 device handle
    total: jnp.ndarray  # scalar i32 device handle
    lo: int  # closed parent-window range [lo, hi)
    hi: int


class TierCascade:
    """Per-manager cascade state: one bounded StashState per tier, the
    host watermarks (parent windows flushed so far), the device counter
    lanes and the host-side per-parent sketch merge. Single-chip; the
    sharded twin lives in parallel/sharded.py (per-device tier fold,
    host-merge at drain)."""

    def __init__(self, config: CascadeConfig, base_interval: int,
                 tag_schema: TagSchema, meter_schema: MeterSchema):
        config.validate_base(base_interval)
        self.config = config
        self.base_interval = base_interval
        self.tag_schema = tag_schema
        self.meter_schema = meter_schema
        self.num_tags = tag_schema.num_fields
        self.sum_cols = tuple(int(i) for i in np.nonzero(meter_schema.sum_mask)[0])
        self.max_cols = tuple(int(i) for i in np.nonzero(meter_schema.max_mask)[0])
        # child→tier window ratio per tier (tier 0 folds base windows)
        res = (base_interval,) + tuple(config.intervals)
        self.ratios = tuple(res[i + 1] // res[i] for i in range(len(config.intervals)))
        self.tiers: list[StashState] = [
            stash_init(config.capacity, tag_schema, meter_schema)
            for _ in config.intervals
        ]
        # per-tier accumulator ring + device fill cursor (tier 0's
        # append/amortize architecture one level up — see tier_step):
        # ring capacity = the child stash size, so ~8 steady advances
        # append before one merge. Sized lazily per tier because tier
        # i>0's child is the PREVIOUS tier's stash, not tier 0's.
        self.accs: list[AccumState | None] = [None] * len(config.intervals)
        self.fills: list[jnp.ndarray] = [
            jnp.zeros((), jnp.int32) for _ in config.intervals
        ]
        # first parent window NOT yet flushed, per tier (host ints)
        self.watermarks: list[int] = [0] * len(config.intervals)
        # device [rows, shed] lane vector — rides the counter block
        self.lanes_dev = jnp.zeros((2,), jnp.uint32)
        # host-side sketch tier: parent window → merged child block,
        # per tier (tier i's closed blocks feed tier i+1's pending)
        self.pending_blocks: list[dict[int, WindowSketchBlock]] = [
            {} for _ in config.intervals
        ]
        self.tier_windows_flushed = 0  # host counter (all tiers)

    # -- device side (advance path) --------------------------------------
    def on_advance(self, packed, total, hi: int) -> list[TierFlush]:
        """Fold the advance's packed flush matrix through the tiers and
        flush every tier window that closed. `packed`/`total` are the
        tier-0 flush matrix + its device row count; `hi` tier 0's new
        span start (windows < hi closed). Pure device dispatches —
        nothing here fetches; the returned TierFlush handles ride the
        drain's bundled transfers.

        TWIN CONTRACT: ShardedWindowManager._drain_range mirrors this
        loop over per-device state — a semantic change here (ring
        sizing, the close rule, the pre-flush ring fold, chaining)
        must land there too."""
        out: list[TierFlush] = []
        src, src_total, src_hi = packed, total, int(hi)
        # per-dispatch knob capture, the single-chip convention (the
        # sharded twin captures at build time)
        shared_sort = _use_shared_sort()
        for i, ratio in enumerate(self.ratios):
            child_rows = src.shape[0]
            ring_rows = tier_ring_rows(child_rows)
            if self.accs[i] is None or self.accs[i].capacity < ring_rows:
                if self.accs[i] is not None:
                    # a grown child stash would overflow the old ring —
                    # fold pending rows in before replacing it
                    self.tiers[i], _old, self.lanes_dev = tier_ring_fold(
                        self.tiers[i], self.accs[i], self.lanes_dev,
                        sum_cols_t=self.sum_cols, max_cols_t=self.max_cols,
                        shared_sort=shared_sort,
                    )
                self.accs[i] = accum_init(
                    ring_rows, self.tag_schema, self.meter_schema
                )
                self.fills[i] = jnp.zeros((), jnp.int32)
            self.tiers[i], self.accs[i], self.fills[i], self.lanes_dev = (
                tier_step(
                    self.tiers[i], self.accs[i], self.fills[i],
                    self.lanes_dev, src, src_total, np.uint32(src_hi),
                    ratio=ratio, num_tags=self.num_tags,
                    sum_cols_t=self.sum_cols, max_cols_t=self.max_cols,
                    prefix=tier_prefix(child_rows),
                    shared_sort=shared_sort,
                )
            )
            hi_t = src_hi // ratio
            if hi_t <= self.watermarks[i]:
                break  # nothing closed at this tier → nothing deeper either
            # the flushed parents must see every appended child row —
            # the amortized merge runs now (once per tier close)
            self.tiers[i], self.accs[i], self.lanes_dev = tier_ring_fold(
                self.tiers[i], self.accs[i], self.lanes_dev,
                sum_cols_t=self.sum_cols, max_cols_t=self.max_cols,
                shared_sort=shared_sort,
            )
            self.fills[i] = jnp.zeros((), jnp.int32)
            lo_t = self.watermarks[i]
            # compact=True UNCONDITIONALLY (ISSUE 20): the tier stash
            # must keep the canonical sorted-prefix layout the
            # shared-sort ring fold rank-merges against. Safe — the
            # watermark protocol guarantees lo_t ≤ every live parent
            # slot, and the flushed output is identical either way.
            self.tiers[i], t_packed, t_total = stash_flush_range(
                self.tiers[i], np.uint32(lo_t), np.uint32(hi_t),
                compact=True,
            )
            out.append(TierFlush(
                tier=i, interval=self.config.intervals[i],
                packed=t_packed, total=t_total, lo=lo_t, hi=hi_t,
            ))
            self.watermarks[i] = hi_t
            src, src_total, src_hi = t_packed, t_total, hi_t
        return out

    # -- host side (drain path) ------------------------------------------
    def feed_block(self, tier: int, window: int, block: WindowSketchBlock) -> None:
        """Merge one closed child block into its parent's pending merge
        (tier 0 children feed tier index 0; a closed tier-i window's
        merged block feeds tier i+1). The merge is the r12 algebra —
        register max / counter add / candidate union — so fold order
        never matters."""
        if tier >= len(self.ratios):
            return
        merge_into_parent(
            self.pending_blocks[tier], window, self.ratios[tier], block
        )

    def take_tier_windows(self, tf: TierFlush, rows: np.ndarray, total: int):
        """Fetched tier flush rows → FlushedWindow list (window order),
        marrying each parent's merged sketch block; parents in [lo, hi)
        whose exact rows were all shed but whose children had sketch
        blocks become sketch-only windows (count == 0 — the same
        coverage contract as tier 0). Closed blocks cascade one level
        up before leaving."""
        from .window import FlushedWindow  # cycle: window.py imports us

        i = tf.tier
        flushed: list[FlushedWindow] = []
        if total:
            win, key_hi, key_lo, tags, meters = unpack_flush_rows(
                rows, self.num_tags
            )
            bounds = np.flatnonzero(
                np.r_[True, win[1:] != win[:-1]]
            ).tolist() + [total]
            for a, b in zip(bounds, bounds[1:]):
                w = int(win[a])
                flushed.append(FlushedWindow(
                    window_idx=w, start_time=w * tf.interval,
                    key_hi=key_hi[a:b], key_lo=key_lo[a:b],
                    tags=tags[a:b], meters=meters[a:b], count=b - a,
                    tier=i + 1, interval=tf.interval,
                ))
        for f in flushed:
            f.sketches = self.pending_blocks[i].pop(f.window_idx, None)
        exact = {f.window_idx for f in flushed}
        for w in sorted(self.pending_blocks[i]):
            if tf.lo <= w < tf.hi and w not in exact:
                blk = self.pending_blocks[i].pop(w)
                flushed.append(FlushedWindow(
                    window_idx=w, start_time=w * tf.interval,
                    key_hi=np.zeros((0,), np.uint32),
                    key_lo=np.zeros((0,), np.uint32),
                    tags=np.zeros((0, self.num_tags), np.uint32),
                    meters=np.zeros(
                        (0, self.meter_schema.num_fields), np.float32
                    ),
                    count=0, sketches=blk, tier=i + 1, interval=tf.interval,
                ))
        flushed.sort(key=lambda f: f.window_idx)
        for f in flushed:
            if f.sketches is not None:
                self.feed_block(i + 1, f.window_idx, f.sketches)
        self.tier_windows_flushed += len(flushed)
        return flushed

    # -- shutdown / checkpoint -------------------------------------------
    def settle_rings(self) -> None:
        """Fold every tier accumulator ring into its stash — the
        checkpoint rule the main ingest ring follows too: ring rows
        must reach the stash before a snapshot, so the rings need no
        serialization (restore re-initializes them empty). Merge
        output order is deterministic given contents (the fold sorts
        by (slot, key)), so fold batching never shows in flush rows."""
        for i in range(len(self.tiers)):
            if self.accs[i] is not None:
                self.tiers[i], self.accs[i], self.lanes_dev = tier_ring_fold(
                    self.tiers[i], self.accs[i], self.lanes_dev,
                    sum_cols_t=self.sum_cols, max_cols_t=self.max_cols,
                    shared_sort=_use_shared_sort(),
                )
                self.fills[i] = jnp.zeros((), jnp.int32)

    def flush_hi(self) -> int:
        """The tier-0 `hi` that closes every tier window (flush_all)."""
        return int(_U32_MAX)

    def get_counters(self) -> dict:
        """Host ints only (the fetch-free Countable stance) — the device
        lane mirrors live on the owning manager (CB v5)."""
        return {
            "cascade_tiers": len(self.config.intervals),
            "cascade_tier_windows": self.tier_windows_flushed,
            "cascade_pending_blocks": sum(
                len(p) for p in self.pending_blocks
            ),
        }


# ---------------------------------------------------------------------------
# checkpoint support (format v5) — block (de)serialization for the
# host-side pending sketch merges; tier stashes pack through the same
# pack_u32_columns layout as tier 0 (checkpoint.py drives it).

_BLOCK_FIELDS = ("hll", "cms", "hist", "tk_hi", "tk_lo", "tk_ida",
                 "tk_idb", "tk_votes")


def pending_block_arrays(pending: list[dict]) -> tuple[list, dict]:
    """(meta list, arrays dict) for every pending parent block — open
    minute/hour windows' partially-merged sketches must survive a
    checkpoint or a mid-minute kill silently drops the already-folded
    children's approximate state (the recovery pin's exact scenario).
    `pending` is the per-tier parent→block dict list (TierCascade's or
    the sharded manager's — both share this layout)."""
    meta, arrays = [], {}
    for tier, pend in enumerate(pending):
        for w, blk in sorted(pend.items()):
            key = f"cascblk_{tier}_{w}"
            meta.append({"tier": tier, "window": w, "key": key,
                         "n_updates": blk.n_updates})
            for f in _BLOCK_FIELDS:
                arrays[f"{key}_{f}"] = np.asarray(getattr(blk, f))
    return meta, arrays


def restore_pending_blocks(pending: list[dict], meta: list, arrays: dict,
                           sketch_config) -> None:
    for m in meta:
        key = m["key"]
        blk = WindowSketchBlock(
            window=int(m["window"]), config=sketch_config,
            n_updates=int(m["n_updates"]),
            **{f: arrays[f"{key}_{f}"] for f in _BLOCK_FIELDS},
        )
        pending[int(m["tier"])][int(m["window"])] = blk


__all__ = [
    "CascadeConfig",
    "TierCascade",
    "TierFlush",
    "tier_step",
    "tier_ring_fold",
    "tier_prefix",
    "tier_ring_rows",
    "merge_into_parent",
    "pending_block_arrays",
    "restore_pending_blocks",
]
