"""Window lineage tracing + data-freshness plane (ISSUE 13).

The repo already re-implements the reference's signature feature —
zero-instrumentation distributed tracing — for *ingested* telemetry
(TraceTreeBuilder over l7_flow_log). This module turns that engine on
the pipeline itself: every window's journey from receiver frame
admission to the row becoming queryable is recorded as a set of HOPS,
each hop exported as a span row on the same OTLP `l7_flow_log` lane,
so `tracing.tree.assemble_trace` / `TraceTreeBuilder` assemble a
per-window trace tree that answers "where did window W spend its
2.3 s?" with the repo's own trace machinery.

Design constraints, in order:

  * **zero new device fetches** — every hop is a HOST wall stamp taken
    at a seam the host already owns (frame admission, pump, journal
    append, staged upload, dispatch call, counter-block replay, flush
    drain, store insert, store scan). Device-side hops are *derived,
    not fetched*: the counter blocks / K-ring stats / flush watermarks
    that already ride the existing ≤3-fetch drain tell WHICH dispatch
    closed a window; a small FIFO of dispatch wall stamps (pushed per
    dispatch, popped per replayed block) tells WHEN it was dispatched.
    The CI gate (`test_perf_gate::test_lineage_tracing_budget`) pins
    ingest-attributable fetch parity with the plane attached.
  * **no context on the wire** — the propagated trace context IS the
    window id: `window_trace_id(service, window, interval)` is a pure
    function, so the receiver, feeder, manager, store sink and querier
    all join the same trace without a header field. Hops that happen
    before windows are known (admission, pump, journal, upload) park in
    a per-pump *pending context* and bind to the batch's window span
    the moment the host computes it (numpy min/max over timestamps it
    already holds — pre-upload, never a transfer).
  * **bounded** — at most `max_windows` live lineage records
    (oldest-evicted-counted), a bounded admission-stamp ring, a bounded
    dispatch-stamp FIFO.

On top of the trees, `FreshnessTracker` computes per-tier
event-time-to-queryable lag lanes — the SLO a live query plane is
actually judged on:

  * `ingest`     — last fused dispatch covering the window vs the
                   window's event-time end
  * `flush`      — flush-drain completion vs event-time end
  * `cascade`    — tier close vs the TIER window's event-time end
  * `visibility` — store insert (row queryable via SQL/PromQL) vs
                   event-time end
  * `partial`    — a live-snapshot read serving the still-OPEN window,
                   anchored on the window START (age of the open
                   window when the live read served it) and kept as a
                   DISTINCT lane so dashboards can tell a partial
                   answer from post-flush visibility

Each tier registers its own Countable (`tpu_freshness`, tier label),
so the lanes dogfood into `deepflow_system` and answer via SQL AND
PromQL; one rule over `tpu_freshness_visibility_lag_ms` gets a
per-series for-ladder per tier through the r15/r16 alert engine. Every
lag sample carries an EXEMPLAR: the trace id of the window that
produced it, linking the metric that fired a page to the trace tree
that explains it.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict, deque

import numpy as np

from ..utils.spans import SpanHistSpec, loghist_quantiles_np
from ..utils.stats import register_countable
from .tree import SpanRow, assemble_trace, search_index

#: the hop vocabulary — one trace-tree node per hop (app_service =
#: hop name; TraceTreeBuilder collapses per service)
HOP_RECEIVER_ADMIT = "receiver.admit"
HOP_FEEDER_PUMP = "feeder.pump"
HOP_JOURNAL_APPEND = "journal.append"
HOP_UPLOAD_STAGE = "upload.stage"
HOP_INGEST_DISPATCH = "ingest.dispatch"
HOP_WINDOW_ADVANCE = "window.advance"
HOP_FLUSH_DRAIN = "flush.drain"
HOP_CASCADE_CLOSE = "cascade.close"
HOP_STORE_INSERT = "store.insert"
HOP_QUERY_SNAPSHOT = "query.snapshot"  # partial (live) read — distinct
HOP_QUERY_FIRST = "query.first"

#: static parent topology. At export time a hop's parent is the NEAREST
#: ancestor along this chain that exists in the same window's record, so
#: a feederless pipeline (no pump/journal hops) still assembles with no
#: orphans — children just re-root on what actually ran.
HOP_PARENT = {
    HOP_RECEIVER_ADMIT: None,
    HOP_FEEDER_PUMP: HOP_RECEIVER_ADMIT,
    HOP_JOURNAL_APPEND: HOP_FEEDER_PUMP,
    HOP_UPLOAD_STAGE: HOP_FEEDER_PUMP,
    HOP_INGEST_DISPATCH: HOP_UPLOAD_STAGE,
    HOP_WINDOW_ADVANCE: HOP_INGEST_DISPATCH,
    HOP_FLUSH_DRAIN: HOP_WINDOW_ADVANCE,
    HOP_CASCADE_CLOSE: HOP_FLUSH_DRAIN,
    HOP_STORE_INSERT: HOP_FLUSH_DRAIN,
    HOP_QUERY_SNAPSHOT: HOP_INGEST_DISPATCH,
    HOP_QUERY_FIRST: HOP_STORE_INSERT,
}

#: freshness lag kinds (FreshnessTracker lanes)
LAG_INGEST = "ingest"
LAG_FLUSH = "flush"
LAG_CASCADE = "cascade"
LAG_VISIBILITY = "visibility"
LAG_PARTIAL = "partial"

DEFAULT_SERVICE = "tpu.pipeline"

#: a window's spans EXPORT only once one of these hops exists — the
#: window left the device (or became externally visible), so the
#: pre-close hops have stopped merging and each span id is emitted
#: exactly once (the store lane is append-only; a re-emitted id would
#: double-count in assembled trees)
TERMINAL_HOPS = (HOP_FLUSH_DRAIN, HOP_CASCADE_CLOSE, HOP_STORE_INSERT,
                 HOP_QUERY_FIRST)

_U64 = 0xFFFFFFFFFFFFFFFF


def window_trace_id(service: str, window_idx: int, interval: int = 1) -> str:
    """The deterministic 128-bit trace id of one (service, tier,
    window): high 64 bits fingerprint the (service, interval) pair, low
    64 bits are the window index. Pure function — ANY component that
    knows the window id can join (or query) the trace without a
    propagated header, and `dfctl trace window <id>` needs no lookup."""
    hi = search_index(f"{service}/{int(interval)}s")
    return f"{hi:016x}{int(window_idx) & _U64:016x}"


def hop_span_id(trace_id: str, hop: str) -> str:
    """Deterministic span id of one hop inside one window's trace —
    parents can be referenced before (or without) seeing them emitted."""
    return f"{search_index(f'{trace_id}/{hop}'):016x}"


class _HopAgg:
    """One hop's aggregate inside one window's lineage: multiple events
    (e.g. every batch that fed the window dispatches once) collapse into
    first-start / last-end / count — one span per (window, hop)."""

    __slots__ = ("start_s", "end_s", "count", "rows", "exported")

    def __init__(self, start_s: float, end_s: float, rows: int = 0):
        self.start_s = start_s
        self.end_s = end_s
        self.count = 1
        self.rows = rows
        self.exported = False

    def merge(self, start_s: float, end_s: float, rows: int = 0) -> None:
        self.start_s = min(self.start_s, start_s)
        self.end_s = max(self.end_s, end_s)
        self.count += 1
        self.rows += rows
        # `exported` is STICKY: the store lane is append-only and the
        # tree assemblers have no span-id dedup, so re-emitting the
        # same span id would double-count the hop in RED aggregates.
        # drain_spans defers a window's export until it has a terminal
        # hop, so pre-close merges are folded in before the one export.


class WindowLineage:
    """Every recorded hop of one (tier interval, window)."""

    __slots__ = ("window_idx", "interval", "hops", "lags")

    def __init__(self, window_idx: int, interval: int):
        self.window_idx = int(window_idx)
        self.interval = int(interval)
        self.hops: dict[str, _HopAgg] = {}
        self.lags: dict[str, float] = {}  # kind → lag seconds (latest)

    @property
    def event_end_s(self) -> int:
        """Event-time end of the window — the freshness anchor."""
        return (self.window_idx + 1) * self.interval

    def note(self, hop: str, start_s: float, end_s: float, rows: int = 0):
        agg = self.hops.get(hop)
        if agg is None:
            self.hops[hop] = _HopAgg(start_s, end_s, rows)
        else:
            agg.merge(start_s, end_s, rows)

    def parent_hop(self, hop: str) -> str | None:
        """Nearest ancestor hop PRESENT in this record (fallback chain)."""
        p = HOP_PARENT.get(hop)
        while p is not None and p not in self.hops:
            p = HOP_PARENT.get(p)
        return p

    def span_rows(self, trace_id: str, *, only_unexported: bool = False,
                  mark: bool = False) -> list[SpanRow]:
        rows = []
        name = f"w{self.window_idx}@{self.interval}s"
        for hop, agg in self.hops.items():
            if only_unexported and agg.exported:
                continue
            parent = self.parent_hop(hop)
            rows.append(
                SpanRow(
                    trace_id=trace_id,
                    span_id=hop_span_id(trace_id, hop),
                    parent_span_id=(
                        hop_span_id(trace_id, parent) if parent else ""
                    ),
                    app_service=hop,
                    endpoint=name,
                    start_us=int(agg.start_s * 1e6),
                    end_us=int(agg.end_s * 1e6),
                    response_duration_us=max(
                        0, int((agg.end_s - agg.start_s) * 1e6)
                    ),
                )
            )
            if mark:
                agg.exported = True
        return rows


# ---------------------------------------------------------------------------
# freshness lanes


class _FreshLane:
    __slots__ = ("last_ms", "max_ms", "samples", "hist",
                 "last_window", "last_trace")

    def __init__(self, bins: int):
        self.last_ms = 0.0
        self.max_ms = 0.0
        self.samples = 0
        self.hist = np.zeros(bins, np.int64)
        self.last_window = -1
        self.last_trace = ""


class _TierFreshView:
    """The per-tier Countable face: one of these registers per tier
    label (`tpu_freshness{tier="60s"}`), so ONE PromQL rule over
    `tpu_freshness_visibility_lag_ms` fans into per-series alert
    ladders — one per tier — through the r16 per-series engine."""

    def __init__(self, owner: "FreshnessTracker", interval: int):
        self.owner = owner
        self.interval = interval

    def get_counters(self) -> dict[str, float | int]:
        return self.owner._tier_counters(self.interval)


#: lag histograms: 512 log bins over 1 µs .. ~3.4e8 ms at ≤3.5% error
_FRESH_HIST = SpanHistSpec(bins=512, vmin=0.001, gamma=1.07)
_FRESH_QS = (0.5, 0.95)


class FreshnessTracker:
    """Per-tier event-time-to-queryable lag lanes + exemplars.

    Pure host arithmetic: `observe()` is a dict update + one histogram
    increment. Lag = hop wall stamp − window event-time end (window
    START for the `partial` lane — the window is still open), in
    SECONDS in, milliseconds out on the Countable face."""

    def __init__(self, *, name: str = "freshness", collector=None,
                 autoregister: bool = True, group: str | None = None):
        self.name = name
        # per-shard-group freshness lanes (ISSUE 14): a multi-host
        # deployment runs one tracker per shard group, labelled
        # tpu_freshness{tier=..., group=...} — cross-host skew between
        # groups is a dashboard diff of the same lane across labels
        self.group = group
        self._lock = threading.Lock()
        # (interval, kind) → _FreshLane
        self._lanes: dict[tuple[int, str], _FreshLane] = {}
        self._views: dict[int, _TierFreshView] = {}  # strong refs (weak reg)
        self._srcs: list = []
        self._collector = collector
        self._autoregister = autoregister

    def _get_collector(self):
        if self._collector is not None:
            return self._collector
        from ..utils.stats import default_collector

        return default_collector

    def observe(self, kind: str, interval: int, lag_s: float,
                window_idx: int, trace_id: str) -> None:
        interval = int(interval)
        lag_ms = float(lag_s) * 1e3
        with self._lock:
            lane = self._lanes.get((interval, kind))
            if lane is None:
                lane = self._lanes[(interval, kind)] = _FreshLane(
                    _FRESH_HIST.bins
                )
                if interval not in self._views:
                    view = self._views[interval] = _TierFreshView(
                        self, interval
                    )
                    if self._autoregister:
                        tags = {"tier": f"{interval}s", "name": self.name}
                        if self.group is not None:
                            tags["group"] = self.group
                        self._srcs.append(
                            self._get_collector().register(
                                "tpu_freshness", view, **tags
                            )
                        )
            lane.last_ms = lag_ms
            lane.max_ms = max(lane.max_ms, lag_ms)
            lane.samples += 1
            lane.hist[_FRESH_HIST.bin(max(lag_ms, 0.0))] += 1
            lane.last_window = int(window_idx)
            lane.last_trace = trace_id

    def _tier_counters(self, interval: int) -> dict[str, float | int]:
        out: dict[str, float | int] = {}
        with self._lock:
            items = [
                (kind, lane) for (iv, kind), lane in self._lanes.items()
                if iv == interval
            ]
            for kind, lane in items:
                out[f"{kind}_lag_ms"] = round(lane.last_ms, 3)
                out[f"{kind}_lag_max_ms"] = round(lane.max_ms, 3)
                out[f"{kind}_samples"] = lane.samples
                qv = loghist_quantiles_np(lane.hist, _FRESH_HIST, _FRESH_QS)
                for q, v in zip(_FRESH_QS, qv):
                    out[f"{kind}_lag_p{int(q * 100)}_ms"] = round(float(v), 3)
        return out

    def get_counters(self) -> dict[str, float | int]:
        """Flat all-tier face (lane names prefixed `<interval>s.`) —
        the bench-snapshot/debug shape; the per-tier views above are
        the dogfood registration."""
        out: dict[str, float | int] = {}
        with self._lock:
            tiers = sorted({iv for iv, _ in self._lanes})
        for iv in tiers:
            for k, v in self._tier_counters(iv).items():
                out[f"{iv}s.{k}"] = v
        return out

    def hist_dump(self) -> dict[str, list[int]]:
        """lane → raw log-histogram bin counts (nonzero (bin, count)
        pairs, compact). The elastic-topology proof (ISSUE 15) pins
        that a rebalanced group's lag distribution across BOTH owners
        sums bin-for-bin to the uninterrupted oracle's — histograms
        add; quantile summaries don't."""
        with self._lock:
            return {
                f"{iv}s.{kind}": [
                    [int(b), int(lane.hist[b])]
                    for b in np.nonzero(lane.hist)[0]
                ]
                for (iv, kind), lane in self._lanes.items()
            }

    def exemplars(self) -> dict[str, dict]:
        """lane → {trace_id, window, lag_ms}: the metric→trace links a
        dashboard renders next to each lag series (the ISSUE 13
        exemplar contract)."""
        with self._lock:
            return {
                f"{iv}s.{kind}": {
                    "trace_id": lane.last_trace,
                    "window": lane.last_window,
                    "lag_ms": round(lane.last_ms, 3),
                }
                for (iv, kind), lane in self._lanes.items()
                if lane.samples
            }

    def close(self) -> None:
        col = self._get_collector()
        for src in self._srcs:
            try:
                col.deregister(src)
            except Exception:
                pass
        self._srcs.clear()


def merge_hist_dumps(*dumps: dict) -> dict[str, list[list[int]]]:
    """Sum `hist_dump()` outputs bin-for-bin across trackers/hosts —
    the summary-domain merge algebra (histograms add; quantile
    summaries don't). Output is the same sparse sorted shape
    `hist_dump()` emits, so the merge composes: the fleet pane pins
    merge(host dumps) bit-exact against the aggregator's view."""
    acc: dict[str, dict[int, int]] = {}
    for dump in dumps:
        for lane, pairs in dump.items():
            tgt = acc.setdefault(lane, {})
            for b, c in pairs:
                tgt[int(b)] = tgt.get(int(b), 0) + int(c)
    return {
        lane: [[b, tgt[b]] for b in sorted(tgt)]
        for lane, tgt in sorted(acc.items())
    }


# ---------------------------------------------------------------------------
# the tracker

#: process-wide registry of live trackers — the REST/dfctl live
#: fallback assembles a not-yet-exported window trace from here
_REGISTRY: "weakref.WeakSet[LineageTracker]" = weakref.WeakSet()


def all_trackers() -> list["LineageTracker"]:
    return list(_REGISTRY)


class LineageTracker:
    """Per-window hop recorder for one pipeline (one service name, one
    base tier interval; cascade tiers share the tracker with their own
    interval key). Attach with `RollupPipeline.attach_lineage` /
    `ShardedWindowManager.attach_lineage` (receiver/feeder take it as
    `lineage=`); everything else is plumbing-free — the window id is
    the context."""

    MAX_ADMIT_STAMPS = 4096
    MAX_DISPATCH_STAMPS = 256
    #: a batch whose (t_min, t_max) spans more than this many windows
    #: binds only the newest MAX_BIND_SPAN (counted) — a corrupt
    #: timestamp must not turn one bind into a million dict inserts
    MAX_BIND_SPAN = 64

    def __init__(self, service: str = DEFAULT_SERVICE, interval: int = 1,
                 *, clock=time.time, freshness: FreshnessTracker | None = None,
                 max_windows: int = 4096, name: str = "lineage",
                 group: str | None = None):
        self.service = service
        self.interval = int(interval)
        self.clock = clock
        self.freshness = freshness
        self.name = name
        # multi-host mesh (ISSUE 14): shard-group label for the
        # Countable rows. Trace ids stay PURE functions of (service,
        # window, interval) — deliberately NOT of the group — so every
        # host's hops for one window join ONE trace with no wire
        # context; the group label only distinguishes tracker rows
        self.group = group
        self.max_windows = int(max_windows)
        self._lock = threading.RLock()
        # (interval, window_idx) → WindowLineage, eviction order
        self._windows: "OrderedDict[tuple[int, int], WindowLineage]" = (
            OrderedDict()
        )
        self._admit_ring: deque[float] = deque(maxlen=self.MAX_ADMIT_STAMPS)
        self._dispatch_ring: deque[tuple[float, float]] = deque(
            maxlen=self.MAX_DISPATCH_STAMPS
        )
        # per-pump pending context: hop → (start_s, end_s) — bound to
        # windows at the next dispatch with a known span. Scope: a
        # feeder pump resets it via begin_pump(); FEEDERLESS pipelines
        # (attach_lineage + direct ingest, no pump loop) reset it after
        # every dispatch bind instead — without that, note_stage's
        # min-merge would pin upload.stage's start at the first-ever
        # stage call and every window's span would grow to process
        # uptime.
        self._ctx: dict[str, tuple[float, float]] = {}
        self._in_pump = False
        # incremental-export + query bookkeeping: keys touched since
        # the last drain_spans, and keys inserted-but-not-yet-queried —
        # the hot faces stay O(changed), not O(max_windows)
        self._dirty: set[tuple[int, int]] = set()
        self._awaiting_query: set[tuple[int, int]] = set()
        self.counters = {
            "hops_recorded": 0,
            "windows_tracked": 0,
            "windows_evicted": 0,
            "spans_exported": 0,
            "bind_span_clamped": 0,
        }
        tags = {"name": name}
        if group is not None:
            tags["group"] = group
        self._stats_src = register_countable("tpu_lineage", self, **tags)
        _REGISTRY.add(self)

    # -- countable face ---------------------------------------------------
    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["windows_live"] = len(self._windows)
            out["admit_stamps_pending"] = len(self._admit_ring)
        return out

    def close(self) -> None:
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)
        if self.freshness is not None:
            self.freshness.close()
        _REGISTRY.discard(self)

    # -- record plumbing --------------------------------------------------
    def _record(self, interval: int, window_idx: int) -> WindowLineage:
        key = (int(interval), int(window_idx))
        rec = self._windows.get(key)
        if rec is None:
            rec = self._windows[key] = WindowLineage(window_idx, interval)
            self.counters["windows_tracked"] += 1
            while len(self._windows) > self.max_windows:
                old_key, _old = self._windows.popitem(last=False)
                self._dirty.discard(old_key)
                self._awaiting_query.discard(old_key)
                self.counters["windows_evicted"] += 1
        else:
            self._windows.move_to_end(key)
        return rec

    def _note(self, rec: WindowLineage, hop: str, start_s, end_s, rows=0):
        rec.note(hop, float(start_s), float(end_s), int(rows))
        self._dirty.add((rec.interval, rec.window_idx))
        self.counters["hops_recorded"] += 1

    def _fresh(self, kind: str, rec: WindowLineage, stamp_s: float,
               *, anchor_start: bool = False) -> None:
        if self.freshness is None:
            return
        anchor = (
            rec.window_idx * rec.interval if anchor_start else rec.event_end_s
        )
        lag = float(stamp_s) - anchor
        rec.lags[kind] = lag
        self.freshness.observe(
            kind, rec.interval, lag, rec.window_idx,
            window_trace_id(self.service, rec.window_idx, rec.interval),
        )

    # -- ownership handover (ISSUE 15) ------------------------------------
    def export_open(self, lo_window: int) -> dict:
        """Serialize the hop records of every still-open window (≥
        `lo_window` on the base tier) for a shard-group handover: the
        moving group's state checkpoint carries its windows' partial
        aggregates, and THIS carries their partial lineage — so the
        new owner's flush still observes the ingest lag a window
        accrued on the old owner, and its trace joins the hops from
        both hosts (ids are derived, so no id mapping is needed)."""
        with self._lock:
            wins = []
            for (iv, w), rec in self._windows.items():
                if iv != self.interval or w < int(lo_window):
                    continue
                wins.append({
                    "window": w,
                    "hops": {
                        h: [a.start_s, a.end_s, a.count, a.rows]
                        for h, a in rec.hops.items()
                    },
                })
            return {"interval": self.interval, "windows": wins}

    def import_open(self, data: dict) -> None:
        """Adopt exported open-window lineage (the export_open twin on
        the new owner). Hop aggregates merge, so importing into a
        tracker that already saw post-flip traffic for a window is
        safe — first-start/last-end semantics hold across hosts."""
        with self._lock:
            for win in data.get("windows", ()):
                rec = self._record(
                    int(data.get("interval", self.interval)),
                    int(win["window"]),
                )
                for hop, vals in win["hops"].items():
                    start_s, end_s, count, rows = (
                        float(vals[0]), float(vals[1]),
                        int(vals[2]), int(vals[3]),
                    )
                    rec.note(hop, start_s, end_s, rows)
                    agg = rec.hops[hop]
                    # note() counted one event; fold the remaining
                    # event count in so RED rates stay truthful
                    agg.count += count - 1
                self._dirty.add((rec.interval, rec.window_idx))

    # -- pre-window context (receiver / feeder / journal / upload) --------
    def note_admit(self, t: float | None = None) -> None:
        """Receiver frame admission stamp (called from receiver dispatch
        threads — just an append under the lock)."""
        with self._lock:
            self._admit_ring.append(self.clock() if t is None else t)

    def begin_pump(self) -> None:
        """Feeder pump start: reset the pending context (and flip the
        context scope to pump-lifetime — see _ctx)."""
        with self._lock:
            now = self.clock()
            self._in_pump = True
            self._ctx = {HOP_FEEDER_PUMP: (now, now)}

    def note_frames(self, n: int) -> None:
        """Pair n admitted frames with their receiver admission stamps
        (FIFO): the earliest stamp opens the receiver.admit hop, the
        pump time closes it."""
        with self._lock:
            now = self.clock()
            t0 = None
            for _ in range(min(n, len(self._admit_ring))):
                s = self._admit_ring.popleft()
                t0 = s if t0 is None else min(t0, s)
            if t0 is not None:
                have = self._ctx.get(HOP_RECEIVER_ADMIT)
                self._ctx[HOP_RECEIVER_ADMIT] = (
                    (min(have[0], t0), now) if have else (t0, now)
                )
            # the pump hop's end tracks the latest activity
            p = self._ctx.get(HOP_FEEDER_PUMP)
            if p is not None:
                self._ctx[HOP_FEEDER_PUMP] = (p[0], now)

    def drop_stamps(self, n: int) -> None:
        """Discard n admission stamps WITHOUT folding them into the
        context — for frames the feeder admitted but that contribute
        no rows (quarantined/bad, counted-shed, empty). Every admitted
        frame must consume exactly one stamp or the FIFO pairing
        drifts: a 1% bad-frame rate would otherwise make every later
        window's receiver.admit start monotonically staler."""
        with self._lock:
            for _ in range(min(n, len(self._admit_ring))):
                self._admit_ring.popleft()

    def note_journal(self, start_s: float) -> None:
        with self._lock:
            now = self.clock()
            have = self._ctx.get(HOP_JOURNAL_APPEND)
            self._ctx[HOP_JOURNAL_APPEND] = (
                (min(have[0], start_s), now) if have else (start_s, now)
            )

    def note_stage(self, start_s: float) -> None:
        """Staged device upload (RollupPipeline.stage)."""
        with self._lock:
            now = self.clock()
            have = self._ctx.get(HOP_UPLOAD_STAGE)
            self._ctx[HOP_UPLOAD_STAGE] = (
                (min(have[0], start_s), now) if have else (start_s, now)
            )

    # -- dispatch / advance / flush (the manager seams) -------------------
    def note_dispatch(self, window_span: tuple[int, int] | None,
                      start_s: float) -> None:
        """One fused-step dispatch: bind the pending context + the
        ingest.dispatch hop to every window in `window_span` (inclusive
        lo..hi, from the batch's own host-side timestamps) and push a
        wall stamp onto the FIFO the counter-block replay pops — the
        derived-not-fetched device time base for advances discovered at
        a K-ring drain."""
        with self._lock:
            end_s = self.clock()
            self._dispatch_ring.append((start_s, end_s))
            if window_span is None:
                return
            lo, hi = int(window_span[0]), int(window_span[1])
            if hi - lo + 1 > self.MAX_BIND_SPAN:
                self.counters["bind_span_clamped"] += 1
                lo = hi - self.MAX_BIND_SPAN + 1
            ctx = dict(self._ctx)
            for w in range(lo, hi + 1):
                rec = self._record(self.interval, w)
                for hop, (a, b) in ctx.items():
                    self._note(rec, hop, a, b)
                self._note(rec, HOP_INGEST_DISPATCH, start_s, end_s)
            if not self._in_pump:
                # feederless scope: this dispatch consumed its context
                # (a pump-scoped context is reset by begin_pump instead)
                self._ctx = {}

    def pop_dispatch_stamp(self) -> tuple[float, float] | None:
        """FIFO pairing: one counter block replayed = one dispatch."""
        with self._lock:
            return self._dispatch_ring.popleft() if self._dispatch_ring else None

    def note_advance(self, lo: int, hi: int,
                     stamp: tuple[float, float] | None) -> None:
        """Windows [lo, hi) closed: the advance hop, timed from the
        dispatch stamp of the batch whose counter block triggered it
        (start) to now (the host discovered/flushed it)."""
        with self._lock:
            now = self.clock()
            start = stamp[0] if stamp else now
            for (iv, w), rec in list(self._windows.items()):
                if iv == self.interval and lo <= w < hi:
                    self._note(rec, HOP_WINDOW_ADVANCE, start, now)

    def note_flush_windows(self, items: list[tuple[int, int]],
                           start_s: float | None = None) -> None:
        """Flush-drain completion for tier-0 windows: items are
        (window_idx, rows). Freshness: ingest lag (from the recorded
        dispatch hop) + flush lag anchor here — the window is closed."""
        with self._lock:
            now = self.clock()
            for w, rows in items:
                rec = self._record(self.interval, w)
                self._note(rec, HOP_FLUSH_DRAIN,
                           now if start_s is None else start_s, now, rows)
                disp = rec.hops.get(HOP_INGEST_DISPATCH)
                if disp is not None:
                    self._fresh(LAG_INGEST, rec, disp.end_s)
                self._fresh(LAG_FLUSH, rec, now)

    def note_tier_windows(self, items: list[tuple[int, int, int]],
                          start_s: float | None = None) -> None:
        """Cascade tier closes: items are (tier_interval_s, window_idx,
        rows). Tier windows get their own trace (same service, tier
        interval in the id) rooted at cascade.close."""
        with self._lock:
            now = self.clock()
            for interval, w, rows in items:
                rec = self._record(interval, w)
                self._note(rec, HOP_CASCADE_CLOSE,
                           now if start_s is None else start_s, now, rows)
                self._fresh(LAG_CASCADE, rec, now)

    # -- downstream (store / query) ---------------------------------------
    def note_store_insert(self, items: list[tuple[int, int]]) -> None:
        """Rows of closed windows landed in the store — the moment the
        window becomes queryable (visibility lag). Items are
        (tier_interval_s, window_idx); tier 0 callers pass the base
        interval."""
        with self._lock:
            now = self.clock()
            for interval, w in items:
                rec = self._record(interval or self.interval, w)
                self._note(rec, HOP_STORE_INSERT, now, now)
                self._awaiting_query.add((rec.interval, rec.window_idx))
                self._fresh(LAG_VISIBILITY, rec, now)

    def note_snapshot(self, items: list[tuple[int, int]]) -> None:
        """A live snapshot served these still-OPEN windows: the
        query.snapshot hop + the DISTINCT `partial` freshness lane
        (anchored on window start — the window has no end yet)."""
        with self._lock:
            now = self.clock()
            for w, rows in items:
                rec = self._record(self.interval, w)
                self._note(rec, HOP_QUERY_SNAPSHOT, now, now, rows)
                self._fresh(LAG_PARTIAL, rec, now, anchor_start=True)

    def note_query(self, lo: int | None = None, hi: int | None = None) -> None:
        """A store scan touched [lo, hi): the first query over a
        flushed window closes its lineage with query.first. Only
        windows that already have store.insert and no query.first yet
        are candidates (the `_awaiting_query` set, so a dashboard-rate
        scan hook costs O(still-unqueried), not O(max_windows)) —
        repeated dashboards don't widen the span."""
        with self._lock:
            now = self.clock()
            for key in list(self._awaiting_query):
                rec = self._windows.get(key)
                if rec is None:
                    self._awaiting_query.discard(key)
                    continue
                iv, w = key
                w_lo, w_hi = w * iv, (w + 1) * iv
                if lo is not None and w_hi <= lo:
                    continue
                if hi is not None and w_lo >= hi:
                    continue
                self._note(rec, HOP_QUERY_FIRST, now, now)
                self._awaiting_query.discard(key)

    # -- export faces ------------------------------------------------------
    def trace_id_of(self, window_idx: int, interval: int | None = None) -> str:
        return window_trace_id(
            self.service, window_idx,
            self.interval if interval is None else interval,
        )

    def record_of(self, window_idx: int,
                  interval: int | None = None) -> WindowLineage | None:
        with self._lock:
            return self._windows.get(
                (self.interval if interval is None else int(interval),
                 int(window_idx))
            )

    def drain_spans(self) -> list[SpanRow]:
        """Every unexported hop of every CLOSED window touched since
        the last drain, as l7-shaped SpanRows. Export is deferred until
        a window has a TERMINAL_HOPS entry and each span id is emitted
        exactly ONCE (sticky per-hop exported flag): the l7 lane is
        append-only and the tree assemblers have no span-id dedup, so
        re-emitting a merged hop would double-count it in the tree's
        RED aggregates. Open windows stay in the dirty set and export
        at close. Walks only touched windows — an every-batch consumer
        stays O(changed), never O(max_windows)."""
        out: list[SpanRow] = []
        with self._lock:
            dirty, self._dirty = self._dirty, set()
            still_open: set[tuple[int, int]] = set()
            for key in sorted(dirty):
                rec = self._windows.get(key)
                if rec is None:
                    continue  # evicted since it was touched
                if not any(h in rec.hops for h in TERMINAL_HOPS):
                    still_open.add(key)  # export at close
                    continue
                iv, w = key
                tid = window_trace_id(self.service, w, iv)
                out.extend(
                    rec.span_rows(tid, only_unexported=True, mark=True)
                )
            self._dirty |= still_open
            self.counters["spans_exported"] += len(out)
        return out

    def export_otlp(self, exporter, *, table: str = "l7_flow_log") -> int:
        """Drain through an exporter's traces lane — the same
        `exporter.export(table, cols)` path the span tracer and every
        l7 row takes (OtlpExporter → OTel spans; pointing it at our own
        collector closes the dogfood loop, pinned by the round-trip
        test)."""
        rows = self.drain_spans()
        if not rows:
            return 0
        exporter.export(table, spanrows_to_l7_cols(rows))
        return len(rows)

    def export_store(self, store, *, org: int = 1, builder=None) -> int:
        """Drain straight into the store's `l7_flow_log` table (the
        in-process dogfood lane — no wire hop) and, optionally, into a
        TraceTreeBuilder so quiet traces assemble into trace_tree rows."""
        rows = self.drain_spans()
        if not rows:
            return 0
        write_l7_span_rows(store, rows, org=org)
        if builder is not None:
            builder.observe(rows, org=org)
        return len(rows)

    def assemble(self, window_idx: int, interval: int | None = None):
        """Live (pre-export) tree of one window — the REST fallback."""
        iv = self.interval if interval is None else int(interval)
        with self._lock:
            rec = self._windows.get((iv, int(window_idx)))
            if rec is None:
                return None
            rows = rec.span_rows(
                window_trace_id(self.service, window_idx, iv)
            )
            lags = dict(rec.lags)
        tree = assemble_trace(rows)
        if tree is None:
            return None
        out = tree.to_dict()
        out["freshness"] = {k: round(v * 1e3, 3) for k, v in lags.items()}
        return out


# ---------------------------------------------------------------------------
# l7 lane helpers


def spanrows_to_l7_cols(rows: list[SpanRow]) -> dict[str, np.ndarray]:
    """SpanRows → the minimal l7_flow_log-shaped column dict the
    exporter traces lane consumes (utils/spans.export_otlp's shape,
    with REAL trace/parent ids)."""
    n = len(rows)
    return {
        "time": np.asarray([r.start_us // 1_000_000 for r in rows], np.uint32),
        "start_time": np.asarray(
            [r.start_us // 1_000_000 for r in rows], np.uint32
        ),
        "response_duration": np.asarray(
            [min(r.response_duration_us, 0xFFFFFFFF) for r in rows], np.uint32
        ),
        "app_service": np.asarray([r.app_service for r in rows]),
        "endpoint": np.asarray([r.endpoint for r in rows]),
        "trace_id": np.asarray([r.trace_id for r in rows]),
        "span_id": np.asarray([r.span_id for r in rows]),
        "parent_span_id": np.asarray([r.parent_span_id for r in rows]),
    }


def write_l7_span_rows(store, rows: list[SpanRow], *, org: int = 1) -> None:
    """Write lineage spans as real `flow_log.l7_flow_log` rows (the
    columnar-store-native lane `tracing.query.query_trace` reads), via
    the same LogSchema the OTel import path uses."""
    from ..datamodel.code import SignalSource
    from ..flowlog.aggr import FlowLogBatch
    from ..flowlog.schema import L7_FLOW_LOG
    from ..flowlog.server import log_batch_to_columns, log_table_schema
    from ..storage.store import org_db

    s = L7_FLOW_LOG
    n = len(rows)
    ints = np.zeros((n, len(s.ints)), np.uint32)
    nums = np.zeros((n, len(s.nums)), np.float32)
    strs = {f.name: [""] * n for f in s.strs}
    ii = s.int_index
    for r, sp in enumerate(rows):
        ints[r, ii("signal_source")] = int(SignalSource.OTEL)
        ints[r, ii("type")] = 2
        ints[r, ii("tap_side")] = 50  # s-app: our own process observed
        ints[r, ii("start_time")] = sp.start_us // 1_000_000
        ints[r, ii("end_time")] = sp.end_us // 1_000_000
        ints[r, ii("response_duration")] = min(
            sp.response_duration_us, 0xFFFFFFFF
        )
        ints[r, ii("status")] = 1
        strs["app_service"][r] = sp.app_service
        strs["endpoint"][r] = sp.endpoint
        strs["trace_id"][r] = sp.trace_id
        strs["span_id"][r] = sp.span_id
        strs["parent_span_id"][r] = sp.parent_span_id
    batch = FlowLogBatch(s, ints, nums, np.ones(n, bool), strs)
    db = org_db("flow_log", org)
    schema = log_table_schema(s)
    store.create_table(db, schema)
    store.insert(db, schema.name, log_batch_to_columns(batch))


def connect_store_reads(store, tracker: LineageTracker, db: str, table: str):
    """Register a scan hook: the first SQL/PromQL read touching a
    flushed window's (db, table) closes the lineage with query.first.
    Returns the hook (pass to `store.remove_scan_hook` to detach)."""

    def hook(sdb: str, stable: str, time_range):
        if sdb != db or stable != table:
            return
        lo, hi = (None, None) if time_range is None else time_range
        tracker.note_query(lo, hi)

    store.add_scan_hook(hook)
    return hook


def query_window_trace(
    store, window_idx: int, *, interval: int = 1,
    service: str = DEFAULT_SERVICE, org: int = 1,
) -> dict | None:
    """`GET /v1/trace/window/<id>` / `dfctl trace window <id>`: the
    assembled lineage tree of one window — from the store (exported
    spans / trace_tree rows) when present, else live from a registered
    tracker. The trace id is derived, never looked up."""
    from .query import query_trace

    tid = window_trace_id(service, window_idx, interval)
    out = None
    if store is not None:
        out = query_trace(store, tid, org=org)
    tracker = next(
        (
            t for t in all_trackers()
            if t.service == service
            and t.record_of(window_idx, interval) is not None
        ),
        None,
    )
    if out is None and tracker is not None:
        out = tracker.assemble(window_idx, interval)
    if out is not None:
        out.setdefault("trace_id", tid)
        out["window"] = int(window_idx)
        out["interval"] = int(interval)
        if "freshness" not in out and tracker is not None:
            rec = tracker.record_of(window_idx, interval)
            if rec is not None:
                out["freshness"] = {
                    k: round(v * 1e3, 3) for k, v in rec.lags.items()
                }
    return out
