"""Trace query surface — trace-by-id and the cross-trace trace map.

The reference serves these from the querier's distributed_tracing app
(querier/app/distributed_tracing/router/tracemap.go; trace_tree /
span_with_trace_id tables, engine/clickhouse/common/const.go:32-33).
Here both run directly over the columnar store:

  * query_trace: prefer the assembled `trace_tree` row (closed traces);
    fall back to on-the-fly assembly over `l7_flow_log` spans so a trace
    can be queried before its quiet period expires.
  * trace_map: aggregate service→service call edges over every tree in
    a time range — edge call counts, duration sums, error counts — the
    "aggregate from trace_tree" model (model/raw_trace_map.go:24-26).
"""

from __future__ import annotations

import numpy as np

from ..storage.store import ColumnarStore, org_db
from .builder import FLOW_LOG_DB, TRACE_TREE_SCHEMA
from .tree import SpanRow, TraceTree, assemble_trace


def _spans_from_l7(store: ColumnarStore, db: str, trace_id: str,
                   time_range=None) -> list[SpanRow]:
    try:
        cols = store.scan(
            db,
            "l7_flow_log",
            time_range=time_range,
            columns=[
                "time", "trace_id", "span_id", "parent_span_id",
                "app_service", "tap_side", "endpoint", "start_time",
                "end_time", "response_duration", "status",
            ],
        )
    except KeyError:
        return []
    sel = cols["trace_id"] == trace_id
    if not sel.any():
        return []
    spans = []
    for i in np.nonzero(sel)[0]:
        spans.append(
            SpanRow(
                trace_id=trace_id,
                span_id=str(cols["span_id"][i]),
                parent_span_id=str(cols["parent_span_id"][i]),
                app_service=str(cols["app_service"][i]),
                tap_side=int(cols["tap_side"][i]),
                endpoint=str(cols["endpoint"][i]),
                start_us=int(cols["start_time"][i]) * 1_000_000,
                end_us=int(cols["end_time"][i]) * 1_000_000,
                response_duration_us=int(cols["response_duration"][i]),
                server_error=int(cols["status"][i]) == 4,
            )
        )
    return spans


def query_trace(
    store: ColumnarStore,
    trace_id: str,
    org: int = 1,
    time_range: tuple[int, int] | None = None,
) -> dict | None:
    """Full tree for one trace id, or None if unknown."""
    db = org_db(FLOW_LOG_DB, org)
    try:
        cols = store.scan(
            db, TRACE_TREE_SCHEMA.name, time_range=time_range
        )
        sel = cols["trace_id"] == trace_id
        if sel.any():
            i = int(np.nonzero(sel)[0][-1])  # latest assembly wins
            try:
                tree = TraceTree.decode(
                    int(cols["time"][i]), trace_id, str(cols["encoded_span_list"][i])
                )
                return tree.to_dict()
            except (ValueError, KeyError, IndexError):
                pass  # corrupt row: fall through to on-the-fly assembly
    except KeyError:
        pass  # no trace_tree table yet
    tree = assemble_trace(_spans_from_l7(store, db, trace_id, time_range))
    return tree.to_dict() if tree is not None else None


def tempo_trace(
    store: ColumnarStore,
    trace_id: str,
    org: int = 1,
    time_range: tuple[int, int] | None = None,
) -> dict | None:
    """Tempo/OTLP-shaped trace response — the querier's Tempo adapter
    seat (the reference serves Grafana's Tempo datasource from its span
    store). Raw spans come from l7_flow_log; shape follows the OTLP JSON
    trace schema Grafana consumes: batches → scopeSpans → spans."""
    db = org_db(FLOW_LOG_DB, org)
    spans = _spans_from_l7(store, db, trace_id, time_range)
    if not spans:
        return None
    by_service: dict[str, list] = {}
    for s in spans:
        by_service.setdefault(s.app_service, []).append(s)
    batches = []
    for service, group in by_service.items():
        batches.append(
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name", "value": {"stringValue": service}}
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "deepflow_tpu"},
                        "spans": [
                            {
                                "traceId": trace_id,
                                "spanId": s.span_id,
                                "parentSpanId": s.parent_span_id,
                                "name": s.endpoint or service,
                                # OTLP: 2=SERVER, 3=CLIENT — derived
                                # from which side of the call the tap
                                # observed (TapSide.CLIENT bit)
                                "kind": 3 if (s.tap_side & 1) else 2,
                                "startTimeUnixNano": str(s.start_us * 1000),
                                "endTimeUnixNano": str(
                                    (s.start_us + s.response_duration_us) * 1000
                                ),
                                "status": {"code": 2 if s.server_error else 0},
                            }
                            for s in group
                        ],
                    }
                ],
            }
        )
    return {"batches": batches}


def trace_map(
    store: ColumnarStore,
    time_range: tuple[int, int] | None = None,
    org: int = 1,
) -> list[dict]:
    """Service-edge aggregation across all trees in the range.

    Returns one row per (client_service, server_service) edge:
    {client, server, call_count, duration_sum_us, error_count,
     trace_count, pseudo_link_count}, sorted by call_count desc.
    """
    db = org_db(FLOW_LOG_DB, org)
    try:
        cols = store.scan(db, TRACE_TREE_SCHEMA.name, time_range=time_range)
    except KeyError:
        return []
    edges: dict[tuple[str, str], dict] = {}
    for i in range(len(cols["time"])):
        try:
            tree = TraceTree.decode(
                int(cols["time"][i]),
                str(cols["trace_id"][i]),
                str(cols["encoded_span_list"][i]),
            )
        except (ValueError, KeyError, IndexError):
            continue  # one corrupt row must not break the whole map
        for n in tree.nodes:
            client = (
                tree.nodes[n.parent_node_index].app_service
                if n.parent_node_index >= 0
                else ""
            )
            key = (client, n.app_service)
            e = edges.get(key)
            if e is None:
                e = edges[key] = {
                    "client": client,
                    "server": n.app_service,
                    "call_count": 0,
                    "duration_sum_us": 0,
                    "error_count": 0,
                    "trace_count": 0,
                    "pseudo_link_count": 0,
                }
            e["call_count"] += n.response_total
            e["duration_sum_us"] += n.response_duration_sum
            e["error_count"] += n.response_status_server_error_count
            e["trace_count"] += 1
            e["pseudo_link_count"] += n.pseudo_link
    return sorted(edges.values(), key=lambda e: -e["call_count"])
