from .tree import SpanRow, TraceTree, TreeNode, assemble_trace, search_index
from .builder import TraceTreeBuilder, TRACE_TREE_SCHEMA
from .query import query_trace, trace_map
from .lineage import (
    FreshnessTracker,
    LineageTracker,
    hop_span_id,
    query_window_trace,
    window_trace_id,
)

__all__ = [
    "SpanRow",
    "TraceTree",
    "TreeNode",
    "assemble_trace",
    "search_index",
    "TraceTreeBuilder",
    "TRACE_TREE_SCHEMA",
    "query_trace",
    "trace_map",
    "FreshnessTracker",
    "LineageTracker",
    "hop_span_id",
    "query_window_trace",
    "window_trace_id",
]
