from .tree import SpanRow, TraceTree, TreeNode, assemble_trace, search_index
from .builder import TraceTreeBuilder, TRACE_TREE_SCHEMA
from .query import query_trace, trace_map

__all__ = [
    "SpanRow",
    "TraceTree",
    "TreeNode",
    "assemble_trace",
    "search_index",
    "TraceTreeBuilder",
    "TRACE_TREE_SCHEMA",
    "query_trace",
    "trace_map",
]
