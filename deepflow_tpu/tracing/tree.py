"""Trace-tree assembly — spans of one trace_id → a service call tree.

The reference defines the trace-tree *data model* (TraceTree/TreeNode
with per-node RED aggregates, parent links, pseudo-links for broken
chains — server/libs/tracetree/tracetree.go:38-90) and the storage
tables, but the open-source tree builder is an enterprise stub
(querier/app/distributed_tracing/service/tracemap/tracemap_generator.go:32
`Start() {}`), so the assembly below is designed fresh:

  * one node per *service* seen in the trace (app_service name, falling
    back to the enriched auto_service id) — spans of the same service
    collapse into the node's RED aggregates, mirroring the reference's
    node-level ResponseDurationSum/ResponseTotal/ServerErrorCount;
  * parent link = service of the span referenced by parent_span_id;
    spans whose parent span is absent from the trace attach to the
    root with `pseudo_link=1` (tracetree.go:80 PseudoLink);
  * levels are depths after link resolution; cycles (malformed data)
    are cut at the back-edge and marked pseudo.

Wire form: a compact self-describing JSON (the reference uses a custom
zigzag codec because ClickHouse stores it as an opaque string; our
columnar store holds it in a string column where JSON is the idiomatic
opaque encoding).
"""

from __future__ import annotations

import dataclasses
import json


def search_index(trace_id: str) -> int:
    """64-bit FNV-1a of the trace id — the fixed-width key the trace_tree
    table is ordered by (the reference orders by a string hash too,
    tracetree.go:33)."""
    h = 0xCBF29CE484222325
    for b in trace_id.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


@dataclasses.dataclass
class SpanRow:
    """The slice of one l7_flow_log row the assembler needs."""

    trace_id: str
    span_id: str
    parent_span_id: str
    app_service: str
    auto_service_id: int = 0
    tap_side: int = 0
    endpoint: str = ""
    start_us: int = 0
    end_us: int = 0
    response_duration_us: int = 0
    server_error: bool = False


@dataclasses.dataclass
class TreeNode:
    app_service: str
    auto_service_id: int = 0
    parent_node_index: int = -1
    pseudo_link: int = 0
    level: int = 0
    topic: str = ""
    response_duration_sum: int = 0  # µs
    response_total: int = 0
    response_status_server_error_count: int = 0


@dataclasses.dataclass
class TraceTree:
    time: int  # earliest span second
    trace_id: str
    nodes: list[TreeNode]

    @property
    def search_index(self) -> int:
        return search_index(self.trace_id)

    def encode(self) -> str:
        return json.dumps(
            {
                "v": 1,
                "nodes": [
                    [
                        n.app_service,
                        n.auto_service_id,
                        n.parent_node_index,
                        n.pseudo_link,
                        n.level,
                        n.topic,
                        n.response_duration_sum,
                        n.response_total,
                        n.response_status_server_error_count,
                    ]
                    for n in self.nodes
                ],
            },
            separators=(",", ":"),
        )

    @staticmethod
    def decode(time: int, trace_id: str, text: str) -> "TraceTree":
        obj = json.loads(text)
        nodes = [
            TreeNode(
                app_service=r[0],
                auto_service_id=r[1],
                parent_node_index=r[2],
                pseudo_link=r[3],
                level=r[4],
                topic=r[5],
                response_duration_sum=r[6],
                response_total=r[7],
                response_status_server_error_count=r[8],
            )
            for r in obj["nodes"]
        ]
        return TraceTree(time=time, trace_id=trace_id, nodes=nodes)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "time": self.time,
            "search_index": self.search_index,
            "nodes": [dataclasses.asdict(n) for n in self.nodes],
        }


def _service_key(s: SpanRow) -> tuple:
    return (s.app_service or f"auto:{s.auto_service_id}", s.auto_service_id)


def assemble_trace(spans: list[SpanRow]) -> TraceTree | None:
    """Collapse one trace's spans into a service tree.

    Returns None for an empty span list. Deterministic: node order is
    (level, first-seen order), so equal inputs encode identically.
    """
    if not spans:
        return None
    trace_id = spans[0].trace_id

    by_span_id = {s.span_id: s for s in spans if s.span_id}

    # service nodes in first-seen order
    key_to_idx: dict[tuple, int] = {}
    nodes: list[TreeNode] = []
    for s in spans:
        k = _service_key(s)
        if k not in key_to_idx:
            key_to_idx[k] = len(nodes)
            nodes.append(TreeNode(app_service=k[0], auto_service_id=k[1]))
        n = nodes[key_to_idx[k]]
        n.response_total += 1
        n.response_duration_sum += max(0, s.response_duration_us)
        if s.server_error:
            n.response_status_server_error_count += 1

    # parent resolution per node: the first span of the node whose parent
    # resolves inside the trace wins; otherwise the node is a root or a
    # pseudo-linked orphan.
    has_parent = [False] * len(nodes)
    is_orphan_with_parent_ref = [False] * len(nodes)
    for s in spans:
        idx = key_to_idx[_service_key(s)]
        if has_parent[idx]:
            continue
        if s.parent_span_id and s.parent_span_id in by_span_id:
            pidx = key_to_idx[_service_key(by_span_id[s.parent_span_id])]
            if pidx != idx:  # intra-service parent stays merged
                nodes[idx].parent_node_index = pidx
                has_parent[idx] = True
        elif s.parent_span_id:
            is_orphan_with_parent_ref[idx] = True

    # root: first node with no parent; orphans attach there pseudo-linked
    root_idx = next(
        (i for i, n in enumerate(nodes) if n.parent_node_index < 0), 0
    )
    for i, n in enumerate(nodes):
        if i != root_idx and n.parent_node_index < 0:
            n.parent_node_index = root_idx
            if is_orphan_with_parent_ref[i]:
                n.pseudo_link = 1

    # levels, with cycle cut (defensive against malformed span data):
    # a walk that hasn't reached a root within |nodes| hops is cyclic —
    # re-attach the start node to the root as a pseudo link.
    for i, n in enumerate(nodes):
        level, j = 0, i
        while nodes[j].parent_node_index >= 0:
            j = nodes[j].parent_node_index
            level += 1
            if level > len(nodes):
                # root_idx itself can sit inside the cycle: it becomes
                # the true root, everything else re-attaches beneath it.
                n.parent_node_index = root_idx if i != root_idx else -1
                n.pseudo_link = 0 if i == root_idx else 1
                level = 0 if i == root_idx else 1
                break
        n.level = level

    t0 = min((s.start_us for s in spans if s.start_us), default=0) // 1_000_000
    return TraceTree(time=int(t0), trace_id=trace_id, nodes=nodes)
