"""TraceTreeBuilder — buffers l7 spans per trace, closes quiet traces,
writes `flow_log.trace_tree` rows.

The reference streams spans into `span_with_trace_id` and feeds trees
through a shared OverwriteQueue into TraceTreeWriter
(flow_log/dbwriter/tracetree_writer.go, common/module_shared.go:38); the
component that fills that queue is enterprise-only. Here the builder is
the whole loop: `observe()` from any l7 write path that carries a
trace_id, `tick()` (driven by the server's housekeeping tick) closes
traces that have been quiet for `close_after_s`, assembles them
(tree.assemble_trace) and hands rows to a TableWriter per org database.

Spans are NOT duplicated into a span_with_trace_id table — l7_flow_log
already stores every span with its trace_id, and the querier can filter
it directly; one copy is the columnar-store-native design.

Backpressure: at most `max_traces` open traces and `max_spans_per_trace`
spans each; beyond that, oldest traces close early / extra spans drop
and are counted — the OverwriteQueue shed-oldest stance
(libs/queue/queue.go:139).
"""

from __future__ import annotations

import threading
import time as _time

from ..storage.store import ColumnSpec, TableSchema, org_db
from ..storage.writer import TableWriter
from .tree import SpanRow, TraceTree, assemble_trace

import numpy as np

FLOW_LOG_DB = "flow_log"

TRACE_TREE_SCHEMA = TableSchema(
    "trace_tree",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("search_index", "u8"),
        ColumnSpec("trace_id", "U64"),
        ColumnSpec("encoded_span_list", "U4096"),
    ),
    partition_s=3600,
)


class TraceTreeBuilder:
    def __init__(
        self,
        store,
        *,
        close_after_s: float = 3.0,
        max_traces: int = 4096,
        max_spans_per_trace: int = 512,
        writer_args: dict | None = None,
    ):
        self.store = store
        self.close_after_s = close_after_s
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self.writer_args = writer_args or {"flush_interval_s": 0.5}
        self._writers: dict[str, TableWriter] = {}
        # (org, trace_id) -> (spans, last_seen_monotonic)
        self._open: dict[tuple[int, str], tuple[list[SpanRow], float]] = {}
        self._lock = threading.Lock()
        self.counters = {
            "spans_in": 0,
            "spans_dropped": 0,
            "traces_closed": 0,
            "traces_evicted": 0,
        }

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    # -- ingest side ----------------------------------------------------
    def observe(self, spans: list[SpanRow], org: int = 1) -> None:
        """Buffer spans (called from the OTel/l7 write paths)."""
        now = _time.monotonic()
        to_close: list[tuple[int, str, list[SpanRow]]] = []
        with self._lock:
            for s in spans:
                if not s.trace_id:
                    continue
                self.counters["spans_in"] += 1
                key = (org, s.trace_id)
                entry = self._open.get(key)
                if entry is None:
                    if len(self._open) >= self.max_traces:
                        # shed the stalest open trace (close it early)
                        old_key = min(self._open, key=lambda k: self._open[k][1])
                        old_spans, _t = self._open.pop(old_key)
                        self.counters["traces_evicted"] += 1
                        to_close.append((old_key[0], old_key[1], old_spans))
                    entry = ([], now)
                    self._open[key] = entry
                if len(entry[0]) >= self.max_spans_per_trace:
                    self.counters["spans_dropped"] += 1
                    continue
                entry[0].append(s)
                self._open[key] = (entry[0], now)
        for org_id, _tid, spans_ in to_close:
            self._write_tree(org_id, spans_)

    # -- close side -----------------------------------------------------
    def tick(self, now: float | None = None) -> int:
        """Close traces quiet for close_after_s; returns trees written."""
        now = _time.monotonic() if now is None else now
        closed = []
        with self._lock:
            for key in list(self._open):
                spans, last = self._open[key]
                if now - last >= self.close_after_s:
                    del self._open[key]
                    closed.append((key[0], spans))
        for org_id, spans in closed:
            self._write_tree(org_id, spans)
        return len(closed)

    def drain(self) -> int:
        """Close everything (shutdown)."""
        with self._lock:
            items = [(k[0], s) for k, (s, _t) in self._open.items()]
            self._open.clear()
        for org_id, spans in items:
            self._write_tree(org_id, spans)
        return len(items)

    def flush(self) -> None:
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.flush()

    def stop(self) -> None:
        self.drain()
        with self._lock:
            writers = list(self._writers.values())
            self._writers.clear()
        for w in writers:
            w.stop()

    # -- internals ------------------------------------------------------
    def _writer(self, org: int) -> TableWriter:
        db = org_db(FLOW_LOG_DB, org)
        with self._lock:
            w = self._writers.get(db)
            if w is None:
                w = TableWriter(self.store, db, TRACE_TREE_SCHEMA, **self.writer_args)
                self._writers[db] = w
            return w

    # storage column width for encoded_span_list (TRACE_TREE_SCHEMA U4096);
    # numpy would truncate longer strings SILENTLY, leaving undecodable
    # JSON — so oversized trees shed their deepest nodes until they fit.
    MAX_ENCODED = 4096

    def _shrink_encode(self, tree) -> str:
        """Encode within MAX_ENCODED, shedding deepest-level nodes first.

        Keeping a prefix of the (level, index)-sorted node order always
        keeps every kept node's parent (a parent's level is strictly
        smaller), so reindexed trees stay well-formed."""
        import dataclasses as _dc

        encoded = tree.encode()
        order = sorted(range(len(tree.nodes)), key=lambda i: (tree.nodes[i].level, i))
        k = len(order)
        while len(encoded) > self.MAX_ENCODED and k > 1:
            k = max(1, (k * 4) // 5)
            keep = order[:k]
            remap = {old: new for new, old in enumerate(keep)}
            nodes = [
                _dc.replace(
                    tree.nodes[old],
                    parent_node_index=remap.get(tree.nodes[old].parent_node_index, -1),
                )
                for old in keep
            ]
            encoded = TraceTree(tree.time, tree.trace_id, nodes).encode()
        if k < len(order):
            with self._lock:
                self.counters["nodes_shed_oversize"] = (
                    self.counters.get("nodes_shed_oversize", 0)
                    + (len(order) - k)
                )
        return encoded

    def _write_tree(self, org: int, spans: list[SpanRow]) -> None:
        tree = assemble_trace(spans)
        if tree is None:
            return
        encoded = self._shrink_encode(tree)
        self._writer(org).put(
            {
                "time": np.array([tree.time], np.uint32),
                "search_index": np.array([tree.search_index], np.uint64),
                "trace_id": np.array([tree.trace_id]),
                "encoded_span_list": np.array([encoded]),
            }
        )
        with self._lock:
            self.counters["traces_closed"] += 1
