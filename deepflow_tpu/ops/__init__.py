from .hashing import fingerprint64, fmix32
from .segment import Grouped, groupby_reduce

__all__ = ["fingerprint64", "fmix32", "Grouped", "groupby_reduce"]
