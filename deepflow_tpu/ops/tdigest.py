"""t-digest centroids — compact percentile export format.

The streaming path accumulates log-binned histograms (ops/histogram.py);
at window close each group's histogram is *compressed* into a fixed-size
t-digest: C centroids whose mass allocation follows the arcsine scale
function k(q) = 1/2 + asin(2q−1)/π, giving fine resolution at the tails
(p99/p999) and coarse resolution mid-distribution — the classic t-digest
trade. All steps are sort/cumsum/segment_sum with static shapes, so the
compressor vmaps over groups and jits cleanly; merge = concatenate + re-
compress, which is associative up to the digest's accuracy guarantee.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import LogHistSpec


def _kscale(q: jnp.ndarray) -> jnp.ndarray:
    q = jnp.clip(q, 0.0, 1.0)
    return 0.5 + jnp.arcsin(2.0 * q - 1.0) / math.pi


@partial(jax.jit, static_argnames=("compression",))
def tdigest_compress(means: jnp.ndarray, weights: jnp.ndarray, compression: int = 64):
    """(means [n], weights [n]) → (means [C], weights [C]).

    Zero-weight inputs are ignored. Output centroids are mean-sorted with
    zero-weight padding at unused tail positions.
    """
    n = means.shape[0]
    c = compression
    w = jnp.maximum(weights.astype(jnp.float32), 0.0)
    # sort by mean; zero-weight rows pushed to the end via +inf key
    key = jnp.where(w > 0, means.astype(jnp.float32), jnp.inf)
    key, m_s, w_s = lax.sort((key, means.astype(jnp.float32), w), num_keys=1)
    total = jnp.sum(w_s)
    cum = jnp.cumsum(w_s)
    q_mid = (cum - 0.5 * w_s) / jnp.maximum(total, 1.0)
    bucket = jnp.floor(_kscale(q_mid) * c).astype(jnp.int32)
    bucket = jnp.clip(bucket, 0, c - 1)
    bucket = jnp.where(w_s > 0, bucket, c)  # dropped
    out_w = jax.ops.segment_sum(w_s, bucket, num_segments=c)
    out_wm = jax.ops.segment_sum(w_s * m_s, bucket, num_segments=c)
    out_m = jnp.where(out_w > 0, out_wm / jnp.maximum(out_w, 1e-30), 0.0)
    return out_m, out_w


def tdigest_from_loghist(hist: jnp.ndarray, spec: LogHistSpec, compression: int = 64):
    """[G, B] histogram plane → ([G, C] means, [G, C] weights)."""
    centers = spec.vmin * jnp.power(
        jnp.float32(spec.gamma), jnp.arange(spec.bins, dtype=jnp.float32) + 0.5
    )
    f = jax.vmap(lambda h: tdigest_compress(centers, h, compression))
    return f(hist.astype(jnp.float32))


@jax.jit
def tdigest_quantile(means: jnp.ndarray, weights: jnp.ndarray, qs: jnp.ndarray) -> jnp.ndarray:
    """Interpolated quantiles from one digest ([C] means/weights, [Q] qs)."""
    total = jnp.sum(weights)
    cum = jnp.cumsum(weights) - 0.5 * weights
    q_cent = cum / jnp.maximum(total, 1e-30)
    # Zero-weight padding centroids must not drag the interpolation: park
    # them beyond q=1 *with the largest real mean* so tail queries
    # saturate at the true maximum instead of sliding toward mean=0.
    real = weights > 0
    max_mean = jnp.max(jnp.where(real, means, -jnp.inf))
    q_cent = jnp.where(real, q_cent, 2.0)
    means_r = jnp.where(real, means, max_mean)
    order = jnp.argsort(q_cent)
    out = jnp.interp(qs, q_cent[order], means_r[order])
    return jnp.where(total > 0, out, 0.0)


def tdigest_merge(m1, w1, m2, w2, compression: int = 64):
    """Merge two digests (concat + re-compress)."""
    return tdigest_compress(
        jnp.concatenate([m1, m2]), jnp.concatenate([w1, w2]), compression
    )
