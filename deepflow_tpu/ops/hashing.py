"""Vectorized 64-bit fingerprints built from uint32 lanes.

TPUs have no native 64-bit integer path worth using, so the group-by key is
a pair of u32 lanes produced by two murmur3-style column folds with
different seeds. This replaces the reference's hand-packed 128-bit
`fast_id` (collector.rs:196-330): instead of packing bit-fields per Code
combination, we fingerprint *all* tag columns (inactive ones zeroed per
Code by the fanout stage), which reproduces StashKey equality with a
2^-64 collision probability per pair.

The same function serves device (jnp) and oracle (np) callers — both
array namespaces implement wrapping uint32 arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_FMIX1 = 0x85EBCA6B
_FMIX2 = 0xC2B2AE35

SEED_HI = 0x9747B28C
SEED_LO = 0x3C6EF372


def _u32(x, xp):
    return xp.asarray(x, dtype=xp.uint32)


def _rotl(x, r: int, xp):
    return (x << xp.uint32(r)) | (x >> xp.uint32(32 - r))


def fmix32(h, xp=jnp):
    """murmur3 32-bit finalizer (avalanche)."""
    h = _u32(h, xp)
    h = h ^ (h >> xp.uint32(16))
    h = h * xp.uint32(_FMIX1)
    h = h ^ (h >> xp.uint32(13))
    h = h * xp.uint32(_FMIX2)
    h = h ^ (h >> xp.uint32(16))
    return h


def _fold(cols, seed: int, xp):
    """murmur3_32 body over a list of [N] u32 columns."""
    n = len(cols)
    h = None
    for c in cols:
        k = _u32(c, xp) * xp.uint32(_C1)
        k = _rotl(k, 15, xp)
        k = k * xp.uint32(_C2)
        if h is None:
            h = xp.full_like(k, xp.uint32(seed))
        h = h ^ k
        h = _rotl(h, 13, xp)
        h = h * xp.uint32(5) + xp.uint32(0xE6546B64)
    h = h ^ xp.uint32(n * 4)
    return fmix32(h, xp)


def fingerprint64(tags, xp=jnp):
    """[N, T] u32 tag matrix → (hi, lo) pair of [N] u32 fingerprints.

    Unrolled over the (static) column count; each step is a handful of VPU
    ops on [N] vectors. Device callers on the hot path should prefer
    `fingerprint64_t` — extracting columns from a row-major [N, T] device
    array is a strided gather on TPU (~100x the cost of the hash itself).
    """
    tags = xp.asarray(tags, dtype=xp.uint32)
    cols = [tags[:, j] for j in range(tags.shape[1])]
    return _fold(cols, SEED_HI, xp), _fold(cols, SEED_LO, xp)


def fingerprint64_t(tags_t, xp=jnp):
    """Column-major twin: [T, N] u32 → (hi, lo) [N] u32. Identical hash
    values to `fingerprint64` on the transposed matrix; each column is a
    contiguous [N] vector so the fold stays pure VPU work."""
    tags_t = xp.asarray(tags_t, dtype=xp.uint32)
    cols = [tags_t[j] for j in range(tags_t.shape[0])]
    return _fold(cols, SEED_HI, xp), _fold(cols, SEED_LO, xp)


def fingerprint64_words(words, xp=jnp):
    """Fold a pre-packed word list (datamodel.code.pack_tag_words) into
    the (hi, lo) pair. The packed representation covers the same key
    bits in ~40% fewer fold rounds than the raw column fold — the hot
    paths build the words ONCE and feed both seeds (PERF.md §9d).
    Hash VALUES differ from fingerprint64 on the raw columns; only
    within-path consistency matters (every producer of a given key
    space goes through the same packing plan)."""
    return _fold(words, SEED_HI, xp), _fold(words, SEED_LO, xp)
