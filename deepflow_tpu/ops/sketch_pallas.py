"""Single-pass fused sketch update — the Pallas kernel behind
DEEPFLOW_FUSED_SKETCH (ISSUE 17, tentpole b).

The shared-sort rewrite (aggregator/sketchplane.py) already collapses
the sketch plane's sorts to one; what remains on the XLA path is a
fan of scatters over the sorted batch — HLL register max, count-min
run-head adds, and a segment-max/min pair per top-K hash row. The FPGA
sketch accelerators (PAPERS.md: HLL on FPGA 2005.13332, the streaming
top-K engine 2511.16797) get their throughput from doing all of these
in ONE pass over the stream against on-chip banked state. This kernel
is that shape on the TPU: one sequential sweep over the sorted rows
with the whole plane state resident in VMEM, per-row lanes riding in
SMEM (the `segreduce_pallas.py` perm-in-SMEM idiom).

Per sorted row i (skipping rows the phase mask excludes):

  * HLL:   hll[slot·G + gid, reg] = max(old, rho) — idempotent, so the
           original-vs-sorted order change is invisible;
  * CMS:   at run HEADS only, cms[slot·D + d, col_d] += run_weight —
           one banked add per (window, key) run instead of per row
           (adds commute → totals bit-identical);
  * top-K: a streaming best-challenger table per hash row:
           strictly-greater run weight replaces the bucket's candidate,
           which reproduces the XLA path's first-heaviest-run stable
           tie-break because rows arrive in the shared sort order.

The weighted-MJRTY vote epilogue is NOT in the kernel — both the XLA
presorted path and this kernel feed the same `ops.topk._apply_challengers`,
so the two paths share their tail by construction and the parity pin
(tests/test_sketch_onepass.py) covers exactly the divergent half.

Exactness note on the challenger table: buckets whose heaviest run
weight is 0 report got=False here but got=True (hw=0) on the XLA path.
A zero-weight challenger is provably a vote NO-OP (votes are always
≥ 0: same-key adds 0; a take needs challenged < 0, impossible at
hw = 0), so the applied lanes — the only thing that escapes the step —
are still bit-identical; the fuzz pins lanes, not the intermediates.

Shape guard: the state must fit the VMEM budget and the per-row SMEM
lanes must stay small. Unsupported shapes fall back LOUDLY to the XLA
presorted path — a warning once per shape plus a module counter
(`FUSED_SKETCH_FALLBACKS`, asserted in tier-1) — never silently
(ADVICE.md #2, the m≤LANES stance of segreduce_pallas).

Default OFF until on-chip numbers land (PERF.md §25 reserves the A/B
columns — the §15 flip-the-default convention); interpret-mode parity
runs on CPU in tier-1 either way.
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: rows above this would bloat the SMEM-resident per-row lanes past the
#: scalar memory budget (~13 lanes × 4 B × N)
MAX_FUSED_ROWS = 1 << 15
#: VMEM budget for the resident plane state (HLL + CMS + 5 challenger
#: lanes), conservative slice of the ~16 MB/core VMEM
MAX_STATE_BYTES = 8 << 20

#: count of guarded fallbacks to the XLA presorted path — degradation
#: must be loud and countable, never silent
FUSED_SKETCH_FALLBACKS = 0
_WARNED_SHAPES: set = set()


def fused_sketch_guard(
    n: int, ring: int, g: int, m: int, d_cms: int, w_cms: int,
    d_tk: int, c_tk: int,
) -> bool:
    """Trace-time (static shapes) support check. False → the caller
    takes the XLA presorted path; the miss is warned once per shape and
    counted in FUSED_SKETCH_FALLBACKS."""
    global FUSED_SKETCH_FALLBACKS
    state_bytes = 4 * (
        ring * g * m + ring * d_cms * w_cms + 5 * d_tk * ring * c_tk
    )
    reasons = []
    if d_tk < 1:
        reasons.append("top-K lane disabled (nothing to fuse the sort for)")
    if n > MAX_FUSED_ROWS:
        reasons.append(f"batch rows {n} > MAX_FUSED_ROWS {MAX_FUSED_ROWS}")
    if state_bytes > MAX_STATE_BYTES:
        reasons.append(
            f"plane state {state_bytes} B > MAX_STATE_BYTES {MAX_STATE_BYTES}"
        )
    if not reasons:
        return True
    FUSED_SKETCH_FALLBACKS += 1
    key = (n, ring, g, m, d_cms, w_cms, d_tk, c_tk)
    if key not in _WARNED_SHAPES:
        _WARNED_SHAPES.add(key)
        warnings.warn(
            "DEEPFLOW_FUSED_SKETCH: falling back to the XLA presorted "
            "path for shape %r: %s" % (key, "; ".join(reasons)),
            stacklevel=2,
        )
    return False


def _fused_kernel(
    s_slot, s_gid, s_reg, s_rho, s_live, w_head, rw,
    cms_slot, tk_col, s_hi, s_lo, s_ia, s_ib,
    hll_in, cms_in,  # alias the hll_ref/cms_ref outputs — same storage
    hll_ref, cms_ref, bw_ref, bh_ref, bl_ref, ba_ref, bb_ref,
    *, n: int, g: int, d_cms: int, w_cms: int, d_tk: int, c_tk: int,
):
    """One sequential sweep over the sorted batch. State refs:
    hll [R·G, m] (aliased in/out), cms [R·D, W] (aliased in/out),
    challenger tables [d, R·C] (fresh outputs, built here)."""
    del hll_in, cms_in  # input_output_aliases: state reads go via out refs
    z = lambda ref: jnp.zeros(ref.shape, ref.dtype)
    bw_ref[:] = z(bw_ref)
    bh_ref[:] = z(bh_ref)
    bl_ref[:] = z(bl_ref)
    ba_ref[:] = z(ba_ref)
    bb_ref[:] = z(bb_ref)

    def body(i, carry):
        slot = s_slot[i]
        live = s_live[i] != 0

        @pl.when(live)
        def _():
            # HLL register max (idempotent — order-free)
            row = slot * g + s_gid[i]
            reg = s_reg[i]
            old = hll_ref[row, reg]
            hll_ref[row, reg] = jnp.maximum(old, s_rho[i])

        # CMS run-head add: w_head is 0 off-head / for fully-masked
        # runs, so gating on it alone preserves the oracle's totals
        @pl.when(w_head[i] != 0)
        def _():
            for dd in range(d_cms):
                crow = slot * d_cms + dd
                ccol = cms_slot[dd, i]
                cms_ref[crow, ccol] = cms_ref[crow, ccol] + w_head[i]

        # streaming best-challenger per hash row: strictly greater run
        # weight replaces — first-seen wins ties, which IS the XLA
        # path's min-position stable tie-break under the shared order
        @pl.when(live)
        def _():
            for rr in range(d_tk):
                b = slot * c_tk + tk_col[rr, i]

                @pl.when(rw[i] > bw_ref[rr, b])
                def _():
                    bw_ref[rr, b] = rw[i]
                    bh_ref[rr, b] = s_hi[i]
                    bl_ref[rr, b] = s_lo[i]
                    ba_ref[rr, b] = s_ia[i]
                    bb_ref[rr, b] = s_ib[i]

        return carry

    jax.lax.fori_loop(0, n, body, 0)


def sketch_update_fused(
    hll, cms, *, tk_shape, s_slot, s_gid, s_reg, s_rho, s_mask, w_head,
    rw, cms_slots, s_hi, s_lo, s_ia, s_ib,
):
    """hll [R, G, m] i32 and cms [R, D, W] i32 updated in one fused
    pass over the SORTED batch lanes; returns (hll, cms, challengers)
    where `challengers` is the `ops.topk._apply_challengers` input list
    (one (got, h_hi, h_lo, h_ia, h_ib, hw) per hash row, flat [R·C]).

    `tk_shape` is the static (topk_rows, topk_cols) pair. `cms_slots`
    [D, N] carries the ops.cms.row_slots values (they already embed the
    d·W row offset — they split into the kernel's [R·D, W] banked
    layout here). Callers pass shapes through `fused_sketch_guard`
    first."""
    # tk_col derives here (not at the call site) so the kernel and the
    # XLA presorted path share the same bucket_cols avalanche
    from .topk import bucket_cols

    ring, g, m = hll.shape
    d_cms, w_cms = cms.shape[1], cms.shape[2]
    d_tk, c_tk = tk_shape
    n = s_slot.shape[0]

    i32 = lambda x: jnp.asarray(x).astype(jnp.int32)
    # strip the per-depth w·d offset: the banked layout addresses
    # (slot·D + d, col) instead of flat slot·D·W + row_slots
    offs = (jnp.arange(d_cms, dtype=jnp.int32) * w_cms)[:, None]
    cms_col = i32(cms_slots) - offs
    tk_col = jnp.stack([bucket_cols(s_hi, s_lo, r, c_tk) for r in range(d_tk)])

    out_shape = [
        jax.ShapeDtypeStruct((ring * g, m), jnp.int32),
        jax.ShapeDtypeStruct((ring * d_cms, w_cms), jnp.int32),
        jax.ShapeDtypeStruct((d_tk, ring * c_tk), jnp.int32),
        jax.ShapeDtypeStruct((d_tk, ring * c_tk), jnp.uint32),
        jax.ShapeDtypeStruct((d_tk, ring * c_tk), jnp.uint32),
        jax.ShapeDtypeStruct((d_tk, ring * c_tk), jnp.uint32),
        jax.ShapeDtypeStruct((d_tk, ring * c_tk), jnp.uint32),
    ]
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    hll2, cms2, bw, bh, bl, ba, bb = pl.pallas_call(
        partial(
            _fused_kernel, n=n, g=g, d_cms=d_cms, w_cms=w_cms,
            d_tk=d_tk, c_tk=c_tk,
        ),
        in_specs=[
            smem(),  # s_slot
            smem(),  # s_gid
            smem(),  # s_reg
            smem(),  # s_rho
            smem(),  # s_live
            smem(),  # w_head
            smem(),  # rw
            smem(),  # cms_col [D, N]
            smem(),  # tk_col [d, N]
            smem(),  # s_hi
            smem(),  # s_lo
            smem(),  # s_ia
            smem(),  # s_ib
            vmem(),  # hll state
            vmem(),  # cms state
        ],
        out_specs=[vmem() for _ in out_shape],
        out_shape=out_shape,
        # the plane state updates in place: inputs 13/14 alias outputs
        # 0/1 (positions count pallas_call operands, kernel order)
        input_output_aliases={13: 0, 14: 1},
        interpret=jax.default_backend() == "cpu",
    )(
        i32(s_slot), i32(s_gid), i32(s_reg), i32(s_rho),
        i32(s_mask), i32(w_head), i32(rw), cms_col, tk_col,
        jnp.asarray(s_hi, jnp.uint32), jnp.asarray(s_lo, jnp.uint32),
        jnp.asarray(s_ia, jnp.uint32), jnp.asarray(s_ib, jnp.uint32),
        hll.reshape(ring * g, m), cms.reshape(ring * d_cms, w_cms),
    )

    challengers = []
    for r in range(d_tk):
        got = bw[r] > 0
        hw = jnp.maximum(bw[r], 0)
        challengers.append((got, bh[r], bl[r], ba[r], bb[r], hw))
    return (
        hll2.reshape(ring, g, m),
        cms2.reshape(ring, d_cms, w_cms),
        challengers,
    )
