"""Sort-based group-by reduction — the aggregation hot loop.

Replaces the reference's HashMap stash merge (`Stash::add`,
collector.rs:810; `SubQuadGen::inject_flow`, quadruple_generator.rs:544)
with a fully static-shape XLA pattern:

    lax.sort((slot, key_hi, key_lo, iota), num_keys=3)
      → head flags from key-change deltas → segment ids (one cumsum)
      → segment_sum / segment_max with sorted ids, num_segments = cap
      → representative-row gathers only at the ≤cap segment heads

Layout at the interface: tags stay column-major ([T, N] with the row
axis minor — it maps rows onto the 128-wide vector lanes and keeps
column selection free); the METER payload is row-major [N, M] since r6,
because the reduce consumes rows — one row-gather of [N, M] moves M
contiguous elements per index (~17x better than M strided
lane-gathers), and the fused Pallas path (segreduce_pallas.py) streams
rows through the sort permutation by per-row DMA, which needs the
original array row-contiguous. The batch pre-reduce hot path produces
[N, M] natively (FlowBatch.meters), so no transpose is ever
materialized at 2M rows; the stash fold transposes its column-major
state at the call site, where XLA folds it into the downstream
gather/copy.

Kernel selection is measurement-driven (PERF.md, round 4, v5e):
  * round-3 segmented `associative_scan`: 5.4-35 ms at 32k rows and
    superlinear compile times — replaced by this kernel.
  * round-2 row-major segment ops: 4.9 ms at 32k; this kernel is the
    same reduction with the gathers restricted to segment heads and
    `num_segments` capped at the stash capacity instead of N.
  * the sort itself costs 3.3 ms at 32k but only 4.0 ms at 131k — it is
    overhead-dominated at batch sizes, which is why the stash
    accumulates raw rows and amortizes ONE big sort over many batches
    (see aggregator/stash.py).

Everything is O(N log N) compare-exchange on u32 lanes plus linear
segment passes — no data-dependent shapes, no scatter (XLA lowers
scatter poorly on TPU: a 65k-row scatter-add measured 4 ms, as much as
the whole sort).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Sentinel slot value for invalid rows: sorts after every real window.
SENTINEL_SLOT = np.uint32(0xFFFFFFFF)


def _use_pallas_reduce() -> bool:
    """The Pallas suffix-scan reduce replaces the per-row scatter
    segment ops on TPU (PERF.md §9); XLA ops stay for CPU (fast there,
    and the conformance suite pins the two paths equal).
    DEEPFLOW_SEGREDUCE=pallas|xla overrides."""
    mode = os.environ.get("DEEPFLOW_SEGREDUCE", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    return jax.default_backend() not in ("cpu",)


def _use_fused_gather() -> bool:
    """On the pallas path, gather meter rows INSIDE the kernel via
    permutation-indexed DMA (PERF.md §9d) instead of a standalone
    `take` pass. DEEPFLOW_FUSED_GATHER=0 re-enables the pre-gather
    variant for on-chip A/B runs."""
    return os.environ.get("DEEPFLOW_FUSED_GATHER", "1") != "0"


def _use_merge_scatter() -> bool:
    """Merged-order construction for the incremental merge-fold
    (aggregator/stash.py): default is a single-key `lax.sort` over the
    precomputed merge ranks (2 lanes, 1 u32 key — ~a third of the
    compare work of the 3-key fold sort it replaces, and the primitive
    this repo trusts on TPU). DEEPFLOW_MERGE_SCATTER=1 switches to the
    truly-linear one-scatter construction for on-chip A/B — scatter
    lowers poorly on TPU historically (module docstring), but this one
    is a plain unique-index i32 scatter, not a scatter-add, so it is
    worth measuring."""
    return os.environ.get("DEEPFLOW_MERGE_SCATTER", "0") == "1"


def _use_shared_sort() -> bool:
    """One-pass sketch fold (ISSUE 17): the sketch plane computes the
    batch's keyed sort permutation ONCE per fused dispatch and threads
    the sorted lanes through both fold phases, every top-K hash row and
    the count-min run dedup — 4 sorts/dispatch → 1 with sketch+topk ON.
    Bit-exact vs the multi-sort oracle (pinned in
    tests/test_sketch_onepass.py), so it defaults ON.
    DEEPFLOW_SHARED_SORT=0 restores the per-consumer sorts for A/B."""
    return os.environ.get("DEEPFLOW_SHARED_SORT", "1") != "0"


def _use_fused_sketch() -> bool:
    """On the shared-sort path, run the HLL/CMS/top-K challenger update
    as ONE Pallas pass over the sorted batch (ops/sketch_pallas.py)
    instead of the XLA presorted path. Default OFF until on-chip
    numbers land (the §15 flip-the-default convention); interpret-mode
    parity is pinned on CPU either way. DEEPFLOW_FUSED_SKETCH=1
    enables."""
    return os.environ.get("DEEPFLOW_FUSED_SKETCH", "0") == "1"


_U32_MAX = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Grouped:
    """Result of one group-by reduce over N input rows. Payloads are
    column-major; key/flag lanes have leading dim `cap` (the requested
    output capacity); `seg_valid` marks live segments (a prefix —
    segments are emitted in sorted key order)."""

    slot: jnp.ndarray  # [cap] u32 — window index per segment
    key_hi: jnp.ndarray  # [cap] u32
    key_lo: jnp.ndarray  # [cap] u32
    tags: jnp.ndarray  # [T, cap] u32 — representative (first) row's tags
    meters: jnp.ndarray  # [M, cap] f32 — reduced
    seg_valid: jnp.ndarray  # [cap] bool
    num_segments: jnp.ndarray  # scalar i32 — live segment count (may exceed cap)


def groupby_reduce(
    slot,
    key_hi,
    key_lo,
    tags_t,
    meters_rows,
    valid,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
    out_capacity: int | None = None,
) -> Grouped:
    """Group rows by (slot, key_hi, key_lo) and reduce meters.

    Args:
      slot/key_hi/key_lo: [N] u32. Invalid rows are re-keyed to sentinel.
      tags_t: [T, N] u32; meters_rows: [N, M] f32 ROW-major (one meter
        row per record — see the module docstring on layout); valid:
        [N] bool.
      sum_cols / max_cols: static np arrays of meter row indices, a
        partition of range(M) (from MeterSchema.sum_mask/max_mask).
      out_capacity: static output size; segments beyond it (in ascending
        (slot, key) order) are dropped from the output but still counted
        in num_segments so callers can account overflow. Defaults to N.
    """
    n = slot.shape[0]

    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    key_hi = jnp.where(valid, key_hi, jnp.uint32(_U32_MAX))
    key_lo = jnp.where(valid, key_lo, jnp.uint32(_U32_MAX))

    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, key_hi, key_lo, iota), num_keys=3)
    return groupby_reduce_sorted(
        s_slot, s_hi, s_lo, perm, tags_t, meters_rows,
        sum_cols, max_cols, out_capacity=out_capacity,
    )


def groupby_reduce_sorted(
    s_slot,
    s_hi,
    s_lo,
    perm,
    tags_t,
    meters_rows,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
    out_capacity: int | None = None,
) -> Grouped:
    """The post-sort phase of `groupby_reduce`, for callers that already
    hold the key lanes in sorted order — the incremental merge-fold
    (aggregator/stash.py) constructs them with a rank-merge instead of a
    full keyed re-sort, then reuses this exact reduce so the two fold
    paths cannot drift.

    Args:
      s_slot/s_hi/s_lo: [N] u32 key lanes in ascending (slot, hi, lo)
        order, PRE-normalized — invalid rows keyed
        (SENTINEL_SLOT, U32_MAX, U32_MAX) so they sort last.
      perm: [N] i32 mapping sorted position → original row index into
        tags_t ([T, N]) / meters_rows ([N, M]), exactly what `lax.sort`
        with an iota payload produces.
    """
    n = s_slot.shape[0]
    m = meters_rows.shape[1]
    cap = int(out_capacity) if out_capacity is not None else n
    sum_cols = np.asarray(sum_cols, np.int32)
    max_cols = np.asarray(max_cols, np.int32)

    head = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
        ]
    )
    # Sentinel rows sort after every live row, so live rows are a prefix
    # and live segments are exactly segment ids [0, num_seg).
    live_row = s_slot != jnp.uint32(SENTINEL_SLOT)
    live_head = head & live_row
    num_seg = jnp.sum(live_head.astype(jnp.int32))
    seg_id = jnp.cumsum(head.astype(jnp.int32)) - 1  # [N] ascending
    # Dead rows get an out-of-range id so every segment op drops them.
    # It must be `n`, not `cap`: live overflow segments carry ids in
    # [cap, num_seg) and the id sequence must stay ascending for the
    # indices_are_sorted hint below to be honest.
    seg_id = jnp.where(live_row, seg_id, n)

    # First sorted position of each kept segment: seg_id is ascending by
    # construction, so first occurrence = binary search. A segment_min
    # here measured ~24 ms at 2M rows (r5 bisect, stage G−F) because
    # TPU scatter reductions cost per ROW; searchsorted is O(cap·log N).
    first_pos = jnp.searchsorted(seg_id, jnp.arange(cap, dtype=jnp.int32))

    # Full-width segment ops + per-column select, NOT subset-indexed
    # ops: `meters_rows[:, sum_cols]` materializes a strided copy of
    # [N, |subset|] before each op, which costs more than running the
    # op over all M lanes and discarding the unwanted half (measured
    # ~16% off the whole fold at 588k rows — PERF.md §7b follow-up).
    # On TPU both ops fuse into ONE scatter-free Pallas suffix-scan
    # pass (segreduce_pallas.py, PERF.md §9).
    if m and _use_pallas_reduce():
        from .segreduce_pallas import sorted_segment_sum_max

        if _use_fused_gather():
            # the kernel reads rows THROUGH the sort permutation — no
            # standalone gather pass ever materializes the sorted payload
            ps, pm = sorted_segment_sum_max(
                meters_rows, seg_id, cap, first_pos, perm=perm
            )
        else:
            ps, pm = sorted_segment_sum_max(
                jnp.take(meters_rows, perm, axis=0), seg_id, cap, first_pos
            )
        if not max_cols.size:
            out_meters = ps.T
        elif not sum_cols.size:
            out_meters = pm.T
        else:
            is_sum = np.zeros((m,), bool)
            is_sum[sum_cols] = True
            out_meters = jnp.where(jnp.asarray(is_sum)[None, :], ps, pm).T
    elif m:
        # One row-gather moves all M meter lanes of a row at once.
        sorted_rows = jnp.take(meters_rows, perm, axis=0)  # [N, M]
        # (segment_max yields -inf for empty segments; the seg_valid mask
        # below zeroes those columns, so no isfinite rewrite — it would
        # also mask NaNs from genuinely corrupt meters.)
        ps = (
            jax.ops.segment_sum(
                sorted_rows, seg_id, num_segments=cap, indices_are_sorted=True
            )
            if sum_cols.size
            else None
        )
        pm = (
            jax.ops.segment_max(
                sorted_rows, seg_id, num_segments=cap, indices_are_sorted=True
            )
            if max_cols.size
            else None
        )
        if pm is None:
            out_meters = ps.T
        elif ps is None:
            out_meters = pm.T
        else:
            is_sum = np.zeros((m,), bool)
            is_sum[sum_cols] = True
            out_meters = jnp.where(jnp.asarray(is_sum)[None, :], ps, pm).T  # [M, cap]
    else:
        out_meters = jnp.zeros((0, cap), meters_rows.dtype)

    k = jnp.arange(cap, dtype=jnp.int32)
    seg_valid = k < jnp.minimum(num_seg, cap)
    fp = jnp.where(seg_valid, first_pos, 0).astype(jnp.int32)

    out_slot = jnp.where(seg_valid, jnp.take(s_slot, fp), jnp.uint32(SENTINEL_SLOT))
    out_hi = jnp.where(seg_valid, jnp.take(s_hi, fp), 0)
    out_lo = jnp.where(seg_valid, jnp.take(s_lo, fp), 0)
    rep_orig = jnp.take(perm, fp)
    out_tags = jnp.where(seg_valid[None, :], jnp.take(tags_t, rep_orig, axis=1), 0)
    out_meters = jnp.where(seg_valid[None, :], out_meters, 0)

    return Grouped(
        slot=out_slot,
        key_hi=out_hi,
        key_lo=out_lo,
        tags=out_tags,
        meters=out_meters,
        seg_valid=seg_valid,
        num_segments=num_seg,
    )


# ---------------------------------------------------------------------------
# Rank-merge primitives for the incremental merge-fold (ISSUE 5).
#
# Two sequences already sorted by the same lexicographic (slot, hi, lo)
# u32 triple merge in O(A log S + S log A) comparisons: each element's
# merged position ("merge rank") is its own index plus the count of
# other-sequence elements before it, found by a vectorized binary
# search. Ranks are a permutation of [0, S+A) by construction, so the
# merged order follows from one cheap single-key sort (or one scatter —
# `_use_merge_scatter`), never a full keyed re-sort of both sequences.


def _lex_less(a_sl, a_hi, a_lo, b_sl, b_hi, b_lo):
    """Elementwise lexicographic (slot, hi, lo) u32 triple compare."""
    return (a_sl < b_sl) | (
        (a_sl == b_sl) & ((a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo)))
    )


def lex_searchsorted(keys, queries, *, side: str):
    """`jnp.searchsorted` generalized to a lexicographic u32 triple.

    keys: (slot, hi, lo) arrays [N], ascending under `_lex_less`.
    queries: (slot, hi, lo) arrays [Q]. Returns [Q] i32 insertion
    points (side="left": count of keys strictly less; side="right":
    count of keys less-or-equal). Vectorized binary search — a static
    ceil(log2(N+1)) unroll of one 3-lane gather + compare per step, so
    Q queries cost O(Q log N) instead of packing 96-bit keys into a
    scalar the 32-bit lanes cannot hold.
    """
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    k_sl, k_hi, k_lo = keys
    q_sl, q_hi, q_lo = queries
    n = int(k_sl.shape[0])
    lo = jnp.zeros(q_sl.shape, jnp.int32)
    if n == 0:
        return lo
    hi = jnp.full(q_sl.shape, n, jnp.int32)
    for _ in range(n.bit_length()):
        mid = (lo + hi) >> 1
        m_sl = jnp.take(k_sl, mid)
        m_hi = jnp.take(k_hi, mid)
        m_lo = jnp.take(k_lo, mid)
        if side == "left":
            go_right = _lex_less(m_sl, m_hi, m_lo, q_sl, q_hi, q_lo)
        else:
            go_right = ~_lex_less(q_sl, q_hi, q_lo, m_sl, m_hi, m_lo)
        active = lo < hi
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def merge_ranks(first, second):
    """Merged positions for two key-sorted (slot, hi, lo) sequences.

    Tie-break: `first` elements precede equal `second` elements, and
    each sequence keeps its internal order — exactly the order a STABLE
    `lax.sort` over their concatenation (first then second) produces,
    which is what makes the merge-fold bit-exact against the full-sort
    fold. Returns (rank_first [S], rank_second [A]), together a
    permutation of [0, S+A).
    """
    nf = int(first[0].shape[0])
    ns = int(second[0].shape[0])
    rank_f = jnp.arange(nf, dtype=jnp.int32) + lex_searchsorted(
        second, first, side="left"
    )
    rank_s = jnp.arange(ns, dtype=jnp.int32) + lex_searchsorted(
        first, second, side="right"
    )
    return rank_f, rank_s


def merge_order(rank_f, rank_s, payload_f, payload_s):
    """Invert merge ranks into a gather order: returns [S+A] i32 where
    position p holds the payload of the element whose merged rank is p.
    Default: single-u32-key 2-lane sort; DEEPFLOW_MERGE_SCATTER=1 uses
    the linear unique-index scatter instead (on-chip A/B knob)."""
    rank = jnp.concatenate([rank_f, rank_s])
    payload = jnp.concatenate([payload_f, payload_s]).astype(jnp.int32)
    if _use_merge_scatter():
        return (
            jnp.zeros((rank.shape[0],), jnp.int32)
            .at[rank]
            .set(payload, unique_indices=True)
        )
    _, order = lax.sort((rank.astype(jnp.uint32), payload), num_keys=1)
    return order
