"""Sort-based group-by reduction — the aggregation hot loop.

Replaces the reference's HashMap stash merge (`Stash::add`,
collector.rs:810; `SubQuadGen::inject_flow`, quadruple_generator.rs:544)
with a fully static-shape XLA pattern:

    lax.sort((slot, key_hi, key_lo, iota), num_keys=3)
      → segment ids from key-change flags (cumsum)
      → segment_sum / segment_max per meter column group
      → representative-row gather for tag columns

Everything is O(N log N) compare-exchange on u32 lanes plus a few linear
passes — no data-dependent shapes, no serial probing.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Sentinel slot value for invalid rows: sorts after every real window.
SENTINEL_SLOT = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Grouped:
    """Result of one group-by reduce over N input rows. All arrays have
    leading dim N (max possible segments); `seg_valid` marks live segments
    (prefix — segments are emitted in sorted key order)."""

    slot: jnp.ndarray  # [N] u32 — window index per segment
    key_hi: jnp.ndarray  # [N] u32
    key_lo: jnp.ndarray  # [N] u32
    tags: jnp.ndarray  # [N, T] u32 — representative (first) row's tags
    meters: jnp.ndarray  # [N, M] f32 — reduced
    seg_valid: jnp.ndarray  # [N] bool
    num_segments: jnp.ndarray  # scalar i32 — live segment count


def groupby_reduce(
    slot,
    key_hi,
    key_lo,
    tags,
    meters,
    valid,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
) -> Grouped:
    """Group rows by (slot, key_hi, key_lo) and reduce meters.

    Args:
      slot/key_hi/key_lo: [N] u32. Invalid rows are re-keyed to sentinel.
      tags: [N, T] u32; meters: [N, M] f32; valid: [N] bool.
      sum_cols / max_cols: static np arrays of column indices, a partition
        of range(M) (from MeterSchema.sum_mask/max_mask).
    """
    n = slot.shape[0]
    m = meters.shape[1]
    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    key_hi = jnp.where(valid, key_hi, jnp.uint32(0xFFFFFFFF))
    key_lo = jnp.where(valid, key_lo, jnp.uint32(0xFFFFFFFF))

    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, key_hi, key_lo, iota), num_keys=3)

    first = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
        ]
    )
    seg_id = jnp.cumsum(first.astype(jnp.int32)) - 1  # [N], ascending

    meters_sorted = jnp.take(meters, perm, axis=0)
    reduced = jnp.zeros((n, m), dtype=meters.dtype)
    if sum_cols.size:
        part = jax.ops.segment_sum(meters_sorted[:, sum_cols], seg_id, num_segments=n)
        reduced = reduced.at[:, sum_cols].set(part)
    if max_cols.size:
        part = jax.ops.segment_max(meters_sorted[:, max_cols], seg_id, num_segments=n)
        # segment_max yields -inf for empty segments; zero them.
        part = jnp.where(jnp.isfinite(part), part, 0.0)
        reduced = reduced.at[:, max_cols].set(part)

    # Representative row (first in sorted order) per segment → tags.
    rep_sorted_pos = jax.ops.segment_min(iota, seg_id, num_segments=n)
    rep_sorted_pos = jnp.where(rep_sorted_pos >= n, 0, rep_sorted_pos)  # empty segs
    rep_orig = jnp.take(perm, rep_sorted_pos)
    tags_out = jnp.take(tags, rep_orig, axis=0)

    # Per-segment keys: value at the representative position.
    slot_out = jnp.take(s_slot, rep_sorted_pos)
    hi_out = jnp.take(s_hi, rep_sorted_pos)
    lo_out = jnp.take(s_lo, rep_sorted_pos)

    total_segments = jnp.max(seg_id) + 1
    # Segments holding sentinel rows are invalid; they sort last, so valid
    # segments are exactly the prefix whose slot != SENTINEL.
    seg_index = jnp.arange(n, dtype=jnp.int32)
    seg_valid = (seg_index < total_segments) & (slot_out != SENTINEL_SLOT)
    num_valid = jnp.sum(seg_valid.astype(jnp.int32))

    # Defensive: clear outputs of dead segments so stale tag bytes never
    # masquerade as live keys downstream.
    slot_out = jnp.where(seg_valid, slot_out, jnp.uint32(SENTINEL_SLOT))

    return Grouped(
        slot=slot_out,
        key_hi=hi_out,
        key_lo=lo_out,
        tags=tags_out,
        meters=reduced,
        seg_valid=seg_valid,
        num_segments=num_valid,
    )
