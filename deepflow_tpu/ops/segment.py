"""Sort-based group-by reduction — the aggregation hot loop.

Replaces the reference's HashMap stash merge (`Stash::add`,
collector.rs:810; `SubQuadGen::inject_flow`, quadruple_generator.rs:544)
with a fully static-shape XLA pattern:

    lax.sort((slot, key_hi, key_lo, iota), num_keys=3)
      → head flags from key-change deltas
      → segmented inclusive scans (associative_scan) per merge class
      → boundary gathers at run edges, compaction via one aux sort

Layout is column-major: tag and meter payloads are [T, N] / [M, N] with
the row axis minor. On TPU the minor axis maps to the 128-wide vector
lanes, so every per-column op is a contiguous [N] vector op; the
row-major [N, T] layout this replaced wasted (128-T)/128 of each tile
and made column extraction a strided gather (measured 7.2 ms vs 0.02 ms
for one [40, 128k] gather on v5e — see PERF.md).

Everything is O(N log N) compare-exchange on u32 lanes plus log-depth
scans — no data-dependent shapes, and no scatter anywhere (XLA lowers
scatter poorly on TPU; the one index-construction scatter the v2 kernel
kept was still its bottleneck).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Sentinel slot value for invalid rows: sorts after every real window.
SENTINEL_SLOT = np.uint32(0xFFFFFFFF)

_U32_MAX = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Grouped:
    """Result of one group-by reduce over N input rows. Payloads are
    column-major; key/flag lanes have leading dim `cap` (the requested
    output capacity); `seg_valid` marks live segments (a prefix —
    segments are emitted in sorted key order)."""

    slot: jnp.ndarray  # [cap] u32 — window index per segment
    key_hi: jnp.ndarray  # [cap] u32
    key_lo: jnp.ndarray  # [cap] u32
    tags: jnp.ndarray  # [T, cap] u32 — representative (first) row's tags
    meters: jnp.ndarray  # [M, cap] f32 — reduced
    seg_valid: jnp.ndarray  # [cap] bool
    num_segments: jnp.ndarray  # scalar i32 — live segment count (may exceed cap)


def _seg_scan(vals: jnp.ndarray, head: jnp.ndarray, op) -> jnp.ndarray:
    """Segmented inclusive scan along the minor axis.

    vals: [C, N]; head: [N] bool, True where a new run starts. Returns
    [C, N] where each position holds the reduction of its run's prefix —
    so a run's *last* position holds the run total. log2(N) fused
    elementwise passes; no scatter.
    """
    flags = jnp.broadcast_to(head[None, :], vals.shape)

    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = lax.associative_scan(comb, (vals, flags), axis=1)
    return out


def groupby_reduce(
    slot,
    key_hi,
    key_lo,
    tags_t,
    meters_t,
    valid,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
    out_capacity: int | None = None,
) -> Grouped:
    """Group rows by (slot, key_hi, key_lo) and reduce meters.

    Args:
      slot/key_hi/key_lo: [N] u32. Invalid rows are re-keyed to sentinel.
      tags_t: [T, N] u32; meters_t: [M, N] f32; valid: [N] bool.
      sum_cols / max_cols: static np arrays of meter row indices, a
        partition of range(M) (from MeterSchema.sum_mask/max_mask).
      out_capacity: static output size; segments beyond it (in ascending
        (slot, key) order) are dropped from the output but still counted
        in num_segments so callers can account overflow. Defaults to N.
    """
    n = slot.shape[0]
    m = meters_t.shape[0]
    cap = int(out_capacity) if out_capacity is not None else n
    sum_cols = np.asarray(sum_cols, np.int32)
    max_cols = np.asarray(max_cols, np.int32)

    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    key_hi = jnp.where(valid, key_hi, jnp.uint32(_U32_MAX))
    key_lo = jnp.where(valid, key_lo, jnp.uint32(_U32_MAX))

    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, key_hi, key_lo, iota), num_keys=3)

    head = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
        ]
    )

    meters_sorted = jnp.take(meters_t, perm, axis=1)  # [M, N]

    # Per merge-class segmented scans; reassemble rows in schema order
    # (static permutation — free at trace time).
    scanned_rows: list = [None] * m
    if sum_cols.size:
        part = _seg_scan(meters_sorted[sum_cols, :], head, lambda a, b: a + b)
        for j, c in enumerate(sum_cols):
            scanned_rows[int(c)] = part[j]
    if max_cols.size:
        part = _seg_scan(meters_sorted[max_cols, :], head, jnp.maximum)
        for j, c in enumerate(max_cols):
            scanned_rows[int(c)] = part[j]
    scanned = jnp.stack(scanned_rows) if m else meters_sorted

    # Sentinel rows sort after every live row, so live rows are a prefix.
    live_row = s_slot != jnp.uint32(SENTINEL_SLOT)
    live_head = head & live_row
    num_seg = jnp.sum(live_head.astype(jnp.int32))
    n_live = jnp.sum(live_row.astype(jnp.int32))

    # Compaction without scatter: ascending positions of live run heads
    # via one 1-lane sort (dead lanes key to U32_MAX and sink).
    head_pos = jnp.sort(jnp.where(live_head, iota.astype(jnp.uint32), _U32_MAX))
    # +1: the next head bounds the last kept run; pad so the slice is
    # always in range even at cap == N.
    head_pos = jnp.concatenate([head_pos, jnp.full((1,), _U32_MAX, jnp.uint32)])
    first_pos = head_pos[: cap + 1]

    k = jnp.arange(cap, dtype=jnp.int32)
    seg_valid = k < jnp.minimum(num_seg, cap)
    fp = jnp.where(seg_valid, first_pos[:cap], 0).astype(jnp.int32)
    # A run ends where the next one starts; the globally-last live run
    # ends at the last live row.
    has_next = k + 1 < num_seg
    lp = jnp.where(
        has_next, first_pos[1 : cap + 1].astype(jnp.int32) - 1, n_live - 1
    )
    lp = jnp.clip(jnp.where(seg_valid, lp, 0), 0, n - 1)

    out_slot = jnp.where(seg_valid, jnp.take(s_slot, fp), jnp.uint32(SENTINEL_SLOT))
    out_hi = jnp.where(seg_valid, jnp.take(s_hi, fp), 0)
    out_lo = jnp.where(seg_valid, jnp.take(s_lo, fp), 0)
    rep_orig = jnp.take(perm, fp)
    out_tags = jnp.where(seg_valid[None, :], jnp.take(tags_t, rep_orig, axis=1), 0)
    out_meters = jnp.where(seg_valid[None, :], jnp.take(scanned, lp, axis=1), 0)

    return Grouped(
        slot=out_slot,
        key_hi=out_hi,
        key_lo=out_lo,
        tags=out_tags,
        meters=out_meters,
        seg_valid=seg_valid,
        num_segments=num_seg,
    )
