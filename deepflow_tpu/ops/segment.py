"""Sort-based group-by reduction — the aggregation hot loop.

Replaces the reference's HashMap stash merge (`Stash::add`,
collector.rs:810; `SubQuadGen::inject_flow`, quadruple_generator.rs:544)
with a fully static-shape XLA pattern:

    lax.sort((slot, key_hi, key_lo, iota), num_keys=3)
      → head flags from key-change deltas → segment ids (one cumsum)
      → segment_sum / segment_max with sorted ids, num_segments = cap
      → representative-row gathers only at the ≤cap segment heads

Layout at the interface: tags stay column-major ([T, N] with the row
axis minor — it maps rows onto the 128-wide vector lanes and keeps
column selection free); the METER payload is row-major [N, M] since r6,
because the reduce consumes rows — one row-gather of [N, M] moves M
contiguous elements per index (~17x better than M strided
lane-gathers), and the fused Pallas path (segreduce_pallas.py) streams
rows through the sort permutation by per-row DMA, which needs the
original array row-contiguous. The batch pre-reduce hot path produces
[N, M] natively (FlowBatch.meters), so no transpose is ever
materialized at 2M rows; the stash fold transposes its column-major
state at the call site, where XLA folds it into the downstream
gather/copy.

Kernel selection is measurement-driven (PERF.md, round 4, v5e):
  * round-3 segmented `associative_scan`: 5.4-35 ms at 32k rows and
    superlinear compile times — replaced by this kernel.
  * round-2 row-major segment ops: 4.9 ms at 32k; this kernel is the
    same reduction with the gathers restricted to segment heads and
    `num_segments` capped at the stash capacity instead of N.
  * the sort itself costs 3.3 ms at 32k but only 4.0 ms at 131k — it is
    overhead-dominated at batch sizes, which is why the stash
    accumulates raw rows and amortizes ONE big sort over many batches
    (see aggregator/stash.py).

Everything is O(N log N) compare-exchange on u32 lanes plus linear
segment passes — no data-dependent shapes, no scatter (XLA lowers
scatter poorly on TPU: a 65k-row scatter-add measured 4 ms, as much as
the whole sort).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Sentinel slot value for invalid rows: sorts after every real window.
SENTINEL_SLOT = np.uint32(0xFFFFFFFF)


def _use_pallas_reduce() -> bool:
    """The Pallas suffix-scan reduce replaces the per-row scatter
    segment ops on TPU (PERF.md §9); XLA ops stay for CPU (fast there,
    and the conformance suite pins the two paths equal).
    DEEPFLOW_SEGREDUCE=pallas|xla overrides."""
    mode = os.environ.get("DEEPFLOW_SEGREDUCE", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    return jax.default_backend() not in ("cpu",)


def _use_fused_gather() -> bool:
    """On the pallas path, gather meter rows INSIDE the kernel via
    permutation-indexed DMA (PERF.md §9d) instead of a standalone
    `take` pass. DEEPFLOW_FUSED_GATHER=0 re-enables the pre-gather
    variant for on-chip A/B runs."""
    return os.environ.get("DEEPFLOW_FUSED_GATHER", "1") != "0"

_U32_MAX = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Grouped:
    """Result of one group-by reduce over N input rows. Payloads are
    column-major; key/flag lanes have leading dim `cap` (the requested
    output capacity); `seg_valid` marks live segments (a prefix —
    segments are emitted in sorted key order)."""

    slot: jnp.ndarray  # [cap] u32 — window index per segment
    key_hi: jnp.ndarray  # [cap] u32
    key_lo: jnp.ndarray  # [cap] u32
    tags: jnp.ndarray  # [T, cap] u32 — representative (first) row's tags
    meters: jnp.ndarray  # [M, cap] f32 — reduced
    seg_valid: jnp.ndarray  # [cap] bool
    num_segments: jnp.ndarray  # scalar i32 — live segment count (may exceed cap)


def groupby_reduce(
    slot,
    key_hi,
    key_lo,
    tags_t,
    meters_rows,
    valid,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
    out_capacity: int | None = None,
) -> Grouped:
    """Group rows by (slot, key_hi, key_lo) and reduce meters.

    Args:
      slot/key_hi/key_lo: [N] u32. Invalid rows are re-keyed to sentinel.
      tags_t: [T, N] u32; meters_rows: [N, M] f32 ROW-major (one meter
        row per record — see the module docstring on layout); valid:
        [N] bool.
      sum_cols / max_cols: static np arrays of meter row indices, a
        partition of range(M) (from MeterSchema.sum_mask/max_mask).
      out_capacity: static output size; segments beyond it (in ascending
        (slot, key) order) are dropped from the output but still counted
        in num_segments so callers can account overflow. Defaults to N.
    """
    n = slot.shape[0]
    m = meters_rows.shape[1]
    cap = int(out_capacity) if out_capacity is not None else n
    sum_cols = np.asarray(sum_cols, np.int32)
    max_cols = np.asarray(max_cols, np.int32)

    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    key_hi = jnp.where(valid, key_hi, jnp.uint32(_U32_MAX))
    key_lo = jnp.where(valid, key_lo, jnp.uint32(_U32_MAX))

    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, key_hi, key_lo, iota), num_keys=3)

    head = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
        ]
    )
    # Sentinel rows sort after every live row, so live rows are a prefix
    # and live segments are exactly segment ids [0, num_seg).
    live_row = s_slot != jnp.uint32(SENTINEL_SLOT)
    live_head = head & live_row
    num_seg = jnp.sum(live_head.astype(jnp.int32))
    seg_id = jnp.cumsum(head.astype(jnp.int32)) - 1  # [N] ascending
    # Dead rows get an out-of-range id so every segment op drops them.
    # It must be `n`, not `cap`: live overflow segments carry ids in
    # [cap, num_seg) and the id sequence must stay ascending for the
    # indices_are_sorted hint below to be honest.
    seg_id = jnp.where(live_row, seg_id, n)

    # First sorted position of each kept segment: seg_id is ascending by
    # construction, so first occurrence = binary search. A segment_min
    # here measured ~24 ms at 2M rows (r5 bisect, stage G−F) because
    # TPU scatter reductions cost per ROW; searchsorted is O(cap·log N).
    first_pos = jnp.searchsorted(seg_id, jnp.arange(cap, dtype=jnp.int32))

    # Full-width segment ops + per-column select, NOT subset-indexed
    # ops: `meters_rows[:, sum_cols]` materializes a strided copy of
    # [N, |subset|] before each op, which costs more than running the
    # op over all M lanes and discarding the unwanted half (measured
    # ~16% off the whole fold at 588k rows — PERF.md §7b follow-up).
    # On TPU both ops fuse into ONE scatter-free Pallas suffix-scan
    # pass (segreduce_pallas.py, PERF.md §9).
    if m and _use_pallas_reduce():
        from .segreduce_pallas import sorted_segment_sum_max

        if _use_fused_gather():
            # the kernel reads rows THROUGH the sort permutation — no
            # standalone gather pass ever materializes the sorted payload
            ps, pm = sorted_segment_sum_max(
                meters_rows, seg_id, cap, first_pos, perm=perm
            )
        else:
            ps, pm = sorted_segment_sum_max(
                jnp.take(meters_rows, perm, axis=0), seg_id, cap, first_pos
            )
        if not max_cols.size:
            out_meters = ps.T
        elif not sum_cols.size:
            out_meters = pm.T
        else:
            is_sum = np.zeros((m,), bool)
            is_sum[sum_cols] = True
            out_meters = jnp.where(jnp.asarray(is_sum)[None, :], ps, pm).T
    elif m:
        # One row-gather moves all M meter lanes of a row at once.
        sorted_rows = jnp.take(meters_rows, perm, axis=0)  # [N, M]
        # (segment_max yields -inf for empty segments; the seg_valid mask
        # below zeroes those columns, so no isfinite rewrite — it would
        # also mask NaNs from genuinely corrupt meters.)
        ps = (
            jax.ops.segment_sum(
                sorted_rows, seg_id, num_segments=cap, indices_are_sorted=True
            )
            if sum_cols.size
            else None
        )
        pm = (
            jax.ops.segment_max(
                sorted_rows, seg_id, num_segments=cap, indices_are_sorted=True
            )
            if max_cols.size
            else None
        )
        if pm is None:
            out_meters = ps.T
        elif ps is None:
            out_meters = pm.T
        else:
            is_sum = np.zeros((m,), bool)
            is_sum[sum_cols] = True
            out_meters = jnp.where(jnp.asarray(is_sum)[None, :], ps, pm).T  # [M, cap]
    else:
        out_meters = jnp.zeros((0, cap), meters_rows.dtype)

    k = jnp.arange(cap, dtype=jnp.int32)
    seg_valid = k < jnp.minimum(num_seg, cap)
    fp = jnp.where(seg_valid, first_pos, 0).astype(jnp.int32)

    out_slot = jnp.where(seg_valid, jnp.take(s_slot, fp), jnp.uint32(SENTINEL_SLOT))
    out_hi = jnp.where(seg_valid, jnp.take(s_hi, fp), 0)
    out_lo = jnp.where(seg_valid, jnp.take(s_lo, fp), 0)
    rep_orig = jnp.take(perm, fp)
    out_tags = jnp.where(seg_valid[None, :], jnp.take(tags_t, rep_orig, axis=1), 0)
    out_meters = jnp.where(seg_valid[None, :], out_meters, 0)

    return Grouped(
        slot=out_slot,
        key_hi=out_hi,
        key_lo=out_lo,
        tags=out_tags,
        meters=out_meters,
        seg_valid=seg_valid,
        num_segments=num_seg,
    )
