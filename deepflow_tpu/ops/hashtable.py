"""Device-resident static hash tables: vectorized u64-key → u32-value lookup.

The reference resolves platform metadata with pointer-chasing hash maps on
the host (`PlatformInfoTable` LRUs + id maps, grpc_platformdata.go:263-392;
hmap/idmap u64/u128 maps). On TPU the same lookups become *gathers*: the
host builds a fixed-capacity open-addressing table (linear probing) as flat
u32 arrays, ships it to HBM once per refresh, and the device probes it for
a whole batch at once — `max_probes` is measured at build time and becomes
the static unroll bound, so a lookup is `max_probes` gathers + compares on
the VPU with no data-dependent control flow.

Keys are (hi, lo) u32 lane pairs (TPUs have no useful native u64 path —
see ops/hashing.py). Values are u32; multi-field values are expressed as a
row index into a caller-side matrix, gathered after lookup.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import fmix32

NOT_FOUND = np.uint32(0xFFFFFFFF)
# Multiplicative mixing constant (2^32 / golden ratio) used to combine the
# two key lanes before the finalizer.
_PHI = 0x9E3779B9


def _bucket_hash(hi, lo, xp):
    h = xp.asarray(hi, xp.uint32) * xp.uint32(_PHI) ^ xp.asarray(lo, xp.uint32)
    return fmix32(h, xp)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceHashTable:
    """Flat open-addressing table. `filled` marks occupied buckets."""

    key_hi: jnp.ndarray  # [C] u32
    key_lo: jnp.ndarray  # [C] u32
    value: jnp.ndarray  # [C] u32
    filled: jnp.ndarray  # [C] bool
    # static: max probe distance measured by the host builder
    max_probes: int = dataclasses.field(metadata={"static": True}, default=1)

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]

    def lookup(self, hi, lo):
        """Batched probe: [N] u32 lanes → ([N] u32 values, [N] bool found).

        Misses return NOT_FOUND with found=False. The probe loop is a
        static unroll of `max_probes` gather+compare steps.
        """
        hi = jnp.asarray(hi, jnp.uint32)
        lo = jnp.asarray(lo, jnp.uint32)
        mask = jnp.uint32(self.capacity - 1)
        idx = _bucket_hash(hi, lo, jnp) & mask
        value = jnp.full(hi.shape, NOT_FOUND, dtype=jnp.uint32)
        found = jnp.zeros(hi.shape, dtype=bool)
        for p in range(self.max_probes):
            slot = (idx + jnp.uint32(p)) & mask
            hit = (
                self.filled[slot]
                & (self.key_hi[slot] == hi)
                & (self.key_lo[slot] == lo)
                & ~found
            )
            value = jnp.where(hit, self.value[slot], value)
            found = found | hit
        return value, found


def build_table(
    keys_hi: np.ndarray, keys_lo: np.ndarray, values: np.ndarray, min_capacity: int = 8
) -> DeviceHashTable:
    """Host-side construction with numpy linear probing.

    Capacity is the next power of two ≥ 2×n (load factor ≤ 0.5), so probe
    chains stay short; the realized worst chain becomes `max_probes`.
    Duplicate keys: last insert wins (refresh overwrite semantics).
    """
    keys_hi = np.asarray(keys_hi, dtype=np.uint32)
    keys_lo = np.asarray(keys_lo, dtype=np.uint32)
    values = np.asarray(values, dtype=np.uint32)
    n = keys_hi.shape[0]
    cap = int(min_capacity)
    while cap < max(2 * n, min_capacity):
        cap *= 2

    t_hi = np.zeros(cap, dtype=np.uint32)
    t_lo = np.zeros(cap, dtype=np.uint32)
    t_val = np.zeros(cap, dtype=np.uint32)
    t_fill = np.zeros(cap, dtype=bool)
    max_probes = 1
    mask = cap - 1
    start = _bucket_hash(keys_hi, keys_lo, np)
    for i in range(n):
        idx = int(start[i]) & mask
        for p in range(cap):
            slot = (idx + p) & mask
            if not t_fill[slot]:
                t_hi[slot], t_lo[slot], t_val[slot] = keys_hi[i], keys_lo[i], values[i]
                t_fill[slot] = True
                max_probes = max(max_probes, p + 1)
                break
            if t_hi[slot] == keys_hi[i] and t_lo[slot] == keys_lo[i]:
                t_val[slot] = values[i]  # overwrite duplicate
                break
    return DeviceHashTable(
        key_hi=jnp.asarray(t_hi),
        key_lo=jnp.asarray(t_lo),
        value=jnp.asarray(t_val),
        filled=jnp.asarray(t_fill),
        max_probes=max_probes,
    )


def empty_table() -> DeviceHashTable:
    """A valid table with no entries (all lookups miss)."""
    return build_table(
        np.zeros(0, np.uint32), np.zeros(0, np.uint32), np.zeros(0, np.uint32)
    )
