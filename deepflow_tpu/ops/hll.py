"""HyperLogLog — per-group cardinality on device.

The reference aggregates exactly (no sketches anywhere in server/ or
agent/ — SURVEY §0); HLL is this framework's addition for per-service
distinct counts (BASELINE config 3). Design for TPU:

  * state is a dense `[num_groups, m]` int32 register plane
    (m = 2^precision). Updates are one `scatter-max`; merges are
    elementwise `max`, so cross-chip merge is a single `pmax` over the
    mesh axis — no host round-trip.
  * rho (leading-zero rank) is computed from the hash's hi lane via
    floor(log2): exact, because only the top set bit matters.

precision=14 → 16384 registers/group → ~0.81% standard error, meeting the
<1% north-star bound.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def hll_init(num_groups: int, precision: int = 14) -> jnp.ndarray:
    return jnp.zeros((num_groups, 1 << precision), dtype=jnp.int32)


def _clz32(x: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros of u32, exactly, via branchless binary search
    (float log2 rounds up near powers of two, which would bias rho low)."""
    x = x.astype(jnp.uint32)
    zero_in = x == 0
    n = jnp.zeros(x.shape, dtype=jnp.int32)
    for s in (16, 8, 4, 2, 1):
        has_s_zeros = x < jnp.uint32(1 << (32 - s))
        n = jnp.where(has_s_zeros, n + s, n)
        x = jnp.where(has_s_zeros, x << jnp.uint32(s), x)
    return jnp.where(zero_in, jnp.int32(32), n)


@partial(jax.jit, donate_argnums=(0,))
def hll_update(state: jnp.ndarray, group_ids, hash_hi, hash_lo, valid) -> jnp.ndarray:
    """Scatter-max one batch of observations.

    group_ids: [N] i32 (rows in state); hash_hi/lo: [N] u32 fingerprint of
    the *distinct-counted entity* (e.g. client ip); valid: [N] bool.
    """
    m = state.shape[1]
    p = int(m).bit_length() - 1
    reg = (hash_lo & jnp.uint32(m - 1)).astype(jnp.int32)
    rho = (_clz32(hash_hi) + 1).astype(jnp.int32)  # 1..33
    gid = jnp.where(valid, group_ids, state.shape[0])  # OOB rows dropped
    return state.at[gid, reg].max(rho, mode="drop")


@jax.jit
def hll_estimate(state: jnp.ndarray) -> jnp.ndarray:
    """[num_groups] cardinality estimates (classic HLL with small-range
    linear-counting correction)."""
    m = state.shape[1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    regs = state.astype(jnp.float32)
    raw = alpha * m * m / jnp.sum(jnp.exp2(-regs), axis=1)
    zeros = jnp.sum((state == 0).astype(jnp.float32), axis=1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)


def hll_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Register-wise max — associative/commutative, safe under psum-style
    tree merges (`lax.pmax` over a mesh axis does this in-network)."""
    return jnp.maximum(a, b)


def hll_estimate_np(state) -> "np.ndarray":
    """Host-side estimate over a fetched register plane (np in/out) —
    the same classic-HLL math as `hll_estimate`, for query paths that
    must not touch the device (sketchplane.WindowSketchBlock)."""
    import numpy as np

    state = np.asarray(state)
    m = state.shape[1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    raw = alpha * m * m / np.sum(np.exp2(-state.astype(np.float64)), axis=1)
    zeros = np.sum(state == 0, axis=1).astype(np.float64)
    with np.errstate(divide="ignore"):
        linear = m * np.log(m / np.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return np.where(use_linear, linear, raw)


clz32 = _clz32  # per-register rank helper, shared with the window plane


# ---------------------------------------------------------------------------
# pooled sub-sketch form (ISSUE 20). A compact pool slot keeps the FULL
# m registers — rho is 1..33, so int8 holds a register exactly and the
# compact HLL is bit-identical to the wide plane (promotion is a cast,
# merge stays register max). Density comes from the 4× narrower dtype;
# the packed-u32 form below is the wire/pending-block layout (4
# registers per word, little-endian byte order).


def hll_pack_registers(regs, xp=jnp):
    """[..., m] i8/i32 registers → [..., m//4] u32 words (4 per word,
    byte 0 = register 0). m must be divisible by 4 (precision ≥ 2)."""
    r = xp.asarray(regs).astype(xp.uint32) & xp.uint32(0xFF)
    b = r.reshape(r.shape[:-1] + (r.shape[-1] // 4, 4))
    return (
        b[..., 0]
        | (b[..., 1] << xp.uint32(8))
        | (b[..., 2] << xp.uint32(16))
        | (b[..., 3] << xp.uint32(24))
    )


def hll_unpack_registers_np(words, m: int):
    """Host inverse of `hll_pack_registers`: [..., m//4] u32 → [..., m]
    i32 registers (values 0..33 — no sign handling needed)."""
    import numpy as np

    w = np.asarray(words, dtype=np.uint32)
    out = np.empty(w.shape[:-1] + (m,), dtype=np.int32)
    b = out.reshape(w.shape[:-1] + (m // 4, 4))
    b[..., 0] = w & np.uint32(0xFF)
    b[..., 1] = (w >> np.uint32(8)) & np.uint32(0xFF)
    b[..., 2] = (w >> np.uint32(16)) & np.uint32(0xFF)
    b[..., 3] = (w >> np.uint32(24)) & np.uint32(0xFF)
    return out
