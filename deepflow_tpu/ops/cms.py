"""Count-min sketch — heavy-hitter frequency estimation on device.

`[depth, width]` int32 counter plane; row hashes are derived from the
64-bit key fingerprint by the Kirsch–Mitzenmacher construction
(h_d = hi + d·lo), so no extra hashing per row. Update is one scatter-add
over the flattened plane; merge is elementwise add (`psum` over mesh axes
for cross-chip merge — BASELINE config 4/5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def cms_init(depth: int = 4, width: int = 1 << 16) -> jnp.ndarray:
    assert width & (width - 1) == 0, "width must be a power of two"
    return jnp.zeros((depth, width), dtype=jnp.int32)


def row_slots(hash_hi, hash_lo, depth: int, width: int, xp=jnp):
    """[depth, N] flattened slot indices.

    `xp` follows the ops/hashing convention: jnp for device updates, np
    for host-side point queries over fetched sketch blocks
    (aggregator/sketchplane.WindowSketchBlock) — one implementation, so
    the two sides cannot drift."""
    d = xp.arange(depth, dtype=xp.uint32)[:, None]
    h = xp.asarray(hash_hi, dtype=xp.uint32)[None, :] + d * xp.asarray(
        hash_lo, dtype=xp.uint32
    )[None, :]  # wrapping u32
    # avalanche the row mix so consecutive d don't alias
    h = h ^ (h >> xp.uint32(15))
    h = h * xp.uint32(0x2C1B3C6D)
    h = h ^ (h >> xp.uint32(12))
    col = (h & xp.uint32(width - 1)).astype(xp.int32)
    row_base = (xp.arange(depth, dtype=xp.int32) * width)[:, None]
    return row_base + col


_row_slots = row_slots


@partial(jax.jit, donate_argnums=(0,))
def cms_update(state: jnp.ndarray, hash_hi, hash_lo, weight, valid) -> jnp.ndarray:
    """Add `weight` (i32, e.g. 1 or a byte count) for each valid row."""
    depth, width = state.shape
    slots = _row_slots(hash_hi, hash_lo, depth, width)  # [depth, N]
    w = jnp.where(valid, weight.astype(jnp.int32), 0)
    w = jnp.broadcast_to(w[None, :], slots.shape)
    flat = state.reshape(-1).at[slots.reshape(-1)].add(w.reshape(-1))
    return flat.reshape(depth, width)


@jax.jit
def cms_query(state: jnp.ndarray, hash_hi, hash_lo) -> jnp.ndarray:
    """[N] frequency estimates: min over rows."""
    depth, width = state.shape
    slots = _row_slots(hash_hi, hash_lo, depth, width)
    vals = state.reshape(-1)[slots.reshape(-1)].reshape(depth, -1)
    return jnp.min(vals, axis=0)


def cms_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a + b


def cms_expand(compact, width: int, xp=jnp):
    """Up-tile a pooled compact [depth, Wc] plane to [depth, width]
    (ISSUE 20 promotion/merge-at-pooled-widths). Sound by construction:
    `row_slots`' column hash is width-independent, so for Wc | width
    (both powers of two) the compact column of a key is its wide column
    mod Wc — tiling places every compact counter at EVERY wide column
    that folds onto it, so wide-column reads see exactly the compact
    count plus later wide-phase adds. Overestimate-only is preserved
    (the fold can only add colliders, never drop weight); merge with a
    wide plane is the ordinary elementwise add."""
    wc = compact.shape[-1]
    assert width % wc == 0 and width & (width - 1) == 0, (wc, width)
    return xp.tile(compact, (1, width // wc))


def cms_query_np(state, hash_hi, hash_lo):
    """Host-side point query over a fetched counter plane (np in/out) —
    same row math as `cms_query` via the shared `row_slots`."""
    import numpy as np

    state = np.asarray(state)
    depth, width = state.shape
    slots = row_slots(hash_hi, hash_lo, depth, width, xp=np)
    vals = state.reshape(-1)[slots.reshape(-1)].reshape(depth, -1)
    return vals.min(axis=0)
