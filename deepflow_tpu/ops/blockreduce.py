"""Blocked, scatter-free sorted-segment reduction — the v2 hot loop.

Round 1's `groupby_reduce` (ops/segment.py) lowers `segment_sum` /
`segment_max` and the column re-assembly to XLA scatter, which runs at
~45M rows/s on this chip and dominated the step time (see PERF.md).
This module reduces sorted runs with TPU-friendly primitives only —
sort, static shifts, cumsum, gathers, and exactly one 1-lane scatter
for the compaction index:

  * rows are sorted by (slot, key_hi, key_lo) as before;
  * the sorted array is tiled into blocks of `BLOCK` rows; within each
    block a masked log-shift *suffix* scan reduces equal-key runs, so
    the run's first row ends up holding the run's in-block total;
  * segments straddling block boundaries are fixed with a tiny
    segmented suffix scan over the [num_blocks] per-block head
    partials (the continuation chain of a segment is exactly the run
    of following blocks whose first id equals this block's last id);
  * every segment's total is then available at its *global* first row:
    emitted rows are compacted to a static-size prefix with one cumsum
    + one 1-lane scatter (positions) + payload gathers.

The result contract matches ops/segment.groupby_reduce (`Grouped`), so
stash/window machinery is unchanged. Semantics mirror the reference's
stash merges (collector.rs:810, quadruple_generator.rs:544): SUM lanes
add, MAX lanes max, tags come from the segment's first sorted row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .segment import Grouped, SENTINEL_SLOT

BLOCK = 256  # rows per tile (power of two)


def _suffix_segscan_block(vals: jnp.ndarray, ids: jnp.ndarray, op: str) -> jnp.ndarray:
    """vals [NB, B, C], ids [NB, B] sorted within block. Returns the
    suffix reduction of each equal-id run: out[b, r] = op over
    vals[b, r:end_of_run(r)]. log2(B) masked shift steps, no scatter."""
    v = vals
    d = 1
    while d < BLOCK:
        same = ids[:, :-d] == ids[:, d:]  # [NB, B-d]
        head = v[:, :-d]
        tail = v[:, d:]
        if op == "sum":
            upd = head + jnp.where(same[..., None], tail, 0)
        else:
            upd = jnp.where(same[..., None], jnp.maximum(head, tail), head)
        v = jnp.concatenate([upd, v[:, -d:]], axis=1)
        d *= 2
    return v


def _suffix_segscan_flat(vals: jnp.ndarray, keys: jnp.ndarray, op: str) -> jnp.ndarray:
    """1-D variant over [NB, C] block carries keyed by keys [NB]."""
    v = vals
    n = keys.shape[0]
    d = 1
    while d < n:
        same = keys[:-d] == keys[d:]
        head = v[:-d]
        tail = v[d:]
        if op == "sum":
            upd = head + jnp.where(same[:, None], tail, 0)
        else:
            upd = jnp.where(same[:, None], jnp.maximum(head, tail), head)
        v = jnp.concatenate([upd, v[-d:]], axis=0)
        d *= 2
    return v


def _reduce_lanes(meters_sorted, g_ids, gmax, chain_next, is_last_run, cols, op):
    """Per-segment totals (at global-first rows) for one op class.

    meters_sorted [N, M] in sorted order; cols: static np indices.
    Returns [N, len(cols)] where only global-first rows are meaningful.
    """
    if cols.size == 0:
        n = meters_sorted.shape[0]
        return jnp.zeros((n, 0), meters_sorted.dtype)
    nb = g_ids.shape[0]
    sub = jnp.take(meters_sorted, jnp.asarray(cols), axis=1)
    sub_b = sub.reshape(nb, BLOCK, -1)
    scanned = _suffix_segscan_block(sub_b, g_ids, op)
    # head partial of each block = partial of the segment containing row 0
    head = scanned[:, 0, :]  # [NB, C]
    gmin = g_ids[:, 0]
    cont = jnp.concatenate([jnp.zeros((1,), bool), gmin[1:] == gmax[:-1]])
    chain_vals = jnp.where(cont[:, None], head, 0 if op == "sum" else head * 0)
    chain = _suffix_segscan_flat(chain_vals, gmin, op)
    # extra for block b = combined chain starting at b+1 (if continuing)
    nxt = jnp.concatenate([chain[1:], jnp.zeros_like(chain[:1])], axis=0)
    extra = jnp.where(chain_next[:, None], nxt, 0)  # [NB, C]
    if op == "sum":
        out = scanned + jnp.where(is_last_run[..., None], extra[:, None, :], 0)
    else:
        out = jnp.where(
            is_last_run[..., None],
            jnp.maximum(scanned, jnp.where(chain_next[:, None, None], extra[:, None, :], scanned)),
            scanned,
        )
    return out.reshape(-1, cols.size)


def blocked_groupby_reduce(
    slot,
    key_hi,
    key_lo,
    tags,
    meters,
    valid,
    sum_cols: np.ndarray,
    max_cols: np.ndarray,
    out_capacity: int | None = None,
) -> Grouped:
    """Drop-in replacement for ops.segment.groupby_reduce with output
    arrays sized `out_capacity` (default N). Segments beyond capacity
    (in ascending (slot, key) order) are dropped from the output but
    still counted in num_segments, so callers can account overflow."""
    n_in = slot.shape[0]
    cap = int(out_capacity or n_in)
    m_cols = meters.shape[1]
    sum_cols = np.asarray(sum_cols, np.int32)
    max_cols = np.asarray(max_cols, np.int32)

    slot = jnp.where(valid, slot, jnp.uint32(SENTINEL_SLOT))
    key_hi = jnp.where(valid, key_hi, jnp.uint32(0xFFFFFFFF))
    key_lo = jnp.where(valid, key_lo, jnp.uint32(0xFFFFFFFF))

    # pad to a BLOCK multiple with sentinel rows
    n = ((n_in + BLOCK - 1) // BLOCK) * BLOCK
    pad = n - n_in
    if pad:
        slot = jnp.concatenate([slot, jnp.full((pad,), SENTINEL_SLOT, jnp.uint32)])
        key_hi = jnp.concatenate([key_hi, jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)])
        key_lo = jnp.concatenate([key_lo, jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)])
        meters = jnp.concatenate([meters, jnp.zeros((pad, m_cols), meters.dtype)])
        tags = jnp.concatenate([tags, jnp.zeros((pad, tags.shape[1]), tags.dtype)])
    nb = n // BLOCK

    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, key_hi, key_lo, iota), num_keys=3)

    first = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (s_slot[1:] != s_slot[:-1])
            | (s_hi[1:] != s_hi[:-1])
            | (s_lo[1:] != s_lo[:-1]),
        ]
    )
    seg_id = jnp.cumsum(first.astype(jnp.int32)) - 1  # [n] ascending

    meters_sorted = jnp.take(meters, perm, axis=0)

    g_ids = seg_id.reshape(nb, BLOCK)
    gmax = g_ids[:, -1]
    gmin = g_ids[:, 0]
    # does block b's last segment continue into b+1?
    chain_next = jnp.concatenate([gmin[1:] == gmax[:-1], jnp.zeros((1,), bool)])
    is_last_run = g_ids == gmax[:, None]  # [NB, B]

    sums = _reduce_lanes(meters_sorted, g_ids, gmax, chain_next, is_last_run, sum_cols, "sum")
    maxs = _reduce_lanes(meters_sorted, g_ids, gmax, chain_next, is_last_run, max_cols, "max")

    # reassemble [n, M] in schema order via static concat permutation
    pieces = [None] * m_cols
    for j, c in enumerate(sum_cols):
        pieces[int(c)] = sums[:, j : j + 1]
    for j, c in enumerate(max_cols):
        pieces[int(c)] = maxs[:, j : j + 1]
    totals = jnp.concatenate(pieces, axis=1)  # meaningful at first rows only

    # --- compaction: emit global-first rows of live segments ----------
    live = first & (s_slot != jnp.uint32(SENTINEL_SLOT))
    pos = jnp.cumsum(live.astype(jnp.int32)) - 1
    num_live = pos[-1] + 1
    write_pos = jnp.where(live, pos, cap + 1)
    src = jnp.full((cap,), -1, jnp.int32)
    src = src.at[write_pos].set(iota, mode="drop")
    got = src >= 0
    taken = jnp.maximum(src, 0)

    out_meters = jnp.where(got[:, None], jnp.take(totals, taken, axis=0), 0)
    out_slot = jnp.where(got, jnp.take(s_slot, taken), jnp.uint32(SENTINEL_SLOT))
    out_hi = jnp.where(got, jnp.take(s_hi, taken), 0)
    out_lo = jnp.where(got, jnp.take(s_lo, taken), 0)
    rep_rows = jnp.take(perm, taken)
    out_tags = jnp.where(got[:, None], jnp.take(tags, rep_rows, axis=0), 0)

    return Grouped(
        slot=out_slot,
        key_hi=out_hi,
        key_lo=out_lo,
        tags=out_tags,
        meters=out_meters,
        seg_valid=got,
        num_segments=num_live,
    )
