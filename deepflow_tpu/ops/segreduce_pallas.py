"""Scatter-free sorted segmented sum+max — the Pallas hot-loop kernel.

The r5 bisection (PERF.md §9) showed TPU segment reductions pay a
per-ROW scatter cost regardless of lane width: at 2M rows,
`segment_sum` ≈ 10 ms, `segment_max` ≈ 29 ms — 39 ms of the 82 ms
append. This kernel replaces both with one streaming pass:

  * rows arrive in sorted-key order (the groupby invariant), so each
    segment is a contiguous run;
  * per block of B rows, a segmented Hillis-Steele SUFFIX scan in VMEM
    (log2(B) doubling passes, sum and max together) leaves, at every
    row i, the reduction of rows i..min(end-of-segment, end-of-block);
  * the value at a segment's HEAD row is its in-block total; the value
    at each block's row 0 is the block's leading-run partial;
  * cross-block carries combine in XLA over ONE ROW PER BLOCK
    (n/B rows, three orders of magnitude smaller than n), then a
    [cap]-row gather at the segment head positions finishes the job.

Fused gather (r6, PERF.md §9d): the [N, M] payload used to be
pre-gathered into sorted order by a standalone `jnp.take` pass
(~14.5 ms of HBM read+write at 2M rows). With `perm` supplied, the
kernel instead streams rows THROUGH the sort permutation: the per-block
slice of `perm` rides in SMEM, and a pipelined chain of row-sized
async copies (permutation-indexed block DMA) lands each block's rows in
VMEM scratch in sorted order — the payload is read exactly once, in
its original layout, and the pre-gather pass disappears. Lanes past M
in the scratch are never DMA'd and hold garbage; every lane is
independent under sum/max, and callers slice [:, :m].

No scatter touches the [N, M] payload; everything wide is sequential
VMEM streaming (MXU-free, VPU + bandwidth bound).

Semantics replaced: reference `Stash::add` hash-merge loops
(collector.rs:810, quadruple_generator.rs:544) — same SUM/MAX per-key
fold, vectorized.

Exactness: within-segment summation is tree-ordered instead of linear.
For the integer-valued meter lanes this framework folds (packet/byte/
count deltas well under 2^24), f32 tree sums are bit-exact; the
conformance suite pins the pallas path against the XLA ops directly,
with and without the fused gather.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # f32 lane tile; meter payloads are padded up to this
_NEG = np.float32(-3.4e38)  # practical -inf that survives where()

# Outstanding row DMAs in the fused-gather pipeline. Small enough to
# stay within the DMA queue, deep enough to hide issue latency behind
# the in-flight copies.
_GATHER_LOOKAHEAD = 8


def _suffix_scan(seg, x, block: int):
    """Segmented Hillis-Steele suffix scan over one VMEM-resident block:
    seg [B, 1] i32 ascending, x [B, LANES] f32 → (suffix_sum,
    suffix_max), each row i holding the fold of i..end-of-run."""
    s = x
    m = x
    k = 1
    while k < block:
        seg_shift = jnp.concatenate(
            [seg[k:], jnp.full((k, 1), -1, jnp.int32)], axis=0
        )
        same = seg_shift == seg  # [B, 1]
        s_shift = jnp.concatenate(
            [s[k:], jnp.zeros((k, LANES), jnp.float32)], axis=0
        )
        m_shift = jnp.concatenate(
            [m[k:], jnp.full((k, LANES), _NEG, jnp.float32)], axis=0
        )
        s = s + jnp.where(same, s_shift, jnp.float32(0))
        m = jnp.maximum(m, jnp.where(same, m_shift, _NEG))
        k *= 2
    return s, m


def _suffix_kernel(seg_ref, rows_ref, sum_ref, max_ref, *, block: int):
    s, m = _suffix_scan(seg_ref[:], rows_ref[:], block)
    sum_ref[:] = s
    max_ref[:] = m


def _block_suffix(rows: jnp.ndarray, seg2d: jnp.ndarray, block: int):
    """rows [N, LANES] f32 (N % block == 0), seg2d [N, 1] i32 →
    (suffix_sum, suffix_max), both [N, LANES]."""
    n = rows.shape[0]
    grid = (n // block,)
    return pl.pallas_call(
        partial(_suffix_kernel, block=block),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ],
        interpret=jax.default_backend() == "cpu",
    )(seg2d, rows)


def _gather_suffix_kernel(
    perm_ref, seg_ref, rows_ref, sum_ref, max_ref, rows_vmem, sems,
    *, block: int, m: int,
):
    """Fused variant: rows_ref is the FULL [N, m] payload in HBM
    (original order); perm_ref holds this block's slice of the sort
    permutation in SMEM. Rows land in VMEM scratch in sorted order via
    a lookahead-pipelined chain of row DMAs, then the suffix scan runs
    unchanged."""
    la = min(_GATHER_LOOKAHEAD, block)

    def row_copy(j):
        return pltpu.make_async_copy(
            rows_ref.at[perm_ref[j]],
            rows_vmem.at[j, pl.ds(0, m)],
            sems.at[j % la],
        )

    for j in range(la):  # warm-up: fill the pipeline
        row_copy(j).start()

    def body(j, carry):
        @pl.when(j + la < block)
        def _():
            row_copy(j + la).start()

        row_copy(j).wait()
        return carry

    jax.lax.fori_loop(0, block, body, 0)

    s, mx = _suffix_scan(seg_ref[:], rows_vmem[:], block)
    sum_ref[:] = s
    max_ref[:] = mx


def _block_suffix_gather(
    rows: jnp.ndarray, perm: jnp.ndarray, seg2d: jnp.ndarray, block: int
):
    """rows [N, m] f32 in ORIGINAL order, perm [P] i32 (P % block == 0,
    values < N), seg2d [P, 1] i32 → (suffix_sum, suffix_max) of
    rows[perm], both [P, LANES] (lanes ≥ m are garbage — callers
    slice)."""
    n_sorted = perm.shape[0]
    m = rows.shape[1]
    grid = (n_sorted // block,)
    la = min(_GATHER_LOOKAHEAD, block)
    return pl.pallas_call(
        partial(_gather_suffix_kernel, block=block, m=m),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),  # full payload, kernel-DMA'd
        ],
        out_specs=[
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_sorted, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_sorted, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((la,)),
        ],
        interpret=jax.default_backend() == "cpu",
    )(perm, seg2d, rows)


def sorted_segment_sum_max(
    rows: jnp.ndarray,
    seg_id: jnp.ndarray,
    num_segments: int,
    first_pos: jnp.ndarray,
    *,
    perm: jnp.ndarray | None = None,
    block: int = 2048,
):
    """Segment sum AND max of `rows` [N, M] f32 grouped by the ASCENDING
    `seg_id` [N] (dead rows carry an id ≥ num_segments and must sort
    last). `first_pos` [num_segments] are the first occurrence indices
    (searchsorted upstream). Returns (sums, maxs), both
    [num_segments, M].

    With `perm` [N] i32 supplied, `rows` is in ORIGINAL (pre-sort)
    order and row i of the reduction input is rows[perm[i]] — the
    gather happens inside the kernel via permutation-indexed DMA, so no
    pre-gathered copy of the payload is ever materialized. Without
    `perm`, rows must already be sorted (legacy contract).

    CONTRACT: rows of ABSENT segments are garbage — searchsorted points
    an absent id at the next live segment's head, so its totals bleed
    in (NOT the 0 / -inf identities the XLA segment ops emit). Callers
    MUST mask by their live-segment prefix (groupby_reduce's seg_valid
    does); never detect emptiness from these values."""
    n, m = rows.shape
    if m > LANES:
        raise ValueError(
            f"meter payload has {m} lanes but the suffix-scan kernel streams "
            f"a single {LANES}-wide tile; widen via lane-chunk tiling before "
            f"growing a meter schema past {LANES} columns"
        )
    cap = int(num_segments)
    blk = int(min(block, max(8, 1 << (n - 1).bit_length())))
    pad_rows = (-n) % blk
    if pad_rows:
        seg_id = jnp.pad(seg_id, (0, pad_rows), constant_values=np.int32(2**31 - 1))
        if perm is None:
            rows = jnp.pad(rows, ((0, pad_rows), (0, 0)))
        else:
            # padded tail rows read a real row (index 0) but carry the
            # sentinel segment id, so they never reach any live output
            perm = jnp.pad(perm, (0, pad_rows))
        n += pad_rows
    seg2d = seg_id.astype(jnp.int32)[:, None]

    if perm is None:
        if m < LANES:
            rows = jnp.pad(rows, ((0, 0), (0, LANES - m)))
        suf_sum, suf_max = _block_suffix(rows, seg2d, blk)
    else:
        suf_sum, suf_max = _block_suffix_gather(
            rows, perm.astype(jnp.int32), seg2d, blk
        )

    # in-block totals at the segment heads
    fp = jnp.clip(first_pos, 0, n - 1)
    base_sum = jnp.take(suf_sum, fp, axis=0)  # [cap, LANES]
    base_max = jnp.take(suf_max, fp, axis=0)

    # cross-block carries: one row per block — the block's leading-run
    # partial belongs to the segment still open at the block boundary
    nb = n // blk
    starts = jnp.arange(nb, dtype=jnp.int32) * blk
    first_seg = jnp.take(seg_id, starts).astype(jnp.int32)
    prefix_sum = jnp.take(suf_sum, starts, axis=0)  # [nb, LANES]
    prefix_max = jnp.take(suf_max, starts, axis=0)
    # a block whose row 0 IS a head contributes through base_*, not as
    # a carry (its leading run equals the head suffix — double count)
    prev = jnp.take(seg_id, jnp.maximum(starts - 1, 0)).astype(jnp.int32)
    continues = (jnp.arange(nb) > 0) & (first_seg == prev)
    carry_seg = jnp.where(continues, first_seg, np.int32(2**31 - 1))
    # carry_seg is NOT sorted (masked blocks get a big id in place), so
    # no indices_are_sorted hint; at n/B rows the scatter cost is noise
    carry_sum = jax.ops.segment_sum(
        jnp.where(continues[:, None], prefix_sum, 0.0),
        carry_seg, num_segments=cap,
    )
    carry_max = jax.ops.segment_max(
        jnp.where(continues[:, None], prefix_max, _NEG),
        carry_seg, num_segments=cap,
    )
    carry_max = jnp.where(jnp.isfinite(carry_max), carry_max, _NEG)

    out_sum = (base_sum + carry_sum)[:, :m]
    out_max = jnp.maximum(base_max, carry_max)[:, :m]
    return out_sum, out_max
