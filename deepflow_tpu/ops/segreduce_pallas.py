"""Scatter-free sorted segmented sum+max — the Pallas hot-loop kernel.

The r5 bisection (PERF.md §9) showed TPU segment reductions pay a
per-ROW scatter cost regardless of lane width: at 2M rows,
`segment_sum` ≈ 10 ms, `segment_max` ≈ 29 ms — 39 ms of the 82 ms
append. This kernel replaces both with one streaming pass:

  * rows arrive in sorted-key order (the groupby invariant), so each
    segment is a contiguous run;
  * per block of B rows, a segmented Hillis-Steele SUFFIX scan in VMEM
    (log2(B) doubling passes, sum and max together) leaves, at every
    row i, the reduction of rows i..min(end-of-segment, end-of-block);
  * the value at a segment's HEAD row is its in-block total; the value
    at each block's row 0 is the block's leading-run partial;
  * cross-block carries combine in XLA over ONE ROW PER BLOCK
    (n/B rows, three orders of magnitude smaller than n), then a
    [cap]-row gather at the segment head positions finishes the job.

No scatter touches the [N, M] payload; everything wide is sequential
VMEM streaming (MXU-free, VPU + bandwidth bound).

Semantics replaced: reference `Stash::add` hash-merge loops
(collector.rs:810, quadruple_generator.rs:544) — same SUM/MAX per-key
fold, vectorized.

Exactness: within-segment summation is tree-ordered instead of linear.
For the integer-valued meter lanes this framework folds (packet/byte/
count deltas well under 2^24), f32 tree sums are bit-exact; the
conformance suite pins the pallas path against the XLA ops directly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # f32 lane tile; meter payloads are padded up to this
_NEG = np.float32(-3.4e38)  # practical -inf that survives where()


def _suffix_kernel(seg_ref, rows_ref, sum_ref, max_ref, *, block: int):
    seg = seg_ref[:]  # [B, 1] i32
    x = rows_ref[:]  # [B, LANES] f32
    s = x
    m = x
    k = 1
    while k < block:
        seg_shift = jnp.concatenate(
            [seg[k:], jnp.full((k, 1), -1, jnp.int32)], axis=0
        )
        same = seg_shift == seg  # [B, 1]
        s_shift = jnp.concatenate(
            [s[k:], jnp.zeros((k, LANES), jnp.float32)], axis=0
        )
        m_shift = jnp.concatenate(
            [m[k:], jnp.full((k, LANES), _NEG, jnp.float32)], axis=0
        )
        s = s + jnp.where(same, s_shift, jnp.float32(0))
        m = jnp.maximum(m, jnp.where(same, m_shift, _NEG))
        k *= 2
    sum_ref[:] = s
    max_ref[:] = m


def _block_suffix(rows: jnp.ndarray, seg2d: jnp.ndarray, block: int):
    """rows [N, LANES] f32 (N % block == 0), seg2d [N, 1] i32 →
    (suffix_sum, suffix_max), both [N, LANES]."""
    n = rows.shape[0]
    grid = (n // block,)
    return pl.pallas_call(
        partial(_suffix_kernel, block=block),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ],
        interpret=jax.default_backend() == "cpu",
    )(seg2d, rows)


def sorted_segment_sum_max(
    rows: jnp.ndarray,
    seg_id: jnp.ndarray,
    num_segments: int,
    first_pos: jnp.ndarray,
    *,
    block: int = 2048,
):
    """Segment sum AND max of `rows` [N, M] f32 grouped by the ASCENDING
    `seg_id` [N] (dead rows carry an id ≥ num_segments and must sort
    last). `first_pos` [num_segments] are the first occurrence indices
    (searchsorted upstream). Returns (sums, maxs), both
    [num_segments, M].

    CONTRACT: rows of ABSENT segments are garbage — searchsorted points
    an absent id at the next live segment's head, so its totals bleed
    in (NOT the 0 / -inf identities the XLA segment ops emit). Callers
    MUST mask by their live-segment prefix (groupby_reduce's seg_valid
    does); never detect emptiness from these values."""
    n, m = rows.shape
    cap = int(num_segments)
    blk = int(min(block, max(8, 1 << (n - 1).bit_length())))
    pad_rows = (-n) % blk
    if pad_rows:
        rows = jnp.pad(rows, ((0, pad_rows), (0, 0)))
        seg_id = jnp.pad(seg_id, (0, pad_rows), constant_values=np.int32(2**31 - 1))
        n += pad_rows
    if m < LANES:
        rows = jnp.pad(rows, ((0, 0), (0, LANES - m)))
    seg2d = seg_id.astype(jnp.int32)[:, None]

    suf_sum, suf_max = _block_suffix(rows, seg2d, blk)

    # in-block totals at the segment heads
    fp = jnp.clip(first_pos, 0, n - 1)
    base_sum = jnp.take(suf_sum, fp, axis=0)  # [cap, LANES]
    base_max = jnp.take(suf_max, fp, axis=0)

    # cross-block carries: one row per block — the block's leading-run
    # partial belongs to the segment still open at the block boundary
    nb = n // blk
    starts = jnp.arange(nb, dtype=jnp.int32) * blk
    first_seg = jnp.take(seg_id, starts).astype(jnp.int32)
    prefix_sum = jnp.take(suf_sum, starts, axis=0)  # [nb, LANES]
    prefix_max = jnp.take(suf_max, starts, axis=0)
    # a block whose row 0 IS a head contributes through base_*, not as
    # a carry (its leading run equals the head suffix — double count)
    prev = jnp.take(seg_id, jnp.maximum(starts - 1, 0)).astype(jnp.int32)
    continues = (jnp.arange(nb) > 0) & (first_seg == prev)
    carry_seg = jnp.where(continues, first_seg, np.int32(2**31 - 1))
    # carry_seg is NOT sorted (masked blocks get a big id in place), so
    # no indices_are_sorted hint; at n/B rows the scatter cost is noise
    carry_sum = jax.ops.segment_sum(
        jnp.where(continues[:, None], prefix_sum, 0.0),
        carry_seg, num_segments=cap,
    )
    carry_max = jax.ops.segment_max(
        jnp.where(continues[:, None], prefix_max, _NEG),
        carry_seg, num_segments=cap,
    )
    carry_max = jnp.where(jnp.isfinite(carry_max), carry_max, _NEG)

    out_sum = (base_sum + carry_sum)[:, :m]
    out_max = jnp.maximum(base_max, carry_max)[:, :m]
    return out_sum, out_max
