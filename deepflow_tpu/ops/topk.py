"""Invertible top-K heavy-hitter sketch — bucketed key recovery on device.

The exact stash answers "what did every flow do"; under high-cardinality
traffic it sheds. This sketch answers the question that survives the
shed: *which K keys were heaviest* — without ever flushing the key
space. Design follows the invertible-sketch / streaming top-K line
(PAPERS.md: "A Fast and Compact Invertible Sketch for Network-Wide
Heavy Flow Detection", "A streaming algorithm and hardware accelerator
for top-K flow detection"):

  * `[rows, cols]` buckets; each key maps to one bucket per row via an
    avalanche of its 64-bit fingerprint (no extra hashing per row).
  * Each bucket runs a weighted MJRTY (Boyer–Moore majority vote):
    matching keys add their weight to the bucket's vote, non-matching
    keys subtract; a vote crossing zero replaces the stored key. A key
    whose weight dominates its bucket in any row survives with its key
    bits *stored in the bucket* — that is the inversion: candidates are
    read straight out of the sketch.
  * Batch updates vectorize by aggregating the batch per (bucket, key)
    first — one 3-key sort + segment reductions (the ingest hot path's
    own machinery) — then applying ONE vote update per bucket with the
    bucket's heaviest batch key as the challenger. Within a batch only
    the heaviest challenger per bucket competes; lighter same-batch
    keys are absorbed into the next batch's aggregation. This keeps the
    update a fixed op count per row regardless of key skew, and it only
    *strengthens* the heavy-hitter guarantee (fewer spurious
    decrements).
  * Merge is bucket-wise MJRTY combination (same key: votes add;
    different keys: heavier survives with the vote difference) — the
    cross-shard close combines per-device sketches without any key
    exchange.

Frequencies are NOT read from the votes (votes are a survival signal,
not an estimate): `topk_select` estimates each recovered candidate via
the companion count-min plane of the same window — the classic
invertible pairing. Two u32 identity lanes (`id_a`, `id_b`) ride each
bucket so a recovered key also carries a human-readable flow preview
(e.g. client ip word + service port) without a reverse lookup.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def topk_init(rows: int, cols: int, ring: int = 1):
    """→ (votes, key_hi, key_lo, id_a, id_b) lane arrays, each
    [ring, rows, cols] (ring = per-window slots; 1 = a single sketch).
    votes <= 0 marks an empty bucket."""
    shape = (ring, rows, cols)
    z32 = jnp.zeros(shape, dtype=jnp.int32)
    zu = jnp.zeros(shape, dtype=jnp.uint32)
    return z32, zu, zu, zu, zu


def bucket_cols(key_hi, key_lo, row: int, cols: int, xp=jnp):
    """[N] i32 bucket column for hash row `row` (Kirsch–Mitzenmacher
    base + a different avalanche than the CMS rows, so the two sketches
    of one window never alias)."""
    assert cols & (cols - 1) == 0, "cols must be a power of two"
    h = xp.asarray(key_hi, xp.uint32) + xp.uint32(row + 1) * xp.asarray(
        key_lo, xp.uint32
    )
    h = h ^ (h >> xp.uint32(16))
    h = h * xp.uint32(0x7FEB352D)
    h = h ^ (h >> xp.uint32(15))
    h = h * xp.uint32(0x846CA68B)
    h = h ^ (h >> xp.uint32(16))
    return (h & xp.uint32(cols - 1)).astype(xp.int32)


def _apply_challengers(lanes, challengers):
    """Weighted-MJRTY vote epilogue, shared by every update path (the
    fresh-sort oracle, the shared-sort presorted path, and the fused
    Pallas kernel — ops/sketch_pallas.py): apply, per hash row, ONE
    challenger per flat [R*C] bucket. `challengers` is a list of
    (got, h_hi, h_lo, h_ia, h_ib, hw) tuples, one per hash row, with hw
    already clamped ≥ 0 and 0 wherever got is False."""
    votes, l_hi, l_lo, l_ia, l_ib = lanes
    r_ring, d, c = votes.shape
    for r, (got, h_hi, h_lo, h_ia, h_ib, hw) in enumerate(challengers):
        v = votes[:, r, :].reshape(-1)
        bh = l_hi[:, r, :].reshape(-1)
        bl = l_lo[:, r, :].reshape(-1)
        ba = l_ia[:, r, :].reshape(-1)
        bb = l_ib[:, r, :].reshape(-1)
        live = v > 0
        same = live & (bh == h_hi) & (bl == h_lo)
        challenged = jnp.where(live, v - hw, -hw)
        take = got & ~same & (challenged < 0)
        new_v = jnp.where(same, v + hw, jnp.where(take, -challenged, challenged))
        new_v = jnp.where(got, new_v, v)
        votes = votes.at[:, r, :].set(new_v.reshape(r_ring, c))
        l_hi = l_hi.at[:, r, :].set(jnp.where(take, h_hi, bh).reshape(r_ring, c))
        l_lo = l_lo.at[:, r, :].set(jnp.where(take, h_lo, bl).reshape(r_ring, c))
        l_ia = l_ia.at[:, r, :].set(jnp.where(take, h_ia, ba).reshape(r_ring, c))
        l_ib = l_ib.at[:, r, :].set(jnp.where(take, h_ib, bb).reshape(r_ring, c))
    return votes, l_hi, l_lo, l_ia, l_ib


def topk_update(lanes, slot, key_hi, key_lo, id_a, id_b, weight, valid):
    """One batch of weighted observations into the [R, d, C] lanes.

    `slot` is the per-row ring index ([N] i32); rows with slot outside
    [0, R) or valid=False are dropped. Traced — callers fuse this into
    their jitted ingest step. This is the multi-sort ORACLE: one fresh
    3-key sort per hash row. The shared-sort hot path
    (`topk_challengers_presorted`, driven from
    aggregator/sketchplane.py) is pinned bit-exact against it."""
    votes, l_hi, l_lo, l_ia, l_ib = lanes
    r_ring, d, c = votes.shape
    n = key_hi.shape[0]
    segs = r_ring * c
    key_hi = jnp.asarray(key_hi, jnp.uint32)
    key_lo = jnp.asarray(key_lo, jnp.uint32)
    w = jnp.where(valid, jnp.asarray(weight).astype(jnp.int32), 0)
    slot = jnp.asarray(slot, jnp.int32)
    ok = valid & (slot >= 0) & (slot < r_ring)
    iota = jnp.arange(n, dtype=jnp.int32)
    challengers = []
    for r in range(d):
        col = bucket_cols(key_hi, key_lo, r, c)
        seg = jnp.where(ok, slot * c + col, segs)
        # aggregate the batch per (bucket, key): one 3-key sort, then
        # run-level weight sums
        s_seg, s_hi, s_lo, s_w, s_ia, s_ib = lax.sort(
            (seg, key_hi, key_lo, w, jnp.asarray(id_a, jnp.uint32),
             jnp.asarray(id_b, jnp.uint32)),
            num_keys=3,
        )
        first = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (s_seg[1:] != s_seg[:-1])
                | (s_hi[1:] != s_hi[:-1])
                | (s_lo[1:] != s_lo[:-1]),
            ]
        )
        run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
        run_w = jax.ops.segment_sum(s_w, run_id, num_segments=n)
        rw = run_w[run_id]  # per row: its (bucket, key)'s batch weight
        heavy_w = jax.ops.segment_max(rw, s_seg, num_segments=segs + 1)[:segs]
        # first row of the heaviest run per bucket (stable tie-break)
        in_seg = s_seg < segs
        is_heavy = in_seg & (rw == heavy_w[jnp.clip(s_seg, 0, segs - 1)])
        win_row = jax.ops.segment_min(
            jnp.where(is_heavy, iota, n), s_seg, num_segments=segs + 1
        )[:segs]
        got = win_row < n
        wr = jnp.clip(win_row, 0, n - 1)
        hw = jnp.where(got, jnp.maximum(heavy_w, 0), 0)
        challengers.append((got, s_hi[wr], s_lo[wr], s_ia[wr], s_ib[wr], hw))
    return _apply_challengers(lanes, challengers)


def topk_challengers_presorted(
    s_slot, s_hi, s_lo, s_ia, s_ib, rw, s_mask, r_ring: int, d: int, c: int
):
    """Per-hash-row challenger extraction from an ALREADY SORTED batch —
    zero sorts (ISSUE 17, shared-sort path).

    Inputs are the batch's lanes gathered through ONE shared
    (window, key_hi, key_lo)-stable sort permutation (the sketch
    plane's), with `rw` the per-row (window, key)-run weight sum under
    the phase mask `s_mask` (computed once upstream, shared with the
    count-min run dedup). Bit-exactness vs the per-row fresh sort of
    `topk_update` holds because a bucket only ever receives rows of ONE
    window (slot ↔ window is bijective within a phase span < R), so the
    shared order restricted to a bucket is the oracle's
    (key_hi, key_lo, original-position) order — same heaviest run, same
    stable first-row tie-break. Returns the `_apply_challengers` input
    list."""
    n = s_hi.shape[0]
    segs = r_ring * c
    iota = jnp.arange(n, dtype=jnp.int32)
    challengers = []
    for r in range(d):
        col = bucket_cols(s_hi, s_lo, r, c)
        seg = jnp.where(s_mask, s_slot * c + col, segs)
        heavy_w = jax.ops.segment_max(rw, seg, num_segments=segs + 1)[:segs]
        in_seg = seg < segs
        is_heavy = in_seg & (rw == heavy_w[jnp.clip(seg, 0, segs - 1)])
        win_row = jax.ops.segment_min(
            jnp.where(is_heavy, iota, n), seg, num_segments=segs + 1
        )[:segs]
        got = win_row < n
        wr = jnp.clip(win_row, 0, n - 1)
        hw = jnp.where(got, jnp.maximum(heavy_w, 0), 0)
        challengers.append((got, s_hi[wr], s_lo[wr], s_ia[wr], s_ib[wr], hw))
    return challengers


def topk_tile(lanes, cols: int):
    """Up-tile one slot's pooled compact lanes ([d, Cc] each) to
    [d, cols] wide buckets (ISSUE 20 promotion). `bucket_cols`' hash is
    width-independent, so a key's compact bucket is its wide bucket mod
    Cc — tiling copies every compact bucket (key bits, ids, votes) into
    each wide bucket that folds onto it, which keeps the key's own entry
    present in its true wide bucket. The copies landing in OTHER wide
    buckets are spurious candidates; they are harmless — each bucket
    runs its own MJRTY against the keys that actually hash there, and
    `topk_select` dedupes candidates by key before ranking."""
    votes, l_hi, l_lo, l_ia, l_ib = lanes
    cc = votes.shape[-1]
    assert cols % cc == 0 and cols & (cols - 1) == 0, (cc, cols)
    t = lambda x: jnp.tile(x, (1, cols // cc))
    return t(votes), t(l_hi), t(l_lo), t(l_ia), t(l_ib)


def topk_merge(a, b):
    """Bucket-wise MJRTY combine of two same-shape lane tuples: same key
    → votes add; different keys → the heavier key survives carrying the
    vote difference. Commutative up to dead buckets (an exact vote tie
    between different keys leaves votes=0 — empty either way)."""
    va, ha, la, aa, ab_ = a
    vb, hb, lb, ba, bb = b
    va_, vb_ = jnp.maximum(va, 0), jnp.maximum(vb, 0)
    same = (ha == hb) & (la == lb)
    take_b = ~same & (vb_ > va_)
    v = jnp.where(same, va_ + vb_, jnp.abs(va_ - vb_))
    pick = lambda x, y: jnp.where(take_b, y, x)
    return v, pick(ha, hb), pick(la, lb), pick(aa, ba), pick(ab_, bb)


def topk_candidates(votes, key_hi, key_lo, id_a, id_b):
    """Host-side inversion, step 1: read every surviving bucket
    (votes > 0) straight out of the sketch → flat np candidate arrays
    (key_hi, key_lo, id_a, id_b, votes)."""
    v = np.asarray(votes).reshape(-1)
    keep = v > 0
    flat = lambda x: np.asarray(x).reshape(-1)[keep]
    return flat(key_hi), flat(key_lo), flat(id_a), flat(id_b), v[keep]


def topk_select(cand_hi, cand_lo, cand_ia, cand_ib, estimates, k: int):
    """Host-side inversion, step 2: dedupe candidates by key, rank by
    the (caller-supplied, e.g. count-min) estimate, return the top-k
    row indices into the deduped arrays → (hi, lo, id_a, id_b, est)."""
    if len(cand_hi) == 0:
        z = np.zeros((0,), np.uint32)
        return z, z, z, z, np.zeros((0,), np.int64)
    key = cand_hi.astype(np.uint64) << np.uint64(32) | cand_lo.astype(np.uint64)
    _, first = np.unique(key, return_index=True)
    est = np.asarray(estimates)[first]
    rank = np.argsort(-est, kind="stable")[: max(0, k)]
    rows = first[rank]
    return (
        cand_hi[rows],
        cand_lo[rows],
        cand_ia[rows],
        cand_ib[rows],
        est[rank].astype(np.int64),
    )
