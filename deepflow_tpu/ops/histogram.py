"""Log-binned latency histograms (DDSketch-style) — the streaming
quantile path.

Per-group `[num_groups, bins]` int32 planes with geometric bin edges:
bin(v) = floor(log_gamma(v / vmin)). Updates are one scatter-add, merges
are elementwise add (`psum`-able), and quantile queries are a cumsum +
threshold search at flush time. Guaranteed relative quantile error is
(gamma-1)/(gamma+1); the default covers 1µs..~17min at ≤2% error with
1024 bins. At window close the plane can also be compressed into t-digest
centroids (ops/tdigest.py) for compact export.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LogHistSpec:
    bins: int = 1024
    vmin: float = 1.0  # values at/below vmin land in bin 0
    gamma: float = 1.02

    @property
    def vmax(self) -> float:
        return self.vmin * self.gamma ** (self.bins - 1)

    def rel_error(self) -> float:
        return (self.gamma - 1.0) / (self.gamma + 1.0)


def loghist_init(num_groups: int, spec: LogHistSpec) -> jnp.ndarray:
    return jnp.zeros((num_groups, spec.bins), dtype=jnp.int32)


def loghist_bin(values: jnp.ndarray, spec: LogHistSpec) -> jnp.ndarray:
    """[N] f32 values → [N] i32 bin ids."""
    v = jnp.maximum(values.astype(jnp.float32), spec.vmin)
    b = jnp.floor(jnp.log(v / spec.vmin) / math.log(spec.gamma)).astype(jnp.int32)
    return jnp.clip(b, 0, spec.bins - 1)


@partial(jax.jit, static_argnames=("spec",), donate_argnums=(0,))
def loghist_update(state: jnp.ndarray, group_ids, values, valid, spec: LogHistSpec) -> jnp.ndarray:
    b = loghist_bin(values, spec)
    gid = jnp.where(valid, group_ids, state.shape[0])  # OOB → dropped
    return state.at[gid, b].add(1, mode="drop")


@partial(jax.jit, static_argnames=("spec", "qs"))
def loghist_quantiles(state: jnp.ndarray, spec: LogHistSpec, qs: tuple[float, ...]) -> jnp.ndarray:
    """[num_groups, len(qs)] quantile estimates (geometric bin centers)."""
    counts = state.astype(jnp.float32)
    cum = jnp.cumsum(counts, axis=1)
    total = cum[:, -1:]
    centers = spec.vmin * jnp.power(
        jnp.float32(spec.gamma), jnp.arange(spec.bins, dtype=jnp.float32) + 0.5
    )
    out = []
    for q in qs:
        target = q * total  # rank threshold per group
        idx = jnp.sum((cum < target).astype(jnp.int32), axis=1)
        idx = jnp.clip(idx, 0, spec.bins - 1)
        est = centers[idx]
        out.append(jnp.where(total[:, 0] > 0, est, 0.0))
    return jnp.stack(out, axis=1)


def loghist_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a + b


# ---------------------------------------------------------------------------
# pooled sub-sketch form (ISSUE 20): a compact pool slot keeps
# bins//factor geometric bins — equivalent to the same spec with
# gamma^factor, so the compact relative-error bound widens to
# (gamma^f - 1)/(gamma^f + 1). The compact bin derives from the ALREADY
# computed wide bin by integer division (exact — no second float
# binning that could drift off by one), and expansion re-centers each
# compact bin at the middle wide bin it covers.


def loghist_coarsen_bin(wide_bin, factor: int, xp=jnp):
    """[N] wide bin ids → compact bin ids (factor wide bins per compact
    bin). Exact integer correspondence with `loghist_bin` at the wide
    spec."""
    return xp.asarray(wide_bin) // factor


def loghist_expand(compact, bins: int, xp=jnp):
    """[..., bins//factor] compact counts → [..., bins], each compact
    bin's mass placed at the central wide bin it covers (matches the
    geometric-center estimate `loghist_quantiles`/tdigest read)."""
    bc = compact.shape[-1]
    factor = bins // bc
    assert factor * bc == bins, (bc, bins)
    out = xp.zeros(compact.shape[:-1] + (bins,), dtype=compact.dtype)
    centers = xp.arange(bc) * factor + factor // 2
    if xp is jnp:
        return out.at[..., centers].set(compact)
    out[..., centers] = compact
    return out
