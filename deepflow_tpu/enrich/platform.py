"""Tag enrichment — the TPU-native `DocumentExpand`.

The reference enriches every document on the ingest host by chasing
hash-map pointers per doc (unmarshaller/handle_document.go:114-270,
grpc_platformdata.go:263-392): gpid→pod fill, pod→info, MAC→info,
(EPC,IP)→info fallback chain, pod-service / custom-service resolution,
auto_service / auto_instance priority encoding, multicast peer fill,
other-region drop and OTel fixups.

Here the whole batch is enriched *on device*: the controller-synced
platform metadata is compiled by the host into `DeviceHashTable`s +
a dense info matrix (see ops/hashtable.py), and `enrich_docs` resolves
every doc row with vectorized probes and gathers — no per-row host work.
The fallback chain becomes nested `jnp.where` selects; the region filter
becomes a keep-mask instead of an error return.

Deviation from the reference (documented): pod-service resolution
(grpc_platformdata.go:1685-2054 QueryPodService) is keyed here on
(pod_group_id | pod_node_id, protocol, port) with port-0 wildcard rows,
rather than the reference's clusterIP/backend-IP LRU complex; custom
services are keyed on (EPC, IP[, port]) exactly like the reference's
QueryCustomService.
"""

from __future__ import annotations

import dataclasses
import ipaddress

import jax
import jax.numpy as jnp
import numpy as np

from ..datamodel.code import CodeId, SignalSource
from ..datamodel.schema import TAG_SCHEMA
from ..ops.hashing import fingerprint64
from ..ops.hashtable import DeviceHashTable, build_table, empty_table

_T = TAG_SCHEMA

# EPC sentinel values, i16 sign-folded to u16 (datatype/endpoint.go:28-30).
EPC_INTERNET = 0xFFFE  # -2
EPC_UNKNOWN = 0

# TagSource bits (flow-metrics/tag.go:257-266).
TS_GPID = 1
TS_POD_ID = 2
TS_MAC = 4
TS_EPC_IP = 8
TS_PEER = 16

# AutoService/AutoInstance type codes (trident.proto:332-364,
# ingester/common/common.go:145-193).
TYPE_INTERNET_IP = 0
TYPE_IP = 255
TYPE_POD = 10
TYPE_POD_SERVICE = 11
TYPE_POD_NODE = 14
TYPE_POD_CLUSTER = 103
TYPE_CUSTOM_SERVICE = 104
TYPE_PROCESS = 120
DEVICE_TYPE_POD_SERVICE = 11

# Info matrix column layout (grpc.Info, grpc_platformdata.go:64-90).
INFO_FIELDS = (
    "region_id",
    "host_id",
    "l3_device_id",
    "l3_device_type",
    "subnet_id",
    "pod_node_id",
    "pod_ns_id",
    "az_id",
    "pod_group_id",
    "pod_group_type",
    "pod_id",
    "pod_cluster_id",
)
_I = {n: i for i, n in enumerate(INFO_FIELDS)}

# Per-side enrichment output columns.
ENRICH_FIELDS = INFO_FIELDS + (
    "service_id",
    "auto_instance_id",
    "auto_instance_type",
    "auto_service_id",
    "auto_service_type",
    "tag_source",
)

# Table-key seeds: each keyspace prepends a distinct discriminator column
# so fingerprints never collide across tables that share a state pytree.
_KS_MAC = 1
_KS_EPC_IP = 2
_KS_POD_SVC = 3
_KS_CUSTOM_SVC = 4


def _ip_words(ip) -> tuple[int, tuple[int, int, int, int]]:
    """Accept '1.2.3.4', 'fd00::1', int (v4), or (is_v6, words) →
    (is_v6, 4×u32 words, v4 right-aligned in word 3)."""
    if isinstance(ip, tuple):
        return ip
    if isinstance(ip, int):
        return 0, (0, 0, 0, ip & 0xFFFFFFFF)
    addr = ipaddress.ip_address(ip)
    n = int(addr)
    if addr.version == 4:
        return 0, (0, 0, 0, n)
    return 1, tuple((n >> s) & 0xFFFFFFFF for s in (96, 64, 32, 0))


def _fold_epc(epc: int) -> int:
    return epc & 0xFFFF


def _fp_np(cols: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    mat = np.stack([np.asarray(c, np.uint32) for c in cols], axis=1)
    return fingerprint64(mat, xp=np)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PlatformState:
    """Device-resident platform metadata (one refresh generation)."""

    infos: jnp.ndarray  # [M, len(INFO_FIELDS)] u32; row 0 = zero info
    gproc_rows: jnp.ndarray  # [G, 2] u32 (agent_id, pod_id); row 0 = zeros
    pod_t: DeviceHashTable  # pod_id → info row
    mac_t: DeviceHashTable  # fp(epc, mac) → info row
    epcip_t: DeviceHashTable  # fp(is_v6, epc, ip words) → info row
    gproc_t: DeviceHashTable  # gpid → gproc row
    podsvc_t: DeviceHashTable  # fp(kind, id, proto, port) → service_id
    customsvc_t: DeviceHashTable  # fp(is_v6, epc, ip words, port) → service_id
    my_region_id: jnp.ndarray  # scalar u32 (0 = no region filtering)


class PlatformInfoTable:
    """Host-side registry; `build()` compiles to a `PlatformState`.

    The controller sync path (trisolaris push → PlatformInfoTable refresh,
    grpc_platformdata.go:147) maps to: apply updates here, rebuild, and
    swap the new pytree into the jit'd pipeline — generation semantics
    instead of in-place LRU mutation.
    """

    def __init__(self, my_region_id: int = 0):
        self.my_region_id = my_region_id
        self._infos: list[dict] = []
        self._pod: dict[int, int] = {}
        self._mac: dict[tuple[int, int], int] = {}  # (epc, mac48) → info idx
        self._epcip: dict[tuple, int] = {}  # (is_v6, epc, words) → info idx
        self._gproc: dict[int, tuple[int, int]] = {}  # gpid → (agent, pod)
        self._podsvc: dict[tuple, int] = {}  # (kind, id, proto, port) → svc
        self._customsvc: dict[tuple, int] = {}  # (is_v6, epc, words, port) → svc

    # -- population ----------------------------------------------------
    def add_info(self, *, epc_id: int = 0, ips=(), mac: int = 0, pod_id: int = 0, **fields):
        """Register one resource (interface/pod) with its metadata.

        `fields` are INFO_FIELDS values; `ips`/`mac`/`pod_id` key it.
        """
        unknown = set(fields) - set(INFO_FIELDS)
        if unknown:
            raise KeyError(f"unknown info fields: {unknown}")
        idx = len(self._infos) + 1  # row 0 is the zero info
        rec = {f: int(fields.get(f, 0)) for f in INFO_FIELDS}
        if pod_id:
            rec["pod_id"] = int(pod_id)  # keys double as Info.PodID
        self._infos.append(rec)
        epc = _fold_epc(epc_id)
        if pod_id:
            self._pod[pod_id] = idx
        if mac:
            self._mac[(epc, mac)] = idx
        for ip in ips:
            is_v6, words = _ip_words(ip)
            self._epcip[(is_v6, epc, words)] = idx
        return idx

    def add_gprocess(self, gpid: int, agent_id: int, pod_id: int):
        self._gproc[gpid] = (agent_id, pod_id)

    def add_pod_service(self, service_id: int, *, pod_group_id: int = 0, pod_node_id: int = 0, protocol: int = 0, server_port: int = 0):
        """port/protocol 0 rows act as wildcards (any-port service)."""
        if pod_group_id:
            self._podsvc[(0, pod_group_id, protocol, server_port)] = service_id
        if pod_node_id:
            self._podsvc[(1, pod_node_id, protocol, server_port)] = service_id

    def add_custom_service(self, service_id: int, *, epc_id: int, ip, server_port: int = 0):
        is_v6, words = _ip_words(ip)
        self._customsvc[(is_v6, _fold_epc(epc_id), words, server_port)] = service_id

    # -- compile -------------------------------------------------------
    def build(self) -> PlatformState:
        infos = np.zeros((len(self._infos) + 1, len(INFO_FIELDS)), dtype=np.uint32)
        for i, rec in enumerate(self._infos):
            infos[i + 1] = [rec[f] for f in INFO_FIELDS]

        gproc_rows = np.zeros((len(self._gproc) + 1, 2), dtype=np.uint32)
        g_keys, g_vals = [], []
        for i, (gpid, (agent, pod)) in enumerate(self._gproc.items()):
            gproc_rows[i + 1] = (agent, pod)
            g_keys.append(gpid)
            g_vals.append(i + 1)

        def table(d: dict, key_fn) -> DeviceHashTable:
            if not d:
                return empty_table()
            cols = [key_fn(k) for k in d]
            hi, lo = _fp_np([np.array([c[j] for c in cols], np.uint32) for j in range(len(cols[0]))])
            return build_table(hi, lo, np.array(list(d.values()), np.uint32))

        pod_t = (
            build_table(
                np.zeros(len(self._pod), np.uint32),
                np.fromiter(self._pod.keys(), np.uint32, len(self._pod)),
                np.fromiter(self._pod.values(), np.uint32, len(self._pod)),
            )
            if self._pod
            else empty_table()
        )
        gproc_t = (
            build_table(
                np.zeros(len(g_keys), np.uint32),
                np.asarray(g_keys, np.uint32),
                np.asarray(g_vals, np.uint32),
            )
            if g_keys
            else empty_table()
        )
        mac_t = table(self._mac, lambda k: (_KS_MAC, k[0], (k[1] >> 32) & 0xFFFF, k[1] & 0xFFFFFFFF))
        epcip_t = table(self._epcip, lambda k: (_KS_EPC_IP, k[0], k[1], *k[2]))
        podsvc_t = table(self._podsvc, lambda k: (_KS_POD_SVC, *k))
        customsvc_t = table(
            self._customsvc, lambda k: (_KS_CUSTOM_SVC, k[0], k[1], *k[2], k[3])
        )
        return PlatformState(
            infos=jnp.asarray(infos),
            gproc_rows=jnp.asarray(gproc_rows),
            pod_t=pod_t,
            mac_t=mac_t,
            epcip_t=epcip_t,
            gproc_t=gproc_t,
            podsvc_t=podsvc_t,
            customsvc_t=customsvc_t,
            my_region_id=jnp.asarray(self.my_region_id, jnp.uint32),
        )


def _fp_cols(cols):
    mat = jnp.stack([jnp.asarray(c, jnp.uint32) for c in cols], axis=1)
    return fingerprint64(mat)


def _col(tags, name):
    return tags[:, _T.index(name)]


def _lookup_fp(t: DeviceHashTable, cols):
    hi, lo = _fp_cols(cols)
    return t.lookup(hi, lo)


def _is_multicast(is_v6, w0, w3):
    v4 = (w3 >> jnp.uint32(28)) == jnp.uint32(0xE)
    v6 = (w0 >> jnp.uint32(24)) == jnp.uint32(0xFF)
    return jnp.where(is_v6 != 0, v6, v4)


def _enrich_side(state: PlatformState, tags, side: int, is_edge, is_otel):
    """Resolve one endpoint: the getPlatformInfos fallback chain
    (handle_document.go:41-112) + service/auto encodings (:137-240)."""
    n = tags.shape[0]
    zero = jnp.zeros((n,), jnp.uint32)
    sfx = "" if side == 0 else "1"
    epc = _col(tags, "l3_epc_id" + sfx) & jnp.uint32(0xFFFF)
    gpid = _col(tags, "gpid" + ("0" if side == 0 else "1"))
    mac_hi = _col(tags, f"mac{side}_hi")
    mac_lo = _col(tags, f"mac{side}_lo")
    ipw = [_col(tags, f"ip{side}_w{w}") for w in range(4)]
    is_v6 = _col(tags, "is_ipv6")
    agent_id = _col(tags, "agent_id")
    pod = _col(tags, "pod_id") if side == 0 else zero

    # side 1 participates only in edge docs; side 0 always.
    in_play = (is_edge if side == 1 else jnp.ones((n,), bool)) & (
        epc != jnp.uint32(EPC_INTERNET)
    )
    tag_source = zero

    # gpid → pod fill (QueryGprocessInfo; agent match required)
    g_row, g_found = state.gproc_t.lookup(zero, gpid)
    g_row = jnp.where(g_found, g_row, 0)
    g_agent = state.gproc_rows[g_row, 0]
    g_pod = state.gproc_rows[g_row, 1]
    use_gproc = in_play & (gpid != 0) & (pod == 0) & g_found & (g_pod != 0) & (g_agent == agent_id)
    pod = jnp.where(use_gproc, g_pod, pod)
    tag_source = tag_source | jnp.where(use_gproc, jnp.uint32(TS_GPID), 0)

    # pod → info
    try_pod = in_play & (pod != 0)
    pod_idx, pod_found = state.pod_t.lookup(zero, pod)
    pod_hit = try_pod & pod_found
    tag_source = tag_source | jnp.where(try_pod, jnp.uint32(TS_POD_ID), 0)

    # mac → info (key includes EPC, grpc_platformdata.go:63)
    try_mac = in_play & ~pod_hit & ((mac_hi | mac_lo) != 0)
    mac_idx, mac_found = _lookup_fp(
        state.mac_t, [jnp.full((n,), _KS_MAC, jnp.uint32), epc, mac_hi, mac_lo]
    )
    mac_hit = try_mac & mac_found
    tag_source = tag_source | jnp.where(try_mac, jnp.uint32(TS_MAC), 0)

    # (EPC, IP) → info
    ip_idx, ip_found = _lookup_fp(
        state.epcip_t, [jnp.full((n,), _KS_EPC_IP, jnp.uint32), is_v6, epc, *ipw]
    )
    try_ip = in_play & ~pod_hit & ~mac_hit
    ip_hit = try_ip & ip_found
    tag_source = tag_source | jnp.where(try_ip, jnp.uint32(TS_EPC_IP), 0)

    have = pod_hit | mac_hit | ip_hit
    idx = jnp.where(pod_hit, pod_idx, jnp.where(mac_hit, mac_idx, jnp.where(ip_hit, ip_idx, 0)))
    info = jnp.where(have[:, None], state.infos[idx], 0)

    out = {f: info[:, _I[f]] for f in INFO_FIELDS}
    # matched info overwrites PodID (handle_document.go:192 t.PodID =
    # info.PodID); with no info the original/gpid-filled pod survives for
    # the auto_instance chain (GetAutoInstance takes t.PodID)
    out["pod_id"] = jnp.where(have, out["pod_id"], pod)

    # -- pod service (IsPodServiceIP gate, handle_document.go:151,194-202)
    dev_type = out["l3_device_type"]
    server_port = _col(tags, "server_port")
    protocol = _col(tags, "protocol")
    is_pod_svc_ip = (dev_type == jnp.uint32(DEVICE_TYPE_POD_SERVICE)) | (out["pod_id"] != 0) | (out["pod_node_id"] != 0)
    if side == 0:
        # single-side with valid port → port-matched; else any-port, and
        # pod-node-only endpoints don't match (handle_document.go:199).
        use_port = (server_port > 0) & ~is_edge
        port_key = jnp.where(use_port, server_port, zero)
        proto_key = jnp.where(use_port, protocol, zero)
        gate = have & is_pod_svc_ip & (
            use_port
            | (dev_type == jnp.uint32(DEVICE_TYPE_POD_SERVICE))
            | (out["pod_id"] != 0)
        )
    else:
        port_key = server_port
        proto_key = protocol
        gate = have & is_pod_svc_ip

    def podsvc_lookup(kind_const, ident, proto_c, port_c):
        v, f = _lookup_fp(
            state.podsvc_t,
            [
                jnp.full((n,), _KS_POD_SVC, jnp.uint32),
                jnp.full((n,), kind_const, jnp.uint32),
                ident,
                proto_c,
                port_c,
            ],
        )
        return v, f

    svc = zero
    svc_found = jnp.zeros((n,), bool)
    for kind, ident in ((0, out["pod_group_id"]), (1, out["pod_node_id"])):
        for p_proto, p_port in ((proto_key, port_key), (zero, zero)):
            v, f = podsvc_lookup(kind, ident, p_proto, p_port)
            use = gate & (ident != 0) & f & ~svc_found
            svc = jnp.where(use, v, svc)
            svc_found = svc_found | use
    out["service_id"] = svc

    # -- custom service (QueryCustomService: exact port then any-port).
    # Side 0 uses the port only for single-side docs (handle_document.go:236-238);
    # side 1 always does (:178).
    cs_port = server_port if side == 1 else jnp.where(~is_edge, server_port, zero)
    cs = zero
    cs_found = jnp.zeros((n,), bool)
    for p in (cs_port, zero):
        v, f = _lookup_fp(
            state.customsvc_t,
            [jnp.full((n,), _KS_CUSTOM_SVC, jnp.uint32), is_v6, epc, *ipw, p],
        )
        use = f & ~cs_found & (epc != jnp.uint32(EPC_INTERNET))
        cs = jnp.where(use, v, cs)
        cs_found = cs_found | use

    # -- auto instance / auto service priority chains (common.go:160-193)
    is_internet = epc == jnp.uint32(EPC_INTERNET)

    def chain(*pairs, internet, fallback):
        cid, ctype = fallback
        cid, ctype = jnp.where(is_internet, internet[0], cid), jnp.where(
            is_internet, internet[1], ctype
        )
        for pid, ptype in reversed(pairs):
            take = pid > 0
            cid = jnp.where(take, pid, cid)
            ctype = jnp.where(take, ptype, ctype)
        return cid, ctype

    dev = out["l3_device_id"]
    out["auto_instance_id"], out["auto_instance_type"] = chain(
        (out["pod_id"], jnp.full((n,), TYPE_POD, jnp.uint32)),
        (gpid, jnp.full((n,), TYPE_PROCESS, jnp.uint32)),
        (out["pod_node_id"], jnp.full((n,), TYPE_POD_NODE, jnp.uint32)),
        (dev, dev_type),
        internet=(zero, jnp.full((n,), TYPE_INTERNET_IP, jnp.uint32)),
        fallback=(out["subnet_id"], jnp.full((n,), TYPE_IP, jnp.uint32)),
    )
    out["auto_service_id"], out["auto_service_type"] = chain(
        (cs, jnp.full((n,), TYPE_CUSTOM_SERVICE, jnp.uint32)),
        (svc, jnp.full((n,), TYPE_POD_SERVICE, jnp.uint32)),
        (out["pod_group_id"], out["pod_group_type"]),
        (gpid, jnp.full((n,), TYPE_PROCESS, jnp.uint32)),
        (out["pod_cluster_id"], jnp.full((n,), TYPE_POD_CLUSTER, jnp.uint32)),
        (dev, dev_type),
        internet=(zero, jnp.full((n,), TYPE_INTERNET_IP, jnp.uint32)),
        fallback=(out["subnet_id"], jnp.full((n,), TYPE_IP, jnp.uint32)),
    )

    # OTel: Internet-typed endpoints display as plain IP (handle_document.go:255-266)
    for f in ("auto_service_type", "auto_instance_type"):
        out[f] = jnp.where(
            is_otel & (out[f] == jnp.uint32(TYPE_INTERNET_IP)), jnp.uint32(TYPE_IP), out[f]
        )

    out["tag_source"] = tag_source
    return out, have


@jax.jit
def enrich_docs(state: PlatformState, tags: jnp.ndarray, valid: jnp.ndarray):
    """Enrich a doc batch: [N, T] u32 tag matrix → (side0 dict, side1 dict,
    keep mask, other_region_drops).

    keep = valid ∧ ¬other-region (the reference returns an error per doc
    and drops it, handle_document.go:170-231).
    """
    code_id = _col(tags, "code_id")
    is_edge = (code_id >= jnp.uint32(CodeId.EDGE_IP_PORT)) & (
        code_id <= jnp.uint32(CodeId.EDGE_MAC_IP_PORT_APP)
    )
    sig = _col(tags, "signal_source")
    is_otel = sig == jnp.uint32(SignalSource.OTEL)
    # exact CLIENT/SERVER compare — sided variants (e.g. SERVER_NODE) are
    # not region-checked in the reference (handle_document.go:171,221)
    tap_side = _col(tags, "tap_side")

    side0, have0 = _enrich_side(state, tags, 0, is_edge, is_otel)
    side1, have1 = _enrich_side(state, tags, 1, is_edge, is_otel)

    # multicast peer fill (handle_document.go:154-168, 203-217)
    is_v6 = _col(tags, "is_ipv6")
    mc0 = _is_multicast(is_v6, _col(tags, "ip0_w0"), _col(tags, "ip0_w3"))
    mc1 = _is_multicast(is_v6, _col(tags, "ip1_w0"), _col(tags, "ip1_w3"))
    fill0 = ~have0 & have1 & mc0 & is_edge
    fill1 = ~have1 & have0 & mc1 & is_edge
    for f in ("region_id", "subnet_id", "az_id"):
        side0[f] = jnp.where(fill0, side1[f], side0[f])
        side1[f] = jnp.where(fill1, side0[f], side1[f])
    side0["tag_source"] = side0["tag_source"] | jnp.where(fill0, jnp.uint32(TS_PEER), 0)
    side1["tag_source"] = side1["tag_source"] | jnp.where(fill1, jnp.uint32(TS_PEER), 0)

    # other-region filter (handle_document.go:170-231): single-side docs
    # must match my region; edge docs check the observation side.
    my = state.my_region_id
    r0, r1 = side0["region_id"], side1["region_id"]
    filtering = my != 0
    bad_single = ~is_edge & (r0 != 0) & (r0 != my)
    bad_edge_client = is_edge & (tap_side == 1) & (r0 != 0) & (r0 != my)
    bad_edge_server = is_edge & (tap_side == 2) & (r1 != 0) & (r1 != my)
    other_region = filtering & (bad_single | bad_edge_client | bad_edge_server)
    keep = valid & ~other_region

    drops = jnp.sum((valid & other_region).astype(jnp.int32))
    return side0, side1, keep, drops
