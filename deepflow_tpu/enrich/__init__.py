from .platform import (  # noqa: F401
    ENRICH_FIELDS,
    PlatformInfoTable,
    PlatformState,
    enrich_docs,
)
