"""deepflow_tpu — TPU-native flow-metrics aggregation framework.

A ground-up JAX/XLA re-design of DeepFlow's server-side metrics plane
(reference: svc-design/deepflow; see /root/repo/SURVEY.md): windowed
tag-dimension group-by of flow meters via sort + segment-reduce, streaming
sketches (HyperLogLog, count-min, log-histogram → t-digest) for
per-service rollups, sharded over device meshes with collective merges.
"""

__version__ = "0.1.0"
