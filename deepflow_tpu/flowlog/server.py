"""flow_log ingester — TAGGEDFLOW / PROTOCOLLOG frames → storage rows.

The TPU re-composition of server/ingester/flow_log: receiver fanout into
per-type decode queues (decoder/decoder.go:150), schema-driven columnar
decode, per-second throttled sampling (throttler/throttling_queue.go),
device tag enrichment (the L4/L7FlowLog.Fill PlatformInfoTable queries,
log_data/l4_flow_log.go), and batched columnar writes into the
`flow_log` database (l4_flow_log / l7_flow_log tables).

Enrichment rides the existing enrich_docs kernel: log identity columns
are gathered into a TAG_SCHEMA-shaped matrix (edge Code so both sides
resolve) — one jit kernel serves metrics and logs.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..datamodel.code import CodeId
from ..datamodel.schema import TAG_SCHEMA
from ..enrich.platform import ENRICH_FIELDS, PlatformState, enrich_docs
from ..ingest.framing import HEADER_LEN, FlowHeader, MessageType, split_messages
from ..ingest.queues import new_queue
from ..ingest.receiver import Receiver
from ..storage.store import ColumnarStore, ColumnSpec, TableSchema, org_db
from ..storage.writer import TableWriter
from ..utils.stats import register_countable
from .aggr import FlowLogBatch, ThrottlingQueue
from .codec import decode_rows
from .schema import L4_FLOW_LOG, L7_FLOW_LOG, LogSchema

FLOW_LOG_DB = "flow_log"

# log int column → TAG_SCHEMA column feeding the enrichment kernel
_TAG_FROM_LOG = {
    "agent_id": "agent_id",
    "is_ipv6": "is_ipv6",
    **{f"ip{s}_w{w}": f"ip{s}_w{w}" for s in (0, 1) for w in range(4)},
    "l3_epc_id_0": "l3_epc_id",
    "l3_epc_id_1": "l3_epc_id1",
    "server_port": "server_port",
    "protocol": "protocol",
    "tap_side": "tap_side",
    "gpid_0": "gpid0",
    "gpid_1": "gpid1",
    "signal_source": "signal_source",
    "l7_protocol": "l7_protocol",
    "pod_id_0": "pod_id",
}


# table columns provided by enrichment; same-named raw log ints (the
# agent-reported pod ids) feed the kernel but the enriched value is what
# lands in the table (DocumentExpand overwrite stance)
_ENRICH_COLS = {f"{f}_{s}" for f in ENRICH_FIELDS for s in (0, 1)}


def log_table_schema(schema: LogSchema, partition_s: int = 3600) -> TableSchema:
    cols = [ColumnSpec("time", "u4")]
    cols += [ColumnSpec(f.name, "u4") for f in schema.ints if f.name not in _ENRICH_COLS]
    cols += [ColumnSpec(f.name, "f4") for f in schema.nums]
    cols += [ColumnSpec(f.name, "U256") for f in schema.strs]
    cols += [ColumnSpec(f"{f}_{s}", "u4") for s in (0, 1) for f in ENRICH_FIELDS]
    return TableSchema(schema.name, tuple(cols), partition_s=partition_s)


def log_batch_to_columns(
    batch: FlowLogBatch, enrich0: dict | None = None, enrich1: dict | None = None
) -> dict[str, np.ndarray]:
    """FlowLogBatch → storage columns for log_table_schema tables.

    THE one assembly for every l7/l4 log write path (throttled ingest,
    OTel spans, future sources): time from end_time, raw ints except the
    enrichment-owned columns, nums, strings, and per-side enrich columns
    (from the given dicts, falling back to raw agent-reported ints, then
    zeros)."""
    schema = batch.schema
    cols: dict[str, np.ndarray] = {"time": batch.col("end_time").astype(np.uint32)}
    for i, f in enumerate(schema.ints):
        if f.name not in _ENRICH_COLS:
            cols[f.name] = batch.ints[:, i]
    for i, f in enumerate(schema.nums):
        cols[f.name] = batch.nums[:, i]
    for f in schema.strs:
        cols[f.name] = np.array(batch.strs[f.name] if batch.strs else [""] * batch.size)
    for side, enriched in ((0, enrich0), (1, enrich1)):
        for f in ENRICH_FIELDS:
            name = f"{f}_{side}"
            if enriched is not None:
                cols[name] = np.asarray(enriched[f])[: batch.size]
            elif name in schema._int_idx:
                cols[name] = batch.ints[:, schema.int_index(name)]
            else:
                cols[name] = np.zeros(batch.size, np.uint32)
    return cols


def _tags_for_enrich(batch: FlowLogBatch) -> np.ndarray:
    n = batch.size
    p = max(1, 1 << (n - 1).bit_length())  # pad to pow2 → O(log N) jit shapes
    tags = np.zeros((p, TAG_SCHEMA.num_fields), np.uint32)
    s = batch.schema
    for log_col, tag_col in _TAG_FROM_LOG.items():
        if log_col in s._int_idx:
            tags[:n, TAG_SCHEMA.index(tag_col)] = batch.ints[:, s.int_index(log_col)]
    # edge Code: both endpoints resolve (l4_flow_log enriches both sides)
    tags[:n, TAG_SCHEMA.index("code_id")] = np.uint32(CodeId.EDGE_IP_PORT)
    valid = np.zeros(p, bool)
    valid[:n] = batch.valid
    return tags, valid, n


class FlowLogIngester:
    """TAGGEDFLOW + PROTOCOLLOG pipelines → flow_log db."""

    def __init__(
        self,
        receiver: Receiver,
        store: ColumnarStore,
        *,
        platform_state: PlatformState | None = None,
        l4_throttle: int = 50000,
        l7_throttle: int = 50000,
        n_workers: int = 1,
        queue_capacity: int = 1 << 14,
        batch_size: int = 128,
        writer_args: dict | None = None,
    ):
        self.store = store
        self.platform_state = platform_state
        self.batch_size = batch_size
        self.writer_args = writer_args or {}
        self._writers: dict[tuple[str, str], TableWriter] = {}
        self._throttles = {
            MessageType.TAGGEDFLOW: l4_throttle,
            MessageType.PROTOCOLLOG: l7_throttle,
        }
        self._schemas = {
            MessageType.TAGGEDFLOW: L4_FLOW_LOG,
            MessageType.PROTOCOLLOG: L7_FLOW_LOG,
        }
        self.counters = {
            "frames_in": 0,
            "rows_in": 0,
            "rows_written": 0,
            "decode_errors": 0,
            "throttle_dropped": 0,
        }
        self._lock = threading.Lock()
        self._running = True
        self._threads = []
        self.queues = {}
        for mt in (MessageType.TAGGEDFLOW, MessageType.PROTOCOLLOG):
            qs = [new_queue(queue_capacity, prefer_native=False) for _ in range(n_workers)]
            receiver.register_handler(mt, qs)
            self.queues[mt] = qs
            for q in qs:
                t = threading.Thread(target=self._worker, args=(mt, q), daemon=True)
                t.start()
                self._threads.append(t)
        register_countable("flow_log_ingester", self)

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    def _writer(self, db: str, schema: LogSchema) -> TableWriter:
        with self._lock:
            w = self._writers.get((db, schema.name))
            if w is None:
                w = TableWriter(
                    self.store, db, log_table_schema(schema), **self.writer_args
                )
                self._writers[(db, schema.name)] = w
            return w

    # -- worker ---------------------------------------------------------
    def _worker(self, mt: MessageType, q) -> None:
        """One throttler per (worker, org): reservoirs and org→db
        attribution must not mix tenants (the reference fans out by org at
        the receiver; here the queue is shared so the split is per-org)."""
        schema = self._schemas[mt]
        throttlers: dict[int, ThrottlingQueue] = {}
        max_sec: dict[int, int] = {}
        dropped_prev: dict[int, int] = {}
        idle_since: float | None = None
        HOLD_S = 0.3  # how long a stream pause closes the current second

        def _account_drops(org: int, thr: ThrottlingQueue) -> None:
            d = thr.counters["dropped"]
            delta = d - dropped_prev.get(org, 0)
            dropped_prev[org] = d
            if delta:
                with self._lock:
                    self.counters["throttle_dropped"] += delta

        while self._running:
            frames = q.gets(self.batch_size, timeout_ms=100)
            if not frames:
                # stream pause: the in-flight second is wall-clock closed
                # after HOLD_S — drain fully so rows never strand; shorter
                # pauses only drain seconds older than the newest seen
                now = time.monotonic()
                idle_since = idle_since or now
                full = now - idle_since >= HOLD_S
                for org, thr in throttlers.items():
                    up_to = None if full else max_sec.get(org)
                    self._emit(mt, thr.drain(up_to_sec=up_to), org)
                    _account_drops(org, thr)
                continue
            idle_since = None
            for raw in frames:
                try:
                    header = FlowHeader.parse(raw[:HEADER_LEN])
                    msgs = split_messages(raw[HEADER_LEN:])
                except ValueError:
                    with self._lock:
                        self.counters["decode_errors"] += 1
                    continue
                org = header.organization_id
                batch, errors = decode_rows(schema, msgs)
                with self._lock:
                    self.counters["frames_in"] += 1
                    self.counters["rows_in"] += int(batch.valid.sum())
                    self.counters["decode_errors"] += errors
                thr = throttlers.get(org)
                if thr is None:
                    thr = throttlers[org] = ThrottlingQueue(self._throttles[mt])
                thr.put(batch)
                sec = int(batch.col("end_time").max(initial=0))
                if sec > max_sec.get(org, 0):
                    # buckets strictly older than the newest second are closed
                    max_sec[org] = sec
                    self._emit(mt, thr.drain(up_to_sec=sec), org)
                _account_drops(org, thr)
        for org, thr in throttlers.items():  # shutdown: flush everything
            self._emit(mt, thr.drain(), org)
            _account_drops(org, thr)

    def _emit(self, mt: MessageType, sampled: list[FlowLogBatch], org: int) -> None:
        db = org_db(FLOW_LOG_DB, org)
        schema = self._schemas[mt]
        for batch in sampled:
            s0 = s1 = None
            if self.platform_state is not None:
                tags, valid, _n = _tags_for_enrich(batch)
                s0, s1, _keep, _drops = enrich_docs(self.platform_state, tags, valid)
            cols = log_batch_to_columns(batch, s0, s1)
            self._writer(db, schema).put(cols)
            with self._lock:
                self.counters["rows_written"] += batch.size

    def flush(self):
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.flush()

    def stop(self, timeout: float = 5.0):
        self._running = False
        for qs in self.queues.values():
            for q in qs:
                q.close()
        for t in self._threads:
            t.join(timeout=timeout)
        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            w.stop()
