"""Schema-driven flow-log wire codec.

The reference ships flow logs as protobuf `TaggedFlow` / `AppProtoLogsData`
messages (message/flow_log.proto:14,211) inside the standard framed
transport. Our wire format keeps the proto3 encoding primitives (varint /
length-delimited, so frames remain debuggable with stock pb tooling) but
derives the message layout from the LogSchema instead of a hand-kept
.proto tree: int lanes get field ids 1..Ki, num lanes Ki+1..Ki+Kn
(varint of the integral value), string columns after that
(length-delimited UTF-8). Zero values are omitted, proto3-style.

One codec serves every LogSchema — l4, l7, and any future log table —
and the columnar decode fills SoA lanes directly, never building row
objects (the DecodePB stance, libs/app/codec.go:28).
"""

from __future__ import annotations

import numpy as np

from ..ingest.codec import _iter_fields, _put_varint
from .aggr import FlowLogBatch
from .schema import LogSchema


def encode_rows(batch: FlowLogBatch) -> list[bytes]:
    s = batch.schema
    ki, kn = len(s.ints), len(s.nums)
    out = []
    ints = batch.ints
    nums = batch.nums
    for r in range(batch.size):
        if not batch.valid[r]:
            continue
        buf = bytearray()
        for i in range(ki):
            v = int(ints[r, i])
            if v:
                _put_varint(buf, (i + 1) << 3 | 0)
                _put_varint(buf, v)
        for j in range(kn):
            v = int(nums[r, j])
            if v:
                _put_varint(buf, (ki + 1 + j) << 3 | 0)
                _put_varint(buf, v)
        if batch.strs:
            for k, f in enumerate(s.strs):
                sv = batch.strs[f.name][r]
                if sv:
                    b = sv.encode()
                    _put_varint(buf, (ki + kn + 1 + k) << 3 | 2)
                    _put_varint(buf, len(b))
                    buf += b
        out.append(bytes(buf))
    return out


def decode_rows(schema: LogSchema, msgs: list[bytes]) -> tuple[FlowLogBatch, int]:
    """Decode messages → FlowLogBatch; returns (batch, decode_errors)."""
    ki, kn = len(schema.ints), len(schema.nums)
    ks = len(schema.strs)
    n = len(msgs)
    ints = np.zeros((n, ki), np.uint32)
    nums = np.zeros((n, kn), np.float32)
    strs: dict[str, list[str]] = {f.name: [""] * n for f in schema.strs}
    valid = np.zeros(n, bool)
    errors = 0
    for r, msg in enumerate(msgs):
        try:
            for field, v in _iter_fields(msg):
                if 1 <= field <= ki:
                    ints[r, field - 1] = v & 0xFFFFFFFF
                elif ki < field <= ki + kn:
                    nums[r, field - ki - 1] = float(v)
                elif ki + kn < field <= ki + kn + ks and isinstance(v, (bytes, bytearray)):
                    strs[schema.strs[field - ki - kn - 1].name][r] = bytes(v).decode(
                        errors="replace"
                    )
            valid[r] = True
        except Exception:
            errors += 1
    return FlowLogBatch(schema, ints, nums, valid, strs if ks else None), errors
