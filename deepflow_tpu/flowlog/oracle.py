"""Straight-line NumPy/dict oracle for the flow-log minute merge.

Re-implements minute_merge's per-flow sequential fold (flow_aggr.rs:216)
with Python dicts and exact integer arithmetic, applying each LogSchema
column's merge class in arrival order. Conformance tests replay identical
batches through MinuteAggr (device) and this oracle and assert equal
rows — same role as oracle/numpy_oracle.py for the metrics stash.
"""

from __future__ import annotations

from .aggr import FlowLogBatch
from .schema import LogOp, LogSchema


def minute_merge_oracle(schema: LogSchema, batches: list[FlowLogBatch]) -> dict:
    """→ {(minute, key_tuple): {col: value}} — exact fold in arrival order."""
    out: dict = {}
    for batch in batches:
        for row in batch.to_rows():
            minute = int(row["end_time"]) // 60
            key = (minute,) + tuple(int(row[k]) for k in schema.key)
            cur = out.get(key)
            if cur is None:
                out[key] = {
                    f.name: row[f.name] for f in schema.ints + schema.nums
                }
                continue
            for f in schema.ints:
                v = int(row[f.name])
                if f.op is LogOp.FIRST:
                    pass
                elif f.op is LogOp.LAST:
                    cur[f.name] = v
                elif f.op is LogOp.MIN:
                    cur[f.name] = min(cur[f.name], v)
                elif f.op is LogOp.MAX:
                    cur[f.name] = max(cur[f.name], v)
                elif f.op is LogOp.OR:
                    cur[f.name] = cur[f.name] | v
            for f in schema.nums:
                v = float(row[f.name])
                if f.op is LogOp.SUM:
                    cur[f.name] = cur[f.name] + v
                else:  # MAX
                    cur[f.name] = max(cur[f.name], v)
    return out


def batches_to_dict(schema: LogSchema, batches: list[FlowLogBatch]) -> dict:
    """Flushed device output → same {(minute, key): cols} shape."""
    out: dict = {}
    for batch in batches:
        for row in batch.to_rows():
            minute = int(row["end_time"]) // 60
            key = (minute,) + tuple(int(row[k]) for k in schema.key)
            assert key not in out, f"duplicate merged row {key}"
            out[key] = {f.name: row[f.name] for f in schema.ints + schema.nums}
    return out
