"""Flow-log plane: L4 flow logs (minute-merged TaggedFlows) and L7
request logs, with throttled sampling — the TPU rebuild of
agent/src/collector/flow_aggr.rs + server/ingester/flow_log.
"""
