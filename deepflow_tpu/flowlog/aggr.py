"""L4 flow-log minute aggregation + throttled sampling.

The reference's FlowAggr thread merges the per-second `TaggedFlow`
emissions of each flow into one minute-level log row keyed by flow_id
(`minute_merge`, agent/src/collector/flow_aggr.rs:216 — long-lived flows
emit every second via inject_flush_ticker; the minute merge folds them so
l4_flow_log carries one row per flow per minute), then samples the output
through a per-second reservoir `ThrottlingQueue` (flow_aggr.rs:500,
send_with_throttling :558).

TPU shape: the merge is the same sort→segment-reduce pattern as the
metrics stash, extended with the flow-log merge classes (FIRST/LAST/
MIN/MAX/OR int lanes — see schema.py). Arrival order is the sort
tiebreak, so FIRST/LAST reproduce the reference's sequential-merge
"last arrival wins" lifecycle semantics exactly: stash rows concatenate
before batch rows and `lax.sort` is stable.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.hashing import fingerprint64
from ..ops.segment import SENTINEL_SLOT
from ..utils.stats import register_countable
from .schema import L4_FLOW_LOG, LogOp, LogSchema

_OR_BITS = 16  # OR lanes are bitmasks ≤16 bits (TCP flags)


@dataclasses.dataclass
class FlowLogBatch:
    """SoA flow-log rows: device int/num lanes + host string columns."""

    schema: LogSchema
    ints: np.ndarray  # [N, Ki] u32
    nums: np.ndarray  # [N, Kn] f32
    valid: np.ndarray  # [N] bool
    strs: dict[str, list[str]] | None = None

    @property
    def size(self) -> int:
        return self.ints.shape[0]

    def col(self, name: str) -> np.ndarray:
        s = self.schema
        if name in s._int_idx:
            return self.ints[:, s.int_index(name)]
        return self.nums[:, s.num_index(name)]

    @staticmethod
    def from_rows(schema: LogSchema, rows: list[dict]) -> "FlowLogBatch":
        n = len(rows)
        ints = np.zeros((n, len(schema.ints)), np.uint32)
        nums = np.zeros((n, len(schema.nums)), np.float32)
        strs: dict[str, list[str]] = {f.name: [""] * n for f in schema.strs}
        for r, row in enumerate(rows):
            for k, v in row.items():
                if k in schema._int_idx:
                    ints[r, schema.int_index(k)] = v
                elif k in schema._num_idx:
                    nums[r, schema.num_index(k)] = v
                elif k in strs:
                    strs[k][r] = v
        return FlowLogBatch(schema, ints, nums, np.ones(n, bool), strs or None)

    def to_rows(self) -> list[dict]:
        out = []
        for r in range(self.size):
            if not self.valid[r]:
                continue
            d = {f.name: int(self.ints[r, i]) for i, f in enumerate(self.schema.ints)}
            d.update(
                {f.name: float(self.nums[r, i]) for i, f in enumerate(self.schema.nums)}
            )
            if self.strs:
                d.update({k: v[r] for k, v in self.strs.items()})
            out.append(d)
        return out


# ---------------------------------------------------------------------------
# device kernel


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LogStashState:
    slot: jnp.ndarray  # [S] u32 minute index (SENTINEL = empty)
    key_hi: jnp.ndarray  # [S] u32
    key_lo: jnp.ndarray  # [S] u32
    ints: jnp.ndarray  # [S, Ki] u32
    nums: jnp.ndarray  # [S, Kn] f32
    valid: jnp.ndarray  # [S] bool
    dropped_overflow: jnp.ndarray  # scalar i32

    @property
    def capacity(self) -> int:
        return self.slot.shape[0]


def log_stash_init(capacity: int, schema: LogSchema) -> LogStashState:
    return LogStashState(
        slot=jnp.full((capacity,), SENTINEL_SLOT, dtype=jnp.uint32),
        key_hi=jnp.zeros((capacity,), jnp.uint32),
        key_lo=jnp.zeros((capacity,), jnp.uint32),
        ints=jnp.zeros((capacity, len(schema.ints)), jnp.uint32),
        nums=jnp.zeros((capacity, len(schema.nums)), jnp.float32),
        valid=jnp.zeros((capacity,), bool),
        dropped_overflow=jnp.zeros((), jnp.int32),
    )


def _seg_reduce_ints(ints_sorted, seg_id, first_pos, last_pos, n, col_groups):
    """Apply per-class reductions to the sorted u32 int lanes."""
    ki = ints_sorted.shape[1]
    out = jnp.zeros((n, ki), jnp.uint32)
    first_cols, last_cols, min_cols, max_cols, or_cols = col_groups
    if first_cols.size:
        out = out.at[:, first_cols].set(jnp.take(ints_sorted[:, first_cols], first_pos, axis=0))
    if last_cols.size:
        out = out.at[:, last_cols].set(jnp.take(ints_sorted[:, last_cols], last_pos, axis=0))
    # MIN/MAX run on u32 directly; empty segments get the op identity
    # (0xFFFFFFFF / 0) but are masked invalid downstream regardless.
    if min_cols.size:
        part = jax.ops.segment_min(ints_sorted[:, min_cols], seg_id, num_segments=n)
        out = out.at[:, min_cols].set(part)
    if max_cols.size:
        part = jax.ops.segment_max(ints_sorted[:, max_cols], seg_id, num_segments=n)
        out = out.at[:, max_cols].set(part)
    if or_cols.size:
        # OR = per-bit segment_max over _OR_BITS static lanes
        vals = ints_sorted[:, or_cols]  # [N, O]
        bits = (vals[:, :, None] >> jnp.arange(_OR_BITS, dtype=jnp.uint32)) & 1
        red = jax.ops.segment_max(
            bits.reshape(bits.shape[0], -1).astype(jnp.int32), seg_id, num_segments=n
        )
        red = jnp.maximum(red, 0).reshape(n, or_cols.size, _OR_BITS)
        recombined = jnp.sum(
            red.astype(jnp.uint32) << jnp.arange(_OR_BITS, dtype=jnp.uint32), axis=-1
        )
        out = out.at[:, or_cols].set(recombined)
    return out


def _log_merge_impl(state: LogStashState, slot, key_hi, key_lo, ints, nums, valid, schema: LogSchema):
    s = state.capacity
    all_slot = jnp.concatenate([state.slot, slot])
    all_hi = jnp.concatenate([state.key_hi, key_hi])
    all_lo = jnp.concatenate([state.key_lo, key_lo])
    all_ints = jnp.concatenate([state.ints, ints], axis=0)
    all_nums = jnp.concatenate([state.nums, nums], axis=0)
    all_valid = jnp.concatenate([state.valid, valid])
    n = all_slot.shape[0]

    all_slot = jnp.where(all_valid, all_slot, jnp.uint32(SENTINEL_SLOT))
    all_hi = jnp.where(all_valid, all_hi, jnp.uint32(0xFFFFFFFF))
    all_lo = jnp.where(all_valid, all_lo, jnp.uint32(0xFFFFFFFF))

    iota = jnp.arange(n, dtype=jnp.int32)
    # stable sort → ties keep concat (arrival) order: stash before batch
    s_slot, s_hi, s_lo, perm = lax.sort((all_slot, all_hi, all_lo, iota), num_keys=3)

    first = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
        ]
    )
    seg_id = jnp.cumsum(first.astype(jnp.int32)) - 1

    first_pos = jax.ops.segment_min(iota, seg_id, num_segments=n)
    last_pos = jax.ops.segment_max(iota, seg_id, num_segments=n)
    first_pos = jnp.clip(first_pos, 0, n - 1)
    last_pos = jnp.clip(last_pos, 0, n - 1)

    ints_sorted = jnp.take(all_ints, perm, axis=0)
    nums_sorted = jnp.take(all_nums, perm, axis=0)

    col_groups = tuple(
        schema.int_cols_with(op)
        for op in (LogOp.FIRST, LogOp.LAST, LogOp.MIN, LogOp.MAX, LogOp.OR)
    )
    ints_out = _seg_reduce_ints(ints_sorted, seg_id, first_pos, last_pos, n, col_groups)

    kn = nums_sorted.shape[1]
    nums_out = jnp.zeros((n, kn), jnp.float32)
    sum_cols = schema.num_cols_with(LogOp.SUM)
    nmax_cols = schema.num_cols_with(LogOp.MAX)
    if sum_cols.size:
        nums_out = nums_out.at[:, sum_cols].set(
            jax.ops.segment_sum(nums_sorted[:, sum_cols], seg_id, num_segments=n)
        )
    if nmax_cols.size:
        part = jax.ops.segment_max(nums_sorted[:, nmax_cols], seg_id, num_segments=n)
        nums_out = nums_out.at[:, nmax_cols].set(jnp.where(jnp.isfinite(part), part, 0.0))

    slot_out = jnp.take(s_slot, first_pos)
    hi_out = jnp.take(s_hi, first_pos)
    lo_out = jnp.take(s_lo, first_pos)
    total = jnp.max(seg_id) + 1
    seg_index = jnp.arange(n, dtype=jnp.int32)
    seg_valid = (seg_index < total) & (slot_out != SENTINEL_SLOT)
    slot_out = jnp.where(seg_valid, slot_out, jnp.uint32(SENTINEL_SLOT))

    dropped = jnp.maximum(jnp.sum(seg_valid.astype(jnp.int32)) - s, 0)
    return LogStashState(
        slot=slot_out[:s],
        key_hi=hi_out[:s],
        key_lo=lo_out[:s],
        ints=ints_out[:s],
        nums=nums_out[:s],
        valid=seg_valid[:s],
        dropped_overflow=state.dropped_overflow + dropped,
    )


_log_merge = partial(jax.jit, static_argnames=("schema",), donate_argnums=(0,))(
    _log_merge_impl
)

# public alias: the agent FlowMap reuses the same schema-driven merge for
# its flow-state table (one LogStash, slot pinned to 0)
log_stash_merge = _log_merge


@jax.jit
def _log_flush(state: LogStashState, slot_idx):
    """Close one minute slot: compact its rows to the output prefix on
    device so the host transfer is O(emitted rows), not O(capacity)."""
    mask = state.valid & (state.slot == jnp.asarray(slot_idx, jnp.uint32))
    order = jnp.argsort(jnp.where(mask, 0, 1), stable=True)
    out = {
        "count": jnp.sum(mask.astype(jnp.int32)),
        "ints": jnp.take(state.ints, order, axis=0),
        "nums": jnp.take(state.nums, order, axis=0),
    }
    new_state = dataclasses.replace(
        state,
        slot=jnp.where(mask, jnp.uint32(SENTINEL_SLOT), state.slot),
        valid=state.valid & ~mask,
    )
    return new_state, out


# ---------------------------------------------------------------------------
# host drivers


class MinuteAggr:
    """FlowAggr analog: minute_merge of per-second flow emissions.

    Windows: a flow row lands in minute slot end_time//60; slots flush
    once `now` passes slot end + delay (flow_aggr thread ticks on its
    input's 1s cadence, flushing the previous minute — flow_aggr.rs:216).
    """

    def __init__(
        self,
        schema: LogSchema = L4_FLOW_LOG,
        *,
        capacity: int = 1 << 16,
        batch_size: int = 4096,
        delay_s: int = 10,
    ):
        self.schema = schema
        self.batch_size = batch_size
        self.delay_s = delay_s
        self.state = log_stash_init(capacity, schema)
        self._time_col = schema.int_index("end_time")
        self._max_time = 0
        self.counters = {"rows_in": 0, "rows_out": 0, "drop_before_window": 0}
        self._flushed_min = -1  # minutes ≤ this are closed
        register_countable("flow_aggr", self, schema=schema.name)

    def get_counters(self):
        c = dict(self.counters)
        c["dropped_overflow"] = int(np.asarray(self.state.dropped_overflow))
        return c

    def ingest(self, batch: FlowLogBatch) -> list[FlowLogBatch]:
        assert batch.schema is self.schema
        n = batch.size
        if n > self.batch_size:
            raise ValueError(f"batch {n} > batch_size {self.batch_size}")
        pad = self.batch_size - n
        ints = np.pad(batch.ints, ((0, pad), (0, 0)))
        nums = np.pad(batch.nums, ((0, pad), (0, 0)))
        valid = np.pad(batch.valid, (0, pad))

        t = ints[:, self._time_col].astype(np.int64)
        slot = (t // 60).astype(np.uint32)
        # late rows for already-flushed minutes are dropped and counted
        # (drop_before_window stance, collector.rs:386-391)
        late = valid & (slot <= np.uint32(self._flushed_min)) if self._flushed_min >= 0 else np.zeros_like(valid)
        self.counters["drop_before_window"] += int(late.sum())
        valid = valid & ~late

        key_mat = ints[:, self.schema.key_cols]
        hi, lo = fingerprint64(key_mat, xp=np)
        self.state = _log_merge(
            self.state,
            jnp.asarray(slot),
            jnp.asarray(hi),
            jnp.asarray(lo),
            jnp.asarray(ints),
            jnp.asarray(nums),
            jnp.asarray(valid),
            self.schema,
        )
        self.counters["rows_in"] += int(valid.sum())
        self._max_time = max(self._max_time, int(t[valid].max()) if valid.any() else 0)
        return self._flush_due()

    def _flush_due(self) -> list[FlowLogBatch]:
        due_until = (self._max_time - self.delay_s) // 60 - 1
        if due_until <= self._flushed_min:
            return []
        # sync live slots once per closing minute — flush only minutes
        # that actually hold rows (time jumps don't cause slot sweeps)
        slot = np.asarray(self.state.slot)
        live = np.unique(slot[np.asarray(self.state.valid)])
        out = [self._flush_slot(int(m)) for m in live if int(m) <= due_until]
        self._flushed_min = due_until
        return [b for b in out if b.size]

    def _flush_slot(self, minute: int) -> FlowLogBatch:
        self.state, raw = _log_flush(self.state, np.uint32(minute))
        n = int(raw["count"])
        # slicing the device array first keeps the D2H copy at O(n)
        ints = np.asarray(raw["ints"][:n])
        nums = np.asarray(raw["nums"][:n])
        self.counters["rows_out"] += n
        return FlowLogBatch(self.schema, ints, nums, np.ones(n, bool))

    def drain(self) -> list[FlowLogBatch]:
        out = []
        for m in sorted(
            int(s) for s in np.unique(np.asarray(self.state.slot)[np.asarray(self.state.valid)])
        ):
            b = self._flush_slot(m)
            if b.size:
                out.append(b)
            self._flushed_min = max(self._flushed_min, m)
        return out


class ThrottlingQueue:
    """Per-second reservoir sampler (flow_aggr.rs:500 ThrottlingQueue;
    server twin throttler/throttling_queue.go).

    Keeps ≤ throttle rows per distinct second bucket; once a bucket
    overflows, each further row replaces a random kept slot with
    probability throttle/seen — classic reservoir, deterministic here via
    a seeded generator.
    """

    def __init__(self, throttle: int = 1000, seed: int = 0, time_col: str = "end_time"):
        self.throttle = throttle
        self.time_col = time_col
        self._rng = np.random.default_rng(seed)
        self._buckets: dict[int, tuple[int, list]] = {}
        self.counters = {"in": 0, "kept": 0, "dropped": 0}

    def put(self, batch: FlowLogBatch) -> None:
        ts = batch.col(self.time_col)
        rows = np.nonzero(batch.valid)[0]
        self.counters["in"] += len(rows)
        for r in rows:
            sec = int(ts[r])
            seen, kept = self._buckets.get(sec, (0, []))
            if seen < self.throttle:
                kept.append((batch, int(r)))
            else:
                j = int(self._rng.integers(0, seen + 1))
                if j < self.throttle:
                    kept[j] = (batch, int(r))
            self._buckets[sec] = (seen + 1, kept)

    def drain(self, up_to_sec: int | None = None) -> list[FlowLogBatch]:
        """Emit buckets with second < up_to_sec (None = all)."""
        out = []
        for sec in sorted(self._buckets):
            if up_to_sec is not None and sec >= up_to_sec:
                continue
            seen, kept = self._buckets.pop(sec)
            self.counters["kept"] += len(kept)
            self.counters["dropped"] += seen - len(kept)
            if kept:
                out.append(_gather_rows(kept))
        return out


def _gather_rows(kept: list[tuple[FlowLogBatch, int]]) -> FlowLogBatch:
    schema = kept[0][0].schema
    ints = np.stack([b.ints[r] for b, r in kept])
    nums = np.stack([b.nums[r] for b, r in kept])
    strs = None
    if any(b.strs for b, _ in kept):
        strs = {
            f.name: [(b.strs[f.name][r] if b.strs else "") for b, r in kept]
            for f in schema.strs
        }
    return FlowLogBatch(schema, ints, nums, np.ones(len(kept), bool), strs)
