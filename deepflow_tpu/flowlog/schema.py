"""Flow-log column registries.

Unlike metric Documents (pure SUM/MAX meters), flow-log rows need richer
merge classes when the minute aggregator folds per-second TaggedFlow
emissions of one flow into a single log row (flow_aggr.rs:216
minute_merge): identity columns keep the first value, lifecycle columns
the *latest* (close_type/status follow the flow's last state), times are
MIN/MAX, counters SUM, TCP flags OR. Each column declares its class here
and the device kernel derives its reduction — same declarative pattern as
datamodel/schema.py.

Device layout: `ints` [N, Ki] u32 (FIRST/LAST/MIN/MAX/OR) and `nums`
[N, Kn] f32 (SUM/MAX). f32 counters are exact to 2^24 per flow·minute
(ARCHITECTURE §5 exactness stance; flow-log sums never cross windows).
String columns are host-side only (wire + storage, never on device).

Column sets abridge the reference's row models
(server/ingester/flow_log/log_data/l4_flow_log.go:44-214,
l7_flow_log.go:63-212) to the fields the pipelines populate.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


class LogOp(enum.Enum):
    FIRST = "first"  # identity: value from the earliest emission
    LAST = "last"  # lifecycle: value from the latest emission
    MIN = "min"  # start_time
    MAX = "max"  # end_time (int) — for f32 watermarks too
    OR = "or"  # tcp flag bitmasks
    SUM = "sum"  # counters (f32 lane)


_INT_OPS = (LogOp.FIRST, LogOp.LAST, LogOp.MIN, LogOp.MAX, LogOp.OR)
_NUM_OPS = (LogOp.SUM, LogOp.MAX)


@dataclasses.dataclass(frozen=True)
class LogField:
    name: str
    op: LogOp
    kind: str = "int"  # "int" (u32 device) | "num" (f32 device) | "str" (host)


@dataclasses.dataclass(frozen=True)
class LogSchema:
    name: str
    key: tuple[str, ...]  # merge key columns (within a window slot)
    fields: tuple[LogField, ...]

    def __post_init__(self):
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate columns in {self.name}")
        for f in self.fields:
            if f.kind == "int" and f.op not in _INT_OPS:
                raise ValueError(f"{f.name}: op {f.op} invalid for int lane")
            if f.kind == "num" and f.op not in _NUM_OPS:
                raise ValueError(f"{f.name}: op {f.op} invalid for num lane")
        object.__setattr__(self, "ints", tuple(f for f in self.fields if f.kind == "int"))
        object.__setattr__(self, "nums", tuple(f for f in self.fields if f.kind == "num"))
        object.__setattr__(self, "strs", tuple(f for f in self.fields if f.kind == "str"))
        object.__setattr__(self, "_int_idx", {f.name: i for i, f in enumerate(self.ints)})
        object.__setattr__(self, "_num_idx", {f.name: i for i, f in enumerate(self.nums)})
        for k in self.key:
            if k not in self._int_idx:
                raise ValueError(f"key column {k} must be an int column")

    def int_index(self, name: str) -> int:
        return self._int_idx[name]

    def num_index(self, name: str) -> int:
        return self._num_idx[name]

    def int_cols_with(self, op: LogOp) -> np.ndarray:
        return np.array(
            [i for i, f in enumerate(self.ints) if f.op is op], dtype=np.int32
        )

    def num_cols_with(self, op: LogOp) -> np.ndarray:
        return np.array(
            [i for i, f in enumerate(self.nums) if f.op is op], dtype=np.int32
        )

    @property
    def key_cols(self) -> np.ndarray:
        return np.array([self.int_index(k) for k in self.key], dtype=np.int32)


def _i(name, op=LogOp.FIRST):
    return LogField(name, op, "int")


def _n(name, op=LogOp.SUM):
    return LogField(name, op, "num")


def _s(name):
    return LogField(name, LogOp.FIRST, "str")


# L4 flow log (l4_flow_log.go:44-214 abridged). One row per flow per
# minute; minute_merge folds per-second TaggedFlow emissions.
L4_FLOW_LOG = LogSchema(
    "l4_flow_log",
    key=("flow_id_hi", "flow_id_lo"),
    fields=tuple(
        [
            _i("flow_id_hi"),
            _i("flow_id_lo"),
            _i("agent_id"),
            # identity (DataLinkLayer/NetworkLayer/TransportLayer groups)
            _i("is_ipv6"),
            *[_i(f"ip{s}_w{w}") for s in (0, 1) for w in range(4)],
            _i("mac0_hi"),
            _i("mac0_lo"),
            _i("mac1_hi"),
            _i("mac1_lo"),
            _i("l3_epc_id_0"),
            _i("l3_epc_id_1"),
            _i("client_port"),
            _i("server_port"),
            _i("protocol"),
            _i("tap_type"),
            _i("tap_port"),
            _i("tap_side"),
            _i("gpid_0"),
            _i("gpid_1"),
            _i("signal_source"),
            _i("l7_protocol"),
            _i("pod_id_0"),
            _i("pod_id_1"),
            # lifecycle
            _i("start_time", LogOp.MIN),
            _i("end_time", LogOp.MAX),
            _i("status", LogOp.LAST),
            _i("close_type", LogOp.LAST),
            _i("state", LogOp.LAST),
            # set on the flow's first emission; OR so a minute window
            # containing the flow's birth keeps the mark
            _i("is_new_flow", LogOp.OR),
            _i("tcp_flags_bit_0", LogOp.OR),
            _i("tcp_flags_bit_1", LogOp.OR),
            # counters (FlowPerfStats / metrics peers)
            _n("packet_tx"),
            _n("packet_rx"),
            _n("byte_tx"),
            _n("byte_rx"),
            _n("l3_byte_tx"),
            _n("l3_byte_rx"),
            _n("l4_byte_tx"),
            _n("l4_byte_rx"),
            _n("total_packet_tx"),
            _n("total_packet_rx"),
            _n("total_byte_tx"),
            _n("total_byte_rx"),
            _n("syn_count"),
            _n("synack_count"),
            _n("retrans_tx"),
            _n("retrans_rx"),
            _n("zero_win_tx"),
            _n("zero_win_rx"),
            _n("rtt", LogOp.MAX),
            _n("rtt_client_max", LogOp.MAX),
            _n("rtt_server_max", LogOp.MAX),
            _n("srt_max", LogOp.MAX),
            _n("art_max", LogOp.MAX),
            _n("rrt_max", LogOp.MAX),
            _n("cit_max", LogOp.MAX),
            _n("srt_sum"),
            _n("art_sum"),
            _n("rrt_sum"),
            _n("cit_sum"),
            _n("srt_count"),
            _n("art_count"),
            _n("rrt_count"),
            _n("cit_count"),
        ]
    ),
)


# L7 request log (l7_flow_log.go:63-212 abridged). One row per request /
# response / session — never merged, only throttled.
L7_FLOW_LOG = LogSchema(
    "l7_flow_log",
    key=("flow_id_hi", "flow_id_lo"),
    fields=tuple(
        [
            _i("flow_id_hi"),
            _i("flow_id_lo"),
            _i("agent_id"),
            _i("is_ipv6"),
            *[_i(f"ip{s}_w{w}") for s in (0, 1) for w in range(4)],
            _i("l3_epc_id_0"),
            _i("l3_epc_id_1"),
            _i("client_port"),
            _i("server_port"),
            _i("protocol"),
            _i("tap_type"),
            _i("tap_port"),
            _i("tap_side"),
            _i("gpid_0"),
            _i("gpid_1"),
            _i("signal_source"),
            _i("l7_protocol"),
            _i("pod_id_0"),
            _i("pod_id_1"),
            _i("version"),
            _i("type"),  # 0 request / 1 response / 2 session
            _i("request_id"),
            _i("status"),  # ok / client_error / server_error / timeout
            _i("status_code"),
            _i("start_time"),  # µs within-second handled host-side; s here
            _i("end_time"),
            _i("response_duration"),  # µs
            _s("request_type"),
            _s("request_domain"),
            _s("request_resource"),
            _s("endpoint"),
            _s("response_exception"),
            _s("trace_id"),
            _s("span_id"),
            _s("parent_span_id"),
            _s("x_request_id"),
            _s("app_service"),
            _s("app_instance"),
        ]
    ),
)
