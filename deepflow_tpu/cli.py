"""dfctl — the deepflow-ctl seat (cli/ctl/).

Commands mirror the reference CLI surface that applies to this build:

  dfctl server -f config.yaml            run the composed server
  dfctl query  --store DIR "SQL"         SQL over a store
  dfctl promql --store DIR "EXPR" -t T   PromQL instant query
  dfctl metrics --store DIR TABLE        metric catalog for a table
  dfctl tables --store DIR               db/table/row inventory
  dfctl flame  --store DIR --service S   flame tree JSON
  dfctl counters --port P [--module M]   live counter dump (debug UDP)
  dfctl agents --port P                  agent liveness (debug UDP)
  dfctl datasource ... (list/add)        downsampler management
  dfctl subscriptions --port P           push-plane standing queries:
                                         watcher counts + eval latency
  dfctl alerts --port P                  alert rules: state, value,
                                         last transition
  dfctl rest --port P METHOD PATH [JSON] controller REST (agent-group /
                                         domain / resource mgmt seats:
                                         resources, datasources, traces,
                                         tracemap, prom, profile)
  dfctl profile --port P device          device profiling plane: HBM
                                         ledger + XLA step census
                                         (--no-analyze skips compiles;
                                         --json for machine output)
  dfctl fleet --port P health|hosts|skew fleet pane (ISSUE 18): merged
                                         cross-host status, per-host
                                         roster + staleness, skew
                                         surfaces (--json for machine
                                         output)
  dfctl watch --port P QUERY             wire delivery lane (ISSUE 19):
                                         stream push-plane results over
                                         GET /v1/watch as they arrive
                                         (--sql for SQL, --alerts for
                                         the notification topic, --json
                                         for raw events; reconnects
                                         with capped backoff)
  dfctl agent-group --port P ...         trisolaris group config/upgrade
  dfctl plugin --dir D list              L7 protocol plugin inventory
  dfctl trace --port P TRACE_ID          assembled trace tree (REST)
  dfctl trace --port P window WID        window lineage tree (ISSUE 13:
                                         the pipeline traced by its own
                                         trace engine; --interval for
                                         cascade tiers, --service)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _store(args):
    from .storage.store import ColumnarStore

    if not args.store:
        sys.exit("--store DIR is required for this command")
    return ColumnarStore(args.store)


def cmd_query(args):
    from .querier import QueryEngine

    r = QueryEngine(_store(args)).execute(args.sql)
    print(json.dumps(r.to_dicts(), default=str, indent=None))


def cmd_promql(args):
    from .querier.promql import query_instant

    t = args.time or int(time.time())
    out = query_instant(_store(args), args.expr, t)
    print(json.dumps(out, default=str))


def cmd_metrics(args):
    from .querier.metrics import list_metrics

    print(json.dumps(list_metrics(args.table), indent=2))


def cmd_tables(args):
    store = _store(args)
    out = {db: {t: store.row_count(db, t) for t in store.tables(db)} for db in store.databases()}
    print(json.dumps(out, indent=2))


def cmd_flame(args):
    from .querier.profile import query_flame

    print(json.dumps(query_flame(_store(args), app_service=args.service)))


def cmd_debug(args, cmd: str, **extra):
    from .server.debug import debug_request

    print(json.dumps(debug_request(args.host, args.port, {"cmd": cmd, **extra}), indent=2))


def cmd_server(args):
    from .server.main import Server
    from .utils.config import load_config

    cfg, unknown = load_config(args.config)
    for k in unknown:
        print(f"warning: unknown config key {k}", file=sys.stderr)
    srv = Server(cfg).start()
    print(
        f"server up: receiver tcp/udp :{srv.receiver.tcp_port}/:{srv.receiver.udp_port} "
        f"debug :{srv.debug.port} trisolaris :{srv.trisolaris.port}"
    )
    try:
        while True:
            time.sleep(10)
            try:
                srv.tick()
            except Exception as e:  # a tick must never take the server down
                print(f"tick error: {e!r}", flush=True)
    except KeyboardInterrupt:
        srv.stop()


def cmd_rest(args):
    import urllib.request

    url = f"http://{args.host}:{args.port}{args.path}"
    data = args.body.encode() if args.body else None
    req = urllib.request.Request(url, data=data, method=args.method.upper())
    try:
        with urllib.request.urlopen(req) as r:
            body = r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
    print(body.decode())


def cmd_trace(args):
    import urllib.parse
    import urllib.request

    if args.trace_id == "window":
        # window lineage plane (ISSUE 13): `dfctl trace window <id>`
        # serves the pipeline's own trace tree for one window
        if args.window_id is None:
            sys.exit("usage: dfctl trace window WINDOW_ID [--interval N] "
                     "[--service S]")
        q = {"interval": str(args.interval)}
        if args.service:
            q["service"] = args.service
        url = (
            f"http://{args.host}:{args.port}/v1/trace/window/"
            f"{args.window_id}?{urllib.parse.urlencode(q)}"
        )
    else:
        url = f"http://{args.host}:{args.port}/v1/traces/{args.trace_id}"
    try:
        with urllib.request.urlopen(url) as r:
            body = r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
    print(json.dumps(json.loads(body), indent=2))


def _render_table(rows, columns=None):
    """Minimal aligned text table over a list of row dicts — the human
    faces of `dfctl profile`/`dfctl fleet` (pass --json for the
    machine shape dashboards consume)."""
    rows = [dict(r) for r in rows]
    if not rows:
        return "(no rows)"
    if columns is None:
        columns = []
        for r in rows:
            for k in r:
                if k not in columns:
                    columns.append(k)
    def cell(r, c):
        v = r.get(c, "")
        return json.dumps(v) if isinstance(v, (dict, list)) else str(v)
    widths = {
        c: max(len(c), *(len(cell(r, c)) for r in rows)) for c in columns
    }
    lines = ["  ".join(c.ljust(widths[c]) for c in columns)]
    lines.append("  ".join("-" * widths[c] for c in columns))
    for r in rows:
        lines.append("  ".join(cell(r, c).ljust(widths[c]) for c in columns))
    return "\n".join(lines)


def _render_kv(d):
    return "\n".join(f"{k}: {v}" for k, v in d.items())


def cmd_profile(args):
    """Device profiling plane (ISSUE 12): `dfctl profile device` pulls
    the HBM ledger + step census over the controller REST surface.
    Human tables by default; --json emits the raw machine shape."""
    import urllib.request

    if args.what != "device":
        sys.exit(f"unknown profile target {args.what!r}")
    analyze = "0" if args.no_analyze else "1"
    with urllib.request.urlopen(
        f"http://{args.host}:{args.port}/v1/profile/device?analyze={analyze}"
    ) as r:
        out = json.loads(r.read())
    if args.json:
        print(json.dumps(out, separators=(",", ":"), default=str))
        return
    print("# hbm ledger")
    print(_render_table(out.get("hbm", [])))
    print("\n# hbm totals")
    print(_render_kv(out.get("hbm_totals", {})))
    census = out.get("census", {})
    entries = census.pop("entries", None) if isinstance(census, dict) else None
    print("\n# step census")
    if isinstance(census, dict):
        print(_render_kv(census))
    else:
        print(json.dumps(census, indent=2))
    if isinstance(entries, list) and entries:
        print(_render_table(entries))


def cmd_fleet(args):
    """Fleet pane (ISSUE 18): `dfctl fleet health|hosts|skew` pulls the
    aggregator's merged cross-host views over REST. Human tables by
    default; --json emits the raw machine shape."""
    import urllib.request

    url = f"http://{args.host}:{args.port}/v1/fleet/{args.what}"
    try:
        with urllib.request.urlopen(url) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        if args.json:
            print(body.decode())
        else:
            sys.exit(f"fleet {args.what}: HTTP {e.code} {body.decode()}")
        return
    if args.json:
        print(json.dumps(out, separators=(",", ":"), default=str))
        return
    if args.what == "hosts":
        print(_render_table(
            out,
            columns=["host", "groups", "epoch", "frames", "age_s",
                     "stale", "hbm_bytes"],
        ))
    else:
        print(_render_kv(out))


def _watch_render(event) -> list[str]:
    """Human lines for one wire event: one line per series row for
    local PromQL payloads, merged per-host rows for fleet envelopes,
    a one-liner for alerts/staleness — anything else prints compact."""
    if isinstance(event, list):  # local promql lane: list of series
        out = []
        for s in event:
            if not isinstance(s, dict):
                out.append(json.dumps(s, default=str))
                continue
            metric = s.get("metric", {})
            values = s.get("values", [])
            t, v = (values[-1] if values else ("-", "-"))
            flag = " partial" if s.get("partial") else ""
            out.append(f"{json.dumps(metric, sort_keys=True)} t={t} v={v}{flag}")
        return out or ["(empty result)"]
    if isinstance(event, dict):
        kind = event.get("type") or ("alert" if "rule" in event else None)
        if kind == "result":  # fleet merged envelope
            out = []
            for s in event.get("merged", []):
                metric = s.get("metric", {}) if isinstance(s, dict) else {}
                values = s.get("values", []) if isinstance(s, dict) else []
                t, v = (values[-1] if values else ("-", "-"))
                flag = " partial" if isinstance(s, dict) and s.get("partial") else ""
                out.append(
                    f"{json.dumps(metric, sort_keys=True)} t={t} v={v}{flag}"
                )
            stale = [
                h for h, hs in event.get("hosts", {}).items() if hs.get("stale")
            ]
            if stale:
                out.append(f"! stale hosts: {', '.join(sorted(stale))}")
            return out or ["(empty merged result)"]
        if kind == "staleness":
            return [f"! host {event.get('host')} went stale"]
        if kind == "alert" or "rule" in event:
            return [
                f"ALERT {event.get('state')} rule={event.get('rule')} "
                f"value={event.get('value')} host={event.get('host', 'local')}"
            ]
    return [json.dumps(event, default=str)]


def cmd_watch(args):
    """Streaming client for the wire lane: connects to /v1/watch,
    prints rows as they arrive, reconnects with capped backoff when
    the stream drops (server restart, network blip) — a dashboard in
    40 lines of stdlib."""
    import urllib.error
    import urllib.parse
    import urllib.request

    q: dict[str, str] = {}
    if args.alerts:
        q["alerts"] = "1"
    elif args.sql:
        q["sql"] = args.query
    else:
        q["promql"] = args.query
    q["span_s"] = str(args.span)
    q["step"] = str(args.step)
    q["db"] = args.db
    q["table"] = args.table
    if args.scope:
        q["scope"] = args.scope
    if args.max_events:
        q["max_events"] = str(args.max_events)
    url = (f"http://{args.host}:{args.port}/v1/watch?"
           + urllib.parse.urlencode(q))
    backoff, seen = 0.5, 0
    while True:
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as r:
                backoff = 0.5  # a live stream resets the backoff
                for raw in r:
                    if not raw.startswith(b"data: "):
                        continue  # heartbeat / comment lines
                    event = json.loads(raw[6:])
                    if args.json:
                        print(json.dumps(event, separators=(",", ":"),
                                         default=str), flush=True)
                    else:
                        for line in _watch_render(event):
                            print(line, flush=True)
                    seen += 1
                    if args.max_events and seen >= args.max_events:
                        return
            if args.max_events and seen >= args.max_events:
                return
        except KeyboardInterrupt:
            return
        except urllib.error.HTTPError as e:
            # 4xx = the query itself is bad — retrying won't fix it
            sys.exit(f"watch: HTTP {e.code} {e.read().decode()}")
        except (urllib.error.URLError, OSError, TimeoutError):
            pass
        print(f"# stream dropped; reconnecting in {backoff:.1f}s",
              file=sys.stderr, flush=True)
        time.sleep(backoff)
        backoff = min(backoff * 2, args.max_backoff)


def cmd_agent_group(args):
    """Trisolaris group management over the sync socket (line-JSON):
    the deepflow-ctl agent-group/agent-group-config seat."""
    import base64
    import socket

    if args.action == "set-config":
        # configs are set through the REST/debug plane in-process; over
        # the wire we print the payload the server operator applies
        print(json.dumps({"group": args.group, "config": json.loads(args.value)}))
        return
    req = {"agent_id": args.agent_id, "config_rev": 0, "platform_version": 0}
    if args.action == "upgrade":
        req = {"type": "upgrade", "agent_id": args.agent_id}
    with socket.create_connection((args.host, args.port), timeout=5) as s:
        f = s.makefile("rwb")
        f.write(json.dumps(req).encode() + b"\n")
        f.flush()
        resp = json.loads(f.readline())
    if args.action == "upgrade" and "package_b64" in resp:
        resp["package_bytes"] = len(base64.b64decode(resp.pop("package_b64")))
    print(json.dumps(resp, indent=2))


def cmd_plugin(args):
    from .agent.l7.plugins import load_plugins

    loaded = load_plugins(args.dir)
    print(json.dumps([{"protocol": p, "name": n} for p, n in loaded], indent=2))


def main(argv=None):
    p = argparse.ArgumentParser(prog="dfctl")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("server")
    sp.add_argument("-f", "--config", default=None)
    sp.set_defaults(fn=cmd_server)

    for name, fn, extra in (
        ("query", cmd_query, [("sql",)]),
        ("promql", cmd_promql, [("expr",)]),
        ("metrics", cmd_metrics, [("table",)]),
        ("tables", cmd_tables, []),
        ("flame", cmd_flame, []),
    ):
        sp = sub.add_parser(name)
        sp.add_argument("--store", default="")
        for a in extra:
            sp.add_argument(*a)
        if name == "promql":
            sp.add_argument("-t", "--time", type=int, default=0)
        if name == "flame":
            sp.add_argument("--service", required=True)
        sp.set_defaults(fn=fn)

    for name in ("counters", "agents", "datasources", "subscriptions",
                 "alerts", "ping"):
        sp = sub.add_parser(name)
        sp.add_argument("--host", default="127.0.0.1")
        sp.add_argument("--port", type=int, required=True)
        if name == "counters":
            sp.add_argument("--module", default=None)
        sp.set_defaults(
            fn=lambda a, _n=name: cmd_debug(
                a, _n, **({"module": a.module} if _n == "counters" and a.module else {})
            )
        )

    sp = sub.add_parser("rest")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("method")
    sp.add_argument("path")
    sp.add_argument("body", nargs="?", default=None)
    sp.set_defaults(fn=cmd_rest)

    sp = sub.add_parser("trace")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("trace_id",
                    help="a trace id, or the literal 'window' followed "
                         "by a window id (window lineage tree)")
    sp.add_argument("window_id", nargs="?", default=None)
    sp.add_argument("--interval", type=int, default=1,
                    help="tier interval seconds for 'window' (default 1)")
    sp.add_argument("--service", default=None,
                    help="lineage service name (default tpu.pipeline)")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("profile")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("what", choices=["device"])
    sp.add_argument("--no-analyze", action="store_true",
                    help="skip the XLA cost/memory analysis (no compile)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable output (compact JSON)")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("fleet")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("what", choices=["health", "hosts", "skew"])
    sp.add_argument("--json", action="store_true",
                    help="machine-readable output (compact JSON)")
    sp.set_defaults(fn=cmd_fleet)

    sp = sub.add_parser("watch")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("query", nargs="?", default="",
                    help="PromQL expression (or SQL with --sql); "
                         "omit with --alerts")
    sp.add_argument("--sql", action="store_true",
                    help="treat QUERY as SQL instead of PromQL")
    sp.add_argument("--alerts", action="store_true",
                    help="stream alert notifications instead of a query")
    sp.add_argument("--json", action="store_true",
                    help="raw event JSON, one object per line")
    sp.add_argument("--span", type=int, default=60,
                    help="range span seconds (default 60)")
    sp.add_argument("--step", type=int, default=1,
                    help="range step seconds (default 1)")
    sp.add_argument("--db", default="deepflow_system")
    sp.add_argument("--table", default="deepflow_system")
    sp.add_argument("--scope", default="",
                    choices=["", "local", "fleet"],
                    help="local store or fleet router (default auto)")
    sp.add_argument("--max-events", type=int, default=0,
                    help="exit after N events (0 = stream forever)")
    sp.add_argument("--timeout", type=float, default=300.0,
                    help="socket timeout seconds (default 300)")
    sp.add_argument("--max-backoff", type=float, default=30.0,
                    help="reconnect backoff cap seconds (default 30)")
    sp.set_defaults(fn=cmd_watch)

    sp = sub.add_parser("agent-group")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("action", choices=["sync", "upgrade", "set-config"])
    sp.add_argument("--agent-id", type=int, default=0)
    sp.add_argument("--group", default="default")
    sp.add_argument("--value", default="{}")
    sp.set_defaults(fn=cmd_agent_group)

    sp = sub.add_parser("plugin")
    sp.add_argument("--dir", required=True)
    sp.add_argument("action", choices=["list"])
    sp.set_defaults(fn=cmd_plugin)

    args = p.parse_args(argv)
    try:
        args.fn(args)
    except BrokenPipeError:  # `dfctl ... | head` is normal usage
        sys.stderr.close()


if __name__ == "__main__":
    main()
