"""trident.proto gRPC facade — stock-agent registration compatibility.

The control plane here speaks JSON-lines (a documented deviation); this
facade puts a real gRPC `trident.Synchronizer` endpoint in front of
TrisolarisService so a stock deepflow-agent can register and receive
config pushes: Sync + AnalyzerSync (unary) and Push (server-streaming)
over the byte-exact wire subset of /root/reference/message/trident.proto:

  SyncRequest:  boot_time(1), config_accepted(2), revision(5),
                process_name(7), version_platform_data(9),
                ctrl_ip(21), host(22), ctrl_mac(25),
                vtap_group_id_request(26), cpu_num(32)
  SyncResponse: status(1)=SUCCESS, config(2){enabled(1), sync_interval
                (4), vtap_id(40)}, revision(4),
                version_platform_data(6)

Messages are built/parsed with the same hand-rolled varint codec as the
rest of the framework (no generated stubs — grpcio's generic handlers
carry raw bytes). Agent identity follows the reference's IP_AND_MAC
default (AgentIdentifier, trident.proto:91): (ctrl_ip, ctrl_mac) maps
to a stable allocated vtap_id.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

from ..ingest.codec import (
    _iter_fields,
    pb_bytes as _pb_sub,
    pb_str as _pb_str,
    pb_varint as _pb_varint,
)

STATUS_SUCCESS = 0
STATUS_HEARTBEAT = 2


def parse_sync_request(body: bytes) -> dict:
    req: dict = {}
    names = {1: "boot_time", 5: "revision", 7: "process_name",
             9: "version_platform_data", 21: "ctrl_ip", 22: "host",
             25: "ctrl_mac", 26: "vtap_group_id_request", 32: "cpu_num"}
    try:
        for f, v in _iter_fields(body):
            name = names.get(f)
            if name is None:
                continue
            if isinstance(v, (bytes, bytearray)):
                req[name] = bytes(v).decode(errors="replace")
            else:
                req[name] = int(v)
    except ValueError:
        pass  # truncated/garbled frame → whatever parsed so far
    return req


def build_sync_response(*, vtap_id: int, sync_interval: int,
                        platform_version: int, revision: str = "",
                        config_push: bool = True,
                        status: int = STATUS_SUCCESS) -> bytes:
    out = bytearray()
    _pb_varint(out, 1, status)
    if config_push:
        cfg = bytearray()
        _pb_varint(cfg, 1, 1)  # enabled
        _pb_varint(cfg, 4, sync_interval)
        _pb_varint(cfg, 40, vtap_id)
        _pb_sub(out, 2, bytes(cfg))
    if revision:
        _pb_str(out, 4, revision)
    _pb_varint(out, 6, platform_version)
    return bytes(out)


def parse_sync_response(body: bytes) -> dict:
    """Client-side decode of the subset (tests + SDK); total on
    garbage input like every untrusted-edge decoder here."""
    resp: dict = {}
    try:
        _parse_sync_response_into(resp, body)
    except ValueError:
        pass
    return resp


def _parse_sync_response_into(resp: dict, body: bytes) -> None:
    for f, v in _iter_fields(body):
        if f == 1:
            resp["status"] = int(v)
        elif f == 2 and isinstance(v, (bytes, bytearray)):
            cfg = {}
            for f2, v2 in _iter_fields(bytes(v)):
                if f2 == 1:
                    cfg["enabled"] = bool(v2)
                elif f2 == 4:
                    cfg["sync_interval"] = int(v2)
                elif f2 == 40:
                    cfg["vtap_id"] = int(v2)
            resp["config"] = cfg
        elif f == 4 and isinstance(v, (bytes, bytearray)):
            resp["revision"] = bytes(v).decode(errors="replace")
        elif f == 6:
            resp["version_platform_data"] = int(v)


class TridentGrpcFacade:
    """gRPC front for TrisolarisService (Sync + config push subset)."""

    def __init__(self, trisolaris, *, host: str = "127.0.0.1", port: int = 0,
                 sync_interval: int = 60, push_poll_s: float = 0.2,
                 push_heartbeat_s: float = 10.0, max_workers: int = 32):
        import grpc

        self._tri = trisolaris
        self.sync_interval = sync_interval
        self.push_poll_s = push_poll_s
        self.push_heartbeat_s = push_heartbeat_s
        # each long-lived Push stream PINS one executor thread for the
        # client's lifetime (the generator sleep-polls), so the pool
        # bounds the concurrent stock-agent count — size it accordingly
        self._lock = threading.Lock()
        self._ids: dict[tuple[str, str], int] = {}
        self._next_id = 1  # vtap ids are dense and ≤ 64000 (trident.proto:57)
        self.counters = {"syncs": 0, "registers": 0, "pushes": 0}

        handlers = {
            "Sync": grpc.unary_unary_rpc_method_handler(self._sync),
            "AnalyzerSync": grpc.unary_unary_rpc_method_handler(self._sync),
            "Push": grpc.unary_stream_rpc_method_handler(self._push),
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("trident.Synchronizer", handlers),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    # -- identity --------------------------------------------------------
    def _vtap_id(self, req: dict) -> int:
        key = (req.get("ctrl_ip", ""), req.get("ctrl_mac", ""))
        with self._lock:
            vid = self._ids.get(key)
            if vid is None:
                vid = self._next_id
                self._next_id += 1
                self._ids[key] = vid
                self.counters["registers"] += 1
            return vid

    # -- rpc bodies ------------------------------------------------------
    def _sync_response(self, body: bytes) -> bytes:
        req = parse_sync_request(bytes(body))
        vid = self._vtap_id(req)
        group = req.get("vtap_group_id_request") or "default"
        self._tri.assign_agent(vid, group)
        resp = self._tri.handle_sync({
            "agent_id": vid,
            "agent_version": req.get("revision", ""),
            "platform_version": req.get("version_platform_data", 0),
            # a stock agent has no JSON config revision; 0 forces the
            # first push, after which version_platform_data gates
            "config_rev": -1,
        })
        self.counters["syncs"] += 1
        return build_sync_response(
            vtap_id=vid,
            sync_interval=self.sync_interval,
            platform_version=int(resp.get("platform_version", 0)),
            revision=str(resp.get("upgrade", {}).get("version", "")),
        )

    def _sync(self, body, context):
        return self._sync_response(body)

    def _push(self, body, context):
        """Server-streaming config push: one immediate response, then
        one per platform/config revision change, plus periodic
        heartbeats (the reference controller pushes on an interval too;
        a steady message flow also keeps gRPC's blocking-iterator
        stream adapter from parking a response in an unflushed
        buffer)."""
        yield self._sync_response(body)
        last = self._tri.db.version
        last_beat = time.time()
        while context.is_active():
            time.sleep(self.push_poll_s)
            cur = self._tri.db.version
            beat = time.time() - last_beat >= self.push_heartbeat_s
            if cur != last or beat:
                if cur != last:
                    self.counters["pushes"] += 1
                last = cur
                last_beat = time.time()
                yield self._sync_response(body)

    def stop(self) -> None:
        self._server.stop(grace=0.5)
