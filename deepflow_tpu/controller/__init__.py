"""Control plane: resource registry (MySQL seat), tagrecorder
(SmartEncoding dictionary materialization), trisolaris-style agent and
ingester sync, and leader election — the server/controller seat.
"""
