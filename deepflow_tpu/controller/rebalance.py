"""Analyzer assignment & rebalance.

The reference's controller/monitor/analyzer.go watches ingester
(analyzer) liveness and redistributes agents when one dies or load
skews (vtap counts weighted by analyzer capacity); assignments ride to
agents in the trisolaris sync response. Same model: analyzers register
with a capacity weight and heartbeat; `assign()` gives an agent the
least-loaded live analyzer and is sticky; `rebalance()` drains dead
analyzers and narrows the load spread to within one agent of the
weighted ideal.
"""

from __future__ import annotations

import threading
import time


class AnalyzerBalancer:
    def __init__(self, *, dead_after_s: float = 60.0):
        self.dead_after_s = dead_after_s
        self._analyzers: dict[str, dict] = {}  # ip → {capacity, last_seen}
        self._assign: dict[int, str] = {}  # agent_id → analyzer ip
        self._lock = threading.Lock()
        self.counters = {"assigns": 0, "moves": 0, "drains": 0}

    # -- analyzer registry ---------------------------------------------
    def register(self, ip: str, *, capacity: int = 1, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._analyzers[ip] = {"capacity": max(1, capacity), "last_seen": now}

    def heartbeat(self, ip: str, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            if ip in self._analyzers:
                self._analyzers[ip]["last_seen"] = now

    def _alive(self, now: float) -> list[str]:
        return [
            ip
            for ip, a in self._analyzers.items()
            if now - a["last_seen"] <= self.dead_after_s
        ]

    def _loads(self) -> dict[str, int]:
        loads = {ip: 0 for ip in self._analyzers}
        for ip in self._assign.values():
            if ip in loads:
                loads[ip] += 1
        return loads

    # -- assignment -----------------------------------------------------
    def assign(self, agent_id: int, now: float | None = None) -> str | None:
        """Sticky least-normalized-load placement; None when no live
        analyzer exists (agents then keep their last assignment —
        escape semantics live agent-side)."""
        now = time.time() if now is None else now
        with self._lock:
            alive = set(self._alive(now))
            cur = self._assign.get(agent_id)
            if cur in alive:
                return cur
            if not alive:
                return None
            loads = self._loads()
            ip = min(
                alive,
                key=lambda i: (loads[i] / self._analyzers[i]["capacity"], i),
            )
            self._assign[agent_id] = ip
            self.counters["assigns"] += 1
            return ip

    def rebalance(self, now: float | None = None) -> int:
        """Drain dead analyzers, then move agents from over- to
        under-loaded ones until every analyzer is within one agent of
        its weighted share. Returns number of moves."""
        now = time.time() if now is None else now
        moves = 0
        with self._lock:
            alive = self._alive(now)
            if not alive:
                return 0
            alive_set = set(alive)
            # 1. drain: agents on dead analyzers
            orphans = [a for a, ip in self._assign.items() if ip not in alive_set]
            for a in orphans:
                del self._assign[a]
            self.counters["drains"] += len(orphans)

            total_cap = sum(self._analyzers[ip]["capacity"] for ip in alive)

            def ideal(ip: str, n_agents: int) -> float:
                return n_agents * self._analyzers[ip]["capacity"] / total_cap

            # re-place orphans least-loaded-first
            for a in sorted(orphans):
                loads = self._loads()
                ip = min(
                    alive, key=lambda i: (loads[i] / self._analyzers[i]["capacity"], i)
                )
                self._assign[a] = ip
                moves += 1

            # 2. narrow the spread
            n = len(self._assign)
            for _ in range(n):
                loads = self._loads()
                over = max(alive, key=lambda i: loads[i] - ideal(i, n))
                under = min(alive, key=lambda i: loads[i] - ideal(i, n))
                if loads[over] - ideal(over, n) <= 1.0:
                    break
                movable = [a for a, ip in self._assign.items() if ip == over]
                if not movable:
                    break
                self._assign[min(movable)] = under
                moves += 1
            self.counters["moves"] += moves
        return moves

    def assignments(self) -> dict[int, str]:
        with self._lock:
            return dict(self._assign)
