"""Analyzer assignment & rebalance.

The reference's controller/monitor/analyzer.go watches ingester
(analyzer) liveness and redistributes agents when one dies or load
skews (vtap counts weighted by analyzer capacity); assignments ride to
agents in the trisolaris sync response. Same model: analyzers register
with a capacity weight and heartbeat; `assign()` gives an agent the
least-loaded live analyzer and is sticky; `rebalance()` drains dead
analyzers and narrows the load spread to within one agent of the
weighted ideal.

`ShardGroupPlanner` (ISSUE 15) is the same watch-and-redistribute
model one level down: PROCESSES of the TPU mesh heartbeat here, and
when one dies (or is drained for maintenance) the planner emits the
(group, to_process) moves that `parallel/rebalance.GroupRebalancer`
executes — the controller decides, the hosts run the quiesce →
checkpoint → publish → restore → flip protocol.
"""

from __future__ import annotations

import threading
import time


class AnalyzerBalancer:
    def __init__(self, *, dead_after_s: float = 60.0):
        self.dead_after_s = dead_after_s
        self._analyzers: dict[str, dict] = {}  # ip → {capacity, last_seen}
        self._assign: dict[int, str] = {}  # agent_id → analyzer ip
        self._lock = threading.Lock()
        self.counters = {"assigns": 0, "moves": 0, "drains": 0}

    # -- analyzer registry ---------------------------------------------
    def register(self, ip: str, *, capacity: int = 1, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._analyzers[ip] = {"capacity": max(1, capacity), "last_seen": now}

    def heartbeat(self, ip: str, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            if ip in self._analyzers:
                self._analyzers[ip]["last_seen"] = now

    def _alive(self, now: float) -> list[str]:
        return [
            ip
            for ip, a in self._analyzers.items()
            if now - a["last_seen"] <= self.dead_after_s
        ]

    def _loads(self) -> dict[str, int]:
        loads = {ip: 0 for ip in self._analyzers}
        for ip in self._assign.values():
            if ip in loads:
                loads[ip] += 1
        return loads

    # -- assignment -----------------------------------------------------
    def assign(self, agent_id: int, now: float | None = None) -> str | None:
        """Sticky least-normalized-load placement; None when no live
        analyzer exists (agents then keep their last assignment —
        escape semantics live agent-side)."""
        now = time.time() if now is None else now
        with self._lock:
            alive = set(self._alive(now))
            cur = self._assign.get(agent_id)
            if cur in alive:
                return cur
            if not alive:
                return None
            loads = self._loads()
            ip = min(
                alive,
                key=lambda i: (loads[i] / self._analyzers[i]["capacity"], i),
            )
            self._assign[agent_id] = ip
            self.counters["assigns"] += 1
            return ip

    def rebalance(self, now: float | None = None) -> int:
        """Drain dead analyzers, then move agents from over- to
        under-loaded ones until every analyzer is within one agent of
        its weighted share. Returns number of moves."""
        now = time.time() if now is None else now
        moves = 0
        with self._lock:
            alive = self._alive(now)
            if not alive:
                return 0
            alive_set = set(alive)
            # 1. drain: agents on dead analyzers
            orphans = [a for a, ip in self._assign.items() if ip not in alive_set]
            for a in orphans:
                del self._assign[a]
            self.counters["drains"] += len(orphans)

            total_cap = sum(self._analyzers[ip]["capacity"] for ip in alive)

            def ideal(ip: str, n_agents: int) -> float:
                return n_agents * self._analyzers[ip]["capacity"] / total_cap

            # re-place orphans least-loaded-first
            for a in sorted(orphans):
                loads = self._loads()
                ip = min(
                    alive, key=lambda i: (loads[i] / self._analyzers[i]["capacity"], i)
                )
                self._assign[a] = ip
                moves += 1

            # 2. narrow the spread
            n = len(self._assign)
            for _ in range(n):
                loads = self._loads()
                over = max(alive, key=lambda i: loads[i] - ideal(i, n))
                under = min(alive, key=lambda i: loads[i] - ideal(i, n))
                if loads[over] - ideal(over, n) <= 1.0:
                    break
                movable = [a for a, ip in self._assign.items() if ip == over]
                if not movable:
                    break
                self._assign[min(movable)] = under
                moves += 1
            self.counters["moves"] += moves
        return moves

    def assignments(self) -> dict[int, str]:
        with self._lock:
            return dict(self._assign)


class ShardGroupPlanner:
    """Controller-side planning for shard-group rebalances (ISSUE 15).

    Mesh processes heartbeat with their owned groups; `plan_moves()`
    emits (group, to_process) moves for every group stranded on a dead
    process, least-loaded-first, and `plan_drain(p)` empties a live
    process for decommission the same way. The planner only DECIDES —
    executing a move is `parallel/rebalance.GroupRebalancer` on the
    hosts (quiesce → checkpoint → publish → restore → flip), so a
    planner crash mid-sequence loses nothing but pending intent."""

    def __init__(self, *, dead_after_s: float = 60.0):
        self.dead_after_s = dead_after_s
        self._procs: dict[int, dict] = {}  # process → {groups, last_seen}
        self._lock = threading.Lock()
        self.counters = {"moves_planned": 0, "drains_planned": 0}

    def heartbeat(self, process: int, groups, *,
                  now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._procs[int(process)] = {
                "groups": sorted(int(g) for g in groups),
                "last_seen": now,
            }

    def _alive(self, now: float) -> list[int]:
        return sorted(
            p for p, rec in self._procs.items()
            if now - rec["last_seen"] <= self.dead_after_s
        )

    def _spread(self, groups, targets, loads) -> list[tuple[int, int]]:
        """Stranded groups → least-loaded live targets, deterministic
        (sorted groups, ties broken by process index)."""
        moves = []
        for g in sorted(groups):
            to = min(targets, key=lambda p: (loads[p], p))
            moves.append((g, to))
            loads[to] += 1
        return moves

    def plan_moves(self, *, now: float | None = None) -> list[tuple[int, int]]:
        """Moves for every group whose owner stopped heartbeating:
        [(group, to_process), ...] — empty when the fleet is healthy
        or nothing is live to receive them. Level-triggered: a group a
        LIVE process already heartbeats as owned is never re-planned
        (the rescue landed — planning it again would bounce it between
        hosts forever), while a still-stranded group keeps being
        planned every tick until some owner claims it (a failed
        execution loses only intent, never the group). Dead records
        whose groups are all rescued are pruned."""
        now = time.time() if now is None else now
        with self._lock:
            alive = self._alive(now)
            if not alive:
                return []
            owned_live = {
                g for p in alive for g in self._procs[p]["groups"]
            }
            # dedupe across dead records: two dead processes can both
            # list a group (owner died, rescuer died later) — planning
            # it twice would split one key range across two adopters
            seen = set(owned_live)
            stranded, rescued_dead = [], []
            for p, rec in sorted(self._procs.items()):
                if p in alive:
                    continue
                left = [g for g in rec["groups"] if g not in seen]
                seen.update(left)
                stranded.extend(left)
                if all(g in owned_live for g in rec["groups"]):
                    rescued_dead.append(p)
            for p in rescued_dead:
                del self._procs[p]  # a revived host re-heartbeats
            loads = {p: len(self._procs[p]["groups"]) for p in alive}
            moves = self._spread(stranded, alive, loads)
            self.counters["moves_planned"] += len(moves)
            return moves

    def plan_drain(self, process: int, *,
                   now: float | None = None) -> list[tuple[int, int]]:
        """Decommission plan: move every group off a LIVE process
        (maintenance drain), least-loaded-first across the rest."""
        now = time.time() if now is None else now
        with self._lock:
            alive = [p for p in self._alive(now) if p != int(process)]
            rec = self._procs.get(int(process))
            if rec is None or not alive:
                return []
            loads = {p: len(self._procs[p]["groups"]) for p in alive}
            moves = self._spread(rec["groups"], alive, loads)
            self.counters["drains_planned"] += len(moves)
            return moves
