"""Public-cloud discovery adapters — recorded API responses → snapshots.

The reference ships one adapter per provider (server/controller/cloud/
aliyun/, aws/, …), each paging the provider SDK and normalizing into
the common resource model the recorder diffs. No cloud API is reachable
from this environment, so these adapters consume *recorded* API
response documents (the same JSON the SDKs return — fixtures in tests,
operator-supplied dumps in production) and perform the same
normalization:

  regions → region, zones → az, VPCs → l3_epc, vSwitches/subnets →
  subnet, instances → device(type=vm), ENIs → vinterfaces with MAC+IPs.

Numeric ids (epc_id, subnet_id, l3_device_id…) are recorder-allocated;
vinterface rows carry `_refs` markers that CloudTask.poll resolves
against the recorder's (domain, kind, uid) → id map — the same
two-poll settling used for K8s pod vifs.

Reference: cloud/aliyun/aliyun.go (GetCloudData assembly), vm.go,
network.go, vpc.go; cloud/aws/aws.go, vinterface_and_ip.go.
"""

from __future__ import annotations

DEVICE_TYPE_VM = 1


def _mac_int(mac: str) -> int:
    try:
        return int(mac.replace(":", "").replace("-", ""), 16)
    except ValueError:
        return 0


def _aliyun_list(doc: dict, outer: str, inner: str) -> list:
    """Aliyun responses nest lists as {"Vpcs": {"Vpc": [...]}}."""
    v = doc.get(outer, {})
    return v.get(inner, []) if isinstance(v, dict) else (v or [])


class AliyunPlatform:
    """Aliyun (ECS/VPC) API-response documents → recorder snapshot.

    `responses` maps API names to their recorded JSON bodies:
      DescribeRegions, DescribeZones, DescribeVpcs, DescribeVSwitches,
      DescribeInstances, DescribeNetworkInterfaces.
    """

    def __init__(self, responses: dict, *, domain: str = "aliyun"):
        self.domain = domain
        self._r = responses

    def update(self, responses: dict) -> None:
        self._r = responses

    def snapshot(self) -> dict:
        r = self._r
        res: dict[str, list] = {
            "region": [], "az": [], "l3_epc": [], "subnet": [], "device": [],
        }
        for reg in _aliyun_list(r.get("DescribeRegions", {}), "Regions", "Region"):
            res["region"].append({
                "uid": reg["RegionId"],
                "name": reg.get("LocalName", reg["RegionId"]),
            })
        for z in _aliyun_list(r.get("DescribeZones", {}), "Zones", "Zone"):
            res["az"].append({
                "uid": z["ZoneId"],
                "name": z.get("LocalName", z["ZoneId"]),
                "region": z.get("RegionId", ""),
            })
        for vpc in _aliyun_list(r.get("DescribeVpcs", {}), "Vpcs", "Vpc"):
            res["l3_epc"].append({
                "uid": vpc["VpcId"],
                "name": vpc.get("VpcName") or vpc["VpcId"],
                "cidr": vpc.get("CidrBlock", ""),
                "region": vpc.get("RegionId", ""),
            })
        for sw in _aliyun_list(r.get("DescribeVSwitches", {}), "VSwitches", "VSwitch"):
            res["subnet"].append({
                "uid": sw["VSwitchId"],
                "name": sw.get("VSwitchName") or sw["VSwitchId"],
                "cidr": sw.get("CidrBlock", ""),
                "epc": sw.get("VpcId", ""),
                "az": sw.get("ZoneId", ""),
            })
        inst_vpc: dict[str, str] = {}
        for inst in _aliyun_list(r.get("DescribeInstances", {}), "Instances", "Instance"):
            vpc_uid = inst.get("VpcAttributes", {}).get("VpcId", "")
            inst_vpc[inst["InstanceId"]] = vpc_uid
            res["device"].append({
                "uid": inst["InstanceId"],
                "name": inst.get("InstanceName") or inst["InstanceId"],
                "type": "vm",
                "epc": vpc_uid,
                "az": inst.get("ZoneId", ""),
                "state": inst.get("Status", ""),
            })
        vifs = []
        for eni in _aliyun_list(
            r.get("DescribeNetworkInterfaces", {}),
            "NetworkInterfaceSets", "NetworkInterfaceSet",
        ):
            ips = [
                p["PrivateIpAddress"]
                for p in _aliyun_list(eni, "PrivateIpSets", "PrivateIpSet")
                if p.get("PrivateIpAddress")
            ]
            primary = eni.get("PrivateIpAddress")
            if primary and primary not in ips:
                ips.insert(0, primary)
            inst = eni.get("InstanceId", "")
            vifs.append({
                "mac": _mac_int(eni.get("MacAddress", "")),
                "ips": ips,
                "l3_device_type": DEVICE_TYPE_VM,
                "_refs": [
                    ("epc_id", "l3_epc", eni.get("VpcId") or inst_vpc.get(inst, "")),
                    ("subnet_id", "subnet", eni.get("VSwitchId", "")),
                    ("l3_device_id", "device", inst),
                ],
            })
        return {"resources": res, "vinterfaces": vifs}


class AwsPlatform:
    """AWS (EC2/VPC) API-response documents → recorder snapshot.

    `responses` maps boto3-shaped API names to bodies: DescribeRegions,
    DescribeAvailabilityZones, DescribeVpcs, DescribeSubnets,
    DescribeInstances (Reservations form).
    """

    def __init__(self, responses: dict, *, domain: str = "aws"):
        self.domain = domain
        self._r = responses

    def update(self, responses: dict) -> None:
        self._r = responses

    @staticmethod
    def _tag_name(obj: dict, default: str) -> str:
        for t in obj.get("Tags", []):
            if t.get("Key") == "Name" and t.get("Value"):
                return t["Value"]
        return default

    def snapshot(self) -> dict:
        r = self._r
        res: dict[str, list] = {
            "region": [], "az": [], "l3_epc": [], "subnet": [], "device": [],
        }
        for reg in r.get("DescribeRegions", {}).get("Regions", []):
            res["region"].append({
                "uid": reg["RegionName"], "name": reg["RegionName"],
            })
        for z in r.get("DescribeAvailabilityZones", {}).get("AvailabilityZones", []):
            res["az"].append({
                "uid": z["ZoneName"], "name": z["ZoneName"],
                "region": z.get("RegionName", ""),
            })
        for vpc in r.get("DescribeVpcs", {}).get("Vpcs", []):
            res["l3_epc"].append({
                "uid": vpc["VpcId"],
                "name": self._tag_name(vpc, vpc["VpcId"]),
                "cidr": vpc.get("CidrBlock", ""),
            })
        for sn in r.get("DescribeSubnets", {}).get("Subnets", []):
            res["subnet"].append({
                "uid": sn["SubnetId"],
                "name": self._tag_name(sn, sn["SubnetId"]),
                "cidr": sn.get("CidrBlock", ""),
                "epc": sn.get("VpcId", ""),
                "az": sn.get("AvailabilityZone", ""),
            })
        vifs = []
        for resv in r.get("DescribeInstances", {}).get("Reservations", []):
            for inst in resv.get("Instances", []):
                res["device"].append({
                    "uid": inst["InstanceId"],
                    "name": self._tag_name(inst, inst["InstanceId"]),
                    "type": "vm",
                    "epc": inst.get("VpcId", ""),
                    "az": inst.get("Placement", {}).get("AvailabilityZone", ""),
                    "state": inst.get("State", {}).get("Name", ""),
                })
                for eni in inst.get("NetworkInterfaces", []):
                    ips = [
                        p["PrivateIpAddress"]
                        for p in eni.get("PrivateIpAddresses", [])
                        if p.get("PrivateIpAddress")
                    ] or ([inst["PrivateIpAddress"]]
                          if inst.get("PrivateIpAddress") else [])
                    vifs.append({
                        "mac": _mac_int(eni.get("MacAddress", "")),
                        "ips": ips,
                        "l3_device_type": DEVICE_TYPE_VM,
                        "_refs": [
                            ("epc_id", "l3_epc",
                             eni.get("VpcId") or inst.get("VpcId", "")),
                            ("subnet_id", "subnet",
                             eni.get("SubnetId") or inst.get("SubnetId", "")),
                            ("l3_device_id", "device", inst["InstanceId"]),
                        ],
                    })
        return {"resources": res, "vinterfaces": vifs}
