"""Genesis — agent-reported resource discovery.

The reference's genesis plane (server/controller/genesis/: grpc intake
from agents, store with per-vtap lifetimes, updater into the recorder)
covers hosts no cloud adapter knows about: every agent reports its
local interfaces/IPs with each sync, the store keeps them alive on a
lease, and the aggregate becomes one more recorder domain. Same here:
`TrisolarisService` feeds `report()` from the sync payload's
`genesis` key, and `snapshot()` emits the recorder shape with one
`host` resource per agent plus its interfaces as vinterfaces.
"""

from __future__ import annotations

import threading
import time

GENESIS_DOMAIN = "genesis"


class GenesisStore:
    def __init__(self, *, lease_s: float = 130.0, epc_id: int = 0):
        """lease_s: how long a report stays alive without refresh (the
        reference ages vtap data out of the genesis store on the same
        kind of timer); epc_id: EPC assigned to genesis interfaces."""
        self.lease_s = lease_s
        self.epc_id = epc_id
        self._agents: dict[int, dict] = {}
        self._lock = threading.Lock()
        self.counters = {"reports": 0, "expired": 0}

    def report(self, agent_id: int, payload: dict, now: float | None = None) -> None:
        """payload: {"hostname": str, "interfaces": [{"mac": int,
        "ips": [str], "name": str}]} — the agent's local view."""
        now = time.time() if now is None else now
        with self._lock:
            self._agents[agent_id] = {
                "hostname": payload.get("hostname", f"agent-{agent_id}"),
                "interfaces": list(payload.get("interfaces", [])),
                "last_seen": now,
            }
            self.counters["reports"] += 1

    def expire(self, now: float | None = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            dead = [
                aid
                for aid, a in self._agents.items()
                if now - a["last_seen"] > self.lease_s
            ]
            for aid in dead:
                del self._agents[aid]
            self.counters["expired"] += len(dead)
        return len(dead)

    def snapshot(self, now: float | None = None) -> dict:
        """Recorder-shape snapshot of everything still on lease."""
        self.expire(now)
        hosts = []
        vifs = []
        with self._lock:
            for aid, a in sorted(self._agents.items()):
                hosts.append(
                    {
                        "uid": f"genesis/host/{aid}",
                        "name": a["hostname"],
                        "agent_id": aid,
                    }
                )
                for itf in a["interfaces"]:
                    ips = [ip for ip in itf.get("ips", []) if ip]
                    if not ips:
                        continue
                    vifs.append(
                        {
                            "epc_id": self.epc_id,
                            "ips": ips,
                            "mac": int(itf.get("mac", 0)),
                        }
                    )
        return {"resources": {"host": hosts}, "vinterfaces": vifs}

    # mirror the cloud source interface so CloudTask can drive genesis
    domain = GENESIS_DOMAIN
